"""Module mutation grid (parity: the reference exercises every module's
mutation methods per class — tests/test_modules/*, SURVEY.md §4).

For every evolvable module class x every discovered @mutation method:
- the mutation applies without error and reports ``applied``/mutation name
- the forward pass still produces the same output shape, finite values
- overlapping weights are preserved (output on the same input changes only
  where the architecture actually changed: we check param overlap directly)
- repeated application respects min/max bounds (no crash at the rails)
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.tiering import fast_core
from gymnasium import spaces

from agilerl_tpu.modules import (
    EvolvableBERT,
    EvolvableCNN,
    EvolvableGPT,
    EvolvableLSTM,
    EvolvableMLP,
    EvolvableMultiInput,
    EvolvableResNet,
    EvolvableSimBa,
)

KEY = jax.random.PRNGKey(0)

DICT_SPACE = spaces.Dict(
    {
        "vec": spaces.Box(-1, 1, (5,), np.float32),
        "img": spaces.Box(0, 1, (12, 12, 3), np.float32),
    }
)


def make_module(name):
    key = jax.random.PRNGKey(0)
    if name == "mlp":
        m = EvolvableMLP(num_inputs=6, num_outputs=3, hidden_size=(16, 16), key=key)
        x = jnp.ones((4, 6))
    elif name == "cnn":
        m = EvolvableCNN(
            input_shape=(12, 12, 3), num_outputs=3,
            channel_size=(8, 8), kernel_size=(3, 3), stride_size=(1, 1), key=key,
        )
        x = jnp.ones((4, 12, 12, 3))
    elif name == "lstm":
        m = EvolvableLSTM(num_inputs=6, num_outputs=3, key=key)
        x = jnp.ones((4, 5, 6))  # [B, T, F]
    elif name == "multi_input":
        m = EvolvableMultiInput(observation_space=DICT_SPACE, num_outputs=3, key=key)
        x = {"vec": jnp.ones((4, 5)), "img": jnp.ones((4, 12, 12, 3))}
    elif name == "simba":
        m = EvolvableSimBa(num_inputs=6, num_outputs=3, key=key)
        x = jnp.ones((4, 6))
    elif name == "resnet":
        m = EvolvableResNet(
            input_shape=(12, 12, 3), num_outputs=3, channel_size=8, num_blocks=1,
            key=key,
        )
        x = jnp.ones((4, 12, 12, 3))
    elif name == "gpt":
        m = EvolvableGPT(
            vocab_size=64, n_layer=2, n_head=2, d_model=32, max_seq_len=16, key=key,
        )
        x = jnp.zeros((2, 8), jnp.int32)
    elif name == "bert":
        m = EvolvableBERT(
            vocab_size=64, n_encoder_layers=1, n_decoder_layers=1, n_head=2,
            d_model=32, max_seq_len=16, key=key,
        )
        x = jnp.zeros((2, 8), jnp.int32)
    else:  # pragma: no cover
        raise ValueError(name)
    return m, x


MODULES = ["mlp", "cnn", "lstm", "multi_input", "simba", "resnet", "gpt", "bert"]


def forward(m, x):
    # BERT is encoder-decoder: the shape-stable surface is decoder logits
    # (encoder-only output is [B, T, d_model], which node mutations resize)
    out = m(x, tgt=x) if isinstance(m, EvolvableBERT) else m(x)
    # transformers return (logits, extras) tuples; encoders return arrays
    if isinstance(out, tuple):
        out = out[0]
    return np.asarray(out)


def _grid():
    for name in MODULES:
        cls = {
            "mlp": EvolvableMLP, "cnn": EvolvableCNN, "lstm": EvolvableLSTM,
            "multi_input": EvolvableMultiInput, "simba": EvolvableSimBa,
            "resnet": EvolvableResNet, "gpt": EvolvableGPT, "bert": EvolvableBERT,
        }[name]
        for mut in sorted(cls.get_mutation_methods()):
            yield name, mut


@pytest.mark.parametrize("name,mut", list(_grid()))
def test_mutation_preserves_shape_and_weights(name, mut):
    m, x = make_module(name)
    before = forward(m, x)
    old_flat = {
        jax.tree_util.keystr(p): np.asarray(v)
        for p, v in jax.tree_util.tree_flatten_with_path(m.params)[0]
    }
    rng = np.random.default_rng(0)
    m.apply_mutation(mut, rng=rng)
    after = forward(m, x)
    assert after.shape == before.shape
    assert np.isfinite(after).all()
    # weight preservation: every param path that survives with the same shape
    # must carry the old values on the overlapping slice (reference semantics:
    # modules/base.py:472 preserve_parameters)
    new_flat = {
        jax.tree_util.keystr(p): np.asarray(v)
        for p, v in jax.tree_util.tree_flatten_with_path(m.params)[0]
    }
    preserved = 0
    for path, old_v in old_flat.items():
        new_v = new_flat.get(path)
        if new_v is None or new_v.ndim != old_v.ndim:
            continue
        sl = tuple(slice(0, min(a, b)) for a, b in zip(old_v.shape, new_v.shape))
        if all(s.stop > 0 for s in sl):
            overlap_new = new_v[sl]
            overlap_old = old_v[sl]
            if overlap_new.shape == overlap_old.shape and np.allclose(
                overlap_new, overlap_old, atol=1e-6
            ):
                preserved += 1
    # at least half the surviving paths keep their trained weights
    assert preserved >= max(1, len(old_flat) // 2), (
        f"{name}.{mut}: only {preserved}/{len(old_flat)} param paths preserved"
    )


@pytest.mark.parametrize("name", fast_core(MODULES, fast=("mlp",)))
def test_mutation_rails(name):
    """Hammer random mutations; bounds must hold and forward must stay valid."""
    m, x = make_module(name)
    rng = np.random.default_rng(1)
    for i in range(12):
        method = m.sample_mutation_method(rng=rng)
        m.apply_mutation(method, rng=rng)
    out = forward(m, x)
    assert np.isfinite(out).all()


@pytest.mark.parametrize("name", MODULES)
def test_clone_exact(name):
    m, x = make_module(name)
    c = m.clone()
    np.testing.assert_array_equal(forward(m, x), forward(c, x))
    # independence: mutating the clone leaves the original untouched
    rng = np.random.default_rng(2)
    c.apply_mutation(c.sample_mutation_method(rng=rng), rng=rng)
    before = forward(m, x)
    np.testing.assert_array_equal(before, forward(m, x))


@pytest.mark.parametrize("name", MODULES)
def test_state_dict_roundtrip(name):
    m, x = make_module(name)
    sd = m.state_dict()
    m2, _ = make_module(name)
    # fresh init differs, then loading restores exactly
    m2.load_state_dict(jax.tree_util.tree_map(np.asarray, sd))
    np.testing.assert_array_equal(forward(m, x), forward(m2, x))
