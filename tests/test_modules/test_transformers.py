import jax
import jax.numpy as jnp
import numpy as np
import pytest

from agilerl_tpu.llm.model import GPTConfig
from agilerl_tpu.modules.bert import EvolvableBERT
from agilerl_tpu.modules.gpt import EvolvableGPT


def make_gpt(key):
    cfg = GPTConfig(vocab_size=50, n_layer=2, n_head=4, d_model=64,
                    max_seq_len=32, dtype=jnp.float32)
    return EvolvableGPT(config=cfg, key=key)


class TestEvolvableGPT:
    def test_forward(self, key):
        gpt = make_gpt(key)
        logits = gpt(jnp.zeros((2, 8), jnp.int32))
        assert logits.shape == (2, 8, 50)

    def test_layer_mutation_preserves(self, key):
        gpt = make_gpt(key)
        w0 = np.asarray(gpt.params["blocks"]["0"]["wq"]).copy()
        gpt.add_layer()
        assert gpt.config.n_layer == 3
        np.testing.assert_array_equal(w0, np.asarray(gpt.params["blocks"]["0"]["wq"]))
        assert gpt(jnp.zeros((1, 4), jnp.int32)).shape == (1, 4, 50)
        gpt.remove_layer()
        assert gpt.config.n_layer == 2

    def test_node_mutation(self, key):
        gpt = make_gpt(key)
        old = np.asarray(gpt.params["blocks"]["0"]["wq"]).copy()
        gpt.add_node(numb_new_nodes=16)
        assert gpt.config.d_model == 80
        assert gpt.config.d_model % gpt.config.n_head == 0
        new = np.asarray(gpt.params["blocks"]["0"]["wq"])
        np.testing.assert_array_equal(new[:64, :64], old[:, :64])
        assert gpt(jnp.zeros((1, 4), jnp.int32)).shape == (1, 4, 50)

    def test_estimate_mfu(self, key):
        gpt = make_gpt(key)
        mfu = gpt.estimate_mfu(tokens_per_step=1024, dt=0.1)
        assert 0 <= mfu < 1


class TestEvolvableBERT:
    def test_encode_decode(self, key):
        bert = EvolvableBERT(vocab_size=40, key=key, d_model=64, n_head=4)
        src = jnp.zeros((2, 6), jnp.int32)
        tgt = jnp.zeros((2, 5), jnp.int32)
        logits = bert(src, tgt=tgt)
        assert logits.shape == (2, 5, 40)
        enc = bert(src)
        assert enc.shape == (2, 6, 64)

    def test_mutations(self, key, rng):
        bert = EvolvableBERT(vocab_size=40, key=key, d_model=64, n_head=4)
        bert.add_layer(rng=rng)
        bert.add_node(numb_new_nodes=16)
        src = jnp.zeros((1, 4), jnp.int32)
        tgt = jnp.zeros((1, 3), jnp.int32)
        assert bert(src, tgt=tgt).shape == (1, 3, 40)
