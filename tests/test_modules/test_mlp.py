import jax
import jax.numpy as jnp
import numpy as np
import pytest

from agilerl_tpu.modules import EvolvableMLP
from agilerl_tpu.modules.base import preserve_params


def make_mlp(key, **kw):
    defaults = dict(num_inputs=4, num_outputs=2, hidden_size=(32, 32))
    defaults.update(kw)
    return EvolvableMLP(key=key, **defaults)


def test_forward_shape(key):
    mlp = make_mlp(key)
    x = jnp.ones((8, 4))
    out = mlp(x)
    assert out.shape == (8, 2)
    assert jnp.isfinite(out).all()


def test_forward_jit_consistent(key):
    mlp = make_mlp(key)
    x = jax.random.normal(key, (5, 4))
    eager = mlp(x)
    jitted = jax.jit(lambda p, x: EvolvableMLP.apply(mlp.config, p, x))(mlp.params, x)
    np.testing.assert_allclose(eager, jitted, rtol=1e-5)


@pytest.mark.parametrize("activation", ["ReLU", "Tanh", "GELU", "ELU", "LeakyReLU"])
def test_activations(key, activation):
    mlp = make_mlp(key, activation=activation)
    assert mlp(jnp.ones((1, 4))).shape == (1, 2)


def test_noisy(key):
    mlp = make_mlp(key, noisy=True)
    x = jnp.ones((3, 4))
    det = mlp(x)
    noisy1 = mlp(x, key=jax.random.PRNGKey(0))
    noisy2 = mlp(x, key=jax.random.PRNGKey(1))
    assert det.shape == (3, 2)
    assert not jnp.allclose(noisy1, noisy2)


def test_add_layer_preserves_weights(key):
    mlp = make_mlp(key)
    old_l0 = mlp.params["layer_0"]["kernel"]
    mlp.add_layer()
    assert len(mlp.config.hidden_size) == 3
    np.testing.assert_array_equal(mlp.params["layer_0"]["kernel"], old_l0)
    assert mlp(jnp.ones((2, 4))).shape == (2, 2)
    assert mlp.last_mutation_attr == "add_layer"


def test_remove_layer(key):
    mlp = make_mlp(key, hidden_size=(32, 32, 32))
    mlp.remove_layer()
    assert len(mlp.config.hidden_size) == 2
    assert mlp(jnp.ones((2, 4))).shape == (2, 2)


def test_add_node_preserves_slab(key, rng):
    mlp = make_mlp(key)
    old = mlp.params["layer_0"]["kernel"]
    info = mlp.add_node(hidden_layer=0, numb_new_nodes=16)
    assert mlp.config.hidden_size[0] == 48
    assert info["numb_new_nodes"] == 16
    new = mlp.params["layer_0"]["kernel"]
    assert new.shape == (4, 48)
    np.testing.assert_array_equal(new[:, :32], old)
    assert mlp(jnp.ones((2, 4))).shape == (2, 2)


def test_remove_node_respects_min(key):
    mlp = make_mlp(key, hidden_size=(70,), min_mlp_nodes=64)
    mlp.remove_node(hidden_layer=0, numb_new_nodes=32)
    assert mlp.config.hidden_size[0] == 64


def test_layer_bounds(key, rng):
    mlp = make_mlp(key, hidden_size=(32,), min_hidden_layers=1, max_hidden_layers=1)
    # both mutations should fall back to node mutation
    mlp.add_layer(rng=rng)
    assert len(mlp.config.hidden_size) == 1
    mlp.remove_layer(rng=rng)
    assert len(mlp.config.hidden_size) == 1


def test_clone_independent(key):
    mlp = make_mlp(key)
    clone = mlp.clone()
    np.testing.assert_array_equal(
        clone.params["layer_0"]["kernel"], mlp.params["layer_0"]["kernel"]
    )
    clone.add_node(hidden_layer=0, numb_new_nodes=16)
    assert mlp.config.hidden_size[0] == 32
    assert clone.config.hidden_size[0] == 48


def test_mutation_discovery():
    methods = EvolvableMLP.get_mutation_methods()
    assert set(methods) == {"add_layer", "remove_layer", "add_node", "remove_node"}
    assert set(EvolvableMLP.layer_mutation_methods()) == {"add_layer", "remove_layer"}


def test_sample_mutation_method(key, rng):
    mlp = make_mlp(key)
    names = {mlp.sample_mutation_method(rng=rng) for _ in range(50)}
    assert names <= {"add_layer", "remove_layer", "add_node", "remove_node"}
    assert names & {"add_node", "remove_node"}


def test_preserve_params_shrink(key):
    a = {"w": jnp.arange(12.0).reshape(3, 4)}
    b = {"w": jnp.zeros((2, 2))}
    out = preserve_params(a, b)
    np.testing.assert_array_equal(out["w"], jnp.array([[0.0, 1.0], [4.0, 5.0]]))
