"""The flywheel entry point: telemetry/resilience wiring, eval cadence,
and kill-resume continuation of the learner epoch line."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from agilerl_tpu.algorithms.grpo import GRPO
from agilerl_tpu.llm import model as M
from agilerl_tpu.observability import MemorySink, MetricsRegistry, RunTelemetry
from agilerl_tpu.training.train_llm_online import finetune_llm_reasoning_online
from agilerl_tpu.utils.llm_utils import CharTokenizer, ReasoningGym

pytestmark = pytest.mark.flywheel

TOK = CharTokenizer()
CFG = M.GPTConfig(vocab_size=TOK.vocab_size, n_layer=2, n_head=4, d_model=32,
                  max_seq_len=64, dtype=jnp.float32)


def reasoning_rows(n, seed):
    rng = np.random.default_rng(seed)
    return [
        {"question": f"{a}+{b}=", "answer": str(a + b)}
        for a, b in rng.integers(0, 5, (n, 2))
    ]


def make_env():
    return ReasoningGym(
        reasoning_rows(16, 0), reasoning_rows(4, 1), TOK,
        reward_fn=lambda c, a, p: 0.1 * len(c) + float(c.startswith(str(a))),
        data_batch_size=4)


def test_online_entry_point_runs_and_logs(tmp_path):
    env = make_env()
    agent = GRPO(config=CFG, pad_token_id=TOK.pad_token_id,
                 eos_token_id=TOK.eos_token_id, group_size=2, batch_size=8,
                 max_output_tokens=4, seed=0)
    sink = MemorySink()
    telem = RunTelemetry(registry=MetricsRegistry(sink=sink), lineage=False)
    out, fitnesses = finetune_llm_reasoning_online(
        agent, env, tmp_path, max_epochs=2, evaluation_interval=1,
        max_staleness_epochs=0, verbose=False, telemetry=telem)
    assert out is agent
    assert len(fitnesses) == 2  # one eval per learner epoch at interval 1
    losses = [e["train/loss"] for e in sink.events
              if e["kind"] == "metrics" and "train/loss" in e]
    assert len(losses) == 2 and all(np.isfinite(l) for l in losses)
    reg = telem.registry
    assert reg.counter("flywheel/learn_steps_total").value == 2
    assert reg.counter("flywheel/trajectories_published_total").value == 2
    assert reg.counter("flywheel/trajectories_consumed_total").value == 2
    # the stores live under the workdir
    assert (tmp_path / "weights").is_dir()
    assert (tmp_path / "trajectories").is_dir()


def test_online_resume_requires_resilience(tmp_path):
    """resume=True without resilience= has no snapshot to define the epoch
    line — it must fail fast, not drop-spin to max_ticks."""
    agent = GRPO(config=CFG, pad_token_id=TOK.pad_token_id, seed=0)
    with pytest.raises(ValueError, match="resume=True requires"):
        finetune_llm_reasoning_online(
            agent, make_env(), tmp_path, max_epochs=1, resume=True,
            verbose=False)


def test_fresh_run_on_reused_workdir_starts_clean(tmp_path):
    """resume=False on a dirty workdir must purge the stores: a previous
    run's newest epoch would out-number the fresh learner's, the rollout
    pod would adopt the stale adapter, and every batch would drop with
    negative lag until max_ticks."""
    from agilerl_tpu.llm.flywheel import WeightStore

    ws = WeightStore(tmp_path / "weights")
    ws.publish(37, {"w": np.zeros(2, np.float32)})  # previous-run leftover
    agent = GRPO(config=CFG, pad_token_id=TOK.pad_token_id,
                 eos_token_id=TOK.eos_token_id, group_size=2, batch_size=8,
                 max_output_tokens=4, seed=0)
    _, fit = finetune_llm_reasoning_online(
        agent, make_env(), tmp_path, max_epochs=1, evaluation_interval=1,
        max_staleness_epochs=0, verbose=False)
    assert len(fit) == 1
    assert max(ws.epochs()) == 1  # stale epoch 37 purged, fresh line 0->1


def test_online_resume_purges_precrash_store_state(tmp_path):
    """Kill-resume continuation of the learner epoch line: a crash can
    leave post-snapshot weight epochs and unconsumed trajectory batches in
    the stores. Resume must purge both — otherwise actors adopt the
    PRE-crash adapter (newer epoch number wins), last-K GC can collect the
    restored re-publish as the oldest entry, and leftover batches train
    with negative lag against the wrong weight line."""
    from agilerl_tpu.llm.flywheel import (
        TrajectoryBatch, TrajectoryStore, WeightStore)
    from agilerl_tpu.resilience import Resilience

    def make_agent():
        return GRPO(config=CFG, pad_token_id=TOK.pad_token_id,
                    eos_token_id=TOK.eos_token_id, group_size=2,
                    batch_size=8, max_output_tokens=4, index=0, seed=0)

    work = tmp_path / "run"
    res = Resilience(tmp_path / "snaps", save_every=1, handle_signals=False)
    agent, fit = finetune_llm_reasoning_online(
        make_agent(), make_env(), work, max_epochs=2, evaluation_interval=1,
        max_staleness_epochs=0, keep_weight_epochs=3, verbose=False,
        resilience=res)
    assert len(fit) == 2  # snapshots landed at done_epochs 1 and 2

    # emulate the crash aftermath: post-snapshot epochs 3/4 and an
    # unconsumed batch decoded under the pre-crash line
    fake = {"w": np.zeros(4, np.float32)}
    ws = WeightStore(work / "weights", keep_last=3)
    ws.publish(3, fake)
    ws.publish(4, fake)
    ts = TrajectoryStore(work / "trajectories")
    ts.publish(TrajectoryBatch(
        seq=0, actor_id=0, weight_epoch=4, data_epoch=0,
        ids=np.zeros((2, 4), np.int32), action_masks=np.ones((2, 3)),
        rewards=np.zeros((1, 2)), behavior_lp=np.zeros((2, 3))))

    # resume with max_epochs == restored done_epochs: the purge+republish
    # runs, the training loop does not — the store state is inspectable
    agent2 = make_agent()
    res2 = Resilience(tmp_path / "snaps", save_every=1,
                      handle_signals=False)
    finetune_llm_reasoning_online(
        agent2, make_env(), work, max_epochs=2, evaluation_interval=1,
        max_staleness_epochs=0, keep_weight_epochs=3, verbose=False,
        resilience=res2, resume=True)
    assert ts.pending() == 0  # leftover batch cleared, never trained
    epoch, lora = ws.load_latest()
    assert epoch == 2 and max(ws.epochs()) == 2  # fake 3/4 truncated
    for a, b in zip(jax.tree_util.tree_leaves(lora),
                    jax.tree_util.tree_leaves(agent2.actor.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))

    # and the restored line continues the UNINTERRUPTED prompt stream: the
    # resumed third epoch must match an unkilled 3-epoch reference (the
    # snapshot carries the rollout pod's in-flight prompt batch — dropping
    # it would re-reset the env, skip one batch, and diverge)
    agent3 = make_agent()
    res3 = Resilience(tmp_path / "snaps", save_every=1,
                      handle_signals=False)
    _, fit3 = finetune_llm_reasoning_online(
        agent3, make_env(), work, max_epochs=3, evaluation_interval=1,
        max_staleness_epochs=0, keep_weight_epochs=3, verbose=False,
        resilience=res3, resume=True)
    assert len(fit3) == 3 and max(ws.epochs()) == 3

    res_ref = Resilience(tmp_path / "snaps_ref", save_every=1,
                         handle_signals=False)
    _, fit_ref = finetune_llm_reasoning_online(
        make_agent(), make_env(), tmp_path / "ref", max_epochs=3,
        evaluation_interval=1, max_staleness_epochs=0, keep_weight_epochs=3,
        verbose=False, resilience=res_ref)
    np.testing.assert_array_equal(np.asarray(fit_ref), np.asarray(fit3))


def test_online_entry_point_mutation_guard(tmp_path):
    from agilerl_tpu.hpo import Mutations

    env = make_env()
    agent = GRPO(config=CFG, pad_token_id=TOK.pad_token_id, seed=0)
    bad = Mutations(no_mutation=0.5, architecture=0.5, parameters=0.0,
                    activation=0.0, rl_hp=0.0)
    with pytest.raises(AssertionError):
        finetune_llm_reasoning_online(
            agent, env, tmp_path, max_epochs=1, mutation=bad, verbose=False)
