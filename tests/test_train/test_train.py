"""End-to-end evolutionary training loops on tiny budgets
(parity: tests/test_train/test_train.py in the reference — every loop runs
end-to-end on small envs)."""

import numpy as np
import pytest

from agilerl_tpu.components import MultiStepReplayBuffer, ReplayBuffer
from agilerl_tpu.envs import CartPole, JaxVecEnv
from agilerl_tpu.hpo import Mutations, TournamentSelection
from agilerl_tpu.training.train_off_policy import train_off_policy
from agilerl_tpu.training.train_on_policy import train_on_policy
from agilerl_tpu.utils.utils import create_population


@pytest.fixture
def vec_env():
    return JaxVecEnv(CartPole(), num_envs=4, seed=0)


def small_net():
    return {"latent_dim": 16, "encoder_config": {"hidden_size": (32,)}}


def make_hpo(pop_size):
    tournament = TournamentSelection(2, True, pop_size, eval_loop=1,
                                     rng=np.random.default_rng(0))
    mutation = Mutations(no_mutation=0.3, architecture=0.2, parameters=0.2,
                         activation=0.1, rl_hp=0.2, rand_seed=0)
    return tournament, mutation


def test_train_off_policy_e2e(vec_env):
    pop = create_population(
        "DQN", vec_env.single_observation_space, vec_env.single_action_space,
        population_size=2, seed=0, net_config=small_net(),
        INIT_HP={"BATCH_SIZE": 32, "LR": 1e-3, "LEARN_STEP": 8},
    )
    memory = ReplayBuffer(max_size=2048)
    tournament, mutation = make_hpo(2)
    pop, fitnesses = train_off_policy(
        vec_env, "CartPole-v1", "DQN", pop, memory,
        max_steps=600, evo_steps=300, eval_steps=40, eval_loop=1,
        tournament=tournament, mutation=mutation, verbose=False,
    )
    assert len(pop) == 2
    assert all(len(f) >= 1 for f in fitnesses)
    assert all(np.isfinite(f).all() for f in fitnesses)


def test_train_off_policy_nstep(vec_env):
    pop = create_population(
        "DQN", vec_env.single_observation_space, vec_env.single_action_space,
        population_size=2, seed=0, net_config=small_net(),
        INIT_HP={"BATCH_SIZE": 32, "LR": 1e-3, "LEARN_STEP": 8},
    )
    memory = ReplayBuffer(max_size=2048)
    n_step_memory = MultiStepReplayBuffer(max_size=2048, n_step=3, gamma=0.99)
    pop, fitnesses = train_off_policy(
        vec_env, "CartPole-v1", "DQN", pop, memory,
        max_steps=400, evo_steps=200, eval_steps=40, eval_loop=1,
        n_step=True, n_step_memory=n_step_memory, verbose=False,
    )
    assert all(np.isfinite(f).all() for f in fitnesses)


def test_train_on_policy_e2e(vec_env):
    pop = create_population(
        "PPO", vec_env.single_observation_space, vec_env.single_action_space,
        population_size=2, seed=0, net_config=small_net(),
        num_envs=4, learn_step=16, batch_size=32, update_epochs=2,
    )
    tournament, mutation = make_hpo(2)
    pop, fitnesses = train_on_policy(
        vec_env, "CartPole-v1", "PPO", pop,
        max_steps=400, evo_steps=128, eval_steps=40, eval_loop=1,
        tournament=tournament, mutation=mutation, verbose=False,
    )
    assert len(pop) == 2
    assert all(np.isfinite(f).all() for f in fitnesses)


def test_checkpointing(tmp_path, vec_env):
    pop = create_population(
        "DQN", vec_env.single_observation_space, vec_env.single_action_space,
        population_size=2, seed=0, net_config=small_net(),
        INIT_HP={"BATCH_SIZE": 16, "LR": 1e-3, "LEARN_STEP": 8},
    )
    memory = ReplayBuffer(max_size=1024)
    ckpt = tmp_path / "pop.ckpt"
    train_off_policy(
        vec_env, "CartPole-v1", "DQN", pop, memory,
        max_steps=200, evo_steps=100, eval_steps=20, eval_loop=1,
        checkpoint=100, checkpoint_path=str(ckpt), overwrite_checkpoints=True,
        verbose=False,
    )
    assert (tmp_path / "pop_0.ckpt").exists()
    assert (tmp_path / "pop_1.ckpt").exists()
    # overwrite_checkpoints=False keeps per-step history instead
    from agilerl_tpu.utils.utils import save_population_checkpoint

    save_population_checkpoint(pop, str(ckpt), overwrite_checkpoints=False)
    assert any("step" in p.name for p in tmp_path.glob("pop_*_step*.ckpt"))

    from agilerl_tpu.utils.utils import load_population_checkpoint

    loaded = load_population_checkpoint("DQN", str(ckpt), [0, 1])
    assert len(loaded) == 2


def test_train_off_policy_rainbow_per_nstep(vec_env):
    """The full PER + n-step + Rainbow path through the training loop
    (regression: epsilon compat, paired-buffer alignment, priority plumbing)."""
    from agilerl_tpu.components import PrioritizedReplayBuffer
    from agilerl_tpu.utils.utils import create_population

    pop = create_population(
        "RainbowDQN", vec_env.single_observation_space, vec_env.single_action_space,
        population_size=1, seed=0, net_config=small_net(),
        INIT_HP={"BATCH_SIZE": 32, "LR": 1e-3, "LEARN_STEP": 8,
                 "V_MIN": 0.0, "V_MAX": 200.0, "NUM_ATOMS": 21, "N_STEP": 3},
    )
    memory = PrioritizedReplayBuffer(max_size=2048, alpha=0.6)
    from agilerl_tpu.components import MultiStepReplayBuffer

    n_step_memory = MultiStepReplayBuffer(max_size=2048, n_step=3, gamma=0.99)
    pop, fitnesses = train_off_policy(
        vec_env, "CartPole-v1", "RainbowDQN", pop, memory,
        max_steps=400, evo_steps=200, eval_steps=40, eval_loop=1,
        per=True, n_step=True, n_step_memory=n_step_memory, verbose=False,
    )
    assert all(np.isfinite(f).all() for f in fitnesses)
    # priorities were actually updated away from the initial max value
    pri = np.asarray(pop[0:1][0] is not None and memory.per_state.priorities)
    filled = pri[: len(memory)]
    assert (filled > 0).all() and filled.std() > 0


def test_train_off_policy_gymnasium_host_path():
    """End-to-end through real gymnasium vector envs (NEXT_STEP autoreset):
    post-done bogus transitions must be filtered from the buffer."""
    import gymnasium as gym

    env = gym.vector.SyncVectorEnv([lambda: gym.make("CartPole-v1") for _ in range(2)])
    pop = create_population(
        "DQN", env.single_observation_space, env.single_action_space,
        population_size=1, seed=0, net_config=small_net(),
        INIT_HP={"BATCH_SIZE": 32, "LR": 1e-3, "LEARN_STEP": 8},
    )
    memory = ReplayBuffer(max_size=2048)
    pop, fitnesses = train_off_policy(
        env, "CartPole-v1", "DQN", pop, memory,
        max_steps=400, evo_steps=200, eval_steps=40, eval_loop=1, verbose=False,
    )
    assert all(np.isfinite(f).all() for f in fitnesses)
    # no bogus zero-reward post-done rows: CartPole rewards are always 1.0
    stored_rewards = np.asarray(memory.state.storage["reward"])[: len(memory)]
    assert (stored_rewards == 1.0).all()
