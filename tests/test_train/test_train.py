"""End-to-end evolutionary training loops on tiny budgets
(parity: tests/test_train/test_train.py in the reference — every loop runs
end-to-end on small envs)."""

import numpy as np
import pytest

from agilerl_tpu.components import MultiStepReplayBuffer, ReplayBuffer
from agilerl_tpu.envs import CartPole, JaxVecEnv
from agilerl_tpu.hpo import Mutations, TournamentSelection
from agilerl_tpu.training.train_off_policy import train_off_policy
from agilerl_tpu.training.train_on_policy import train_on_policy
from agilerl_tpu.utils.utils import create_population


@pytest.fixture
def vec_env():
    return JaxVecEnv(CartPole(), num_envs=4, seed=0)


def small_net():
    return {"latent_dim": 16, "encoder_config": {"hidden_size": (32,)}}


def make_hpo(pop_size):
    tournament = TournamentSelection(2, True, pop_size, eval_loop=1,
                                     rng=np.random.default_rng(0))
    mutation = Mutations(no_mutation=0.3, architecture=0.2, parameters=0.2,
                         activation=0.1, rl_hp=0.2, rand_seed=0)
    return tournament, mutation


def test_train_off_policy_e2e(vec_env):
    pop = create_population(
        "DQN", vec_env.single_observation_space, vec_env.single_action_space,
        population_size=2, seed=0, net_config=small_net(),
        INIT_HP={"BATCH_SIZE": 32, "LR": 1e-3, "LEARN_STEP": 8},
    )
    memory = ReplayBuffer(max_size=2048)
    tournament, mutation = make_hpo(2)
    pop, fitnesses = train_off_policy(
        vec_env, "CartPole-v1", "DQN", pop, memory,
        max_steps=600, evo_steps=300, eval_steps=40, eval_loop=1,
        tournament=tournament, mutation=mutation, verbose=False,
    )
    assert len(pop) == 2
    assert all(len(f) >= 1 for f in fitnesses)
    assert all(np.isfinite(f).all() for f in fitnesses)


def test_train_off_policy_nstep(vec_env):
    pop = create_population(
        "DQN", vec_env.single_observation_space, vec_env.single_action_space,
        population_size=2, seed=0, net_config=small_net(),
        INIT_HP={"BATCH_SIZE": 32, "LR": 1e-3, "LEARN_STEP": 8},
    )
    memory = ReplayBuffer(max_size=2048)
    n_step_memory = MultiStepReplayBuffer(max_size=2048, n_step=3, gamma=0.99)
    pop, fitnesses = train_off_policy(
        vec_env, "CartPole-v1", "DQN", pop, memory,
        max_steps=400, evo_steps=200, eval_steps=40, eval_loop=1,
        n_step=True, n_step_memory=n_step_memory, verbose=False,
    )
    assert all(np.isfinite(f).all() for f in fitnesses)


def test_train_on_policy_e2e(vec_env):
    pop = create_population(
        "PPO", vec_env.single_observation_space, vec_env.single_action_space,
        population_size=2, seed=0, net_config=small_net(),
        num_envs=4, learn_step=16, batch_size=32, update_epochs=2,
    )
    tournament, mutation = make_hpo(2)
    pop, fitnesses = train_on_policy(
        vec_env, "CartPole-v1", "PPO", pop,
        max_steps=400, evo_steps=128, eval_steps=40, eval_loop=1,
        tournament=tournament, mutation=mutation, verbose=False,
    )
    assert len(pop) == 2
    assert all(np.isfinite(f).all() for f in fitnesses)


def test_checkpointing(tmp_path, vec_env):
    pop = create_population(
        "DQN", vec_env.single_observation_space, vec_env.single_action_space,
        population_size=2, seed=0, net_config=small_net(),
        INIT_HP={"BATCH_SIZE": 16, "LR": 1e-3, "LEARN_STEP": 8},
    )
    memory = ReplayBuffer(max_size=1024)
    ckpt = tmp_path / "pop.ckpt"
    train_off_policy(
        vec_env, "CartPole-v1", "DQN", pop, memory,
        max_steps=200, evo_steps=100, eval_steps=20, eval_loop=1,
        checkpoint=100, checkpoint_path=str(ckpt), overwrite_checkpoints=True,
        verbose=False,
    )
    assert (tmp_path / "pop_0.ckpt").exists()
    assert (tmp_path / "pop_1.ckpt").exists()
    # overwrite_checkpoints=False keeps per-step history instead
    from agilerl_tpu.utils.utils import save_population_checkpoint

    save_population_checkpoint(pop, str(ckpt), overwrite_checkpoints=False)
    assert any("step" in p.name for p in tmp_path.glob("pop_*_step*.ckpt"))

    from agilerl_tpu.utils.utils import load_population_checkpoint

    loaded = load_population_checkpoint("DQN", str(ckpt), [0, 1])
    assert len(loaded) == 2


def test_train_off_policy_rainbow_per_nstep(vec_env):
    """The full PER + n-step + Rainbow path through the training loop
    (regression: epsilon compat, paired-buffer alignment, priority plumbing)."""
    from agilerl_tpu.components import PrioritizedReplayBuffer
    from agilerl_tpu.utils.utils import create_population

    pop = create_population(
        "RainbowDQN", vec_env.single_observation_space, vec_env.single_action_space,
        population_size=1, seed=0, net_config=small_net(),
        INIT_HP={"BATCH_SIZE": 32, "LR": 1e-3, "LEARN_STEP": 8,
                 "V_MIN": 0.0, "V_MAX": 200.0, "NUM_ATOMS": 21, "N_STEP": 3},
    )
    memory = PrioritizedReplayBuffer(max_size=2048, alpha=0.6)
    from agilerl_tpu.components import MultiStepReplayBuffer

    n_step_memory = MultiStepReplayBuffer(max_size=2048, n_step=3, gamma=0.99)
    pop, fitnesses = train_off_policy(
        vec_env, "CartPole-v1", "RainbowDQN", pop, memory,
        max_steps=400, evo_steps=200, eval_steps=40, eval_loop=1,
        per=True, n_step=True, n_step_memory=n_step_memory, verbose=False,
    )
    assert all(np.isfinite(f).all() for f in fitnesses)
    # priorities were actually updated away from the initial max value
    pri = np.asarray(pop[0:1][0] is not None and memory.per_state.priorities)
    filled = pri[: len(memory)]
    assert (filled > 0).all() and filled.std() > 0


class ScriptedNextStepVecEnv:
    """2 synchronised envs, episode length 3, NEXT_STEP autoreset, reward 1.
    Obs value encodes 10*episode + step so rows are identifiable in buffers."""

    autoreset_mode = "NEXT_STEP"
    num_envs = 2

    def __init__(self):
        import gymnasium as gym

        self.single_observation_space = gym.spaces.Box(
            -np.inf, np.inf, (1,), np.float32
        )
        self.single_action_space = gym.spaces.Discrete(2)
        self.ep = 0
        self.t = 0
        self.pending_reset = False

    def _obs(self):
        return np.full((2, 1), self.ep * 10 + self.t, np.float32)

    def reset(self, **kw):
        self.ep, self.t, self.pending_reset = 0, 0, False
        return self._obs(), {}

    def step(self, action):
        if self.pending_reset:  # bogus autoreset step: action ignored
            self.ep += 1
            self.t = 0
            self.pending_reset = False
            return (self._obs(), np.zeros(2, np.float32),
                    np.zeros(2, bool), np.zeros(2, bool), {})
        self.t += 1
        done = self.t >= 3
        if done:
            self.pending_reset = True
        return (self._obs(), np.ones(2, np.float32),
                np.full(2, done), np.zeros(2, bool), {})


def test_nstep_folds_do_not_cross_next_step_autoreset():
    """Advisor (medium): with n_step=True on gymnasium NEXT_STEP autoreset
    envs, the bogus post-done row must be neutralised — folds starting at it
    must NOT accumulate the new episode's rewards onto the old terminal obs."""
    env = ScriptedNextStepVecEnv()
    pop = create_population(
        "DQN", env.single_observation_space, env.single_action_space,
        population_size=1, seed=0, net_config=small_net(),
        # huge batch size -> learning never triggers; we only inspect buffers
        INIT_HP={"BATCH_SIZE": 100_000, "LR": 1e-3, "LEARN_STEP": 8},
    )
    memory = ReplayBuffer(max_size=512)
    n_step_memory = MultiStepReplayBuffer(max_size=512, n_step=3, gamma=0.5)
    train_off_policy(
        env, "scripted", "DQN", pop, memory,
        max_steps=80, evo_steps=80, eval_steps=6, eval_loop=1, verbose=False,
        n_step=True, n_step_memory=n_step_memory,
    )
    fused_obs = np.asarray(n_step_memory.state.storage["obs"])[: len(n_step_memory)]
    fused_rew = np.asarray(n_step_memory.state.storage["reward"])[: len(n_step_memory)]
    fused_done = np.asarray(n_step_memory.state.storage["done"])[: len(n_step_memory)]
    step_in_ep = fused_obs[:, 0] % 10
    # the bogus post-done filler row (obs = terminal obs, step 3) must never
    # appear — it is substituted by a duplicate of the episode-ending row
    assert not (step_in_ep == 3).any()
    # folds starting at episode starts span the full horizon: 1 + .5 + .25
    np.testing.assert_allclose(fused_rew[step_in_ep == 0], 1.75)
    # folds starting mid-episode freeze at the terminal boundary
    np.testing.assert_allclose(fused_rew[step_in_ep == 1], 1.5)
    np.testing.assert_allclose(fused_rew[step_in_ep == 2], 1.0)
    # the duplicated episode-ending rows keep done=1, so nothing bootstraps
    # across the reset; main-buffer rows stay pure (reward always 1)
    np.testing.assert_allclose(fused_done[step_in_ep == 2], 1.0)
    main_rew = np.asarray(memory.state.storage["reward"])[: len(memory)]
    np.testing.assert_allclose(main_rew, 1.0)


def test_merge_final_obs_same_step_object_array():
    """Advisor (low): SAME_STEP autoreset envs give final_observation as an
    object array with None for non-done envs — merge per env, never wholesale."""
    from agilerl_tpu.training.train_off_policy import merge_final_obs

    next_obs = np.arange(8, dtype=np.float32).reshape(4, 2)
    final = np.empty(4, object)
    final[1] = np.array([100.0, 101.0], np.float32)
    done = np.array([False, True, False, False])
    out = merge_final_obs(next_obs, final, done)
    np.testing.assert_array_equal(out[1], [100.0, 101.0])
    np.testing.assert_array_equal(out[[0, 2, 3]], next_obs[[0, 2, 3]])
    # dense final_obs (JaxVecEnv): applied only where done
    dense_final = next_obs + 50.0
    out = merge_final_obs(next_obs, dense_final, done)
    np.testing.assert_array_equal(out[1], next_obs[1] + 50.0)
    np.testing.assert_array_equal(out[[0, 2, 3]], next_obs[[0, 2, 3]])
    # None final_obs passes through
    assert merge_final_obs(next_obs, None, done) is next_obs
    # Dict observation spaces: per-env object array of per-env dicts
    dict_next = {"a": next_obs.copy(), "b": next_obs.copy() + 10}
    dict_final = np.empty(4, object)
    dict_final[1] = {"a": np.array([100.0, 101.0], np.float32),
                     "b": np.array([200.0, 201.0], np.float32)}
    out = merge_final_obs(dict_next, dict_final, done)
    np.testing.assert_array_equal(out["a"][1], [100.0, 101.0])
    np.testing.assert_array_equal(out["b"][1], [200.0, 201.0])
    np.testing.assert_array_equal(out["a"][[0, 2, 3]], dict_next["a"][[0, 2, 3]])


def test_train_off_policy_gymnasium_host_path():
    """End-to-end through real gymnasium vector envs (NEXT_STEP autoreset):
    post-done bogus transitions must be filtered from the buffer."""
    import gymnasium as gym

    env = gym.vector.SyncVectorEnv([lambda: gym.make("CartPole-v1") for _ in range(2)])
    pop = create_population(
        "DQN", env.single_observation_space, env.single_action_space,
        population_size=1, seed=0, net_config=small_net(),
        INIT_HP={"BATCH_SIZE": 32, "LR": 1e-3, "LEARN_STEP": 8},
    )
    memory = ReplayBuffer(max_size=2048)
    pop, fitnesses = train_off_policy(
        env, "CartPole-v1", "DQN", pop, memory,
        max_steps=400, evo_steps=200, eval_steps=40, eval_loop=1, verbose=False,
    )
    assert all(np.isfinite(f).all() for f in fitnesses)
    # no bogus zero-reward post-done rows: CartPole rewards are always 1.0
    stored_rewards = np.asarray(memory.state.storage["reward"])[: len(memory)]
    assert (stored_rewards == 1.0).all()
