"""Multi-process pod launcher × GRPO flywheel (ISSUE 19 acceptance gates).

The heavy gates: an N-process flywheel (separate rollout + learner +
launcher processes) reproduces the in-process ``OnlineGRPOFlywheel``
loss/param stream exactly at staleness 0; ``kill -9`` on the learner
warm-restarts from the carried store state and CONTINUES the exact
stream; ``kill -9`` on one of two rollout processes recovers within the
probe window while both actors keep feeding one learner.

Each child process pays a full package import + GRPO compile, so these
are ``slow`` + ``launch`` (``run_tests.sh launch``); the cheap
real-subprocess harness tests live in ``tests/test_resilience/test_proc``.

The ``make_agent``/``make_env`` factories below are the children's entry
points (``tests.test_train.test_launch:make_agent``) — the SAME seed in
every process is what makes per-agent RNG streams line up across the
process split, mirroring the in-process reference built from two
separately-seeded clones (one per pod)."""

import json
import os
import signal
import time
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from agilerl_tpu.algorithms.grpo import GRPO
from agilerl_tpu.llm import model as M
from agilerl_tpu.llm.flywheel import (
    LearnerPod,
    OnlineGRPOFlywheel,
    RolloutPod,
    TrajectoryStore,
    WeightStore,
)
from agilerl_tpu.observability import MetricsRegistry
from agilerl_tpu.training.launch import (
    CURSORS_DIR,
    WEIGHTS_DIR,
    PodLauncher,
    launch_flywheel,
    read_loss_stream,
)
from agilerl_tpu.utils.llm_utils import CharTokenizer, ReasoningGym

pytestmark = [pytest.mark.launch, pytest.mark.slow]

REPO_ROOT = str(Path(__file__).resolve().parents[2])
_ENV = {"PYTHONPATH": REPO_ROOT, "JAX_PLATFORMS": "cpu"}

TOK = CharTokenizer()
CFG = M.GPTConfig(vocab_size=TOK.vocab_size, n_layer=2, n_head=4, d_model=32,
                  max_seq_len=64, dtype=jnp.float32)


def reasoning_rows(n, seed):
    rng = np.random.default_rng(seed)
    return [
        {"question": f"{a}+{b}=", "answer": str(a + b)}
        for a, b in rng.integers(0, 5, (n, 2))
    ]


def spread_reward(completion, answer, prompt):
    return 0.1 * len(completion) + float(completion.startswith(str(answer)))


def make_env(seed=0):
    return ReasoningGym(reasoning_rows(16, 0), reasoning_rows(4, 1), TOK,
                        reward_fn=spread_reward, data_batch_size=4)


def make_agent(seed=0):
    return GRPO(config=CFG, pad_token_id=TOK.pad_token_id,
                eos_token_id=TOK.eos_token_id, group_size=2, batch_size=8,
                max_output_tokens=4, seed=seed)


MAKE_AGENT = "tests.test_train.test_launch:make_agent"
MAKE_ENV = "tests.test_train.test_launch:make_env"


def _inprocess_reference(tmp_path, max_epochs, seed=0):
    """The in-process driver built the way the process split decomposes
    it: SEPARATE rollout/learner agent clones (same seed), so each pod's
    RNG stream matches its process counterpart draw for draw."""
    reg = MetricsRegistry()
    ws = WeightStore(tmp_path / "w", keep_last=max_epochs + 1, metrics=reg)
    ts = TrajectoryStore(tmp_path / "t", metrics=reg)
    learner = LearnerPod(make_agent(seed), ws, ts, max_staleness_epochs=0,
                         metrics=reg, carry_state=True)
    rollout = RolloutPod(make_agent(seed), make_env(), ws, ts, metrics=reg)
    OnlineGRPOFlywheel(rollout, learner, metrics=reg).run(max_epochs)
    return learner, ws


def _weights(root):
    return WeightStore(Path(root) / WEIGHTS_DIR, metrics=MetricsRegistry())


def _assert_tree_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# --------------------------------------------------------------------------- #
# carry-state restore (in-process unit for the learner warm-restart path)
# --------------------------------------------------------------------------- #
def _drive_lockstep(rollout, learner, to_epoch):
    while learner.epoch < to_epoch:
        rollout.poll_weights()
        rollout.rollout_once()
        learner.step()


def test_learner_carry_state_restore_continues_exact_stream(tmp_path):
    reg = MetricsRegistry()
    ref_ws = WeightStore(tmp_path / "rw", keep_last=8, metrics=reg)
    ref_ts = TrajectoryStore(tmp_path / "rt", metrics=reg)
    ref_learner = LearnerPod(make_agent(0), ref_ws, ref_ts, metrics=reg,
                             carry_state=True)
    ref_rollout = RolloutPod(make_agent(0), make_env(), ref_ws, ref_ts,
                             metrics=reg)
    _drive_lockstep(ref_rollout, ref_learner, 4)

    # interrupted run: drive to epoch 2, then REPLACE the learner with a
    # fresh agent restored from the store (the respawn path, minus the OS
    # process) and continue to epoch 4
    ws = WeightStore(tmp_path / "w", keep_last=8, metrics=reg)
    ts = TrajectoryStore(tmp_path / "t", metrics=reg)
    learner = LearnerPod(make_agent(0), ws, ts, metrics=reg, carry_state=True)
    rollout = RolloutPod(make_agent(0), make_env(), ws, ts, metrics=reg)
    _drive_lockstep(rollout, learner, 2)

    restored = LearnerPod(make_agent(0), ws, ts, metrics=reg,
                          carry_state=True, publish_initial=False)
    assert restored.restore_from_store() is True
    assert restored.epoch == 2
    assert restored.losses == learner.losses
    _drive_lockstep(rollout, restored, 4)

    assert restored.losses == ref_learner.losses
    assert restored.kls == ref_learner.kls
    assert restored.trained_seqs == ref_learner.trained_seqs
    _assert_tree_equal(restored.agent.actor.params,
                       ref_learner.agent.actor.params)
    _assert_tree_equal(restored.agent.optimizer.opt_state,
                       ref_learner.agent.optimizer.opt_state)


def test_restore_from_store_returns_false_on_fresh_root(tmp_path):
    reg = MetricsRegistry()
    ws = WeightStore(tmp_path / "w", metrics=reg)
    ts = TrajectoryStore(tmp_path / "t", metrics=reg)
    pod = LearnerPod(make_agent(0), ws, ts, metrics=reg, carry_state=True,
                     publish_initial=False)
    assert pod.restore_from_store() is False
    assert ws.latest_epoch() is None  # restore never publishes


# --------------------------------------------------------------------------- #
# equivalence gate
# --------------------------------------------------------------------------- #
def test_nproc_flywheel_matches_inprocess_driver_at_staleness_0(tmp_path):
    max_epochs = 3
    ref_learner, ref_ws = _inprocess_reference(tmp_path / "ref", max_epochs)

    root = tmp_path / "launch"
    summary = launch_flywheel(
        root, MAKE_AGENT, MAKE_ENV, max_epochs=max_epochs, num_rollouts=1,
        max_staleness_epochs=0, agent_kwargs={"seed": 0},
        lease_timeout=10.0, grace_s=30.0, timeout=600.0, env=_ENV)

    assert summary["exits"] == {"learner": 0, "rollout_0": 0}, \
        summary["statuses"]
    assert summary["orphans"] == []

    # loss stream ≡ (read back from weight-epoch manifests)
    np.testing.assert_array_equal(np.asarray(summary["losses"]),
                                  np.asarray(ref_learner.losses))
    assert len(summary["losses"]) == max_epochs

    # final params ≡ (bit-for-bit across the process split)
    got_epoch, got_lora = _weights(root).load_latest()
    ref_epoch, ref_lora = ref_ws.load_latest()
    assert got_epoch == ref_epoch == max_epochs
    _assert_tree_equal(got_lora, ref_lora)


# --------------------------------------------------------------------------- #
# kill -9 the learner: warm restart continues the exact stream
# --------------------------------------------------------------------------- #
def test_kill9_learner_warm_restarts_and_continues_exact_stream(tmp_path):
    max_epochs = 4
    ref_learner, ref_ws = _inprocess_reference(tmp_path / "ref", max_epochs)

    root = tmp_path / "launch"
    launcher = PodLauncher(root, lease_timeout=10.0, grace_s=30.0)
    kwargs = {"make_agent": MAKE_AGENT, "agent_kwargs": {"seed": 0},
              "max_epochs": max_epochs, "max_staleness_epochs": 0,
              "keep_last": max_epochs + 1}
    launcher.add_role("learner", "agilerl_tpu.training.launch:learner_role",
                      kwargs=kwargs, env=_ENV, poll_interval=0.01)
    launcher.add_role(
        "rollout_0", "agilerl_tpu.training.launch:rollout_role",
        kwargs={"make_agent": MAKE_AGENT, "agent_kwargs": {"seed": 0},
                "make_env": MAKE_ENV, "actor_id": 0,
                "max_seqs": max_epochs, "max_staleness_epochs": 0,
                "lockstep": True, "keep_last": max_epochs + 1},
        env=_ENV, poll_interval=0.01)
    launcher.start(join_timeout=300.0)

    # let the run make real progress, then SIGKILL the learner mid-flight
    ws = _weights(root)
    deadline = time.monotonic() + 300.0
    while time.monotonic() < deadline and (ws.latest_epoch() or 0) < 2:
        launcher.poll()
        time.sleep(0.05)
    assert (ws.latest_epoch() or 0) >= 2, "no progress before kill"
    victim_pid = launcher.supervisor.procs["learner"].pid
    os.kill(victim_pid, signal.SIGKILL)

    # supervisor respawns the learner (bumped incarnation)
    deadline = time.monotonic() + 60.0
    restarted = []
    while time.monotonic() < deadline and not restarted:
        restarted = [e for e in launcher.poll()
                     if e["role"] == "learner" and e["action"] == "restarted"]
        time.sleep(0.05)
    assert restarted, "learner was not respawned"
    assert launcher.supervisor.procs["learner"].spec.incarnation == 1

    summary = launcher.run(timeout=600.0)
    assert summary["statuses"]["learner"]["state"] == "done", summary
    assert summary["orphans"] == []

    # the respawned learner restored the carried state and continued the
    # EXACT loss/param stream of the uninterrupted reference
    losses = read_loss_stream(root)
    np.testing.assert_array_equal(np.asarray(losses),
                                  np.asarray(ref_learner.losses))
    got_epoch, got_lora = ws.load_latest()
    ref_epoch, ref_lora = ref_ws.load_latest()
    assert got_epoch == ref_epoch == max_epochs
    _assert_tree_equal(got_lora, ref_lora)


# --------------------------------------------------------------------------- #
# kill -9 one rollout: ≥2 actors feed one learner, fast recovery
# --------------------------------------------------------------------------- #
def _cursor_seq(root, actor):
    path = Path(root) / CURSORS_DIR / f"actor_{actor:03d}.json"
    if not path.exists():
        return 0
    return json.loads(path.read_text())["seq"]


def test_kill9_rollout_recovers_and_two_actors_feed_one_learner(tmp_path):
    # actor 1 publishes only 3 batches, the learner needs 12: the run can
    # only complete if actor 0 keeps publishing AFTER its kill -9 + respawn
    # (the completion itself proves recovery + seq-line continuation,
    # independent of how fast the respawn recompiles)
    max_epochs = 12
    root = tmp_path / "launch"
    launcher = PodLauncher(root, lease_timeout=10.0, grace_s=30.0)
    launcher.add_role(
        "learner", "agilerl_tpu.training.launch:learner_role",
        kwargs={"make_agent": MAKE_AGENT, "agent_kwargs": {"seed": 0},
                "max_epochs": max_epochs, "max_staleness_epochs": 2},
        env=_ENV, poll_interval=0.01)
    for i, seqs in enumerate((10_000, 3)):
        launcher.add_role(
            f"rollout_{i}", "agilerl_tpu.training.launch:rollout_role",
            kwargs={"make_agent": MAKE_AGENT, "agent_kwargs": {"seed": i},
                    "make_env": MAKE_ENV, "actor_id": i,
                    "max_seqs": seqs, "max_staleness_epochs": 2},
            replica=i, env=_ENV, poll_interval=0.01)
    launcher.start(join_timeout=300.0)

    # wait for real progress, then SIGKILL one rollout process
    ws = _weights(root)
    deadline = time.monotonic() + 300.0
    while time.monotonic() < deadline and (ws.latest_epoch() or 0) < 1:
        launcher.poll()
        time.sleep(0.05)
    assert (ws.latest_epoch() or 0) >= 1, "no progress before kill"
    victim = launcher.supervisor.procs["rollout_0"]
    seq_at_kill = _cursor_seq(root, 0)
    t_kill = time.monotonic()
    os.kill(victim.pid, signal.SIGKILL)

    # detection + respawn is pid-probe fast (well inside the lease window)
    restarted = []
    while time.monotonic() < t_kill + 60.0 and not restarted:
        restarted = [e for e in launcher.poll()
                     if e["role"] == "rollout_0"
                     and e["action"] == "restarted"]
        time.sleep(0.05)
    assert restarted, "rollout_0 was not respawned"
    mttr_detect_s = time.monotonic() - t_kill
    assert mttr_detect_s < 60.0

    until = lambda: launcher.statuses().get(  # noqa: E731
        "learner", {}).get("state") == "done"
    summary = launcher.run(timeout=600.0, until=until)
    assert summary["statuses"]["learner"]["state"] == "done", summary
    assert summary["orphans"] == [] and summary["escalated"] == []
    # learner + the small actor finished; the unbounded respawned actor
    # was drained gracefully by the launcher at learner completion
    assert summary["exits"]["learner"] == 0
    assert summary["exits"]["rollout_1"] == 0
    assert summary["exits"]["rollout_0"] == 3

    # the respawned actor CONTINUED its seq line past the kill point
    # (restored from the per-actor cursor, not replayed from 0)
    assert _cursor_seq(root, 0) > seq_at_kill
    assert _cursor_seq(root, 1) > 0

    # both actors' batches were TRAINED: the two seq lines overlap, so a
    # duplicate seq in trained_seqs can only come from distinct actors
    state = _weights(root).load_latest_payload()["learner_state"]
    assert len(state["trained_seqs"]) == max_epochs
    assert len(set(state["trained_seqs"])) < len(state["trained_seqs"])
    losses = read_loss_stream(root)
    assert len(losses) >= 1  # manifests carry the stream (keep_last-bounded)
