"""Checkpoint → resume round-trips for every training loop, plus the wandb
logging branch (parity: the reference's tests/test_train/test_train.py covers
these trainer branches across ~100 tests; this file is the distilled
equivalent — every one of the 8 loops must checkpoint and resume in place).
"""

import sys
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from agilerl_tpu.components import MultiAgentReplayBuffer, ReplayBuffer
from agilerl_tpu.envs import CartPole, JaxVecEnv
from agilerl_tpu.envs.multi_agent import MultiAgentJaxVecEnv, SimpleSpreadJax
from agilerl_tpu.training.train_bandits import train_bandits
from agilerl_tpu.training.train_multi_agent_off_policy import (
    train_multi_agent_off_policy,
)
from agilerl_tpu.training.train_multi_agent_on_policy import (
    train_multi_agent_on_policy,
)
from agilerl_tpu.training.train_off_policy import train_off_policy
from agilerl_tpu.training.train_offline import train_offline
from agilerl_tpu.training.train_on_policy import train_on_policy
from agilerl_tpu.utils.utils import create_population
from agilerl_tpu.wrappers import BanditEnv

NET = {"latent_dim": 16, "encoder_config": {"hidden_size": (32,)}}


def policy_leaves(agent):
    """Flat list of the acting policy's parameter arrays."""
    net = getattr(agent, agent.registry.policy_group.eval)
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(net.params)]


def assert_same_policy(a, b):
    la, lb = policy_leaves(a), policy_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(x, y)


def assert_restored(fresh_pop, trained_pop):
    """fresh_pop (post-resume) must carry trained_pop's params and steps."""
    for fresh, trained in zip(fresh_pop, trained_pop):
        assert fresh.steps[-1] == trained.steps[-1] > 0
        assert_same_policy(fresh, trained)


# --------------------------------------------------------------------------
# Single-agent loops
# --------------------------------------------------------------------------

def _dqn_pop(env, size=1):
    return create_population(
        "DQN", env.single_observation_space, env.single_action_space,
        population_size=size, seed=0, net_config=NET,
        INIT_HP={"BATCH_SIZE": 16, "LR": 1e-3, "LEARN_STEP": 8},
    )


def test_resume_off_policy_roundtrip(tmp_path):
    env = JaxVecEnv(CartPole(), num_envs=2, seed=0)
    ckpt = str(tmp_path / "dqn.ckpt")
    pop = _dqn_pop(env)
    memory = ReplayBuffer(max_size=512)
    trained, _ = train_off_policy(
        env, "CartPole-v1", "DQN", pop, memory,
        max_steps=100, evo_steps=50, eval_steps=10, eval_loop=1,
        checkpoint=50, checkpoint_path=ckpt, overwrite_checkpoints=True,
        verbose=False,
    )
    fresh = _dqn_pop(env)
    assert fresh[0].steps[-1] == 0
    # resume restores in place, then training continues from the saved steps
    resumed, fitnesses = train_off_policy(
        env, "CartPole-v1", "DQN", fresh, ReplayBuffer(max_size=512),
        max_steps=trained[0].steps[-1] + 60, evo_steps=50, eval_steps=10,
        eval_loop=1, checkpoint_path=ckpt, resume=True, verbose=False,
    )
    assert resumed[0].steps[-1] > trained[0].steps[-1]
    assert all(np.isfinite(f).all() for f in fitnesses)

    # restore-only round-trip: max_steps below saved steps -> no training,
    # params must be bit-identical to the checkpointed agent
    fresh2 = _dqn_pop(env)
    restored, _ = train_off_policy(
        env, "CartPole-v1", "DQN", fresh2, ReplayBuffer(max_size=512),
        max_steps=1, checkpoint_path=ckpt, resume=True, verbose=False,
    )
    assert_restored(restored, trained)


def test_resume_on_policy_roundtrip(tmp_path):
    env = JaxVecEnv(CartPole(), num_envs=2, seed=0)
    ckpt = str(tmp_path / "ppo.ckpt")

    def make():
        return create_population(
            "PPO", env.single_observation_space, env.single_action_space,
            population_size=1, seed=0, net_config=NET,
            num_envs=2, learn_step=16, batch_size=16, update_epochs=1,
        )

    trained, _ = train_on_policy(
        env, "CartPole-v1", "PPO", make(),
        max_steps=100, evo_steps=32, eval_steps=10, eval_loop=1,
        checkpoint=32, checkpoint_path=ckpt, overwrite_checkpoints=True,
        verbose=False,
    )
    restored, _ = train_on_policy(
        env, "CartPole-v1", "PPO", make(),
        max_steps=1, checkpoint_path=ckpt, resume=True, verbose=False,
    )
    assert_restored(restored, trained)


def _offline_dataset(n=128):
    rng = np.random.default_rng(0)
    return {
        "observations": rng.normal(size=(n, 4)).astype(np.float32),
        "actions": rng.integers(0, 2, size=(n, 1)),
        "rewards": np.ones((n, 1), np.float32),
        "next_observations": rng.normal(size=(n, 4)).astype(np.float32),
        "terminals": (rng.random((n, 1)) < 0.1).astype(np.float32),
    }


def test_resume_offline_roundtrip(tmp_path):
    env = JaxVecEnv(CartPole(), num_envs=2, seed=0)
    ckpt = str(tmp_path / "cqn.ckpt")

    def make():
        return create_population(
            "CQN", env.single_observation_space, env.single_action_space,
            population_size=1, seed=0, net_config=NET,
            INIT_HP={"BATCH_SIZE": 16, "LR": 1e-3, "LEARN_STEP": 8},
        )

    dataset = _offline_dataset()
    trained, _ = train_offline(
        env, "CartPole-v1", dataset, "CQN", make(), ReplayBuffer(max_size=256),
        max_steps=64, evo_steps=32, eval_steps=10, eval_loop=1,
        checkpoint=16, checkpoint_path=ckpt, overwrite_checkpoints=True,
        verbose=False,
    )
    restored, _ = train_offline(
        env, "CartPole-v1", dataset, "CQN", make(), ReplayBuffer(max_size=256),
        max_steps=1, checkpoint_path=ckpt, resume=True, verbose=False,
    )
    assert_restored(restored, trained)


def _bandit_env():
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 3, 60)
    centers = rng.normal(size=(3, 4)) * 2.0
    features = centers[labels] + rng.normal(size=(60, 4)) * 0.5
    return BanditEnv(features, labels)


def test_resume_bandits_roundtrip(tmp_path):
    env = _bandit_env()
    ckpt = str(tmp_path / "ucb.ckpt")

    def make():
        return create_population(
            "NeuralUCB", env.observation_space, env.action_space,
            population_size=1, seed=0, net_config=NET,
            INIT_HP={"BATCH_SIZE": 16, "LR": 1e-3, "LAMBDA": 1.0,
                     "REG": 0.000625, "LEARN_STEP": 2},
        )

    trained, _ = train_bandits(
        env, "bandit", "NeuralUCB", make(), ReplayBuffer(max_size=512),
        max_steps=60, episode_steps=30, evo_steps=30, eval_steps=10,
        eval_loop=1, checkpoint=30, checkpoint_path=ckpt,
        overwrite_checkpoints=True, verbose=False,
    )
    restored, _ = train_bandits(
        env, "bandit", "NeuralUCB", make(), ReplayBuffer(max_size=512),
        max_steps=1, checkpoint_path=ckpt, resume=True, verbose=False,
    )
    assert_restored(restored, trained)


# --------------------------------------------------------------------------
# Multi-agent loops
# --------------------------------------------------------------------------

def test_resume_multi_agent_off_policy_roundtrip(tmp_path):
    env = MultiAgentJaxVecEnv(SimpleSpreadJax(n_agents=2), num_envs=2, seed=0)
    ckpt = str(tmp_path / "maddpg.ckpt")

    def make():
        return create_population(
            "MADDPG", env.observation_spaces, env.action_spaces,
            agent_ids=env.agent_ids, population_size=1, seed=0, net_config=NET,
            INIT_HP={"BATCH_SIZE": 16, "LEARN_STEP": 8},
        )

    trained, _ = train_multi_agent_off_policy(
        env, "spread", "MADDPG", make(),
        MultiAgentReplayBuffer(max_size=512, agent_ids=env.agent_ids),
        max_steps=80, evo_steps=40, eval_steps=10, eval_loop=1,
        checkpoint=40, checkpoint_path=ckpt, overwrite_checkpoints=True,
        verbose=False,
    )
    restored, _ = train_multi_agent_off_policy(
        env, "spread", "MADDPG", make(),
        MultiAgentReplayBuffer(max_size=512, agent_ids=env.agent_ids),
        max_steps=1, checkpoint_path=ckpt, resume=True, verbose=False,
    )
    for fresh, t in zip(restored, trained):
        assert fresh.steps[-1] == t.steps[-1] > 0
        # ModuleDict-valued policy: compare per-agent leaves
        net_f = getattr(fresh, fresh.registry.policy_group.eval)
        net_t = getattr(t, t.registry.policy_group.eval)
        for k in net_t.keys():
            for x, y in zip(
                jax.tree_util.tree_leaves(net_f[k].params),
                jax.tree_util.tree_leaves(net_t[k].params),
            ):
                np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_resume_multi_agent_on_policy_roundtrip(tmp_path):
    env = MultiAgentJaxVecEnv(SimpleSpreadJax(n_agents=2), num_envs=2, seed=0)
    ckpt = str(tmp_path / "ippo.ckpt")

    def make():
        return create_population(
            "IPPO", env.observation_spaces, env.action_spaces,
            agent_ids=env.agent_ids, population_size=1, seed=0, net_config=NET,
            num_envs=2, learn_step=16, batch_size=16, update_epochs=1,
        )

    trained, _ = train_multi_agent_on_policy(
        env, "spread", "IPPO", make(),
        max_steps=80, evo_steps=32, eval_steps=10, eval_loop=1,
        checkpoint=32, checkpoint_path=ckpt, overwrite_checkpoints=True,
        verbose=False,
    )
    restored, _ = train_multi_agent_on_policy(
        env, "spread", "IPPO", make(),
        max_steps=1, checkpoint_path=ckpt, resume=True, verbose=False,
    )
    for fresh, t in zip(restored, trained):
        assert fresh.steps[-1] == t.steps[-1] > 0


# --------------------------------------------------------------------------
# LLM loops
# --------------------------------------------------------------------------

def _llm_bits():
    from agilerl_tpu.llm import model as M
    from agilerl_tpu.utils.llm_utils import CharTokenizer

    tok = CharTokenizer()
    cfg = M.GPTConfig(vocab_size=tok.vocab_size, n_layer=1, n_head=2,
                      d_model=32, max_seq_len=48, dtype=jnp.float32)
    return tok, cfg


def test_resume_llm_reasoning_roundtrip(tmp_path):
    from agilerl_tpu.algorithms.grpo import GRPO
    from agilerl_tpu.training.train_llm import finetune_llm_reasoning
    from agilerl_tpu.utils.llm_utils import ReasoningGym

    tok, cfg = _llm_bits()
    rows = [{"question": f"{a}+1=", "answer": str(a + 1)} for a in range(8)]
    env = ReasoningGym(rows[:6], rows[6:], tok,
                       reward_fn=lambda c, a, p: float(c.startswith(str(a))),
                       data_batch_size=2)
    ckpt = str(tmp_path / "grpo")

    def make():
        return [GRPO(config=cfg, pad_token_id=tok.pad_token_id,
                     eos_token_id=tok.eos_token_id, group_size=2, batch_size=4,
                     max_output_tokens=2, index=0, seed=0)]

    trained, _ = finetune_llm_reasoning(
        make(), env, max_steps=2, evaluation_interval=2, verbose=False,
        checkpoint_interval=2, checkpoint_path=ckpt,
        overwrite_checkpoints=True,
    )
    fresh = make()
    resumed, _ = finetune_llm_reasoning(
        fresh, env, max_steps=1, evaluation_interval=5, verbose=False,
        checkpoint_path=ckpt, resume=True,
    )
    # policy params restored before the single continued step ran
    assert resumed[0].steps[-1] >= trained[0].steps[-1]


def test_resume_llm_preference_roundtrip(tmp_path):
    from agilerl_tpu.algorithms.dpo import DPO
    from agilerl_tpu.training.train_llm import finetune_llm_preference
    from agilerl_tpu.utils.llm_utils import PreferenceGym

    tok, cfg = _llm_bits()
    rows = [{"prompt": f"{a}+1=", "chosen": str(a + 1), "rejected": str(a)}
            for a in range(8)]
    env = PreferenceGym(rows[:6], rows[6:], tok, data_batch_size=4)
    ckpt = str(tmp_path / "dpo")

    def make():
        return [DPO(config=cfg, pad_token_id=tok.pad_token_id,
                    eos_token_id=tok.eos_token_id, lr=1e-3, index=0, seed=0)]

    trained, _ = finetune_llm_preference(
        make(), env, max_steps=2, evaluation_interval=2, verbose=False,
        checkpoint_interval=2, checkpoint_path=ckpt,
        overwrite_checkpoints=True,
    )
    fresh = make()
    before = [np.asarray(x) for x in jax.tree_util.tree_leaves(fresh[0].lora_params)] \
        if hasattr(fresh[0], "lora_params") else None
    resumed, _ = finetune_llm_preference(
        fresh, env, max_steps=1, evaluation_interval=5, verbose=False,
        checkpoint_path=ckpt, resume=True,
    )
    assert resumed[0].steps[-1] >= trained[0].steps[-1]


# --------------------------------------------------------------------------
# wandb branch — a fake module proves the logging path executes
# --------------------------------------------------------------------------

class FakeWandb(types.ModuleType):
    def __init__(self):
        super().__init__("wandb")
        self.inits = []
        self.logged = []

    def init(self, **kwargs):
        self.inits.append(kwargs)
        return self

    def log(self, metrics, **kwargs):
        self.logged.append(dict(metrics))

    def finish(self):
        pass


@pytest.fixture
def fake_wandb(monkeypatch):
    fake = FakeWandb()
    monkeypatch.setitem(sys.modules, "wandb", fake)
    return fake


def test_wandb_branch_off_policy(fake_wandb):
    env = JaxVecEnv(CartPole(), num_envs=2, seed=0)
    pop = _dqn_pop(env)
    train_off_policy(
        env, "CartPole-v1", "DQN", pop, ReplayBuffer(max_size=512),
        max_steps=100, evo_steps=50, eval_steps=10, eval_loop=1,
        wb=True, verbose=False,
    )
    assert fake_wandb.inits, "init_wandb never initialised the run"
    assert any("eval/mean_fitness" in m for m in fake_wandb.logged)


def test_wandb_branch_on_policy(fake_wandb):
    env = JaxVecEnv(CartPole(), num_envs=2, seed=0)
    pop = create_population(
        "PPO", env.single_observation_space, env.single_action_space,
        population_size=1, seed=0, net_config=NET,
        num_envs=2, learn_step=16, batch_size=16, update_epochs=1,
    )
    train_on_policy(
        env, "CartPole-v1", "PPO", pop,
        max_steps=64, evo_steps=32, eval_steps=10, eval_loop=1,
        wb=True, verbose=False,
    )
    assert any("eval/mean_fitness" in m for m in fake_wandb.logged)


def test_save_elite_and_target_early_stop(tmp_path):
    """Trainer branches: save_elite writes the elite checkpoint after
    evolution; target fitness triggers early stop."""
    from agilerl_tpu.hpo import Mutations, TournamentSelection

    env = JaxVecEnv(CartPole(), num_envs=2, seed=0)
    pop = _dqn_pop(env, size=2)
    elite_path = tmp_path / "elite"
    elite_path.mkdir()
    pop, fitnesses = train_off_policy(
        env, "CartPole-v1", "DQN", pop, ReplayBuffer(max_size=512),
        max_steps=10_000, evo_steps=50, eval_steps=10, eval_loop=1,
        tournament=TournamentSelection(2, True, 2, 1),
        mutation=Mutations(no_mutation=1.0, architecture=0, parameters=0,
                           activation=0, rl_hp=0, rand_seed=0),
        save_elite=True, elite_path=str(elite_path),
        target=0.0,  # any finite fitness beats it -> stops after 1st eval
        verbose=False,
    )
    # early stop: far fewer steps than max_steps
    assert pop[0].steps[-1] < 1000
    assert list(elite_path.glob("*_elite.ckpt"))
    # every member got exactly one eval before stopping
    assert all(len(f) == 1 for f in fitnesses)
