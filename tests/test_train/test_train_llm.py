"""End-to-end LLM finetuning loops (parity: tests/test_train/test_train_llm.py
— runs finetune_llm_reasoning/preference with tiny models incl. evolution
branches)."""

import jax.numpy as jnp
import numpy as np
import pytest

from agilerl_tpu.algorithms.dpo import DPO
from agilerl_tpu.algorithms.grpo import GRPO
from agilerl_tpu.hpo import Mutations, TournamentSelection
from agilerl_tpu.llm import model as M
from agilerl_tpu.training.train_llm import (
    finetune_llm_preference,
    finetune_llm_reasoning,
)
from agilerl_tpu.utils.llm_utils import CharTokenizer, PreferenceGym, ReasoningGym

TOK = CharTokenizer()
CFG = M.GPTConfig(vocab_size=TOK.vocab_size, n_layer=2, n_head=4, d_model=64,
                  max_seq_len=64, dtype=jnp.float32)


def reasoning_rows(n, seed):
    rng = np.random.default_rng(seed)
    return [
        {"question": f"{a}+{b}=", "answer": str(a + b)}
        for a, b in rng.integers(0, 5, (n, 2))
    ]


def pref_rows(n, seed):
    rng = np.random.default_rng(seed)
    return [
        {"prompt": f"{a}+1=", "chosen": str(a + 1), "rejected": str(a)}
        for a in rng.integers(0, 5, n)
    ]


def test_reasoning_with_evolution():
    env = ReasoningGym(reasoning_rows(24, 0), reasoning_rows(8, 1), TOK,
                       reward_fn=lambda c, a, p: float(c.startswith(str(a))),
                       data_batch_size=4)
    pop = [GRPO(config=CFG, pad_token_id=TOK.pad_token_id,
                eos_token_id=TOK.eos_token_id, group_size=2, batch_size=8,
                max_output_tokens=4, index=i, seed=i) for i in range(2)]
    pop[1].base_params = pop[0].base_params
    tournament = TournamentSelection(2, True, 2, 1, rng=np.random.default_rng(0))
    mutation = Mutations(no_mutation=0.5, architecture=0.0, parameters=0.0,
                         activation=0.0, rl_hp=0.5, rand_seed=0)
    pop, fitnesses = finetune_llm_reasoning(
        pop, env, max_steps=4, evaluation_interval=2, verbose=False,
        tournament=tournament, mutation=mutation,
    )
    assert len(pop) == 2
    assert all(len(f) >= 1 for f in fitnesses)
    # HP mutation path only (arch/param asserted zero)
    assert all(a.mut in ("None", "lr", "beta", "group_size") for a in pop)


def test_llm_mutation_guard():
    env = ReasoningGym(reasoning_rows(8, 0), reasoning_rows(4, 1), TOK,
                       reward_fn=lambda c, a, p: 0.0, data_batch_size=4)
    pop = [GRPO(config=CFG, pad_token_id=TOK.pad_token_id, seed=0)]
    bad = Mutations(no_mutation=0.5, architecture=0.5, parameters=0.0,
                    activation=0.0, rl_hp=0.0)
    with pytest.raises(AssertionError):
        finetune_llm_reasoning(pop, env, max_steps=1, tournament=object(),
                               mutation=bad, verbose=False)


def test_preference_loop():
    env = PreferenceGym(pref_rows(16, 0), pref_rows(8, 1), TOK, data_batch_size=8)
    pop = [DPO(config=CFG, pad_token_id=TOK.pad_token_id,
               eos_token_id=TOK.eos_token_id, lr=2e-3, beta=0.3, index=i, seed=i)
           for i in range(2)]
    pop[1].base_params = pop[0].base_params
    pop, fitnesses = finetune_llm_preference(
        pop, env, max_steps=4, evaluation_interval=2, verbose=False,
    )
    assert all(len(f) >= 1 for f in fitnesses)


def test_eval_sweeps_full_test_split():
    """Fitness must be computed over the WHOLE test split, not a fixed first
    slice (VERDICT weak #8): with 10 test rows and data_batch_size=4 the
    reward_fn must see every test prompt during one agent.test()."""
    seen = []

    def reward_fn(completion, answer, prompt):
        seen.append(prompt)
        return 0.0

    test_rows = [{"question": f"{i}+0=", "answer": str(i)} for i in range(10)]
    env = ReasoningGym(reasoning_rows(8, 0), test_rows, TOK,
                       reward_fn=reward_fn, data_batch_size=4)
    agent = GRPO(config=CFG, pad_token_id=TOK.pad_token_id,
                 eos_token_id=TOK.eos_token_id, group_size=2, batch_size=4,
                 max_output_tokens=2, seed=0)
    agent.test(env)
    assert sorted(set(seen)) == sorted(r["question"] for r in test_rows)

    # PreferenceGym eval_batches covers the whole split too
    prefs = [{"prompt": f"{i}=", "chosen": str(i), "rejected": "x"}
             for i in range(7)]
    penv = PreferenceGym(prefs[:3], prefs, TOK, data_batch_size=3)
    sizes = [b["chosen_ids"].shape[0] for b in penv.eval_batches()]
    assert sizes == [3, 3, 1]


def test_eval_restores_training_batch_state():
    """agent.test() must NOT leave the gym's current batch pointing at the
    last eval window — the next training step would score completions against
    eval answers (review finding)."""
    env = ReasoningGym(reasoning_rows(8, 0),
                       [{"question": f"{i}+0=", "answer": str(i)} for i in range(5)],
                       TOK, reward_fn=lambda c, a, p: 0.0, data_batch_size=4)
    agent = GRPO(config=CFG, pad_token_id=TOK.pad_token_id,
                 eos_token_id=TOK.eos_token_id, group_size=2, batch_size=4,
                 max_output_tokens=2, seed=0)
    train_prompts = env.reset()
    current_before = env._current
    prompts_before = env._current_prompts
    agent.test(env)  # sweeps eval windows incl. a ragged final one (4+1)
    assert env._current is current_before
    assert env._current_prompts is prompts_before
