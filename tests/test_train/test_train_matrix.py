"""Per-loop trainer-branch matrix (VERDICT r3 next #5): every public
training loop × {wandb, checkpoint-cadence, eval-branch, evolution,
target-early-stop} — the distilled equivalent of the reference's ~100-cell
tests/test_train/test_train.py grid.

Budgets are tiny (compile-dominated); the fast tier keeps one loop per
branch, everything else runs in the sharded full tier.
"""

import sys
import types

import jax.numpy as jnp
import numpy as np
import pytest

from agilerl_tpu.components import MultiAgentReplayBuffer, ReplayBuffer
from agilerl_tpu.envs import CartPole, JaxVecEnv
from agilerl_tpu.envs.multi_agent import MultiAgentJaxVecEnv, SimpleSpreadJax
from agilerl_tpu.hpo import Mutations, TournamentSelection
from agilerl_tpu.training.train_bandits import train_bandits
from agilerl_tpu.training.train_llm import (
    finetune_llm_preference,
    finetune_llm_reasoning,
)
from agilerl_tpu.training.train_multi_agent_off_policy import (
    train_multi_agent_off_policy,
)
from agilerl_tpu.training.train_multi_agent_on_policy import (
    train_multi_agent_on_policy,
)
from agilerl_tpu.training.train_off_policy import train_off_policy
from agilerl_tpu.training.train_offline import train_offline
from agilerl_tpu.training.train_on_policy import train_on_policy
from agilerl_tpu.utils.utils import create_population
from agilerl_tpu.wrappers import BanditEnv

from tests.tiering import fast_core

NET = {"latent_dim": 16, "encoder_config": {"hidden_size": (32,)}}


class FakeWandb(types.ModuleType):
    def __init__(self):
        super().__init__("wandb")
        self.inits, self.logged = [], []

    def init(self, **kwargs):
        self.inits.append(kwargs)
        return self

    def log(self, metrics, **kwargs):
        self.logged.append(dict(metrics))

    def finish(self):
        pass


@pytest.fixture
def fake_wandb(monkeypatch):
    fake = FakeWandb()
    monkeypatch.setitem(sys.modules, "wandb", fake)
    return fake


def _evo(pop_size, llm=False):
    """Tournament + mutation pair; LLM loops only allow rl_hp mutations."""
    t = TournamentSelection(2, True, pop_size, eval_loop=1,
                            rng=np.random.default_rng(0))
    if llm:
        m = Mutations(no_mutation=0.5, architecture=0, parameters=0,
                      activation=0, rl_hp=0.5, rand_seed=0)
    else:
        m = Mutations(no_mutation=0.3, architecture=0.2, parameters=0.3,
                      activation=0, rl_hp=0.2, rand_seed=0)
    return t, m


# --------------------------------------------------------------------------
# loop adapters: build population/env/memory and run with branch kwargs
# --------------------------------------------------------------------------

def _run_off_policy(pop_size, kw):
    env = JaxVecEnv(CartPole(), num_envs=2, seed=0)
    pop = create_population(
        "DQN", env.single_observation_space, env.single_action_space,
        population_size=pop_size, seed=0, net_config=NET,
        INIT_HP={"BATCH_SIZE": 16, "LR": 1e-3, "LEARN_STEP": 8},
    )
    return train_off_policy(
        env, "CartPole-v1", "DQN", pop, ReplayBuffer(max_size=512),
        max_steps=kw.pop("max_steps", 100), evo_steps=50, eval_steps=10,
        eval_loop=kw.pop("eval_loop", 1), verbose=False, **kw,
    )


def _run_on_policy(pop_size, kw):
    env = JaxVecEnv(CartPole(), num_envs=2, seed=0)
    pop = create_population(
        "PPO", env.single_observation_space, env.single_action_space,
        population_size=pop_size, seed=0, net_config=NET,
        num_envs=2, learn_step=16, batch_size=16, update_epochs=1,
    )
    return train_on_policy(
        env, "CartPole-v1", "PPO", pop,
        max_steps=kw.pop("max_steps", 96), evo_steps=32, eval_steps=10,
        eval_loop=kw.pop("eval_loop", 1), verbose=False, **kw,
    )


def _run_offline(pop_size, kw):
    env = JaxVecEnv(CartPole(), num_envs=2, seed=0)
    rng = np.random.default_rng(0)
    dataset = {
        "observations": rng.normal(size=(128, 4)).astype(np.float32),
        "actions": rng.integers(0, 2, size=(128, 1)),
        "rewards": np.ones((128, 1), np.float32),
        "next_observations": rng.normal(size=(128, 4)).astype(np.float32),
        "terminals": (rng.random((128, 1)) < 0.1).astype(np.float32),
    }
    pop = create_population(
        "CQN", env.single_observation_space, env.single_action_space,
        population_size=pop_size, seed=0, net_config=NET,
        INIT_HP={"BATCH_SIZE": 16, "LR": 1e-3, "LEARN_STEP": 4},
    )
    return train_offline(
        env, "CartPole-v1", dataset, "CQN", pop, ReplayBuffer(max_size=512),
        max_steps=kw.pop("max_steps", 64), evo_steps=32, eval_steps=10,
        eval_loop=kw.pop("eval_loop", 1), verbose=False, **kw,
    )


def _run_bandits(pop_size, kw):
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 3, 60)
    centers = rng.normal(size=(3, 4)) * 2.0
    env = BanditEnv(centers[labels] + rng.normal(size=(60, 4)) * 0.5, labels)
    pop = create_population(
        "NeuralUCB", env.observation_space, env.action_space,
        population_size=pop_size, seed=0, net_config=NET,
        INIT_HP={"BATCH_SIZE": 16, "LR": 1e-3, "LAMBDA": 1.0,
                 "REG": 0.000625, "LEARN_STEP": 2},
    )
    return train_bandits(
        env, "bandit", "NeuralUCB", pop, ReplayBuffer(max_size=512),
        max_steps=kw.pop("max_steps", 60), episode_steps=30, evo_steps=30,
        eval_steps=10, eval_loop=kw.pop("eval_loop", 1), verbose=False, **kw,
    )


def _run_ma_off_policy(pop_size, kw):
    env = MultiAgentJaxVecEnv(SimpleSpreadJax(n_agents=2), num_envs=2, seed=0)
    pop = create_population(
        "MADDPG", env.observation_spaces, env.action_spaces,
        agent_ids=env.agent_ids, population_size=pop_size, seed=0,
        net_config=NET, INIT_HP={"BATCH_SIZE": 16, "LEARN_STEP": 8},
    )
    return train_multi_agent_off_policy(
        env, "spread", "MADDPG", pop,
        MultiAgentReplayBuffer(max_size=512, agent_ids=env.agent_ids),
        max_steps=kw.pop("max_steps", 80), evo_steps=40, eval_steps=10,
        eval_loop=kw.pop("eval_loop", 1), verbose=False, **kw,
    )


def _run_ma_on_policy(pop_size, kw):
    env = MultiAgentJaxVecEnv(SimpleSpreadJax(n_agents=2), num_envs=2, seed=0)
    pop = create_population(
        "IPPO", env.observation_spaces, env.action_spaces,
        agent_ids=env.agent_ids, population_size=pop_size, seed=0,
        net_config=NET, num_envs=2, learn_step=16, batch_size=16,
        update_epochs=1,
    )
    return train_multi_agent_on_policy(
        env, "spread", "IPPO", pop,
        max_steps=kw.pop("max_steps", 80), evo_steps=32, eval_steps=10,
        eval_loop=kw.pop("eval_loop", 1), verbose=False, **kw,
    )


def _llm_bits():
    from agilerl_tpu.llm import model as M
    from agilerl_tpu.utils.llm_utils import CharTokenizer

    tok = CharTokenizer()
    cfg = M.GPTConfig(vocab_size=tok.vocab_size, n_layer=1, n_head=2,
                      d_model=32, max_seq_len=48, dtype=jnp.float32)
    return tok, cfg


def _run_llm_reasoning(pop_size, kw):
    from agilerl_tpu.algorithms.grpo import GRPO
    from agilerl_tpu.utils.llm_utils import ReasoningGym

    tok, cfg = _llm_bits()
    rows = [{"question": f"{a}+1=", "answer": str(a + 1)} for a in range(8)]
    env = ReasoningGym(rows[:6], rows[6:], tok,
                       reward_fn=lambda c, a, p: float(c.startswith(str(a))),
                       data_batch_size=2)
    pop = [GRPO(config=cfg, pad_token_id=tok.pad_token_id,
                eos_token_id=tok.eos_token_id, group_size=2, batch_size=4,
                max_output_tokens=2, index=i, seed=i)
           for i in range(pop_size)]
    # translate the generic branch kwargs to this loop's names
    kw.setdefault("max_steps", 2)
    kw["evaluation_interval"] = kw.pop("eval_interval", 2)
    return finetune_llm_reasoning(pop, env, verbose=False, **kw)


def _run_llm_preference(pop_size, kw):
    from agilerl_tpu.algorithms.dpo import DPO
    from agilerl_tpu.utils.llm_utils import PreferenceGym

    tok, cfg = _llm_bits()
    rows = [{"prompt": f"{a}+1=", "chosen": str(a + 1), "rejected": str(a)}
            for a in range(8)]
    env = PreferenceGym(rows[:6], rows[6:], tok, data_batch_size=4)
    pop = [DPO(config=cfg, pad_token_id=tok.pad_token_id,
               eos_token_id=tok.eos_token_id, lr=1e-3, index=i, seed=i)
           for i in range(pop_size)]
    kw.setdefault("max_steps", 2)
    kw["evaluation_interval"] = kw.pop("eval_interval", 2)
    return finetune_llm_preference(pop, env, verbose=False, **kw)


LOOPS = {
    "off_policy": (_run_off_policy, False),
    "on_policy": (_run_on_policy, False),
    "offline": (_run_offline, False),
    "bandits": (_run_bandits, False),
    "ma_off_policy": (_run_ma_off_policy, False),
    "ma_on_policy": (_run_ma_on_policy, False),
    "llm_reasoning": (_run_llm_reasoning, True),
    "llm_preference": (_run_llm_preference, True),
}

# fast tier keeps the cheapest representative per branch; the rest is the
# sharded full tier
_FAST = {"off_policy", "llm_reasoning"}
LOOP_CELLS = fast_core(list(LOOPS), fast=_FAST)


def _finite(fitnesses):
    assert all(np.isfinite(f).all() for f in fitnesses)


@pytest.mark.parametrize("loop", LOOP_CELLS)
def test_wandb_branch(loop, fake_wandb):
    runner, _ = LOOPS[loop]
    pop, fitnesses = runner(1, {"wb": True})
    assert fake_wandb.inits, f"{loop}: init_wandb never ran"
    assert any("eval/mean_fitness" in m for m in fake_wandb.logged), (
        f"{loop}: eval metrics never logged"
    )
    _finite(fitnesses)


@pytest.mark.parametrize("loop", LOOP_CELLS)
def test_checkpoint_cadence_branch(loop, tmp_path):
    runner, llm = LOOPS[loop]
    ckpt = tmp_path / "run.ckpt"
    if llm:
        kw = {"checkpoint_interval": 1, "checkpoint_path": str(ckpt),
              "overwrite_checkpoints": False}
    else:
        # cadence WITHOUT overwrite -> step-stamped history files
        kw = {"checkpoint": 32, "checkpoint_path": str(ckpt),
              "overwrite_checkpoints": False}
        if loop == "bandits":
            kw["checkpoint"] = 30
    pop, fitnesses = runner(1, kw)
    stamped = list(tmp_path.glob("run_*step*.ckpt"))
    assert stamped, f"{loop}: no step-stamped checkpoints at the cadence"
    _finite(fitnesses)


@pytest.mark.parametrize("loop", LOOP_CELLS)
def test_eval_branch(loop):
    runner, llm = LOOPS[loop]
    if llm:
        pop, fitnesses = runner(1, {"eval_interval": 1, "max_steps": 2})
        # eval every step -> 2 fitness entries
        assert all(len(f) == 2 for f in fitnesses)
    else:
        pop, fitnesses = runner(1, {"eval_loop": 2})
        assert all(len(f) >= 1 for f in fitnesses)
    _finite(fitnesses)


@pytest.mark.parametrize("loop", LOOP_CELLS)
def test_evolution_branch(loop, tmp_path):
    runner, llm = LOOPS[loop]
    t, m = _evo(2, llm=llm)
    kw = {"tournament": t, "mutation": m,
          "save_elite": True, "elite_path": str(tmp_path)}
    if llm:
        kw["max_steps"] = 2
    pop, fitnesses = runner(2, kw)
    assert len(pop) == 2
    assert all(hasattr(a, "mut") for a in pop), f"{loop}: mutation never ran"
    assert list(tmp_path.glob("*elite*.ckpt")), f"{loop}: elite not saved"
    _finite(fitnesses)


@pytest.mark.parametrize("loop", LOOP_CELLS)
def test_target_early_stop_branch(loop):
    runner, llm = LOOPS[loop]
    if llm:
        # any finite eval reward beats -1e9 -> stop at the first eval
        pop, fitnesses = runner(1, {"max_reward": -1e9, "max_steps": 50,
                                    "eval_interval": 1})
        assert all(len(f) == 1 for f in fitnesses)
    else:
        pop, fitnesses = runner(1, {"target": -1e9, "max_steps": 100_000})
        # early stop: one eval per member, far below max_steps
        assert all(len(f) == 1 for f in fitnesses)
        assert all(a.steps[-1] < 10_000 for a in pop)
    _finite(fitnesses)
