"""Host↔device pipelining regression tests (ISSUE 2): the off-policy hot
loop must issue ≤2 device dispatches per env step after warmup (action +
amortised flush/fused-learn, vs ≥4 blocking ones before), never sync
``len(memory)``, write PER priorities back inside the learn dispatch, and
surface host/device/overlap gauges on the timeline."""

import gymnasium as gym
import jax
import numpy as np
import pytest

import agilerl_tpu.algorithms.core.base as base_mod
import agilerl_tpu.components.replay_buffer as rb_mod
from agilerl_tpu.components import (
    MultiStepReplayBuffer,
    PrioritizedReplayBuffer,
    ReplayBuffer,
)
from agilerl_tpu.training.train_off_policy import train_off_policy
from agilerl_tpu.utils.utils import create_population

NET = {"latent_dim": 16, "encoder_config": {"hidden_size": (32,)}}


class HostVecEnv:
    """Pure-host 2-env vector env (no jax anywhere): every device dispatch
    observed during training is issued by the TRAINING LOOP, so dispatch
    counts are attributable."""

    num_envs = 2

    def __init__(self, episode_len=50):
        self.single_observation_space = gym.spaces.Box(
            -1.0, 1.0, (4,), np.float32
        )
        self.single_action_space = gym.spaces.Discrete(2)
        self.rng = np.random.default_rng(0)
        self.episode_len = episode_len
        self.t = 0

    def _obs(self):
        return self.rng.normal(size=(2, 4)).astype(np.float32)

    def reset(self, **kw):
        self.t = 0
        return self._obs(), {}

    def step(self, action):
        self.t += 1
        done = np.full(2, self.t % self.episode_len == 0)
        return (self._obs(), np.ones(2, np.float32), done,
                np.zeros(2, bool), {})


@pytest.fixture
def dispatch_counter(monkeypatch):
    """Count every device dispatch the training loop can issue: calls of
    jit_fn-built functions (act / learn / fused learn) plus the replay
    buffer module's jitted entry points. Functions traced INSIDE the fused
    jit don't dispatch — inline tracing is the point — so only host-level
    calls count."""
    counts = {"n": 0}

    orig_jit_fn = base_mod.EvolvableAlgorithm.jit_fn

    def counting_jit_fn(self, name, factory, static_key=None):
        fn = orig_jit_fn(self, name, factory, static_key=static_key)

        def wrapper(*a, **k):
            counts["n"] += 1
            return fn(*a, **k)

        return wrapper

    monkeypatch.setattr(base_mod.EvolvableAlgorithm, "jit_fn", counting_jit_fn)
    for fname in ("_add", "_per_add", "_sample", "_per_sample",
                  "_per_update", "_gather"):
        orig = getattr(rb_mod, fname)

        def make(orig):
            def wrapper(*a, **k):
                counts["n"] += 1
                return orig(*a, **k)

            return wrapper

        monkeypatch.setattr(rb_mod, fname, make(orig))
    return counts


def _population(env, algo="DQN", **hp):
    INIT_HP = {"BATCH_SIZE": 16, "LR": 1e-3, "LEARN_STEP": 4}
    INIT_HP.update(hp)
    return create_population(
        algo, env.single_observation_space, env.single_action_space,
        population_size=1, seed=0, net_config=NET, INIT_HP=INIT_HP,
    )


def test_off_policy_hot_loop_dispatch_budget(dispatch_counter):
    """≤2 device dispatches per env step: action select (1/step) + flush and
    fused learn (amortised over learn_step). The legacy loop issued ≥4
    (add + sample + learn + priority round-trips)."""
    from agilerl_tpu.analysis import CompileGuard

    env = HostVecEnv()
    pop = _population(env)
    for agent in pop:
        agent.test = lambda *a, **k: 0.0  # eval dispatches aren't hot-loop
    memory = ReplayBuffer(max_size=512, seed=0)
    iters = 150  # evo_steps // num_envs
    train_off_policy(
        env, "host", "DQN", pop, memory,
        max_steps=iters * 2, evo_steps=iters * 2, eval_steps=2, eval_loop=1,
        verbose=False, seed=0, flush_every=4,
    )
    per_step = dispatch_counter["n"] / iters
    assert per_step <= 2.0, (
        f"{dispatch_counter['n']} dispatches over {iters} steps "
        f"({per_step:.2f}/step) — hot loop regressed past the 2/step budget"
    )
    # sanity: the loop really ran (1 act dispatch per step at minimum)
    assert dispatch_counter["n"] >= iters
    # steady state is also compile-free: a second pass over the SAME warmed
    # population/buffer must reuse every live program (CompileGuard is the
    # one no-recompile assertion repo-wide, ISSUE 11)
    with CompileGuard(label="off-policy steady state"):
        train_off_policy(
            env, "host", "DQN", pop, memory,
            max_steps=60, evo_steps=60, eval_steps=2, eval_loop=1,
            verbose=False, seed=0, flush_every=4,
        )


def test_per_priority_write_back_needs_no_host_round_trip():
    """With the fused path, the loop never calls update_priorities — the
    write-back rides the learn dispatch — yet priorities move."""
    env = HostVecEnv()
    pop = _population(env, BATCH_SIZE=16)
    for agent in pop:
        agent.test = lambda *a, **k: 0.0
    memory = PrioritizedReplayBuffer(max_size=512, seed=0)

    def boom(*a, **k):
        raise AssertionError(
            "host-side update_priorities called — PER write-back left "
            "the fused dispatch"
        )

    memory.update_priorities = boom
    train_off_policy(
        env, "host", "DQN", pop, memory,
        max_steps=120, evo_steps=120, eval_steps=2, eval_loop=1,
        per=True, verbose=False, seed=0,
    )
    pri = np.asarray(memory.per_state.priorities)[: len(memory)]
    assert (pri > 0).all() and pri.std() > 0


@pytest.mark.parametrize("algo", ["DDPG", "TD3"])
def test_continuous_control_routes_through_fused_path(algo, monkeypatch):
    """DDPG/TD3 must train through learn_from_buffer in train_off_policy
    (acceptance: fused path used by all four off-policy algorithms)."""
    env = HostVecEnv()
    env.single_action_space = gym.spaces.Box(-1.0, 1.0, (2,), np.float32)
    INIT_HP = {"BATCH_SIZE": 16, "LR_ACTOR": 1e-3, "LR_CRITIC": 1e-3,
               "LEARN_STEP": 4}
    pop = create_population(
        algo, env.single_observation_space, env.single_action_space,
        population_size=1, seed=0, net_config=NET, INIT_HP=INIT_HP,
    )
    for agent in pop:
        agent.test = lambda *a, **k: 0.0
    calls = {"n": 0}
    orig = type(pop[0]).learn_from_buffer

    def counting(self, *a, **k):
        calls["n"] += 1
        return orig(self, *a, **k)

    monkeypatch.setattr(type(pop[0]), "learn_from_buffer", counting)
    memory = ReplayBuffer(max_size=512, seed=0)
    train_off_policy(
        env, "host", algo, pop, memory,
        max_steps=120, evo_steps=120, eval_steps=2, eval_loop=1,
        verbose=False, seed=0,
    )
    assert calls["n"] > 0, f"{algo} never used the fused learn path"
    assert all(np.isfinite(np.asarray(leaf)).all()
               for leaf in jax.tree_util.tree_leaves(pop[0].actor.params))


def test_rainbow_per_nstep_routes_through_fused_path(monkeypatch):
    """Rainbow + PER + paired n-step through the loop: one fused dispatch
    per learn, paired batch gathered at the same indices in-jit."""
    env = HostVecEnv()
    INIT_HP = {"BATCH_SIZE": 16, "LR": 1e-3, "LEARN_STEP": 4,
               "V_MIN": 0.0, "V_MAX": 10.0, "NUM_ATOMS": 11, "N_STEP": 3}
    pop = create_population(
        "RainbowDQN", env.single_observation_space, env.single_action_space,
        population_size=1, seed=0, net_config=NET, INIT_HP=INIT_HP,
    )
    for agent in pop:
        agent.test = lambda *a, **k: 0.0
    calls = {"n": 0}
    orig = type(pop[0]).learn_from_buffer

    def counting(self, *a, **k):
        calls["n"] += 1
        return orig(self, *a, **k)

    monkeypatch.setattr(type(pop[0]), "learn_from_buffer", counting)
    memory = PrioritizedReplayBuffer(max_size=512, seed=0)
    n_step_memory = MultiStepReplayBuffer(max_size=512, n_step=3, gamma=0.99,
                                          seed=1)
    train_off_policy(
        env, "host", "RainbowDQN", pop, memory,
        max_steps=160, evo_steps=160, eval_steps=2, eval_loop=1,
        per=True, n_step=True, n_step_memory=n_step_memory,
        verbose=False, seed=0,
    )
    assert calls["n"] > 0
    assert len(memory) == len(n_step_memory)  # paired rings stay aligned


def test_timeline_emits_host_device_overlap_gauges():
    from agilerl_tpu.observability import MemorySink, MetricsRegistry, StepTimeline

    sink = MemorySink()
    reg = MetricsRegistry(sink=sink)
    tl = StepTimeline(reg, name="train", memory_stats_every=0)
    tl.step(env_steps=2)
    events = [
        tl.step(env_steps=2, host_time_s=0.008, device_time_s=0.002)
        for _ in range(3)
    ]
    assert all(e is not None for e in events)
    for e in events:
        assert e["host_time_s"] == pytest.approx(0.008)
        assert e["device_time_s"] == pytest.approx(0.002)
        assert 0.0 <= e["overlap_fraction"] <= 1.0
    assert reg.gauge("train/host_time_s").value == pytest.approx(0.008)
    assert reg.gauge("train/device_time_s").value == pytest.approx(0.002)
    assert 0.0 <= reg.gauge("train/overlap_fraction").value <= 1.0
    agg = tl.aggregate()
    for key in ("host_time_s", "device_time_s", "overlap_fraction"):
        assert key in agg


def test_training_loop_feeds_pipeline_gauges():
    """End-to-end: train_off_policy populates the host/device/overlap
    gauges and the sync-wait metric on its telemetry stream."""
    from agilerl_tpu.observability import MemorySink, MetricsRegistry, RunTelemetry

    sink = MemorySink()
    reg = MetricsRegistry(sink=sink)
    telem = RunTelemetry(registry=reg, lineage=False)
    env = HostVecEnv()
    pop = _population(env)
    for agent in pop:
        agent.test = lambda *a, **k: 0.0
    train_off_policy(
        env, "host", "DQN", pop, ReplayBuffer(max_size=256, seed=0),
        max_steps=60, evo_steps=60, eval_steps=2, eval_loop=1,
        verbose=False, telemetry=telem, seed=0,
    )
    assert reg.gauge("train/host_time_s").value > 0
    assert reg.gauge("train/device_time_s").value > 0
    assert 0.0 <= reg.gauge("train/overlap_fraction").value <= 1.0
    metrics = [e for e in sink.events if e["kind"] == "metrics"]
    assert metrics and "pipeline/sync_wait_s" in metrics[-1]
