"""End-to-end multi-agent training loops on tiny budgets
(parity: tests/test_train/ multi-agent loop coverage)."""

import numpy as np
import pytest

from agilerl_tpu.components import MultiAgentReplayBuffer
from agilerl_tpu.envs.multi_agent import MultiAgentJaxVecEnv, SimpleSpreadJax
from agilerl_tpu.hpo import Mutations, TournamentSelection
from agilerl_tpu.training.train_multi_agent_off_policy import (
    train_multi_agent_off_policy,
)
from agilerl_tpu.training.train_multi_agent_on_policy import (
    train_multi_agent_on_policy,
)
from agilerl_tpu.utils.utils import create_population

NET = {"latent_dim": 16, "encoder_config": {"hidden_size": (32,)}}


@pytest.fixture
def ma_env():
    return MultiAgentJaxVecEnv(SimpleSpreadJax(n_agents=2), num_envs=2, seed=0)


def test_train_multi_agent_off_policy_e2e(ma_env):
    pop = create_population(
        "MADDPG", ma_env.observation_spaces, ma_env.action_spaces,
        agent_ids=ma_env.agent_ids, population_size=2, seed=0, net_config=NET,
        INIT_HP={"BATCH_SIZE": 16, "LEARN_STEP": 8},
    )
    memory = MultiAgentReplayBuffer(max_size=1024, agent_ids=ma_env.agent_ids)
    pop, fitnesses = train_multi_agent_off_policy(
        ma_env, "SimpleSpread", "MADDPG", pop, memory,
        max_steps=200, evo_steps=100, eval_steps=10, eval_loop=1,
        tournament=TournamentSelection(2, True, 2, 1),
        mutation=Mutations(no_mutation=0.5, architecture=0.25, parameters=0.25,
                           activation=0.0, rl_hp=0.0, rand_seed=0),
        verbose=False,
    )
    assert len(pop) == 2
    assert all(np.isfinite(f).all() for f in fitnesses)


def test_train_multi_agent_on_policy_e2e(ma_env):
    pop = create_population(
        "IPPO", ma_env.observation_spaces, ma_env.action_spaces,
        agent_ids=ma_env.agent_ids, population_size=2, seed=0, net_config=NET,
        num_envs=2, learn_step=16, batch_size=32, update_epochs=2,
    )
    pop, fitnesses = train_multi_agent_on_policy(
        ma_env, "SimpleSpread", "IPPO", pop,
        max_steps=200, evo_steps=64, eval_steps=10, eval_loop=1,
        tournament=TournamentSelection(2, True, 2, 1),
        mutation=Mutations(no_mutation=0.6, architecture=0.2, parameters=0.2,
                           activation=0.0, rl_hp=0.0, rand_seed=0),
        verbose=False,
    )
    assert all(np.isfinite(f).all() for f in fitnesses)
