"""Learning-correctness checks on probe envs for the remaining value-based
algorithms (parity: probe-env checks, agilerl/utils/probe_envs.py:1114+)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from agilerl_tpu.algorithms import CQN, DQN, RainbowDQN
from agilerl_tpu.components import ReplayBuffer
from agilerl_tpu.envs.probe import ConstantRewardEnv, ObsDependentRewardEnv, fill_buffer_random

NET = {"latent_dim": 16, "encoder_config": {"hidden_size": (32,)}}


@pytest.mark.slow
def test_rainbow_value_convergence():
    """C51 distributional backup must converge E[Q] to the true value 1."""
    env = ConstantRewardEnv()
    agent = RainbowDQN(
        env.observation_space, env.action_space, net_config=NET,
        num_atoms=21, v_min=0.0, v_max=2.0, lr=2e-3, tau=0.5, gamma=0.9, seed=0,
    )
    buf = fill_buffer_random(env, ReplayBuffer(max_size=1024), steps=32)
    for _ in range(300):
        agent.learn(buf.sample(64))
    q = np.asarray(agent.actor(jnp.zeros((1, 1))))
    np.testing.assert_allclose(q, 1.0, atol=0.2)
    # and the atom distribution is a proper distribution
    logp = np.asarray(agent.actor(jnp.zeros((1, 1)), q_values=False))
    np.testing.assert_allclose(np.exp(logp).sum(-1), 1.0, rtol=1e-4)


@pytest.mark.slow
def test_cqn_is_conservative_on_ood_actions():
    """The CQL term must push Q of actions ABSENT from the dataset down
    relative to plain DQN trained on the same data (that is the point of
    conservative Q-learning: in-distribution actions are both taken uniformly
    so the penalty's softmax-minus-onehot gradient cancels there)."""
    env = ConstantRewardEnv()
    buf = ReplayBuffer(max_size=1024)
    rng = np.random.default_rng(0)
    for _ in range(128):  # dataset contains ONLY action 0
        buf.add({
            "obs": np.zeros(1, np.float32), "action": np.int32(0),
            "reward": np.float32(1.0), "next_obs": np.zeros(1, np.float32),
            "done": np.float32(1.0),
        })
    kwargs = dict(
        observation_space=env.observation_space, action_space=env.action_space,
        net_config=NET, lr=2e-3, tau=0.5, gamma=0.9, seed=0,
    )
    dqn = DQN(**kwargs)
    cqn = CQN(cql_alpha=1.0, **kwargs)
    for i in range(200):
        batch = buf.sample(64, key=jax.random.PRNGKey(i))
        dqn.learn(batch)
        cqn.learn(batch)
    obs = jnp.zeros((1, 1))
    q_dqn_ood = float(np.asarray(dqn.actor(obs))[0, 1])  # unseen action 1
    q_cqn_ood = float(np.asarray(cqn.actor(obs))[0, 1])
    assert q_cqn_ood < q_dqn_ood - 0.05  # conservatism on the OOD action
    # while the data action still converges near its true value
    assert abs(float(np.asarray(cqn.actor(obs))[0, 0]) - 1.0) < 0.4
