import pytest

from agilerl_tpu.algorithms import DDPG
from agilerl_tpu.envs.probe import (
    FixedObsPolicyEnv,
    check_policy_q_learning_with_probe_env,
)


@pytest.mark.slow
def test_ddpg_continuous_probe():
    env = FixedObsPolicyEnv(continuous=True)
    check_policy_q_learning_with_probe_env(
        env,
        DDPG,
        dict(
            observation_space=env.observation_space,
            action_space=env.action_space,
            lr_actor=3e-3, lr_critic=5e-3, gamma=0.9, tau=0.3,
            policy_freq=1, O_U_noise=False, seed=2,
            net_config={"latent_dim": 16, "encoder_config": {"hidden_size": (32,)}},
        ),
        learn_steps=400,
    )
