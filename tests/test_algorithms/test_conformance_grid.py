"""Algorithm conformance grid (parity: the reference exercises every
algorithm's get_action/learn/clone/save/load across parametrized
observation/action spaces via tests/helper_functions.py generators —
SURVEY.md §4). Each cell checks:

- get_action: shape/dtype/bounds, deterministic when training=False
- learn: finite loss on synthetic experiences
- clone: identical deterministic behaviour, independent parameters
- save_checkpoint -> load: identical deterministic behaviour
"""

import jax
import numpy as np
import pytest

from tests.tiering import fast_core
from gymnasium import spaces

from agilerl_tpu.algorithms import CQN, DDPG, DQN, PPO, TD3, RainbowDQN
from agilerl_tpu.components import ReplayBuffer

NET = {"latent_dim": 16, "encoder_config": {"hidden_size": (32,)}}
# image/dict spaces pick the CNN / multi-input encoders automatically; no
# encoder_config override (hidden_size is an MLP knob)
NET_AUTO = {"latent_dim": 16}

OBS_SPACES = {
    "vec": spaces.Box(-1, 1, (6,), np.float32),
    "img": spaces.Box(0, 255, (10, 10, 3), np.uint8),
    "dict": spaces.Dict(
        {
            "pos": spaces.Box(-1, 1, (4,), np.float32),
            "cam": spaces.Box(0, 255, (10, 10, 1), np.uint8),
        }
    ),
}
# the value grid additionally covers Discrete observations end-to-end
# (one-hot preprocessing through get_action/learn/save-load); the
# continuous/PPO grids stay on three obs families to bound suite runtime
# on the 1-core CI box (review finding: keep algorithm-level discrete-obs
# coverage somewhere, not only the networks encoder grid)
VALUE_OBS_SPACES = {**OBS_SPACES, "discrete": spaces.Discrete(4)}

DISC_ACT = spaces.Discrete(3)
# asymmetric bounds exercise DeterministicActor.rescale_action
BOX_ACT = spaces.Box(np.array([-2.0, 0.0], np.float32), np.array([2.0, 1.0], np.float32))


def net_for(obs_name):
    return NET if obs_name in ("vec", "discrete") else NET_AUTO


def sample_obs(space, rng, batch=None):
    """Sample a (batched) observation as numpy, matching the space's dtype."""
    if isinstance(space, spaces.Dict):
        return {k: sample_obs(s, rng, batch) for k, s in space.spaces.items()}
    if isinstance(space, spaces.Tuple):
        return tuple(sample_obs(s, rng, batch) for s in space.spaces)
    if isinstance(space, spaces.Discrete):
        n = space.n
        return rng.integers(0, n, size=() if batch is None else (batch,)).astype(np.int64)
    if isinstance(space, spaces.MultiDiscrete):
        shape = space.nvec.shape if batch is None else (batch,) + space.nvec.shape
        return (rng.random(shape) * space.nvec).astype(np.int64)
    assert isinstance(space, spaces.Box)
    shape = space.shape if batch is None else (batch,) + space.shape
    low = np.maximum(space.low, -10.0)
    high = np.minimum(space.high, 10.0)
    x = rng.random(shape) * (high - low) + low
    return x.astype(space.dtype)


def sample_action(space, rng, batch=None):
    if isinstance(space, spaces.Discrete):
        return rng.integers(0, space.n, size=() if batch is None else (batch,)).astype(
            np.int32
        )
    if isinstance(space, spaces.MultiDiscrete):
        shape = space.nvec.shape if batch is None else (batch,) + space.nvec.shape
        return (rng.random(shape) * space.nvec).astype(np.int32)
    assert isinstance(space, spaces.Box)
    shape = space.shape if batch is None else (batch,) + space.shape
    x = rng.random(shape) * (space.high - space.low) + space.low
    return x.astype(np.float32)


def fill_buffer(obs_space, act_space, n=96, seed=0, max_size=128):
    rng = np.random.default_rng(seed)
    buf = ReplayBuffer(max_size=max_size)
    for _ in range(n):
        buf.add(
            {
                "obs": sample_obs(obs_space, rng),
                "action": sample_action(act_space, rng),
                "reward": np.float32(rng.normal()),
                "next_obs": sample_obs(obs_space, rng),
                "done": np.float32(rng.random() < 0.2),
            }
        )
    return buf


def assert_same_policy(a, b, obs_space, batch=6, seed=3):
    rng = np.random.default_rng(seed)
    obs = sample_obs(obs_space, rng, batch)
    act_a = a.get_action(obs, training=False)
    act_b = b.get_action(obs, training=False)
    np.testing.assert_array_equal(np.asarray(act_a), np.asarray(act_b))


# --------------------------------------------------------------------------- #
# Value-based off-policy: DQN / Rainbow / CQN over every obs family
# --------------------------------------------------------------------------- #

VALUE_ALGOS = {
    "dqn": lambda obs, name: DQN(obs, DISC_ACT, net_config=net_for(name), seed=0),
    "double_dqn": lambda obs, name: DQN(
        obs, DISC_ACT, net_config=net_for(name), double=True, seed=0
    ),
    "rainbow": lambda obs, name: RainbowDQN(
        obs, DISC_ACT, net_config=net_for(name), v_min=-2, v_max=2, num_atoms=13, seed=0
    ),
    "cqn": lambda obs, name: CQN(obs, DISC_ACT, net_config=net_for(name), seed=0),
}


@pytest.mark.parametrize("obs_name", fast_core(list(VALUE_OBS_SPACES)))
@pytest.mark.parametrize("algo", list(VALUE_ALGOS))
class TestValueGrid:
    def _agent(self, algo, obs_name):
        return VALUE_ALGOS[algo](VALUE_OBS_SPACES[obs_name], obs_name)

    def test_get_action(self, algo, obs_name):
        agent = self._agent(algo, obs_name)
        rng = np.random.default_rng(0)
        obs = sample_obs(VALUE_OBS_SPACES[obs_name], rng, 5)
        acts = np.asarray(agent.get_action(obs))
        assert acts.shape == (5,)
        assert acts.min() >= 0 and acts.max() < DISC_ACT.n
        # deterministic greedy path
        a1 = np.asarray(agent.get_action(obs, training=False))
        a2 = np.asarray(agent.get_action(obs, training=False))
        np.testing.assert_array_equal(a1, a2)

    def test_learn_clone_saveload(self, algo, obs_name, tmp_path):
        obs_space = VALUE_OBS_SPACES[obs_name]
        agent = self._agent(algo, obs_name)
        buf = fill_buffer(obs_space, DISC_ACT)
        for _ in range(3):
            out = agent.learn(buf.sample(16))
            loss = out[0] if isinstance(out, tuple) else out
            assert np.isfinite(loss)
        clone = agent.clone(index=7)
        assert clone.index == 7
        assert_same_policy(agent, clone, obs_space)
        # clones are independent: training the original must not move the clone
        before = jax.tree_util.tree_map(np.asarray, clone.actor.params)
        agent.learn(buf.sample(16))
        after = jax.tree_util.tree_map(np.asarray, clone.actor.params)
        for x, y in zip(jax.tree_util.tree_leaves(before), jax.tree_util.tree_leaves(after)):
            np.testing.assert_array_equal(x, y)
        path = tmp_path / f"{algo}_{obs_name}.ckpt"
        agent.save_checkpoint(path)
        loaded = type(agent).load(path)
        assert_same_policy(agent, loaded, obs_space)


# --------------------------------------------------------------------------- #
# Continuous-control off-policy: DDPG / TD3 over every obs family
# --------------------------------------------------------------------------- #

CONT_ALGOS = {
    "ddpg": lambda obs, name: DDPG(obs, BOX_ACT, net_config=net_for(name), seed=0),
    "td3": lambda obs, name: TD3(obs, BOX_ACT, net_config=net_for(name), seed=0),
}


@pytest.mark.parametrize("obs_name", fast_core(list(OBS_SPACES)))
@pytest.mark.parametrize("algo", list(CONT_ALGOS))
class TestContinuousGrid:
    def test_action_bounds(self, algo, obs_name):
        agent = CONT_ALGOS[algo](OBS_SPACES[obs_name], obs_name)
        rng = np.random.default_rng(0)
        obs = sample_obs(OBS_SPACES[obs_name], rng, 5)
        a = np.asarray(agent.get_action(obs))
        assert a.shape == (5, 2)
        assert (a >= BOX_ACT.low - 1e-5).all() and (a <= BOX_ACT.high + 1e-5).all()

    def test_learn_clone_saveload(self, algo, obs_name, tmp_path):
        obs_space = OBS_SPACES[obs_name]
        agent = CONT_ALGOS[algo](obs_space, obs_name)
        buf = fill_buffer(obs_space, BOX_ACT)
        for _ in range(3):
            out = agent.learn(buf.sample(16))
            loss = out[0] if isinstance(out, tuple) else out
            assert np.isfinite(np.asarray(loss)).all()
        clone = agent.clone(index=3)
        assert_same_policy(agent, clone, obs_space)
        path = tmp_path / f"{algo}_{obs_name}.ckpt"
        agent.save_checkpoint(path)
        loaded = type(agent).load(path)
        assert_same_policy(agent, loaded, obs_space)


# --------------------------------------------------------------------------- #
# On-policy PPO: obs families x (Discrete | Box | MultiDiscrete) actions
# --------------------------------------------------------------------------- #

ACT_SPACES = {
    "disc": spaces.Discrete(3),
    "box": BOX_ACT,
    "multidisc": spaces.MultiDiscrete([3, 4]),
}


# representative cells: every obs family with discrete actions, every action
# family on vector obs (full cross would recompile 9 extra distinct programs)
PPO_CELLS = [
    ("disc", "vec"), ("disc", "img"), ("disc", "dict"),
    ("box", "vec"), ("multidisc", "vec"),
]


@pytest.mark.parametrize(
    "act_name,obs_name",
    fast_core(PPO_CELLS, is_fast=lambda c: c[1] == "vec"),
)
class TestPPOGrid:
    def _agent(self, obs_name, act_name, num_envs=4, learn_step=8):
        return PPO(
            OBS_SPACES[obs_name],
            ACT_SPACES[act_name],
            num_envs=num_envs,
            learn_step=learn_step,
            batch_size=16,
            update_epochs=1,
            net_config=net_for(obs_name),
            seed=0,
        )

    def test_action_value_logprob(self, obs_name, act_name):
        agent = self._agent(obs_name, act_name)
        rng = np.random.default_rng(0)
        obs = sample_obs(OBS_SPACES[obs_name], rng, 4)
        a, logp, v, _ = agent.get_action_and_value(obs)
        act_space = ACT_SPACES[act_name]
        if isinstance(act_space, spaces.Discrete):
            assert np.asarray(a).shape == (4,)
            assert np.asarray(a).max() < act_space.n
        elif isinstance(act_space, spaces.MultiDiscrete):
            assert np.asarray(a).shape == (4, 2)
            assert (np.asarray(a) < act_space.nvec).all()
        else:
            # unbounded diagonal Normal (reference parity: env-side clipping)
            assert np.asarray(a).shape == (4, 2)
            assert np.isfinite(np.asarray(a)).all()
        assert np.asarray(logp).shape == (4,)
        assert np.asarray(v).shape == (4,)
        assert np.isfinite(np.asarray(logp)).all()

    def test_rollout_learn_clone_saveload(self, obs_name, act_name, tmp_path):
        agent = self._agent(obs_name, act_name)
        rng = np.random.default_rng(1)
        obs_space, act_space = OBS_SPACES[obs_name], ACT_SPACES[act_name]
        obs = sample_obs(obs_space, rng, 4)
        for _ in range(agent.learn_step):
            a, logp, v, _ = agent.get_action_and_value(obs)
            agent.rollout_buffer.add(
                obs=obs,
                action=np.asarray(a),
                reward=rng.normal(size=4).astype(np.float32),
                done=(rng.random(4) < 0.1).astype(np.float32),
                value=np.asarray(v),
                log_prob=np.asarray(logp),
            )
            obs = sample_obs(obs_space, rng, 4)
        # learn() bootstraps from the post-rollout observation, which
        # collect_rollouts normally tracks on the agent
        agent._last_obs = obs
        agent._last_done = np.zeros(4, np.float32)
        loss = agent.learn()
        assert np.isfinite(loss)
        clone = agent.clone(index=2)
        o = sample_obs(obs_space, rng, 3)
        np.testing.assert_array_equal(
            np.asarray(agent.get_action(o, training=False)),
            np.asarray(clone.get_action(o, training=False)),
        )
        path = tmp_path / f"ppo_{obs_name}_{act_name}.ckpt"
        agent.save_checkpoint(path)
        loaded = PPO.load(path)
        np.testing.assert_array_equal(
            np.asarray(agent.get_action(o, training=False)),
            np.asarray(loaded.get_action(o, training=False)),
        )
