"""BPTT correctness: recurrent PPO must solve the memory probe that flat
policies cannot (cue shown only at t=0, decision at t=2)."""

import jax
import numpy as np
import pytest

from agilerl_tpu.algorithms.ppo import PPO
from agilerl_tpu.envs import JaxVecEnv
from agilerl_tpu.envs.probe import MemoryEnv
from agilerl_tpu.rollouts.on_policy import collect_rollouts


@pytest.mark.slow
def test_recurrent_ppo_solves_memory_env():
    env = MemoryEnv()
    vec = JaxVecEnv(env, num_envs=8, seed=0)
    agent = PPO(
        observation_space=env.observation_space,
        action_space=env.action_space,
        num_envs=8,
        learn_step=24,  # divisible by seq_len; episodes are 3 steps
        seq_len=3,
        batch_size=96,
        update_epochs=4,
        lr=5e-3,
        gamma=0.9,
        ent_coef=0.02,
        recurrent=True,
        seed=1,
        net_config={
            "latent_dim": 16,
            "encoder_config": {"hidden_size": 32, "num_layers": 1},
        },
    )
    rewards = []
    for i in range(60):
        r = collect_rollouts(agent, vec, n_steps=agent.learn_step)
        agent.learn()
        rewards.append(r)
    # mean reward per step approaches 1/3 (one +-1 reward every 3 steps)
    late = float(np.mean(rewards[-10:]))
    assert late > 0.15, f"recurrent PPO failed to use memory: {late:.3f}"


def test_memory_env_blank_obs():
    env = MemoryEnv()
    vec = JaxVecEnv(env, num_envs=4, seed=0)
    obs, _ = vec.reset()
    assert set(np.unique(obs[:, 1])) == {1.0}  # first-step flag
    obs2, r, term, trunc, _ = vec.step(np.zeros(4, np.int64))
    np.testing.assert_array_equal(obs2, np.zeros_like(obs2))  # cue hidden
