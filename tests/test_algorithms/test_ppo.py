import jax
import numpy as np
import pytest
from gymnasium import spaces

from agilerl_tpu.algorithms.ppo import PPO
from agilerl_tpu.envs import CartPole, JaxVecEnv
from agilerl_tpu.envs.probe import (
    FixedObsPolicyEnv,
    PolicyEnv,
    check_policy_on_policy_with_probe_env,
)
from agilerl_tpu.rollouts.on_policy import collect_rollouts

BOX = spaces.Box(-1, 1, (4,))
DISC = spaces.Discrete(2)


def make_agent(**kw):
    defaults = dict(
        observation_space=BOX,
        action_space=DISC,
        num_envs=4,
        learn_step=32,
        batch_size=32,
        update_epochs=2,
        seed=0,
    )
    defaults.update(kw)
    return PPO(**defaults)


def test_collect_and_learn():
    env_vec = JaxVecEnv(CartPole(), num_envs=4, seed=0)
    agent = make_agent(
        observation_space=env_vec.single_observation_space,
        action_space=env_vec.single_action_space,
    )
    collect_rollouts(agent, env_vec)
    assert agent.rollout_buffer.full
    loss = agent.learn()
    assert np.isfinite(loss)
    # buffer reset for next iteration
    assert int(agent.rollout_buffer.state.t) == 0
    collect_rollouts(agent, env_vec)
    loss2 = agent.learn()
    assert np.isfinite(loss2)


def test_continuous_action():
    box_act = spaces.Box(-1, 1, (2,))
    agent = make_agent(action_space=box_act)
    a, logp, v, _ = agent.get_action_and_value(np.zeros((4, 4), np.float32))
    assert a.shape == (4, 2)
    assert logp.shape == (4,)
    assert v.shape == (4,)


def test_clone_preserves_weights():
    agent = make_agent()
    clone = agent.clone(index=3)
    obs = np.zeros((2, 4), np.float32)
    a1 = agent.get_action(obs, training=False)
    a2 = clone.get_action(obs, training=False)
    np.testing.assert_array_equal(a1, a2)
    assert clone.index == 3


@pytest.mark.slow
def test_mutation_then_learn():
    env_vec = JaxVecEnv(CartPole(), num_envs=4, seed=0)
    agent = make_agent(
        observation_space=env_vec.single_observation_space,
        action_space=env_vec.single_action_space,
    )
    collect_rollouts(agent, env_vec)
    agent.learn()
    agent.actor.apply_mutation("add_latent_node")
    agent.critic.apply_mutation("add_latent_node")
    agent.reinit_optimizers()
    agent.mutation_hook()
    collect_rollouts(agent, env_vec)
    loss = agent.learn()
    assert np.isfinite(loss)


@pytest.mark.slow
@pytest.mark.parametrize("env_cls", [FixedObsPolicyEnv, PolicyEnv])
def test_probe_policy(env_cls):
    env = env_cls()
    check_policy_on_policy_with_probe_env(
        env,
        PPO,
        dict(
            observation_space=env.observation_space,
            action_space=env.action_space,
            num_envs=8,
            learn_step=16,
            batch_size=64,
            update_epochs=4,
            lr=3e-3,
            gamma=0.5,
            ent_coef=0.05,
            seed=3,
            net_config={"latent_dim": 16, "encoder_config": {"hidden_size": (32,)}},
        ),
        train_iters=80,
    )


@pytest.mark.slow
def test_recurrent_ppo_runs():
    env_vec = JaxVecEnv(CartPole(), num_envs=4, seed=0)
    agent = PPO(
        observation_space=env_vec.single_observation_space,
        action_space=env_vec.single_action_space,
        num_envs=4,
        learn_step=32,
        batch_size=32,
        update_epochs=1,
        recurrent=True,
        seq_len=8,
        seed=0,
    )
    collect_rollouts(agent, env_vec)
    loss = agent.learn()
    assert np.isfinite(loss)
