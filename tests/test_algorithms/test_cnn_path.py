"""End-to-end CNN (image-obs) path: DQN with an EvolvableCNN encoder on the
on-device rendered VisualCartPole (the Atari-workload stand-in)."""

import numpy as np
import pytest

from agilerl_tpu.algorithms import DQN
from agilerl_tpu.components import ReplayBuffer
from agilerl_tpu.envs import JaxVecEnv
from agilerl_tpu.envs.classic import VisualCartPole


@pytest.mark.slow
def test_cnn_dqn_end_to_end():
    env = JaxVecEnv(VisualCartPole(size=24), num_envs=4, seed=0)
    agent = DQN(
        env.single_observation_space, env.single_action_space,
        lr=1e-3, batch_size=32, learn_step=4, seed=0,
        net_config={
            "latent_dim": 32,
            "encoder_config": {
                "channel_size": (8, 8), "kernel_size": (3, 3), "stride_size": (2, 2),
            },
        },
    )
    assert agent.actor.config.encoder_kind == "cnn"
    buf = ReplayBuffer(max_size=2048)
    obs, _ = env.reset()
    for step in range(60):
        action = agent.get_action(obs, epsilon=0.5)
        next_obs, reward, term, trunc, _ = env.step(action)
        buf.add({"obs": obs, "action": action,
                 "reward": np.asarray(reward, np.float32),
                 "next_obs": next_obs, "done": np.asarray(term, np.float32)},
                batched=True)
        obs = next_obs
        if len(buf) > 64 and step % 4 == 0:
            loss = agent.learn(buf.sample(32))
            assert np.isfinite(loss)
    # CNN arch mutations keep working end-to-end
    agent.actor.apply_mutation("encoder.add_channel")
    agent.actor_target.config = agent.actor.config
    import jax, jax.numpy as jnp

    agent.actor_target.params = jax.tree_util.tree_map(jnp.copy, agent.actor.params)
    agent.reinit_optimizers()
    agent.mutation_hook()
    assert np.isfinite(agent.learn(buf.sample(32)))
    fitness = agent.test(env, max_steps=50, loop=1)
    assert np.isfinite(fitness)
