import jax
import jax.numpy as jnp
import numpy as np
import pytest
from gymnasium import spaces

from agilerl_tpu.algorithms import CQN, DDPG, TD3, NeuralTS, NeuralUCB, RainbowDQN
from agilerl_tpu.components import PrioritizedReplayBuffer, ReplayBuffer
from agilerl_tpu.wrappers.learning import BanditEnv

BOX = spaces.Box(-1, 1, (4,))
DISC = spaces.Discrete(2)
ACT_BOX = spaces.Box(-1, 1, (2,))
NET = {"latent_dim": 16, "encoder_config": {"hidden_size": (32,)}}


def fill_buffer(buf, continuous=False, n=128, seed=0):
    rng = np.random.default_rng(seed)
    for i in range(n):
        buf.add({
            "obs": rng.normal(size=4).astype(np.float32),
            "action": (rng.uniform(-1, 1, 2).astype(np.float32) if continuous
                       else np.int32(i % 2)),
            "reward": np.float32(1.0),
            "next_obs": rng.normal(size=4).astype(np.float32),
            "done": np.float32(1.0),
        })
    return buf


class TestRainbow:
    @pytest.mark.slow
    def test_action_and_learn(self):
        agent = RainbowDQN(BOX, DISC, net_config=NET, v_min=0, v_max=2,
                           num_atoms=21, lr=1e-3, seed=0)
        acts = agent.get_action(np.zeros((6, 4), np.float32))
        assert acts.shape == (6,)
        buf = fill_buffer(ReplayBuffer(max_size=256))
        losses = [agent.learn(buf.sample(32))[0] for _ in range(100)]
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]
        # with done=1 everywhere and reward 1, E[Q] -> 1
        q = np.asarray(agent.actor(jnp.zeros((1, 4))))
        assert abs(q.mean() - 1.0) < 0.4

    @pytest.mark.slow
    def test_per_priorities(self):
        agent = RainbowDQN(BOX, DISC, net_config=NET, v_min=0, v_max=2, seed=0)
        buf = PrioritizedReplayBuffer(max_size=256)
        fill_buffer(buf)
        batch, idxs, weights = buf.sample(16, beta=0.4, key=jax.random.PRNGKey(0))
        loss, new_pri = agent.learn((batch, idxs, weights))
        assert np.isfinite(loss)
        assert new_pri.shape == (16,)
        assert (new_pri > 0).all()
        buf.update_priorities(idxs, new_pri)

    def test_clone(self):
        agent = RainbowDQN(BOX, DISC, net_config=NET, seed=0)
        clone = agent.clone(index=5)
        obs = np.zeros((2, 4), np.float32)
        np.testing.assert_array_equal(
            agent.get_action(obs, training=False), clone.get_action(obs, training=False)
        )


class TestDDPG:
    def test_action_bounds_and_noise(self):
        agent = DDPG(BOX, ACT_BOX, net_config=NET, seed=0)
        a = agent.get_action(np.zeros((5, 4), np.float32))
        assert a.shape == (5, 2)
        assert (a >= -1).all() and (a <= 1).all()
        a_det = agent.get_action(np.zeros((5, 4), np.float32), training=False)
        a_det2 = agent.get_action(np.zeros((5, 4), np.float32), training=False)
        np.testing.assert_array_equal(a_det, a_det2)

    def test_learn(self):
        agent = DDPG(BOX, ACT_BOX, net_config=NET, lr_actor=1e-3, lr_critic=1e-3, seed=0)
        buf = fill_buffer(ReplayBuffer(max_size=256), continuous=True)
        losses = [agent.learn(buf.sample(32)) for _ in range(60)]
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]

    def test_checkpoint_roundtrip(self, tmp_path):
        agent = DDPG(BOX, ACT_BOX, net_config=NET, seed=0)
        agent.save_checkpoint(tmp_path / "ddpg.ckpt")
        loaded = DDPG.load(tmp_path / "ddpg.ckpt")
        obs = np.zeros((2, 4), np.float32)
        np.testing.assert_array_equal(
            agent.get_action(obs, training=False), loaded.get_action(obs, training=False)
        )


class TestTD3:
    def test_learn_and_policy_delay(self):
        agent = TD3(BOX, ACT_BOX, net_config=NET, policy_freq=2, seed=0)
        buf = fill_buffer(ReplayBuffer(max_size=256), continuous=True)
        actor_before = np.asarray(agent.actor.params["head"]["output"]["kernel"]).copy()
        agent.learn(buf.sample(32))  # counter=1: no actor update
        np.testing.assert_array_equal(
            actor_before, np.asarray(agent.actor.params["head"]["output"]["kernel"])
        )
        agent.learn(buf.sample(32))  # counter=2: actor updates
        assert not np.array_equal(
            actor_before, np.asarray(agent.actor.params["head"]["output"]["kernel"])
        )

    def test_twin_targets_mirror(self):
        agent = TD3(BOX, ACT_BOX, net_config=NET, seed=0)
        assert agent.critic_2_target.config == agent.critic_2.config


class TestCQN:
    def test_learn(self):
        agent = CQN(BOX, DISC, net_config=NET, lr=1e-3, seed=0)
        buf = fill_buffer(ReplayBuffer(max_size=256))
        losses = [agent.learn(buf.sample(32)) for _ in range(50)]
        assert np.isfinite(losses).all()


class TestBandits:
    def make_env(self):
        rng = np.random.default_rng(0)
        features = rng.normal(size=(64, 4)).astype(np.float32)
        targets = (features[:, 0] > 0).astype(np.int64)
        return BanditEnv(features, targets)

    @pytest.mark.parametrize("cls", [NeuralUCB, NeuralTS])
    def test_bandit_learns(self, cls):
        env = self.make_env()
        obs_space = spaces.Box(-np.inf, np.inf, (env.context_dim,))
        act_space = spaces.Discrete(env.arms)
        agent = cls(obs_space, act_space, net_config=NET, lr=3e-3, seed=0)
        buf = ReplayBuffer(max_size=512)
        context = env.reset()
        # warmup + train
        for step in range(150):
            arm = agent.get_action(context)
            next_context, reward = env.step(arm)
            buf.add({"obs": context[int(arm)], "reward": reward,
                     "action": np.int32(arm), "next_obs": context[int(arm)],
                     "done": np.float32(1)})
            context = next_context
            if len(buf) >= 32 and step % 2 == 0:
                agent.learn(buf.sample(32))
        score = agent.test(env, max_steps=50)
        assert score > 0.6  # better than random (0.5)
