import numpy as np
import pytest

from agilerl_tpu.algorithms import IPPO, MATD3
from agilerl_tpu.components import MultiAgentReplayBuffer
from agilerl_tpu.envs.multi_agent import MultiAgentJaxVecEnv, SimpleSpreadJax
from agilerl_tpu.hpo import Mutations, TournamentSelection

NET = {"latent_dim": 16, "encoder_config": {"hidden_size": (32,)}}


def make_env(continuous=False, num_envs=2):
    return MultiAgentJaxVecEnv(
        SimpleSpreadJax(n_agents=2, continuous=continuous), num_envs=num_envs, seed=0
    )


class TestMATD3:
    def test_learn(self):
        env = make_env(continuous=True)
        agent = MATD3(
            observation_spaces=env.observation_spaces,
            action_spaces=env.action_spaces,
            agent_ids=env.agent_ids,
            net_config=NET, seed=0, policy_freq=2,
        )
        buf = MultiAgentReplayBuffer(max_size=256, agent_ids=env.agent_ids)
        obs, _ = env.reset()
        for _ in range(40):
            actions = agent.get_action(obs)
            next_obs, rew, term, trunc, _ = env.step(actions)
            done = {a: np.asarray(term[a], np.float32) for a in env.agent_ids}
            buf.save_to_memory(obs, actions, rew, next_obs, done, is_vectorised=True)
            obs = next_obs
        losses = [agent.learn(buf.sample(32)) for _ in range(6)]
        assert np.isfinite(losses).all()

    def test_clone(self):
        env = make_env(continuous=True)
        agent = MATD3(
            observation_spaces=env.observation_spaces,
            action_spaces=env.action_spaces,
            agent_ids=env.agent_ids, net_config=NET, seed=0,
        )
        clone = agent.clone(index=2)
        obs, _ = env.reset()
        a1, a2 = agent.get_action(obs, training=False), clone.get_action(obs, training=False)
        for aid in env.agent_ids:
            np.testing.assert_array_equal(a1[aid], a2[aid])


class TestIPPO:
    @pytest.mark.parametrize("continuous", [False, True])
    def test_collect_and_learn(self, continuous):
        env = make_env(continuous)
        agent = IPPO(
            observation_spaces=env.observation_spaces,
            action_spaces=env.action_spaces,
            agent_ids=env.agent_ids,
            net_config=NET, num_envs=2, learn_step=16, batch_size=32,
            update_epochs=2, seed=0,
        )
        agent.collect_rollouts(env)
        loss = agent.learn()
        assert np.isfinite(loss)
        # groups share nets: only one actor for the homogeneous group
        assert list(agent.actors.keys()) == ["agent"]

    def test_test_loop(self):
        env = make_env()
        agent = IPPO(
            observation_spaces=env.observation_spaces,
            action_spaces=env.action_spaces,
            agent_ids=env.agent_ids, net_config=NET, num_envs=2,
            learn_step=8, seed=0,
        )
        assert np.isfinite(agent.test(env, max_steps=10, loop=1))


class TestMultiAgentEvolution:
    @pytest.mark.slow
    def test_tournament_and_mutation(self):
        env = make_env()
        pop = [
            MATD3(
                observation_spaces=env.observation_spaces,
                action_spaces=env.action_spaces,
                agent_ids=env.agent_ids, net_config=NET, seed=i, index=i,
            )
            for i in range(3)
        ]
        for i, a in enumerate(pop):
            a.fitness = [float(i)]
        ts = TournamentSelection(2, True, 3, 1, rng=np.random.default_rng(0))
        mut = Mutations(no_mutation=0.25, architecture=0.5, parameters=0.25,
                        activation=0, rl_hp=0, rand_seed=0)
        elite, new_pop = ts.select(pop)
        new_pop = mut.mutation(new_pop)
        obs, _ = env.reset()
        for agent in new_pop:
            actions = agent.get_action(obs, training=False)
            assert set(actions) == set(env.agent_ids)
            # homogeneous architecture maintained across sub-agents
            cfgs = {str(agent.actors[a].config) for a in agent.agent_ids}
            assert len(cfgs) == 1
