import jax.numpy as jnp
import numpy as np
import pytest

from agilerl_tpu.algorithms.ilql import BC_LM, ILQL
from agilerl_tpu.data.rl_data import Language_Observation, RL_Dataset
from agilerl_tpu.llm.model import GPTConfig
from agilerl_tpu.utils.llm_utils import CharTokenizer

TOK = CharTokenizer()
CFG = GPTConfig(vocab_size=TOK.vocab_size, n_layer=2, n_head=4, d_model=64,
                max_seq_len=32, dtype=jnp.float32)


def make_dataset(n=32, seed=0):
    rng = np.random.default_rng(seed)
    obs = []
    for _ in range(n):
        a = int(rng.integers(0, 5))
        good = rng.random() < 0.5
        answer = str(a + 1) if good else str(a)
        obs.append(Language_Observation(
            sequence=[(f"{a}+1=", None), (answer, 1.0 if good else -1.0)],
        ))
    return RL_Dataset(obs, TOK, max_len=8)


def test_rl_dataset_shapes():
    ds = make_dataset()
    batch = ds.sample_batch(4, np.random.default_rng(0))
    assert batch["tokens"].shape == (4, 8)
    assert batch["rewards"].shape == (4, 8)
    # reward lands on the final answer token
    assert set(np.unique(batch["rewards"])) <= {-1.0, 0.0, 1.0}


def test_ilql_learn_and_act():
    ds = make_dataset()
    agent = ILQL(config=CFG, lr=1e-3, seed=0)
    rng = np.random.default_rng(0)
    losses = [agent.learn(ds.sample_batch(8, rng)) for _ in range(10)]
    assert np.isfinite(losses).all()
    toks = np.zeros((2, 4), np.int32)
    mask = np.ones((2, 4), np.int32)
    acts = agent.get_action(toks, mask)
    assert acts.shape == (2,)


def test_bc_lm_loss_decreases():
    ds = make_dataset(64)
    agent = BC_LM(config=CFG, lr=3e-3, seed=0)
    rng = np.random.default_rng(0)
    losses = [agent.learn(ds.sample_batch(16, rng)) for _ in range(30)]
    assert losses[-1] < losses[0]
    comp, cmask = agent.generate(np.ones((1, 4), np.int32), np.ones((1, 4), np.int32),
                                 max_new_tokens=4)
    assert comp.shape == (1, 4)


@pytest.mark.slow
def test_ilql_policy_generation_prefers_rewarded_tokens():
    """VERDICT #6: the acting policy (sample/greedy/beam over the Q/V-
    reweighted LM) must select the reward-preferred continuation after
    training on a dataset where only '8' is rewarded for prompt '7+1='."""
    from agilerl_tpu.algorithms.ilql import ILQL_Policy

    good = TOK.encode("8")[0]
    obs = []
    for _ in range(16):
        obs.append(Language_Observation(sequence=[("7+1=", None), ("8", 1.0)]))
        obs.append(Language_Observation(sequence=[("7+1=", None), ("9", -1.0)]))
    ds = RL_Dataset(obs, TOK, max_len=8)
    agent = ILQL(config=CFG, lr=3e-3, gamma=0.9, cql_weight=0.0, seed=0)
    rng = np.random.default_rng(0)
    for _ in range(60):
        agent.learn(ds.sample_batch(16, rng))

    toks = np.asarray([TOK.encode("7+1=")] * 2, np.int32)
    mask = np.ones_like(toks)
    P = toks.shape[1]

    # greedy and beam must both pick the rewarded token first
    g_toks, g_mask = agent.generate(toks, mask, max_new_tokens=2, mode="greedy",
                                    q_scale=2.0)
    assert g_toks.shape == (2, P + 2)
    assert (g_toks[:, P] == good).all(), g_toks[:, P]
    assert (np.asarray(g_mask)[:, P] == 1).all()

    policy = ILQL_Policy(agent, kind="beam", max_new_tokens=2, beam_width=3,
                         q_scale=2.0)
    b_toks, b_mask = policy.act(toks, mask)
    assert b_toks.shape == (2, P + 2)
    assert (b_toks[:, P] == good).all(), b_toks[:, P]

    # sampling at low temperature should overwhelmingly agree
    s_toks, _ = agent.generate(toks, mask, max_new_tokens=1, mode="sample",
                               temperature=0.1, q_scale=2.0)
    assert (s_toks[:, P] == good).all()


@pytest.mark.slow
def test_ilql_rewards_shape_q_values():
    """After the token-alignment fix, Q(prompt, good_token) must rise above
    Q(prompt, bad_token) when only 'good' completions are rewarded."""
    import jax
    from agilerl_tpu.modules import layers as L
    from agilerl_tpu.llm import model as M

    good, bad = TOK.encode("8")[0], TOK.encode("9")[0]
    obs = []
    for _ in range(16):
        obs.append(Language_Observation(sequence=[("7+1=", None), ("8", 1.0)]))
        obs.append(Language_Observation(sequence=[("7+1=", None), ("9", -1.0)]))
    ds = RL_Dataset(obs, TOK, max_len=8)
    agent = ILQL(config=CFG, lr=3e-3, gamma=0.9, cql_weight=0.0, seed=0)
    rng = np.random.default_rng(0)
    for _ in range(60):
        agent.learn(ds.sample_batch(16, rng))
    toks = np.asarray([TOK.encode("7+1=")], np.int32)
    mask = np.ones_like(toks)
    hidden, _ = M.forward(CFG, agent.actor.params["gpt"], jnp.asarray(toks),
                          attention_mask=jnp.asarray(mask))
    qs = np.asarray(L.dense_apply(agent.actor.params["q_head"], hidden))[0, -1]
    assert qs[good] > qs[bad] + 0.2, (qs[good], qs[bad])


def test_double_q_heads_and_hard_update():
    """Twin Q heads regress to the shared TD target; targets track via polyak
    and hard_update copies exactly (parity: ilql.py double_q / hard_update)."""
    ds = make_dataset()
    agent = ILQL(config=CFG, lr=1e-3, seed=0, double_q=True)
    assert "q2_head" in agent.actor.params
    assert "q2_head" in agent.target_q.params
    rng = np.random.default_rng(0)
    before_t = np.asarray(agent.target_q.params["q2_head"]["kernel"]).copy()
    for _ in range(3):
        loss = agent.learn(ds.sample_batch(8, rng))
        assert np.isfinite(loss)
    after_t = np.asarray(agent.target_q.params["q2_head"]["kernel"])
    assert not np.array_equal(before_t, after_t)  # polyak moved the target
    agent.hard_update()
    np.testing.assert_array_equal(
        np.asarray(agent.target_q.params["q_head"]["kernel"]),
        np.asarray(agent.actor.params["q_head"]["kernel"]),
    )
    np.testing.assert_array_equal(
        np.asarray(agent.target_q.params["q2_head"]["kernel"]),
        np.asarray(agent.actor.params["q2_head"]["kernel"]),
    )
    # single-Q config still works and has no q2 head
    single = ILQL(config=CFG, lr=1e-3, seed=0, double_q=False)
    assert "q2_head" not in single.actor.params
    assert np.isfinite(single.learn(ds.sample_batch(8, rng)))


def test_dm_loss_pushes_margin():
    ds = make_dataset()
    agent = ILQL(config=CFG, lr=1e-3, seed=0, dm_weight=1.0, dm_margin=0.1)
    rng = np.random.default_rng(0)
    loss = agent.learn(ds.sample_batch(8, rng))
    assert np.isfinite(loss)


def test_top_advantage_ngrams():
    from agilerl_tpu.algorithms.ilql import TopAdvantageNGrams

    ds = make_dataset()
    agent = ILQL(config=CFG, lr=1e-3, seed=0)
    rng = np.random.default_rng(0)
    for _ in range(2):
        agent.learn(ds.sample_batch(8, rng))
    probe = TopAdvantageNGrams(tokenizer=TOK, n_gram=2, print_k=5)
    probe.evaluate(agent, ds.sample_batch(8, rng))
    top = probe.top()
    assert 0 < len(top) <= 5
    text, adv = top[0]
    assert isinstance(text, str) and np.isfinite(adv)
    # sorted descending by mean advantage
    advs = [a for _, a in top]
    assert advs == sorted(advs, reverse=True)


def test_ilql_evaluator_reward_rollout():
    from agilerl_tpu.algorithms.ilql import ILQL_Evaluator

    agent = ILQL(config=CFG, lr=1e-3, seed=0)

    class PromptEnv:
        def eval_prompts(self):
            seqs = [TOK.encode("3+1=") for _ in range(2)]
            ids = np.asarray(seqs, np.int32)
            pad = np.zeros((2, 12 - ids.shape[1]), np.int32)
            tokens = np.concatenate([ids, pad], axis=1)
            mask = (tokens != 0).astype(np.float32)
            yield tokens, mask

        def reward(self, tokens, mask):
            return np.ones(tokens.shape[0], np.float32)

    ev = ILQL_Evaluator(PromptEnv(), kind="greedy", max_new_tokens=2)
    metrics = ev.evaluate(agent)
    assert metrics["env_reward"] == 1.0 and metrics["episodes"] == 2.0
    assert ev.dump()["results"]
