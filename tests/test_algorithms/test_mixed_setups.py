"""MIXED/HETEROGENEOUS multi-agent setups (VERDICT r3 next #4).

Parity: setup classification /root/reference/agilerl/algorithms/core/base.py:1482,
per-group net-config building :1606, analogous-mutation search
/root/reference/agilerl/hpo/mutation.py:1163 — plus the transactional
rollback that replaces the reference's warn-and-continue.
"""

import warnings

import jax
import numpy as np
import pytest
from gymnasium import spaces

from agilerl_tpu.algorithms.core.base import MultiAgentSetup
from agilerl_tpu.algorithms.ippo import IPPO
from agilerl_tpu.algorithms.maddpg import MADDPG
from agilerl_tpu.hpo.mutation import Mutations

VEC = spaces.Box(-1, 1, (4,), np.float32)
IMG = spaces.Box(0, 255, (12, 12, 3), np.uint8)
ACT = spaces.Discrete(3)

MIXED_OBS = {"scout_0": VEC, "scout_1": VEC, "cam_0": IMG}
MIXED_ACT = {a: ACT for a in MIXED_OBS}
# a flat config carrying BOTH families' keys: each group keeps only its own
NET = {"latent_dim": 16,
       "encoder_config": {"hidden_size": (32,), "channel_size": (8,),
                          "kernel_size": (3,), "stride_size": (2,)}}


def test_setup_classification():
    homo = MADDPG({"a_0": VEC, "a_1": VEC}, {"a_0": ACT, "a_1": ACT},
                  net_config={"latent_dim": 16,
                              "encoder_config": {"hidden_size": (32,)}},
                  seed=0)
    assert homo.get_setup() is MultiAgentSetup.HOMOGENEOUS
    mixed = MADDPG(MIXED_OBS, MIXED_ACT, net_config=NET, seed=0)
    assert mixed.get_setup() is MultiAgentSetup.MIXED
    hetero = MADDPG(
        {"a": VEC, "b": IMG},
        {"a": ACT, "b": ACT},
        net_config=NET, seed=0,
    )
    assert hetero.get_setup() is MultiAgentSetup.HETEROGENEOUS
    assert len(mixed.unique_observation_spaces) == 2


def test_build_net_config_flat_filters_per_family():
    agent = MADDPG(MIXED_OBS, MIXED_ACT, net_config=NET, seed=0)
    cfgs = agent.build_net_config(NET)
    assert cfgs["scout_0"]["encoder_config"] == {"hidden_size": (32,)}
    assert set(cfgs["cam_0"]["encoder_config"]) == {
        "channel_size", "kernel_size", "stride_size"}
    # the built nets carry the right encoder families
    assert agent.actors["scout_0"].config.encoder_kind == "mlp"
    assert agent.actors["cam_0"].config.encoder_kind == "cnn"
    # centralised critics always see the flat joint vector -> MLP
    assert agent.critics["cam_0"].config.encoder_kind == "mlp"


def test_build_net_config_keyed_overrides():
    keyed = {
        "scout": {"latent_dim": 16, "encoder_config": {"hidden_size": (48,)}},
        "cam_0": {"latent_dim": 16,
                  "encoder_config": {"channel_size": (4,), "kernel_size": (3,),
                                     "stride_size": (1,)}},
    }
    agent = MADDPG(MIXED_OBS, MIXED_ACT, net_config=keyed, seed=0)
    cfgs = agent.build_net_config(keyed)
    assert cfgs["scout_1"]["encoder_config"] == {"hidden_size": (48,)}
    assert cfgs["cam_0"]["encoder_config"]["channel_size"] == (4,)
    assert agent.actors["scout_0"].config.encoder.hidden_size == (48,)


def _mixed_batch(rng, agent_ids, obs_spaces, B=16):
    obs = {}
    next_obs = {}
    for a in agent_ids:
        shape = (B,) + obs_spaces[a].shape
        obs[a] = rng.random(shape).astype(np.float32)
        next_obs[a] = rng.random(shape).astype(np.float32)
    return {
        "obs": obs,
        "action": {a: rng.integers(0, 3, size=B) for a in agent_ids},
        "reward": {a: rng.random(B).astype(np.float32) for a in agent_ids},
        "next_obs": next_obs,
        "done": {a: np.zeros(B, np.float32) for a in agent_ids},
    }


def test_maddpg_mixed_trains_and_mutates_without_divergence():
    """The VERDICT done-criterion: a vector group + an image group train AND
    architecture-mutate together with zero divergence warnings."""
    agent = MADDPG(MIXED_OBS, MIXED_ACT, net_config=NET, seed=0)
    rng = np.random.default_rng(0)
    obs = {a: rng.random((2,) + MIXED_OBS[a].shape).astype(np.float32)
           for a in agent.agent_ids}
    acts = agent.get_action(obs)
    assert set(acts) == set(agent.agent_ids)
    loss = agent.learn(_mixed_batch(rng, agent.agent_ids, MIXED_OBS))
    assert np.isfinite(loss)

    muts = Mutations(architecture=1.0, no_mutation=0.0, parameters=0.0,
                     activation=0.0, rl_hp=0.0, rand_seed=1)
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # ANY divergence warning fails
        for _ in range(6):
            agent = muts.architecture_mutate(agent)
            assert agent.mut != "None"
    # families preserved through repeated mutation
    assert agent.actors["scout_0"].config.encoder_kind == "mlp"
    assert agent.actors["cam_0"].config.encoder_kind == "cnn"
    # and the mutated agent still learns
    loss = agent.learn(_mixed_batch(rng, agent.agent_ids, MIXED_OBS))
    assert np.isfinite(loss)


class _MixedVecEnv:
    num_envs = 2
    agents = list(MIXED_OBS)

    def __init__(self):
        self.rng = np.random.default_rng(0)

    def _obs(self):
        return {a: self.rng.random((2,) + MIXED_OBS[a].shape).astype(np.float32)
                for a in self.agents}

    def reset(self):
        return self._obs(), {}

    def step(self, actions):
        z = {a: np.zeros(2, bool) for a in self.agents}
        r = {a: np.ones(2, np.float32) for a in self.agents}
        return self._obs(), r, z, z, {}


def test_ippo_mixed_collect_learn_mutate():
    agent = IPPO(MIXED_OBS, MIXED_ACT, net_config=NET, num_envs=2,
                 learn_step=8, batch_size=8, update_epochs=1, seed=0)
    assert agent.get_setup() is MultiAgentSetup.MIXED
    assert agent.actors["scout"].config.encoder_kind == "mlp"
    assert agent.actors["cam"].config.encoder_kind == "cnn"
    env = _MixedVecEnv()
    agent.collect_rollouts(env, n_steps=8)
    assert np.isfinite(agent.learn())
    muts = Mutations(architecture=1.0, no_mutation=0.0, parameters=0.0,
                     activation=0.0, rl_hp=0.0, rand_seed=2)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        for _ in range(4):
            agent = muts.architecture_mutate(agent)
            assert agent.mut != "None"
    agent.collect_rollouts(env, n_steps=8)
    assert np.isfinite(agent.learn())


def test_architecture_mutation_rolls_back_atomically():
    """A failure mid-mutation must leave the agent EXACTLY as before (no
    sibling divergence), set mut='None', warn once — and preserve the
    optimizer moments (ADVICE r4: reinit after rollback silently reset the
    Adam dynamics even though the restored params matched the old state)."""
    agent = MADDPG(MIXED_OBS, MIXED_ACT, net_config=NET, seed=0)
    # accumulate non-trivial Adam moments before the failed mutation
    agent.learn(_mixed_batch(np.random.default_rng(0), agent.agent_ids,
                             MIXED_OBS))
    before_opt = {
        cfg.name: jax.tree_util.tree_map(
            np.asarray, getattr(agent, cfg.name).opt_state)
        for cfg in agent.registry.optimizer_configs
    }
    before_cfgs = {a: agent.actors[a].config for a in agent.agent_ids}
    before_params = {
        a: np.asarray(
            next(iter(agent.actors[a].params["head"].values()))
            if isinstance(agent.actors[a].params["head"], dict)
            else agent.actors[a].params["head"]["w0"]
        )
        for a in agent.agent_ids
    }

    # make the LAST critic blow up mid-transaction
    victim = agent.critics[agent.agent_ids[-1]]
    orig = victim.apply_mutation

    def boom(name, rng=None):
        raise RuntimeError("synthetic mutation failure")

    victim.apply_mutation = boom
    muts = Mutations(architecture=1.0, no_mutation=0.0, parameters=0.0,
                     activation=0.0, rl_hp=0.0, rand_seed=3)
    with pytest.warns(RuntimeWarning, match="rolled back"):
        agent = muts.architecture_mutate(agent)
    victim.apply_mutation = orig
    assert agent.mut == "None"
    for a in agent.agent_ids:
        assert agent.actors[a].config == before_cfgs[a], "config diverged"
    # params restored too
    after_params = {
        a: np.asarray(
            next(iter(agent.actors[a].params["head"].values()))
            if isinstance(agent.actors[a].params["head"], dict)
            else agent.actors[a].params["head"]["w0"]
        )
        for a in agent.agent_ids
    }
    for a in agent.agent_ids:
        np.testing.assert_array_equal(before_params[a], after_params[a])
    # optimizer moments survived the rollback (a true no-op, not a reinit)
    for cfg in agent.registry.optimizer_configs:
        after = jax.tree_util.tree_map(
            np.asarray, getattr(agent, cfg.name).opt_state)
        jax.tree_util.tree_map(
            np.testing.assert_array_equal, before_opt[cfg.name], after)
    # and the rolled-back agent still works
    assert np.isfinite(agent.learn(
        _mixed_batch(np.random.default_rng(1), agent.agent_ids, MIXED_OBS)))


def test_build_net_config_flat_defaults_with_override():
    """Flat keys survive as defaults underneath per-agent overrides
    (review finding: keyed mode must not discard them)."""
    mixed_cfg = {
        "latent_dim": 16,
        "encoder_config": {"hidden_size": (48,), "channel_size": (8,),
                           "kernel_size": (3,), "stride_size": (2,)},
        "cam_0": {"encoder_config": {"channel_size": (4,), "kernel_size": (3,),
                                     "stride_size": (1,)}},
    }
    agent = MADDPG(MIXED_OBS, MIXED_ACT, net_config=mixed_cfg, seed=0)
    cfgs = agent.build_net_config(mixed_cfg)
    # scouts keep the flat defaults (MLP keys only)
    assert cfgs["scout_0"]["latent_dim"] == 16
    assert cfgs["scout_0"]["encoder_config"] == {"hidden_size": (48,)}
    # cam keeps its explicit override AND the flat latent_dim
    assert cfgs["cam_0"]["latent_dim"] == 16
    assert cfgs["cam_0"]["encoder_config"]["channel_size"] == (4,)
    assert agent.actors["scout_0"].config.encoder.hidden_size == (48,)


def test_matd3_mixed_builds_and_learns():
    """MATD3's twin critics go through build_critic_config too — mixed
    populations must construct and learn (the critic_2s are built in the
    MATD3 subclass, a separate code path from MADDPG's critics)."""
    from agilerl_tpu.algorithms.matd3 import MATD3

    agent = MATD3(MIXED_OBS, MIXED_ACT, net_config=NET, seed=0)
    assert agent.get_setup() is MultiAgentSetup.MIXED
    assert agent.actors["cam_0"].config.encoder_kind == "cnn"
    # every critic tier sees the flat joint vector
    for aid in agent.agent_ids:
        assert agent.critics[aid].config.encoder_kind == "mlp"
        assert agent.critic_2s[aid].config.encoder_kind == "mlp"
    rng = np.random.default_rng(0)
    loss = agent.learn(_mixed_batch(rng, agent.agent_ids, MIXED_OBS))
    assert np.isfinite(loss)
    # and architecture-mutates without divergence warnings
    muts = Mutations(architecture=1.0, no_mutation=0.0, parameters=0.0,
                     activation=0.0, rl_hp=0.0, rand_seed=5)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        agent = muts.architecture_mutate(agent)
    assert agent.mut != "None"
    assert np.isfinite(agent.learn(
        _mixed_batch(rng, agent.agent_ids, MIXED_OBS)))
