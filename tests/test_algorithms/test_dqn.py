import jax
import jax.numpy as jnp
import numpy as np
import pytest
from gymnasium import spaces

from agilerl_tpu.algorithms.dqn import DQN
from agilerl_tpu.components import ReplayBuffer
from agilerl_tpu.envs.probe import (
    ConstantRewardEnv,
    DiscountedRewardEnv,
    ObsDependentRewardEnv,
    check_q_learning_with_probe_env,
    fill_buffer_random,
)

BOX = spaces.Box(-1, 1, (4,))
DISC = spaces.Discrete(2)


def make_agent(**kw):
    defaults = dict(observation_space=BOX, action_space=DISC, lr=1e-3, seed=0)
    defaults.update(kw)
    return DQN(**defaults)


def test_get_action_shapes():
    agent = make_agent()
    a = agent.get_action(np.zeros((5, 4), np.float32))
    assert a.shape == (5,)
    a1 = agent.get_action(np.zeros(4, np.float32))
    assert a1.shape == ()


def test_epsilon_explores():
    agent = make_agent()
    acts = agent.get_action(np.zeros((500, 4), np.float32), epsilon=1.0)
    assert set(np.unique(acts)) == {0, 1}


def test_action_mask():
    agent = make_agent()
    mask = np.tile([1, 0], (10, 1))
    acts = agent.get_action(np.zeros((10, 4), np.float32), epsilon=1.0, action_mask=mask)
    assert (acts == 0).all()


def test_learn_reduces_loss():
    agent = make_agent()
    buf = ReplayBuffer(max_size=512)
    rng = np.random.default_rng(0)
    for i in range(128):
        buf.add(
            {
                "obs": rng.normal(size=4).astype(np.float32),
                "action": np.int32(i % 2),
                "reward": np.float32(1.0),
                "next_obs": rng.normal(size=4).astype(np.float32),
                "done": np.float32(1.0),
            }
        )
    losses = [agent.learn(buf.sample(64, key=jax.random.PRNGKey(i))) for i in range(200)]
    assert losses[-1] < losses[0]
    assert losses[-1] < 0.05


def test_clone_and_checkpoint(tmp_path):
    agent = make_agent()
    agent.fitness = [1.0, 2.0]
    clone = agent.clone(index=7)
    assert clone.index == 7
    obs = np.zeros((3, 4), np.float32)
    np.testing.assert_array_equal(agent.get_action(obs, training=False),
                                  clone.get_action(obs, training=False))

    path = tmp_path / "dqn.ckpt"
    agent.save_checkpoint(path)
    loaded = DQN.load(path)
    np.testing.assert_array_equal(
        np.asarray(agent.actor.params["encoder"]["layer_0"]["kernel"]),
        np.asarray(loaded.actor.params["encoder"]["layer_0"]["kernel"]),
    )
    assert loaded.fitness == [1.0, 2.0]


@pytest.mark.slow
def test_mutation_then_learn():
    """Architecture mutation must keep the agent trainable (recompile path)."""
    env = ConstantRewardEnv()
    agent = make_agent(
        observation_space=env.observation_space, action_space=env.action_space
    )
    buf = ReplayBuffer(max_size=256)
    fill_buffer_random(env, buf, steps=16, num_envs=8)
    agent.learn(buf.sample(32))
    agent.actor.apply_mutation("encoder.add_node")
    agent.actor_target.apply_mutation("encoder.add_node")
    # mirror mutation: re-sync target arch from actor (what the HPO engine does)
    agent.actor_target.config = agent.actor.config
    agent.actor_target.params = jax.tree_util.tree_map(jnp.copy, agent.actor.params)
    agent.reinit_optimizers()
    agent.mutation_hook()
    loss = agent.learn(buf.sample(32))
    assert np.isfinite(loss)


@pytest.mark.slow
@pytest.mark.parametrize(
    "env_cls", [ConstantRewardEnv, ObsDependentRewardEnv, DiscountedRewardEnv]
)
def test_probe_envs(env_cls):
    env = env_cls()
    check_q_learning_with_probe_env(
        env,
        DQN,
        dict(
            observation_space=env.observation_space,
            action_space=env.action_space,
            lr=5e-3,
            gamma=0.9,
            tau=0.5,
            seed=1,
            net_config={"latent_dim": 16, "encoder_config": {"hidden_size": (32,)}},
        ),
        learn_steps=400,
    )
