import jax
import numpy as np
import pytest

from agilerl_tpu.algorithms.maddpg import MADDPG
from agilerl_tpu.components import MultiAgentReplayBuffer
from agilerl_tpu.envs.multi_agent import MultiAgentJaxVecEnv, SimpleSpreadJax

NET = {"latent_dim": 16, "encoder_config": {"hidden_size": (32,)}}


def make_env(continuous=False, num_envs=2):
    return MultiAgentJaxVecEnv(
        SimpleSpreadJax(n_agents=2, continuous=continuous), num_envs=num_envs, seed=0
    )


def make_agent(env, **kw):
    defaults = dict(
        observation_spaces=env.observation_spaces,
        action_spaces=env.action_spaces,
        agent_ids=env.agent_ids,
        net_config=NET,
        seed=0,
    )
    defaults.update(kw)
    return MADDPG(**defaults)


@pytest.mark.parametrize("continuous", [False, True])
def test_get_action(continuous):
    env = make_env(continuous)
    agent = make_agent(env)
    obs, _ = env.reset()
    actions = agent.get_action(obs)
    assert set(actions) == set(env.agent_ids)
    for aid in env.agent_ids:
        if continuous:
            assert actions[aid].shape == (2, 2)
        else:
            assert actions[aid].shape == (2,)
            assert actions[aid].max() < 5


@pytest.mark.parametrize("continuous", [False, True])
def test_step_and_learn(continuous):
    env = make_env(continuous)
    agent = make_agent(env)
    buf = MultiAgentReplayBuffer(max_size=512, agent_ids=env.agent_ids)
    obs, _ = env.reset()
    for _ in range(40):
        actions = agent.get_action(obs)
        next_obs, rew, term, trunc, _ = env.step(actions)
        done = {a: np.asarray(term[a], np.float32) for a in env.agent_ids}
        buf.save_to_memory(obs, actions, rew, next_obs, done, is_vectorised=True)
        obs = next_obs
    losses = [agent.learn(buf.sample(32)) for _ in range(10)]
    assert np.isfinite(losses).all()


def test_grouping():
    env = make_env()
    agent = make_agent(env)
    assert agent.grouped_agents == {"agent": ["agent_0", "agent_1"]}


def test_clone_and_checkpoint(tmp_path):
    env = make_env()
    agent = make_agent(env)
    clone = agent.clone(index=9)
    obs, _ = env.reset()
    a1 = agent.get_action(obs, training=False)
    a2 = clone.get_action(obs, training=False)
    for aid in env.agent_ids:
        np.testing.assert_array_equal(a1[aid], a2[aid])
    agent.save_checkpoint(tmp_path / "ma.ckpt")
    loaded = MADDPG.load(tmp_path / "ma.ckpt")
    a3 = loaded.get_action(obs, training=False)
    for aid in env.agent_ids:
        np.testing.assert_array_equal(a1[aid], a3[aid])


def test_test_loop():
    env = make_env()
    agent = make_agent(env)
    fitness = agent.test(env, max_steps=10, loop=2)
    assert np.isfinite(fitness)
