import jax
import jax.numpy as jnp
import numpy as np
import pytest

from agilerl_tpu.algorithms.dpo import DPO
from agilerl_tpu.algorithms.grpo import GRPO
from agilerl_tpu.llm import model as M
from agilerl_tpu.utils.llm_utils import CharTokenizer, PreferenceGym, ReasoningGym

TOK = CharTokenizer()
CFG = M.GPTConfig(
    vocab_size=TOK.vocab_size, n_layer=2, n_head=4, n_kv_head=2, d_model=64,
    max_seq_len=64, dtype=jnp.float32,
)


def make_reasoning_dataset(n=32, seed=0):
    rng = np.random.default_rng(seed)
    rows = []
    for _ in range(n):
        a, b = rng.integers(0, 5, 2)
        rows.append({"question": f"{a}+{b}=", "answer": str(a + b)})
    return rows


def reward_fn(completion: str, answer: str, prompt: str) -> float:
    return 1.0 if completion.strip().startswith(answer) else 0.0


def make_grpo(**kw):
    defaults = dict(
        config=CFG, pad_token_id=TOK.pad_token_id, eos_token_id=TOK.eos_token_id,
        group_size=4, batch_size=8, max_output_tokens=4, lr=1e-3, seed=0,
    )
    defaults.update(kw)
    return GRPO(**defaults)


def make_gym(batch=4):
    return ReasoningGym(
        make_reasoning_dataset(24), make_reasoning_dataset(8, seed=1), TOK,
        reward_fn=reward_fn, data_batch_size=batch,
    )


class TestGRPO:
    def test_get_action_shapes(self):
        agent = make_grpo()
        env = make_gym()
        prompts = env.reset()
        comp, cmask = agent.get_action(prompts)
        assert comp.shape == (4 * 4, 4)
        assert cmask.shape == comp.shape

    def test_advantage_zscore(self):
        rewards = jnp.array([[1.0, 0.0, 1.0, 0.0], [2.0, 2.0, 2.0, 2.0]])
        adv = GRPO._calculate_advantage(rewards)
        assert adv.shape == (8,)
        np.testing.assert_allclose(np.asarray(adv[4:]), 0.0, atol=1e-2)
        assert adv[0] > 0 and adv[1] < 0

    def test_learn_updates_only_lora(self):
        agent = make_grpo()
        env = make_gym()
        prompts = env.reset()
        comp, cmask = agent.get_action(prompts)
        ids, action_masks = env.assemble_learn_batch(comp, cmask)
        env.step(comp, cmask)
        # synthetic within-group reward SPREAD: sampled completions can all
        # earn identical rewards (advantage == 0 -> zero gradient by GRPO
        # construction), which would vacuously pass the base check and fail
        # the lora one — the property under test is the parameter split,
        # not sampling luck
        rewards = np.linspace(0.0, 1.0, comp.shape[0], dtype=np.float32)
        rewards = rewards.reshape(-1, agent.group_size)
        base_before = np.asarray(agent.base_params["blocks"]["0"]["wq"]).copy()
        lora_before = np.asarray(agent.actor.params["blocks"]["0"]["wq"]["B"]).copy()
        loss, _ = agent.learn((ids, action_masks, rewards))
        assert np.isfinite(loss)
        np.testing.assert_array_equal(
            base_before, np.asarray(agent.base_params["blocks"]["0"]["wq"])
        )
        assert not np.array_equal(
            lora_before, np.asarray(agent.actor.params["blocks"]["0"]["wq"]["B"])
        )

    def test_reference_refresh(self):
        agent = make_grpo()
        agent.actor.params = jax.tree_util.tree_map(
            lambda x: x + 1.0, agent.actor.params
        )
        agent.set_reference_policy(0)
        np.testing.assert_array_equal(
            np.asarray(agent.reference.params["blocks"]["0"]["wq"]["A"]),
            np.asarray(agent.actor.params["blocks"]["0"]["wq"]["A"]),
        )
        # same epoch -> no refresh
        agent.actor.params = jax.tree_util.tree_map(lambda x: x + 1.0, agent.actor.params)
        agent.set_reference_policy(0)
        assert not np.array_equal(
            np.asarray(agent.reference.params["blocks"]["0"]["wq"]["A"]),
            np.asarray(agent.actor.params["blocks"]["0"]["wq"]["A"]),
        )

    def test_clone_shares_base(self):
        agent = make_grpo()
        clone = agent.clone(index=3)
        assert clone.base_params is agent.base_params  # no base copy
        np.testing.assert_array_equal(
            np.asarray(clone.actor.params["blocks"]["0"]["wq"]["A"]),
            np.asarray(agent.actor.params["blocks"]["0"]["wq"]["A"]),
        )

    def test_test_loop(self):
        agent = make_grpo()
        env = make_gym()
        fitness = agent.test(env)
        assert 0.0 <= fitness <= 1.0


def make_pref_dataset(n=16, seed=0):
    rng = np.random.default_rng(seed)
    rows = []
    for _ in range(n):
        a = int(rng.integers(0, 5))
        rows.append({
            "prompt": f"{a}+1=", "chosen": str(a + 1), "rejected": str(a),
        })
    return rows


class TestDPO:
    def test_learn_and_accuracy_improves(self):
        agent = DPO(
            config=CFG, pad_token_id=TOK.pad_token_id, eos_token_id=TOK.eos_token_id,
            lr=5e-3, beta=0.5, seed=0,
        )
        env = PreferenceGym(
            make_pref_dataset(16), make_pref_dataset(8, seed=1), TOK, data_batch_size=8,
        )
        batch = env.reset()
        losses = []
        for _ in range(15):
            loss, acc = agent.learn(batch)
            losses.append(loss)
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]
        fitness = agent.test(env)
        assert fitness >= 0.5  # margin should be positive after training


@pytest.mark.slow
def test_finetune_llm_reasoning_e2e():
    from agilerl_tpu.training.train_llm import finetune_llm_reasoning

    pop = [make_grpo(seed=i) for i in range(2)]
    for i, a in enumerate(pop):
        a.index = i
    env = make_gym()
    pop, fitnesses = finetune_llm_reasoning(
        pop, env, max_steps=4, evaluation_interval=2, verbose=False,
    )
    assert all(len(f) >= 1 for f in fitnesses)


def test_grpo_gradient_direction():
    """GRPO must raise logprobs of advantaged completions and lower the rest
    (exact mechanism check, independent of cold-start convergence)."""
    cfg = M.GPTConfig(vocab_size=46, n_layer=2, n_head=4, d_model=64,
                      max_seq_len=32, dtype=jnp.float32)
    agent = GRPO(config=cfg, pad_token_id=0, eos_token_id=1, group_size=4,
                 batch_size=8, lr=5e-3, beta=0.0, update_epochs=1, seed=0,
                 lora_targets=("wq", "wv", "wo", "w_down"))
    rng = np.random.default_rng(0)
    B, T = 8, 12
    ids = jnp.asarray(rng.integers(2, 46, (B, T)).astype(np.int32))
    mask = np.zeros((B, T - 1), np.float32)
    mask[:, 6:] = 1.0
    rewards = np.zeros((2, 4), np.float32)
    rewards[:, 0] = 1.0  # first member of each group advantaged

    lp_fn = agent.jit_fn("logprobs", agent._logprob_fn)

    def mean_lp(rows):
        lp = lp_fn(agent.actor.params, ids, (ids != 0).astype(jnp.int32))
        lp = np.asarray(lp * jnp.asarray(mask)).sum(-1) / mask.sum(-1)
        return lp[rows].mean()

    pos, neg = [0, 4], [1, 2, 3, 5, 6, 7]
    before_pos, before_neg = mean_lp(pos), mean_lp(neg)
    for _ in range(30):
        agent.learn((ids, jnp.asarray(mask), jnp.asarray(rewards)))
    assert mean_lp(pos) > before_pos + 0.03
    assert mean_lp(neg) < before_neg


def test_grpo_sampling_knobs_and_lr_schedule():
    """Reference-parity GRPO kwargs: top_k/top_p/min_output_tokens thread to
    the generate loop (completions respect the length floor) and
    cosine_lr_schedule_config builds a scheduled optimizer (grpo.py:130-142
    reference surface)."""
    from agilerl_tpu.algorithms.core.optimizer import CosineLRScheduleConfig

    agent = make_grpo(
        top_k=10, top_p=0.9, max_output_tokens=6, min_output_tokens=4,
        cosine_lr_schedule_config=CosineLRScheduleConfig(
            num_epochs=2, steps_per_epoch=4
        ),
    )
    env = make_gym()
    prompts = env.reset()
    comp, cmask = agent.get_action(prompts)
    # min_output_tokens: every completion has >= 4 live tokens
    assert (np.asarray(cmask).sum(axis=1) >= 4).all()
    # the scheduled optimizer still learns
    ids, action_masks = env.assemble_learn_batch(comp, cmask)
    _, rewards = env.step(comp, cmask)
    loss, _ = agent.learn((ids, action_masks, rewards))
    assert np.isfinite(loss)
    # clone round-trips the new kwargs
    c = agent.clone()
    assert c.top_k == 10 and c.min_output_tokens == 4


def test_grpo_lr_mutation_rebuilds_scheduled_optimizer():
    """With a cosine schedule, lr lives in tx (peak_value), so an RL-HP lr
    mutation must drop the cached jitted update closure — otherwise the
    mutated agent silently trains at the old lr (review finding)."""
    from agilerl_tpu.algorithms.core.optimizer import CosineLRScheduleConfig
    from agilerl_tpu.hpo.mutation import Mutations

    agent = make_grpo(
        cosine_lr_schedule_config=CosineLRScheduleConfig(
            num_epochs=1, steps_per_epoch=8
        ),
    )
    env = make_gym()
    prompts = env.reset()
    comp, cmask = agent.get_action(prompts)
    ids, action_masks = env.assemble_learn_batch(comp, cmask)
    _, rewards = env.step(comp, cmask)
    agent.learn((ids, action_masks, rewards))   # populate the jit cache
    assert "update" in agent._jit_cache
    mut = Mutations(no_mutation=0.0, architecture=0.0, parameters=0.0,
                    activation=0.0, rl_hp=1.0, rand_seed=0)
    # force an lr mutation (sample until the hp picked is lr)
    for _ in range(20):
        mutated = mut.rl_hyperparam_mutation(agent)
        if mutated.mut == "lr":
            break
    assert mutated.mut == "lr"
    assert "update" not in mutated._jit_cache, (
        "stale jitted update would train at the unmutated lr"
    )
    loss, _ = mutated.learn((ids, action_masks, rewards))
    assert np.isfinite(loss)
