"""Probe-env convergence grid: every algorithm family checked on vector, image
and Dict observations (parity: the reference exercises its 30-env probe suite
across DQN/Rainbow/DDPG/TD3/PPO, agilerl/utils/probe_envs.py:1114-1328 +
docs/debugging_rl).

The table-driven check fns read each env's ground-truth q/v/policy tables, so
one test body serves the whole grid."""

import numpy as np
import pytest

from agilerl_tpu.algorithms import DDPG, DQN, PPO, TD3
from agilerl_tpu.envs import probe as P

VEC_NET = {"latent_dim": 16, "encoder_config": {"hidden_size": (32,)}}
IMG_NET = {
    "latent_dim": 16,
    "encoder_config": {
        "channel_size": (8,), "kernel_size": (2,), "stride_size": (1,),
    },
}
DICT_NET = {"latent_dim": 16}


def _net_for(env):
    from gymnasium import spaces

    if isinstance(env.observation_space, spaces.Dict):
        return DICT_NET
    if len(env.observation_space.shape) == 3:
        return IMG_NET
    return VEC_NET


# --------------------------------------------------------------------------- #
# DQN: value learning across the full obs grid
# --------------------------------------------------------------------------- #


@pytest.mark.slow
@pytest.mark.parametrize(
    "env_cls",
    [
        P.ConstantRewardEnv,
        P.ConstantRewardImageEnv,
        P.ConstantRewardDictEnv,
        P.ObsDependentRewardEnv,
        P.ObsDependentRewardImageEnv,
        P.DiscountedRewardEnv,
        P.PolicyEnv,
        P.PolicyImageEnv,
        P.PolicyDictEnv,
    ],
)
def test_dqn_probe_grid(env_cls):
    env = env_cls()
    P.check_q_learning_with_probe_env(
        env,
        DQN,
        dict(
            observation_space=env.observation_space,
            action_space=env.action_space,
            lr=2e-3, gamma=0.9, tau=0.5, double=False, seed=0,
            net_config=_net_for(env),
        ),
        learn_steps=400,
    )


# --------------------------------------------------------------------------- #
# DDPG / TD3: continuous policy + critic across obs kinds
# --------------------------------------------------------------------------- #


@pytest.mark.slow
@pytest.mark.parametrize(
    "env_cls",
    [
        P.FixedObsPolicyContActionsEnv,
        P.FixedObsPolicyContActionsImageEnv,
        P.DiscountedRewardContActionsEnv,
    ],
)
def test_ddpg_probe_grid(env_cls):
    env = env_cls()
    P.check_policy_q_learning_with_probe_env(
        env,
        DDPG,
        dict(
            observation_space=env.observation_space,
            action_space=env.action_space,
            lr_actor=3e-3, lr_critic=5e-3, gamma=0.9, tau=0.3,
            policy_freq=1, O_U_noise=False, seed=2,
            net_config=_net_for(env),
        ),
        learn_steps=400,
    )


@pytest.mark.slow
def test_td3_continuous_probe():
    env = P.FixedObsPolicyContActionsEnv()
    P.check_policy_q_learning_with_probe_env(
        env,
        TD3,
        dict(
            observation_space=env.observation_space,
            action_space=env.action_space,
            lr_actor=3e-3, lr_critic=5e-3, gamma=0.9, tau=0.3,
            policy_freq=2, O_U_noise=False, seed=2,
            net_config=VEC_NET,
        ),
        learn_steps=500,
    )


# --------------------------------------------------------------------------- #
# PPO: discrete + continuous policies across obs kinds
# --------------------------------------------------------------------------- #


def _ppo_args(env, **over):
    args = dict(
        observation_space=env.observation_space,
        action_space=env.action_space,
        num_envs=8, learn_step=32, batch_size=64, update_epochs=4,
        lr=5e-3, gamma=0.9, ent_coef=0.01, seed=0,
        net_config=_net_for(env),
    )
    args.update(over)
    return args


@pytest.mark.slow
@pytest.mark.parametrize(
    "env_cls",
    [P.PolicyEnv, P.PolicyImageEnv, P.FixedObsPolicyEnv],
)
def test_ppo_discrete_probe_grid(env_cls):
    env = env_cls()
    P.check_policy_on_policy_with_probe_env(
        env, PPO, _ppo_args(env), train_iters=50, solved_reward=0.9
    )


@pytest.mark.slow
def test_ppo_continuous_probe():
    env = P.FixedObsPolicyContActionsEnv()
    P.check_policy_on_policy_with_probe_env(
        env, PPO, _ppo_args(env, ent_coef=0.0), train_iters=60, atol=0.2
    )


# --------------------------------------------------------------------------- #
# Table sanity for the whole 31-class grid (cheap, not marked slow)
# --------------------------------------------------------------------------- #


def test_probe_tables_consistent():
    names = [
        n for n in dir(P)
        if (n.endswith("Env") or n.endswith("EnvSimple"))
        and not n.startswith("_")
        and n not in ("JaxEnv", "JaxVecEnv", "MemoryEnv")
    ]
    assert len(names) >= 31
    for n in names:
        env = getattr(P, n)()
        assert env.sample_obs, n
        if env.q_values is not None:
            assert len(env.q_values) == len(env.sample_obs), n
        if env.policy_values is not None:
            assert len(env.policy_values) == len(env.sample_obs), n
        if env.continuous:
            assert env.sample_actions is None or len(env.sample_actions) == len(
                env.sample_obs
            ), n
