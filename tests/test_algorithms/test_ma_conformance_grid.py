"""Multi-agent conformance grid: MADDPG / MATD3 / IPPO x discrete/continuous
actions — get_action/learn/clone/save-load per cell (parity: the reference's
per-algo parametrized MA suites, SURVEY.md §4).
"""

import jax
import numpy as np
import pytest

from tests.tiering import fast_core

from agilerl_tpu.algorithms import IPPO, MADDPG, MATD3
from agilerl_tpu.components import MultiAgentReplayBuffer
from agilerl_tpu.envs.multi_agent import MultiAgentJaxVecEnv, SimpleSpreadJax

NET = {"latent_dim": 16, "encoder_config": {"hidden_size": (32,)}}


def make_env(continuous, num_envs=2):
    return MultiAgentJaxVecEnv(
        SimpleSpreadJax(n_agents=2, continuous=continuous), num_envs=num_envs, seed=0
    )


def make_agent(cls, env, **kw):
    kwargs = dict(
        observation_spaces=env.observation_spaces,
        action_spaces=env.action_spaces,
        agent_ids=env.agent_ids,
        net_config=NET,
        seed=0,
    )
    kwargs.update(kw)
    return cls(**kwargs)


def fill_ma_buffer(env, agent, n=40):
    buf = MultiAgentReplayBuffer(max_size=256, agent_ids=env.agent_ids)
    obs, _ = env.reset()
    for _ in range(n):
        actions = agent.get_action(obs)
        next_obs, rew, term, trunc, _ = env.step(actions)
        done = {a: np.asarray(term[a], np.float32) for a in env.agent_ids}
        buf.save_to_memory(obs, actions, rew, next_obs, done, is_vectorised=True)
        obs = next_obs
    return buf


OFF_POLICY = {"maddpg": MADDPG, "matd3": MATD3}


@pytest.mark.parametrize(
    "continuous",
    fast_core([False, True], is_fast=lambda c: c is False),
    ids=["disc", "cont"],
)
@pytest.mark.parametrize("algo", list(OFF_POLICY))
class TestMAOffPolicyGrid:
    def test_learn_clone_saveload(self, algo, continuous, tmp_path):
        env = make_env(continuous)
        agent = make_agent(OFF_POLICY[algo], env)
        buf = fill_ma_buffer(env, agent)
        for _ in range(3):
            loss = agent.learn(buf.sample(16))
            vals = loss.values() if isinstance(loss, dict) else [loss]
            assert all(np.isfinite(np.asarray(v)).all() for v in vals)
        obs, _ = env.reset()
        clone = agent.clone(index=4)
        assert clone.index == 4
        a1 = agent.get_action(obs, training=False)
        a2 = clone.get_action(obs, training=False)
        for aid in env.agent_ids:
            np.testing.assert_array_equal(np.asarray(a1[aid]), np.asarray(a2[aid]))
        path = tmp_path / f"{algo}_{continuous}.ckpt"
        agent.save_checkpoint(path)
        loaded = type(agent).load(path)
        a3 = loaded.get_action(obs, training=False)
        for aid in env.agent_ids:
            np.testing.assert_array_equal(np.asarray(a1[aid]), np.asarray(a3[aid]))


@pytest.mark.parametrize(
    "continuous",
    fast_core([False, True], is_fast=lambda c: c is False),
    ids=["disc", "cont"],
)
class TestIPPOGrid:
    def test_rollout_learn_clone(self, continuous, tmp_path):
        env = make_env(continuous)
        agent = make_agent(IPPO, env, learn_step=8, batch_size=16)
        agent.collect_rollouts(env, n_steps=8)
        obs, _ = env.reset()
        losses = agent.learn()
        vals = losses.values() if isinstance(losses, dict) else [losses]
        assert all(np.isfinite(np.asarray(v)).all() for v in vals)
        clone = agent.clone(index=2)
        a1 = agent.get_action(obs, training=False)
        a2 = clone.get_action(obs, training=False)
        for aid in env.agent_ids:
            np.testing.assert_array_equal(np.asarray(a1[aid]), np.asarray(a2[aid]))
        path = tmp_path / f"ippo_{continuous}.ckpt"
        agent.save_checkpoint(path)
        loaded = IPPO.load(path)
        a3 = loaded.get_action(obs, training=False)
        for aid in env.agent_ids:
            np.testing.assert_array_equal(np.asarray(a1[aid]), np.asarray(a3[aid]))
