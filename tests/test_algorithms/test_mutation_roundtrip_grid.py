"""Per-algorithm save -> load -> clone -> mutate -> learn round-trips across
the observation-space grid (VERDICT r4 next #5) — the depth the reference's
tests/test_algorithms/ exercises per algorithm (mutation interplay with
checkpointing, cloning, and continued learning; SURVEY.md §4).

Four tiers:
- A: full chain for every single-agent algorithm x {vec, img, dict} obs;
- B: every mutation KIND (architecture / parameter / activation / rl-hp)
  followed by a learn() for every single-agent algorithm;
- C: contextual bandits (NeuralUCB / NeuralTS) chains;
- D: multi-agent (MADDPG / MATD3 / IPPO) chains on SimpleSpread.

The invariant throughout: a mutated agent must keep training (finite loss),
its mutated architecture must survive a checkpoint round-trip, and the
pre-mutation agent must be untouched.
"""

import jax
import numpy as np
import pytest
from gymnasium import spaces

from agilerl_tpu.algorithms import (
    CQN, DDPG, DQN, IPPO, MADDPG, MATD3, PPO, TD3, RainbowDQN,
)
from agilerl_tpu.algorithms.neural_ts_bandit import NeuralTS
from agilerl_tpu.algorithms.neural_ucb_bandit import NeuralUCB
from agilerl_tpu.components import MultiAgentReplayBuffer
from agilerl_tpu.envs.multi_agent import MultiAgentJaxVecEnv, SimpleSpreadJax
from agilerl_tpu.hpo import Mutations

from tests.test_algorithms.test_conformance_grid import (
    BOX_ACT, DISC_ACT, OBS_SPACES, assert_same_policy, fill_buffer, net_for,
    sample_obs,
)

pytestmark = pytest.mark.slow

ALGOS = {
    "dqn": ("value", lambda obs, name: DQN(
        obs, DISC_ACT, net_config=net_for(name), seed=0)),
    "rainbow": ("value", lambda obs, name: RainbowDQN(
        obs, DISC_ACT, net_config=net_for(name), v_min=-2, v_max=2,
        num_atoms=13, seed=0)),
    "cqn": ("value", lambda obs, name: CQN(
        obs, DISC_ACT, net_config=net_for(name), seed=0)),
    "ddpg": ("cont", lambda obs, name: DDPG(
        obs, BOX_ACT, net_config=net_for(name), seed=0)),
    "td3": ("cont", lambda obs, name: TD3(
        obs, BOX_ACT, net_config=net_for(name), seed=0)),
    "ppo": ("ppo", lambda obs, name: PPO(
        obs, DISC_ACT, num_envs=4, learn_step=8, batch_size=16,
        update_epochs=1, net_config=net_for(name), seed=0)),
}


def learn_once(agent, kind, obs_space, rng):
    """One finite learn() appropriate to the algorithm family."""
    if kind in ("value", "cont"):
        act = DISC_ACT if kind == "value" else BOX_ACT
        buf = fill_buffer(obs_space, act, n=48, seed=int(rng.integers(1e6)),
                          max_size=64)
        out = agent.learn(buf.sample(16))
        loss = out[0] if isinstance(out, tuple) else out
        assert np.isfinite(np.asarray(loss)).all()
        return
    assert kind == "ppo"
    obs = sample_obs(obs_space, rng, 4)
    for _ in range(agent.learn_step):
        a, logp, v, _ = agent.get_action_and_value(obs)
        agent.rollout_buffer.add(
            obs=obs, action=np.asarray(a),
            reward=rng.normal(size=4).astype(np.float32),
            done=(rng.random(4) < 0.1).astype(np.float32),
            value=np.asarray(v), log_prob=np.asarray(logp),
        )
        obs = sample_obs(obs_space, rng, 4)
    agent._last_obs = obs
    agent._last_done = np.zeros(4, np.float32)
    assert np.isfinite(agent.learn())


def make_muts(**kw):
    defaults = dict(no_mutation=0.0, architecture=0.0, parameters=0.0,
                    activation=0.0, rl_hp=0.0, rand_seed=7)
    defaults.update(kw)
    return Mutations(**defaults)


# --------------------------------------------------------------------------- #
# A: full chain across the observation grid
# --------------------------------------------------------------------------- #


@pytest.mark.parametrize("obs_name", ["vec", "img", "dict"])
@pytest.mark.parametrize("algo", list(ALGOS))
def test_save_load_clone_mutate_learn_chain(algo, obs_name, tmp_path):
    kind, build = ALGOS[algo]
    obs_space = OBS_SPACES[obs_name]
    rng = np.random.default_rng(0)
    agent = build(obs_space, obs_name)
    learn_once(agent, kind, obs_space, rng)

    # save -> load: identical policy
    p1 = tmp_path / "a.ckpt"
    agent.save_checkpoint(p1)
    loaded = type(agent).load(p1)
    assert_same_policy(agent, loaded, obs_space)

    # clone the loaded agent, then architecture-mutate ONLY the clone
    clone = loaded.clone(index=5)
    assert clone.index == 5
    mutated = make_muts(architecture=1.0).architecture_mutate(clone)
    assert mutated.mut is not None
    # the pre-mutation lineage is untouched
    assert_same_policy(agent, loaded, obs_space)

    # the mutated agent keeps learning
    learn_once(mutated, kind, obs_space, rng)

    # and the MUTATED architecture survives a checkpoint round-trip
    p2 = tmp_path / "b.ckpt"
    mutated.save_checkpoint(p2)
    reloaded = type(agent).load(p2)
    assert_same_policy(mutated, reloaded, obs_space)
    assert str(reloaded.actor.config) == str(mutated.actor.config)


# --------------------------------------------------------------------------- #
# B: every mutation kind, then learn
# --------------------------------------------------------------------------- #

KINDS = {
    "architecture": lambda m, a: m.architecture_mutate(a),
    "parameters": lambda m, a: m.parameter_mutation(a),
    "activation": lambda m, a: m.activation_mutation(a),
    "rl_hp": lambda m, a: m.rl_hyperparam_mutation(a),
}


@pytest.mark.parametrize("mkind", list(KINDS))
@pytest.mark.parametrize("algo", list(ALGOS))
def test_each_mutation_kind_then_learn(algo, mkind):
    kind, build = ALGOS[algo]
    obs_space = OBS_SPACES["vec"]
    rng = np.random.default_rng(1)
    agent = build(obs_space, "vec")
    learn_once(agent, kind, obs_space, rng)
    before = jax.tree_util.tree_map(
        np.asarray, jax.tree_util.tree_leaves(agent.actor.params)[0])

    mutated = KINDS[mkind](
        make_muts(**{"parameters" if mkind == "parameters" else mkind: 1.0}
                  if mkind != "rl_hp" else {"rl_hp": 1.0}), agent)
    assert mutated.mut is not None
    if mkind == "parameters":
        after = jax.tree_util.tree_map(
            np.asarray, jax.tree_util.tree_leaves(mutated.actor.params)[0])
        assert not np.array_equal(before, after), (
            "parameter mutation left the policy unchanged")
    learn_once(mutated, kind, obs_space, rng)


# --------------------------------------------------------------------------- #
# C: contextual bandits
# --------------------------------------------------------------------------- #

BANDITS = {"neural_ucb": NeuralUCB, "neural_ts": NeuralTS}


def _bandit_batch(rng, dim, n=32):
    return {
        "obs": rng.normal(size=(n, dim)).astype(np.float32),
        "reward": rng.normal(size=(n,)).astype(np.float32),
    }


@pytest.mark.parametrize("mkind", ["architecture", "parameters"])
@pytest.mark.parametrize("bandit", list(BANDITS))
def test_bandit_mutate_then_learn(bandit, mkind, tmp_path):
    dim, arms = 4, 3
    obs_space = spaces.Box(-1, 1, (dim,), np.float32)
    agent = BANDITS[bandit](
        obs_space, spaces.Discrete(arms),
        net_config={"latent_dim": 16, "encoder_config": {"hidden_size": (32,)}},
        seed=0)
    rng = np.random.default_rng(0)
    assert np.isfinite(agent.learn(_bandit_batch(rng, dim)))

    ctx = rng.normal(size=(arms, dim)).astype(np.float32)
    p1 = tmp_path / "bandit.ckpt"
    agent.save_checkpoint(p1)
    loaded = type(agent).load(p1)
    np.testing.assert_array_equal(
        np.asarray(agent.get_action(ctx, training=False)),
        np.asarray(loaded.get_action(ctx, training=False)))

    mutated = KINDS[mkind](make_muts(**{mkind: 1.0}), loaded.clone(index=2))
    assert mutated.mut is not None
    assert np.isfinite(mutated.learn(_bandit_batch(rng, dim)))


# --------------------------------------------------------------------------- #
# D: multi-agent chains
# --------------------------------------------------------------------------- #

MA_NET = {"latent_dim": 16, "encoder_config": {"hidden_size": (32,)}}


def _ma_env(continuous):
    return MultiAgentJaxVecEnv(
        SimpleSpreadJax(n_agents=2, continuous=continuous), num_envs=2,
        seed=0)


def _ma_same_policy(a, b, env):
    obs, _ = env.reset()
    x, y = a.get_action(obs, training=False), b.get_action(obs, training=False)
    for aid in env.agent_ids:
        np.testing.assert_array_equal(np.asarray(x[aid]), np.asarray(y[aid]))


def _ma_fill_and_learn(agent, env):
    buf = MultiAgentReplayBuffer(max_size=256, agent_ids=env.agent_ids)
    obs, _ = env.reset()
    for _ in range(30):
        actions = agent.get_action(obs)
        next_obs, rew, term, trunc, _ = env.step(actions)
        done = {a: np.asarray(term[a], np.float32) for a in env.agent_ids}
        buf.save_to_memory(obs, actions, rew, next_obs, done,
                           is_vectorised=True)
        obs = next_obs
    loss = agent.learn(buf.sample(32))
    assert np.isfinite(np.asarray(jax.tree_util.tree_leaves(loss))).all()


MA_CASES = {
    "maddpg_disc": (False, lambda env: MADDPG(
        observation_spaces=env.observation_spaces,
        action_spaces=env.action_spaces, agent_ids=env.agent_ids,
        net_config=MA_NET, seed=0)),
    "maddpg_cont": (True, lambda env: MADDPG(
        observation_spaces=env.observation_spaces,
        action_spaces=env.action_spaces, agent_ids=env.agent_ids,
        net_config=MA_NET, seed=0)),
    "matd3_cont": (True, lambda env: MATD3(
        observation_spaces=env.observation_spaces,
        action_spaces=env.action_spaces, agent_ids=env.agent_ids,
        net_config=MA_NET, seed=0, policy_freq=2)),
}


@pytest.mark.parametrize("case", list(MA_CASES))
def test_ma_save_load_clone_mutate_learn_chain(case, tmp_path):
    continuous, build = MA_CASES[case]
    env = _ma_env(continuous)
    agent = build(env)
    _ma_fill_and_learn(agent, env)

    p1 = tmp_path / "ma.ckpt"
    agent.save_checkpoint(p1)
    loaded = type(agent).load(p1)
    _ma_same_policy(agent, loaded, env)

    mutated = make_muts(architecture=1.0).architecture_mutate(
        loaded.clone(index=3))
    assert mutated.mut is not None
    # homogeneous group keeps ONE architecture across sub-agents
    cfgs = {str(mutated.actors[a].config) for a in env.agent_ids}
    assert len(cfgs) == 1
    _ma_fill_and_learn(mutated, env)

    p2 = tmp_path / "ma2.ckpt"
    mutated.save_checkpoint(p2)
    reloaded = type(agent).load(p2)
    _ma_same_policy(mutated, reloaded, env)


@pytest.mark.parametrize("continuous", [False, True])
def test_ippo_save_load_clone_mutate_learn_chain(continuous, tmp_path):
    env = _ma_env(continuous)
    agent = IPPO(
        observation_spaces=env.observation_spaces,
        action_spaces=env.action_spaces, agent_ids=env.agent_ids,
        net_config=MA_NET, num_envs=2, learn_step=16, batch_size=32,
        update_epochs=1, seed=0)
    agent.collect_rollouts(env)
    assert np.isfinite(agent.learn())

    p1 = tmp_path / "ippo.ckpt"
    agent.save_checkpoint(p1)
    loaded = IPPO.load(p1)
    _ma_same_policy(agent, loaded, env)

    mutated = make_muts(architecture=1.0).architecture_mutate(
        loaded.clone(index=4))
    assert mutated.mut is not None
    mutated.collect_rollouts(env)
    assert np.isfinite(mutated.learn())

    p2 = tmp_path / "ippo2.ckpt"
    mutated.save_checkpoint(p2)
    reloaded = IPPO.load(p2)
    _ma_same_policy(mutated, reloaded, env)


# --------------------------------------------------------------------------- #
# E: LLM algorithms (GRPO / DPO / ILQL / BC_LM) chains
# --------------------------------------------------------------------------- #


def _params_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _llm_cfg():
    import jax.numpy as jnp

    from agilerl_tpu.llm.model import GPTConfig
    from agilerl_tpu.utils.llm_utils import CharTokenizer

    tok = CharTokenizer()
    return tok, GPTConfig(vocab_size=tok.vocab_size, n_layer=2, n_head=4,
                          n_kv_head=2, d_model=64, max_seq_len=64,
                          dtype=jnp.float32)


def _grpo_batch(agent, tok):
    from agilerl_tpu.utils.llm_utils import ReasoningGym

    rng = np.random.default_rng(0)
    rows = [{"question": f"{int(a)}+{int(b)}=", "answer": str(int(a + b))}
            for a, b in rng.integers(0, 5, (24, 2))]
    env = ReasoningGym(rows, rows[:8], tok,
                       reward_fn=lambda c, a, p: float(c.strip().startswith(a)),
                       data_batch_size=4)
    prompts = env.reset()
    comp, cmask = agent.get_action(prompts)
    ids, action_masks = env.assemble_learn_batch(comp, cmask)
    _, rewards = env.step(comp, cmask)
    return (ids, action_masks, rewards)


def test_grpo_save_load_clone_mutate_learn_chain(tmp_path):
    from agilerl_tpu.algorithms.grpo import GRPO

    tok, cfg = _llm_cfg()
    agent = GRPO(config=cfg, pad_token_id=tok.pad_token_id,
                 eos_token_id=tok.eos_token_id, group_size=4, batch_size=8,
                 max_output_tokens=4, lr=1e-3, seed=0)
    batch = _grpo_batch(agent, tok)
    assert np.isfinite(agent.learn(batch)[0])

    p = tmp_path / "grpo.ckpt"
    agent.save_checkpoint(p)
    loaded = GRPO.load(p)
    _params_equal(agent.actor.params, loaded.actor.params)

    clone = loaded.clone(index=2)
    mutated = make_muts(rl_hp=1.0).rl_hyperparam_mutation(clone)
    assert mutated.mut is not None
    assert np.isfinite(mutated.learn(batch)[0])
    # the pre-mutation lineage is untouched
    _params_equal(agent.actor.params, loaded.actor.params)

    p2 = tmp_path / "grpo2.ckpt"
    mutated.save_checkpoint(p2)
    reloaded = GRPO.load(p2)
    _params_equal(mutated.actor.params, reloaded.actor.params)


def test_dpo_save_load_clone_mutate_learn_chain(tmp_path):
    from agilerl_tpu.algorithms.dpo import DPO
    from agilerl_tpu.utils.llm_utils import PreferenceGym

    tok, cfg = _llm_cfg()
    rng = np.random.default_rng(0)
    rows = [{"prompt": f"{int(a)}+1=", "chosen": str(int(a) + 1),
             "rejected": str(int(a))} for a in rng.integers(0, 5, 16)]
    env = PreferenceGym(rows, rows[:8], tok, data_batch_size=8)
    batch = env.reset()
    agent = DPO(config=cfg, pad_token_id=tok.pad_token_id,
                eos_token_id=tok.eos_token_id, lr=5e-3, beta=0.5, seed=0)
    assert np.isfinite(agent.learn(batch)[0])

    p = tmp_path / "dpo.ckpt"
    agent.save_checkpoint(p)
    loaded = DPO.load(p)
    _params_equal(agent.actor.params, loaded.actor.params)

    mutated = make_muts(rl_hp=1.0).rl_hyperparam_mutation(loaded.clone(index=1))
    assert mutated.mut is not None
    assert np.isfinite(mutated.learn(batch)[0])

    p2 = tmp_path / "dpo2.ckpt"
    mutated.save_checkpoint(p2)
    _params_equal(mutated.actor.params, DPO.load(p2).actor.params)


@pytest.mark.parametrize("algo_name", ["ilql", "bc_lm"])
def test_legacy_language_rl_chain(algo_name, tmp_path):
    from agilerl_tpu.algorithms.ilql import BC_LM, ILQL
    from agilerl_tpu.data.rl_data import Language_Observation, RL_Dataset
    from agilerl_tpu.utils.llm_utils import CharTokenizer

    tok = CharTokenizer()
    import jax.numpy as jnp

    from agilerl_tpu.llm.model import GPTConfig

    cfg = GPTConfig(vocab_size=tok.vocab_size, n_layer=2, n_head=4,
                    d_model=64, max_seq_len=32, dtype=jnp.float32)
    rng = np.random.default_rng(0)
    obs = []
    for _ in range(24):
        a = int(rng.integers(0, 5))
        good = rng.random() < 0.5
        obs.append(Language_Observation(sequence=[
            (f"{a}+1=", None),
            (str(a + 1) if good else str(a), 1.0 if good else -1.0)]))
    ds = RL_Dataset(obs, tok, max_len=8)
    cls = ILQL if algo_name == "ilql" else BC_LM
    agent = cls(config=cfg, lr=1e-3, seed=0)
    assert np.isfinite(agent.learn(ds.sample_batch(8, rng)))

    p = tmp_path / f"{algo_name}.ckpt"
    agent.save_checkpoint(p)
    loaded = cls.load(p)
    _params_equal(agent.actor.params, loaded.actor.params)

    mutated = make_muts(rl_hp=1.0).rl_hyperparam_mutation(loaded.clone(index=3))
    assert mutated.mut is not None
    assert np.isfinite(mutated.learn(ds.sample_batch(8, rng)))

    p2 = tmp_path / f"{algo_name}2.ckpt"
    mutated.save_checkpoint(p2)
    _params_equal(mutated.actor.params, cls.load(p2).actor.params)
