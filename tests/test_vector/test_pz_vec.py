import functools

import numpy as np
import pytest
from gymnasium import spaces


class TinyParallelEnv:
    """Minimal PettingZoo-parallel-API env for vectorisation tests."""

    def __init__(self, n_agents=2, episode_len=5):
        self.possible_agents = [f"a_{i}" for i in range(n_agents)]
        self.agents = []
        self.episode_len = episode_len
        self._t = 0

    def observation_space(self, agent):
        return spaces.Box(-1, 1, (3,), np.float32)

    def action_space(self, agent):
        return spaces.Discrete(2)

    def reset(self, seed=None, options=None):
        self.agents = list(self.possible_agents)
        self._t = 0
        obs = {a: np.full(3, self._t, np.float32) for a in self.agents}
        return obs, {}

    def step(self, actions):
        self._t += 1
        done = self._t >= self.episode_len
        obs = {a: np.full(3, self._t, np.float32) for a in self.agents}
        rew = {a: float(actions[a]) for a in self.agents}
        term = {a: False for a in self.agents}
        trunc = {a: done for a in self.agents}
        if done:
            self.agents = []
        return obs, rew, term, trunc, {}

    def close(self):
        pass


def test_sync_vec_env():
    from agilerl_tpu.vector import PettingZooVecEnv

    env = PettingZooVecEnv([TinyParallelEnv for _ in range(3)])
    obs, _ = env.reset(seed=0)
    assert obs["a_0"].shape == (3, 3)
    for t in range(7):  # across the autoreset boundary
        actions = {a: np.ones(3, np.int64) for a in env.agents}
        obs, rew, term, trunc, _ = env.step(actions)
        assert rew["a_0"].shape == (3,)
    env.close()


def test_async_vec_env():
    from agilerl_tpu.vector import AsyncPettingZooVecEnv

    env = AsyncPettingZooVecEnv([TinyParallelEnv for _ in range(2)])
    obs, _ = env.reset(seed=0)
    assert obs["a_0"].shape == (2, 3)
    for _ in range(6):
        actions = {a: np.zeros(2, np.int64) for a in env.agents}
        obs, rew, term, trunc, _ = env.step(actions)
        assert obs["a_1"].shape == (2, 3)
        assert rew["a_0"].shape == (2,)
    env.close()


class DictObsParallelEnv(TinyParallelEnv):
    """Dict observation space with mixed dtypes (float image + int flag)."""

    def observation_space(self, agent):
        return spaces.Dict({
            "img": spaces.Box(0, 1, (2, 2, 1), np.float32),
            "flag": spaces.Discrete(4),
        })

    def _obs(self):
        return {
            a: {"img": np.full((2, 2, 1), self._t, np.float32),
                "flag": np.int64(self._t % 4)}
            for a in self.agents
        }

    def reset(self, seed=None, options=None):
        self.agents = list(self.possible_agents)
        self._t = 0
        return self._obs(), {}

    def step(self, actions):
        self._t += 1
        done = self._t >= self.episode_len
        obs = self._obs()
        rew = {a: 1.0 for a in self.agents}
        term = {a: done for a in self.agents}
        trunc = {a: False for a in self.agents}
        if done:
            self.agents = []
        return obs, rew, term, trunc, {}


class DyingAgentEnv(TinyParallelEnv):
    """Agent a_1 dies (drops out of the dicts) after step 2."""

    def step(self, actions):
        self._t += 1
        done = self._t >= self.episode_len
        if self._t == 2:
            self.agents = [a for a in self.agents if a != "a_1"]
        obs = {a: np.full(3, self._t, np.float32) for a in self.agents}
        rew = {a: 1.0 for a in self.agents}
        term = {a: done for a in self.agents}
        trunc = {a: False for a in self.agents}
        if done:
            self.agents = []
        return obs, rew, term, trunc, {}


class CrashingEnv(TinyParallelEnv):
    def step(self, actions):
        raise RuntimeError("worker exploded")


def test_async_final_obs_at_autoreset():
    """VERDICT #4: the TRUE final observation (pre-reset successor) must reach
    the trainer — without it MA bootstrap targets at boundaries are corrupt."""
    from agilerl_tpu.vector import AsyncPettingZooVecEnv

    env = AsyncPettingZooVecEnv(
        [functools.partial(TinyParallelEnv, episode_len=3) for _ in range(2)]
    )
    env.reset(seed=0)
    for t in range(1, 3):
        obs, rew, term, trunc, info = env.step(
            {a: np.zeros(2, np.int64) for a in env.agents}
        )
        assert "final_obs" not in info
    # 3rd step ends the episode in every env
    obs, rew, term, trunc, info = env.step(
        {a: np.zeros(2, np.int64) for a in env.agents}
    )
    assert trunc["a_0"].all()
    # next_obs is the autoreset obs (t=0); final_obs is the true successor (t=3)
    np.testing.assert_allclose(obs["a_0"], 0.0)
    assert "final_obs" in info
    np.testing.assert_allclose(info["final_obs"]["a_0"], 3.0)
    np.testing.assert_allclose(info["final_obs"]["a_1"], 3.0)
    env.close()


def test_async_dict_obs_typed_shared_memory():
    """Dict spaces decompose into typed shared-memory leaves; int leaves must
    come back as ints, not float32-flattened."""
    from agilerl_tpu.vector import AsyncPettingZooVecEnv

    env = AsyncPettingZooVecEnv(
        [functools.partial(DictObsParallelEnv, episode_len=4) for _ in range(2)]
    )
    obs, _ = env.reset(seed=0)
    assert obs["a_0"]["img"].shape == (2, 2, 1, 1) or obs["a_0"]["img"].shape == (2, 2, 2, 1)
    obs, rew, term, trunc, info = env.step(
        {a: np.zeros(2, np.int64) for a in env.agents}
    )
    assert obs["a_0"]["img"].shape == (2, 2, 2, 1)
    assert obs["a_0"]["img"].dtype == np.float32
    np.testing.assert_allclose(obs["a_0"]["img"][:, 0, 0, 0], 1.0)
    assert np.issubdtype(obs["a_0"]["flag"].dtype, np.integer)
    np.testing.assert_array_equal(obs["a_0"]["flag"], [1, 1])
    # final_obs carries the Dict structure too
    for _ in range(3):
        obs, rew, term, trunc, info = env.step(
            {a: np.zeros(2, np.int64) for a in env.agents}
        )
    assert "final_obs" in info
    np.testing.assert_allclose(info["final_obs"]["a_0"]["img"][:, 0, 0, 0], 4.0)
    np.testing.assert_array_equal(info["final_obs"]["a_0"]["flag"], [0, 0])
    env.close()


def test_async_dead_agent_placeholder():
    """An agent absent from a step's dicts gets a NaN placeholder obs and a
    NaN reward — detectably invalid, as the reference's get_placeholder_value
    :765 returns (0.0 would be a legal reward/observation)."""
    from agilerl_tpu.vector import AsyncPettingZooVecEnv

    env = AsyncPettingZooVecEnv([functools.partial(DyingAgentEnv, episode_len=4) for _ in range(2)])
    env.reset(seed=0)
    obs, rew, *_ = env.step({a: np.zeros(2, np.int64) for a in env.agents})
    np.testing.assert_allclose(obs["a_1"], 1.0)  # still alive at t=1
    obs, rew, *_ = env.step({a: np.zeros(2, np.int64) for a in env.agents})
    assert np.isnan(obs["a_1"]).all()  # dead -> NaN placeholder
    assert np.isnan(rew["a_1"]).all()
    np.testing.assert_allclose(obs["a_0"], 2.0)  # survivor unaffected
    env.close()


def test_async_worker_error_propagates():
    from agilerl_tpu.vector import AsyncPettingZooVecEnv

    env = AsyncPettingZooVecEnv([CrashingEnv for _ in range(2)])
    env.reset(seed=0)
    with pytest.raises(RuntimeError, match="worker exploded"):
        env.step({a: np.zeros(2, np.int64) for a in env.agents})
    env.close()


def test_ma_off_policy_buffer_purity_at_boundaries():
    """e2e: transitions written through the async vec env must bootstrap from
    the TRUE final obs at episode ends, never the autoreset obs (the MA mirror
    of the single-agent final_obs test; VERDICT #4 'done' criterion)."""
    from agilerl_tpu.components import MultiAgentReplayBuffer
    from agilerl_tpu.vector import AsyncPettingZooVecEnv

    ep_len = 3
    env = AsyncPettingZooVecEnv(
        [functools.partial(TinyParallelEnv, episode_len=ep_len) for _ in range(2)]
    )
    buf = MultiAgentReplayBuffer(max_size=64, agent_ids=env.agents)
    obs, _ = env.reset(seed=0)
    for _ in range(2 * ep_len):
        actions = {a: np.zeros(2, np.int64) for a in env.agents}
        next_obs, rew, term, trunc, info = env.step(actions)
        store_next = info.get("final_obs", next_obs)
        done = {a: np.logical_or(term[a], trunc[a]).astype(np.float32)
                for a in env.agents}
        buf.save_to_memory(obs, actions, rew, store_next, done, is_vectorised=True)
        obs = next_obs
    n = len(buf)
    stored_obs = np.asarray(buf.state.storage["obs"]["a_0"])[:n]
    stored_next = np.asarray(buf.state.storage["next_obs"]["a_0"])[:n]
    stored_done = np.asarray(buf.state.storage["done"]["a_0"])[:n]
    # every transition's successor is obs value + 1 — including at episode
    # boundaries, where the autoreset obs (0) would break the chain
    np.testing.assert_allclose(stored_next[:, 0], stored_obs[:, 0] + 1.0)
    assert stored_done.sum() > 0  # boundaries were crossed
    env.close()


def test_sanitize_ma_transition_zeroes_nan_placeholders():
    """Standard (non-wrapper) training loops must stay finite when agents die:
    NaN placeholder obs/rewards are zeroed at the trainer boundary."""
    from agilerl_tpu.vector import sanitize_ma_transition

    obs = {"a_0": np.array([[1.0, 2.0], [np.nan, np.nan]], np.float32),
           "a_1": {"img": np.full((2, 3), np.nan, np.float32),
                   "flag": np.array([1, 2], np.int64)}}
    rew = {"a_0": np.array([0.5, np.nan]), "a_1": np.float64(np.nan)}
    clean_obs, clean_rew = sanitize_ma_transition(obs, rew)
    np.testing.assert_array_equal(clean_obs["a_0"][1], [0.0, 0.0])
    np.testing.assert_array_equal(clean_obs["a_0"][0], [1.0, 2.0])
    np.testing.assert_array_equal(clean_obs["a_1"]["img"], 0.0)
    np.testing.assert_array_equal(clean_obs["a_1"]["flag"], [1, 2])  # ints pass
    np.testing.assert_allclose(clean_rew["a_0"], [0.5, 0.0])
    assert clean_rew["a_1"] == 0.0
