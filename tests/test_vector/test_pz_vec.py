import numpy as np
import pytest
from gymnasium import spaces


class TinyParallelEnv:
    """Minimal PettingZoo-parallel-API env for vectorisation tests."""

    def __init__(self, n_agents=2, episode_len=5):
        self.possible_agents = [f"a_{i}" for i in range(n_agents)]
        self.agents = []
        self.episode_len = episode_len
        self._t = 0

    def observation_space(self, agent):
        return spaces.Box(-1, 1, (3,), np.float32)

    def action_space(self, agent):
        return spaces.Discrete(2)

    def reset(self, seed=None, options=None):
        self.agents = list(self.possible_agents)
        self._t = 0
        obs = {a: np.full(3, self._t, np.float32) for a in self.agents}
        return obs, {}

    def step(self, actions):
        self._t += 1
        done = self._t >= self.episode_len
        obs = {a: np.full(3, self._t, np.float32) for a in self.agents}
        rew = {a: float(actions[a]) for a in self.agents}
        term = {a: False for a in self.agents}
        trunc = {a: done for a in self.agents}
        if done:
            self.agents = []
        return obs, rew, term, trunc, {}

    def close(self):
        pass


def test_sync_vec_env():
    from agilerl_tpu.vector import PettingZooVecEnv

    env = PettingZooVecEnv([TinyParallelEnv for _ in range(3)])
    obs, _ = env.reset(seed=0)
    assert obs["a_0"].shape == (3, 3)
    for t in range(7):  # across the autoreset boundary
        actions = {a: np.ones(3, np.int64) for a in env.agents}
        obs, rew, term, trunc, _ = env.step(actions)
        assert rew["a_0"].shape == (3,)
    env.close()


def test_async_vec_env():
    from agilerl_tpu.vector import AsyncPettingZooVecEnv

    env = AsyncPettingZooVecEnv([TinyParallelEnv for _ in range(2)])
    obs, _ = env.reset(seed=0)
    assert obs["a_0"].shape == (2, 3)
    for _ in range(6):
        actions = {a: np.zeros(2, np.int64) for a in env.agents}
        obs, rew, term, trunc, _ = env.step(actions)
        assert obs["a_1"].shape == (2, 3)
        assert rew["a_0"].shape == (2,)
    env.close()
