"""Vector-env depth tier (VERDICT r4 weak #7: test breadth vs the
reference's 57-cell test_vector suite). Exercises the semantics the core
tests skip: per-env seeding determinism, options passthrough, worker
errors raised from reset, typed shared-memory fidelity for bool/uint8
leaves, final_obs row selectivity at partial autoreset, and lifecycle
misuse (step-after-close, double close, reset during a pending step).

Ref model: /root/reference/tests/test_vector/test_vector.py (shared-memory
plumbing, autoreset, error propagation over pz_vector_test_utils fixtures).
"""

import numpy as np
import pytest
from gymnasium import spaces


class SeededObsEnv:
    """Obs drawn from the reset seed — distinguishes per-env seed offsets."""

    def __init__(self, episode_len=4):
        self.possible_agents = ["a_0", "a_1"]
        self.agents = []
        self.episode_len = episode_len
        self._t = 0
        self._rng = np.random.default_rng(0)

    def observation_space(self, agent):
        return spaces.Box(-10, 10, (2,), np.float32)

    def action_space(self, agent):
        return spaces.Discrete(3)

    def reset(self, seed=None, options=None):
        self.agents = list(self.possible_agents)
        self._t = 0
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        bias = float((options or {}).get("bias", 0.0))
        obs = {a: self._rng.uniform(-1, 1, 2).astype(np.float32) + bias
               for a in self.agents}
        return obs, {"options_seen": options}

    def step(self, actions):
        self._t += 1
        done = self._t >= self.episode_len
        obs = {a: self._rng.uniform(-1, 1, 2).astype(np.float32)
               for a in self.agents}
        rew = {a: 1.0 for a in self.agents}
        term = {a: False for a in self.agents}
        trunc = {a: done for a in self.agents}
        if done:
            self.agents = []
        return obs, rew, term, trunc, {}

    def close(self):
        pass


class MixedLeafEnv:
    """bool + uint8 + float leaves in one Dict space: shared memory must
    carry each leaf in its own dtype (float32-flattening would corrupt
    the uint8 image and the bool flag)."""

    def __init__(self, episode_len=3):
        self.possible_agents = ["a_0"]
        self.agents = []
        self.episode_len = episode_len
        self._t = 0

    def observation_space(self, agent):
        return spaces.Dict({
            "img": spaces.Box(0, 255, (2, 2, 1), np.uint8),
            "flag": spaces.MultiBinary(3),
            "vec": spaces.Box(-1, 1, (2,), np.float32),
        })

    def action_space(self, agent):
        return spaces.Discrete(2)

    def _obs(self):
        return {"a_0": {
            "img": np.full((2, 2, 1), 200 + self._t, np.uint8),
            "flag": np.array([1, 0, self._t % 2], np.int8),
            "vec": np.full(2, 0.5 * self._t, np.float32),
        }}

    def reset(self, seed=None, options=None):
        self.agents = list(self.possible_agents)
        self._t = 0
        return self._obs(), {}

    def step(self, actions):
        self._t += 1
        done = self._t >= self.episode_len
        obs = self._obs()
        if done:
            self.agents = []
        return (obs, {"a_0": 0.0}, {"a_0": False}, {"a_0": done}, {})

    def close(self):
        pass


class FailingResetEnv:
    possible_agents = ["a_0"]
    agents = []

    def observation_space(self, agent):
        return spaces.Box(-1, 1, (2,), np.float32)

    def action_space(self, agent):
        return spaces.Discrete(2)

    def reset(self, seed=None, options=None):
        raise ValueError("boom at reset")

    def step(self, actions):  # pragma: no cover - never reached
        raise AssertionError

    def close(self):
        pass


class VariableLenEnv:
    """Episode length differs per instance so autoreset hits one row only."""

    def __init__(self, episode_len):
        self.possible_agents = ["a_0"]
        self.agents = []
        self.episode_len = episode_len
        self._t = 0

    def observation_space(self, agent):
        return spaces.Box(-100, 100, (1,), np.float32)

    def action_space(self, agent):
        return spaces.Discrete(2)

    def reset(self, seed=None, options=None):
        self.agents = list(self.possible_agents)
        self._t = 0
        return {"a_0": np.zeros(1, np.float32)}, {}

    def step(self, actions):
        self._t += 1
        done = self._t >= self.episode_len
        obs = {"a_0": np.full(1, self._t, np.float32)}
        if done:
            self.agents = []
        return (obs, {"a_0": float(self._t)}, {"a_0": False},
                {"a_0": done}, {})

    def close(self):
        pass


# --------------------------------------------------------------------------
# async
# --------------------------------------------------------------------------


def test_async_seeding_deterministic_and_per_env_distinct():
    from agilerl_tpu.vector import AsyncPettingZooVecEnv

    env = AsyncPettingZooVecEnv([SeededObsEnv for _ in range(2)])
    obs1, _ = env.reset(seed=7)
    obs2, _ = env.reset(seed=7)
    np.testing.assert_array_equal(obs1["a_0"], obs2["a_0"])
    # env i resets with seed + i: rows must differ
    assert not np.allclose(obs1["a_0"][0], obs1["a_0"][1])
    obs3, _ = env.reset(seed=8)
    assert not np.allclose(obs1["a_0"], obs3["a_0"])
    env.close()


def test_async_reset_options_reach_workers():
    from agilerl_tpu.vector import AsyncPettingZooVecEnv

    env = AsyncPettingZooVecEnv([SeededObsEnv for _ in range(2)])
    base, _ = env.reset(seed=0)
    biased, _ = env.reset(seed=0, options={"bias": 5.0})
    # options must reach every worker's env.reset: same seed, shifted obs
    np.testing.assert_allclose(biased["a_0"], base["a_0"] + 5.0, rtol=1e-6)
    env.close()


def test_async_mixed_leaf_dtypes_roundtrip_exactly():
    from agilerl_tpu.vector import AsyncPettingZooVecEnv

    env = AsyncPettingZooVecEnv([MixedLeafEnv for _ in range(2)])
    obs, _ = env.reset(seed=0)
    assert obs["a_0"]["img"].dtype == np.uint8
    np.testing.assert_array_equal(
        obs["a_0"]["img"], np.full((2, 2, 2, 1), 200, np.uint8))
    acts = {"a_0": np.zeros(2, np.int64)}
    obs, _, _, _, _ = env.step(acts)
    np.testing.assert_array_equal(
        obs["a_0"]["img"], np.full((2, 2, 2, 1), 201, np.uint8))
    np.testing.assert_array_equal(
        obs["a_0"]["flag"][:, 2], np.ones(2, obs["a_0"]["flag"].dtype))
    np.testing.assert_allclose(obs["a_0"]["vec"], 0.5)
    env.close()


def test_async_reset_error_propagates_with_traceback():
    from agilerl_tpu.vector import AsyncPettingZooVecEnv

    env = AsyncPettingZooVecEnv([FailingResetEnv])
    with pytest.raises(RuntimeError, match="boom at reset"):
        env.reset(seed=0)
    env.close()


def test_async_reset_during_pending_step_raises():
    from agilerl_tpu.vector import AsyncPettingZooVecEnv

    env = AsyncPettingZooVecEnv([SeededObsEnv for _ in range(2)])
    env.reset(seed=0)
    env.step_async({"a_0": np.zeros(2, np.int64),
                    "a_1": np.zeros(2, np.int64)})
    with pytest.raises(RuntimeError, match="pending"):
        env.reset(seed=1)
    env.step_wait()  # drain so close() is clean
    env.close()


def test_async_step_after_close_fails_loudly():
    from agilerl_tpu.vector import AsyncPettingZooVecEnv

    env = AsyncPettingZooVecEnv([SeededObsEnv])
    env.reset(seed=0)
    env.close()
    with pytest.raises((AssertionError, RuntimeError, BrokenPipeError, EOFError)):
        env.step({"a_0": np.zeros(1, np.int64),
                  "a_1": np.zeros(1, np.int64)})


def test_async_close_idempotent():
    from agilerl_tpu.vector import AsyncPettingZooVecEnv

    env = AsyncPettingZooVecEnv([SeededObsEnv])
    env.reset(seed=0)
    env.close()
    env.close()  # second close must not raise/hang


def test_async_partial_autoreset_touches_only_finished_rows():
    from agilerl_tpu.vector import AsyncPettingZooVecEnv

    import functools

    env = AsyncPettingZooVecEnv([
        functools.partial(VariableLenEnv, 2),
        functools.partial(VariableLenEnv, 5)])
    env.reset(seed=0)
    acts = {"a_0": np.zeros(2, np.int64)}
    env.step(acts)
    _, rew, _, trunc, info = env.step(acts)  # env0 finishes at t=2
    assert info["autoreset"].tolist() == [True, False]
    final = info["final_obs"]["a_0"]
    assert float(final[0, 0]) == 2.0      # env0: true pre-reset successor
    assert float(final[1, 0]) == 2.0      # env1: its CURRENT obs (t=2)
    assert float(rew["a_0"][1]) == 2.0    # env1 unaffected by env0's reset
    # next step: env0 runs its fresh episode (t=1), env1 continues (t=3)
    obs, rew, _, _, info = env.step(acts)
    assert info["autoreset"].tolist() == [False, False]
    assert float(obs["a_0"][0, 0]) == 1.0
    assert float(obs["a_0"][1, 0]) == 3.0
    env.close()


def test_async_single_env_edge():
    from agilerl_tpu.vector import AsyncPettingZooVecEnv

    env = AsyncPettingZooVecEnv([SeededObsEnv])
    obs, _ = env.reset(seed=0)
    assert obs["a_0"].shape == (1, 2)
    obs, rew, term, trunc, _ = env.step(
        {"a_0": np.zeros(1, np.int64), "a_1": np.zeros(1, np.int64)})
    assert rew["a_0"].shape == (1,)
    assert term["a_0"].dtype == np.bool_ or term["a_0"].dtype == bool
    env.close()


# --------------------------------------------------------------------------
# sync
# --------------------------------------------------------------------------


def test_sync_seeding_deterministic():
    from agilerl_tpu.vector import PettingZooVecEnv

    env = PettingZooVecEnv([SeededObsEnv for _ in range(2)])
    obs1, _ = env.reset(seed=3)
    obs2, _ = env.reset(seed=3)
    np.testing.assert_array_equal(obs1["a_0"], obs2["a_0"])
    assert not np.allclose(obs1["a_0"][0], obs1["a_0"][1])
    env.close()


def test_sync_mixed_leaf_dtypes():
    from agilerl_tpu.vector import PettingZooVecEnv

    env = PettingZooVecEnv([MixedLeafEnv for _ in range(2)])
    obs, _ = env.reset(seed=0)
    assert obs["a_0"]["img"].dtype == np.uint8
    np.testing.assert_array_equal(
        obs["a_0"]["img"], np.full((2, 2, 2, 1), 200, np.uint8))
    env.close()


def test_sync_autoreset_reward_at_boundary():
    from agilerl_tpu.vector import PettingZooVecEnv

    env = PettingZooVecEnv([lambda: VariableLenEnv(2) for _ in range(2)])
    env.reset(seed=0)
    acts = {"a_0": np.zeros(2, np.int64)}
    env.step(acts)
    _, rew, _, trunc, _ = env.step(acts)
    # the boundary step's reward belongs to the FINISHED episode
    np.testing.assert_allclose(rew["a_0"], 2.0)
    assert trunc["a_0"].all()
    env.close()
