"""Async vec-env semantics grid: state-machine guards, seeding determinism,
Tuple/MultiDiscrete spaces, heterogeneous per-agent spaces, close idempotence
(parity: the reference's tests/test_vector suite, SURVEY.md §4).
"""

import numpy as np
import pytest
from gymnasium import spaces


class SpacedParallelEnv:
    """Parallel env with per-agent heterogeneous obs spaces and a Tuple obs."""

    def __init__(self, episode_len=4):
        self.possible_agents = ["walker", "flyer"]
        self.agents = []
        self.episode_len = episode_len
        self._t = 0
        self._seed = 0

    def observation_space(self, agent):
        if agent == "walker":
            return spaces.Tuple(
                (spaces.Box(-1, 1, (2,), np.float32), spaces.Discrete(4))
            )
        return spaces.Box(0, 255, (3, 3, 1), np.uint8)

    def action_space(self, agent):
        if agent == "walker":
            return spaces.MultiDiscrete([2, 3])
        return spaces.Box(-1, 1, (2,), np.float32)

    def _obs(self, rng):
        return {
            "walker": (rng.normal(size=2).astype(np.float32).clip(-1, 1),
                       int(rng.integers(0, 4))),
            "flyer": rng.integers(0, 255, size=(3, 3, 1)).astype(np.uint8),
        }

    def reset(self, seed=None, options=None):
        if seed is not None:
            self._seed = seed
        self._rng = np.random.default_rng(self._seed)
        self.agents = list(self.possible_agents)
        self._t = 0
        return self._obs(self._rng), {}

    def step(self, actions):
        assert np.asarray(actions["walker"]).shape == (2,)
        assert np.asarray(actions["flyer"]).shape == (2,)
        self._t += 1
        done = self._t >= self.episode_len
        obs = self._obs(self._rng)
        rew = {a: float(self._t) for a in self.agents}
        term = {a: done for a in self.agents}
        trunc = {a: False for a in self.agents}
        if done:
            self.agents = []
        return obs, rew, term, trunc, {}

    def close(self):
        pass


@pytest.fixture
def env():
    from agilerl_tpu.vector import AsyncPettingZooVecEnv

    e = AsyncPettingZooVecEnv([SpacedParallelEnv for _ in range(2)])
    yield e
    e.close()


def test_heterogeneous_tuple_and_image_obs(env):
    obs, _ = env.reset(seed=0)
    walker = obs["walker"]
    assert isinstance(walker, tuple) and len(walker) == 2
    assert walker[0].shape == (2, 2) and walker[0].dtype == np.float32
    assert walker[1].shape == (2,)  # batched Discrete
    assert obs["flyer"].shape == (2, 3, 3, 1) and obs["flyer"].dtype == np.uint8


def test_multidiscrete_and_box_actions_roundtrip(env):
    env.reset(seed=0)
    actions = {
        "walker": np.tile(np.int64([1, 2]), (2, 1)),
        "flyer": np.zeros((2, 2), np.float32),
    }
    obs, rew, term, trunc, _ = env.step(actions)
    assert rew["walker"].shape == (2,)
    np.testing.assert_allclose(rew["walker"], 1.0)


def test_seeding_is_deterministic():
    from agilerl_tpu.vector import AsyncPettingZooVecEnv

    def run(seed):
        e = AsyncPettingZooVecEnv([SpacedParallelEnv for _ in range(2)])
        try:
            obs, _ = e.reset(seed=seed)
            return np.asarray(obs["flyer"]).copy()
        finally:
            e.close()

    a, b, c = run(7), run(7), run(8)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)


def test_step_before_reset_raises(env):
    actions = {
        "walker": np.tile(np.int64([0, 0]), (2, 1)),
        "flyer": np.zeros((2, 2), np.float32),
    }
    with pytest.raises(Exception):
        env.step(actions)


def test_double_step_async_raises(env):
    env.reset(seed=0)
    actions = {
        "walker": np.tile(np.int64([0, 0]), (2, 1)),
        "flyer": np.zeros((2, 2), np.float32),
    }
    env.step_async(actions)
    with pytest.raises(Exception):
        env.step_async(actions)
    env.step_wait()


def test_step_wait_without_async_raises(env):
    env.reset(seed=0)
    with pytest.raises(Exception):
        env.step_wait()


def test_close_idempotent():
    from agilerl_tpu.vector import AsyncPettingZooVecEnv

    e = AsyncPettingZooVecEnv([SpacedParallelEnv for _ in range(2)])
    e.reset(seed=0)
    e.close()
    e.close()  # second close must be a no-op, not a crash


def test_autoreset_continues_stepping(env):
    env.reset(seed=0)
    actions = {
        "walker": np.tile(np.int64([1, 1]), (2, 1)),
        "flyer": np.zeros((2, 2), np.float32),
    }
    rewards = []
    for _ in range(9):  # across two autoreset boundaries (episode_len=4)
        _, rew, term, trunc, _ = env.step(actions)
        rewards.append(float(rew["walker"][0]))
    # reward == t within each episode: 1,2,3,4 then autoreset repeats
    assert rewards[:4] == [1.0, 2.0, 3.0, 4.0]
    assert 1.0 in rewards[4:6]  # new episode restarted counting
