"""Fast-tier parametrize helper (VERDICT r2 next #4c).

`-m "not slow"` (run_tests.sh fast) must still touch every algorithm, module
and loop, so each grid keeps its core cell(s) fast and demotes the expensive
variants to the full tier through this ONE helper."""

import pytest


def fast_core(cells, fast=("vec",), is_fast=None):
    """Keep core cells in the fast tier; mark every other cell slow.

    `is_fast` (a predicate over the cell) covers tuple/bool cells that a
    membership test can't; by default a cell is fast iff it is in `fast`.
    Tuple cells are splatted into pytest.param so multi-arg parametrize
    signatures keep working."""
    if is_fast is None:
        def is_fast(c):
            return c in fast

    def demote(c):
        args = c if isinstance(c, tuple) else (c,)
        return pytest.param(*args, marks=pytest.mark.slow)

    return [c if is_fast(c) else demote(c) for c in cells]
