"""Entry-surface tests (VERDICT #8): the configs/training tree is consumable
end-to-end by create_population, and the benchmarking scripts run at tiny scale
(parity model: the reference's tests/test_train/test_train.py runs every loop
through its public entry surface)."""

import pathlib

import numpy as np
import pytest
from gymnasium import spaces

from agilerl_tpu.modules.configs import load_yaml_config
from agilerl_tpu.utils.utils import create_population

REPO = pathlib.Path(__file__).resolve().parents[1]
CONFIGS = sorted((REPO / "configs" / "training").rglob("*.yaml"))

BOX4 = spaces.Box(-1, 1, (4,), np.float32)
IMG = spaces.Box(0, 1, (24, 24, 3), np.float32)
DISC = spaces.Discrete(2)
CONT = spaces.Box(-1, 1, (1,), np.float32)


def _spaces_for(cfg, name):
    algo = cfg["INIT_HP"]["ALGO"]
    obs = IMG if "image" in name or "resnet" in name else BOX4
    if algo in ("DDPG", "TD3"):
        return obs, CONT
    return obs, DISC


def test_config_tree_covers_reference_families():
    names = {p.stem for p in CONFIGS}
    for required in ("dqn", "dqn_rainbow", "dqn_lstm", "ddpg", "ddpg_simba",
                     "td3", "cqn", "neural_ucb", "neural_ts", "maddpg",
                     "matd3", "ippo", "ppo", "ppo_image", "ppo_recurrent",
                     "dpo", "grpo", "multi_input"):
        assert required in names, f"missing configs/training YAML: {required}"


@pytest.mark.parametrize(
    "path", [p for p in CONFIGS if p.stem not in ("grpo", "dpo")],
    ids=lambda p: str(p.relative_to(REPO / "configs" / "training")),
)
def test_every_yaml_builds_a_population(path):
    """Each YAML's INIT_HP + NET_CONFIG must construct a real agent."""
    cfg = load_yaml_config(path)
    hp = cfg["INIT_HP"]
    net = cfg.get("NET_CONFIG") or {}
    algo = hp["ALGO"]

    if algo in ("MADDPG", "MATD3", "IPPO"):
        ids = ["agent_0", "agent_1"]
        obs = {a: BOX4 for a in ids}
        act = {a: DISC for a in ids}
        pop = create_population(algo, obs, act, agent_ids=ids,
                                population_size=1, net_config=net,
                                INIT_HP=hp, seed=0)
    elif algo in ("NeuralUCB", "NeuralTS"):
        pop = create_population(
            algo, spaces.Box(-1, 1, (6,), np.float32), spaces.Discrete(3),
            population_size=1, net_config=net, INIT_HP=hp, seed=0,
        )
    else:
        obs, act = _spaces_for(cfg, path.stem)
        pop = create_population(algo, obs, act, population_size=1,
                                net_config=net, INIT_HP=hp, seed=0)
    agent = pop[0]
    assert agent.index == 0
    # the mapped HPs actually landed on the agent
    if "LR" in hp and hasattr(agent, "lr"):
        assert agent.lr == pytest.approx(hp["LR"])
    if "BATCH_SIZE" in hp and hasattr(agent, "batch_size"):
        assert agent.batch_size == hp["BATCH_SIZE"]


def test_llm_yaml_configs_parse():
    for stem in ("grpo", "dpo"):
        cfg = load_yaml_config(REPO / "configs" / "training" / f"{stem}.yaml")
        assert cfg["INIT_HP"]["ALGO"].lower() == stem


@pytest.mark.slow
def test_benchmarking_resnet_tiny():
    from benchmarking.benchmarking_resnet import main

    main(max_steps=400, pop_size=1)


@pytest.mark.slow
def test_benchmarking_multi_agent_on_policy_tiny():
    from benchmarking.benchmarking_multi_agent_on_policy import main

    main(max_steps=1024, pop_size=2)


@pytest.mark.slow
def test_benchmarking_off_policy_distributed_tiny():
    """The pod-sharded EvoDQN generation runs on the 8-device virtual mesh."""
    from benchmarking.benchmarking_off_policy_distributed import main

    main(generations=1, members_per_device=1)


@pytest.mark.slow
def test_dryrun_multichip_8_devices():
    """The driver's multi-chip validation surface (__graft_entry__.
    dryrun_multichip) must keep working: full sharded GRPO step + sp/ep/pp
    axes + composed-mesh grad-parity cells on 8 virtual CPU devices.
    Run in a subprocess — it force-configures the backend/device count."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8)"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=1700,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "dryrun_multichip OK on 8 devices" in proc.stdout
