"""Numeric parity for the action-distribution layer (parity:
agilerl/networks/distributions.py — EvolvableDistribution:110, apply_mask:239).

The reference builds on torch.distributions; here torch is the independent
oracle: log_prob / entropy for every family are pinned against
torch.distributions closed forms on shared random inputs, masking is checked
both statistically (masked actions never sampled) and analytically (masked
log-softmax == renormalised over the valid set), and the tanh-squashed Normal
is compared against torch's TransformedDistribution(TanhTransform).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.distributions as tdist

from agilerl_tpu.networks.distributions import (
    DistConfig,
    dist_config_from_space,
    entropy,
    extra_params,
    log_prob,
    mode,
    sample,
)
from gymnasium import spaces

KEY = jax.random.PRNGKey(0)
RTOL = 1e-5
ATOL = 1e-5


def _rand(shape, seed=0):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


class TestCategorical:
    CFG = DistConfig(kind="categorical", action_dim=5)

    def test_log_prob_matches_torch(self):
        logits = _rand((7, 5))
        actions = np.array([0, 1, 2, 3, 4, 0, 3])
        ours = log_prob(self.CFG, jnp.asarray(logits), jnp.asarray(actions))
        ref = tdist.Categorical(logits=torch.tensor(logits)).log_prob(
            torch.tensor(actions)
        )
        np.testing.assert_allclose(np.asarray(ours), ref.numpy(), rtol=RTOL, atol=ATOL)

    def test_entropy_matches_torch(self):
        logits = _rand((7, 5))
        ours = entropy(self.CFG, jnp.asarray(logits))
        ref = tdist.Categorical(logits=torch.tensor(logits)).entropy()
        np.testing.assert_allclose(np.asarray(ours), ref.numpy(), rtol=RTOL, atol=ATOL)

    def test_sample_frequencies_match_probs(self):
        logits = jnp.asarray([[2.0, 0.0, -1.0, 0.5, 1.0]])
        n = 20_000
        acts = sample(
            self.CFG, jnp.broadcast_to(logits, (n, 5)), KEY
        )
        freqs = np.bincount(np.asarray(acts), minlength=5) / n
        probs = np.asarray(jax.nn.softmax(logits[0]))
        np.testing.assert_allclose(freqs, probs, atol=0.02)

    def test_mask_blocks_sampling_and_renormalises(self):
        logits = _rand((4, 5))
        m = np.array([1, 0, 1, 0, 1], np.float32)
        acts = sample(
            self.CFG, jnp.asarray(np.tile(logits, (500, 1))), KEY,
            mask=jnp.asarray(np.tile(m, (2000, 1))),
        )
        assert not np.isin(np.asarray(acts), [1, 3]).any()
        # masked log_prob == log-softmax renormalised over the valid subset
        ours = log_prob(
            self.CFG, jnp.asarray(logits), jnp.zeros((4,), jnp.int32),
            mask=jnp.asarray(np.tile(m, (4, 1))),
        )
        valid = logits[:, m.astype(bool)]
        ref = valid[:, 0] - np.log(np.exp(valid).sum(axis=1))
        np.testing.assert_allclose(np.asarray(ours), ref, rtol=1e-4, atol=1e-4)

    def test_mode_is_argmax_respecting_mask(self):
        logits = jnp.asarray([[5.0, 10.0, 1.0]])
        cfg = DistConfig(kind="categorical", action_dim=3)
        assert int(mode(cfg, logits)[0]) == 1
        assert int(mode(cfg, logits, mask=jnp.asarray([[1.0, 0.0, 1.0]]))[0]) == 0


class TestMultiDiscrete:
    CFG = DistConfig(kind="multidiscrete", action_dim=9, nvec=(2, 3, 4))

    def test_log_prob_is_sum_of_branches(self):
        logits = _rand((6, 9))
        actions = np.stack(
            [np.random.default_rng(i).integers(0, n, 6) for i, n in enumerate((2, 3, 4))],
            axis=-1,
        )
        ours = log_prob(self.CFG, jnp.asarray(logits), jnp.asarray(actions))
        ref = np.zeros(6)
        for i, (s, n) in enumerate(((0, 2), (2, 3), (5, 4))):
            ref += (
                tdist.Categorical(logits=torch.tensor(logits[:, s : s + n]))
                .log_prob(torch.tensor(actions[:, i]))
                .numpy()
            )
        np.testing.assert_allclose(np.asarray(ours), ref, rtol=RTOL, atol=ATOL)

    def test_entropy_is_sum_of_branches(self):
        logits = _rand((6, 9))
        ours = entropy(self.CFG, jnp.asarray(logits))
        ref = sum(
            tdist.Categorical(logits=torch.tensor(logits[:, s : s + n])).entropy().numpy()
            for s, n in ((0, 2), (2, 3), (5, 4))
        )
        np.testing.assert_allclose(np.asarray(ours), ref, rtol=RTOL, atol=ATOL)

    def test_samples_within_ranges(self):
        acts = np.asarray(sample(self.CFG, jnp.asarray(_rand((1000, 9))), KEY))
        assert acts.shape == (1000, 3)
        for i, n in enumerate((2, 3, 4)):
            assert acts[:, i].min() >= 0 and acts[:, i].max() < n


class TestBernoulli:
    CFG = DistConfig(kind="bernoulli", action_dim=4)

    def test_log_prob_matches_torch(self):
        logits = _rand((5, 4))
        actions = (np.random.default_rng(1).random((5, 4)) < 0.5).astype(np.float32)
        ours = log_prob(self.CFG, jnp.asarray(logits), jnp.asarray(actions))
        ref = (
            tdist.Bernoulli(logits=torch.tensor(logits))
            .log_prob(torch.tensor(actions))
            .sum(-1)
        )
        np.testing.assert_allclose(np.asarray(ours), ref.numpy(), rtol=RTOL, atol=ATOL)

    def test_entropy_matches_torch(self):
        logits = _rand((5, 4))
        ours = entropy(self.CFG, jnp.asarray(logits))
        ref = tdist.Bernoulli(logits=torch.tensor(logits)).entropy().sum(-1)
        np.testing.assert_allclose(np.asarray(ours), ref.numpy(), rtol=RTOL, atol=ATOL)

    def test_mode_thresholds_at_zero(self):
        logits = jnp.asarray([[-1.0, 0.5, 3.0, -0.1]])
        np.testing.assert_array_equal(np.asarray(mode(self.CFG, logits))[0], [0, 1, 1, 0])


class TestNormal:
    CFG = DistConfig(kind="normal", action_dim=3, log_std_init=-0.3)

    def _extra(self):
        return {k: jnp.asarray(v) for k, v in extra_params(self.CFG).items()}

    def test_log_prob_matches_torch_diag_normal(self):
        mean = _rand((8, 3))
        actions = _rand((8, 3), seed=2)
        extra = self._extra()
        ours = log_prob(
            self.CFG, jnp.asarray(mean), jnp.asarray(actions), dist_extra=extra
        )
        std = np.exp(np.asarray(extra["log_std"]))
        ref = (
            tdist.Normal(torch.tensor(mean), torch.tensor(std))
            .log_prob(torch.tensor(actions))
            .sum(-1)
        )
        np.testing.assert_allclose(np.asarray(ours), ref.numpy(), rtol=1e-4, atol=1e-4)

    def test_entropy_matches_torch(self):
        mean = _rand((8, 3))
        extra = self._extra()
        ours = entropy(self.CFG, jnp.asarray(mean), dist_extra=extra)
        std = np.exp(np.asarray(extra["log_std"]))
        ref = (
            tdist.Normal(torch.tensor(mean), torch.tensor(np.tile(std, (8, 1))))
            .entropy()
            .sum(-1)
        )
        np.testing.assert_allclose(np.asarray(ours), ref.numpy(), rtol=1e-4, atol=1e-4)

    def test_sample_statistics(self):
        mean = jnp.asarray([[0.5, -1.0, 2.0]])
        extra = self._extra()
        acts = np.asarray(
            sample(self.CFG, jnp.broadcast_to(mean, (50_000, 3)), KEY, dist_extra=extra)
        )
        np.testing.assert_allclose(acts.mean(0), np.asarray(mean)[0], atol=0.02)
        np.testing.assert_allclose(
            acts.std(0), np.exp(np.asarray(extra["log_std"])), atol=0.02
        )

    def test_squashed_log_prob_matches_torch_tanh_transform(self):
        cfg = DistConfig(kind="normal", action_dim=3, log_std_init=-0.3, squash=True)
        mean = _rand((8, 3))
        extra = {k: jnp.asarray(v) for k, v in extra_params(cfg).items()}
        u = _rand((8, 3), seed=3)
        a = np.tanh(u).astype(np.float32)
        ours = log_prob(cfg, jnp.asarray(mean), jnp.asarray(a), dist_extra=extra)
        std = np.exp(np.asarray(extra["log_std"]))
        base = tdist.Normal(torch.tensor(mean), torch.tensor(np.tile(std, (8, 1))))
        ref = tdist.TransformedDistribution(
            base, [tdist.transforms.TanhTransform(cache_size=1)]
        ).log_prob(torch.tensor(a)).sum(-1)
        # both sides guard atanh/log with small epsilons — keep tolerance loose
        np.testing.assert_allclose(np.asarray(ours), ref.numpy(), rtol=1e-3, atol=1e-3)

    def test_squash_bounds_samples_and_mode(self):
        cfg = DistConfig(kind="normal", action_dim=2, log_std_init=0.5, squash=True)
        extra = {k: jnp.asarray(v) for k, v in extra_params(cfg).items()}
        mean = jnp.asarray(np.full((1000, 2), 3.0, np.float32))
        acts = np.asarray(sample(cfg, mean, KEY, dist_extra=extra))
        assert (np.abs(acts) <= 1.0).all()
        assert (np.abs(np.asarray(mode(cfg, mean))) < 1.0).all()


class TestSpaceMapping:
    @pytest.mark.parametrize(
        "space,kind,dim",
        [
            (spaces.Discrete(6), "categorical", 6),
            (spaces.MultiDiscrete([2, 3]), "multidiscrete", 5),
            (spaces.MultiBinary(4), "bernoulli", 4),
            (spaces.Box(-1, 1, (3,)), "normal", 3),
        ],
    )
    def test_config_from_space(self, space, kind, dim):
        cfg = dist_config_from_space(space)
        assert cfg.kind == kind and cfg.action_dim == dim
