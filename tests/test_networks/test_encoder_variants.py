"""Encoder-variant grid: simba/recurrent/resnet switches across every network
head (parity: the reference's per-network simba/recurrent parametrisations —
networks/base.py:182, SURVEY.md §2.2).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from gymnasium import spaces

from agilerl_tpu.networks import (
    ContinuousQNetwork,
    DeterministicActor,
    QNetwork,
    StochasticActor,
    ValueNetwork,
)
from agilerl_tpu.utils.spaces import preprocess_observation, sample_obs

BOX = spaces.Box(-1, 1, (6,), np.float32)
IMG = spaces.Box(0, 255, (16, 16, 3), np.uint8)
DISC = spaces.Discrete(3)
ACT_BOX = spaces.Box(-1, 1, (2,), np.float32)


@pytest.mark.parametrize("net_cls,kwargs", [
    (QNetwork, {"action_space": DISC}),
    (ValueNetwork, {}),
    (DeterministicActor, {"action_space": ACT_BOX}),
])
def test_simba_encoder_selected(key, net_cls, kwargs):
    net = net_cls(BOX, key=key, simba=True, **kwargs)
    assert net.config.encoder_kind == "simba"
    obs = preprocess_observation(BOX, sample_obs(BOX, 4))
    out = net(obs)
    out = out[0] if isinstance(out, tuple) else out
    assert np.isfinite(np.asarray(out)).all()
    # simba encoders keep their block mutations available through the network
    net.apply_mutation("encoder.add_block")
    out2 = net(obs)
    out2 = out2[0] if isinstance(out2, tuple) else out2
    assert np.asarray(out2).shape == np.asarray(out).shape


def test_resnet_encoder_selected(key):
    net = QNetwork(IMG, DISC, key=key, resnet=True, latent_dim=16)
    assert net.config.encoder_kind == "resnet"
    obs = preprocess_observation(IMG, sample_obs(IMG, 2))
    assert net(obs).shape == (2, 3)


def test_recurrent_encoder_selected(key):
    net = ValueNetwork(BOX, key=key, recurrent=True, latent_dim=16)
    assert net.config.encoder_kind == "lstm"


def test_simba_flag_ignored_for_images(key):
    """simba is an MLP-family architecture; image spaces keep the CNN."""
    net = QNetwork(IMG, DISC, key=key, simba=True, latent_dim=16)
    assert net.config.encoder_kind == "cnn"


@pytest.mark.parametrize("obs_space", [BOX, IMG])
def test_continuous_q_encoder_variants(key, obs_space):
    net = ContinuousQNetwork(obs_space, ACT_BOX, key=key, latent_dim=16)
    obs = preprocess_observation(obs_space, sample_obs(obs_space, 3))
    q = net(obs, jnp.zeros((3, 2)))
    assert q.shape == (3,)
    assert np.isfinite(np.asarray(q)).all()


def test_latent_mutation_rails(key):
    """Latent mutations clamp at min/max and never break the forward."""
    net = QNetwork(BOX, DISC, key=key, latent_dim=16)
    rng = np.random.default_rng(0)
    for _ in range(20):
        net.apply_mutation(
            str(rng.choice(["add_latent_node", "remove_latent_node"])), rng=rng
        )
        assert net.config.min_latent_dim <= net.config.latent_dim <= net.config.max_latent_dim
    obs = preprocess_observation(BOX, sample_obs(BOX, 2))
    assert net(obs).shape == (2, 3)


def test_stochastic_actor_simba_evaluate_consistency(key):
    actor = StochasticActor(BOX, DISC, key=key, simba=True)
    obs = preprocess_observation(BOX, sample_obs(BOX, 5))
    action, logp, ent = actor(obs, key=jax.random.PRNGKey(1))
    logp2, _ = actor.evaluate_actions(obs, action)
    np.testing.assert_allclose(np.asarray(logp), np.asarray(logp2), rtol=1e-5)
