import jax
import jax.numpy as jnp
import numpy as np
import pytest
from gymnasium import spaces

from agilerl_tpu.networks import (
    ContinuousQNetwork,
    DeterministicActor,
    QNetwork,
    RainbowQNetwork,
    StochasticActor,
    ValueNetwork,
)
from agilerl_tpu.utils.spaces import preprocess_observation, sample_obs

BOX = spaces.Box(-1, 1, (4,))
IMG = spaces.Box(0, 255, (16, 16, 3), dtype=np.uint8)
DISC = spaces.Discrete(3)
DICT = spaces.Dict({"img": spaces.Box(0, 255, (16, 16, 3), dtype=np.uint8),
                    "vec": spaces.Box(-1, 1, (5,))})


@pytest.mark.parametrize("obs_space", [BOX, IMG, DISC, DICT])
def test_qnetwork_encoder_autoselect(key, obs_space):
    net = QNetwork(obs_space, DISC, key=key)
    obs = preprocess_observation(obs_space, sample_obs(obs_space, 6))
    q = net(obs)
    assert q.shape == (6, 3)
    assert jnp.isfinite(q).all()


def test_latent_mutation(key):
    net = QNetwork(BOX, DISC, key=key, latent_dim=32)
    info = net.apply_mutation("add_latent_node")
    assert net.config.latent_dim > 32
    assert net.config.head.num_inputs == net.config.latent_dim
    obs = preprocess_observation(BOX, sample_obs(BOX, 2))
    assert net(obs).shape == (2, 3)


def test_encoder_and_head_mutations(key, rng):
    net = QNetwork(BOX, DISC, key=key)
    for name in ["encoder.add_layer", "head.add_node", "encoder.add_node", "head.add_layer"]:
        net.apply_mutation(name, rng=rng)
    obs = preprocess_observation(BOX, sample_obs(BOX, 2))
    assert net(obs).shape == (2, 3)


def test_continuous_q(key):
    act_space = spaces.Box(-2, 2, (2,))
    net = ContinuousQNetwork(BOX, act_space, key=key)
    obs = preprocess_observation(BOX, sample_obs(BOX, 5))
    q = net(obs, jnp.zeros((5, 2)))
    assert q.shape == (5,)
    net.apply_mutation("add_latent_node")
    q2 = net(obs, jnp.zeros((5, 2)))
    assert q2.shape == (5,)


def test_deterministic_actor_rescale(key):
    act_space = spaces.Box(np.array([-2.0, 0.0]), np.array([2.0, 10.0]))
    actor = DeterministicActor(BOX, act_space, key=key)
    obs = preprocess_observation(BOX, sample_obs(BOX, 7))
    a = actor(obs)
    assert a.shape == (7, 2)
    assert (a[:, 0] >= -2).all() and (a[:, 0] <= 2).all()
    assert (a[:, 1] >= 0).all() and (a[:, 1] <= 10).all()


@pytest.mark.parametrize(
    "act_space",
    [DISC, spaces.Box(-1, 1, (2,)), spaces.MultiDiscrete([3, 4]), spaces.MultiBinary(3)],
)
def test_stochastic_actor(key, act_space):
    actor = StochasticActor(BOX, act_space, key=key)
    obs = preprocess_observation(BOX, sample_obs(BOX, 5))
    action, logp, ent = actor(obs, key=jax.random.PRNGKey(1))
    assert logp.shape == (5,)
    assert ent.shape == (5,)
    assert jnp.isfinite(logp).all()
    logp2, ent2 = actor.evaluate_actions(obs, action)
    np.testing.assert_allclose(logp, logp2, rtol=1e-5)


def test_stochastic_actor_masking(key):
    actor = StochasticActor(BOX, DISC, key=key)
    obs = preprocess_observation(BOX, sample_obs(BOX, 100))
    mask = jnp.tile(jnp.array([[True, False, True]]), (100, 1))
    action, _, _ = actor(obs, key=jax.random.PRNGKey(0), action_mask=mask)
    assert not (action == 1).any()


def test_value_network(key):
    net = ValueNetwork(BOX, key=key)
    obs = preprocess_observation(BOX, sample_obs(BOX, 4))
    v = net(obs)
    assert v.shape == (4,)


def test_rainbow_q(key):
    net = RainbowQNetwork(BOX, DISC, num_atoms=21, v_min=-5, v_max=5, key=key)
    obs = preprocess_observation(BOX, sample_obs(BOX, 4))
    q = net(obs)
    assert q.shape == (4, 3)
    logp = net(obs, q_values=False, key=jax.random.PRNGKey(0))
    assert logp.shape == (4, 3, 21)
    np.testing.assert_allclose(jnp.exp(logp).sum(-1), 1.0, rtol=1e-4)


def test_rainbow_mutation(key):
    net = RainbowQNetwork(BOX, DISC, key=key)
    net.apply_mutation("add_latent_node")
    obs = preprocess_observation(BOX, sample_obs(BOX, 2))
    assert net(obs).shape == (2, 3)


def test_clone(key):
    actor = StochasticActor(BOX, DISC, key=key)
    clone = actor.clone()
    obs = preprocess_observation(BOX, sample_obs(BOX, 3))
    a1 = actor(obs, deterministic=True)[0]
    a2 = clone(obs, deterministic=True)[0]
    np.testing.assert_array_equal(a1, a2)
