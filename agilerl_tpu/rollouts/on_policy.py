"""Rollout collection into the RolloutBuffer (parity: agilerl/rollouts/on_policy.py
— collect_rollouts:199, collect_rollouts_recurrent:220, shared core _collect:16
with per-env done resets and hidden-state carry).

Works against any gymnasium.vector-style env (JaxVecEnv or gym.vector).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np


def collect_rollouts(agent, env, n_steps: Optional[int] = None) -> float:
    """Step the env `n_steps` times, storing transitions in agent.rollout_buffer.
    Returns the mean reward collected. Envs that publish "action_mask" on the
    info dict get masked sampling, and the mask rides the buffer so learn()
    recomputes log-probs on the same masked distribution
    (parity: train_on_policy.py:270)."""
    n_steps = n_steps or agent.learn_step
    buf = agent.rollout_buffer
    if agent._last_obs is None:
        obs, info = env.reset()
        agent._last_obs = obs
        agent._last_info = info
        agent._last_done = np.zeros(agent.num_envs, np.float32)
        if agent.recurrent:
            agent._hidden = agent.get_initial_hidden_state()
    obs = agent._last_obs
    info = getattr(agent, "_last_info", None)

    # maskedness is LATCHED on the agent the first time any info carries a
    # mask (reset info, or a step info mid-rollout) and never unlatches, so
    # the buffer schema cannot flip between collects: once masked, every
    # buffered step carries a mask (all-ones when a step omits it); envs that
    # only publish masks on step infos get a ones backfill for earlier rows
    # (review finding — schema drift between collects crashed _write_step)
    def _latch_mask(i):
        if not agent._masked_env and isinstance(i, dict) and i.get("action_mask") is not None:
            agent._masked_env = True
            agent._mask_shape = np.asarray(i["action_mask"]).shape[1:]

    if not hasattr(agent, "_masked_env"):
        agent._masked_env = False
        agent._mask_shape = None
    _latch_mask(info)
    total_reward = 0.0
    for _ in range(n_steps):
        hidden_before = agent._hidden if agent.recurrent else None
        action_mask = (
            info.get("action_mask")
            if agent._masked_env and isinstance(info, dict)
            else None
        )
        action, logp, value, _ = agent.get_action_and_value(
            obs, action_mask=action_mask
        )
        next_obs, reward, terminated, truncated, info = env.step(np.asarray(action))
        agent._last_info = info
        _latch_mask(info)
        done = np.logical_or(terminated, truncated).astype(np.float32)
        # time-limit bootstrapping: truncated episodes fold gamma*V(s') into
        # the final reward so GAE (which treats done as terminal) stays
        # unbiased at truncation boundaries (review finding)
        trunc_arr = np.asarray(truncated, bool)
        if trunc_arr.any() and isinstance(info, dict) and "final_obs" in info:
            v_final = np.asarray(agent.value_of(info["final_obs"]))
            reward = np.asarray(reward, np.float32) + agent.gamma * v_final * trunc_arr
        step = dict(
            obs=obs,
            action=action,
            reward=np.asarray(reward, np.float32),
            done=done,
            value=value,
            log_prob=logp,
        )
        if agent._masked_env:
            step["action_mask"] = np.asarray(
                action_mask if action_mask is not None
                else np.ones((agent.num_envs,) + agent._mask_shape),
                np.float32,
            )
        if agent.recurrent:
            step["hidden_state"] = hidden_before
            # reset hidden for envs that finished
            agent._hidden = jax.tree_util.tree_map(
                lambda h: np.asarray(h) * (1.0 - done)[None, :, None], agent._hidden
            )
        buf.add(**step)
        total_reward += float(np.mean(reward))
        obs = next_obs
    agent._last_obs = obs
    agent._last_done = done
    return total_reward / n_steps


collect_rollouts_recurrent = collect_rollouts  # same core (parity alias :220)
