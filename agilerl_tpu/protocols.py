"""Runtime-checkable protocol contracts for the framework's core interfaces.

Parity: agilerl/protocols.py (612 LoC of torch-facing Protocols). Here the
contracts describe the TPU-native shapes of the same roles:

- modules are ``(frozen config, params pytree)`` pairs whose compute lives in
  static ``apply(config, params, x)`` functions (jit-cacheable by config), so
  ``EvolvableModuleProtocol`` pins the params/state_dict/mutation surface
  rather than torch's ``nn.Module`` forward contract;
- algorithms are thin stateful shells over pure jitted train steps, so
  ``EvolvableAlgorithmProtocol`` pins the registry/clone/checkpoint surface
  that the HPO engine (tournament + mutations) relies on across all
  15 algorithm families.

These are `typing.Protocol` classes marked ``@runtime_checkable`` so both
static checkers and tests can assert conformance structurally
(``isinstance(agent, EvolvableAlgorithmProtocol)``) without inheritance.
``tests/test_protocols.py`` runs that assertion over every concrete module,
network, and algorithm in the package — the anti-drift check the reference
gets from its protocols module (reference agilerl/protocols.py:333,
EvolvableAlgorithmProtocol).

Note: ``@runtime_checkable`` isinstance checks only verify member *presence*,
not signatures — signature drift is still caught by the conformance tests
calling the members.
"""

from __future__ import annotations

from typing import (
    Any,
    Dict,
    Iterator,
    List,
    Optional,
    Protocol,
    Tuple,
    TypeVar,
    runtime_checkable,
)

import numpy as np

from agilerl_tpu.typing import KeyArray, MutationType, Params

__all__ = [
    "MutationMethodProtocol",
    "EvolvableModuleProtocol",
    "ModuleDictProtocol",
    "EvolvableNetworkProtocol",
    "OptimizerWrapperProtocol",
    "NetworkGroupProtocol",
    "OptimizerConfigProtocol",
    "HyperparameterConfigProtocol",
    "MutationRegistryProtocol",
    "EvolvableAlgorithmProtocol",
    "RLAlgorithmProtocol",
    "MultiAgentRLAlgorithmProtocol",
    "AgentWrapperProtocol",
    "VecEnvProtocol",
    "ReplayBufferProtocol",
]


@runtime_checkable
class MutationMethodProtocol(Protocol):
    """A mutation method's descriptor metadata (reference protocols.py:53).

    Attached by the ``@mutation`` decorator: the wrapped config-transforming
    function plus the mutation class it belongs to (LAYER/NODE/ACTIVATION)
    and whether shrinking params must be re-sliced rather than preserved.
    """

    fn: Any
    mutation_type: MutationType
    shrink_params: bool


@runtime_checkable
class EvolvableModuleProtocol(Protocol):
    """A mutation-capable (config, params) module (reference protocols.py:95).

    The reference's protocol revolves around ``nn.Module`` forward/state_dict;
    here the instance surface is the evolution + checkpoint contract, while
    compute is reachable via the class's static ``apply``.
    """

    config: Any
    params: Params

    @property
    def init_dict(self) -> Dict[str, Any]: ...

    @classmethod
    def get_mutation_methods(cls) -> Dict[str, MutationMethodProtocol]: ...

    def sample_mutation_method(
        self,
        new_layer_prob: float = 0.2,
        rng: Optional[np.random.Generator] = None,
    ) -> Optional[str]: ...

    def apply_mutation(
        self, name: str, rng: Optional[np.random.Generator] = None
    ) -> Dict: ...

    def clone(self) -> "EvolvableModuleProtocol": ...

    def state_dict(self) -> Params: ...

    def load_state_dict(self, params: Params) -> None: ...


T_Module = TypeVar("T_Module", bound=EvolvableModuleProtocol)


@runtime_checkable
class ModuleDictProtocol(Protocol):
    """Container of named evolvable modules (reference protocols.py:214)."""

    def __getitem__(self, k: str) -> Any: ...

    def __setitem__(self, k: str, v: Any) -> None: ...

    def __iter__(self) -> Iterator[str]: ...

    def __len__(self) -> int: ...

    def keys(self) -> Any: ...

    def values(self) -> Any: ...

    def items(self) -> Any: ...

    @property
    def params(self) -> Dict[str, Params]: ...

    def clone(self) -> "ModuleDictProtocol": ...


@runtime_checkable
class EvolvableNetworkProtocol(Protocol):
    """Encoder + head network with latent-space mutations
    (reference protocols.py:159).

    Same evolution surface as a module, plus the encoder/head split: the
    network owns an auto-selected encoder (MLP/CNN/MultiInput by observation
    space) and exposes latent mutations that rebuild the head boundary.
    """

    config: Any
    params: Params

    @property
    def init_dict(self) -> Dict[str, Any]: ...

    def mutation_methods(self) -> List[str]: ...

    def sample_mutation_method(
        self,
        new_layer_prob: float = 0.2,
        rng: Optional[np.random.Generator] = None,
    ) -> Optional[str]: ...

    def apply_mutation(
        self, name: str, rng: Optional[np.random.Generator] = None
    ) -> Dict: ...

    def change_activation(self, activation: str, output: bool = False) -> None: ...

    def clone(self) -> "EvolvableNetworkProtocol": ...

    def state_dict(self) -> Params: ...

    def load_state_dict(self, params: Params) -> None: ...


@runtime_checkable
class OptimizerWrapperProtocol(Protocol):
    """Optimizer lifecycle owner (reference protocols.py:81).

    Wraps an optax transformation: (re)init against a params pytree after
    architecture mutations, apply updates, mutate the learning rate in place.
    """

    lr: float

    def init(self, params: Any) -> None: ...

    def reinit(self, params: Any) -> None: ...

    def set_lr(self, lr: float) -> None: ...

    def update(self, grads: Any, params: Any) -> Any: ...

    def state_dict(self) -> Any: ...

    def load_state_dict(self, state: Any) -> None: ...


@runtime_checkable
class NetworkGroupProtocol(Protocol):
    """A policy/evaluation network group (reference protocols.py:278)."""

    eval: str
    shared: Any
    policy: bool

    def shared_names(self) -> List[str]: ...


@runtime_checkable
class OptimizerConfigProtocol(Protocol):
    """Which networks an optimizer owns (reference protocols.py:292)."""

    name: str
    networks: Any
    lr: str


@runtime_checkable
class HyperparameterConfigProtocol(Protocol):
    """Named RL hyperparameter search space (reference hpo/mutation.py usage)."""

    def names(self) -> List[str]: ...

    def sample(self, rng: Optional[np.random.Generator] = None) -> Optional[str]: ...

    def __getitem__(self, k: str) -> Any: ...

    def __contains__(self, k: str) -> bool: ...


@runtime_checkable
class MutationRegistryProtocol(Protocol):
    """Registry binding groups + optimizers + hooks (reference protocols.py:311)."""

    groups: List[Any]
    optimizer_configs: List[Any]
    hooks: List[str]

    def register_group(self, group: Any) -> None: ...

    def register_optimizer(self, cfg: Any) -> None: ...

    def register_hook(self, method_name: str) -> None: ...

    @property
    def policy_group(self) -> Optional[Any]: ...

    def all_network_names(self) -> List[str]: ...

    def validate(self) -> None: ...


@runtime_checkable
class EvolvableAlgorithmProtocol(Protocol):
    """The HPO engine's view of an algorithm (reference protocols.py:333).

    Tournament selection needs fitness/clone/index; Mutations needs the
    registry, evolvable_attributes, hp_config, reinit_optimizers and the
    mutation bookkeeping attrs; trainers and checkpointing need
    save/load_checkpoint. Every concrete algorithm (DQN ... GRPO) satisfies
    this structurally — asserted in tests/test_protocols.py.
    """

    registry: MutationRegistryProtocol
    fitness: List[float]
    scores: List[float]
    steps: List[int]
    index: int
    mut: Any

    def evolvable_attributes(self) -> Dict[str, Any]: ...

    @property
    def hp_config(self) -> Any: ...

    @property
    def init_dict(self) -> Dict[str, Any]: ...

    def clone(self, index: Optional[int] = None, wrap: bool = True) -> Any: ...

    def reinit_optimizers(self) -> None: ...

    def mutation_hook(self) -> None: ...

    def checkpoint_dict(self) -> Dict[str, Any]: ...

    def save_checkpoint(self, path: Any) -> None: ...

    def load_checkpoint(self, path: Any) -> None: ...

    def test(self, env: Any, *args: Any, **kwargs: Any) -> float: ...


@runtime_checkable
class RLAlgorithmProtocol(EvolvableAlgorithmProtocol, Protocol):
    """Single-agent algorithm: adds the acting/learning surface
    (reference protocols.py:333 get_action/learn members)."""

    observation_space: Any
    action_space: Any

    def get_action(self, obs: Any, *args: Any, **kwargs: Any) -> Any: ...

    def learn(self, experiences: Any, *args: Any, **kwargs: Any) -> Any: ...

    def preprocess_observation(self, obs: Any) -> Any: ...


@runtime_checkable
class MultiAgentRLAlgorithmProtocol(EvolvableAlgorithmProtocol, Protocol):
    """Multi-agent algorithm: dict-keyed spaces and grouped agents."""

    observation_spaces: Any
    action_spaces: Any
    agent_ids: List[str]

    def get_action(self, obs: Any, *args: Any, **kwargs: Any) -> Any: ...

    def learn(self, experiences: Any, *args: Any, **kwargs: Any) -> Any: ...

    def preprocess_observation(self, obs: Dict[str, Any]) -> Dict[str, Any]: ...


@runtime_checkable
class AgentWrapperProtocol(Protocol):
    """Wrapper delegating to an algorithm (reference protocols.py:418).

    RSNorm and AsyncAgentsWrapper satisfy this: they forward get_action/learn
    while transforming observations/experiences in between.
    """

    agent: Any

    def get_action(self, obs: Any, *args: Any, **kwargs: Any) -> Any: ...

    def learn(self, experiences: Any, *args: Any, **kwargs: Any) -> Any: ...


@runtime_checkable
class VecEnvProtocol(Protocol):
    """Vectorised env surface the trainers consume (reference
    vector/pz_vec_env.py + gymnasium VectorEnv overlap)."""

    num_envs: int

    def reset(self, *args: Any, **kwargs: Any) -> Any: ...

    def step(self, actions: Any) -> Any: ...


@runtime_checkable
class ReplayBufferProtocol(Protocol):
    """Experience store surface shared by all off-policy buffers."""

    def __len__(self) -> int: ...

    def add(self, *args: Any, **kwargs: Any) -> Any: ...

    def sample(self, *args: Any, **kwargs: Any) -> Any: ...

    def clear(self) -> None: ...
