"""Retry/backoff policies for flaky HOST-LOCAL edges.

The device-side math is deterministic; what flakes in production are host
boundaries — env ``reset``/``step`` over subprocess pipes or network sims,
dataset fetches, metadata servers. These helpers wrap exactly those edges
with bounded exponential backoff and warn-once telemetry
(``resilience/retries_total``), so transient faults cost a retry instead of
a dead multi-day run — and persistent faults still raise.

Multihost COLLECTIVES are deliberately out of scope: a per-host retry of a
collective desynchronizes the pod (the retrying host re-issues an op its
peers already completed and pairs with the wrong collective, deadlocking
until the runtime timeout). Collectives fail fast; snapshot-resume
(:mod:`agilerl_tpu.resilience.snapshot`) is their recovery path.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Tuple


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff. ``retry_on`` lists the exception types
    considered transient — anything else propagates immediately."""

    max_attempts: int = 3
    backoff_s: float = 0.05
    backoff_mult: float = 2.0
    max_backoff_s: float = 2.0
    retry_on: Tuple[type, ...] = field(
        default=(ConnectionError, TimeoutError, OSError, BrokenPipeError)
    )

    def delay(self, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (1-based)."""
        return min(
            self.backoff_s * (self.backoff_mult ** (attempt - 1)),
            self.max_backoff_s,
        )


#: conservative default for env edges: three tries, sub-second total backoff
DEFAULT_ENV_POLICY = RetryPolicy()


def call_with_retries(
    fn: Callable,
    *args,
    policy: Optional[RetryPolicy] = None,
    name: str = "op",
    registry=None,
    sleep: Callable[[float], None] = time.sleep,
    **kwargs,
) -> Any:
    """Run ``fn(*args, **kwargs)`` under ``policy``. Each retry increments
    ``resilience/retries_total`` and warn-onces per call-site name; the final
    failure re-raises the last exception untouched."""
    policy = policy or DEFAULT_ENV_POLICY
    if registry is None:
        from agilerl_tpu.observability import get_registry

        registry = get_registry()
    last_exc: Optional[BaseException] = None
    for attempt in range(1, policy.max_attempts + 1):
        try:
            return fn(*args, **kwargs)
        except policy.retry_on as e:
            last_exc = e
            if attempt >= policy.max_attempts:
                raise
            registry.counter("resilience/retries_total").inc()
            registry.counter(f"resilience/retries_total:{name}").inc()
            registry.warn_once(
                f"resilience:retry:{name}",
                f"transient failure in {name} ({type(e).__name__}: {e}); "
                f"retrying up to {policy.max_attempts - attempt} more time(s)",
            )
            sleep(policy.delay(attempt))
    raise last_exc  # pragma: no cover - loop always returns or raises


def with_retries(
    policy: Optional[RetryPolicy] = None,
    name: Optional[str] = None,
    registry=None,
) -> Callable[[Callable], Callable]:
    """Decorator form of :func:`call_with_retries`."""

    def deco(fn: Callable) -> Callable:
        import functools

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return call_with_retries(
                fn, *args, policy=policy, name=name or fn.__name__,
                registry=registry, **kwargs,
            )

        return wrapped

    return deco


class RetryingEnv:
    """Env proxy whose ``reset``/``step`` run under a :class:`RetryPolicy`.

    On a retried ``step`` the wrapped env may be mid-episode in an undefined
    state, so subclass-specific recovery (e.g. a forced reset) can be wired
    via ``on_step_retry``; the default simply retries the call, which is the
    right behaviour for connection-level flakes where the remote state
    machine is intact.
    """

    def __init__(
        self,
        env,
        policy: Optional[RetryPolicy] = None,
        registry=None,
        sleep: Callable[[float], None] = time.sleep,
        on_step_retry: Optional[Callable[["RetryingEnv"], None]] = None,
    ):
        self.env = env
        self.policy = policy or DEFAULT_ENV_POLICY
        self._registry = registry
        self._sleep = sleep
        self._on_step_retry = on_step_retry

    def reset(self, *args, **kwargs):
        return call_with_retries(
            self.env.reset, *args, policy=self.policy, name="env.reset",
            registry=self._registry, sleep=self._sleep, **kwargs,
        )

    def step(self, *args, **kwargs):
        attempt = 0

        def run():
            nonlocal attempt
            attempt += 1
            if attempt > 1 and self._on_step_retry is not None:
                self._on_step_retry(self)
            return self.env.step(*args, **kwargs)

        return call_with_retries(
            run, policy=self.policy, name="env.step",
            registry=self._registry, sleep=self._sleep,
        )

    def __getattr__(self, item):
        return getattr(self.env, item)
