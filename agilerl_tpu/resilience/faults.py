"""Deterministic fault-injection harness.

Crash consistency is only real if it is exercised: the :class:`FaultInjector`
attaches to the atomic layer's fault hook (:mod:`agilerl_tpu.resilience.atomic`)
and, at scheduled operation indices, kills the process mid-commit
(:class:`InjectedCrash`) or silently truncates the file just written —
simulating SIGKILL-torn writes and disk corruption in ordinary tier-1 CPU
tests. :class:`ScheduledFailureEnv` plays the same role for the flaky
host-side env edge, raising scheduled exceptions from ``reset``/``step`` so
the retry policies are testable without a flaky network.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Dict, Iterable, List, Mapping, Optional, Tuple

from agilerl_tpu.resilience.atomic import set_fault_hook


class InjectedCrash(BaseException):
    """Simulated hard kill (SIGKILL analogue).

    Derives from ``BaseException`` deliberately: recovery code written as
    ``except Exception`` must NOT be able to swallow it, exactly as no
    handler can swallow a real SIGKILL. Tests catch it explicitly.
    """


class FaultInjector:
    """Count durability operations and fault at scheduled indices.

    Ops (fired by the atomic layer, in commit order) are:
    ``write`` (before a file write), ``wrote`` (file durably in place) and
    ``commit`` (before a snapshot directory is published). The injector
    counts only ops in ``match`` — e.g. ``match=("wrote",)`` with
    ``kill_at_op=2`` kills the process after the third file of a snapshot
    landed but before the manifest/commit, the canonical torn-snapshot
    scenario.

    - ``kill_at_op``: raise :class:`InjectedCrash` when the matched-op
      counter reaches this index (0-based).
    - ``truncate_at_ops``: at these matched-op indices, truncate the file
      involved to ``truncate_to`` of its size and continue silently —
      simulating corruption that only validation (content hashes) can catch.
    - ``path_match``: when set, only ops whose path contains this substring
      count — the **torn-island-export mode** is
      ``FaultInjector(truncate_at_ops=[0], match=("wrote",),
      path_match="members.pkl")``, which corrupts exactly the first island
      export payload so refusal-safe import (hash validation +
      skip-and-warn) is exercisable in tier-1 CPU tests.
    - ``kill_host_at``: the **host-loss mode** — a ``{generation: host_id}``
      schedule consumed by the elastic controller at generation boundaries
      via :meth:`host_to_kill`: the named emulated host is killed (stops
      heartbeating, its lease expires) at that boundary, exercising
      membership-change detection and snapshot-restore recovery.

    Use as a context manager (or ``arm()``/``disarm()``); it installs itself
    as the process-wide fault hook and restores the previous hook on exit.
    The counter is deterministic: same save sequence, same ops, same kill
    point.
    """

    def __init__(
        self,
        kill_at_op: Optional[int] = None,
        truncate_at_ops: Iterable[int] = (),
        truncate_to: float = 0.5,
        match: Tuple[str, ...] = ("write", "wrote", "commit"),
        path_match: Optional[str] = None,
        kill_host_at: Optional[Mapping[int, int]] = None,
    ):
        self.kill_at_op = kill_at_op
        self.truncate_at_ops = frozenset(int(i) for i in truncate_at_ops)
        self.truncate_to = float(truncate_to)
        self.match = tuple(match)
        self.path_match = path_match
        self.kill_host_at: Dict[int, int] = {
            int(g): int(h) for g, h in (kill_host_at or {}).items()
        }
        self.hosts_killed: List[Tuple[int, int]] = []  # (generation, host)
        self.op_count = 0
        self.log: List[Tuple[int, str, str]] = []
        self._prev_hook = None
        self._armed = False

    # -- host-loss schedule (consumed by the elastic controller) --------- #
    def host_to_kill(self, generation: int) -> Optional[int]:
        """The host scheduled to die at this generation boundary (once:
        the schedule entry is consumed), else None."""
        host = self.kill_host_at.pop(int(generation), None)
        if host is not None:
            self.hosts_killed.append((int(generation), int(host)))
        return host

    # -- hook ----------------------------------------------------------- #
    def __call__(self, op: str, path: Path) -> None:
        if op not in self.match:
            return
        if self.path_match is not None and self.path_match not in str(path):
            return
        idx = self.op_count
        self.op_count += 1
        self.log.append((idx, op, str(path)))
        if idx in self.truncate_at_ops:
            self._truncate(path)
        if self.kill_at_op is not None and idx >= self.kill_at_op:
            raise InjectedCrash(
                f"injected kill at op {idx} ({op} {path})"
            )

    def _truncate(self, path: Path) -> None:
        if not path.is_file():
            return
        size = path.stat().st_size
        keep = int(size * self.truncate_to)
        with open(path, "rb+") as fh:
            fh.truncate(keep)
            fh.flush()
            os.fsync(fh.fileno())

    # -- lifecycle ------------------------------------------------------- #
    def arm(self) -> "FaultInjector":
        if not self._armed:
            self._prev_hook = set_fault_hook(self)
            self._armed = True
        return self

    def disarm(self) -> None:
        if self._armed:
            set_fault_hook(self._prev_hook)
            self._prev_hook = None
            self._armed = False

    def __enter__(self) -> "FaultInjector":
        return self.arm()

    def __exit__(self, *exc) -> None:
        self.disarm()


class ScheduledFailureEnv:
    """Env proxy that raises scheduled exceptions from ``reset``/``step``.

    ``fail_resets`` / ``fail_steps`` are 0-based call indices that raise
    ``exc_type`` once each; every other call passes through to the wrapped
    env. Deterministic by construction — the retry tests schedule exactly
    which host-side edge flakes and assert the policy recovers.
    """

    def __init__(self, env, fail_resets: Iterable[int] = (),
                 fail_steps: Iterable[int] = (),
                 exc_type=ConnectionError):
        self.env = env
        self._fail_resets = set(int(i) for i in fail_resets)
        self._fail_steps = set(int(i) for i in fail_steps)
        self._exc_type = exc_type
        self.reset_calls = 0
        self.step_calls = 0

    def reset(self, *args, **kwargs):
        idx = self.reset_calls
        self.reset_calls += 1
        if idx in self._fail_resets:
            raise self._exc_type(f"injected env.reset failure (call {idx})")
        return self.env.reset(*args, **kwargs)

    def step(self, *args, **kwargs):
        idx = self.step_calls
        self.step_calls += 1
        if idx in self._fail_steps:
            raise self._exc_type(f"injected env.step failure (call {idx})")
        return self.env.step(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self.env, name)
