"""Crash-consistent whole-run snapshots.

A **snapshot** is a directory of pickled entries plus a ``manifest.json``
written last, committed atomically (``step_N.tmp/`` → fsync → ``os.replace``)
so a kill at any point leaves either nothing (ignorable ``*.tmp`` garbage) or
a complete, hash-validated snapshot. The capture spec covers everything a
"resumed run is the same run" guarantee needs:

- population: per-agent ``checkpoint_dict()`` (weights + HPs + ``steps`` +
  ``fitness``) **plus** the agent's JAX PRNG key and numpy Generator;
- replay-buffer rings (staging rings flushed first via the buffers' own
  ``state_dict`` which reuses ``stage()``/``flush()``);
- host RNG (numpy global + python ``random``) and env PRNG;
- loop counters (``total_steps``, epsilon, fitness history, cadence state);
- tournament/mutation RNG and the lineage genealogy.

:class:`CheckpointManager` owns the on-disk layout, retention (last K plus
the best-fitness snapshot) and the fallback scan: restore always lands on
the newest snapshot whose every entry validates against the manifest's
content hashes — torn or truncated snapshots are skipped with a warn-once,
never loaded.
"""

from __future__ import annotations

import copy
import json
import shutil
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from agilerl_tpu.resilience.atomic import (
    TMP_DIR_SUFFIX,
    CorruptSnapshotError,
    commit_dir,
    content_hash,
    load_validated_pickle,
    remove_stale_tmp_dirs,
    staged_pickle,
    staged_write_bytes,
)

MANIFEST = "manifest.json"
SNAPSHOT_FORMAT = 1
_STEP_PREFIX = "step_"


def _name_seq(name: str) -> int:
    """Resave sequence of a snapshot dir name (``step_N`` -> 0,
    ``step_N_3`` -> 3), parsed NUMERICALLY: a lexicographic name sort
    would rank ``_9`` above ``_10`` and hand restore/retention a stale
    same-step snapshot."""
    rest = name[len(_STEP_PREFIX):]
    if "_" not in rest:
        return 0
    try:
        return int(rest.rsplit("_", 1)[1])
    except ValueError:
        return 0


def _registry():
    from agilerl_tpu.observability import get_registry

    return get_registry()


# --------------------------------------------------------------------------- #
# PRNG key plumbing (legacy uint32 keys and typed key arrays both survive)
# --------------------------------------------------------------------------- #


def key_to_host(key) -> Any:
    """A picklable host representation of a JAX PRNG key (legacy or typed)."""
    if key is None:
        return None
    try:
        if jnp.issubdtype(key.dtype, jax.dtypes.prng_key):  # typed key array
            return {"__typed_key__": True,
                    "data": np.asarray(jax.random.key_data(key))}
    except (AttributeError, TypeError):
        pass
    return np.asarray(jax.device_get(key))


def key_from_host(blob) -> Optional[jax.Array]:
    if blob is None:
        return None
    if isinstance(blob, dict) and blob.get("__typed_key__"):
        return jax.random.wrap_key_data(jnp.asarray(blob["data"]))
    return jnp.asarray(blob)


# --------------------------------------------------------------------------- #
# capture/restore helpers (duck-typed; every piece is optional)
# --------------------------------------------------------------------------- #


def capture_np_generator(gen: Optional[np.random.Generator]) -> Optional[dict]:
    if gen is None:
        return None
    return gen.bit_generator.state


def restore_np_generator(state: Optional[dict]) -> Optional[np.random.Generator]:
    if state is None:
        return None
    bg = getattr(np.random, state["bit_generator"])()
    bg.state = state
    return np.random.Generator(bg)


def capture_agent(agent) -> Dict[str, Any]:
    """checkpoint_dict (params, HP config, steps, fitness) + PRNG streams —
    a resumed agent continues the exact action/exploration sequence."""
    blob: Dict[str, Any] = {"ckpt": agent.checkpoint_dict()}
    if hasattr(agent, "rng_state"):
        blob["rng"] = agent.rng_state()
    return blob


def restore_agent(agent, blob: Dict[str, Any]) -> bool:
    """Restore ``blob`` into ``agent`` in place. Returns False (warn-once,
    agent untouched) on a class mismatch instead of corrupting it."""
    cls = blob["ckpt"].get("agilerl_tpu_class")
    if cls is not None and cls != type(agent).__name__:
        _registry().warn_once(
            f"resilience:agent_class_mismatch:{cls}",
            f"snapshot agent class {cls!r} != live agent {type(agent).__name__!r}"
            " — leaving the live agent untouched",
        )
        return False
    agent._restore(blob["ckpt"])
    if "rng" in blob and hasattr(agent, "set_rng_state"):
        agent.set_rng_state(blob["rng"])
    return True


def capture_host_rng() -> Dict[str, Any]:
    import random

    return {
        "numpy_global": np.random.get_state(),
        "python_random": random.getstate(),
    }


def restore_host_rng(blob: Optional[Dict[str, Any]]) -> None:
    if not blob:
        return
    import random

    if "numpy_global" in blob:
        np.random.set_state(blob["numpy_global"])
    if "python_random" in blob:
        random.setstate(tuple(
            tuple(x) if isinstance(x, list) else x for x in blob["python_random"]
        ))


def _env_attr_owner(env, attr: str):
    """Innermost wrapper-chain object that actually OWNS ``attr``. Wrappers
    (:class:`RetryingEnv`, gym-style proxies) forward attribute READS via
    ``__getattr__``, so a plain setattr on the outer object would only create
    a shadowing attribute and leave the wrapped env's real PRNG untouched —
    restore must assign on the owner. Ownership = the attribute lives in the
    instance dict or is defined by the class (e.g. gymnasium's ``np_random``
    property, whose setter forwards correctly)."""
    target, seen = env, set()
    while target is not None and id(target) not in seen:
        seen.add(id(target))
        if attr in getattr(target, "__dict__", {}) or hasattr(type(target), attr):
            return target
        target = getattr(target, "env", None)
    return None


def capture_env_rng(env) -> Optional[Dict[str, Any]]:
    """Best-effort env PRNG capture: an env's own ``state_dict`` wins; the
    in-tree :class:`~agilerl_tpu.envs.core.JaxVecEnv` exposes ``_key``;
    gymnasium envs expose ``np_random``. Wrapper chains are walked to the
    owning env. The loops reset the env at every generation/agent boundary,
    so the PRNG stream is the only env state a boundary snapshot needs for
    determinism."""
    if env is None:
        return None
    owner = _env_attr_owner(env, "state_dict")
    sd = getattr(owner, "state_dict", None)
    if callable(sd):
        try:
            return {"kind": "state_dict", "state": sd()}
        except Exception as e:
            # falling through to a PRNG-only capture silently breaks the
            # resumed-run-is-the-same-run guarantee for envs with data
            # cursors — say so once, like capture_buffers does
            _registry().warn_once(
                f"resilience:env_state_dict_failed:{type(env).__name__}",
                f"env {type(env).__name__}.state_dict() raised {e!r} — "
                "capturing only its PRNG; a resumed run may not continue "
                "the same env stream",
            )
    owner = _env_attr_owner(env, "_key")
    if owner is not None:
        return {"kind": "jax_key", "key": key_to_host(owner._key)}
    owner = _env_attr_owner(env, "np_random")
    np_random = getattr(owner, "np_random", None)
    if np_random is not None:
        try:
            return {"kind": "np_random", "state": np_random.bit_generator.state}
        except Exception:
            pass
    return None


def restore_env_rng(env, blob: Optional[Dict[str, Any]]) -> None:
    if not blob or env is None:
        return
    kind = blob.get("kind")
    if kind == "state_dict":
        owner = _env_attr_owner(env, "load_state_dict")
        if owner is not None:
            owner.load_state_dict(blob["state"])
    elif kind == "jax_key":
        owner = _env_attr_owner(env, "_key")
        if owner is not None:
            owner._key = key_from_host(blob["key"])
    elif kind == "np_random":
        gen = restore_np_generator(blob["state"])
        owner = _env_attr_owner(env, "np_random")
        if gen is not None and owner is not None:
            try:
                owner.np_random = gen
            except Exception:
                pass


def capture_evolution(tournament, mutation, lineage) -> Dict[str, Any]:
    blob: Dict[str, Any] = {}
    if tournament is not None and getattr(tournament, "rng", None) is not None:
        blob["tournament_rng"] = capture_np_generator(tournament.rng)
    if mutation is not None:
        if getattr(mutation, "rng", None) is not None:
            blob["mutation_rng"] = capture_np_generator(mutation.rng)
        if getattr(mutation, "_key", None) is not None:
            blob["mutation_key"] = key_to_host(mutation._key)
    if lineage is not None:
        blob["lineage"] = capture_lineage(lineage)
    return blob


def restore_evolution(blob: Optional[Dict[str, Any]], tournament, mutation,
                      lineage) -> None:
    if not blob:
        return
    if tournament is not None and blob.get("tournament_rng") is not None:
        tournament.rng = restore_np_generator(blob["tournament_rng"])
    if mutation is not None:
        if blob.get("mutation_rng") is not None:
            mutation.rng = restore_np_generator(blob["mutation_rng"])
        if blob.get("mutation_key") is not None:
            mutation._key = key_from_host(blob["mutation_key"])
    if lineage is not None and blob.get("lineage") is not None:
        restore_lineage(lineage, blob["lineage"])


def capture_lineage(tracker) -> Dict[str, Any]:
    """Genealogy as pure data. ``_pending`` holds references INTO
    ``generations`` — captured as positions so restore can rebuild the
    aliasing (a pickled tracker would carry its unpicklable registry).
    ``generations`` is referenced, not copied: the facade pickles the blob
    in the same synchronous call, and pending entries live in the newest
    generations, so the reverse scan stays O(1) over a long run."""
    positions: Dict[int, Tuple[int, int]] = {}
    for idx, child in tracker._pending.items():
        for gi in range(len(tracker.generations) - 1, -1, -1):
            hit = next(
                (ci for ci, c in enumerate(tracker.generations[gi]["children"])
                 if c is child), None,
            )
            if hit is not None:
                positions[int(idx)] = (gi, hit)
                break
    return {
        "generation": tracker.generation,
        "generations": tracker.generations,
        "pending": positions,
    }


def restore_lineage(tracker, blob: Dict[str, Any]) -> None:
    tracker.generation = int(blob["generation"])
    tracker.generations = copy.deepcopy(blob["generations"])
    tracker._pending = {
        int(idx): tracker.generations[gi]["children"][ci]
        for idx, (gi, ci) in blob["pending"].items()
    }


def capture_buffers(**buffers) -> Dict[str, Any]:
    """``state_dict`` every named buffer that supports it (``None`` values and
    plain user buffers without ``state_dict`` are skipped). The buffers flush
    their own staging rings first."""
    out = {}
    for name, buf in buffers.items():
        if buf is None:
            continue
        sd = getattr(buf, "state_dict", None)
        if callable(sd):
            out[name] = sd()
        else:
            _registry().warn_once(
                f"resilience:buffer_not_capturable:{name}",
                f"buffer {name!r} ({type(buf).__name__}) has no state_dict — "
                "its contents will NOT survive a resume",
            )
    return out


def restore_buffers(blob: Optional[Dict[str, Any]], **buffers) -> None:
    if not blob:
        return
    for name, buf in buffers.items():
        if buf is None or name not in blob:
            continue
        lsd = getattr(buf, "load_state_dict", None)
        if callable(lsd):
            lsd(blob[name])


# --------------------------------------------------------------------------- #
# CheckpointManager
# --------------------------------------------------------------------------- #


class AsyncPytree:
    """Wrap a snapshot entry value to route it through the orbax helpers
    (``utils/checkpoint.py``) instead of pickling: sharded, async-capable
    saves where every host writes only its param shards — the right path for
    LLM-tier populations whose pytrees don't fit a single pickle. The orbax
    directory rides the same staged-tmp atomic commit as the pickled
    entries."""

    __slots__ = ("tree",)

    def __init__(self, tree: Any):
        self.tree = tree


class SnapshotInfo:
    """A committed snapshot directory + its parsed manifest."""

    __slots__ = ("path", "manifest")

    def __init__(self, path: Path, manifest: Dict[str, Any]):
        self.path = path
        self.manifest = manifest

    @property
    def step(self) -> int:
        return int(self.manifest.get("step", -1))

    @property
    def kind(self) -> str:
        return str(self.manifest.get("kind", "cadence"))

    @property
    def fitness(self) -> Optional[float]:
        f = self.manifest.get("fitness")
        return None if f is None else float(f)

    @property
    def member_fitness(self) -> Optional[List[Optional[float]]]:
        """Per-member fitness recorded at save time (manifest-level, so
        best-member restore and island top-k selection read it WITHOUT
        unpickling the population entry). ``None`` when the snapshot
        predates the field."""
        mf = self.manifest.get("member_fitness")
        if mf is None:
            return None
        return [None if f is None else float(f) for f in mf]

    @property
    def member_ids(self) -> Optional[List[int]]:
        """Stable member (slot-lineage) ids aligned with ``member_fitness``,
        for restoring a specific lost member from its snapshot row."""
        ids = self.manifest.get("member_ids")
        if ids is None:
            return None
        return [int(i) for i in ids]

    def best_member_index(self) -> Optional[int]:
        """Row index of the highest finite per-member fitness (None when the
        manifest carries no usable member fitness)."""
        mf = self.member_fitness
        if not mf:
            return None
        finite = [(f, i) for i, f in enumerate(mf)
                  if f is not None and np.isfinite(f)]
        if not finite:
            return None
        return max(finite)[1]

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"SnapshotInfo(step={self.step}, kind={self.kind!r}, path={self.path})"


class CheckpointManager:
    """Atomic versioned snapshots with retention and validated restore.

    Layout::

        <directory>/
          step_000000001000/           # committed snapshot
            population.pkl
            buffers.pkl
            ...
            manifest.json              # written LAST; per-entry sha256
          step_000000002000.tmp/       # crashed save — ignored, swept

    ``save()`` commits atomically; ``load()`` walks snapshots newest-first
    and returns the first whose every entry validates, so a torn or
    corrupted newest snapshot degrades to the previous complete one instead
    of crashing the resume.
    """

    def __init__(
        self,
        directory: Union[str, Path],
        keep_last: int = 3,
        keep_best: bool = True,
        registry=None,
    ):
        self.directory = Path(directory)
        self.keep_last = max(int(keep_last), 1)
        self.keep_best = bool(keep_best)
        self._registry = registry
        self.directory.mkdir(parents=True, exist_ok=True)
        remove_stale_tmp_dirs(self.directory)

    # -- registry plumbing ------------------------------------------------ #
    @property
    def registry(self):
        return self._registry if self._registry is not None else _registry()

    # -- write path ------------------------------------------------------- #
    def save(
        self,
        entries: Dict[str, Any],
        step: int,
        kind: str = "cadence",
        fitness: Optional[float] = None,
        extra_meta: Optional[Dict[str, Any]] = None,
        member_fitness: Optional[Any] = None,
        member_ids: Optional[Any] = None,
    ) -> Path:
        """Commit one snapshot atomically. ``entries`` maps entry name →
        picklable object; each is written to ``<name>.pkl`` with its sha256
        recorded in the manifest, which is written last. Wrap a value in
        :class:`AsyncPytree` to save it through the orbax helpers instead
        (sharded LLM-tier pytrees).

        ``member_fitness`` / ``member_ids`` record the population's
        per-member fitness at MANIFEST level (non-finite values stored as
        null) so best-member restore and island top-k selection never have
        to unpickle whole snapshots. When ``fitness`` is omitted it is
        derived as the best finite member fitness, keeping ``keep_best``
        retention consistent with the per-member field."""
        t0 = time.perf_counter()
        if member_fitness is not None:
            # element-wise, not np.asarray over the list: the input may be
            # exactly what SnapshotInfo.member_fitness returned, nulls and
            # all, and the round-trip must not crash on them
            cleaned = []
            for f in member_fitness:
                f = None if f is None else float(f)
                cleaned.append(f if f is not None and np.isfinite(f) else None)
            member_fitness = cleaned
            finite = [f for f in member_fitness if f is not None]
            if fitness is None and finite:
                fitness = max(finite)
        base = f"{_STEP_PREFIX}{int(step):012d}"
        # never overwrite a committed snapshot: a same-step resave (e.g. a
        # final snapshot right after a cadence one) commits under a suffixed
        # sibling name — the delete-old/publish-new race simply cannot
        # happen, and restore prefers the highest seq at equal step. The
        # seq continues from the MAX existing one, not the first free name:
        # retention frees earlier names, and reusing them would make the
        # (step, seq) order disagree with save order
        siblings = [
            d.name for d in self.directory.iterdir()
            if d.is_dir() and not d.name.endswith(TMP_DIR_SUFFIX)
            and (d.name == base or d.name.startswith(base + "_"))
        ]
        if siblings:
            seq = 1 + max(_name_seq(n) for n in siblings)
            final = self.directory / f"{base}_{seq:04d}"
        else:
            final = self.directory / base
        tmp = self.directory / (final.name + TMP_DIR_SUFFIX)
        if tmp.exists():
            shutil.rmtree(tmp)
        tmp.mkdir(parents=True)
        manifest_entries: Dict[str, Dict[str, Any]] = {}
        for name, obj in entries.items():
            if isinstance(obj, AsyncPytree):
                # orbax path: sharded multi-host writes; integrity is
                # orbax's own (checkpoint metadata), not a content hash
                from agilerl_tpu.utils.checkpoint import save_pytree

                fname = f"{name}.pytree"
                save_pytree(tmp / fname, obj.tree)
                manifest_entries[fname] = {"kind": "pytree"}
                continue
            fname = name if name.endswith(".pkl") else f"{name}.pkl"
            sha, nbytes = staged_pickle(tmp / fname, obj)
            manifest_entries[fname] = {"sha256": sha, "bytes": nbytes}
        manifest = {
            "format": SNAPSHOT_FORMAT,
            "step": int(step),
            "kind": str(kind),
            "fitness": None if fitness is None else float(fitness),
            "time": time.time(),
            "entries": manifest_entries,
        }
        if member_fitness is not None:
            manifest["member_fitness"] = member_fitness
        if member_ids is not None:
            manifest["member_ids"] = [int(i) for i in member_ids]
        if extra_meta:
            manifest.update(extra_meta)
        staged_write_bytes(
            tmp / MANIFEST, json.dumps(manifest, indent=2).encode()
        )
        commit_dir(tmp, final)
        self._retain()
        reg = self.registry
        reg.counter("resilience/snapshots_total").inc()
        reg.gauge("resilience/snapshot_time_s").set(time.perf_counter() - t0)
        return final

    # -- scan/validate ---------------------------------------------------- #
    def snapshots(self) -> List[SnapshotInfo]:
        """Committed snapshots with a readable manifest, ascending by step.
        Uncommitted ``*.tmp`` dirs and manifest-less dirs are invisible."""
        out: List[SnapshotInfo] = []
        if not self.directory.is_dir():
            return out
        for d in self.directory.iterdir():
            if not d.is_dir() or not d.name.startswith(_STEP_PREFIX):
                continue
            if d.name.endswith(TMP_DIR_SUFFIX):
                continue
            mf = d / MANIFEST
            try:
                manifest = json.loads(mf.read_text())
            except (OSError, ValueError):
                continue
            out.append(SnapshotInfo(d, manifest))
        out.sort(key=lambda s: (s.step, _name_seq(s.path.name)))
        return out

    def validate(self, info: SnapshotInfo) -> bool:
        """Every manifest entry exists with a matching content hash (pytree
        entries: a non-empty orbax directory — orbax carries its own
        checkpoint metadata)."""
        try:
            for fname, meta in info.manifest.get("entries", {}).items():
                if meta.get("kind") == "pytree":
                    d = info.path / fname
                    if not d.is_dir() or not any(d.iterdir()):
                        return False
                    continue
                data = (info.path / fname).read_bytes()
                if len(data) != int(meta.get("bytes", len(data))):
                    return False
                if content_hash(data) != meta["sha256"]:
                    return False
        except (OSError, KeyError, TypeError, ValueError):
            return False
        return True

    def latest(self, validate: bool = True) -> Optional[SnapshotInfo]:
        """Newest snapshot (optionally: newest snapshot that fully
        validates — the restore default)."""
        snaps = self.snapshots()
        for info in reversed(snaps):
            if not validate or self.validate(info):
                return info
            self.registry.warn_once(
                f"resilience:snapshot_corrupt:{info.path.name}",
                f"snapshot {info.path.name} failed validation — "
                "falling back to an older snapshot",
            )
            self.registry.counter("resilience/restore_fallbacks_total").inc()
        return None

    def best(self) -> Optional[SnapshotInfo]:
        """Highest-fitness committed snapshot (None when no snapshot carries
        a fitness)."""
        with_fit = [s for s in self.snapshots() if s.fitness is not None]
        if not with_fit:
            return None
        return max(with_fit, key=lambda s: (s.fitness, s.step))

    def load(self, info: Optional[SnapshotInfo] = None) -> Optional[
        Tuple[SnapshotInfo, Dict[str, Any]]
    ]:
        """Unpickle every entry of ``info`` (default: newest), hash-validated.
        Walks backwards past snapshots whose entries fail to load — restore
        always lands on the latest COMPLETE snapshot."""
        candidates = [info] if info is not None else list(reversed(self.snapshots()))
        for cand in candidates:
            try:
                entries = {}
                for fname, meta in cand.manifest.get("entries", {}).items():
                    if meta.get("kind") == "pytree":
                        from agilerl_tpu.utils.checkpoint import load_pytree

                        try:
                            obj = load_pytree(cand.path / fname)
                        except Exception as e:
                            raise CorruptSnapshotError(
                                f"pytree entry unreadable: {cand.path / fname}: {e}"
                            ) from e
                        entries[fname[: -len(".pytree")]] = obj
                        continue
                    obj = load_validated_pickle(
                        cand.path / fname, meta.get("sha256")
                    )
                    entries[fname[:-4] if fname.endswith(".pkl") else fname] = obj
                return cand, entries
            except CorruptSnapshotError as e:
                self.registry.warn_once(
                    f"resilience:snapshot_corrupt:{cand.path.name}",
                    f"snapshot {cand.path.name} unreadable ({e}) — "
                    "falling back to an older snapshot",
                )
                self.registry.counter("resilience/restore_fallbacks_total").inc()
        return None

    # -- retention -------------------------------------------------------- #
    def _retain(self) -> None:
        snaps = self.snapshots()
        keep = {s.path for s in snaps[-self.keep_last:]}
        if self.keep_best:
            best = self.best()
            if best is not None:
                keep.add(best.path)
        for s in snaps:
            if s.path not in keep:
                shutil.rmtree(s.path, ignore_errors=True)
