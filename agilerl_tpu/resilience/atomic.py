"""Crash-consistent file primitives — the commit protocol every snapshot
writer in the resilience subsystem goes through.

The protocol (write-ahead tmp + fsync + ``os.replace``) guarantees that a
reader never observes a half-written file or a half-written snapshot
directory: either the old committed state is visible or the new one is,
regardless of where a SIGKILL lands. Directory commits additionally fsync
the parent directory so the rename itself survives a power cut (POSIX
leaves the directory entry volatile otherwise).

Every durability-relevant operation also fires a **fault hook** (see
:mod:`agilerl_tpu.resilience.faults`): the fault-injection harness installs a
callable here and kills/corrupts the process at scheduled operation indices,
so crash consistency is exercised by tier-1 CPU tests instead of asserted.
"""

from __future__ import annotations

import hashlib
import io
import os
import pickle
from pathlib import Path
from typing import Any, Callable, Optional, Tuple, Union

#: suffix for uncommitted snapshot directories (never read by restore paths)
TMP_DIR_SUFFIX = ".tmp"
#: suffix for uncommitted single files
TMP_FILE_SUFFIX = ".part"


class CorruptSnapshotError(RuntimeError):
    """A snapshot entry failed validation (missing, truncated, or its
    content hash does not match the manifest)."""


# --------------------------------------------------------------------------- #
# fault hook — the seam the FaultInjector attaches to
# --------------------------------------------------------------------------- #

_fault_hook: Optional[Callable[[str, Path], None]] = None


def set_fault_hook(
    hook: Optional[Callable[[str, Path], None]]
) -> Optional[Callable[[str, Path], None]]:
    """Install (or clear, with None) the process-wide fault hook. Returns the
    previous hook so callers can restore it."""
    global _fault_hook
    prev = _fault_hook
    _fault_hook = hook
    return prev


def _fire(op: str, path: Union[str, Path]) -> None:
    """Ops fired, in order, during a snapshot commit:

    - ``write``:  about to write a file (payload not yet on disk)
    - ``wrote``:  the file is durably in place (post-replace, post-fsync)
    - ``commit``: about to atomically publish a snapshot directory
    """
    if _fault_hook is not None:
        _fault_hook(op, Path(path))


# --------------------------------------------------------------------------- #
# durability primitives
# --------------------------------------------------------------------------- #


def fsync_file(path: Union[str, Path]) -> None:
    fd = os.open(str(path), os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(path: Union[str, Path]) -> None:
    """fsync a directory so renames/creates inside it are durable. Silently
    skipped on platforms that refuse O_RDONLY on directories."""
    try:
        fd = os.open(str(path), os.O_RDONLY)
    except OSError:  # pragma: no cover - platform-specific
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover - platform-specific
        pass
    finally:
        os.close(fd)


def content_hash(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def atomic_write_bytes(path: Union[str, Path], data: bytes) -> str:
    """Write ``data`` to ``path`` atomically (tmp + fsync + ``os.replace``)
    and return its sha256 hex digest. A crash at any point leaves either the
    previous file or the new one — never a torn mix."""
    path = Path(path)
    _fire("write", path)
    tmp = path.with_name(path.name + TMP_FILE_SUFFIX)
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    fsync_dir(path.parent)
    _fire("wrote", path)
    return content_hash(data)


def atomic_pickle(path: Union[str, Path], obj: Any) -> Tuple[str, int]:
    """Atomically pickle ``obj`` to ``path``; returns (sha256, byte size)."""
    buf = io.BytesIO()
    pickle.dump(obj, buf, protocol=pickle.HIGHEST_PROTOCOL)
    data = buf.getvalue()
    return atomic_write_bytes(path, data), len(data)


def staged_write_bytes(path: Union[str, Path], data: bytes) -> str:
    """Plain write for a file inside a NOT-YET-COMMITTED staging directory
    (``*.tmp``): no reader can observe the directory until
    :func:`commit_dir` publishes it, and commit_dir fsyncs every file once
    before the rename — so the per-file tmp+fsync+replace dance of
    :func:`atomic_write_bytes` would only double the durability I/O on the
    snapshot hot path. Fires the same ``write``/``wrote`` fault hooks."""
    path = Path(path)
    _fire("write", path)
    with open(path, "wb") as fh:
        fh.write(data)
    _fire("wrote", path)
    return content_hash(data)


def staged_pickle(path: Union[str, Path], obj: Any) -> Tuple[str, int]:
    """Pickle ``obj`` into a staging directory (see :func:`staged_write_bytes`);
    returns (sha256, byte size)."""
    buf = io.BytesIO()
    pickle.dump(obj, buf, protocol=pickle.HIGHEST_PROTOCOL)
    data = buf.getvalue()
    return staged_write_bytes(path, data), len(data)


def read_validated(path: Union[str, Path], sha256: Optional[str] = None) -> bytes:
    """Read a file, raising :class:`CorruptSnapshotError` when it is missing
    or its content hash mismatches the manifest's record (torn/truncated/
    bit-rotted entries are detected here, never silently loaded)."""
    path = Path(path)
    try:
        data = path.read_bytes()
    except OSError as e:
        raise CorruptSnapshotError(f"snapshot entry unreadable: {path}: {e}") from e
    if sha256 is not None and content_hash(data) != sha256:
        raise CorruptSnapshotError(
            f"snapshot entry corrupt (hash mismatch): {path}"
        )
    return data


def load_validated_pickle(path: Union[str, Path], sha256: Optional[str] = None) -> Any:
    data = read_validated(path, sha256)
    try:
        return pickle.loads(data)
    except Exception as e:  # torn pickles raise a zoo of error types
        raise CorruptSnapshotError(f"snapshot entry unpicklable: {path}: {e}") from e


def commit_dir(tmp_dir: Union[str, Path], final_dir: Union[str, Path]) -> None:
    """Atomically publish a fully-written staging directory: fsync every file
    inside, then ``os.replace`` the directory into its final name and fsync
    the parent. Readers scanning for committed snapshots never see
    ``*.tmp`` names, so a kill before the replace leaves only ignorable
    garbage, and a kill after leaves a complete snapshot.

    Prefer committing to a name that does not exist (``CheckpointManager``
    guarantees this by suffixing same-step resaves): directories cannot be
    atomically swapped portably, so overwriting an existing committed
    directory first moves it aside to a ``*.tmp`` name — a kill in the
    gap between the two renames loses THIS name (restore falls back to an
    older snapshot), which is the narrowest window POSIX rename allows."""
    tmp_dir, final_dir = Path(tmp_dir), Path(final_dir)
    for f in tmp_dir.rglob("*"):
        if f.is_file():
            fsync_file(f)
    fsync_dir(tmp_dir)
    _fire("commit", final_dir)
    old: Optional[Path] = None
    if final_dir.exists():
        old = final_dir.with_name(final_dir.name + ".old" + TMP_DIR_SUFFIX)
        if old.exists():
            import shutil

            shutil.rmtree(old)
        os.replace(final_dir, old)
    os.replace(tmp_dir, final_dir)
    fsync_dir(final_dir.parent)
    if old is not None:
        import shutil

        shutil.rmtree(old, ignore_errors=True)


def remove_stale_tmp_dirs(root: Union[str, Path]) -> int:
    """Delete leftover ``*.tmp`` staging directories from crashed saves.
    Returns how many were removed. Safe to call at manager startup: committed
    snapshots are never named ``*.tmp``."""
    root = Path(root)
    if not root.is_dir():
        return 0
    import shutil

    removed = 0
    for d in root.iterdir():
        if d.is_dir() and d.name.endswith(TMP_DIR_SUFFIX):
            shutil.rmtree(d, ignore_errors=True)
            removed += 1
    return removed
