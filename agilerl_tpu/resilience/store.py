"""Generic commit-dir + manifest entry store — ONE home for the atomic
publish / sha-validate / skip-torn / last-K-GC discipline.

Three subsystems grew hand-rolled copies of the same protocol: island
migration exports (``parallel/elastic.py``, PR 7), prefill->decode KV
transfers (``llm/fleet.KVTransferStore``, PR 9), and the online-flywheel
weight/trajectory stores (``llm/flywheel.py``). The protocol is always:

1. **Publish** — stage the pickled payload plus a ``manifest.json`` that
   records its sha256 and byte size into a ``*.tmp`` directory, then
   :func:`~agilerl_tpu.resilience.atomic.commit_dir` publishes the
   directory atomically. A reader either sees a complete, hash-valid entry
   or nothing.
2. **Read** — the manifest is parsed first (readable without unpickling
   the payload), then the payload is hash-validated through
   :func:`~agilerl_tpu.resilience.atomic.load_validated_pickle`. Torn,
   truncated, or corrupt entries raise
   :class:`~agilerl_tpu.resilience.atomic.CorruptSnapshotError` — they are
   NEVER loaded; callers skip (and usually count + warn) instead.
3. **GC** — entries are ordered by the integer suffix in their name, and
   all but the newest ``keep_last`` are deleted.

The module functions are the composable layer (elastic keeps its bespoke
import walk but publishes through :func:`publish_entry`); the
:class:`CommitDirStore` class adds the metrics-wired skip-torn read that
the fleet/flywheel stores share verbatim.
"""

from __future__ import annotations

import json
import os
import re
import shutil
from pathlib import Path
from typing import Any, Dict, List, Optional, Union

from agilerl_tpu.resilience.atomic import (
    TMP_DIR_SUFFIX,
    CorruptSnapshotError,
    commit_dir,
    load_validated_pickle,
    staged_pickle,
    staged_write_bytes,
)

_TRAILING_INT = re.compile(r"(\d+)(?:\D*)$")


def entry_seq(name: str) -> Optional[int]:
    """The LAST integer run in an entry name (``epoch_00000007`` -> 7,
    ``batch_003_00000012`` -> 12) — name layouts must put the ordering
    integer last. Returns None when the name carries no digits."""
    m = _TRAILING_INT.search(name)
    return int(m.group(1)) if m else None


def publish_entry(
    directory: Union[str, Path],
    name: str,
    payload: Any,
    *,
    payload_name: str = "payload.pkl",
    sha_key: str = "payload_sha",
    manifest_extra: Optional[Dict[str, Any]] = None,
) -> Path:
    """Atomically publish one named entry under ``directory`` and return the
    committed path. The manifest records the payload pickle's sha256 (under
    ``sha_key``) and byte size plus ``manifest_extra`` verbatim, so readers
    can inspect provenance without unpickling."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    final = directory / name
    # pid-scoped staging: two processes racing the SAME entry name must not
    # rmtree each other's in-flight staging dir (the PR 12 executable-store
    # lesson, applied store-wide). Both still commit to `final` — commit_dir's
    # rename makes the last writer win, wholesale, never interleaved. The
    # name keeps the ``.tmp`` suffix so committed_entries() and
    # remove_stale_tmp_dirs() continue to classify it as staging.
    tmp = directory / f"{name}.{os.getpid()}{TMP_DIR_SUFFIX}"
    if tmp.exists():
        shutil.rmtree(tmp)
    tmp.mkdir(parents=True)
    sha, size = staged_pickle(tmp / payload_name, payload)
    manifest: Dict[str, Any] = {sha_key: sha, "bytes": size}
    manifest.update(manifest_extra or {})
    staged_write_bytes(
        tmp / "manifest.json", json.dumps(manifest, indent=2).encode()
    )
    commit_dir(tmp, final)
    return final


def read_manifest(path: Union[str, Path]) -> Dict[str, Any]:
    """Parse an entry's manifest; raises :class:`CorruptSnapshotError` when
    it is missing or unparsable (a crash can't produce this under the
    commit protocol — only external corruption can)."""
    path = Path(path)
    try:
        manifest = json.loads((path / "manifest.json").read_text())
    except (OSError, ValueError) as e:
        raise CorruptSnapshotError(
            f"entry manifest unreadable: {path}: {e}"
        ) from e
    if not isinstance(manifest, dict):
        raise CorruptSnapshotError(f"entry manifest malformed: {path}")
    return manifest


def read_entry(
    path: Union[str, Path],
    *,
    payload_name: str = "payload.pkl",
    sha_key: str = "payload_sha",
) -> Any:
    """Hash-validated payload read. Raises :class:`CorruptSnapshotError`
    for anything less than a complete, manifest-matching payload — torn
    entries are never partially loaded."""
    path = Path(path)
    manifest = read_manifest(path)
    sha = manifest.get(sha_key)
    if not isinstance(sha, str):
        raise CorruptSnapshotError(
            f"entry manifest at {path} carries no {sha_key!r} hash"
        )
    return load_validated_pickle(path / payload_name, sha)


def committed_entries(
    directory: Union[str, Path], prefix: str = ""
) -> List[Path]:
    """Committed (non-``*.tmp``) entry directories under ``directory`` whose
    name starts with ``prefix``, ordered oldest-first by the integer suffix
    in the name (ties / no-integer names fall back to the name itself —
    zero-padded layouts order identically either way)."""
    directory = Path(directory)
    if not directory.is_dir():
        return []
    entries = [
        d for d in directory.iterdir()
        if d.is_dir() and d.name.startswith(prefix)
        and not d.name.endswith(TMP_DIR_SUFFIX)
    ]
    return sorted(
        entries, key=lambda d: (entry_seq(d.name) is None,
                                entry_seq(d.name) or 0, d.name)
    )


def gc_entries(
    directory: Union[str, Path], prefix: str = "",
    keep_last: Optional[int] = None,
) -> int:
    """Delete all but the newest ``keep_last`` committed entries (numeric
    order — lexicographic would misrank unpadded sequence numbers). Returns
    how many were removed. ``keep_last=None`` keeps everything."""
    if keep_last is None:
        return 0
    # rank ONLY parseable-seq entries: a digitless stray dir sorts NEWEST
    # in committed_entries (reader walks try it last), and counting it in
    # the keep window would displace a real entry; it also isn't ours to
    # delete
    entries = [e for e in committed_entries(directory, prefix)
               if entry_seq(e.name) is not None]
    removed = 0
    for old in entries[: max(len(entries) - int(keep_last), 0)]:
        shutil.rmtree(old, ignore_errors=True)
        removed += 1
    return removed


class CommitDirStore:
    """The metrics-wired store the fleet/flywheel tiers compose: atomic
    :meth:`publish`, skip-torn :meth:`load` (counter + warn-once, returns
    None — the caller recomputes or falls back, NEVER loads a torn entry),
    :meth:`entries`, :meth:`consume`, and last-K GC on publish."""

    def __init__(
        self,
        directory: Union[str, Path],
        *,
        payload_name: str = "payload.pkl",
        sha_key: str = "payload_sha",
        prefix: str = "",
        keep_last: Optional[int] = None,
        torn_counter: str = "resilience/torn_entries_total",
        torn_help: str = "store entries skipped as torn/corrupt",
        warn_prefix: str = "torn-entry",
        metrics=None,
        tracer=None,
    ):
        from agilerl_tpu import observability

        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.payload_name = payload_name
        self.sha_key = sha_key
        self.prefix = prefix
        self.keep_last = keep_last
        self.torn_counter = torn_counter
        self.torn_help = torn_help
        self.warn_prefix = warn_prefix
        self.metrics = (metrics if metrics is not None
                        else observability.get_registry())
        #: like metrics: an explicit consumer tracer wins (multiple runs in
        #: one process each keep their spans in their own sink); None reads
        #: the process default lazily
        self._tracer = tracer

    @property
    def tracer(self):
        if self._tracer is not None:
            return self._tracer
        from agilerl_tpu.observability import get_tracer

        return get_tracer()

    def publish(self, name: str, payload: Any,
                manifest_extra: Optional[Dict[str, Any]] = None) -> Path:
        path = publish_entry(
            self.directory, name, payload,
            payload_name=self.payload_name, sha_key=self.sha_key,
            manifest_extra=manifest_extra,
        )
        gc_entries(self.directory, self.prefix, self.keep_last)
        return path

    def load(self, path: Union[str, Path]) -> Optional[Any]:
        """Hash-validated read; returns None (after counting + warning) for
        a torn, truncated, or corrupt entry — the skip-torn contract."""
        path = Path(path)
        try:
            return read_entry(path, payload_name=self.payload_name,
                              sha_key=self.sha_key)
        except (OSError, ValueError, KeyError, CorruptSnapshotError) as e:
            if not path.exists():
                # concurrently GC'd between listing and load (another
                # process's keep-last pass) — a vanished entry is routine,
                # not corruption; the torn counter must stay an integrity
                # signal
                return None
            self.metrics.counter(self.torn_counter, help=self.torn_help).inc()
            self.metrics.warn_once(
                f"{self.warn_prefix}-{path.name}",
                f"skipping torn store entry {path.name}: {e}")
            tracer = self.tracer
            if tracer.enabled:
                # torn entry: anomaly — always sampled, error status, one
                # span per skip across EVERY store consumer (KV transfers,
                # weight/trajectory stores, telemetry snapshots)
                tracer.start_span(
                    "store.torn_entry", force=True,
                    attributes={"entry": path.name,
                                "counter": self.torn_counter},
                ).set_error(str(e)).end()
            return None

    def entries(self) -> List[Path]:
        return committed_entries(self.directory, self.prefix)

    def consume(self, path: Union[str, Path]) -> None:
        """Delete a read (or torn) entry directory."""
        shutil.rmtree(Path(path), ignore_errors=True)
