"""Resilience subsystem: crash-consistent run snapshots, preemption-aware
checkpointing, retry policies for flaky host edges, and a deterministic
fault-injection harness (see docs/resilience.md)."""

from agilerl_tpu.resilience.atomic import (
    CorruptSnapshotError,
    atomic_pickle,
    atomic_write_bytes,
    commit_dir,
    content_hash,
    set_fault_hook,
    staged_pickle,
    staged_write_bytes,
)
from agilerl_tpu.resilience.facade import Resilience, max_fitness
from agilerl_tpu.resilience.faults import (
    FaultInjector,
    InjectedCrash,
    ScheduledFailureEnv,
)
from agilerl_tpu.resilience.membership import (
    HeartbeatStore,
    MembershipChange,
    MembershipEvent,
    pid_alive,
)
from agilerl_tpu.resilience.preemption import PreemptionGuard
from agilerl_tpu.resilience.proc import (
    ProcessSupervisor,
    RoleContext,
    RoleSpec,
    SupervisedProcess,
    read_statuses,
    resolve_target,
    run_role,
)
from agilerl_tpu.resilience.retry import (
    DEFAULT_ENV_POLICY,
    RetryingEnv,
    RetryPolicy,
    call_with_retries,
    with_retries,
)
from agilerl_tpu.resilience.store import (
    CommitDirStore,
    committed_entries,
    gc_entries,
    publish_entry,
    read_entry,
    read_manifest,
)
from agilerl_tpu.resilience.snapshot import (
    AsyncPytree,
    CheckpointManager,
    SnapshotInfo,
    capture_agent,
    capture_env_rng,
    capture_host_rng,
    restore_agent,
    restore_env_rng,
    restore_host_rng,
)

__all__ = [
    "Resilience", "max_fitness",
    "AsyncPytree", "CheckpointManager", "SnapshotInfo",
    "PreemptionGuard",
    "RetryPolicy", "RetryingEnv", "call_with_retries", "with_retries",
    "DEFAULT_ENV_POLICY",
    "FaultInjector", "InjectedCrash", "ScheduledFailureEnv",
    "HeartbeatStore", "MembershipChange", "MembershipEvent", "pid_alive",
    "ProcessSupervisor", "RoleContext", "RoleSpec", "SupervisedProcess",
    "read_statuses", "resolve_target", "run_role",
    "CorruptSnapshotError", "set_fault_hook",
    "atomic_write_bytes", "atomic_pickle", "commit_dir", "content_hash",
    "staged_write_bytes", "staged_pickle",
    "CommitDirStore", "publish_entry", "read_entry", "read_manifest",
    "committed_entries", "gc_entries",
    "capture_agent", "restore_agent",
    "capture_host_rng", "restore_host_rng",
    "capture_env_rng", "restore_env_rng",
]
