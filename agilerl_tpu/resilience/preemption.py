"""Preemption-aware signal handling.

TPU pods (and every spot/preemptible tier) deliver SIGTERM with a grace
window before the hard kill. :class:`PreemptionGuard` converts that signal
into a cooperative request: the handler ONLY flips a flag — it may have
interrupted a frame holding the telemetry sink's (non-reentrant) lock, so
even the counter bump and JSONL flush are deferred to the next main-thread
``requested`` read at a step boundary. Signal handlers must never pickle
pytrees, touch JAX, or take locks.

A second SIGINT still raises ``KeyboardInterrupt`` so an interactive ^C ^C
retains its "no really, stop NOW" meaning.

Guards do NOT assume they own the process-wide handlers: when a supervised
child (the pod launcher's role harness) installs an outer guard and a
training loop later installs its own, the inner guard's handler **chains**
to the previously-installed callable handler after flag-flipping. Both
guards observe the signal, so a launcher-forwarded SIGTERM plus the
process-group delivery of the same signal (double delivery) latches both
flags and stays on the graceful path — signal latching is idempotent,
mirroring the "one ^C after SIGTERM stays graceful" rule.
"""

from __future__ import annotations

import signal
import threading
from typing import Iterable, Optional


class PreemptionGuard:
    """Install SIGTERM/SIGINT handlers that request a final snapshot.

    Usage::

        guard = PreemptionGuard()
        guard.install()            # or: with PreemptionGuard() as guard:
        ...
        if guard.requested:        # checked at step boundaries
            snapshot_and_exit()

    ``request()`` triggers the same path programmatically (tests, external
    preemption notices polled from a metadata server).
    """

    def __init__(
        self,
        signals: Iterable[int] = (signal.SIGTERM, signal.SIGINT),
        registry=None,
        telemetry=None,
    ):
        self.signals = tuple(signals)
        self._registry = registry
        self.telemetry = telemetry
        self._requested = False
        self._installed = False
        self._prev_handlers: dict = {}
        self._pending_record: Optional[int] = None
        self._recorded = False
        self._sigint_seen = False

    # -- state ------------------------------------------------------------ #
    @property
    def requested(self) -> bool:
        """True once a preemption was requested. Reading this OUTSIDE signal
        context (the loops' step-boundary checks) performs the deferred
        counter/emit/sink-flush — the handler itself must never touch the
        sink's non-reentrant lock, which the interrupted frame may hold."""
        if self._pending_record is not None or (
            self._requested and not self._recorded
        ):
            signum, self._pending_record = self._pending_record, None
            self._record(signum)
        return self._requested

    def request(self, signum: Optional[int] = None) -> None:
        """Flag a preemption (the manual/test entry point — records the
        telemetry immediately; the signal handler defers it instead). Safe
        to call from any thread."""
        first = not self._requested
        self._requested = True
        if first:
            self._record(signum)

    def reset(self) -> None:
        """Clear a latched request (a reused Resilience object attaching to
        a fresh run must not replay the previous run's preemption)."""
        self._requested = False
        self._recorded = False
        self._pending_record = None
        self._sigint_seen = False

    def _record(self, signum: Optional[int]) -> None:
        if self._recorded:
            return
        self._recorded = True
        reg = self._registry
        if reg is None:
            from agilerl_tpu.observability import get_registry

            reg = get_registry()
        reg.counter("resilience/preemptions_total").inc()
        reg.emit("preemption", signum=signum)
        self._flush_telemetry()

    def _flush_telemetry(self) -> None:
        """Flush the run's JSONL sink so the event stream is durable even if
        the grace window expires before the final snapshot commits. The
        sink's ``_resume_seq`` append-resume means the resumed run continues
        one seq-monotone stream."""
        telem = self.telemetry
        sink = None
        if telem is not None:
            sink = getattr(getattr(telem, "registry", None), "sink", None)
        if sink is None and self._registry is not None:
            sink = getattr(self._registry, "sink", None)
        flush = getattr(sink, "flush", None)
        if callable(flush):
            try:
                flush()
            except Exception:
                pass

    # -- signal plumbing --------------------------------------------------- #
    def _handler(self, signum, frame) -> None:
        # ONLY flag-flips here: the handler may have interrupted a frame
        # holding the JSONL sink's lock, so emit/flush must wait for the
        # next main-thread `requested` read (async-signal-safe discipline)
        # escalation needs a PRIOR ^C specifically: a SIGTERM (pod
        # preemption notice) followed by one ^C must still take the
        # graceful final-snapshot path, not die mid-step
        escalate = self._sigint_seen and signum == signal.SIGINT
        if signum == signal.SIGINT:
            self._sigint_seen = True
        self._requested = True
        if self._pending_record is None and not self._recorded:
            self._pending_record = signum if signum is not None else -1
        if escalate:
            # second ^C: the user means it — don't trap them in a slow
            # final-snapshot path
            raise KeyboardInterrupt
        # chain to whoever held this signal before us: a supervised child's
        # harness guard must still see the signal when an inner loop guard
        # installed over it. Only real callables chain — SIG_DFL/SIG_IGN are
        # sentinels, and the interpreter's default_int_handler would raise
        # KeyboardInterrupt mid-step, exactly what the graceful path avoids.
        prev = self._prev_handlers.get(signum)
        if callable(prev) and prev not in (
            signal.default_int_handler, self._handler
        ):
            prev(signum, frame)

    def install(self) -> "PreemptionGuard":
        """Install handlers (main thread only — a no-op elsewhere, where
        ``request()`` remains the entry point)."""
        if self._installed:
            return self
        if threading.current_thread() is not threading.main_thread():
            return self
        for sig in self.signals:
            try:
                self._prev_handlers[sig] = signal.signal(sig, self._handler)
            except (ValueError, OSError):  # pragma: no cover - exotic hosts
                pass
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for sig, prev in self._prev_handlers.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):  # pragma: no cover
                pass
        self._prev_handlers = {}
        self._installed = False

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()
