"""Process supervision for the multi-process pod launcher.

Every distributed subsystem since PR 9 — serving fleet, GRPO flywheel,
elastic PBT, telemetry plane, executable store — already exchanges ALL
state through commit-dir stores on a shared filesystem root. This module
supplies the missing half of the Podracer/Sebulba deployment story: the
machinery to run each pod as a **real OS process** and supervise it.

Three layers:

- **Role harness** (``python -m agilerl_tpu.resilience.proc <spec.json>``):
  the child-side driver. It installs a :class:`~agilerl_tpu.resilience
  .preemption.PreemptionGuard` FIRST (so even a SIGTERM during JAX import
  drains cleanly), beats a :class:`~agilerl_tpu.resilience.membership
  .HeartbeatStore` lease tagged with the role, resolves the spec's
  ``module:function`` entry point to build the role object, then runs the
  poll-cadence tick loop. Exit is always through a final telemetry flush +
  an atomic status file: ``done`` (tick returned complete), ``preempted``
  (guard latched — final drain ran), or ``crashed`` (exception, traceback
  recorded). Exit codes mirror the states so the supervisor never needs to
  parse a status file to decide on a restart.

- **:class:`SupervisedProcess`**: one spawned role. Children run in their
  OWN session (``start_new_session=True``) so the launcher can signal the
  child's whole process group without ever signalling itself; termination
  is deliberately **double-delivered** (group signal + direct signal) —
  the PreemptionGuard latch is idempotent, and double delivery is exactly
  what a real pod sees when an external preemption notice races the
  launcher's forward.

- **:class:`ProcessSupervisor`**: the fleet of children over one
  filesystem root. ``poll()`` reaps exits, restarts crashed roles with a
  bumped incarnation (bounded by ``max_restarts``), and
  ``shutdown()`` drains every child through SIGTERM within a grace window
  before escalating to SIGKILL — then verifies nothing is left running
  (the no-orphans contract).

Nothing here touches pod payloads: weights, trajectories, KV pages,
telemetry, and executables keep flowing through the existing stores. The
supervisor only moves **signals, liveness, and exit status**.
"""

from __future__ import annotations

import dataclasses
import importlib
import json
import os
import signal
import subprocess
import sys
import time
import traceback
from pathlib import Path
from typing import Any, Dict, List, Optional, Union

from agilerl_tpu.resilience.atomic import atomic_write_bytes
from agilerl_tpu.resilience.membership import HeartbeatStore, pid_alive
from agilerl_tpu.resilience.preemption import PreemptionGuard

#: harness exit codes — the supervisor's restart policy keys off these
EXIT_DONE = 0        #: role tick loop reported completion
EXIT_CRASH = 1       #: unhandled exception (restartable)
EXIT_PREEMPTED = 3   #: guard latched; drained gracefully (NOT restartable)
EXIT_ESCALATED = 130  #: double ^C — immediate stop, no drain

#: root-relative layout the launcher and every role agree on
SPECS_DIR = "specs"
STATUS_DIR = "status"
LOGS_DIR = "logs"
MEMBERSHIP_DIR = "membership"
TELEMETRY_DIR = "telemetry"


@dataclasses.dataclass
class RoleSpec:
    """Everything a child process needs to run one role, JSON-round-trip
    (the spec file IS the process's argv). ``target`` is a
    ``module:function`` entry point called with the :class:`RoleContext`;
    it returns either an object with ``tick()`` (optional ``drain()``) or
    a bare zero-arg tick callable. ``kwargs`` must be JSON-able — object
    graphs are rebuilt child-side from entry points, never pickled across
    the exec boundary."""

    name: str
    target: str
    root: str
    member_id: int
    kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    replica: int = 0
    incarnation: int = 0
    lease_timeout: float = 5.0
    beat_interval: Optional[float] = None  # default: lease_timeout / 4
    poll_interval: float = 0.0
    env: Dict[str, str] = dataclasses.field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)

    @classmethod
    def from_json(cls, text: str) -> "RoleSpec":
        data = json.loads(text)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in data.items() if k in known})


class RoleContext:
    """The harness-side plumbing handed to a role's entry point: the spec,
    the shared root, the lease store (already beating), the preemption
    guard, and the process registry. Roles read ``should_stop`` at their
    own step boundaries when one tick spans multiple store interactions."""

    def __init__(self, spec: RoleSpec, root: Path,
                 heartbeat: HeartbeatStore, guard: PreemptionGuard,
                 metrics) -> None:
        self.spec = spec
        self.root = root
        self.heartbeat = heartbeat
        self.guard = guard
        self.metrics = metrics

    @property
    def should_stop(self) -> bool:
        return self.guard.requested


def resolve_target(target: str):
    """``module:function`` -> the callable (no eval, no pickling)."""
    mod, sep, fn = target.partition(":")
    if not sep or not mod or not fn:
        raise ValueError(
            f"role target must be 'module:function', got {target!r}")
    return getattr(importlib.import_module(mod), fn)


def _status_path(root: Path, name: str) -> Path:
    return root / STATUS_DIR / f"{name}.json"


def _write_status(root: Path, spec: RoleSpec, state: str,
                  ticks: int = 0, error: Optional[str] = None) -> None:
    payload = {
        "role": spec.name,
        "pid": os.getpid(),
        "incarnation": int(spec.incarnation),
        "state": state,
        "ticks": int(ticks),
        "time": time.time(),
    }
    if error:
        payload["error"] = error
    atomic_write_bytes(_status_path(root, spec.name),
                       json.dumps(payload, indent=2).encode())


def read_statuses(root: Union[str, Path]) -> Dict[str, Dict[str, Any]]:
    """All readable role status files under ``root`` (atomic writes mean
    an unreadable one is external damage, not a crash artifact)."""
    out: Dict[str, Dict[str, Any]] = {}
    status_dir = Path(root) / STATUS_DIR
    if not status_dir.is_dir():
        return out
    for p in sorted(status_dir.glob("*.json")):
        try:
            out[p.stem] = json.loads(p.read_text())
        except (OSError, ValueError):
            continue
    return out


def run_role(spec_path: Union[str, Path]) -> int:
    """Child-side harness: guard -> lease -> build role -> tick loop ->
    drain -> status. Returns the process exit code (see ``EXIT_*``)."""
    spec = RoleSpec.from_json(Path(spec_path).read_text())
    root = Path(spec.root)

    # the guard comes FIRST: a SIGTERM that lands during the (seconds-long)
    # package/JAX import must latch, not kill us mid-initialisation. The
    # harness owns the outer handlers; any loop-level guard a role installs
    # later chains back to these (preemption.py's supervised-children fix).
    guard = PreemptionGuard().install()

    from agilerl_tpu import observability

    reg = observability.get_registry()
    sink_path = root / LOGS_DIR / f"{spec.name}.events.jsonl"
    sink_path.parent.mkdir(parents=True, exist_ok=True)
    reg.attach_sink(observability.JsonlSink(str(sink_path)))
    guard._registry = reg  # deferred preemption record lands in OUR sink

    heartbeat = HeartbeatStore(root / MEMBERSHIP_DIR,
                               lease_timeout=spec.lease_timeout,
                               registry=reg)
    meta = {"role": spec.name, "replica": int(spec.replica)}
    heartbeat.beat(spec.member_id, spec.incarnation, meta=meta)
    _write_status(root, spec, "running")

    publisher = observability.TelemetryPublisher(
        root / TELEMETRY_DIR, spec.name, reg,
        interval_s=max(spec.lease_timeout / 2.0, 0.25), metrics=reg)

    beat_interval = (spec.beat_interval if spec.beat_interval is not None
                     else spec.lease_timeout / 4.0)
    ctx = RoleContext(spec, root, heartbeat, guard, reg)
    ticks = 0
    state, code, error = "done", EXIT_DONE, None
    try:
        role = resolve_target(spec.target)(ctx)
        tick = role if callable(role) and not hasattr(role, "tick") \
            else role.tick
        drain = getattr(role, "drain", None)
        last_beat = time.monotonic()
        while True:
            if guard.requested:
                state, code = "preempted", EXIT_PREEMPTED
                break
            done = tick()
            ticks += 1
            now = time.monotonic()
            if now - last_beat >= beat_interval:
                heartbeat.beat(spec.member_id, spec.incarnation, meta=meta)
                last_beat = now
            publisher.publish()  # self-throttled by interval_s
            if done:
                break
            if spec.poll_interval > 0:
                time.sleep(spec.poll_interval)
        # graceful paths drain: the role's final snapshot/flush hook runs
        # for completion AND preemption (the guard's grace window)
        if callable(drain):
            drain()
    except KeyboardInterrupt:
        # double ^C escalation: the user means NOW — no drain
        state, code, error = "escalated", EXIT_ESCALATED, "KeyboardInterrupt"
    except Exception:
        state, code = "crashed", EXIT_CRASH
        error = traceback.format_exc()
    finally:
        try:
            publisher.publish(force=True)
        except Exception:
            pass
        if state in ("done", "preempted"):
            # graceful exits tombstone the lease so observers drop us
            # immediately; a crash leaves the stale lease for the pid
            # probe / lease timeout to surface — truthful failure telemetry
            heartbeat.mark_dead(spec.member_id)
        _write_status(root, spec, state, ticks=ticks, error=error)
        flush = getattr(getattr(reg, "sink", None), "flush", None)
        if callable(flush):
            try:
                flush()
            except Exception:
                pass
    return code


#: child argv — an import (not ``-m``) so runpy never executes a second
#: __main__ copy of this module inside the child
_CHILD_CMD = ("import sys; from agilerl_tpu.resilience.proc import "
              "run_role; sys.exit(run_role(sys.argv[1]))")


class SupervisedProcess:
    """One spawned role: the Popen handle plus the signal plumbing.

    The child gets its OWN session/process group, so group-wide signals
    from the supervisor can never loop back into the launcher, and any
    grandchildren the role spawns die with it on escalation."""

    def __init__(self, spec: RoleSpec, popen: subprocess.Popen,
                 spec_path: Path, log_path: Path) -> None:
        self.spec = spec
        self.popen = popen
        self.spec_path = spec_path
        self.log_path = log_path

    @classmethod
    def spawn(cls, spec: RoleSpec,
              extra_env: Optional[Dict[str, str]] = None
              ) -> "SupervisedProcess":
        root = Path(spec.root)
        for sub in (SPECS_DIR, STATUS_DIR, LOGS_DIR, MEMBERSHIP_DIR,
                    TELEMETRY_DIR):
            (root / sub).mkdir(parents=True, exist_ok=True)
        spec_path = root / SPECS_DIR / \
            f"{spec.name}.{int(spec.incarnation):03d}.json"
        atomic_write_bytes(spec_path, spec.to_json().encode())
        log_path = root / LOGS_DIR / f"{spec.name}.log"
        env = dict(os.environ)
        env.update(spec.env or {})
        env.update(extra_env or {})
        # append-mode log: restarts of the same role continue one file, and
        # a torn tail line on SIGKILL is harmless
        log = open(log_path, "ab")
        try:
            popen = subprocess.Popen(
                [sys.executable, "-u", "-c", _CHILD_CMD, str(spec_path)],
                stdout=log, stderr=subprocess.STDOUT, env=env,
                start_new_session=True)
        finally:
            log.close()  # the child holds its own descriptor now
        return cls(spec, popen, spec_path, log_path)

    @property
    def pid(self) -> int:
        return self.popen.pid

    @property
    def alive(self) -> bool:
        return self.popen.poll() is None

    def poll(self) -> Optional[int]:
        return self.popen.poll()

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        try:
            return self.popen.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return None

    def _signal(self, signum: int) -> None:
        """Double delivery ON PURPOSE: the group signal covers any
        grandchildren, the direct signal covers a child that moved itself
        out of the group. The guard's latch is idempotent, and real pods
        see exactly this race (external notice + launcher forward)."""
        try:
            os.killpg(os.getpgid(self.pid), signum)
        except (ProcessLookupError, PermissionError, OSError):
            pass
        try:
            os.kill(self.pid, signum)
        except (ProcessLookupError, PermissionError, OSError):
            pass

    def terminate(self) -> None:
        self._signal(signal.SIGTERM)

    def kill(self) -> None:
        self._signal(signal.SIGKILL)


class ProcessSupervisor:
    """The launcher's fleet of supervised role processes over one root.

    ``poll()`` is the supervision step: reap exits, classify them, respawn
    crashes with a bumped incarnation (so membership reports the rejoin)
    up to ``max_restarts`` per role. ``shutdown()`` is the graceful drain:
    SIGTERM everyone, give the grace window, SIGKILL stragglers, verify no
    orphans."""

    def __init__(self, root: Union[str, Path], lease_timeout: float = 5.0,
                 grace_s: float = 10.0, max_restarts: int = 2,
                 registry=None, probe_pids: bool = True) -> None:
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.lease_timeout = float(lease_timeout)
        self.grace_s = float(grace_s)
        self.max_restarts = int(max_restarts)
        self._registry_override = registry
        self.heartbeat = HeartbeatStore(
            self.root / MEMBERSHIP_DIR, lease_timeout=lease_timeout,
            registry=registry, probe_pids=probe_pids)
        self.procs: Dict[str, SupervisedProcess] = {}
        self.exits: Dict[str, int] = {}
        self.restarts: Dict[str, int] = {}
        self._shutting_down = False

    @property
    def metrics(self):
        if self._registry_override is not None:
            return self._registry_override
        from agilerl_tpu.observability import get_registry

        return get_registry()

    # -- lifecycle --------------------------------------------------------- #
    def spawn(self, spec: RoleSpec) -> SupervisedProcess:
        spec = dataclasses.replace(spec, root=str(self.root),
                                   lease_timeout=self.lease_timeout)
        proc = SupervisedProcess.spawn(spec)
        self.procs[spec.name] = proc
        self.exits.pop(spec.name, None)
        self.metrics.counter(
            "resilience/proc_spawns_total",
            help="supervised role processes spawned").inc()
        self.metrics.emit("proc_spawn", role=spec.name, pid=proc.pid,
                          incarnation=int(spec.incarnation))
        return proc

    def poll(self) -> List[Dict[str, Any]]:
        """One supervision step. Returns the exit events observed this
        call (``role``, ``code``, ``action``: done | drained | restarted |
        gave_up)."""
        events: List[Dict[str, Any]] = []
        for name, proc in list(self.procs.items()):
            if name in self.exits:
                continue
            code = proc.poll()
            if code is None:
                continue
            self.exits[name] = code
            self.metrics.counter(
                "resilience/proc_exits_total",
                help="supervised role process exits observed").inc()
            if code == EXIT_DONE:
                action = "done"
            elif code == EXIT_PREEMPTED:
                action = "drained"
            elif (not self._shutting_down
                    and self.restarts.get(name, 0) < self.max_restarts):
                self.restarts[name] = self.restarts.get(name, 0) + 1
                self.metrics.counter(
                    "resilience/proc_restarts_total",
                    help="crashed role processes respawned").inc()
                respawn = dataclasses.replace(
                    proc.spec, incarnation=proc.spec.incarnation + 1)
                self.spawn(respawn)
                action = "restarted"
            else:
                action = "gave_up"
            self.metrics.emit("proc_exit", role=name, code=code,
                              action=action)
            events.append({"role": name, "code": code, "action": action})
        return events

    def running(self) -> List[str]:
        return [n for n, p in self.procs.items()
                if n not in self.exits and p.alive]

    def all_done(self) -> bool:
        self.poll()
        return not self.running()

    def wait(self, timeout: float = 60.0,
             poll_interval: float = 0.05) -> bool:
        """Supervise until every role exits (restarts included) or the
        deadline passes. Returns True when the fleet fully drained."""
        deadline = time.monotonic() + float(timeout)
        while time.monotonic() < deadline:
            if self.all_done():
                return True
            time.sleep(poll_interval)
        return self.all_done()

    def statuses(self) -> Dict[str, Dict[str, Any]]:
        return read_statuses(self.root)

    # -- shutdown ---------------------------------------------------------- #
    def shutdown(self, grace_s: Optional[float] = None) -> Dict[str, Any]:
        """Graceful fleet drain: forward SIGTERM (double-delivered) to
        every live child, wait out the grace window, SIGKILL stragglers,
        reap everything, and verify no orphan survived. Returns a summary
        with per-role exit codes and the roles that needed escalation."""
        self._shutting_down = True
        grace = self.grace_s if grace_s is None else float(grace_s)
        live = [p for n, p in self.procs.items() if p.alive]
        for p in live:
            p.terminate()
        deadline = time.monotonic() + grace
        escalated: List[str] = []
        for p in live:
            remaining = deadline - time.monotonic()
            if p.wait(timeout=max(remaining, 0.01)) is None:
                escalated.append(p.spec.name)
                p.kill()
                p.wait(timeout=5.0)
        for name, p in self.procs.items():
            code = p.poll()
            if code is not None:
                self.exits[name] = code
        orphans = [p.spec.name for p in self.procs.values()
                   if pid_alive(p.pid)]
        if escalated:
            self.metrics.counter(
                "resilience/proc_escalations_total",
                help="children that outlived the SIGTERM grace window and "
                     "were SIGKILLed").inc(len(escalated))
        self.metrics.emit("proc_shutdown", exits=dict(self.exits),
                          escalated=escalated, orphans=orphans)
        return {"exits": dict(self.exits), "escalated": escalated,
                "orphans": orphans, "statuses": self.statuses()}


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess
    sys.exit(run_role(sys.argv[1]))
