"""The one resilience object a training loop talks to.

``Resilience`` wires the :class:`~agilerl_tpu.resilience.snapshot.CheckpointManager`
(crash-consistent whole-run snapshots), the
:class:`~agilerl_tpu.resilience.preemption.PreemptionGuard` (SIGTERM/SIGINT →
final snapshot at the next step boundary) and the retry policies into the
``resilience=`` / ``resume=`` kwargs every loop in
``agilerl_tpu/training/`` exposes::

    res = Resilience("runs/exp1/snapshots", save_every=10_000)
    pop, fit = train_off_policy(env, ..., resilience=res, resume=True)

On resume the loop's population, replay buffers, RNG streams (per-agent JAX
keys + numpy Generators, numpy/python globals, env PRNG, tournament/mutation
RNG), lineage genealogy and loop counters are all restored from the latest
COMPLETE snapshot. Cadence snapshots are only ever taken at generation
boundaries (the loops' re-entry points), so a run resumed from one continues
the same step/fitness stream the uninterrupted run would have produced.

Preemption snapshots follow ``on_preempt``:

* ``"now"`` (default): the final snapshot is taken at the next step
  boundary, mid-generation — minimal grace-window usage, maximal work
  preserved. The loops can only re-enter at a generation boundary, so the
  resumed run replays the partial generation from the snapshotted state: a
  valid continuation, but not the bit-identical stream.
* ``"finish_generation"``: the current generation (including eval and
  evolution) completes first and the final snapshot lands on the
  generation boundary — the resumed run continues the exact stream, at the
  cost of up to one generation of grace window.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Dict, List, Optional, Union

import numpy as np

from agilerl_tpu.resilience.preemption import PreemptionGuard
from agilerl_tpu.resilience.retry import RetryPolicy, RetryingEnv
from agilerl_tpu.resilience.snapshot import (
    CheckpointManager,
    capture_agent,
    capture_buffers,
    capture_env_rng,
    capture_evolution,
    capture_host_rng,
    restore_agent,
    restore_buffers,
    restore_env_rng,
    restore_evolution,
    restore_host_rng,
)

_SAVE_COUNT_KEY = "_resilience_save_count"


class Resilience:
    """Crash-consistency + preemption-awareness for one training run.

    Parameters
    ----------
    directory:
        Snapshot root (one run per directory).
    save_every:
        Snapshot cadence in env steps, applied at the loops' step boundaries
        (the generation/evaluation boundary — the only points where a
        snapshot is deterministic to resume). ``None`` disables cadence
        snapshots; preemption snapshots still fire.
    keep_last / keep_best:
        Retention: the last K snapshots plus the best-fitness one survive.
    handle_signals:
        Install the SIGTERM/SIGINT :class:`PreemptionGuard` while attached
        to a run (restored on ``close()``).
    retry:
        Optional :class:`RetryPolicy` used by :meth:`wrap_env`.
    on_preempt:
        What a preemption request interrupts. ``"now"`` (default) aborts
        the generation in flight and snapshots at the next step boundary —
        fastest exit, but the resumed run replays the partial generation
        rather than continuing the identical stream. ``"finish_generation"``
        lets the generation (plus eval/evolution) complete so the final
        snapshot lands on a generation boundary and the resume is
        bit-deterministic.
    """

    ON_PREEMPT_MODES = ("now", "finish_generation")

    def __init__(
        self,
        directory: Union[str, Path],
        *,
        save_every: Optional[int] = None,
        keep_last: int = 3,
        keep_best: bool = True,
        handle_signals: bool = True,
        retry: Optional[RetryPolicy] = None,
        on_preempt: str = "now",
        manager: Optional[CheckpointManager] = None,
        registry=None,
    ):
        if on_preempt not in self.ON_PREEMPT_MODES:
            raise ValueError(
                f"on_preempt must be one of {self.ON_PREEMPT_MODES}, "
                f"got {on_preempt!r}"
            )
        self.on_preempt = on_preempt
        self.manager = manager or CheckpointManager(
            directory, keep_last=keep_last, keep_best=keep_best,
            registry=registry,
        )
        self.save_every = None if save_every is None else max(int(save_every), 1)
        self.retry = retry
        self.guard = PreemptionGuard(registry=registry)
        self._handle_signals = bool(handle_signals)
        self._save_count = 0
        # live run references (attach() wires them; step_boundary re-wires
        # pop, which evolution rebinds every generation)
        self._pop: Optional[List] = None
        self._memory = None
        self._n_step_memory = None
        self._tournament = None
        self._mutation = None
        self._telemetry = None
        self._env = None

    # -- run wiring -------------------------------------------------------- #
    def attach(
        self,
        pop: Optional[List] = None,
        memory=None,
        n_step_memory=None,
        tournament=None,
        mutation=None,
        telemetry=None,
        env=None,
    ) -> "Resilience":
        """Point this object at the live run (called by the training loops
        right after telemetry init)."""
        self._pop = pop
        self._memory = memory
        self._n_step_memory = n_step_memory
        self._tournament = tournament
        self._mutation = mutation
        self._telemetry = telemetry
        self._env = env
        if telemetry is not None:
            # route snapshot/preemption events into the run's sink
            self.manager._registry = telemetry.registry
            self.guard._registry = telemetry.registry
            self.guard.telemetry = telemetry
        # a reused Resilience object must not replay the previous run's
        # latched preemption — the fresh run would exit before step one —
        # nor carry its cadence counter: a fresh run starting at step 0
        # would otherwise take no cadence snapshot until it passed the
        # previous run's last save step (resume() re-seeds it from the
        # snapshot when one exists)
        self.guard.reset()
        self._save_count = 0
        if self._handle_signals:
            self.guard.install()
        return self

    def wrap_env(self, env):
        """Wrap ``env`` with the retry policy (identity when none is set)."""
        if self.retry is None:
            return env
        return RetryingEnv(env, policy=self.retry,
                           registry=self.manager._registry)

    @property
    def registry(self):
        return self.manager.registry

    @property
    def preempted(self) -> bool:
        """True once SIGTERM/SIGINT (or ``guard.request()``) asked for a
        final snapshot — loops check this at step boundaries."""
        return self.guard.requested

    @property
    def abort_generation(self) -> bool:
        """The loops' MID-generation preemption check: True only when a
        preemption was requested AND ``on_preempt="now"``. Under
        ``"finish_generation"`` this stays False so the generation (plus
        eval/evolution) completes and :meth:`step_boundary` takes the final
        snapshot at the generation boundary — the deterministic re-entry
        point."""
        return self.on_preempt == "now" and self.guard.requested

    def _lineage(self):
        if self._telemetry is not None and self._telemetry.lineage is not None:
            return self._telemetry.lineage
        return getattr(self._tournament, "lineage", None)

    # -- snapshot/restore --------------------------------------------------- #
    def snapshot(
        self,
        step: int,
        counters: Optional[Dict[str, Any]] = None,
        kind: str = "cadence",
        fitness: Optional[float] = None,
    ) -> Path:
        """Capture and atomically commit the whole-run state. The staging
        rings are drained first (reusing the buffers' ``stage()``/``flush()``
        machinery) so both paired rings land index-aligned."""
        from agilerl_tpu.components.replay_buffer import drain_staging

        drain_staging(self._memory, self._n_step_memory)
        entries: Dict[str, Any] = {
            "population": [capture_agent(a) for a in (self._pop or [])],
            "buffers": capture_buffers(
                memory=self._memory, n_step_memory=self._n_step_memory
            ),
            "rng": capture_host_rng(),
            "evolution": capture_evolution(
                self._tournament, self._mutation, self._lineage()
            ),
            "counters": {**(counters or {}), _SAVE_COUNT_KEY: self._save_count},
        }
        if self._env is not None:
            env_blob = capture_env_rng(self._env)
            if env_blob is not None:
                entries["env"] = env_blob
        path = self.manager.save(entries, step, kind=kind, fitness=fitness)
        self.registry.emit(
            "snapshot", step=int(step), snapshot_kind=kind, path=str(path),
            fitness=None if fitness is None else float(fitness),
        )
        return path

    def resume(self, counters: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Restore the attached run from the latest complete snapshot.

        Returns the loop counters: the caller's defaults merged under the
        snapshot's saved values (unchanged when no snapshot exists, so a
        fresh run with ``resume=True`` just starts cleanly)."""
        merged = dict(counters or {})
        loaded = self.manager.load()
        if loaded is None:
            return merged
        info, entries = loaded
        saved_pop = entries.get("population", [])
        live_pop = self._pop or []
        if len(saved_pop) != len(live_pop):
            self.registry.warn_once(
                "resilience:population_size_mismatch",
                f"snapshot holds {len(saved_pop)} agents, live population has "
                f"{len(live_pop)} — restoring the overlapping prefix",
            )
        for agent, blob in zip(live_pop, saved_pop):
            restore_agent(agent, blob)
        restore_buffers(
            entries.get("buffers"),
            memory=self._memory, n_step_memory=self._n_step_memory,
        )
        restore_host_rng(entries.get("rng"))
        restore_env_rng(self._env, entries.get("env"))
        restore_evolution(
            entries.get("evolution"), self._tournament, self._mutation,
            self._lineage(),
        )
        saved_counters = dict(entries.get("counters", {}))
        self._save_count = int(saved_counters.pop(_SAVE_COUNT_KEY, 0))
        for key, saved in saved_counters.items():
            live = merged.get(key)
            if (
                isinstance(saved, list) and isinstance(live, list)
                and len(saved) == len(saved_pop) != 0
                and len(live) == len(live_pop)
                and len(live) > len(saved)
            ):
                # a per-agent counter (e.g. pop_fitnesses) from a smaller
                # snapshot population: honor the prefix-restore contract
                # warned about above — saved values for the overlapping
                # agents, the caller's defaults for the extras (a wholesale
                # replace would hand the loop a too-short list and crash its
                # first eval round)
                merged[key] = list(saved) + list(live[len(saved):])
            else:
                merged[key] = saved
        self.registry.emit(
            "resume", step=info.step, snapshot_kind=info.kind,
            path=str(info.path),
        )
        return merged

    # -- the loops' boundary hook ------------------------------------------ #
    def step_boundary(
        self,
        step: int,
        counters: Optional[Dict[str, Any]] = None,
        pop: Optional[List] = None,
        fitness: Optional[float] = None,
    ) -> bool:
        """Called once per step boundary (the loops' old ad-hoc checkpoint
        site). Takes a cadence snapshot when due, or the FINAL snapshot when
        a preemption was requested — in which case it returns True and the
        loop exits cleanly."""
        if pop is not None:
            self._pop = pop
        if fitness is not None and not np.isfinite(fitness):
            fitness = None  # NaN/inf must not poison best-fitness retention
        if self.guard.requested:
            self.snapshot(step, counters, kind="preempt", fitness=fitness)
            return True
        if self.save_every is not None and step // self.save_every > self._save_count:
            self._save_count = step // self.save_every
            self.snapshot(step, counters, kind="cadence", fitness=fitness)
        return False

    def close(self) -> None:
        """Detach from the run: restore signal handlers and drop the run
        references attach() took — a Resilience object kept around between
        sequential runs must not pin the previous run's replay-buffer rings
        and population pytrees until the next attach()."""
        self.guard.uninstall()
        self._pop = None
        self._memory = None
        self._n_step_memory = None
        self._tournament = None
        self._mutation = None
        self._telemetry = None
        self._env = None

    def __enter__(self) -> "Resilience":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def max_fitness(fitnesses) -> Optional[float]:
    """Small shared helper: best fitness of an eval round (None when the
    round produced nothing finite) — feeds the keep-best retention.
    Accepts any sequence, including numpy arrays (whose truth value is
    ambiguous, so no ``if fitnesses`` here)."""
    arr = np.asarray(list(fitnesses), dtype=float)
    if arr.size == 0 or not np.isfinite(arr).any():
        return None
    return float(np.nanmax(arr[np.isfinite(arr)]))
