"""Heartbeat/lease membership for preemptible multi-host populations.

On spot/preemptible TPU capacity, hosts *will* disappear mid-run — and a
vanished host must surface as a **bounded, detectable event**, never as a
fitness all-gather that hangs forever (the Podracer deployment problem,
Hessel et al. 2021, applied to PBT). This module is the detection half of
the elastic controller (:mod:`agilerl_tpu.parallel.elastic`):

- every live host periodically writes a **lease file** into a directory on
  the shared snapshot store (the same filesystem the
  :class:`~agilerl_tpu.resilience.snapshot.CheckpointManager` commits to, so
  no extra coordination service is needed);
- a host whose lease goes stale past ``lease_timeout`` — or that wrote a
  tombstone on graceful shutdown — drops out of the live set;
- :meth:`HeartbeatStore.poll` diffs the live set against the last
  observation and reports a :class:`MembershipEvent` (lost/joined hosts +
  the new leader) while feeding the ``resilience/*`` membership counters;
- the **leader** is simply the lowest live host id (deterministic on every
  observer, no election protocol): leader-only duties are snapshot commits
  and island exports, so a split-brain during a lease-expiry window can at
  worst produce an extra atomic snapshot, never a torn one.

Lease writes deliberately do NOT go through the atomic/fault-hook layer:
leases are ephemeral liveness signals, not durability-critical state — an
fsync per heartbeat would hammer the shared store, and routing beats through
the fault hook would make the :class:`~agilerl_tpu.resilience.faults
.FaultInjector`'s scheduled op indices timing-dependent. A torn lease read
is treated as a missed beat (the next beat rewrites it).
"""

from __future__ import annotations

import json
import os
import socket
import time
import types
from pathlib import Path
from typing import Dict, Mapping, NamedTuple, Optional, Sequence, Tuple, Union


def _registry():
    from agilerl_tpu.observability import get_registry

    return get_registry()


def pid_alive(pid: int) -> bool:
    """Cheap same-host liveness probe: does ``pid`` still exist?

    ``os.kill(pid, 0)`` performs permission checks but delivers nothing.
    ``PermissionError`` means the pid exists but belongs to another user —
    alive for our purposes. A zombie (exited, unreaped) still probes alive;
    the process supervisor reaps its children promptly, so that window is
    the supervisor's poll interval, not the lease window.
    """
    if pid is None or int(pid) <= 0:
        return False
    try:
        os.kill(int(pid), 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except OSError:
        return False
    return True


class MembershipChange(RuntimeError):
    """The live host set changed (lease expiry, tombstone, or a collective
    that timed out because a participant vanished).

    Raised by :func:`agilerl_tpu.parallel.multihost.barrier` /
    ``call_with_collective_timeout`` on timeout and by
    :meth:`HeartbeatStore.wait_for` on a join deadline; the elastic
    controller catches it and routes recovery through snapshot-resume
    (collectives still fail fast — per PR 3's design note, a per-host retry
    inside a collective would desync the pod)."""

    def __init__(
        self,
        message: str,
        lost: Sequence[int] = (),
        joined: Sequence[int] = (),
        alive: Sequence[int] = (),
    ):
        super().__init__(message)
        self.lost: Tuple[int, ...] = tuple(int(h) for h in lost)
        self.joined: Tuple[int, ...] = tuple(int(h) for h in joined)
        self.alive: Tuple[int, ...] = tuple(int(h) for h in alive)


class MembershipEvent(NamedTuple):
    """One observed change of the live host set.

    ``meta`` carries lease payload metadata (the small JSON dict passed to
    :meth:`HeartbeatStore.beat` — e.g. ``{"role": "decode", "replica": 3}``
    for a serving-fleet member) for every ALIVE and every LOST host — a
    lost host's last (stale) lease is still readable, so observers can
    tell a lost decode replica from a lost prefill worker. Hosts whose
    lease is torn/unreadable map to ``{}``. The no-meta default is an
    immutable empty mapping (a shared plain-dict default would let one
    consumer's in-place annotation leak into every other default-
    constructed event)."""

    alive: Tuple[int, ...]
    lost: Tuple[int, ...]
    joined: Tuple[int, ...]
    leader: Optional[int]
    meta: Mapping[int, dict] = types.MappingProxyType({})


class HeartbeatStore:
    """Filesystem lease files as the membership substrate.

    Layout: ``<directory>/host_<id>.json`` holding ``{"host", "time",
    "incarnation"}`` (or ``{"dead": true}`` as a graceful tombstone). Time
    comes from the injectable ``clock`` (default ``time.time`` — leases are
    compared across processes, so a wall clock is required; tests inject a
    fake one).

    ``incarnation`` distinguishes a host that died and came back from one
    that never left: a rejoin after an observed loss is reported as
    ``joined`` even if the id is the same.

    **Fast same-host failure detection** (``probe_pids``, default on): every
    beat records the writer's pid and node name, and :meth:`alive` probes
    the pid of any lease written from *this* node via :func:`pid_alive`. A
    crashed local process therefore drops out of the live set on the very
    next observation instead of after ``lease_timeout`` — the MTTR path the
    single-machine process launcher rides. Leases from other nodes (or
    pre-probe leases without a pid) still age out by lease timeout only.
    """

    def __init__(
        self,
        directory: Union[str, Path],
        lease_timeout: float = 5.0,
        registry=None,
        clock=time.time,
        probe_pids: bool = True,
    ):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.lease_timeout = float(lease_timeout)
        self._registry_override = registry
        self.clock = clock
        self.probe_pids = bool(probe_pids)
        self.node = socket.gethostname()
        #: last observed view: host id -> incarnation (None until baselined)
        self._last_view: Optional[Dict[int, int]] = None

    @property
    def registry(self):
        return self._registry_override if self._registry_override is not None \
            else _registry()

    # -- lease I/O --------------------------------------------------------- #
    def _lease_path(self, host_id: int) -> Path:
        return self.directory / f"host_{int(host_id):04d}.json"

    def _write(self, host_id: int, payload: dict) -> None:
        # plain tmp+rename (no fsync, no fault hook): liveness signal, not
        # durable state — see module docstring
        path = self._lease_path(host_id)
        tmp = path.with_name(path.name + f".{os.getpid()}.beat")
        # leases are liveness, not durability: atomic.py's fsync+fault-hook
        # path would skew FaultInjector op indices and add an fsync per
        # heartbeat; a torn lease reads as a missed beat, which is the
        # correct failure semantics here
        tmp.write_bytes(json.dumps(payload).encode())  # graftcheck: disable=GX004
        os.replace(tmp, path)  # graftcheck: disable=GX004 — see above

    def beat(
        self,
        host_id: int,
        incarnation: int = 0,
        meta: Optional[dict] = None,
        pid: Optional[int] = None,
        node: Optional[str] = None,
    ) -> None:
        """Renew ``host_id``'s lease (call once per generation/heartbeat
        interval; must beat faster than ``lease_timeout`` to stay live).
        ``meta`` is a small JSON payload recorded in the lease — the serving
        fleet writes ``{"role": "prefill"|"decode"|"unified", "replica": id}``
        so :meth:`poll`/:meth:`roles` surface the topology, not just
        liveness. ``pid``/``node`` default to the writing process and this
        node; tests override them to fabricate a crashed-process lease."""
        payload = {
            "host": int(host_id),
            "time": float(self.clock()),
            "incarnation": int(incarnation),
            "pid": int(os.getpid() if pid is None else pid),
            "node": self.node if node is None else str(node),
        }
        if meta:
            payload["meta"] = meta
        self._write(host_id, payload)

    def mark_dead(self, host_id: int) -> None:
        """Graceful tombstone: the host drops out of the live set immediately
        instead of after a lease timeout (SIGTERM/shutdown path)."""
        self._write(host_id, {"host": int(host_id), "dead": True,
                              "time": float(self.clock())})

    # -- observation ------------------------------------------------------- #
    def leases(self) -> Dict[int, dict]:
        """All readable, non-tombstoned lease payloads (fresh or stale)."""
        out: Dict[int, dict] = {}
        for p in sorted(self.directory.glob("host_*.json")):
            try:
                payload = json.loads(p.read_text())
            except (OSError, ValueError):
                continue  # torn/concurrent lease write == missed beat
            if payload.get("dead"):
                continue
            try:
                out[int(payload["host"])] = payload
            except (KeyError, TypeError, ValueError):
                continue
        return out

    def _probed_dead(self, payload: dict) -> bool:
        """True when a lease was written by a process on THIS node whose pid
        no longer exists — a crashed local process whose lease is still
        fresh. Cross-node leases (or pre-probe leases without a pid) are
        never probed; they age out by lease timeout only."""
        if not self.probe_pids:
            return False
        pid = payload.get("pid")
        if pid is None or payload.get("node") != self.node:
            return False
        try:
            return not pid_alive(int(pid))
        except (TypeError, ValueError):
            return False

    def alive(self, now: Optional[float] = None) -> Dict[int, dict]:
        """Hosts with a fresh lease (age ≤ ``lease_timeout``) whose writer —
        when it lives on this node and the probe is enabled — still exists.
        The pid probe turns a same-host crash into an immediate loss instead
        of a lease-window wait."""
        now = float(self.clock()) if now is None else float(now)
        return {
            h: payload for h, payload in self.leases().items()
            if now - float(payload.get("time", -float("inf"))) <= self.lease_timeout
            and not self._probed_dead(payload)
        }

    def leader(self, alive: Optional[Dict[int, dict]] = None) -> Optional[int]:
        """Lowest live host id — deterministic on every observer."""
        a = self.alive() if alive is None else alive
        return min(a) if a else None

    def roles(self, alive: Optional[Dict[int, dict]] = None) -> Dict[int, Optional[str]]:
        """Role recorded in each live host's lease metadata (None when a
        host beats without one) — the serving fleet's prefill/decode/unified
        topology readout."""
        a = self.alive() if alive is None else alive
        return {int(h): (p.get("meta") or {}).get("role")
                for h, p in a.items()}

    def expect(self, host_ids: Sequence[int]) -> None:
        """Baseline the observed set explicitly (e.g. right after the join
        barrier) so the first :meth:`poll` diffs against the real roster
        rather than treating everyone as newly joined. Incarnations come
        from the hosts' current leases (0 when a host has not beat yet)."""
        leases = self.leases()
        self._last_view = {
            int(h): int(leases.get(int(h), {}).get("incarnation", 0))
            for h in host_ids
        }

    def poll(self) -> Optional[MembershipEvent]:
        """Diff the live view against the last observation. Returns ``None``
        when nothing changed (the first poll baselines and reports nothing);
        otherwise records membership metrics, emits a ``membership`` event
        and returns the :class:`MembershipEvent`. A host whose lease carries
        a NEW incarnation — it died and rejoined inside one lease window —
        is reported in both ``lost`` and ``joined``. Lease metadata (role,
        replica id — whatever :meth:`beat` was given) rides on the event's
        ``meta`` for alive AND lost hosts (a lost host's stale lease is
        still readable) so fleet observers can tell a lost decode replica
        from a lost prefill worker."""
        live = self.alive()
        view = {h: int(p.get("incarnation", 0)) for h, p in live.items()}
        if self._last_view is None:
            self._last_view = view
            return None
        if view == self._last_view:
            return None
        lost = tuple(sorted(
            h for h, inc in self._last_view.items() if view.get(h) != inc
        ))
        joined = tuple(sorted(
            h for h, inc in view.items() if self._last_view.get(h) != inc
        ))
        alive = tuple(sorted(view))
        self._last_view = view
        leader = min(alive) if alive else None
        # lost hosts' STALE leases are still readable — their meta rides on
        # the event too, so observers can classify WHAT was lost (a torn or
        # tombstoned lease degrades to {})
        stale = self.leases()
        meta = {int(h): dict(live[h].get("meta") or {}) for h in alive}
        meta.update({
            int(h): dict(stale.get(int(h), {}).get("meta") or {})
            for h in lost
        })
        reg = self.registry
        reg.counter("resilience/membership_changes_total").inc()
        if lost:
            reg.counter("resilience/hosts_lost_total").inc(len(lost))
        if joined:
            reg.counter("resilience/hosts_joined_total").inc(len(joined))
        reg.emit(
            "membership",
            alive=[int(h) for h in alive],
            lost=[int(h) for h in lost],
            joined=[int(h) for h in joined],
            leader=leader,
            roles={int(h): m.get("role") for h, m in meta.items()
                   if m.get("role") is not None},
        )
        return MembershipEvent(alive, lost, joined, leader, meta)

    def wait_for(
        self,
        n_hosts: int,
        timeout: float = 30.0,
        interval: float = 0.05,
        beat_as: Optional[Tuple[int, int]] = None,
    ) -> Dict[int, dict]:
        """Join barrier: block until ``n_hosts`` leases are live (optionally
        renewing our own lease as ``(host_id, incarnation)`` while waiting).
        Raises :class:`MembershipChange` on deadline — a bounded startup
        instead of an indefinite wait for capacity that may never come."""
        deadline = time.monotonic() + float(timeout)
        while True:
            if beat_as is not None:
                self.beat(*beat_as)
            a = self.alive()
            if len(a) >= int(n_hosts):
                return a
            if time.monotonic() >= deadline:
                raise MembershipChange(
                    f"membership join timed out after {timeout}s: "
                    f"{len(a)}/{n_hosts} hosts live ({sorted(a)})",
                    alive=sorted(a),
                )
            time.sleep(interval)
