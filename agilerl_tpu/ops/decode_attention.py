"""Chunked cached attention — the flash-decode path for the in-tree generate
loop (parity goal: replace vLLM's paged decode attention,
agilerl/algorithms/core/base.py:3101; SURVEY.md §2.9).

Decode attention is HBM-bandwidth-bound, not MXU-bound: each step reads the
whole live KV prefix once. The dense XLA path previously scored every q
against the FULL cache allocation [B, S, Hkv, d] (S = prompt + max_new_tokens)
and materialized a GQA-repeated copy of K/V. This op fixes both:

- online-softmax accumulation over KV chunks inside a ``lax.fori_loop`` whose
  trip count is the *dynamic* live length ``ceil((start+T)/block)`` — slots
  beyond the live prefix are never read (a dynamic trip count is a value, not
  a shape, so XLA compiles it once as a while loop);
- GQA folded into the einsum (q reshaped [B,T,Hkv,rep,d]) so K/V are never
  repeated in HBM.

Two callers share this op with different window shapes, both covered by the
same visibility rule (slot j visible to query t iff j <= start[b] + t and
valid[j]):

- plain decode: T = 1, ``start`` = per-row cache depth before the step;
- speculative verify (llm/speculate.py): T = K + 1 — the committed last token
  plus K draft tokens are scored in ONE forward, with ``start`` = per-row
  depth of the committed prefix and the window's K/V already inserted at
  slots start[b]..start[b]+T-1. Query t attends to the committed prefix plus
  the first t window tokens, exactly as if the drafts had been decoded one
  step at a time — which is what makes accept/reject token-exact.

Numerics match the dense masked-softmax path bit-for-bit at f32 accumulation
(tests/test_ops/test_decode_attention.py, incl. the per-row-start T>1
verify-window case). A Pallas kernel is deliberately NOT
used here: with BlockSpec pipelining the operand fetch for a grid step happens
whether or not ``pl.when`` skips the compute, so a static-grid Pallas kernel
cannot skip the dead cache tail — the dynamic-bound XLA loop can, and the
per-chunk math (two matmuls + exp) is already fused by XLA.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np


def _dense_reference(q, k_cache, v_cache, valid, start):
    """Differentiable dense formulation of the same visibility rule — used
    only as the backward path (custom VJP): the chunked forward's
    dynamic-trip-count while_loop is not reverse-differentiable, but its
    output is bit-equal to this dense one, so the VJP of this function AT
    THE SAME INPUTS is the correct gradient."""
    B, T, Hq, d = q.shape
    S, Hkv = k_cache.shape[1], k_cache.shape[2]
    rep = Hq // Hkv
    qr = q.reshape(B, T, Hkv, rep, d)
    scores = jnp.einsum(
        "bthrd,bshd->bhrts", qr, k_cache, preferred_element_type=jnp.float32
    ) / math.sqrt(d)
    slot = jnp.arange(S)
    # start may be [] (all rows aligned) or [B] (paged slots at
    # heterogeneous depths) — broadcast to per-row either way
    start_b = jnp.broadcast_to(jnp.asarray(start), (B,))
    causal = (slot[None, None, :]
              <= (start_b[:, None] + jnp.arange(T)[None, :])[:, :, None])  # [B, T, S]
    mask = jnp.logical_and(
        causal[:, None, None], valid.astype(bool)[:, None, None, None, :]
    )
    probs = jax.nn.softmax(jnp.where(mask, scores, -1e30), axis=-1)
    out = jnp.einsum(
        "bhrts,bshd->bhrtd", probs.astype(v_cache.dtype), v_cache,
        preferred_element_type=jnp.float32,
    )
    return jnp.moveaxis(out, 3, 1).reshape(B, T, Hq, d).astype(q.dtype)


@functools.lru_cache(maxsize=None)
def _make_chunked(block: int):
    @jax.custom_vjp
    def f(q, k_cache, v_cache, valid, start):
        return _chunked_impl(q, k_cache, v_cache, valid, start, block)

    def fwd(q, k_cache, v_cache, valid, start):
        return f(q, k_cache, v_cache, valid, start), (
            q, k_cache, v_cache, valid, start,
        )

    def bwd(res, g):
        q, k_cache, v_cache, valid, start = res
        _, vjp = jax.vjp(
            lambda q_, k_, v_: _dense_reference(q_, k_, v_, valid, start),
            q, k_cache, v_cache,
        )
        dq, dk, dv = vjp(g)
        f0 = jax.dtypes.float0
        return (dq, dk, dv,
                np.zeros(np.shape(valid), f0), np.zeros(np.shape(start), f0))

    f.defvjp(fwd, bwd)
    return f


@functools.partial(jax.jit, static_argnames=("block",))
def chunked_cached_attention(
    q: jax.Array,        # [B, T, Hq, d] RoPE'd queries (absolute pos start..start+T)
    k_cache: jax.Array,  # [B, S, Hkv, d] cache AFTER inserting this step's K
    v_cache: jax.Array,  # [B, S, Hkv, d]
    valid: jax.Array,    # [B, S] 1 = slot holds a real token
    start,               # [] or [B]: cache length before this step — per-row
    #                      for the paged/continuous decode path, whose slots
    #                      sit at heterogeneous depths
    *,
    block: int = 512,
) -> jax.Array:
    """Returns attention output [B, T, Hq, d] (same visibility rule as the
    dense path: slot j visible to query t iff j <= start[b] + t and valid[j]).
    Reverse-differentiable: grads route through a dense backward (custom
    VJP) since the dynamic-bound forward loop cannot be transposed."""
    return _make_chunked(min(block, k_cache.shape[1]))(
        q, k_cache, v_cache, valid, jnp.asarray(start)
    )


def _chunked_impl(q, k_cache, v_cache, valid, start, block):
    B, T, Hq, d = q.shape
    S, Hkv = k_cache.shape[1], k_cache.shape[2]
    rep = Hq // Hkv
    scale = 1.0 / math.sqrt(d)

    qr = q.reshape(B, T, Hkv, rep, d)
    t_ids = jnp.arange(T)

    # start: [] or [B] (paged decode slots sit at heterogeneous depths);
    # the loop bound must cover the DEEPEST row — shallower rows' extra
    # chunks are fully masked and contribute exact zeros
    start_b = jnp.broadcast_to(jnp.asarray(start), (B,))
    live = jnp.max(start_b) + T  # number of potentially-visible slots
    n_chunks = jnp.minimum(
        (live + block - 1) // block, -(-S // block)
    ).astype(jnp.int32)

    m0 = jnp.full((B, Hkv, rep, T), -1e30, jnp.float32)
    l0 = jnp.zeros((B, Hkv, rep, T), jnp.float32)
    acc0 = jnp.zeros((B, Hkv, rep, T, d), jnp.float32)

    def chunk_step(i, carry):
        m, l, acc = carry
        off = i * block
        # when S % block != 0 the last chunk's slice is clamped to S - block
        # (no padding — a pad would COPY the whole cache every call); the
        # re-read slots below `off` are masked out so nothing double-counts
        off_c = jnp.minimum(off, S - block)
        ks = jax.lax.dynamic_slice_in_dim(k_cache, off_c, block, axis=1)
        vs = jax.lax.dynamic_slice_in_dim(v_cache, off_c, block, axis=1)
        vm = jax.lax.dynamic_slice_in_dim(valid, off_c, block, axis=1)

        scores = jnp.einsum(
            "bthrd,bshd->bhrts", qr, ks, preferred_element_type=jnp.float32
        ) * scale  # [B, Hkv, rep, T, BK]

        slot = off_c + jnp.arange(block)
        causal = (slot[None, None, :]
                  <= (start_b[:, None] + t_ids[None, :])[:, :, None])  # [B, T, BK]
        fresh = slot >= off                                            # [BK]
        mask = jnp.logical_and(
            jnp.logical_and(causal, fresh[None, None, :])[:, None, None],
            vm.astype(bool)[:, None, None, None, :],
        )
        scores = jnp.where(mask, scores, -1e30)

        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        p = jnp.exp(scores - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bhrts,bshd->bhrtd", p.astype(vs.dtype), vs,
            preferred_element_type=jnp.float32,
        )
        return m_new, l, acc

    _, l, acc = jax.lax.fori_loop(0, n_chunks, chunk_step, (m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-30)[..., None]   # [B, Hkv, rep, T, d]
    out = jnp.moveaxis(out, 3, 1)                  # [B, T, Hkv, rep, d]
    return out.reshape(B, T, Hq, d).astype(q.dtype)
