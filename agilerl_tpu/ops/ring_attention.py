"""Ring attention — sequence/context parallelism over a mesh axis.

The reference has NO sequence parallelism (SURVEY.md §5.7: absent; long context
is handled only by chunking + vLLM paged attention). This module goes beyond
parity: sequences shard over a "sp" mesh axis, K/V blocks rotate around the ring
via ppermute over ICI, and softmax is accumulated online (flash-style running
max/denominator), so attention memory per chip is O(T/P * T/P) and sequence
length scales linearly with ring size. (Liu et al., Ring Attention, 2023.)
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from agilerl_tpu.compat import shard_map, axis_size
from jax.sharding import Mesh, PartitionSpec as P


def _block_attn(q, k, v, mask, scale):
    """One q-block x kv-block partial attention.

    q [B, Tq, H, d]; k/v [B, Tk, H, d]; mask [Tq, Tk] or None.
    Returns (numerator [B, Tq, H, d], row max m [B, Tq, H], denom l [B, Tq, H])."""
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale  # [B, H, Tq, Tk]
    if mask is not None:
        if mask.ndim == 2:  # [Tq, Tk]
            mask = mask[None, None]
        elif mask.ndim == 3:  # [B, Tq, Tk]
            mask = mask[:, None]
        scores = jnp.where(mask, scores, -1e30)
    m = jnp.max(scores, axis=-1)  # [B, H, Tq]
    p = jnp.exp(scores - m[..., None])
    l = jnp.sum(p, axis=-1)  # [B, H, Tq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return o, jnp.moveaxis(m, 1, 2), jnp.moveaxis(l, 1, 2)  # m,l -> [B, Tq, H]


def _ring_flash(q, k, v, axis_name, causal, kv_mask, block_q, block_k):
    """Ring attention with the Pallas flash kernel as the per-block engine:
    the [T_local, T_local] score matrix never materialises in HBM (online
    softmax in VMEM), so per-chip attention memory is O(block^2) instead of
    O(T_local^2). Each ring offset picks the right kernel via lax.switch
    (earlier block: full attention; diagonal: causal; future: skip), and
    partial results merge by logsumexp — flash_attention_with_lse's lse
    output is differentiable, so this path serves training too."""
    from agilerl_tpu.ops.flash_attention_vjp import flash_attention_with_lse

    p_size = axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    qh = jnp.moveaxis(q, 2, 1)  # [B, H, T, d]

    def step(carry, i):
        k_blk, v_blk, m_blk, o_acc, lse_acc = carry
        src_idx = (my_idx - i) % p_size
        kh = jnp.moveaxis(k_blk, 2, 1)
        vh = jnp.moveaxis(v_blk, 2, 1)

        def past(_):
            return flash_attention_with_lse(
                qh, kh, vh, m_blk, False, block_q, block_k)

        def diag(_):
            return flash_attention_with_lse(
                qh, kh, vh, m_blk, True, block_q, block_k)

        def future(_):
            return (jnp.zeros_like(qh),
                    jnp.zeros(qh.shape[:3], jnp.float32) - 1e30)

        if causal:
            idx = (jnp.where(src_idx == my_idx, 1, 0)
                   + jnp.where(src_idx > my_idx, 2, 0))
            o_b, lse_b = lax.switch(idx, [past, diag, future], None)
        else:
            o_b, lse_b = past(None)

        # merge normalized partials by logsumexp weight
        lse_new = jnp.logaddexp(lse_acc, lse_b)
        w_acc = jnp.exp(lse_acc - lse_new)[..., None]
        w_b = jnp.exp(lse_b - lse_new)[..., None]
        o_new = o_acc * w_acc + o_b.astype(o_acc.dtype) * w_b

        perm = [(j, (j + 1) % p_size) for j in range(p_size)]
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        m_next = (
            lax.ppermute(m_blk, axis_name, perm) if m_blk is not None else None
        )
        return (k_next, v_next, m_next, o_new, lse_new), None

    o0 = qh.astype(jnp.float32) * 0.0
    lse0 = jnp.sum(o0, axis=-1) - 1e30
    (_, _, _, o, _), _ = lax.scan(
        step, (k, v, kv_mask, o0, lse0), jnp.arange(p_size))
    return jnp.moveaxis(o, 1, 2).astype(q.dtype)


def ring_attention(
    q: jax.Array,  # [B, T_local, H, d] — local sequence shard
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "sp",
    causal: bool = True,
    kv_mask: Optional[jax.Array] = None,  # [B, T_local] 1 = real token; the
    # mask ROTATES around the ring with its k/v block (ragged/right-padded seqs)
    use_flash: bool = False,
    block_q: int = 128,
    block_k: int = 128,
) -> jax.Array:
    """Call INSIDE shard_map with q/k/v sharded on the sequence axis.
    ``use_flash=True`` swaps the per-block engine for the Pallas flash
    kernel (O(block^2) VMEM instead of O(T_local^2) HBM scores)."""
    if use_flash:
        return _ring_flash(q, k, v, axis_name, causal, kv_mask,
                           block_q, block_k)
    p_size = axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    B, T, H, d = q.shape
    scale = 1.0 / (d ** 0.5)

    t_ids = jnp.arange(T)
    intra_causal = t_ids[:, None] >= t_ids[None, :]  # causal within a block

    def step(carry, i):
        k_blk, v_blk, m_blk, o_acc, m_acc, l_acc = carry
        src_idx = (my_idx - i) % p_size  # which block this k/v shard came from

        pad_mask = (
            jnp.broadcast_to(m_blk[:, None, :].astype(bool), (B, T, T))
            if m_blk is not None else None
        )
        if causal:
            # select the MASK per ring offset (diagonal block: causal-within;
            # earlier block: full) instead of computing the block attention
            # twice and selecting outputs — halves every causal ring step
            same = src_idx == my_idx
            after = src_idx > my_idx
            eff_mask = jnp.where(same, intra_causal[None, :, :], True)
            if pad_mask is not None:
                eff_mask = jnp.logical_and(eff_mask, pad_mask)
        else:
            eff_mask = pad_mask
        o_b, m_b, l_b = _block_attn(q, k_blk, v_blk, eff_mask, scale)
        if causal:
            # future blocks contribute nothing — explicit overrides (an
            # all-masked score block would otherwise yield p=1 rows)
            m_b = jnp.where(after, -1e30, m_b)
            l_b = jnp.where(after, 0.0, l_b)
            o_b = jnp.where(after, 0.0, o_b)

        # online softmax merge
        m_new = jnp.maximum(m_acc, m_b)
        alpha = jnp.exp(m_acc - m_new)
        beta = jnp.exp(m_b - m_new)
        l_new = l_acc * alpha + l_b * beta
        o_new = o_acc * alpha[..., None] + o_b * beta[..., None]

        # rotate k/v (and their mask) around the ring
        perm = [(j, (j + 1) % p_size) for j in range(p_size)]
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        m_next = (
            lax.ppermute(m_blk, axis_name, perm) if m_blk is not None else None
        )
        return (k_next, v_next, m_next, o_new, m_new, l_new), None

    # derive accumulators from q so they carry the same varying-axis ("vma")
    # type as the per-device loop outputs (new shard_map type system)
    o0 = q * 0.0
    m0 = jnp.sum(o0, axis=-1) - 1e30
    l0 = jnp.sum(o0, axis=-1)
    (k_f, v_f, _mf, o, m, l), _ = lax.scan(
        step, (k, v, kv_mask, o0, m0, l0), jnp.arange(p_size)
    )
    return o / jnp.maximum(l[..., None], 1e-30)


def make_ring_attention(
    mesh: Mesh, axis_name: str = "sp", causal: bool = True,
    with_mask: bool = False, use_flash: bool = False,
):
    """Wrap ring_attention in shard_map: takes [B, T, H, d] arrays sharded on T
    (+ an optional [B, T] kv padding mask when with_mask=True)."""

    spec = P(None, axis_name, None, None)
    mspec = P(None, axis_name)
    fn = functools.partial(ring_attention, axis_name=axis_name, causal=causal,
                           use_flash=use_flash)
    if with_mask:
        def wrapped(q, k, v, m):
            return fn(q, k, v, kv_mask=m)

        return jax.jit(
            shard_map(wrapped, mesh=mesh, in_specs=(spec, spec, spec, mspec),
                      out_specs=spec, check_vma=False)
        )
    # check_vma=False: pallas_call out_shapes carry no vma annotations (the
    # flash per-block engine); collective correctness is covered by the
    # dense-reference parity tests
    return jax.jit(
        shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                  check_vma=False)
    )


def reference_attention(q, k, v, causal: bool = True):
    """Dense attention for correctness checks."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        T = q.shape[1]
        mask = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
        scores = jnp.where(mask[None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)
