"""Differentiable Pallas flash attention (custom VJP, FlashAttention-2 style
backward) — lets the fused kernel serve the TRAINING losses (GRPO/DPO forward-
backward), not just the no-grad passes.

Forward saves per-row logsumexp L; backward recomputes probabilities blockwise:
  D_i  = rowsum(dO_i * O_i)
  P_ij = exp(q_i k_j^T * scale - L_i)
  dV_j = sum_i P_ij^T dO_i
  dS   = P * (dO V^T - D)
  dQ_i = dS_ij K_j * scale        (grid: kv innermost, accumulate in VMEM)
  dK_j = dS_ij^T Q_i * scale      (grid: q innermost, accumulate in VMEM)

Causal masking mirrors the forward. Interpret mode on CPU for tests; native on
TPU. Supports an optional [B, T] padding mask like the forward kernel.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from agilerl_tpu.ops.kernel_mode import resolve_interpret

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None


# --------------------------------------------------------------------------- #
# Forward kernel that also emits L = m + log(l)
# --------------------------------------------------------------------------- #


def _fwd_kernel(scale, causal, block_q, block_k, seq_len, with_mask):
    def kernel(*refs):
        if with_mask:
            (q_ref, k_ref, v_ref, pm_ref, out_ref, lse_ref,
             m_ref, l_ref, acc_ref) = refs
        else:
            q_ref, k_ref, v_ref, out_ref, lse_ref, m_ref, l_ref, acc_ref = refs
            pm_ref = None
        qi = pl.program_id(1)
        kj = pl.program_id(2)
        nk = pl.num_programs(2)

        @pl.when(kj == 0)
        def _init():
            m_ref[:] = jnp.full_like(m_ref, -1e30)
            l_ref[:] = jnp.zeros_like(l_ref)
            acc_ref[:] = jnp.zeros_like(acc_ref)

        def body():
            q, k, v = q_ref[0], k_ref[0], v_ref[0]
            s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
            q_ids = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_ids = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            mask = k_ids < seq_len
            if causal:
                mask = jnp.logical_and(mask, k_ids <= q_ids)
            if pm_ref is not None:
                mask = jnp.logical_and(mask, pm_ref[0] > 0)
            s = jnp.where(mask, s, -1e30)
            m_old = m_ref[:]
            m_new = jnp.maximum(m_old, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m_old - m_new)
            l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
            acc_ref[:] = acc_ref[:] * alpha + jnp.dot(
                p.astype(v.dtype), v, preferred_element_type=jnp.float32
            )
            m_ref[:] = m_new

        if causal:
            @pl.when(kj * block_k <= qi * block_q + block_q - 1)
            def _run():
                body()
        else:
            body()

        @pl.when(kj == nk - 1)
        def _done():
            out_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(out_ref.dtype)
            lse_ref[0] = m_ref[:] + jnp.log(jnp.maximum(l_ref[:], 1e-30))

    return kernel


def _dq_kernel(scale, causal, block_q, block_k, seq_len, with_mask):
    def kernel(*refs):
        if with_mask:
            (q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref, pm_ref,
             dq_ref, acc_ref) = refs
        else:
            q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref, dq_ref, acc_ref = refs
            pm_ref = None
        qi = pl.program_id(1)
        kj = pl.program_id(2)
        nk = pl.num_programs(2)

        @pl.when(kj == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        def body():
            q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
            s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
            q_ids = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_ids = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            mask = k_ids < seq_len
            if causal:
                mask = jnp.logical_and(mask, k_ids <= q_ids)
            if pm_ref is not None:
                mask = jnp.logical_and(mask, pm_ref[0] > 0)
            p = jnp.where(mask, jnp.exp(s - lse_ref[0]), 0.0)
            dov = jnp.dot(do, v.T, preferred_element_type=jnp.float32)  # [BQ, BK]
            ds = p * (dov - dd_ref[0])
            acc_ref[:] = acc_ref[:] + jnp.dot(
                ds.astype(k.dtype), k, preferred_element_type=jnp.float32
            ) * scale

        if causal:
            @pl.when(kj * block_k <= qi * block_q + block_q - 1)
            def _run():
                body()
        else:
            body()

        @pl.when(kj == nk - 1)
        def _done():
            dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)

    return kernel


def _dkv_kernel(scale, causal, block_q, block_k, seq_len, with_mask):
    def kernel(*refs):
        if with_mask:
            (q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref, pm_ref,
             dk_ref, dv_ref, dk_acc, dv_acc) = refs
        else:
            (q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref,
             dk_ref, dv_ref, dk_acc, dv_acc) = refs
            pm_ref = None
        kj = pl.program_id(1)
        qi = pl.program_id(2)
        nq = pl.num_programs(2)

        @pl.when(qi == 0)
        def _init():
            dk_acc[:] = jnp.zeros_like(dk_acc)
            dv_acc[:] = jnp.zeros_like(dv_acc)

        def body():
            q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
            s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
            q_ids = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_ids = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            mask = k_ids < seq_len
            if causal:
                mask = jnp.logical_and(mask, k_ids <= q_ids)
            if pm_ref is not None:
                mask = jnp.logical_and(mask, pm_ref[0] > 0)
            p = jnp.where(mask, jnp.exp(s - lse_ref[0]), 0.0)
            dv_acc[:] = dv_acc[:] + jnp.dot(
                p.T.astype(do.dtype), do, preferred_element_type=jnp.float32
            )
            dov = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
            ds = p * (dov - dd_ref[0])
            dk_acc[:] = dk_acc[:] + jnp.dot(
                ds.T.astype(q.dtype), q, preferred_element_type=jnp.float32
            ) * scale

        if causal:
            # q blocks strictly before this kv block contribute nothing
            @pl.when(qi * block_q + block_q - 1 >= kj * block_k)
            def _run():
                body()
        else:
            body()

        @pl.when(qi == nq - 1)
        def _done():
            dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
            dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)

    return kernel


# --------------------------------------------------------------------------- #
# custom_vjp wrapper
# --------------------------------------------------------------------------- #


def _pad_t(x, pad):
    return jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0))) if pad else x


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def flash_attention_diff(
    q: jax.Array,  # [B, H, T, d]
    k: jax.Array,
    v: jax.Array,
    padding_mask: Optional[jax.Array] = None,  # [B, T] 1=real
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    out, _ = _fwd(q, k, v, padding_mask, causal, block_q, block_k, interpret)
    return out


def _prep(q, T, block_q, block_k):
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    # pad to a multiple of BOTH block sizes, else the grid floor-division
    # silently drops trailing rows (review finding)
    pad = (-T) % math.lcm(block_q, block_k)
    return block_q, block_k, pad


def _fwd(q, k, v, padding_mask, causal, block_q, block_k, interpret):
    interpret = resolve_interpret(interpret)
    if pltpu is None:  # pragma: no cover
        raise RuntimeError("pallas tpu module unavailable")
    B, H, T, d = q.shape
    scale = 1.0 / math.sqrt(d)
    block_q, block_k, pad = _prep(q, T, block_q, block_k)
    Tp = T + pad
    qf = _pad_t(q, pad).reshape(B * H, Tp, d)
    kf = _pad_t(k, pad).reshape(B * H, Tp, d)
    vf = _pad_t(v, pad).reshape(B * H, Tp, d)
    with_mask = padding_mask is not None
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
    ]
    args = [qf, kf, vf]
    if with_mask:
        # mask rides lanes as [B, 1, Tp] / lse rides sublanes as
        # [bh, Tp, 1]: both satisfy Mosaic's last-two-dims block rule in
        # their natural broadcast orientation (no in-kernel transposes).
        # 2-D (rows, Tp) aux arrays with (1, block) blocks fail the TPU
        # lowering whenever rows > 1 — caught by the AOT harness
        # (benchmarking/tpu_aot_compile.py), invisible to interpret mode.
        mp = jnp.pad(padding_mask.astype(jnp.int32), ((0, 0), (0, pad)))
        mp = mp.reshape(B, 1, Tp)
        in_specs.append(
            pl.BlockSpec((1, 1, block_k), lambda b, i, j, H=H: (b // H, 0, j)))
        args.append(mp)
    grid = (B * H, Tp // block_q, Tp // block_k)
    out, lse = pl.pallas_call(
        _fwd_kernel(scale, causal, block_q, block_k, T, with_mask),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tp, d), q.dtype),
            jax.ShapeDtypeStruct((B * H, Tp, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    out4 = out.reshape(B, H, Tp, d)[:, :, :T, :]
    return out4, (q, k, v, padding_mask, out4, lse)


def _fwd_rule(q, k, v, padding_mask, causal, block_q, block_k, interpret):
    out, res = _fwd(q, k, v, padding_mask, causal, block_q, block_k, interpret)
    return out, res


def _bwd_rule(causal, block_q, block_k, interpret, res, do):
    q, k, v, padding_mask, out, lse = res
    interpret = resolve_interpret(interpret)
    B, H, T, d = q.shape
    scale = 1.0 / math.sqrt(d)
    block_q, block_k, pad = _prep(q, T, block_q, block_k)
    Tp = T + pad
    bh = B * H
    qf = _pad_t(q, pad).reshape(bh, Tp, d)
    kf = _pad_t(k, pad).reshape(bh, Tp, d)
    vf = _pad_t(v, pad).reshape(bh, Tp, d)
    dof = _pad_t(do, pad).reshape(bh, Tp, d)
    # D_i = rowsum(dO * O); lse already [bh, Tp, 1] (sublane-oriented)
    dd = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    dd = jnp.pad(dd, ((0, 0), (0, 0), (0, pad))).reshape(bh, Tp, 1)
    with_mask = padding_mask is not None
    mask_args = []
    if with_mask:
        mask_args = [jnp.pad(
            padding_mask.astype(jnp.int32), ((0, 0), (0, pad))
        ).reshape(B, 1, Tp)]

    common_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),  # q by qi
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),  # k by kj
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),  # v by kj
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),  # do by qi
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),  # lse by qi
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),  # dd by qi
    ]
    if with_mask:
        common_specs.append(
            pl.BlockSpec((1, 1, block_k), lambda b, i, j, H=H: (b // H, 0, j))
        )
    dq = pl.pallas_call(
        _dq_kernel(scale, causal, block_q, block_k, T, with_mask),
        grid=(bh, Tp // block_q, Tp // block_k),
        in_specs=common_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, Tp, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, dd, *mask_args)

    dkv_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
    ]
    if with_mask:
        dkv_specs.append(
            pl.BlockSpec((1, 1, block_k), lambda b, j, i, H=H: (b // H, 0, j))
        )
    dk, dv = pl.pallas_call(
        _dkv_kernel(scale, causal, block_q, block_k, T, with_mask),
        grid=(bh, Tp // block_k, Tp // block_q),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, Tp, d), k.dtype),
            jax.ShapeDtypeStruct((bh, Tp, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, dd, *mask_args)

    unpad = lambda x: x.reshape(B, H, Tp, d)[:, :, :T, :]  # noqa: E731
    return unpad(dq), unpad(dk), unpad(dv), None


flash_attention_diff.defvjp(_fwd_rule, _bwd_rule)
