"""Differentiable Pallas flash attention (custom VJP, FlashAttention-2 style
backward) — lets the fused kernel serve the TRAINING losses (GRPO/DPO forward-
backward), not just the no-grad passes.

Forward saves per-row logsumexp L; backward recomputes probabilities blockwise:
  D_i  = rowsum(dO_i * O_i)
  P_ij = exp(q_i k_j^T * scale - L_i)
  dV_j = sum_i P_ij^T dO_i
  dS   = P * (dO V^T - D)
  dQ_i = dS_ij K_j * scale        (grid: kv innermost, accumulate in VMEM)
  dK_j = dS_ij^T Q_i * scale      (grid: q innermost, accumulate in VMEM)

Causal masking mirrors the forward. Interpret mode on CPU for tests; native on
TPU. Supports an optional [B, T] padding mask like the forward kernel.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from agilerl_tpu.ops.kernel_mode import resolve_interpret

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None


# --------------------------------------------------------------------------- #
# Forward kernel that also emits L = m + log(l)
# --------------------------------------------------------------------------- #


def _fwd_kernel(scale, causal, block_q, block_k, seq_len, with_mask):
    def kernel(*refs):
        if with_mask:
            (q_ref, k_ref, v_ref, pm_ref, out_ref, lse_ref,
             m_ref, l_ref, acc_ref) = refs
        else:
            q_ref, k_ref, v_ref, out_ref, lse_ref, m_ref, l_ref, acc_ref = refs
            pm_ref = None
        qi = pl.program_id(1)
        kj = pl.program_id(2)
        nk = pl.num_programs(2)

        @pl.when(kj == 0)
        def _init():
            m_ref[:] = jnp.full_like(m_ref, -1e30)
            l_ref[:] = jnp.zeros_like(l_ref)
            acc_ref[:] = jnp.zeros_like(acc_ref)

        def body():
            q, k, v = q_ref[0], k_ref[0], v_ref[0]
            s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
            q_ids = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_ids = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            mask = k_ids < seq_len
            if causal:
                mask = jnp.logical_and(mask, k_ids <= q_ids)
            if pm_ref is not None:
                mask = jnp.logical_and(mask, pm_ref[0] > 0)
            s = jnp.where(mask, s, -1e30)
            m_old = m_ref[:]
            m_new = jnp.maximum(m_old, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m_old - m_new)
            l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
            acc_ref[:] = acc_ref[:] * alpha + jnp.dot(
                p.astype(v.dtype), v, preferred_element_type=jnp.float32
            )
            m_ref[:] = m_new

        if causal:
            @pl.when(kj * block_k <= qi * block_q + block_q - 1)
            def _run():
                body()
        else:
            body()

        @pl.when(kj == nk - 1)
        def _done():
            out_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(out_ref.dtype)
            lse_ref[0] = m_ref[:] + jnp.log(jnp.maximum(l_ref[:], 1e-30))

    return kernel


def _dq_kernel(scale, causal, block_q, block_k, seq_len, with_mask):
    def kernel(*refs):
        if with_mask:
            (q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref, pm_ref,
             dq_ref, acc_ref) = refs
        else:
            q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref, dq_ref, acc_ref = refs
            pm_ref = None
        qi = pl.program_id(1)
        kj = pl.program_id(2)
        nk = pl.num_programs(2)

        @pl.when(kj == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        def body():
            q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
            s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
            q_ids = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_ids = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            mask = k_ids < seq_len
            if causal:
                mask = jnp.logical_and(mask, k_ids <= q_ids)
            if pm_ref is not None:
                mask = jnp.logical_and(mask, pm_ref[0] > 0)
            p = jnp.where(mask, jnp.exp(s - lse_ref[0]), 0.0)
            dov = jnp.dot(do, v.T, preferred_element_type=jnp.float32)  # [BQ, BK]
            ds = p * (dov - dd_ref[0])
            acc_ref[:] = acc_ref[:] + jnp.dot(
                ds.astype(k.dtype), k, preferred_element_type=jnp.float32
            ) * scale

        if causal:
            @pl.when(kj * block_k <= qi * block_q + block_q - 1)
            def _run():
                body()
        else:
            body()

        @pl.when(kj == nk - 1)
        def _done():
            dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)

    return kernel


def _dkv_kernel(scale, causal, block_q, block_k, seq_len, with_mask):
    def kernel(*refs):
        if with_mask:
            (q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref, pm_ref,
             dk_ref, dv_ref, dk_acc, dv_acc) = refs
        else:
            (q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref,
             dk_ref, dv_ref, dk_acc, dv_acc) = refs
            pm_ref = None
        kj = pl.program_id(1)
        qi = pl.program_id(2)
        nq = pl.num_programs(2)

        @pl.when(qi == 0)
        def _init():
            dk_acc[:] = jnp.zeros_like(dk_acc)
            dv_acc[:] = jnp.zeros_like(dv_acc)

        def body():
            q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
            s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
            q_ids = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_ids = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            mask = k_ids < seq_len
            if causal:
                mask = jnp.logical_and(mask, k_ids <= q_ids)
            if pm_ref is not None:
                mask = jnp.logical_and(mask, pm_ref[0] > 0)
            p = jnp.where(mask, jnp.exp(s - lse_ref[0]), 0.0)
            dv_acc[:] = dv_acc[:] + jnp.dot(
                p.T.astype(do.dtype), do, preferred_element_type=jnp.float32
            )
            dov = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
            ds = p * (dov - dd_ref[0])
            dk_acc[:] = dk_acc[:] + jnp.dot(
                ds.T.astype(q.dtype), q, preferred_element_type=jnp.float32
            ) * scale

        if causal:
            # q blocks strictly before this kv block contribute nothing
            @pl.when(qi * block_q + block_q - 1 >= kj * block_k)
            def _run():
                body()
        else:
            body()

        @pl.when(qi == nq - 1)
        def _done():
            dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
            dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)

    return kernel


# --------------------------------------------------------------------------- #
# custom_vjp wrapper
# --------------------------------------------------------------------------- #


def _pad_t(x, pad):
    return jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0))) if pad else x


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def flash_attention_diff(
    q: jax.Array,  # [B, H, T, d]
    k: jax.Array,
    v: jax.Array,
    padding_mask: Optional[jax.Array] = None,  # [B, T] 1=real
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
    spmd: bool = True,
) -> jax.Array:
    """``spmd=True`` (default) routes through the custom_partitioning
    wrappers so plain-GSPMD callers shard over (batch, heads) at runtime;
    pass ``spmd=False`` when calling from inside an explicit shard_map
    (e.g. model.py's ``flash_shard_axes`` path — the AOT-compatible route:
    custom_partitioning needs a runtime python callback that compile-only
    PJRT clients don't host, 'Custom emitter for CustomSPMDPartitioning
    not found')."""
    out, _ = _fwd_rule(q, k, v, padding_mask, causal, block_q, block_k,
                       interpret, spmd)
    return out


def _prep(q, T, block_q, block_k):
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    # pad to a multiple of BOTH block sizes, else the grid floor-division
    # silently drops trailing rows (review finding)
    pad = (-T) % math.lcm(block_q, block_k)
    return block_q, block_k, pad


def _fwd(q, k, v, padding_mask, causal, block_q, block_k, interpret):
    interpret = resolve_interpret(interpret)
    if pltpu is None:  # pragma: no cover
        raise RuntimeError("pallas tpu module unavailable")
    B, H, T, d = q.shape
    scale = 1.0 / math.sqrt(d)
    block_q, block_k, pad = _prep(q, T, block_q, block_k)
    Tp = T + pad
    qf = _pad_t(q, pad).reshape(B * H, Tp, d)
    kf = _pad_t(k, pad).reshape(B * H, Tp, d)
    vf = _pad_t(v, pad).reshape(B * H, Tp, d)
    with_mask = padding_mask is not None
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
    ]
    args = [qf, kf, vf]
    if with_mask:
        # mask rides lanes as [B, 1, Tp] / lse rides sublanes as
        # [bh, Tp, 1]: both satisfy Mosaic's last-two-dims block rule in
        # their natural broadcast orientation (no in-kernel transposes).
        # 2-D (rows, Tp) aux arrays with (1, block) blocks fail the TPU
        # lowering whenever rows > 1 — caught by the AOT harness
        # (benchmarking/tpu_aot_compile.py), invisible to interpret mode.
        mp = jnp.pad(padding_mask.astype(jnp.int32), ((0, 0), (0, pad)))
        mp = mp.reshape(B, 1, Tp)
        in_specs.append(
            pl.BlockSpec((1, 1, block_k), lambda b, i, j, H=H: (b // H, 0, j)))
        args.append(mp)
    grid = (B * H, Tp // block_q, Tp // block_k)
    out, lse = pl.pallas_call(
        _fwd_kernel(scale, causal, block_q, block_k, T, with_mask),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tp, d), q.dtype),
            jax.ShapeDtypeStruct((B * H, Tp, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    out4 = out.reshape(B, H, Tp, d)[:, :, :T, :]
    # lse rides as [B, H, Tp, 1] so the GSPMD partitioning rule can map its
    # leading dims 1:1 onto q's (batch, heads) axes
    return out4, lse.reshape(B, H, Tp, 1)


# --------------------------------------------------------------------------- #
# GSPMD partitioning (custom_partitioning + Shardy sharding rules)
#
# Mosaic kernels cannot be auto-partitioned ("wrap the call in a shard_map" —
# surfaced by benchmarking/tpu_aot_compile.py's grpo_7b_flash target). The
# TPU-native answer for the production fsdp x tp mesh: attention is
# embarrassingly parallel over (batch, heads) once GQA heads are repeated, so
# we declare exactly that — b and h shard freely, sequence and head_dim are
# need_replication factors (Shardy inserts the all-gathers if a caller hands
# in sp-sharded operands) — and lower the SAME pallas kernels per shard.
# --------------------------------------------------------------------------- #


def _keep_dims(mesh, info, keep):
    """NamedSharding that keeps only `keep` dims of an operand's sharding."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    ndim = len(info.shape)
    spec = getattr(info.sharding, "spec", None)
    parts = list(spec) if spec is not None else []
    parts = parts + [None] * (ndim - len(parts))
    parts = [p if i in keep else None for i, p in enumerate(parts)]
    return NamedSharding(mesh, P(*parts))


def _infer_from_q(mesh, arg_infos, result_infos):
    """Pre-Shardy (``infer_sharding_from_operands``) result inference: every
    result keeps q's (batch, heads) sharding — the same contract the Shardy
    rule declares, spelled for the legacy GSPMD pipeline."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    q = arg_infos[0]
    qspec = list(getattr(q.sharding, "spec", None) or [])
    qspec = qspec + [None] * (4 - len(qspec))
    results = (result_infos if isinstance(result_infos, (tuple, list))
               else (result_infos,))
    out = tuple(
        NamedSharding(mesh, P(qspec[0], qspec[1],
                              *([None] * (len(r.shape) - 2))))
        for r in results
    )
    return out if isinstance(result_infos, (tuple, list)) else out[0]


@functools.lru_cache(maxsize=None)
def _partitioned_fwd(causal, block_q, block_k, interpret, with_mask):
    from jax.experimental.custom_partitioning import custom_partitioning

    def impl(*args):
        q, k, v = args[:3]
        mask = args[3] if with_mask else None
        return _fwd(q, k, v, mask, causal, block_q, block_k, interpret)

    fn = custom_partitioning(impl)
    arg_keep = [(0, 1), (0, 1), (0, 1)] + ([(0,)] if with_mask else [])
    res_keep = [(0, 1), (0, 1)]

    def partition(mesh, arg_infos, result_infos):
        arg_sh = tuple(_keep_dims(mesh, a, k)
                       for a, k in zip(arg_infos, arg_keep))
        res_sh = tuple(_keep_dims(mesh, r, k)
                       for r, k in zip(result_infos, res_keep))
        return mesh, impl, res_sh, arg_sh

    rule = ("b h t d, b h t d, b h t d" + (", b t" if with_mask else "")
            + " -> b h t d, b h p u")
    from agilerl_tpu.compat import def_partition

    def_partition(fn, partition=partition, sharding_rule=rule,
                  need_replication_factors=("t", "d", "p", "u"),
                  infer_sharding_from_operands=_infer_from_q)
    return fn


@functools.lru_cache(maxsize=None)
def _partitioned_bwd(causal, block_q, block_k, interpret, with_mask):
    from jax.experimental.custom_partitioning import custom_partitioning

    def impl(*args):
        q, k, v, do, out, lse = args[:6]
        mask = args[6] if with_mask else None
        return _bwd_arrays(q, k, v, do, out, lse, mask, causal, block_q,
                           block_k, interpret)

    fn = custom_partitioning(impl)
    arg_keep = [(0, 1)] * 6 + ([(0,)] if with_mask else [])
    res_keep = [(0, 1)] * 3

    def partition(mesh, arg_infos, result_infos):
        arg_sh = tuple(_keep_dims(mesh, a, k)
                       for a, k in zip(arg_infos, arg_keep))
        res_sh = tuple(_keep_dims(mesh, r, k)
                       for r, k in zip(result_infos, res_keep))
        return mesh, impl, res_sh, arg_sh

    rule = ("b h t d, b h t d, b h t d, b h t d, b h t d, b h p u"
            + (", b t" if with_mask else "")
            + " -> b h t d, b h t d, b h t d")
    from agilerl_tpu.compat import def_partition

    def_partition(fn, partition=partition, sharding_rule=rule,
                  need_replication_factors=("t", "d", "p", "u"),
                  infer_sharding_from_operands=_infer_from_q)
    return fn


def _fwd_rule(q, k, v, padding_mask, causal, block_q, block_k, interpret,
              spmd=True):
    concrete = resolve_interpret(interpret)
    with_mask = padding_mask is not None
    if spmd:
        args = (q, k, v) + ((padding_mask,) if with_mask else ())
        out, lse = _partitioned_fwd(causal, block_q, block_k, concrete,
                                    with_mask)(*args)
    else:
        out, lse = _fwd(q, k, v, padding_mask, causal, block_q, block_k,
                        concrete)
    return out, (q, k, v, padding_mask, out, lse)


def _bwd_rule(causal, block_q, block_k, interpret, spmd, res, do):
    q, k, v, padding_mask, out, lse = res
    concrete = resolve_interpret(interpret)
    with_mask = padding_mask is not None
    if spmd:
        args = (q, k, v, do, out, lse) + ((padding_mask,) if with_mask else ())
        dq, dk, dv = _partitioned_bwd(causal, block_q, block_k, concrete,
                                      with_mask)(*args)
    else:
        dq, dk, dv = _bwd_arrays(q, k, v, do, out, lse, padding_mask,
                                 causal, block_q, block_k, concrete)
    return dq, dk, dv, None


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def flash_attention_with_lse(
    q: jax.Array,  # [B, H, T, d]
    k: jax.Array,
    v: jax.Array,
    padding_mask: Optional[jax.Array] = None,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Flash attention returning (out [B,H,T,d], lse [B,H,T]) — BOTH
    differentiable. The lse output is what lets callers merge partial
    attentions online (ring attention's per-block path, ops/ring_attention
    .py): o = sum_b o_b * exp(lse_b - lse_total). The backward folds the
    lse cotangent into the FlashAttention-2 dd term: dS gains p * dlse,
    and since dS = p * (dOV^T - dd), that is exactly dd -> dd - dlse.
    Direct (non-custom_partitioning) kernels: built for use INSIDE
    shard_map."""
    out, lse4 = _fwd(q, k, v, padding_mask, causal, block_q, block_k,
                     resolve_interpret(interpret))
    T = q.shape[2]
    return out, lse4[:, :, :T, 0]


def _with_lse_fwd(q, k, v, padding_mask, causal, block_q, block_k, interpret):
    out, lse4 = _fwd(q, k, v, padding_mask, causal, block_q, block_k,
                     resolve_interpret(interpret))
    T = q.shape[2]
    return (out, lse4[:, :, :T, 0]), (q, k, v, padding_mask, out, lse4)


def _with_lse_bwd(causal, block_q, block_k, interpret, res, cts):
    q, k, v, padding_mask, out, lse4 = res
    do, dlse = cts
    dq, dk, dv = _bwd_arrays(q, k, v, do, out, lse4, padding_mask, causal,
                             block_q, block_k, resolve_interpret(interpret),
                             dlse=dlse)
    return dq, dk, dv, None


flash_attention_with_lse.defvjp(_with_lse_fwd, _with_lse_bwd)


def _bwd_arrays(q, k, v, do, out, lse, padding_mask, causal, block_q,
                block_k, interpret, dlse=None):
    interpret = resolve_interpret(interpret)
    B, H, T, d = q.shape
    scale = 1.0 / math.sqrt(d)
    block_q, block_k, pad = _prep(q, T, block_q, block_k)
    Tp = T + pad
    bh = B * H
    qf = _pad_t(q, pad).reshape(bh, Tp, d)
    kf = _pad_t(k, pad).reshape(bh, Tp, d)
    vf = _pad_t(v, pad).reshape(bh, Tp, d)
    dof = _pad_t(do, pad).reshape(bh, Tp, d)
    lse = lse.reshape(bh, Tp, 1)  # arrives [B, H, Tp, 1] (partition layout)
    # D_i = rowsum(dO * O); dd sublane-oriented like lse. An lse cotangent
    # (flash_attention_with_lse) enters as dS += p * dlse == dd -= dlse.
    dd = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    if dlse is not None:
        dd = dd - dlse.astype(jnp.float32)
    dd = jnp.pad(dd, ((0, 0), (0, 0), (0, pad))).reshape(bh, Tp, 1)
    with_mask = padding_mask is not None
    mask_args = []
    if with_mask:
        mask_args = [jnp.pad(
            padding_mask.astype(jnp.int32), ((0, 0), (0, pad))
        ).reshape(B, 1, Tp)]

    common_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),  # q by qi
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),  # k by kj
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),  # v by kj
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),  # do by qi
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),  # lse by qi
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),  # dd by qi
    ]
    if with_mask:
        common_specs.append(
            pl.BlockSpec((1, 1, block_k), lambda b, i, j, H=H: (b // H, 0, j))
        )
    dq = pl.pallas_call(
        _dq_kernel(scale, causal, block_q, block_k, T, with_mask),
        grid=(bh, Tp // block_q, Tp // block_k),
        in_specs=common_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, Tp, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, dd, *mask_args)

    dkv_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
    ]
    if with_mask:
        dkv_specs.append(
            pl.BlockSpec((1, 1, block_k), lambda b, j, i, H=H: (b // H, 0, j))
        )
    dk, dv = pl.pallas_call(
        _dkv_kernel(scale, causal, block_q, block_k, T, with_mask),
        grid=(bh, Tp // block_k, Tp // block_q),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, Tp, d), k.dtype),
            jax.ShapeDtypeStruct((bh, Tp, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, dd, *mask_args)

    unpad = lambda x: x.reshape(B, H, Tp, d)[:, :, :T, :]  # noqa: E731
    return unpad(dq), unpad(dk), unpad(dv)


flash_attention_diff.defvjp(_fwd_rule, _bwd_rule)
