"""Pallas flash attention for TPU (parity goal: replace vLLM paged/flash CUDA
attention, SURVEY.md §2.9, for the in-tree generate/prefill path; long-sequence
scaling across chips is ops/ring_attention.py).

Blocked online-softmax attention: grid = (batch*heads, q blocks, kv blocks),
kv innermost so the (m, l, acc) accumulators live in VMEM scratch across kv
iterations. Causal masking by block index; [BQ, d] x [d, BK] matmuls on the MXU.
On CPU the kernel runs in pallas interpret mode (tests); TPU compiles natively.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from agilerl_tpu.ops.kernel_mode import resolve_interpret

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None


def _make_kernel(
    scale: float, causal: bool, block_q: int, block_k: int, seq_len: int,
    with_mask: bool,
):
    def kernel(*refs):
        if with_mask:
            q_ref, k_ref, v_ref, mask_ref, out_ref, m_ref, l_ref, acc_ref = refs
        else:
            q_ref, k_ref, v_ref, out_ref, m_ref, l_ref, acc_ref = refs
            mask_ref = None
        qi = pl.program_id(1)
        kj = pl.program_id(2)
        nk = pl.num_programs(2)

        @pl.when(kj == 0)
        def _init():
            m_ref[:] = jnp.full_like(m_ref, -1e30)
            l_ref[:] = jnp.zeros_like(l_ref)
            acc_ref[:] = jnp.zeros_like(acc_ref)

        def body():
            q = q_ref[0]  # [BQ, d]
            k = k_ref[0]  # [BK, d]
            v = v_ref[0]
            scores = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale

            q_ids = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, scores.shape, 0
            )
            k_ids = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, scores.shape, 1
            )
            mask = k_ids < seq_len
            if causal:
                mask = jnp.logical_and(mask, k_ids <= q_ids)
            if mask_ref is not None:
                # padding mask for this kv block: [1, BK] -> broadcast rows
                mask = jnp.logical_and(mask, mask_ref[0] > 0)
            scores = jnp.where(mask, scores, -1e30)

            m_old = m_ref[:]
            m_new = jnp.maximum(m_old, jnp.max(scores, axis=1, keepdims=True))
            p = jnp.exp(scores - m_new)
            alpha = jnp.exp(m_old - m_new)
            l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
            acc_ref[:] = acc_ref[:] * alpha + jnp.dot(
                p.astype(v.dtype), v, preferred_element_type=jnp.float32
            )
            m_ref[:] = m_new

        if causal:
            # skip kv blocks entirely in the future of this q block
            @pl.when(kj * block_k <= qi * block_q + block_q - 1)
            def _run():
                body()
        else:
            body()

        @pl.when(kj == nk - 1)
        def _finish():
            out_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(out_ref.dtype)

    return kernel


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def flash_attention(
    q: jax.Array,  # [B, H, T, d]
    k: jax.Array,
    v: jax.Array,
    padding_mask: Optional[jax.Array] = None,  # [B, T] 1=real token
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    interpret = resolve_interpret(interpret)
    B, H, T, d = q.shape
    scale = 1.0 / math.sqrt(d)
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    pad_t = (-T) % math.lcm(block_q, block_k)
    if pad_t:
        qp = jnp.pad(q, ((0, 0), (0, 0), (0, pad_t), (0, 0)))
        kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad_t), (0, 0)))
        vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad_t), (0, 0)))
    else:
        qp, kp, vp = q, k, v
    Tp = T + pad_t
    bh = B * H
    qf = qp.reshape(bh, Tp, d)
    kf = kp.reshape(bh, Tp, d)
    vf = vp.reshape(bh, Tp, d)

    if pltpu is None:  # pragma: no cover
        raise RuntimeError("pallas tpu module unavailable")
    grid = (bh, Tp // block_q, Tp // block_k)
    with_mask = padding_mask is not None
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
    ]
    args = [qf, kf, vf]
    if with_mask:
        # [B, 1, Tp] with a (1, 1, block_k) block: the mask rides the lane
        # dimension (its natural broadcast orientation against [BQ, BK]
        # scores) AND satisfies Mosaic's block rule — the last two block
        # dims (1, block_k) match/divide the array dims (1, Tp). A 2-D
        # (B, Tp) array with (1, block_k) blocks is rejected by the TPU
        # lowering whenever B > 1 (caught by the AOT compile harness,
        # benchmarking/tpu_aot_compile.py; interpret mode never sees it).
        mp = jnp.pad(padding_mask.astype(jnp.int32), ((0, 0), (0, pad_t)))
        mp = mp.reshape(B, 1, Tp)
        in_specs.append(
            pl.BlockSpec((1, 1, block_k), lambda b, i, j, H=H: (b // H, 0, j))
        )
        args.append(mp)
    out = pl.pallas_call(
        _make_kernel(scale, causal, block_q, block_k, T, with_mask),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, Tp, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return out.reshape(B, H, Tp, d)[:, :, :T, :]
