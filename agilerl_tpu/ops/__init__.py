from agilerl_tpu.ops.flash_attention import flash_attention
from agilerl_tpu.ops.fused_loss import fused_token_logprob
from agilerl_tpu.ops.ring_attention import make_ring_attention, ring_attention

__all__ = ["flash_attention", "fused_token_logprob", "ring_attention", "make_ring_attention"]
