"""TPU Pallas kernels + the gate deciding when the framework uses them."""

import os

import jax


def pallas_enabled() -> bool:
    """True when the hot paths should route through the Pallas kernels:
    on the TPU backend, unless AGILERL_TPU_DISABLE_PALLAS is set (safety
    valve: some remote-compile services cannot build Mosaic kernels — the
    XLA fallback paths are numerically identical, just less fused)."""
    if os.environ.get("AGILERL_TPU_DISABLE_PALLAS"):
        return False
    return jax.default_backend() == "tpu"


from agilerl_tpu.ops.flash_attention import flash_attention  # noqa: E402
from agilerl_tpu.ops.fused_loss import fused_token_logprob
from agilerl_tpu.ops.ring_attention import make_ring_attention, ring_attention

__all__ = ["flash_attention", "fused_token_logprob", "ring_attention",
           "make_ring_attention", "pallas_enabled"]
