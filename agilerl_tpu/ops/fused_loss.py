"""Pallas fused chunked lm-head + log-softmax kernel — the Liger-kernel
replacement (parity: liger Triton fused GRPO/DPO/CE losses used at
agilerl/algorithms/grpo.py:558, dpo.py:409, and the chunked logprob path
_memory_efficient_logits, core/base.py:2937).

Computes per-token log p(target) WITHOUT materialising the [N, V] logits: the
grid walks vocab chunks innermost, keeping an online (max, sum-exp,
chosen-logit) accumulator in VMEM scratch; each chunk is one [BN, D] x [D, BV]
matmul on the MXU.

Forward-only by design: it accelerates the no-grad logprob passes (GRPO's
old/reference logprobs are half the learn-step FLOPs); the differentiable path
stays on the XLA-chunked implementation (llm/model.token_logprobs). On CPU the
kernel runs in pallas interpret mode (how the tests exercise it); on TPU it
compiles natively.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None


def _make_kernel(vocab_size: int, inv_temp: float):
    def kernel(hidden_ref, head_ref, target_ref, out_ref, m_ref, s_ref, c_ref):
        j = pl.program_id(1)
        nv = pl.num_programs(1)

        @pl.when(j == 0)
        def _init():
            m_ref[:] = jnp.full_like(m_ref, -1e30)
            s_ref[:] = jnp.zeros_like(s_ref)
            c_ref[:] = jnp.zeros_like(c_ref)

        h = hidden_ref[:]  # [BN, D]
        w = head_ref[:]  # [D, BV]
        logits = jnp.dot(h, w, preferred_element_type=jnp.float32) * inv_temp

        bn, bv = logits.shape
        cols = j * bv + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
        valid = cols < vocab_size  # mask padded vocab columns
        logits = jnp.where(valid, logits, -1e30)

        targets = target_ref[:]  # [BN, 1]
        hit = cols == targets
        c_ref[:] = c_ref[:] + jnp.sum(
            jnp.where(hit, logits, 0.0), axis=1, keepdims=True
        )

        m_old = m_ref[:]
        m_new = jnp.maximum(m_old, jnp.max(logits, axis=1, keepdims=True))
        s_ref[:] = s_ref[:] * jnp.exp(m_old - m_new) + jnp.sum(
            jnp.exp(logits - m_new), axis=1, keepdims=True
        )
        m_ref[:] = m_new

        @pl.when(j == nv - 1)
        def _finish():
            out_ref[:] = c_ref[:] - m_ref[:] - jnp.log(s_ref[:])

    return kernel


@functools.partial(
    jax.jit, static_argnames=("temperature", "block_n", "block_v", "interpret")
)
def fused_token_logprob(
    hidden: jax.Array,  # [N, D]
    head: jax.Array,  # [D, V]
    targets: jax.Array,  # [N] int
    temperature: float = 1.0,
    block_n: int = 256,
    block_v: int = 1024,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Per-row log softmax(hidden @ head / T)[target]. Returns [N] float32."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    N, D = hidden.shape
    V = head.shape[1]
    block_n = min(block_n, max(8, N))
    block_v = min(block_v, V + (-V) % 128)
    pad_n = (-N) % block_n
    pad_v = (-V) % block_v
    h = jnp.pad(hidden.astype(jnp.float32), ((0, pad_n), (0, 0)))
    w = jnp.pad(head.astype(jnp.float32), ((0, 0), (0, pad_v)))
    t = jnp.pad(targets.astype(jnp.int32), (0, pad_n))[:, None]

    grid = (h.shape[0] // block_n, w.shape[1] // block_v)
    if pltpu is None:  # pragma: no cover - CPU wheels without pltpu
        raise RuntimeError("pallas tpu module unavailable")
    scratch = [pltpu.VMEM((block_n, 1), jnp.float32) for _ in range(3)]

    out = pl.pallas_call(
        _make_kernel(V, 1.0 / temperature),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, D), lambda i, j: (i, 0)),
            pl.BlockSpec((D, block_v), lambda i, j: (0, j)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((h.shape[0], 1), jnp.float32),
        scratch_shapes=scratch,
        interpret=interpret,
    )(h, w, t)
    return out[:N, 0]


def reference_token_logprob(hidden, head, targets, temperature: float = 1.0):
    """Dense reference for tests."""
    logits = (hidden.astype(jnp.float32) @ head.astype(jnp.float32)) / temperature
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(logp, targets[:, None].astype(jnp.int32), axis=1)[:, 0]
