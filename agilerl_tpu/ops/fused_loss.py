"""Pallas fused chunked lm-head + log-softmax kernel — the Liger-kernel
replacement (parity: liger Triton fused GRPO/DPO/CE losses used at
agilerl/algorithms/grpo.py:558, dpo.py:409, and the chunked logprob path
_memory_efficient_logits, core/base.py:2937).

Computes per-token log p(target) WITHOUT materialising the [N, V] logits: the
grid walks vocab chunks innermost, keeping an online (max, sum-exp,
chosen-logit) accumulator in VMEM scratch; each chunk is one [BN, D] x [D, BV]
matmul on the MXU.

``fused_token_logprob`` is the forward kernel; ``fused_token_logprob_diff``
wraps it in a custom VJP (the Liger parity point: liger's losses are
differentiable) whose backward pass RECOMPUTES logits per vocab chunk from the
saved (hidden, head, lse) residuals — two more Pallas kernels (dH accumulates
over vocab blocks, dW over row blocks), so the [N, V] logits never materialise
in either direction. On CPU the kernels run in pallas interpret mode (how the
tests exercise them); on TPU they compile natively.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from agilerl_tpu.ops.kernel_mode import resolve_interpret

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None


def _make_kernel(vocab_size: int, inv_temp: float):
    def kernel(hidden_ref, head_ref, target_ref, out_ref, lse_ref, m_ref, s_ref, c_ref):
        j = pl.program_id(1)
        nv = pl.num_programs(1)

        @pl.when(j == 0)
        def _init():
            m_ref[:] = jnp.full_like(m_ref, -1e30)
            s_ref[:] = jnp.zeros_like(s_ref)
            c_ref[:] = jnp.zeros_like(c_ref)

        h = hidden_ref[:]  # [BN, D]
        w = head_ref[:]  # [D, BV]
        logits = jnp.dot(h, w, preferred_element_type=jnp.float32) * inv_temp

        bn, bv = logits.shape
        cols = j * bv + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
        valid = cols < vocab_size  # mask padded vocab columns
        logits = jnp.where(valid, logits, -1e30)

        targets = target_ref[:]  # [BN, 1]
        hit = cols == targets
        c_ref[:] = c_ref[:] + jnp.sum(
            jnp.where(hit, logits, 0.0), axis=1, keepdims=True
        )

        m_old = m_ref[:]
        m_new = jnp.maximum(m_old, jnp.max(logits, axis=1, keepdims=True))
        s_ref[:] = s_ref[:] * jnp.exp(m_old - m_new) + jnp.sum(
            jnp.exp(logits - m_new), axis=1, keepdims=True
        )
        m_ref[:] = m_new

        @pl.when(j == nv - 1)
        def _finish():
            lse = m_ref[:] + jnp.log(s_ref[:])
            out_ref[:] = c_ref[:] - lse
            lse_ref[:] = lse

    return kernel


def _bwd_coef(hidden_ref, head_ref, target_ref, lse_ref, g_ref, j, inv_temp,
              vocab_size):
    """Recompute softmax probs for one (row-block, vocab-block) tile and return
    the shared bwd coefficient g * (onehot(target) - p)."""
    h = hidden_ref[:]  # [BN, D]
    w = head_ref[:]  # [D, BV]
    logits = jnp.dot(h, w, preferred_element_type=jnp.float32) * inv_temp
    bn, bv = logits.shape
    cols = j * bv + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    valid = cols < vocab_size
    p = jnp.where(valid, jnp.exp(logits - lse_ref[:]), 0.0)
    hit = (cols == target_ref[:]) & valid
    return (hit.astype(jnp.float32) - p) * g_ref[:]  # [BN, BV]


def _make_dh_kernel(vocab_size: int, inv_temp: float):
    """grid (i, j), j innermost: accumulate dH_i over vocab blocks."""

    def kernel(hidden_ref, head_ref, target_ref, lse_ref, g_ref, dh_ref, acc_ref):
        j = pl.program_id(1)
        nv = pl.num_programs(1)

        @pl.when(j == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        coef = _bwd_coef(hidden_ref, head_ref, target_ref, lse_ref, g_ref, j,
                         inv_temp, vocab_size)
        acc_ref[:] = acc_ref[:] + jnp.dot(
            coef, head_ref[:].T, preferred_element_type=jnp.float32
        ) * inv_temp

        @pl.when(j == nv - 1)
        def _finish():
            dh_ref[:] = acc_ref[:]

    return kernel


def _make_dw_kernel(vocab_size: int, inv_temp: float):
    """grid (j, i), i innermost: accumulate dW_j over row blocks."""

    def kernel(hidden_ref, head_ref, target_ref, lse_ref, g_ref, dw_ref, acc_ref):
        i = pl.program_id(1)
        ni = pl.num_programs(1)
        j = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        coef = _bwd_coef(hidden_ref, head_ref, target_ref, lse_ref, g_ref, j,
                         inv_temp, vocab_size)
        acc_ref[:] = acc_ref[:] + jnp.dot(
            hidden_ref[:].T, coef, preferred_element_type=jnp.float32
        ) * inv_temp

        @pl.when(i == ni - 1)
        def _finish():
            dw_ref[:] = acc_ref[:]

    return kernel


# Scoped VMEM budget for one double-buffered grid step. The hardware limit
# is 16 MiB (XLA's scoped-vmem cap for custom calls — the AOT harness
# surfaced a 32.9 MiB allocation at llama3-8b dims, RESOURCE_EXHAUSTED);
# 11 MiB leaves slack for the [BN, BV] f32 softmax intermediates.
_VMEM_BUDGET = 11 << 20


def _fit_blocks(block_n, block_v, D, isz_h, isz_w, kind):
    """Pick the largest (block_n, block_v) tile whose grid-step VMEM
    footprint fits: double-buffered operand blocks (each in its OWN input
    dtype — an f32 head over bf16 hidden must not be undercounted) plus the
    kernel's f32 accumulator/output blocks (dh: [BN, D]; dw: [D, BV]).

    Candidates are Mosaic-aligned (sublane blocks snap to multiples of 8
    with floor 8, lane blocks to multiples of 128 with floor 128 — naive
    halving can land on 96-lane or 6-sublane blocks the TPU lowering
    rejects), and the search maximises tile area instead of shrinking one
    dimension to its floor first (for dh the f32 accumulator scales with
    block_n, so grinding block_v down buys nothing), tie-breaking toward a
    wider lane dimension."""

    def est(bn, bv):
        ins = 2 * (bn * D * isz_h + D * bv * isz_w)
        if kind == "dh":
            return ins + 4 * bn * D * 3  # f32 acc + double-buffered out
        if kind == "dw":
            return ins + 4 * D * bv * 3
        return ins

    def candidates(top, align, floor):
        out, v = [top], top
        while v > floor:
            v = max(floor, (v // 2) // align * align)
            out.append(v)
        return out

    best = None
    for bn in candidates(block_n, 8, 8):
        for bv in candidates(block_v, 128, 128):
            if est(bn, bv) <= _VMEM_BUDGET:
                key = (bn * bv, bv)
                if best is None or key > best[0]:
                    best = (key, bn, bv)
    if best is None:  # nothing fits — floor blocks are the best effort
        return min(block_n, 8), min(block_v, 128)
    return best[1], best[2]


def _pad_inputs(hidden, head, targets, block_n, block_v, kind="fwd"):
    """Pad to block multiples WITHOUT changing dtype: the MXU consumes bf16
    natively (f32 accumulation via preferred_element_type), and upcasting
    the [D, BV] head block to f32 doubled its VMEM footprint — the direct
    cause of the scoped-vmem overflow at real vocab dims."""
    N, D = hidden.shape
    V = head.shape[1]
    block_n = min(block_n, max(8, N))
    block_v = min(block_v, V + (-V) % 128)
    block_n, block_v = _fit_blocks(
        block_n, block_v, D, hidden.dtype.itemsize, head.dtype.itemsize,
        kind)
    pad_n = (-N) % block_n
    pad_v = (-V) % block_v
    h = jnp.pad(hidden, ((0, pad_n), (0, 0)))
    w = jnp.pad(head, ((0, 0), (0, pad_v)))
    t = jnp.pad(targets.astype(jnp.int32), (0, pad_n))[:, None]
    return h, w, t, block_n, block_v


def _fwd_call(hidden, head, targets, temperature, block_n, block_v, interpret):
    interpret = resolve_interpret(interpret)
    N, D = hidden.shape
    V = head.shape[1]
    h, w, t, block_n, block_v = _pad_inputs(hidden, head, targets, block_n, block_v)
    grid = (h.shape[0] // block_n, w.shape[1] // block_v)
    if pltpu is None:  # pragma: no cover - CPU wheels without pltpu
        raise RuntimeError("pallas tpu module unavailable")
    scratch = [pltpu.VMEM((block_n, 1), jnp.float32) for _ in range(3)]

    out, lse = pl.pallas_call(
        _make_kernel(V, 1.0 / temperature),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, D), lambda i, j: (i, 0)),
            pl.BlockSpec((D, block_v), lambda i, j: (0, j)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((h.shape[0], 1), jnp.float32),
            jax.ShapeDtypeStruct((h.shape[0], 1), jnp.float32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(h, w, t)
    return out[:N, 0], lse[:N, 0]


@functools.partial(
    jax.jit, static_argnames=("temperature", "block_n", "block_v", "interpret")
)
def fused_token_logprob(
    hidden: jax.Array,  # [N, D]
    head: jax.Array,  # [D, V]
    targets: jax.Array,  # [N] int
    temperature: float = 1.0,
    block_n: int = 256,
    block_v: int = 1024,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Per-row log softmax(hidden @ head / T)[target]. Returns [N] float32.
    Forward-only entry point; use ``fused_token_logprob_diff`` inside losses."""
    return _fwd_call(hidden, head, targets, temperature, block_n, block_v,
                     interpret)[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def fused_token_logprob_diff(
    hidden: jax.Array,
    head: jax.Array,
    targets: jax.Array,
    temperature: float = 1.0,
    block_n: int = 256,
    block_v: int = 1024,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Differentiable fused per-token logprob (the Liger parity point: liger's
    fused GRPO/DPO/CE losses are differentiable, ref grpo.py:558, dpo.py:409).
    Backward recomputes logits per vocab chunk from (hidden, head, lse) — the
    [N, V] logits never materialise in either pass."""
    return _fwd_call(hidden, head, targets, temperature, block_n, block_v,
                     interpret)[0]


def _diff_fwd(hidden, head, targets, temperature, block_n, block_v, interpret):
    out, lse = _fwd_call(hidden, head, targets, temperature, block_n, block_v,
                         interpret)
    return out, (hidden, head, targets, lse)


def _diff_bwd(temperature, block_n, block_v, interpret, res, g):
    hidden, head, targets, lse = res
    interpret = resolve_interpret(interpret)
    N, D = hidden.shape
    V = head.shape[1]
    inv_temp = 1.0 / temperature

    def pad_aux(rows):
        # padded rows must contribute nothing: zero their upstream grad
        # (their recomputed p over the padded head is garbage otherwise)
        lse_p = jnp.pad(lse.astype(jnp.float32), (0, rows - N))[:, None]
        g_p = jnp.pad(g.astype(jnp.float32), (0, rows - N))[:, None]
        return lse_p, g_p

    # the two bwd kernels carry different f32 accumulator blocks (dh:
    # [BN, D], dw: [D, BV]) — fit their VMEM budgets independently
    h, w, t, bn_h, bv_h = _pad_inputs(hidden, head, targets,
                                      block_n, block_v, "dh")
    lse_p, g_p = pad_aux(h.shape[0])
    row_specs = [
        pl.BlockSpec((bn_h, D), lambda i, j: (i, 0)),
        pl.BlockSpec((D, bv_h), lambda i, j: (0, j)),
        pl.BlockSpec((bn_h, 1), lambda i, j: (i, 0)),
        pl.BlockSpec((bn_h, 1), lambda i, j: (i, 0)),
        pl.BlockSpec((bn_h, 1), lambda i, j: (i, 0)),
    ]
    dh = pl.pallas_call(
        _make_dh_kernel(V, inv_temp),
        grid=(h.shape[0] // bn_h, w.shape[1] // bv_h),
        in_specs=row_specs,
        out_specs=pl.BlockSpec((bn_h, D), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((h.shape[0], D), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bn_h, D), jnp.float32)],
        interpret=interpret,
    )(h, w, t, lse_p, g_p)

    h2, w2, t2, bn_w, bv_w = _pad_inputs(hidden, head, targets,
                                         block_n, block_v, "dw")
    if (bn_w, bv_w) != (bn_h, bv_h):
        lse_p, g_p = pad_aux(h2.shape[0])
    else:
        h2, w2, t2 = h, w, t
    col_specs = [
        pl.BlockSpec((bn_w, D), lambda j, i: (i, 0)),
        pl.BlockSpec((D, bv_w), lambda j, i: (0, j)),
        pl.BlockSpec((bn_w, 1), lambda j, i: (i, 0)),
        pl.BlockSpec((bn_w, 1), lambda j, i: (i, 0)),
        pl.BlockSpec((bn_w, 1), lambda j, i: (i, 0)),
    ]
    dw = pl.pallas_call(
        _make_dw_kernel(V, inv_temp),
        grid=(w2.shape[1] // bv_w, h2.shape[0] // bn_w),
        in_specs=col_specs,
        out_specs=pl.BlockSpec((D, bv_w), lambda j, i: (0, j)),
        out_shape=jax.ShapeDtypeStruct((D, w2.shape[1]), jnp.float32),
        scratch_shapes=[pltpu.VMEM((D, bv_w), jnp.float32)],
        interpret=interpret,
    )(h2, w2, t2, lse_p, g_p)

    dhidden = dh[:N].astype(hidden.dtype)
    dhead = dw[:, :V].astype(head.dtype)
    dtargets = np.zeros(targets.shape, jax.dtypes.float0)
    return dhidden, dhead, dtargets


fused_token_logprob_diff.defvjp(_diff_fwd, _diff_bwd)


def reference_token_logprob(hidden, head, targets, temperature: float = 1.0):
    """Dense reference for tests."""
    logits = (hidden.astype(jnp.float32) @ head.astype(jnp.float32)) / temperature
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(logp, targets[:, None].astype(jnp.int32), axis=1)[:, 0]
