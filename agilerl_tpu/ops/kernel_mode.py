"""Target-aware interpret-mode resolution for the Pallas kernels.

The kernels in this package take ``interpret: Optional[bool]``. Explicit
True/False always wins; ``None`` historically meant "interpret unless the
*default* backend is TPU". That heuristic is wrong for ahead-of-time
compilation: when lowering for a TPU *topology* (compile-only PJRT devices
from libtpu — no chip attached, ``jax.default_backend()`` is still ``cpu``),
the kernels must lower natively through Mosaic, not as interpret-mode HLO.

``native_kernels()`` is the override used by the AOT harness
(benchmarking/tpu_aot_compile.py) and any caller staging programs for a
device set that differs from the default backend:

    with native_kernels():
        compiled = jax.jit(step).lower(*abstract_args).compile()  # TPU topo

Sharp edge (documented, deliberate): the override is consulted at TRACE
time. A function traced under the context bakes the mode into that trace;
jit caches are keyed by the ``interpret`` argument the caller passed (often
``None``), not by the override. Mixing modes for the same static signature
in one process therefore requires fresh functions (what the AOT harness
does) or ``jax.clear_caches()``. Public entry points that jit internally
resolve the mode BEFORE entering jit, so their caches stay honest.
"""

from __future__ import annotations

import contextlib
from typing import Optional

import jax

# None = auto (default-backend heuristic); True = force native Mosaic
# lowering; False = force interpret mode.
_FORCE_NATIVE: Optional[bool] = None


def resolve_interpret(explicit: Optional[bool]) -> bool:
    """Resolve an ``interpret=`` argument to a concrete bool."""
    if explicit is not None:
        return bool(explicit)
    if _FORCE_NATIVE is not None:
        return not _FORCE_NATIVE
    return jax.default_backend() != "tpu"


# Compile-path kill switches honoured across the framework. ONE list so the
# bisection probes (benchmarking/grpo_safe_env.py) and every capture labeler
# (bench.py grpo mode, benchmarking/grpo_mfu_sweep.py) stay in lockstep — a
# switch added here is automatically reported by all of them.
KILL_SWITCH_ENV_VARS = (
    "AGILERL_TPU_DISABLE_PALLAS",
    "AGILERL_TPU_DISABLE_SCAN_LAYERS",
    "AGILERL_TPU_DISABLE_CHUNKED_DECODE",
)


def active_kill_switches():
    """Names of the compile-path kill switches set in this process."""
    import os

    return [k for k in KILL_SWITCH_ENV_VARS if os.environ.get(k)]


@contextlib.contextmanager
def native_kernels(enable: bool = True):
    """Force native (Mosaic) Pallas lowering while tracing/lowering inside
    the context — regardless of the default backend. ``enable=False`` forces
    interpret mode instead."""
    global _FORCE_NATIVE
    prev = _FORCE_NATIVE
    _FORCE_NATIVE = bool(enable)
    try:
        yield
    finally:
        _FORCE_NATIVE = prev
