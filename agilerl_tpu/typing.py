"""Type aliases and shared enums (parity: agilerl/typing.py, agilerl/protocols.py).

The reference defines runtime Protocol classes for torch modules; here the
contracts are lighter because modules are (static config, params-pytree) pairs
and algorithms are thin stateful shells around pure jitted functions.
"""

from __future__ import annotations

import enum
from typing import Any, Callable, Dict, Tuple, Union

import jax
import numpy as np

ArrayLike = Union[jax.Array, np.ndarray, float, int]
Params = Any  # pytree of jax.Array leaves
PyTree = Any
KeyArray = jax.Array
ObservationType = Union[jax.Array, np.ndarray, Dict[str, Any], Tuple[Any, ...]]
ExperiencesType = Dict[str, Any]
GymSpaceType = Any  # gymnasium.spaces.Space (kept Any to avoid hard import here)
ApplyFn = Callable[..., Any]


class MutationType(enum.Enum):
    """Classes of architecture mutation a module method can implement.

    Parity: agilerl/protocols.py:39 (MutationType LAYER/NODE/ACTIVATION).
    """

    LAYER = "layer"
    NODE = "node"
    ACTIVATION = "activation"


class MutationMethod:
    """Descriptor metadata attached by the @mutation decorator."""

    __slots__ = ("fn", "mutation_type", "shrink_params")

    def __init__(self, fn, mutation_type: MutationType, shrink_params: bool = False):
        self.fn = fn
        self.mutation_type = mutation_type
        self.shrink_params = shrink_params
