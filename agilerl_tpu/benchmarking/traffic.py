"""Million-user traffic harness: production-shaped load over the serving
fleet, deterministic and record/replay-able.

The fleet (``llm/fleet.ServingFleet``) had never been driven by anything
heavier than the CPU A/B traces (ROADMAP item 4). This module generates the
workload shapes the Orca/DistServe serving lineage measures against and
drives them through the fleet's ``submit()``/``step()`` surface:

- **Heavy-tail sizes** — prompt and output lengths are lognormal (clipped
  to the fleet's bucket grid): most requests are short, the tail is long —
  the mix that exercises continuous batching, paged-KV admission, and the
  decode-budget raggedness real chat traffic has.
- **Arrival processes** — open-loop inhomogeneous Poisson over a VIRTUAL
  time axis: ``steady`` (constant rate), ``diurnal`` (sinusoidal day
  curve), ``flash_crowd`` (a burst window multiplying the base rate —
  the thundering-herd case), ``prefix_skew`` (a fraction of requests share
  one system prompt — the prefix-cache/affinity case). Closed-loop mode
  (fixed concurrency, submit-on-completion) measures capacity instead of
  latency-under-load.
- **Determinism** — every draw flows through one ``np.random.Generator``
  derived via :func:`agilerl_tpu.utils.rng.derive_rng` (GX003-clean): the
  same seed yields the identical request trace, and a trace saved with
  :func:`save_trace` replays exactly (:func:`load_trace` round-trips
  token-for-token). Virtual time advances ``1/steps_per_s`` per fleet
  scheduler step, so the submit SCHEDULE — which requests arrive before
  which step, what the queue depth is when admission decides — is a pure
  function of the trace, not of host speed.
- **Degraded runs** — the driver consults a
  :class:`~agilerl_tpu.resilience.faults.FaultInjector` host-loss schedule
  at virtual-second boundaries (``kill_host_at={virtual_second:
  replica_id}``) and drives an optional
  :class:`~agilerl_tpu.llm.autoscale.AutoscalePolicy` every step, so one
  scenario run exercises replica kill under burst, SLO shedding, failover
  re-dispatch, and the autoscaler's graded reaction — the standing
  workload generator the SLO engine (``observability/slo.py``) scores.
"""

from __future__ import annotations

import dataclasses
import json
import math
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from agilerl_tpu.utils.rng import derive_rng

#: trace-file schema version (bump on layout changes)
TRACE_SCHEMA = 1


@dataclasses.dataclass
class TrafficRequest:
    """One synthetic request: WHEN it arrives (virtual seconds from
    scenario start), WHAT it asks (prompt tokens, output budget), and its
    provenance tags (trace index, shared-prefix membership)."""

    index: int
    arrival_s: float
    tokens: np.ndarray
    max_new: int
    shared_prefix: bool = False

    def to_record(self) -> Dict[str, Any]:
        return {
            "index": int(self.index),
            "arrival_s": float(self.arrival_s),
            "tokens": [int(t) for t in self.tokens],
            "max_new": int(self.max_new),
            "shared_prefix": bool(self.shared_prefix),
        }

    @classmethod
    def from_record(cls, rec: Dict[str, Any]) -> "TrafficRequest":
        return cls(
            index=int(rec["index"]),
            arrival_s=float(rec["arrival_s"]),
            tokens=np.asarray(rec["tokens"], np.int32),
            max_new=int(rec["max_new"]),
            shared_prefix=bool(rec.get("shared_prefix", False)),
        )


@dataclasses.dataclass
class ScenarioSpec:
    """Declarative description of one traffic scenario — everything
    :func:`generate_trace` needs, serializable for provenance.

    ``kind`` selects the arrival curve: ``steady`` | ``diurnal`` |
    ``flash_crowd`` | ``prefix_skew`` (prefix-skew arrivals are steady; the
    skew is in the PROMPTS: ``shared_fraction`` of requests start with one
    ``prefix_len``-token system prompt). Lengths are lognormal —
    ``exp(N(log_mean, sigma))`` — clipped to ``[min_*, max_*]``."""

    name: str
    kind: str = "steady"
    duration_s: float = 10.0
    base_rate_rps: float = 4.0
    vocab: int = 512
    # heavy-tail prompt lengths
    prompt_len_log_mean: float = 2.3      # exp(2.3) ~ 10 tokens median
    prompt_len_sigma: float = 0.7
    min_prompt: int = 4
    max_prompt: int = 28
    # heavy-tail output budgets
    out_len_log_mean: float = 2.0         # exp(2.0) ~ 7 tokens median
    out_len_sigma: float = 0.8
    min_new: int = 1
    max_new: int = 32
    # diurnal curve
    diurnal_amplitude: float = 0.8
    diurnal_period_s: float = 10.0
    # flash crowd
    burst_start_s: float = 4.0
    burst_duration_s: float = 2.0
    burst_x: float = 6.0
    # prefix skew
    shared_fraction: float = 0.7
    prefix_len: int = 12

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ScenarioSpec":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    # -- the arrival-rate curve -------------------------------------------
    def rate_at(self, t: float) -> float:
        """Requests/second at virtual time ``t`` (the inhomogeneous-Poisson
        intensity)."""
        base = float(self.base_rate_rps)
        if self.kind == "diurnal":
            # trough at t=0, peak mid-period: one "day" per period
            phase = 2.0 * math.pi * (t / self.diurnal_period_s)
            return base * (1.0 + self.diurnal_amplitude
                           * 0.5 * (1.0 - math.cos(phase)))
        if self.kind == "flash_crowd":
            in_burst = (self.burst_start_s <= t
                        < self.burst_start_s + self.burst_duration_s)
            return base * (self.burst_x if in_burst else 1.0)
        return base  # steady / prefix_skew

    def peak_rate(self) -> float:
        if self.kind == "diurnal":
            return self.base_rate_rps * (1.0 + self.diurnal_amplitude)
        if self.kind == "flash_crowd":
            return self.base_rate_rps * self.burst_x
        return self.base_rate_rps


def _heavy_tail_len(rng: np.random.Generator, log_mean: float, sigma: float,
                    lo: int, hi: int) -> int:
    return int(np.clip(round(math.exp(rng.normal(log_mean, sigma))), lo, hi))


def generate_trace(spec: ScenarioSpec, seed: int) -> List[TrafficRequest]:
    """The deterministic scenario generator: same ``(spec, seed)`` ⇒ the
    identical request trace (the determinism gate in
    ``tests/test_llm/test_traffic.py``). All randomness flows through ONE
    Generator derived via ``utils/rng`` — no global-stream draws (GX003).

    Arrivals are inhomogeneous Poisson by thinning: candidate gaps are
    exponential at the PEAK rate, each accepted with probability
    ``rate(t)/peak`` — exact for any bounded intensity, and one rng stream
    keeps the whole trace (arrivals, acceptance, lengths, token values)
    reproducible from the single seed."""
    rng = derive_rng(seed=int(seed))
    peak = max(spec.peak_rate(), 1e-9)
    shared = None
    if spec.kind == "prefix_skew":
        shared = rng.integers(
            3, spec.vocab, size=int(spec.prefix_len)).astype(np.int32)
    out: List[TrafficRequest] = []
    t = 0.0
    while True:
        t += float(rng.exponential(1.0 / peak))
        if t >= spec.duration_s:
            break
        if float(rng.random()) >= spec.rate_at(t) / peak:
            continue  # thinned: intensity below peak at this instant
        is_shared = (spec.kind == "prefix_skew"
                     and float(rng.random()) < spec.shared_fraction)
        plen = _heavy_tail_len(rng, spec.prompt_len_log_mean,
                               spec.prompt_len_sigma, spec.min_prompt,
                               spec.max_prompt)
        if is_shared:
            # shared system prompt + a short per-user suffix, clipped to
            # the same grid the cold prompts use
            suffix = rng.integers(
                3, spec.vocab,
                size=max(1, min(plen, spec.max_prompt - shared.size)),
            ).astype(np.int32)
            tokens = np.concatenate([shared, suffix])
        else:
            tokens = rng.integers(3, spec.vocab, size=plen).astype(np.int32)
        out.append(TrafficRequest(
            index=len(out), arrival_s=t, tokens=tokens,
            max_new=_heavy_tail_len(rng, spec.out_len_log_mean,
                                    spec.out_len_sigma, spec.min_new,
                                    spec.max_new),
            shared_prefix=is_shared))
    return out


def scenario_suite(vocab: int = 512, duration_s: float = 10.0,
                   base_rate_rps: float = 4.0, max_prompt: int = 28,
                   max_new: int = 32) -> List[ScenarioSpec]:
    """The standing four-scenario suite ``BENCH_MODE=traffic`` grades:
    steady heavy-tail, diurnal, flash-crowd, prefix-skew — one spec set
    shared by the bench, the tests, and (later) the PBT-over-serving-
    policies fitness evaluation, so 'the scenario a policy was graded on'
    is a name, not a copy-pasted parameter blob."""
    common = dict(vocab=int(vocab), duration_s=float(duration_s),
                  base_rate_rps=float(base_rate_rps),
                  max_prompt=int(max_prompt), max_new=int(max_new))
    return [
        ScenarioSpec(name="steady_heavy_tail", kind="steady", **common),
        ScenarioSpec(name="diurnal", kind="diurnal",
                     diurnal_period_s=float(duration_s), **common),
        ScenarioSpec(name="flash_crowd", kind="flash_crowd",
                     burst_start_s=0.4 * duration_s,
                     burst_duration_s=0.2 * duration_s, **common),
        ScenarioSpec(name="prefix_skew", kind="prefix_skew",
                     prefix_len=max(4, int(max_prompt) // 2), **common),
    ]


# --------------------------------------------------------------------------- #
# record / replay
# --------------------------------------------------------------------------- #

def save_trace(path: Union[str, Path], requests: Sequence[TrafficRequest],
               spec: Optional[ScenarioSpec] = None,
               seed: Optional[int] = None) -> Path:
    """Write a request trace as JSONL — one header line (schema, provenance:
    the generating spec + seed when known) then one line per request —
    atomically, so a crash mid-write can never leave a half-trace a later
    replay run trusts."""
    from agilerl_tpu.resilience.atomic import atomic_write_bytes

    path = Path(path)
    lines = [json.dumps({
        "kind": "trace_header", "schema": TRACE_SCHEMA,
        "n_requests": len(requests),
        "spec": spec.to_dict() if spec is not None else None,
        "seed": int(seed) if seed is not None else None,
    })]
    lines.extend(json.dumps(r.to_record()) for r in requests)
    atomic_write_bytes(path, ("\n".join(lines) + "\n").encode())
    return path


def load_trace(path: Union[str, Path]) -> List[TrafficRequest]:
    """Load a recorded trace; token-for-token identical to what
    :func:`save_trace` wrote (ints and floats round-trip JSON exactly)."""
    requests: List[TrafficRequest] = []
    with open(path, encoding="utf-8") as fh:
        header = json.loads(fh.readline())
        if header.get("kind") != "trace_header":
            raise ValueError(f"{path}: not a traffic trace (missing header)")
        if header.get("schema") != TRACE_SCHEMA:
            raise ValueError(
                f"{path}: trace schema {header.get('schema')} != "
                f"{TRACE_SCHEMA}")
        for line in fh:
            line = line.strip()
            if line:
                requests.append(TrafficRequest.from_record(json.loads(line)))
    return requests


def trace_header(path: Union[str, Path]) -> Dict[str, Any]:
    """The provenance header of a recorded trace."""
    with open(path, encoding="utf-8") as fh:
        return json.loads(fh.readline())


# --------------------------------------------------------------------------- #
# the driver
# --------------------------------------------------------------------------- #

@dataclasses.dataclass
class TrafficRunResult:
    """What one scenario run did — the deterministic half of a scenario
    grade (submit/shed/completion/token counts are a pure function of the
    trace and step schedule; wall-clock latency histograms live in the
    fleet's telemetry, which the SLO engine reads separately)."""

    scenario: str
    mode: str
    n_requests: int
    submitted: int
    shed: int
    completed: int
    steps: int
    virtual_s: float
    wall_s: float
    delivered_tokens: int
    kills: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    scale_events: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class TrafficDriver:
    """Drive a :class:`~agilerl_tpu.llm.fleet.ServingFleet` (or anything
    with its ``submit/step/result/open_requests`` surface) through one
    request trace.

    - ``mode="open"`` — arrival-time-faithful: virtual time advances
      ``1/steps_per_s`` per fleet step and every request whose
      ``arrival_s`` has passed is submitted before that step runs. Sheds
      happen exactly as admission control dictates at that queue state.
    - ``mode="closed"`` — fixed-concurrency: keep ``concurrency`` requests
      in flight, submit the next the moment one finishes (``no_shed`` —
      closed-loop measures capacity, so shedding the replacement request
      would deadlock the loop's own flow control).
    - ``autoscale`` — an :class:`~agilerl_tpu.llm.autoscale.AutoscalePolicy`
      applied every ``autoscale_every`` steps (its cooldowns run on its own
      clock; inject a fake one for deterministic tests).
    - ``fault_injector`` — a :class:`~agilerl_tpu.resilience.faults.
      FaultInjector` whose ``kill_host_at`` schedule is keyed by VIRTUAL
      second: at each virtual-second boundary the scheduled replica is
      killed via ``fleet.kill_replica`` (lease-expiry detection when the
      fleet has a heartbeat store, immediate otherwise).
    - ``on_step(step, vnow)`` — per-step hook; the SLO evaluator's
      continuous-evaluation cadence hangs off this in the bench/tests.

    The driver never blocks on wall time — virtual time IS the step count —
    so a run is as fast as the fleet can step and the submit schedule is
    reproducible across hosts of any speed."""

    def __init__(
        self,
        fleet,
        *,
        mode: str = "open",
        steps_per_s: float = 50.0,
        concurrency: int = 8,
        seed: int = 0,
        autoscale=None,
        autoscale_every: int = 1,
        fault_injector=None,
        on_step: Optional[Callable[[int, float], None]] = None,
        max_steps: int = 200_000,
        metrics=None,
    ):
        if mode not in ("open", "closed"):
            raise ValueError(f"unknown driver mode {mode!r}")
        if steps_per_s <= 0:
            raise ValueError("steps_per_s must be positive")
        self.fleet = fleet
        self.mode = mode
        self.steps_per_s = float(steps_per_s)
        self.concurrency = int(concurrency)
        self.seed = int(seed)
        self.autoscale = autoscale
        self.autoscale_every = max(1, int(autoscale_every))
        self.fault_injector = fault_injector
        self.on_step = on_step
        self.max_steps = int(max_steps)
        self.metrics = metrics if metrics is not None else fleet.metrics

    # -- internals ---------------------------------------------------------
    def _submit(self, req: TrafficRequest, key, no_shed: bool) -> Optional[int]:
        return self.fleet.submit(req.tokens, max_new=req.max_new, key=key,
                                 no_shed=no_shed)

    def _kill_scheduled(self, vsec_from: int, vsec_to: int,
                        kills: List[Dict[str, Any]], vnow: float) -> None:
        if self.fault_injector is None:
            return
        for s in range(vsec_from + 1, vsec_to + 1):
            rid = self.fault_injector.host_to_kill(s)
            if rid is None:
                continue
            live = set(self.fleet.replica_ids)
            if rid not in live:
                continue  # already dead/retired — nothing to kill
            self.fleet.kill_replica(int(rid))
            kills.append({"virtual_s": float(s), "replica": int(rid)})
            self.metrics.emit("traffic_fault", fault="replica_kill",
                              replica=int(rid), virtual_s=float(s),
                              at_s=vnow)

    def run(self, requests: Sequence[TrafficRequest], params, lora=None,
            greedy: bool = True, scenario: str = "trace",
            collect_outputs: bool = False) -> TrafficRunResult:
        """Serve the whole trace to completion (every submitted request
        finishes — sheds are terminal) and return the run's deterministic
        outcome counts. ``collect_outputs`` keeps each request's decoded
        tokens on the result (``.outputs``: index → (tokens, emits)) for
        token-level A/Bs; off by default to bound memory on big traces."""
        import jax

        requests = list(requests)
        base_key = jax.random.PRNGKey(self.seed)
        scale0 = len(getattr(self.fleet, "replica_ids", []))
        tickets: Dict[int, int] = {}     # fleet ticket -> request index
        outputs: Dict[int, Any] = {}
        outcomes = {"submitted": 0, "shed": 0, "completed": 0}
        delivered = 0
        kills: List[Dict[str, Any]] = []
        scale_events: List[Dict[str, Any]] = []
        idx = 0
        step = 0
        vsec = -1
        t0 = time.perf_counter()
        self.metrics.emit("traffic_scenario", scenario=scenario,
                          mode=self.mode, n_requests=len(requests),
                          steps_per_s=self.steps_per_s)
        while True:
            vnow = step / self.steps_per_s
            new_vsec = int(vnow)
            if new_vsec != vsec:
                self._kill_scheduled(vsec, new_vsec, kills, vnow)
                vsec = new_vsec
            if self.mode == "open":
                while idx < len(requests) and requests[idx].arrival_s <= vnow:
                    req = requests[idx]
                    t = self._submit(
                        req, jax.random.fold_in(base_key, req.index),
                        no_shed=False)
                    if t is None:
                        outcomes["shed"] += 1
                    else:
                        tickets[t] = req.index
                        outcomes["submitted"] += 1
                    idx += 1
            else:
                while (idx < len(requests)
                       and len(tickets) < self.concurrency):
                    req = requests[idx]
                    t = self._submit(
                        req, jax.random.fold_in(base_key, req.index),
                        no_shed=True)
                    tickets[t] = req.index
                    outcomes["submitted"] += 1
                    idx += 1
            if idx >= len(requests) and not tickets \
                    and not self.fleet.open_requests:
                break
            if self.autoscale is not None \
                    and step % self.autoscale_every == 0:
                acted = self.autoscale.apply(self.fleet)
                if acted is not None:
                    scale_events.append({
                        "action": acted[0], "replica": int(acted[1]),
                        "virtual_s": vnow, "step": step})
            if self.on_step is not None:
                self.on_step(step, vnow)
            for t in self.fleet.step(params, lora=lora, greedy=greedy):
                toks, emits = self.fleet.result(t)
                ri = tickets.pop(t)
                outcomes["completed"] += 1
                delivered += int(np.asarray(emits).sum())
                if collect_outputs:
                    outputs[ri] = (toks, emits)
            step += 1
            if step >= self.max_steps:
                raise RuntimeError(
                    f"traffic run not drained after {self.max_steps} steps "
                    f"({len(tickets)} in flight, {len(requests) - idx} "
                    "unsubmitted — a killed replica with no failover path?)")
        result = TrafficRunResult(
            scenario=scenario, mode=self.mode, n_requests=len(requests),
            submitted=outcomes["submitted"], shed=outcomes["shed"],
            completed=outcomes["completed"], steps=step,
            virtual_s=step / self.steps_per_s,
            wall_s=time.perf_counter() - t0,
            delivered_tokens=int(delivered), kills=kills,
            scale_events=scale_events)
        if collect_outputs:
            result.outputs = outputs  # type: ignore[attr-defined]
        self.metrics.emit("traffic_scenario_done",
                          **{k: v for k, v in result.to_dict().items()
                             if k not in ("kills", "scale_events")},
                          replicas_start=scale0,
                          replicas_end=len(self.fleet.replica_ids))
        return result
