"""Workload/benchmark harnesses that drive the stack the way production
traffic would (docs/serving.md — the traffic-harness workflow).

The package half of the repo's benchmarking surface: ``benchmarking/`` at
the repo root holds standalone capture scripts (TPU up-window playbook, AOT
sweeps); importable harness *libraries* live here so they are graftcheck-
scanned, unit-tested, and reusable from ``bench.py``, tests, and the
PBT-over-serving-policies work (ROADMAP item 4)."""

from agilerl_tpu.benchmarking.traffic import (
    ScenarioSpec,
    TrafficDriver,
    TrafficRequest,
    TrafficRunResult,
    generate_trace,
    load_trace,
    save_trace,
    scenario_suite,
)

__all__ = [
    "ScenarioSpec", "TrafficRequest", "TrafficDriver", "TrafficRunResult",
    "generate_trace", "load_trace", "save_trace", "scenario_suite",
]
