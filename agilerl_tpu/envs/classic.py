"""Pure-JAX classic-control environments (CartPole, Pendulum, MountainCar,
Acrobot-lite) matching gymnasium dynamics, for zero-host-sync rollouts.

These give the framework its own fast env backend (the reference depends on
gymnasium subprocess workers for everything, agilerl/utils/utils.py:47); the
gymnasium path remains available via utils.make_vect_envs.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from gymnasium import spaces

from agilerl_tpu.envs.core import JaxEnv


class CartPoleState(NamedTuple):
    x: jax.Array
    x_dot: jax.Array
    theta: jax.Array
    theta_dot: jax.Array


class CartPole(JaxEnv):
    """CartPole-v1 dynamics (Euler integration, same constants as gymnasium)."""

    max_episode_steps = 500

    def __init__(self):
        high = np.array([4.8, np.inf, 0.418, np.inf], dtype=np.float32)
        self.observation_space = spaces.Box(-high, high, dtype=np.float32)
        self.action_space = spaces.Discrete(2)

    def reset_fn(self, key):
        vals = jax.random.uniform(key, (4,), minval=-0.05, maxval=0.05)
        state = CartPoleState(vals[0], vals[1], vals[2], vals[3])
        return state, jnp.stack(vals)

    def step_fn(self, state, action, key):
        gravity, masscart, masspole = 9.8, 1.0, 0.1
        total_mass = masscart + masspole
        length = 0.5
        polemass_length = masspole * length
        force_mag, dt = 10.0, 0.02

        force = jnp.where(action == 1, force_mag, -force_mag)
        costh, sinth = jnp.cos(state.theta), jnp.sin(state.theta)
        temp = (force + polemass_length * state.theta_dot**2 * sinth) / total_mass
        theta_acc = (gravity * sinth - costh * temp) / (
            length * (4.0 / 3.0 - masspole * costh**2 / total_mass)
        )
        x_acc = temp - polemass_length * theta_acc * costh / total_mass

        x = state.x + dt * state.x_dot
        x_dot = state.x_dot + dt * x_acc
        theta = state.theta + dt * state.theta_dot
        theta_dot = state.theta_dot + dt * theta_acc
        new = CartPoleState(x, x_dot, theta, theta_dot)
        obs = jnp.stack([x, x_dot, theta, theta_dot])
        terminated = jnp.logical_or(
            jnp.abs(x) > 2.4, jnp.abs(theta) > 12 * jnp.pi / 180
        )
        reward = jnp.float32(1.0)
        return new, obs, reward, terminated, jnp.bool_(False)


class PendulumState(NamedTuple):
    theta: jax.Array
    theta_dot: jax.Array


class Pendulum(JaxEnv):
    """Pendulum-v1 dynamics."""

    max_episode_steps = 200

    def __init__(self):
        high = np.array([1.0, 1.0, 8.0], dtype=np.float32)
        self.observation_space = spaces.Box(-high, high, dtype=np.float32)
        self.action_space = spaces.Box(-2.0, 2.0, (1,), dtype=np.float32)

    def _obs(self, s: PendulumState) -> jax.Array:
        return jnp.stack([jnp.cos(s.theta), jnp.sin(s.theta), s.theta_dot])

    def reset_fn(self, key):
        k1, k2 = jax.random.split(key)
        theta = jax.random.uniform(k1, minval=-jnp.pi, maxval=jnp.pi)
        theta_dot = jax.random.uniform(k2, minval=-1.0, maxval=1.0)
        state = PendulumState(theta, theta_dot)
        return state, self._obs(state)

    def step_fn(self, state, action, key):
        g, m, l, dt = 10.0, 1.0, 1.0, 0.05
        u = jnp.clip(action[0] if action.ndim > 0 else action, -2.0, 2.0)
        th, thdot = state.theta, state.theta_dot
        norm_th = ((th + jnp.pi) % (2 * jnp.pi)) - jnp.pi
        cost = norm_th**2 + 0.1 * thdot**2 + 0.001 * u**2
        newthdot = thdot + (3 * g / (2 * l) * jnp.sin(th) + 3.0 / (m * l**2) * u) * dt
        newthdot = jnp.clip(newthdot, -8.0, 8.0)
        newth = th + newthdot * dt
        new = PendulumState(newth, newthdot)
        return new, self._obs(new), -cost, jnp.bool_(False), jnp.bool_(False)


class MountainCarState(NamedTuple):
    position: jax.Array
    velocity: jax.Array


class MountainCar(JaxEnv):
    """MountainCar-v0 dynamics."""

    max_episode_steps = 200

    def __init__(self):
        self.observation_space = spaces.Box(
            np.array([-1.2, -0.07], np.float32), np.array([0.6, 0.07], np.float32)
        )
        self.action_space = spaces.Discrete(3)

    def reset_fn(self, key):
        pos = jax.random.uniform(key, minval=-0.6, maxval=-0.4)
        state = MountainCarState(pos, jnp.float32(0.0))
        return state, jnp.stack([pos, jnp.float32(0.0)])

    def step_fn(self, state, action, key):
        velocity = state.velocity + (action - 1) * 0.001 + jnp.cos(3 * state.position) * (-0.0025)
        velocity = jnp.clip(velocity, -0.07, 0.07)
        position = jnp.clip(state.position + velocity, -1.2, 0.6)
        velocity = jnp.where((position <= -1.2) & (velocity < 0), 0.0, velocity)
        terminated = (position >= 0.5) & (velocity >= 0)
        new = MountainCarState(position, velocity)
        return new, jnp.stack([position, velocity]), jnp.float32(-1.0), terminated, jnp.bool_(False)


class MountainCarContinuous(JaxEnv):
    """MountainCarContinuous-v0 dynamics (power-scaled Box(1) action, +100
    goal bonus minus action cost) — gives the scan-resident continuous-control
    programs (EvoDDPG/EvoTD3) a second JAX-native env next to Pendulum."""

    max_episode_steps = 999

    def __init__(self):
        self.observation_space = spaces.Box(
            np.array([-1.2, -0.07], np.float32), np.array([0.6, 0.07], np.float32)
        )
        self.action_space = spaces.Box(-1.0, 1.0, (1,), dtype=np.float32)

    def reset_fn(self, key):
        pos = jax.random.uniform(key, minval=-0.6, maxval=-0.4)
        state = MountainCarState(pos, jnp.float32(0.0))
        return state, jnp.stack([pos, jnp.float32(0.0)])

    def step_fn(self, state, action, key):
        force = jnp.clip(action[0] if action.ndim > 0 else action, -1.0, 1.0)
        velocity = state.velocity + force * 0.0015 + jnp.cos(3 * state.position) * (
            -0.0025
        )
        velocity = jnp.clip(velocity, -0.07, 0.07)
        position = jnp.clip(state.position + velocity, -1.2, 0.6)
        velocity = jnp.where((position <= -1.2) & (velocity < 0), 0.0, velocity)
        terminated = (position >= 0.45) & (velocity >= 0)
        reward = jnp.where(terminated, 100.0, 0.0) - 0.1 * force**2
        new = MountainCarState(position, velocity)
        return (new, jnp.stack([position, velocity]), reward, terminated,
                jnp.bool_(False))


class VisualCartPole(CartPole):
    """CartPole with an on-device rendered image observation [H, W, 1] —
    exercises the CNN encoder path end-to-end without an Atari dependency
    (parity target: the reference's Atari Pong CNN workload, BASELINE.md)."""

    def __init__(self, size: int = 24):
        super().__init__()
        self.size = size
        self.observation_space = spaces.Box(0.0, 1.0, (size, size, 1), np.float32)

    def _render(self, state: CartPoleState) -> jax.Array:
        s = self.size
        xs = jnp.arange(s, dtype=jnp.float32)[None, :]
        ys = jnp.arange(s, dtype=jnp.float32)[:, None]
        cart_col = (state.x + 2.4) / 4.8 * (s - 1)
        cart_row = jnp.float32(s - 3)
        cart = jnp.exp(-((xs - cart_col) ** 2) / 4.0) * jnp.exp(
            -((ys - cart_row) ** 2) / 2.0
        )
        tip_col = cart_col + jnp.sin(state.theta) * s * 0.4
        tip_row = cart_row - jnp.cos(state.theta) * s * 0.4
        pole = jnp.exp(-((xs - tip_col) ** 2) / 4.0) * jnp.exp(
            -((ys - tip_row) ** 2) / 4.0
        )
        return jnp.clip(cart + pole, 0.0, 1.0)[..., None]

    def reset_fn(self, key):
        state, _ = super().reset_fn(key)
        return state, self._render(state)

    def step_fn(self, state, action, key):
        new, _, reward, terminated, truncated = super().step_fn(state, action, key)
        return new, self._render(new), reward, terminated, truncated


REGISTRY = {
    "CartPole-v1": CartPole,
    "Pendulum-v1": Pendulum,
    "MountainCar-v0": MountainCar,
    "MountainCarContinuous-v0": MountainCarContinuous,
    "VisualCartPole-v0": VisualCartPole,
}


def make(env_id: str) -> JaxEnv:
    if env_id not in REGISTRY:
        raise KeyError(f"Unknown JAX env {env_id!r}; available: {sorted(REGISTRY)}")
    return REGISTRY[env_id]()
