"""Pure-JAX multi-agent test environments with the PettingZoo parallel-env dict
API, vectorised (complements the host-side PettingZoo wrappers in
agilerl_tpu/vector/ — parity target: the simple_speaker_listener / simple_spread
workloads in BASELINE.md).

SimpleSpreadJax: N agents on a 2D plane must cover N landmarks; shared reward
= -sum(min distances). Discrete(5) or Box(2) actions.
"""

from __future__ import annotations

from typing import Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from gymnasium import spaces

from agilerl_tpu.envs.core import VecState


class MAState(NamedTuple):
    pos: jax.Array  # [n_agents, 2]
    landmarks: jax.Array  # [n_agents, 2]
    t: jax.Array


class SimpleSpreadJax:
    """Cooperative navigation: agents observe own pos + all landmark offsets."""

    def __init__(self, n_agents: int = 2, continuous: bool = False, max_steps: int = 25):
        self.n_agents = n_agents
        self.continuous = continuous
        self.max_episode_steps = max_steps
        self.agent_ids = [f"agent_{i}" for i in range(n_agents)]
        obs_dim = 2 + 2 * n_agents
        self.observation_spaces = {
            a: spaces.Box(-np.inf, np.inf, (obs_dim,), np.float32) for a in self.agent_ids
        }
        if continuous:
            self.action_spaces = {
                a: spaces.Box(-1.0, 1.0, (2,), np.float32) for a in self.agent_ids
            }
        else:
            self.action_spaces = {a: spaces.Discrete(5) for a in self.agent_ids}

    def _obs(self, state: MAState) -> Dict[str, jax.Array]:
        out = {}
        for i, aid in enumerate(self.agent_ids):
            rel = (state.landmarks - state.pos[i]).reshape(-1)
            out[aid] = jnp.concatenate([state.pos[i], rel])
        return out

    def reset_fn(self, key) -> Tuple[MAState, Dict[str, jax.Array]]:
        k1, k2 = jax.random.split(key)
        pos = jax.random.uniform(k1, (self.n_agents, 2), minval=-1, maxval=1)
        lm = jax.random.uniform(k2, (self.n_agents, 2), minval=-1, maxval=1)
        state = MAState(pos, lm, jnp.int32(0))
        return state, self._obs(state)

    def step_fn(self, state: MAState, actions: Dict[str, jax.Array], key):
        moves = []
        for aid in self.agent_ids:
            a = actions[aid]
            if self.continuous:
                moves.append(jnp.clip(a, -1, 1) * 0.1)
            else:
                # 0 stay, 1 left, 2 right, 3 down, 4 up
                dx = jnp.where(a == 1, -0.1, jnp.where(a == 2, 0.1, 0.0))
                dy = jnp.where(a == 3, -0.1, jnp.where(a == 4, 0.1, 0.0))
                moves.append(jnp.stack([dx, dy]))
        pos = jnp.clip(state.pos + jnp.stack(moves), -1.5, 1.5)
        t = state.t + 1
        new = MAState(pos, state.landmarks, t)
        # shared reward: -sum over landmarks of min agent distance
        d = jnp.linalg.norm(pos[:, None, :] - state.landmarks[None, :, :], axis=-1)
        reward = -jnp.sum(jnp.min(d, axis=0))
        truncated = t >= self.max_episode_steps
        obs = self._obs(new)
        rewards = {a: reward for a in self.agent_ids}
        terms = {a: jnp.bool_(False) for a in self.agent_ids}
        truncs = {a: truncated for a in self.agent_ids}
        return new, obs, rewards, terms, truncs


def make_ma_autoreset_step(env: "SimpleSpreadJax") -> Callable:
    """Stacked-array functional step for the scan-resident multi-agent tier.

    Unlike :class:`MultiAgentJaxVecEnv` (the host dict-API wrapper), this
    returns a pure jitted ``vec_step(vstate, actions) -> (vstate, obs,
    reward, terminated, truncated, final_obs)`` where actions/observations
    are **agent-major stacked arrays** ``[A, N, ...]`` (homogeneous agents)
    and ``reward`` is the shared scalar per env ``[N]`` — the layout
    ``EvoIPPO`` vmaps its per-agent networks over. Autoreset follows
    gymnasium semantics (``final_obs`` is the pre-reset true successor)."""
    ids = env.agent_ids
    max_steps = env.max_episode_steps or 10**9

    def single_step(state, step_count, actions, key):
        # actions [A, ...] for one env
        k_step, k_reset = jax.random.split(key)
        act_dict = {aid: actions[i] for i, aid in enumerate(ids)}
        new_state, obs, rew, term, trunc = env.step_fn(state, act_dict, k_step)
        step_count = step_count + 1
        terminated = jnp.any(jnp.stack([term[a] for a in ids]))
        truncated = jnp.logical_or(
            jnp.any(jnp.stack([trunc[a] for a in ids])),
            step_count >= max_steps,
        )
        done = jnp.logical_or(terminated, truncated)
        reset_state, reset_obs = env.reset_fn(k_reset)
        out_state = jax.tree_util.tree_map(
            lambda r, n: jnp.where(done, r, n), reset_state, new_state
        )
        obs_stacked = jnp.stack([obs[a] for a in ids])
        reset_stacked = jnp.stack([reset_obs[a] for a in ids])
        out_obs = jnp.where(done, reset_stacked, obs_stacked)
        out_count = jnp.where(done, 0, step_count)
        # shared-reward envs: every agent sees the same scalar
        reward = rew[ids[0]]
        return (out_state, out_obs, reward, terminated, truncated, out_count,
                obs_stacked)

    @jax.jit
    def vec_step(vstate: VecState, actions: jax.Array):
        key, sub = jax.random.split(vstate.key)
        n = vstate.step_count.shape[0]
        keys = jax.random.split(sub, n)
        acts = jnp.moveaxis(actions, 0, 1)  # [A, N, ...] -> [N, A, ...]
        new_state, obs, reward, term, trunc, counts, final_obs = jax.vmap(
            single_step
        )(vstate.env_state, vstate.step_count, acts, keys)
        return (
            VecState(new_state, counts, key),
            jnp.moveaxis(obs, 0, 1),  # back to [A, N, ...]
            reward, term, trunc,
            jnp.moveaxis(final_obs, 0, 1),
        )

    return vec_step


class MultiAgentJaxVecEnv:
    """Vectorised dict-API wrapper (PettingZoo-parallel-like, batched)."""

    def __init__(self, env: SimpleSpreadJax, num_envs: int = 1, seed: int = 0):
        self.env = env
        self.num_envs = num_envs
        self.agents = env.agent_ids
        self.agent_ids = env.agent_ids
        self.observation_spaces = env.observation_spaces
        self.action_spaces = env.action_spaces
        self._key = jax.random.PRNGKey(seed)
        self._reset_v = jax.jit(jax.vmap(env.reset_fn))
        self._step_v = jax.jit(self._make_step())
        self._state = None
        self._t = None

    def _make_step(self):
        env = self.env

        def single(state, actions, key):
            k1, k2 = jax.random.split(key)
            new, obs, rew, term, trunc = env.step_fn(state, actions, k1)
            done = jnp.any(
                jnp.stack([jnp.logical_or(term[a], trunc[a]) for a in env.agent_ids])
            )
            reset_state, reset_obs = env.reset_fn(k2)
            out_state = jax.tree_util.tree_map(
                lambda r, n: jnp.where(done, r, n), reset_state, new
            )
            out_obs = {
                a: jnp.where(done, reset_obs[a], obs[a]) for a in env.agent_ids
            }
            # obs BEFORE any autoreset (true successor for bootstrapping)
            return out_state, out_obs, rew, term, trunc, obs

        def vec_step(state, actions, key):
            keys = jax.random.split(key, self.num_envs)
            return jax.vmap(single)(state, actions, keys)

        return vec_step

    def reset(self, seed: Optional[int] = None, options=None):
        if seed is not None:
            self._key = jax.random.PRNGKey(seed)
        self._key, sub = jax.random.split(self._key)
        self._state, obs = self._reset_v(jax.random.split(sub, self.num_envs))
        return {a: np.asarray(o) for a, o in obs.items()}, {}

    def step(self, actions: Dict[str, np.ndarray]):
        self._key, sub = jax.random.split(self._key)
        actions = {a: jnp.asarray(v) for a, v in actions.items()}
        self._state, obs, rew, term, trunc, final_obs = self._step_v(
            self._state, actions, sub
        )
        to_np = lambda d: {a: np.asarray(v) for a, v in d.items()}  # noqa: E731
        return (to_np(obs), to_np(rew), to_np(term), to_np(trunc),
                {"final_obs": to_np(final_obs)})

    def close(self):
        pass
