"""Probe environments + learning-correctness check functions
(parity: agilerl/utils/probe_envs.py — 1328 LoC of diagnostic envs and
check_q_learning_with_probe_env:1114, check_policy_q_learning_with_probe_env:1162,
check_policy_on_policy_with_probe_env:1233).

Each probe isolates one capability: value prediction, discounting,
obs-conditioning, action-conditioning. Implemented as pure-JAX envs so the
checks run entirely on device.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from gymnasium import spaces

from agilerl_tpu.envs.core import JaxEnv, JaxVecEnv


class _ScalarState(NamedTuple):
    obs: jax.Array
    t: jax.Array


class ConstantRewardEnv(JaxEnv):
    """One step, obs=0, reward=1. Value must converge to 1."""

    max_episode_steps = 1

    def __init__(self):
        self.observation_space = spaces.Box(0.0, 1.0, (1,), np.float32)
        self.action_space = spaces.Discrete(2)

    def reset_fn(self, key):
        return _ScalarState(jnp.zeros(1), jnp.int32(0)), jnp.zeros(1)

    def step_fn(self, state, action, key):
        return state, jnp.zeros(1), jnp.float32(1.0), jnp.bool_(True), jnp.bool_(False)


class ObsDependentRewardEnv(JaxEnv):
    """One step; obs ∈ {0,1}; reward = -1 if obs==0 else +1."""

    max_episode_steps = 1

    def __init__(self):
        self.observation_space = spaces.Box(0.0, 1.0, (1,), np.float32)
        self.action_space = spaces.Discrete(2)

    def reset_fn(self, key):
        obs = jax.random.bernoulli(key).astype(jnp.float32).reshape(1)
        return _ScalarState(obs, jnp.int32(0)), obs

    def step_fn(self, state, action, key):
        reward = jnp.where(state.obs[0] > 0.5, 1.0, -1.0)
        return state, state.obs, reward, jnp.bool_(True), jnp.bool_(False)


class DiscountedRewardEnv(JaxEnv):
    """Two steps; obs = t; reward 1 only on second step — value(0) must equal
    gamma * value(1)."""

    max_episode_steps = 2

    def __init__(self):
        self.observation_space = spaces.Box(0.0, 1.0, (1,), np.float32)
        self.action_space = spaces.Discrete(2)

    def reset_fn(self, key):
        return _ScalarState(jnp.zeros(1), jnp.int32(0)), jnp.zeros(1)

    def step_fn(self, state, action, key):
        t = state.t + 1
        obs = jnp.full((1,), t, jnp.float32)
        reward = jnp.where(t >= 2, 1.0, 0.0)
        done = t >= 2
        return _ScalarState(obs, t), obs, reward, done, jnp.bool_(False)


class FixedObsPolicyEnv(JaxEnv):
    """One step, obs=0; discrete: action 0 -> +1, action 1 -> -1.
    continuous: reward = -(action - 0.5)^2 maximised at 0.5."""

    max_episode_steps = 1

    def __init__(self, continuous: bool = False):
        self.continuous = continuous
        self.observation_space = spaces.Box(0.0, 1.0, (1,), np.float32)
        if continuous:
            self.action_space = spaces.Box(-1.0, 1.0, (1,), np.float32)
        else:
            self.action_space = spaces.Discrete(2)

    def reset_fn(self, key):
        return _ScalarState(jnp.zeros(1), jnp.int32(0)), jnp.zeros(1)

    def step_fn(self, state, action, key):
        if self.continuous:
            a = action[0] if action.ndim > 0 else action
            reward = -jnp.square(a - 0.5)
        else:
            reward = jnp.where(action == 0, 1.0, -1.0)
        return state, jnp.zeros(1), reward, jnp.bool_(True), jnp.bool_(False)


class PolicyEnv(JaxEnv):
    """One step; obs ∈ {0,1}; correct action must match obs."""

    max_episode_steps = 1

    def __init__(self):
        self.observation_space = spaces.Box(0.0, 1.0, (1,), np.float32)
        self.action_space = spaces.Discrete(2)

    def reset_fn(self, key):
        obs = jax.random.bernoulli(key).astype(jnp.float32).reshape(1)
        return _ScalarState(obs, jnp.int32(0)), obs

    def step_fn(self, state, action, key):
        correct = (state.obs[0] > 0.5).astype(jnp.int32)
        reward = jnp.where(action == correct, 1.0, -1.0)
        return state, state.obs, reward, jnp.bool_(True), jnp.bool_(False)


class MemoryEnv(JaxEnv):
    """POMDP probe: a cue bit is shown ONLY at t=0; at t=2 the agent must act
    equal to the cue. Solvable only with memory — separates recurrent PPO from
    flat PPO (the capability the reference's recurrent stack exists for,
    agilerl/components/rollout_buffer.py BPTT path)."""

    max_episode_steps = 3

    def __init__(self):
        self.observation_space = spaces.Box(0.0, 1.0, (2,), np.float32)
        self.action_space = spaces.Discrete(2)

    def reset_fn(self, key):
        cue = jax.random.bernoulli(key).astype(jnp.float32)
        obs = jnp.stack([cue, jnp.float32(1.0)])  # [cue, is_first_step]
        return _ScalarState(obs, jnp.int32(0)), obs

    def step_fn(self, state, action, key):
        t = state.t + 1
        cue = state.obs[0]
        blank = jnp.stack([jnp.float32(0.0), jnp.float32(0.0)])  # cue hidden
        done = t >= 3
        reward = jnp.where(
            done, jnp.where(action == cue.astype(jnp.int32), 1.0, -1.0), 0.0
        )
        new_obs = blank
        return _ScalarState(jnp.stack([cue, jnp.float32(0.0)]), t), new_obs, reward, done, jnp.bool_(False)


# --------------------------------------------------------------------------- #
# Check functions
# --------------------------------------------------------------------------- #


def fill_buffer_random(env: JaxEnv, memory, steps: int, num_envs: int = 8, seed: int = 0):
    """Collect transitions with uniform-random actions into a replay buffer."""
    vec = JaxVecEnv(env, num_envs=num_envs, seed=seed)
    obs, _ = vec.reset(seed=seed)
    rng = np.random.default_rng(seed)
    for _ in range(steps):
        if isinstance(env.action_space, spaces.Box):
            low = env.action_space.low
            high = env.action_space.high
            action = rng.uniform(low, high, size=(num_envs,) + env.action_space.shape).astype(
                np.float32
            )
        else:
            action = rng.integers(0, env.action_space.n, size=num_envs)
        next_obs, reward, terminated, truncated, info = vec.step(action)
        memory.add(
            {
                "obs": obs,
                "action": action,
                "reward": reward.astype(np.float32),
                "next_obs": info.get("final_obs", next_obs),
                "done": np.asarray(terminated, np.float32),
            },
            batched=True,
        )
        obs = next_obs
    return memory


def check_q_learning_with_probe_env(
    env: JaxEnv, algo_class, algo_args: dict, learn_steps: int = 500, seed: int = 42
) -> None:
    """Train a Q-learner on a probe env and assert its Q-values
    (parity: probe_envs.py:1114)."""
    from agilerl_tpu.components import ReplayBuffer

    agent = algo_class(**algo_args)
    memory = ReplayBuffer(max_size=2048)
    fill_buffer_random(env, memory, steps=256 // 8, num_envs=8, seed=seed)
    for i in range(learn_steps):
        agent.learn(memory.sample(64))

    if isinstance(env, ConstantRewardEnv):
        q = np.asarray(agent.actor(jnp.zeros((1, 1))))
        np.testing.assert_allclose(q, 1.0, atol=0.2)
    elif isinstance(env, ObsDependentRewardEnv):
        q0 = np.asarray(agent.actor(jnp.zeros((1, 1))))
        q1 = np.asarray(agent.actor(jnp.ones((1, 1))))
        np.testing.assert_allclose(q0, -1.0, atol=0.3)
        np.testing.assert_allclose(q1, 1.0, atol=0.3)
    elif isinstance(env, DiscountedRewardEnv):
        q0 = np.asarray(agent.actor(jnp.zeros((1, 1)))).max()
        q1 = np.asarray(agent.actor(jnp.ones((1, 1)))).max()
        np.testing.assert_allclose(q0, agent.gamma * q1, atol=0.15)
        np.testing.assert_allclose(q1, 1.0, atol=0.15)


def check_policy_q_learning_with_probe_env(
    env: JaxEnv, algo_class, algo_args: dict, learn_steps: int = 400, seed: int = 42
) -> None:
    """Train an actor-critic off-policy agent (DDPG/TD3) on a continuous probe
    env and assert actor/critic outputs (parity: probe_envs.py:1162)."""
    from agilerl_tpu.components import ReplayBuffer

    agent = algo_class(**algo_args)
    memory = ReplayBuffer(max_size=2048)
    fill_buffer_random(env, memory, steps=64, num_envs=8, seed=seed)
    for _ in range(learn_steps):
        agent.learn(memory.sample(64))

    if isinstance(env, FixedObsPolicyEnv) and env.continuous:
        import jax.numpy as jnp

        action = np.asarray(agent.get_action(np.zeros((1, 1), np.float32),
                                             training=False))
        np.testing.assert_allclose(action, 0.5, atol=0.25)
        q = np.asarray(agent.critic(jnp.zeros((1, 1)), jnp.full((1, 1), 0.5)))
        np.testing.assert_allclose(q, 0.0, atol=0.25)


def check_policy_on_policy_with_probe_env(
    env: JaxEnv, algo_class, algo_args: dict, train_iters: int = 60, seed: int = 42
) -> None:
    """Train an on-policy agent (PPO-like) on a probe env and assert the policy
    (parity: probe_envs.py:1233). Uses the agent's own rollout collection."""
    from agilerl_tpu.rollouts.on_policy import collect_rollouts

    agent = algo_class(**algo_args)
    vec = JaxVecEnv(env, num_envs=8, seed=seed)
    obs_space = env.observation_space
    for _ in range(train_iters):
        collect_rollouts(agent, vec, n_steps=agent.learn_step)
        agent.learn()

    if isinstance(env, FixedObsPolicyEnv):
        obs = jnp.zeros((1, 1))
        if isinstance(env.action_space, spaces.Discrete):
            action, _, _ = agent.actor(obs, deterministic=True)
            assert int(action[0]) == 0
        else:
            action, _, _ = agent.actor(obs, deterministic=True)
            np.testing.assert_allclose(np.asarray(action), 0.5, atol=0.2)
    elif isinstance(env, PolicyEnv):
        a0, _, _ = agent.actor(jnp.zeros((1, 1)), deterministic=True)
        a1, _, _ = agent.actor(jnp.ones((1, 1)), deterministic=True)
        assert int(a0[0]) == 0 and int(a1[0]) == 1
