"""Probe environments + learning-correctness check functions
(parity: agilerl/utils/probe_envs.py — 1328 LoC of diagnostic envs and
check_q_learning_with_probe_env:1114, check_policy_q_learning_with_probe_env:1162,
check_policy_on_policy_with_probe_env:1233).

Each probe isolates one capability: value prediction, discounting,
obs-conditioning, action-conditioning — across the same observation grid the
reference covers (vector / image / Dict) x (discrete / continuous actions).
Implemented pure-JAX (NamedTuple state, one parametrised family per reward
structure instead of 30 hand-copied gym classes) so the checks run entirely on
device; images are NHWC (TPU-native) where the reference is CHW.

Like the reference, every env carries ground-truth tables — ``sample_obs``,
``q_values``, ``v_values``, ``policy_values`` (+ ``sample_actions`` for
continuous probes) — and the check fns assert against the tables generically.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from gymnasium import spaces

from agilerl_tpu.envs.core import JaxEnv, JaxVecEnv

_IMG_SHAPE = (3, 3, 1)  # NHWC (reference uses CHW (1,3,3), probe_envs.py:45)


class _ProbeState(NamedTuple):
    v: jax.Array  # primary scalar (drives reward / box obs)
    w: jax.Array  # secondary scalar (Dict probes' discrete key)
    t: jax.Array


class _ProbeBase(JaxEnv):
    """Shared machinery: obs emission per kind + space construction."""

    obs_kind = "vector"  # vector | image | dict
    continuous = False
    max_episode_steps = 1

    def __init__(self):
        if self.obs_kind == "vector":
            self.observation_space = spaces.Box(0.0, 1.0, (1,), np.float32)
        elif self.obs_kind == "image":
            self.observation_space = spaces.Box(0.0, 1.0, _IMG_SHAPE, np.float32)
        else:
            self.observation_space = spaces.Dict(
                {
                    "discrete": spaces.Discrete(2),
                    "box": spaces.Box(0.0, 1.0, _IMG_SHAPE, np.float32),
                }
            )
        if self.continuous:
            self.action_space = spaces.Box(0.0, 1.0, (1,), np.float32)
        else:
            self.action_space = spaces.Discrete(2)
        self._init_tables()

    # -- obs plumbing ---------------------------------------------------- #
    def _emit(self, v, w):
        v = jnp.asarray(v, jnp.float32)
        if self.obs_kind == "vector":
            return jnp.full((1,), v, jnp.float32)
        if self.obs_kind == "image":
            return jnp.full(_IMG_SHAPE, v, jnp.float32)
        return {
            "discrete": jnp.asarray(w, jnp.int32),
            "box": jnp.full(_IMG_SHAPE, v, jnp.float32),
        }

    def raw_obs(self, v, w=0):
        """Host-side obs (unbatched) for the ground-truth tables."""
        if self.obs_kind == "vector":
            return np.full((1,), v, np.float32)
        if self.obs_kind == "image":
            return np.full(_IMG_SHAPE, v, np.float32)
        return {"discrete": np.int64(w), "box": np.full(_IMG_SHAPE, v, np.float32)}

    def _cont_a(self, action):
        a = jnp.asarray(action)
        return a.reshape(())[()] if a.ndim == 0 else a.reshape(-1)[0]

    def _init_tables(self):
        self.sample_obs = []
        self.sample_actions = None
        self.q_values = None
        self.v_values = None
        self.policy_values = None


# --------------------------------------------------------------------------- #
# Families
# --------------------------------------------------------------------------- #


class _ConstantReward(_ProbeBase):
    """One step, fixed obs, reward 1 regardless of action. Value -> 1."""

    def reset_fn(self, key):
        st = _ProbeState(jnp.float32(0), jnp.float32(0), jnp.int32(0))
        return st, self._emit(st.v, st.w)

    def step_fn(self, state, action, key):
        return (
            state, self._emit(state.v, state.w), jnp.float32(1.0),
            jnp.bool_(True), jnp.bool_(False),
        )

    def _init_tables(self):
        super()._init_tables()
        self.sample_obs = [self.raw_obs(0, 0)]
        self.v_values = [1.0]
        if self.continuous:
            self.sample_actions = [np.full((1,), 0.5, np.float32)]
            self.q_values = [[1.0]]
        else:
            self.q_values = [[1.0, 1.0]]


class _ObsDependentReward(_ProbeBase):
    """One step; reward fixed by the observation, not the action.
    vector/image: r = +1 if v==1 else -1. Dict: r = +1 iff discrete==box mean
    (forces fusing both keys, parity: ObsDependentRewardDictEnv)."""

    def reset_fn(self, key):
        k1, k2 = jax.random.split(key)
        v = jax.random.bernoulli(k1).astype(jnp.float32)
        if self.obs_kind == "dict":
            w = jax.random.bernoulli(k2).astype(jnp.float32)
        else:
            w = v
        return _ProbeState(v, w, jnp.int32(0)), self._emit(v, w)

    def _reward(self, state, action):
        if self.obs_kind == "dict":
            return jnp.where(state.v == state.w, 1.0, -1.0)
        return jnp.where(state.v > 0.5, 1.0, -1.0)

    def step_fn(self, state, action, key):
        return (
            state, self._emit(state.v, state.w), self._reward(state, action),
            jnp.bool_(True), jnp.bool_(False),
        )

    def _init_tables(self):
        super()._init_tables()
        if self.obs_kind == "dict":
            self.sample_obs = [
                self.raw_obs(v, w) for w in (0, 1) for v in (0, 1)
            ]
            rewards = [1.0, -1.0, -1.0, 1.0]  # (w,v): 00 01 10 11
        else:
            self.sample_obs = [self.raw_obs(0), self.raw_obs(1)]
            rewards = [-1.0, 1.0]
        self.v_values = rewards
        if self.continuous:
            self.sample_actions = [np.full((1,), 0.5, np.float32)] * len(rewards)
            self.q_values = [[r] for r in rewards]
        else:
            self.q_values = [[r, r] for r in rewards]


class _DiscountedReward(_ProbeBase):
    """Two steps; obs = t; reward 1 only on the second step, so
    value(s0) must equal gamma * value(s1) (the discounting probe)."""

    max_episode_steps = 2
    checks_discounting = True

    def reset_fn(self, key):
        st = _ProbeState(jnp.float32(0), jnp.float32(0), jnp.int32(0))
        return st, self._emit(st.v, st.w)

    def step_fn(self, state, action, key):
        t = state.t + 1
        v = t.astype(jnp.float32)
        reward = jnp.where(t >= 2, 1.0, 0.0)
        done = t >= 2
        return _ProbeState(v, v, t), self._emit(v, v), reward, done, jnp.bool_(False)

    def _init_tables(self):
        super()._init_tables()
        # chain: q(sample_obs[0]) == gamma * q(sample_obs[1]); q(s1) == 1
        self.sample_obs = [self.raw_obs(0, 0), self.raw_obs(1, 1)]
        if self.continuous:
            self.sample_actions = [np.full((1,), 0.5, np.float32)] * 2


class _FixedObsPolicy(_ProbeBase):
    """One step, fixed obs; the ACTION determines the reward.
    discrete: action 0 -> +1, action 1 -> -1. continuous: r = -(a - 0.5)^2."""

    def __init__(self, continuous: bool | None = None):
        if continuous is not None:
            self.continuous = continuous
        super().__init__()

    def reset_fn(self, key):
        st = _ProbeState(jnp.float32(0), jnp.float32(0), jnp.int32(0))
        return st, self._emit(st.v, st.w)

    def step_fn(self, state, action, key):
        if self.continuous:
            reward = -jnp.square(self._cont_a(action) - 0.5)
        else:
            reward = jnp.where(jnp.asarray(action) == 0, 1.0, -1.0)
        return (
            state, self._emit(state.v, state.w), reward,
            jnp.bool_(True), jnp.bool_(False),
        )

    def _init_tables(self):
        super()._init_tables()
        self.sample_obs = [self.raw_obs(0, 0)]
        if self.continuous:
            self.sample_actions = [np.full((1,), 0.5, np.float32)]
            self.q_values = [[0.0]]
            self.policy_values = [np.full((1,), 0.5, np.float32)]
        else:
            self.q_values = [[1.0, -1.0]]
            self.policy_values = [0]


class _Policy(_ProbeBase):
    """One step; the correct action DEPENDS on the observation.
    vector/image discrete: act == v. dict discrete: r=+1 iff act==discrete AND
    discrete==box (parity: PolicyDictEnv). continuous: target a = v (or
    1[v==w] for dict)."""

    def reset_fn(self, key):
        k1, k2 = jax.random.split(key)
        v = jax.random.bernoulli(k1).astype(jnp.float32)
        if self.obs_kind == "dict":
            w = jax.random.bernoulli(k2).astype(jnp.float32)
        else:
            w = v
        return _ProbeState(v, w, jnp.int32(0)), self._emit(v, w)

    def step_fn(self, state, action, key):
        if self.continuous:
            if self.obs_kind == "dict":
                target = (state.v == state.w).astype(jnp.float32)
            else:
                target = state.v
            reward = -jnp.square(self._cont_a(action) - target)
        else:
            a = jnp.asarray(action)
            if self.obs_kind == "dict":
                reward = jnp.where(
                    (a == state.w.astype(jnp.int32)) & (state.v == state.w),
                    1.0, -1.0,
                )
            else:
                reward = jnp.where(a == state.v.astype(jnp.int32), 1.0, -1.0)
        return (
            state, self._emit(state.v, state.w), reward,
            jnp.bool_(True), jnp.bool_(False),
        )

    def _init_tables(self):
        super()._init_tables()
        if self.obs_kind == "dict":
            self.sample_obs = [self.raw_obs(v, w) for w in (0, 1) for v in (0, 1)]
            if self.continuous:
                targets = [1.0, 0.0, 0.0, 1.0]  # (w,v): 00 01 10 11
                self.sample_actions = [np.full((1,), t, np.float32) for t in targets]
                self.q_values = [[0.0]] * 4
                self.policy_values = [np.full((1,), t, np.float32) for t in targets]
            else:
                self.q_values = [
                    [1.0, -1.0],   # (0,0): correct action 0
                    [-1.0, -1.0],  # (0,1): mismatch, always -1
                    [-1.0, -1.0],  # (1,0): mismatch
                    [-1.0, 1.0],   # (1,1): correct action 1
                ]
                self.policy_values = [0, None, None, 1]
        else:
            self.sample_obs = [self.raw_obs(0), self.raw_obs(1)]
            if self.continuous:
                self.sample_actions = [
                    np.zeros((1,), np.float32), np.ones((1,), np.float32)
                ]
                self.q_values = [[0.0], [0.0]]
                self.policy_values = [
                    np.zeros((1,), np.float32), np.ones((1,), np.float32)
                ]
            else:
                self.q_values = [[1.0, -1.0], [-1.0, 1.0]]
                self.policy_values = [0, 1]


# --------------------------------------------------------------------------- #
# Named variants (name parity with agilerl/utils/probe_envs.py:13-1110)
# --------------------------------------------------------------------------- #


def _variant(base, name, kind, continuous):
    cls = type(name, (base,), {"obs_kind": kind, "continuous": continuous})
    cls.__module__ = __name__
    return cls


ConstantRewardEnv = _variant(_ConstantReward, "ConstantRewardEnv", "vector", False)
ConstantRewardImageEnv = _variant(_ConstantReward, "ConstantRewardImageEnv", "image", False)
ConstantRewardDictEnv = _variant(_ConstantReward, "ConstantRewardDictEnv", "dict", False)
ConstantRewardContActionsEnv = _variant(_ConstantReward, "ConstantRewardContActionsEnv", "vector", True)
ConstantRewardContActionsImageEnv = _variant(_ConstantReward, "ConstantRewardContActionsImageEnv", "image", True)
ConstantRewardContActionsDictEnv = _variant(_ConstantReward, "ConstantRewardContActionsDictEnv", "dict", True)

ObsDependentRewardEnv = _variant(_ObsDependentReward, "ObsDependentRewardEnv", "vector", False)
ObsDependentRewardImageEnv = _variant(_ObsDependentReward, "ObsDependentRewardImageEnv", "image", False)
ObsDependentRewardDictEnv = _variant(_ObsDependentReward, "ObsDependentRewardDictEnv", "dict", False)
ObsDependentRewardContActionsEnv = _variant(_ObsDependentReward, "ObsDependentRewardContActionsEnv", "vector", True)
ObsDependentRewardContActionsImageEnv = _variant(_ObsDependentReward, "ObsDependentRewardContActionsImageEnv", "image", True)
ObsDependentRewardContActionsDictEnv = _variant(_ObsDependentReward, "ObsDependentRewardContActionsDictEnv", "dict", True)

DiscountedRewardEnv = _variant(_DiscountedReward, "DiscountedRewardEnv", "vector", False)
DiscountedRewardImageEnv = _variant(_DiscountedReward, "DiscountedRewardImageEnv", "image", False)
DiscountedRewardDictEnv = _variant(_DiscountedReward, "DiscountedRewardDictEnv", "dict", False)
DiscountedRewardContActionsEnv = _variant(_DiscountedReward, "DiscountedRewardContActionsEnv", "vector", True)
DiscountedRewardContActionsImageEnv = _variant(_DiscountedReward, "DiscountedRewardContActionsImageEnv", "image", True)
DiscountedRewardContActionsDictEnv = _variant(_DiscountedReward, "DiscountedRewardContActionsDictEnv", "dict", True)


class FixedObsPolicyEnv(_FixedObsPolicy):
    """Vector FixedObsPolicy; ``continuous=True`` selects the Box-action probe
    (back-compat constructor used by existing tests/check fns)."""

    obs_kind = "vector"


FixedObsPolicyImageEnv = _variant(_FixedObsPolicy, "FixedObsPolicyImageEnv", "image", False)
FixedObsPolicyDictEnv = _variant(_FixedObsPolicy, "FixedObsPolicyDictEnv", "dict", False)
FixedObsPolicyContActionsEnv = _variant(_FixedObsPolicy, "FixedObsPolicyContActionsEnv", "vector", True)
FixedObsPolicyContActionsImageEnv = _variant(_FixedObsPolicy, "FixedObsPolicyContActionsImageEnv", "image", True)
FixedObsPolicyContActionsDictEnv = _variant(_FixedObsPolicy, "FixedObsPolicyContActionsDictEnv", "dict", True)

PolicyEnv = _variant(_Policy, "PolicyEnv", "vector", False)
PolicyImageEnv = _variant(_Policy, "PolicyImageEnv", "image", False)
PolicyDictEnv = _variant(_Policy, "PolicyDictEnv", "dict", False)
PolicyContActionsEnv = _variant(_Policy, "PolicyContActionsEnv", "vector", True)
PolicyContActionsImageEnv = _variant(_Policy, "PolicyContActionsImageEnv", "image", True)
PolicyContActionsImageEnvSimple = _variant(_Policy, "PolicyContActionsImageEnvSimple", "image", True)
PolicyContActionsDictEnv = _variant(_Policy, "PolicyContActionsDictEnv", "dict", True)


class _ScalarState(NamedTuple):
    obs: jax.Array
    t: jax.Array


class MemoryEnv(JaxEnv):
    """POMDP probe: a cue bit is shown ONLY at t=0; at t=2 the agent must act
    equal to the cue. Solvable only with memory — separates recurrent PPO from
    flat PPO (the capability the reference's recurrent stack exists for,
    agilerl/components/rollout_buffer.py BPTT path)."""

    max_episode_steps = 3

    def __init__(self):
        self.observation_space = spaces.Box(0.0, 1.0, (2,), np.float32)
        self.action_space = spaces.Discrete(2)

    def reset_fn(self, key):
        cue = jax.random.bernoulli(key).astype(jnp.float32)
        obs = jnp.stack([cue, jnp.float32(1.0)])  # [cue, is_first_step]
        return _ScalarState(obs, jnp.int32(0)), obs

    def step_fn(self, state, action, key):
        t = state.t + 1
        cue = state.obs[0]
        blank = jnp.stack([jnp.float32(0.0), jnp.float32(0.0)])  # cue hidden
        done = t >= 3
        reward = jnp.where(
            done, jnp.where(action == cue.astype(jnp.int32), 1.0, -1.0), 0.0
        )
        new_obs = blank
        return _ScalarState(jnp.stack([cue, jnp.float32(0.0)]), t), new_obs, reward, done, jnp.bool_(False)


# --------------------------------------------------------------------------- #
# Check functions (table-driven, parity: probe_envs.py:1114,1162,1233)
# --------------------------------------------------------------------------- #


def _pre(env, obs):
    """Batch + preprocess one raw table obs for the agent's networks."""
    from agilerl_tpu.utils.spaces import preprocess_observation

    batched = jax.tree_util.tree_map(lambda x: np.asarray(x)[None], obs)
    return preprocess_observation(env.observation_space, batched)


def fill_buffer_random(env: JaxEnv, memory, steps: int, num_envs: int = 8, seed: int = 0):
    """Collect transitions with uniform-random actions into a replay buffer."""
    vec = JaxVecEnv(env, num_envs=num_envs, seed=seed)
    obs, _ = vec.reset(seed=seed)
    rng = np.random.default_rng(seed)
    for _ in range(steps):
        if isinstance(env.action_space, spaces.Box):
            low = env.action_space.low
            high = env.action_space.high
            action = rng.uniform(low, high, size=(num_envs,) + env.action_space.shape).astype(
                np.float32
            )
        else:
            action = rng.integers(0, env.action_space.n, size=num_envs)
        next_obs, reward, terminated, truncated, info = vec.step(action)
        memory.add(
            {
                "obs": obs,
                "action": action,
                "reward": reward.astype(np.float32),
                "next_obs": info.get("final_obs", next_obs),
                "done": np.asarray(terminated, np.float32),
            },
            batched=True,
        )
        obs = next_obs
    return memory


def check_q_learning_with_probe_env(
    env: JaxEnv, algo_class, algo_args: dict, learn_steps: int = 500, seed: int = 42,
    atol: float = 0.3,
) -> None:
    """Train a Q-learner on a probe env and assert its Q-values against the
    env's ground-truth tables (parity: probe_envs.py:1114)."""
    from agilerl_tpu.components import ReplayBuffer

    agent = algo_class(**algo_args)
    memory = ReplayBuffer(max_size=2048)
    fill_buffer_random(env, memory, steps=256 // 8, num_envs=8, seed=seed)
    for _ in range(learn_steps):
        agent.learn(memory.sample(64))

    if getattr(env, "checks_discounting", False):
        q0 = float(np.asarray(agent.actor(_pre(env, env.sample_obs[0]))).max())
        q1 = float(np.asarray(agent.actor(_pre(env, env.sample_obs[1]))).max())
        np.testing.assert_allclose(q1, 1.0, atol=max(atol, 0.15))
        np.testing.assert_allclose(q0, agent.gamma * q1, atol=max(atol, 0.15))
        return
    for obs, qrow in zip(env.sample_obs, env.q_values):
        if qrow is None:
            continue
        pred = np.asarray(agent.actor(_pre(env, obs)))[0]
        np.testing.assert_allclose(pred, qrow, atol=atol)


def check_policy_q_learning_with_probe_env(
    env: JaxEnv, algo_class, algo_args: dict, learn_steps: int = 400, seed: int = 42,
    atol: float = 0.25,
) -> None:
    """Train an actor-critic off-policy agent (DDPG/TD3) on a continuous probe
    env and assert actor/critic outputs against the tables
    (parity: probe_envs.py:1162)."""
    from agilerl_tpu.components import ReplayBuffer

    agent = algo_class(**algo_args)
    memory = ReplayBuffer(max_size=2048)
    fill_buffer_random(env, memory, steps=64, num_envs=8, seed=seed)
    for _ in range(learn_steps):
        agent.learn(memory.sample(64))

    if getattr(env, "checks_discounting", False):
        # critic(s0, a) == gamma * critic(s1, a); critic(s1, a) ~ 1
        a0, a1 = (jnp.asarray(a)[None] for a in env.sample_actions[:2])
        q0 = float(np.asarray(agent.critic(_pre(env, env.sample_obs[0]), a0)).reshape(-1)[0])
        q1 = float(np.asarray(agent.critic(_pre(env, env.sample_obs[1]), a1)).reshape(-1)[0])
        np.testing.assert_allclose(q1, 1.0, atol=max(atol, 0.15))
        np.testing.assert_allclose(q0, agent.gamma * q1, atol=max(atol, 0.15))
        return
    if env.q_values is not None and env.sample_actions is not None:
        for obs, act, qrow in zip(env.sample_obs, env.sample_actions, env.q_values):
            if qrow is None:
                continue
            q = np.asarray(
                agent.critic(_pre(env, obs), jnp.asarray(act)[None])
            )
            np.testing.assert_allclose(q.reshape(-1), qrow, atol=atol)
    if env.policy_values is not None:
        for obs, pol in zip(env.sample_obs, env.policy_values):
            if pol is None:
                continue
            raw = jax.tree_util.tree_map(lambda x: np.asarray(x)[None], obs)
            action = np.asarray(agent.get_action(raw, training=False))
            np.testing.assert_allclose(action.reshape(-1), pol, atol=atol)


def check_policy_on_policy_with_probe_env(
    env: JaxEnv, algo_class, algo_args: dict, train_iters: int = 60, seed: int = 42,
    atol: float = 0.2, solved_reward: float = None,
) -> None:
    """Train an on-policy agent (PPO-like) on a probe env and assert the
    deterministic policy against the tables (parity: probe_envs.py:1233).

    With ``solved_reward`` set, stops once the mean per-step reward stays
    above it for three consecutive iterations: on a SOLVED one-step probe the
    advantages are bootstrap noise and PPO updates on normalised noise can
    destabilise a perfect policy — the probe asserts learnability, so
    train-to-solve is the correct budget."""
    from agilerl_tpu.rollouts.on_policy import collect_rollouts

    agent = algo_class(**algo_args)
    vec = JaxVecEnv(env, num_envs=8, seed=seed)
    streak = 0
    for _ in range(train_iters):
        mean_rew = collect_rollouts(agent, vec, n_steps=agent.learn_step)
        agent.learn()
        if solved_reward is not None and mean_rew >= solved_reward:
            streak += 1
            if streak >= 3:
                break
        else:
            streak = 0

    assert env.policy_values is not None, "probe env has no policy table"
    for obs, pol in zip(env.sample_obs, env.policy_values):
        if pol is None:
            continue
        action, _, _ = agent.actor(_pre(env, obs), deterministic=True)
        if isinstance(env.action_space, spaces.Discrete):
            assert int(np.asarray(action)[0]) == int(pol), (
                f"policy({obs!r}) = {np.asarray(action)[0]}, want {pol}"
            )
        else:
            np.testing.assert_allclose(np.asarray(action).reshape(-1), pol, atol=atol)
