from agilerl_tpu.envs.classic import CartPole, MountainCar, Pendulum, make
from agilerl_tpu.envs.core import JaxEnv, JaxVecEnv, rollout_scan
from agilerl_tpu.envs.multi_agent import MultiAgentJaxVecEnv, SimpleSpreadJax

__all__ = [
    "JaxEnv", "JaxVecEnv", "rollout_scan", "CartPole", "Pendulum", "MountainCar",
    "make", "SimpleSpreadJax", "MultiAgentJaxVecEnv",
]
