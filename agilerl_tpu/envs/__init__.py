from agilerl_tpu.envs.classic import (
    CartPole,
    MountainCar,
    MountainCarContinuous,
    Pendulum,
    make,
)
from agilerl_tpu.envs.core import JaxEnv, JaxVecEnv, rollout_scan
from agilerl_tpu.envs.multi_agent import (
    MultiAgentJaxVecEnv,
    SimpleSpreadJax,
    make_ma_autoreset_step,
)

__all__ = [
    "JaxEnv", "JaxVecEnv", "rollout_scan", "CartPole", "Pendulum", "MountainCar",
    "MountainCarContinuous", "make", "SimpleSpreadJax", "MultiAgentJaxVecEnv",
    "make_ma_autoreset_step",
]
