"""JAX-native environment core.

The reference relies on gymnasium subprocess vector envs
(agilerl/utils/utils.py:47 make_vect_envs -> gym.vector.AsyncVectorEnv). On TPU
the host<->device boundary is the bottleneck, so first-class envs here are pure
JAX state machines: ``reset_fn(key) -> (state, obs)`` and
``step_fn(state, action, key) -> (state, obs, reward, terminated, truncated)``.
They compose three ways:

1. ``JaxVecEnv`` — gymnasium.vector-compatible host API (numpy in/out) over a
   vmapped, jitted, auto-resetting step: drop-in for the training loops.
2. ``rollout_scan`` — fully-jitted policy+env rollout via lax.scan, zero host
   round-trips: the benchmark path (>1M env-steps/sec aggregate).
3. Plain gymnasium envs still work through the same training loops (see
   agilerl_tpu/utils/utils.py make_vect_envs).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class JaxEnv:
    """Base class: subclasses define observation_space, action_space (gymnasium
    spaces), and pure reset_fn/step_fn."""

    observation_space = None
    action_space = None
    max_episode_steps: Optional[int] = None

    def reset_fn(self, key: jax.Array) -> Tuple[Any, jax.Array]:  # pragma: no cover
        raise NotImplementedError

    def step_fn(
        self, state: Any, action: jax.Array, key: jax.Array
    ) -> Tuple[Any, jax.Array, jax.Array, jax.Array, jax.Array]:  # pragma: no cover
        raise NotImplementedError


class VecState(NamedTuple):
    env_state: Any  # vmapped env state [N, ...]
    step_count: jax.Array  # [N] int32
    key: jax.Array


def make_autoreset_step(env: JaxEnv) -> Callable:
    """Build a jitted vmapped step with per-env autoreset (gymnasium semantics:
    the obs returned on the done step is the NEXT episode's initial obs)."""
    max_steps = env.max_episode_steps or 10**9

    def single_step(state, step_count, action, key):
        k_step, k_reset = jax.random.split(key)
        new_state, obs, reward, terminated, truncated = env.step_fn(state, action, k_step)
        step_count = step_count + 1
        truncated = jnp.logical_or(truncated, step_count >= max_steps)
        done = jnp.logical_or(terminated, truncated)
        reset_state, reset_obs = env.reset_fn(k_reset)
        # done is a per-env scalar here (pre-vmap), so it broadcasts cleanly
        out_state = jax.tree_util.tree_map(
            lambda r, n: jnp.where(done, r, n), reset_state, new_state
        )
        out_obs = jax.tree_util.tree_map(
            lambda r, n: jnp.where(done, r, n), reset_obs, obs
        )
        out_count = jnp.where(done, 0, step_count)
        # obs BEFORE any autoreset — needed so truncated transitions can
        # bootstrap from the true successor state, not the next episode's
        # reset obs (gymnasium's final_observation semantics)
        return out_state, out_obs, reward, terminated, truncated, out_count, obs

    @jax.jit
    def vec_step(vstate: VecState, actions: jax.Array):
        key, sub = jax.random.split(vstate.key)
        n = vstate.step_count.shape[0]
        keys = jax.random.split(sub, n)
        new_state, obs, reward, terminated, truncated, counts, final_obs = jax.vmap(
            single_step
        )(vstate.env_state, vstate.step_count, actions, keys)
        return VecState(new_state, counts, key), obs, reward, terminated, truncated, final_obs

    return vec_step


def _to_np(tree):
    """Device->host conversion that preserves Dict/Tuple obs pytrees
    (np.asarray on a dict would yield a useless object array)."""
    return jax.tree_util.tree_map(np.asarray, tree)


class JaxVecEnv:
    """gymnasium.vector-style host API over a JAX-native env."""

    def __init__(self, env: JaxEnv, num_envs: int = 1, seed: int = 0):
        self.env = env
        self.num_envs = int(num_envs)
        self.observation_space = env.observation_space
        self.action_space = env.action_space
        self.single_observation_space = env.observation_space
        self.single_action_space = env.action_space
        self._step = make_autoreset_step(env)
        self._reset = jax.jit(jax.vmap(env.reset_fn))
        self._key = jax.random.PRNGKey(seed)
        self._state: Optional[VecState] = None

    def reset(self, seed: Optional[int] = None, options=None):
        if seed is not None:
            self._key = jax.random.PRNGKey(seed)
        self._key, sub = jax.random.split(self._key)
        keys = jax.random.split(sub, self.num_envs)
        env_state, obs = self._reset(keys)
        self._state = VecState(
            env_state=env_state,
            step_count=jnp.zeros(self.num_envs, jnp.int32),
            key=self._key,
        )
        return _to_np(obs), {}

    def step(self, actions):
        self._state, obs, reward, terminated, truncated, final_obs = self._step(
            self._state, jnp.asarray(actions)
        )
        return (
            _to_np(obs),
            np.asarray(reward),
            np.asarray(terminated),
            np.asarray(truncated),
            {"final_obs": _to_np(final_obs)},
        )

    def close(self):
        pass


def rollout_scan(
    env: JaxEnv,
    policy_fn: Callable[[Any, Any, jax.Array], jax.Array],
    policy_params: Any,
    num_envs: int,
    num_steps: int,
    key: jax.Array,
):
    """Fully-jitted rollout: lax.scan over vmapped env steps with autoreset.

    policy_fn(params, obs_batch, key) -> actions. Returns (trajectory dict with
    leaves [T, N, ...], final carry). This is the zero-host-sync path used by
    bench.py and the pure-device training loops.
    """
    vec_step = make_autoreset_step(env)
    reset = jax.vmap(env.reset_fn)

    def init(key):
        k1, k2 = jax.random.split(key)
        env_state, obs = reset(jax.random.split(k1, num_envs))
        vstate = VecState(env_state, jnp.zeros(num_envs, jnp.int32), k2)
        return vstate, obs

    def body(carry, _):
        vstate, obs, key = carry
        key, k_act = jax.random.split(key)
        actions = policy_fn(policy_params, obs, k_act)
        vstate, next_obs, reward, terminated, truncated, _final = vec_step(vstate, actions)
        out = {
            "obs": obs,
            "action": actions,
            "reward": reward,
            "done": jnp.logical_or(terminated, truncated).astype(jnp.float32),
        }
        return (vstate, next_obs, key), out

    k_init, k_run = jax.random.split(key)
    vstate, obs = init(k_init)
    (vstate, last_obs, _), traj = jax.lax.scan(
        body, (vstate, obs, k_run), None, length=num_steps
    )
    return traj, (vstate, last_obs)

