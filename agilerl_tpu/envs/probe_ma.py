"""Multi-agent probe environments + checks
(parity: agilerl/utils/probe_envs_ma.py — 2225 LoC of multi-agent diagnostic
envs; the compact JAX set here isolates the same capabilities: constant reward,
obs-dependent reward, action-dependent reward, per-agent reward asymmetry).
"""

from __future__ import annotations

from typing import Dict, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from gymnasium import spaces


class _MAState(NamedTuple):
    obs: jax.Array  # [n_agents, obs_dim]
    t: jax.Array


class _MAProbeBase:
    n_agents = 2
    obs_dim = 1
    max_episode_steps = 1

    def __init__(self):
        self.agent_ids = [f"agent_{i}" for i in range(self.n_agents)]
        self.observation_spaces = {
            a: spaces.Box(0.0, 1.0, (self.obs_dim,), np.float32) for a in self.agent_ids
        }
        self.action_spaces = {a: spaces.Discrete(2) for a in self.agent_ids}

    def _obs_dict(self, state):
        return {a: state.obs[i] for i, a in enumerate(self.agent_ids)}

    def reset_fn(self, key):
        state = _MAState(jnp.zeros((self.n_agents, self.obs_dim)), jnp.int32(0))
        return state, self._obs_dict(state)

    def _done(self, val=True):
        return {a: jnp.bool_(val) for a in self.agent_ids}


class ConstantRewardEnvMA(_MAProbeBase):
    """Every agent gets reward 1 every (single-step) episode."""

    def step_fn(self, state, actions, key):
        rewards = {a: jnp.float32(1.0) for a in self.agent_ids}
        return state, self._obs_dict(state), rewards, self._done(), self._done(False)


class ObsDependentRewardEnvMA(_MAProbeBase):
    """Reward +-1 depends on each agent's own observation."""

    def reset_fn(self, key):
        obs = jax.random.bernoulli(key, shape=(self.n_agents, 1)).astype(jnp.float32)
        state = _MAState(obs, jnp.int32(0))
        return state, self._obs_dict(state)

    def step_fn(self, state, actions, key):
        rewards = {
            a: jnp.where(state.obs[i, 0] > 0.5, 1.0, -1.0)
            for i, a in enumerate(self.agent_ids)
        }
        return state, self._obs_dict(state), rewards, self._done(), self._done(False)


class PolicyEnvMA(_MAProbeBase):
    """Reward depends on each agent matching its own observation bit."""

    def reset_fn(self, key):
        obs = jax.random.bernoulli(key, shape=(self.n_agents, 1)).astype(jnp.float32)
        state = _MAState(obs, jnp.int32(0))
        return state, self._obs_dict(state)

    def step_fn(self, state, actions, key):
        rewards = {}
        for i, a in enumerate(self.agent_ids):
            correct = (state.obs[i, 0] > 0.5).astype(jnp.int32)
            rewards[a] = jnp.where(actions[a] == correct, 1.0, -1.0)
        return state, self._obs_dict(state), rewards, self._done(), self._done(False)


def check_ma_q_learning_with_probe_env(
    env, algo_class, algo_args: dict, learn_steps: int = 300, seed: int = 42
) -> None:
    """Train a multi-agent algorithm on a probe env and assert critic values
    (parity: probe_envs_ma.py check fns)."""
    from agilerl_tpu.components import MultiAgentReplayBuffer
    from agilerl_tpu.envs.multi_agent import MultiAgentJaxVecEnv

    vec = MultiAgentJaxVecEnv(env, num_envs=8, seed=seed)
    vec.observation_spaces = env.observation_spaces
    vec.action_spaces = env.action_spaces
    agent = algo_class(**algo_args)
    buf = MultiAgentReplayBuffer(max_size=2048, agent_ids=env.agent_ids)
    obs, _ = vec.reset(seed=seed)
    rng = np.random.default_rng(seed)
    for _ in range(64):
        actions = {a: rng.integers(0, 2, size=8) for a in env.agent_ids}
        next_obs, rew, term, trunc, _ = vec.step(actions)
        done = {a: np.asarray(term[a], np.float32) for a in env.agent_ids}
        buf.save_to_memory(obs, actions, rew, next_obs, done, is_vectorised=True)
        obs = next_obs
    for _ in range(learn_steps):
        agent.learn(buf.sample(64))
    # constant-reward probe: every centralized critic must predict ~1
    if isinstance(env, ConstantRewardEnvMA):
        from agilerl_tpu.networks.base import EvolvableNetwork

        n_in = agent.critics[env.agent_ids[0]].config.encoder.num_inputs
        q = np.asarray(
            EvolvableNetwork.apply(
                agent.critics[env.agent_ids[0]].config,
                agent.critics[env.agent_ids[0]].params,
                jnp.zeros((1, n_in)),
            )
        )
        np.testing.assert_allclose(q, 1.0, atol=0.25)
