"""Multi-agent probe environments + checks
(parity: agilerl/utils/probe_envs_ma.py — 2225 LoC / 22 diagnostic env classes:
5 reward families x {vector, image} x {discrete, continuous} + the joint-action
MultiPolicy pair, with check fns :1867 and :1958).

Implemented as parametrised pure-JAX families (one class per reward structure,
variants generated per obs kind / action kind) rather than 22 hand-copied gym
classes; images are NHWC. Like the single-agent grid (envs/probe.py), every env
carries ground-truth ``sample_obs`` / ``policy_values`` / ``v_values`` tables
and the check fns assert against them generically.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from gymnasium import spaces

_IMG_SHAPE = (3, 3, 1)  # NHWC (reference uses CHW)


class _MAState(NamedTuple):
    v: jax.Array  # [n_agents] per-agent scalar (drives obs + reward)
    t: jax.Array


class _MAProbeBase:
    n_agents = 2
    obs_kind = "vector"  # vector | image
    continuous = False
    max_episode_steps = 1

    def __init__(self):
        self.agent_ids = [f"agent_{i}" for i in range(self.n_agents)]
        if self.obs_kind == "vector":
            obs_space = spaces.Box(0.0, 1.0, (1,), np.float32)
        else:
            obs_space = spaces.Box(0.0, 1.0, _IMG_SHAPE, np.float32)
        self.observation_spaces = {a: obs_space for a in self.agent_ids}
        if self.continuous:
            act_space = spaces.Box(0.0, 1.0, (1,), np.float32)
        else:
            act_space = spaces.Discrete(2)
        self.action_spaces = {a: act_space for a in self.agent_ids}
        self._init_tables()

    # -- obs plumbing ---------------------------------------------------- #
    def _emit_one(self, v):
        if self.obs_kind == "vector":
            return jnp.full((1,), v, jnp.float32)
        return jnp.full(_IMG_SHAPE, v, jnp.float32)

    def _obs_dict(self, state):
        return {a: self._emit_one(state.v[i]) for i, a in enumerate(self.agent_ids)}

    def raw_obs(self, vs):
        """Host-side dict obs for the tables; vs = per-agent scalars."""
        out = {}
        for a, v in zip(self.agent_ids, vs):
            if self.obs_kind == "vector":
                out[a] = np.full((1,), v, np.float32)
            else:
                out[a] = np.full(_IMG_SHAPE, v, np.float32)
        return out

    def _done(self, val=True):
        return {a: jnp.bool_(val) for a in self.agent_ids}

    def reset_fn(self, key):
        state = _MAState(jnp.zeros(self.n_agents), jnp.int32(0))
        return state, self._obs_dict(state)

    def _cont_a(self, action):
        a = jnp.asarray(action)
        return a.reshape(-1)[0] if a.ndim else a

    def _init_tables(self):
        self.sample_obs = []
        self.policy_values = None
        self.v_values = None


class _RandomBitsMixin:
    """reset: independent bernoulli bit per agent."""

    def reset_fn(self, key):
        v = jax.random.bernoulli(key, shape=(self.n_agents,)).astype(jnp.float32)
        return _MAState(v, jnp.int32(0)), self._obs_dict(_MAState(v, jnp.int32(0)))


# --------------------------------------------------------------------------- #
# Families
# --------------------------------------------------------------------------- #


class _ConstantRewardMA(_MAProbeBase):
    """Every agent gets reward 1 every single-step episode: critics -> 1."""

    def step_fn(self, state, actions, key):
        rewards = {a: jnp.float32(1.0) for a in self.agent_ids}
        return state, self._obs_dict(state), rewards, self._done(), self._done(False)

    def _init_tables(self):
        super()._init_tables()
        self.sample_obs = [self.raw_obs([0.0] * self.n_agents)]
        self.v_values = [{a: 1.0 for a in self.agent_ids}]


class _ObsDependentRewardMA(_RandomBitsMixin, _MAProbeBase):
    """Reward +-1 fixed by each agent's own observation bit."""

    def step_fn(self, state, actions, key):
        rewards = {
            a: jnp.where(state.v[i] > 0.5, 1.0, -1.0)
            for i, a in enumerate(self.agent_ids)
        }
        return state, self._obs_dict(state), rewards, self._done(), self._done(False)

    def _init_tables(self):
        super()._init_tables()
        self.sample_obs = [self.raw_obs([0.0, 0.0]), self.raw_obs([1.0, 1.0])]
        self.v_values = [
            {a: -1.0 for a in self.agent_ids},
            {a: 1.0 for a in self.agent_ids},
        ]


class _DiscountedRewardMA(_MAProbeBase):
    """Two steps; reward 1 only on the second: value(s0) = gamma * value(s1)."""

    max_episode_steps = 2
    checks_discounting = True

    def step_fn(self, state, actions, key):
        t = state.t + 1
        v = jnp.full(self.n_agents, t.astype(jnp.float32))
        reward = jnp.where(t >= 2, 1.0, 0.0)
        rewards = {a: reward for a in self.agent_ids}
        done = {a: t >= 2 for a in self.agent_ids}
        return (
            _MAState(v, t), self._obs_dict(_MAState(v, t)), rewards, done,
            self._done(False),
        )

    def _init_tables(self):
        super()._init_tables()
        self.sample_obs = [self.raw_obs([0.0, 0.0]), self.raw_obs([1.0, 1.0])]


class _FixedObsPolicyMA(_MAProbeBase):
    """Fixed obs; each agent's ACTION sets its reward.
    discrete: action 0 -> +1 else -1; continuous: r = -(a - 0.5)^2."""

    def step_fn(self, state, actions, key):
        rewards = {}
        for a in self.agent_ids:
            if self.continuous:
                rewards[a] = -jnp.square(self._cont_a(actions[a]) - 0.5)
            else:
                rewards[a] = jnp.where(jnp.asarray(actions[a]) == 0, 1.0, -1.0)
        return state, self._obs_dict(state), rewards, self._done(), self._done(False)

    def _init_tables(self):
        super()._init_tables()
        self.sample_obs = [self.raw_obs([0.0] * self.n_agents)]
        if self.continuous:
            self.policy_values = [
                {a: np.full((1,), 0.5, np.float32) for a in self.agent_ids}
            ]
        else:
            self.policy_values = [{a: 0 for a in self.agent_ids}]


class _PolicyMA(_RandomBitsMixin, _MAProbeBase):
    """Each agent must match its own observation bit.
    discrete: act == bit; continuous: r = -(a - bit)^2."""

    def step_fn(self, state, actions, key):
        rewards = {}
        for i, a in enumerate(self.agent_ids):
            if self.continuous:
                rewards[a] = -jnp.square(self._cont_a(actions[a]) - state.v[i])
            else:
                rewards[a] = jnp.where(
                    jnp.asarray(actions[a]) == state.v[i].astype(jnp.int32), 1.0, -1.0
                )
        return state, self._obs_dict(state), rewards, self._done(), self._done(False)

    def _init_tables(self):
        super()._init_tables()
        self.sample_obs = [self.raw_obs([0.0, 0.0]), self.raw_obs([1.0, 1.0])]
        if self.continuous:
            self.policy_values = [
                {a: np.zeros((1,), np.float32) for a in self.agent_ids},
                {a: np.ones((1,), np.float32) for a in self.agent_ids},
            ]
        else:
            self.policy_values = [
                {a: 0 for a in self.agent_ids},
                {a: 1 for a in self.agent_ids},
            ]


class _MultiPolicyMA(_RandomBitsMixin, _MAProbeBase):
    """Joint-action probe (parity: probe_envs_ma.py MultiPolicyEnv:1542): an
    agent is rewarded only when EVERY agent matches its own bit — the
    centralized critic must model the joint action."""

    def step_fn(self, state, actions, key):
        if self.continuous:
            errs = [
                jnp.square(self._cont_a(actions[a]) - state.v[i])
                for i, a in enumerate(self.agent_ids)
            ]
            joint = -sum(errs)
            rewards = {a: joint for a in self.agent_ids}
        else:
            matches = [
                jnp.asarray(actions[a]) == state.v[i].astype(jnp.int32)
                for i, a in enumerate(self.agent_ids)
            ]
            all_match = jnp.all(jnp.stack(matches))
            rewards = {a: jnp.where(all_match, 1.0, -1.0) for a in self.agent_ids}
        return state, self._obs_dict(state), rewards, self._done(), self._done(False)

    def _init_tables(self):
        super()._init_tables()
        self.sample_obs = [self.raw_obs([0.0, 0.0]), self.raw_obs([1.0, 1.0])]
        if self.continuous:
            self.policy_values = [
                {a: np.zeros((1,), np.float32) for a in self.agent_ids},
                {a: np.ones((1,), np.float32) for a in self.agent_ids},
            ]
        else:
            self.policy_values = [
                {a: 0 for a in self.agent_ids},
                {a: 1 for a in self.agent_ids},
            ]


# --------------------------------------------------------------------------- #
# Named variants (22-class parity with probe_envs_ma.py; *MA suffix because
# the single-agent grid shares this package's namespace)
# --------------------------------------------------------------------------- #


def _variant(base, name, kind, continuous):
    cls = type(name, (base,), {"obs_kind": kind, "continuous": continuous})
    cls.__module__ = __name__
    return cls


_FAMILIES = {
    "ConstantReward": _ConstantRewardMA,
    "ObsDependentReward": _ObsDependentRewardMA,
    "DiscountedReward": _DiscountedRewardMA,
    "FixedObsPolicy": _FixedObsPolicyMA,
    "Policy": _PolicyMA,
}

for _fam, _base in _FAMILIES.items():
    for _img in (False, True):
        for _cont in (False, True):
            _name = (
                f"{_fam}{'ContActions' if _cont else ''}"
                f"{'Image' if _img else ''}EnvMA"
            )
            globals()[_name] = _variant(
                _base, _name, "image" if _img else "vector", _cont
            )

MultiPolicyEnvMA = _variant(_MultiPolicyMA, "MultiPolicyEnvMA", "vector", False)
MultiPolicyImageEnvMA = _variant(_MultiPolicyMA, "MultiPolicyImageEnvMA", "image", False)


# --------------------------------------------------------------------------- #
# Check functions (parity: probe_envs_ma.py:1867,1958)
# --------------------------------------------------------------------------- #


def _fill_ma_buffer(env, vec, buf, steps: int, seed: int):
    rng = np.random.default_rng(seed)
    n = vec.num_envs
    obs, _ = vec.reset(seed=seed)
    for _ in range(steps):
        actions = {}
        for a in env.agent_ids:
            space = env.action_spaces[a]
            if isinstance(space, spaces.Box):
                actions[a] = rng.uniform(
                    space.low, space.high, size=(n,) + space.shape
                ).astype(np.float32)
            else:
                actions[a] = rng.integers(0, space.n, size=n)
        next_obs, rew, term, trunc, _ = vec.step(actions)
        done = {a: np.asarray(term[a], np.float32) for a in env.agent_ids}
        buf.save_to_memory(obs, actions, rew, next_obs, done, is_vectorised=True)
        obs = next_obs
    return buf


def _batch_one(obs_dict):
    return {a: np.asarray(o)[None] for a, o in obs_dict.items()}


def check_ma_q_learning_with_probe_env(
    env, algo_class, algo_args: dict, learn_steps: int = 300, seed: int = 42,
    atol: float = 0.25,
) -> None:
    """Train a multi-agent off-policy algorithm (MADDPG/MATD3) on a probe env;
    assert critic values and/or per-agent policies against the env tables
    (parity: probe_envs_ma.py:1867)."""
    from agilerl_tpu.components import MultiAgentReplayBuffer
    from agilerl_tpu.envs.multi_agent import MultiAgentJaxVecEnv

    vec = MultiAgentJaxVecEnv(env, num_envs=8, seed=seed)
    vec.observation_spaces = env.observation_spaces
    vec.action_spaces = env.action_spaces
    agent = algo_class(**algo_args)
    buf = MultiAgentReplayBuffer(max_size=2048, agent_ids=env.agent_ids)
    _fill_ma_buffer(env, vec, buf, steps=64, seed=seed)
    for _ in range(learn_steps):
        agent.learn(buf.sample(64))

    if getattr(env, "checks_discounting", False):
        # value(s0) must equal gamma * value(s1), value(s1) ~ 1 (per agent)
        v0 = agent.critic_values(_batch_one(env.sample_obs[0]))
        v1 = agent.critic_values(_batch_one(env.sample_obs[1]))
        for a in env.agent_ids:
            q1 = float(np.asarray(v1[a]).reshape(-1)[0])
            q0 = float(np.asarray(v0[a]).reshape(-1)[0])
            np.testing.assert_allclose(q1, 1.0, atol=atol)
            np.testing.assert_allclose(q0, agent.gamma * q1, atol=atol)
    if env.v_values is not None:
        # centralized critic value at the joint sample obs (uniform behavior
        # policy): compare per agent
        for obs_dict, vrow in zip(env.sample_obs, env.v_values):
            preds = agent.critic_values(_batch_one(obs_dict))
            for a, want in vrow.items():
                np.testing.assert_allclose(
                    float(np.asarray(preds[a]).reshape(-1)[0]), want, atol=atol
                )
    if env.policy_values is not None:
        for obs_dict, prow in zip(env.sample_obs, env.policy_values):
            acts = agent.get_action(_batch_one(obs_dict), training=False)
            for a, want in prow.items():
                if want is None:
                    continue
                got = np.asarray(acts[a]).reshape(-1)
                if isinstance(env.action_spaces[a], spaces.Discrete):
                    assert int(got[0]) == int(want), (a, got, want)
                else:
                    np.testing.assert_allclose(got, want, atol=atol)


def check_ma_on_policy_with_probe_env(
    env, algo_class, algo_args: dict, train_iters: int = 60, seed: int = 42,
    atol: float = 0.2, solved_reward: Optional[float] = 0.95,
) -> None:
    """Train a multi-agent on-policy algorithm (IPPO) on a probe env and assert
    per-agent deterministic policies (parity: probe_envs_ma.py:1958).

    Stops once the mean episodic reward stays >= ``solved_reward`` for three
    consecutive iterations: on a SOLVED one-step probe the advantages are pure
    bootstrap noise, and PPO-family updates on normalised noise destabilise a
    perfect policy — the probe asserts the mapping is learnable, so train-to-
    solve is the correct budget (same role as `target` in the trainers)."""
    from agilerl_tpu.envs.multi_agent import MultiAgentJaxVecEnv

    vec = MultiAgentJaxVecEnv(env, num_envs=8, seed=seed)
    vec.observation_spaces = env.observation_spaces
    vec.action_spaces = env.action_spaces
    agent = algo_class(**algo_args)
    solved_streak = 0
    for _ in range(train_iters):
        mean_rew = agent.collect_rollouts(vec)
        agent.learn()
        if solved_reward is not None and mean_rew >= solved_reward:
            solved_streak += 1
            if solved_streak >= 3:
                break
        else:
            solved_streak = 0

    assert env.policy_values is not None
    for obs_dict, prow in zip(env.sample_obs, env.policy_values):
        acts = agent.get_action(_batch_one(obs_dict), training=False)
        for a, want in prow.items():
            if want is None:
                continue
            got = np.asarray(acts[a]).reshape(-1)
            if isinstance(env.action_spaces[a], spaces.Discrete):
                assert int(got[0]) == int(want), (a, got, want)
            else:
                np.testing.assert_allclose(got, want, atol=atol)
