"""agilerl_tpu — a TPU-native evolutionary reinforcement-learning framework.

Brand-new JAX/XLA/Pallas implementation with the capability surface of AgileRL
(evolutionary HPO over populations of agents; on-/off-policy, offline,
multi-agent, bandit and LLM-finetuning RL) designed TPU-first:

- agents are pytrees of arrays + static configs; architecture mutations change
  the static config and trigger XLA recompilation with weight-preserving pytree
  surgery (vs. the reference's torch module re-instantiation,
  agilerl/modules/base.py:260)
- populations shard across a device mesh with ICI collectives for tournament
  selection (vs. rank-0 + broadcast_object_list, agilerl/hpo/tournament.py:161)
- the LLM stack is GSPMD-sharded pjit (vs. DeepSpeed ZeRO) with an in-tree
  jitted generate loop (vs. vLLM colocate, agilerl/algorithms/core/base.py:3101)
- sequence parallelism via ring attention over ICI (absent in the reference)
"""

__version__ = "0.1.0"

from agilerl_tpu import (
    algorithms,
    analysis,
    components,
    envs,
    hpo,
    llm,
    modules,
    networks,
    observability,
    ops,
    parallel,
    rollouts,
    training,
    utils,
    vector,
    wrappers,
)

__all__ = [
    "algorithms",
    "analysis",
    "components",
    "envs",
    "hpo",
    "llm",
    "modules",
    "networks",
    "observability",
    "ops",
    "parallel",
    "rollouts",
    "training",
    "utils",
    "vector",
    "wrappers",
]
