"""Learning wrappers (parity: agilerl/wrappers/learning.py — Skill:9 curriculum
wrapper, BanditEnv:40 labelled-dataset -> contextual bandit).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import numpy as np


class BanditEnv:
    """Turn a labelled dataset into a contextual bandit (parity: learning.py:40).

    Each step presents one sample encoded as arm-wise contexts via the
    disjoint-model trick: context for arm a is the feature vector placed in the
    a-th block of a (num_arms * dim) vector. Reward 1 for the correct label."""

    def __init__(self, features: np.ndarray, targets: np.ndarray):
        self.features = np.asarray(features, np.float32)
        self.targets = np.asarray(targets).astype(np.int64)
        if self.features.ndim > 2:
            self.features = self.features.reshape(len(self.features), -1)
        self.num_samples, self.dim = self.features.shape
        self.arms = int(self.targets.max()) + 1
        self.context_dim = self.arms * self.dim
        self._rng = np.random.default_rng(0)
        self._idx = 0
        # gym-style spaces so create_population can size networks directly
        # (reference benchmarking scripts pass context_dim/action_dim by hand;
        # exposing spaces keeps our single create_population signature)
        from gymnasium import spaces

        self.observation_space = spaces.Box(-np.inf, np.inf, (self.context_dim,),
                                            np.float32)
        self.action_space = spaces.Discrete(self.arms)

    def _context(self, i: int) -> np.ndarray:
        x = self.features[i]
        ctx = np.zeros((self.arms, self.context_dim), np.float32)
        for a in range(self.arms):
            ctx[a, a * self.dim : (a + 1) * self.dim] = x
        return ctx

    def reset(self) -> np.ndarray:
        self._idx = int(self._rng.integers(0, self.num_samples))
        return self._context(self._idx)

    def step(self, action) -> Tuple[np.ndarray, np.ndarray]:
        reward = np.float32(1.0 if int(action) == int(self.targets[self._idx]) else 0.0)
        self._idx = int(self._rng.integers(0, self.num_samples))
        return self._context(self._idx), reward


class Skill:
    """Curriculum skill wrapper (parity: learning.py:9): overrides the reward
    with a skill-specific shaping while delegating everything else."""

    def __init__(self, env):
        self.env = env
        self.observation_space = env.observation_space
        self.action_space = env.action_space

    def reset(self, **kwargs):
        return self.env.reset(**kwargs)

    def step(self, action):
        obs, reward, terminated, truncated, info = self.env.step(action)
        obs, reward, terminated, truncated, info = self.skill_reward(
            obs, reward, terminated, truncated, info
        )
        return obs, reward, terminated, truncated, info

    def skill_reward(self, obs, reward, terminated, truncated, info):
        """Override in subclasses to shape rewards for this skill."""
        return obs, reward, terminated, truncated, info

    def __getattr__(self, item):
        return getattr(self.env, item)
