from agilerl_tpu.wrappers.agent import AsyncAgentsWrapper, RSNorm, RunningMeanStd
from agilerl_tpu.wrappers.learning import BanditEnv, Skill
from agilerl_tpu.wrappers.make_evolvable import MakeEvolvable
from agilerl_tpu.wrappers.pettingzoo_wrappers import (
    PettingZooAutoResetParallelWrapper,
)

__all__ = [
    "RSNorm",
    "RunningMeanStd",
    "AsyncAgentsWrapper",
    "BanditEnv",
    "Skill",
    "MakeEvolvable",
    "PettingZooAutoResetParallelWrapper",
]
