from agilerl_tpu.wrappers.agent import AsyncAgentsWrapper, RSNorm, RunningMeanStd
from agilerl_tpu.wrappers.learning import BanditEnv, Skill

__all__ = ["RSNorm", "RunningMeanStd", "AsyncAgentsWrapper", "BanditEnv", "Skill"]
