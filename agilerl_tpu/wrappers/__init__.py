from agilerl_tpu.wrappers.agent import AsyncAgentsWrapper, RSNorm, RunningMeanStd
from agilerl_tpu.wrappers.learning import BanditEnv, Skill
from agilerl_tpu.wrappers.make_evolvable import MakeEvolvable

__all__ = [
    "RSNorm",
    "RunningMeanStd",
    "AsyncAgentsWrapper",
    "BanditEnv",
    "Skill",
    "MakeEvolvable",
]
