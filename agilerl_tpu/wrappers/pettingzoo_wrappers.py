"""Standalone PettingZoo parallel-env wrappers (parity:
agilerl/wrappers/pettingzoo_wrappers.py:14 — the single-env autoreset
wrapper users apply outside the vectorised path; the in-tree vec envs
(vector/pz_async_vec_env.py) autoreset internally and don't need it)."""

from __future__ import annotations


class PettingZooAutoResetParallelWrapper:
    """Reset the wrapped parallel env automatically once EVERY agent's
    episode has ended (terminated or truncated). Everything not overridden
    here (agents, state(), render_mode, spaces, ...) delegates to the
    wrapped env, so the full parallel-env surface stays available."""

    def __init__(self, env) -> None:
        self.env = env

    def __getattr__(self, name):
        # only called for names NOT found on the wrapper itself
        return getattr(self.env, name)

    def reset(self, seed=None, options=None):
        return self.env.reset(seed=seed, options=options)

    def step(self, actions):
        obs, rewards, terminations, truncations, infos = self.env.step(actions)
        agents = set(terminations) | set(truncations)
        if agents and all(
            terminations.get(a, False) or truncations.get(a, False)
            for a in agents
        ):
            obs, infos = self.env.reset()
        return obs, rewards, terminations, truncations, infos

    @property
    def unwrapped(self):
        return getattr(self.env, "unwrapped", self.env)
