"""Agent wrappers (parity: agilerl/wrappers/agent.py — RSNorm:225 online obs
normalisation with Welford running stats (wrappers/utils.py:6 RunningMeanStd),
AsyncAgentsWrapper:458 for turn-based PettingZoo envs).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np


class RunningMeanStd:
    """Welford online mean/variance (parity: wrappers/utils.py:6)."""

    def __init__(self, shape=(), epsilon: float = 1e-4):
        self.mean = np.zeros(shape, np.float64)
        self.var = np.ones(shape, np.float64)
        self.count = epsilon

    def update(self, x: np.ndarray) -> None:
        x = np.asarray(x, np.float64)
        if x.ndim == len(self.mean.shape):
            x = x[None]
        batch_mean = x.mean(axis=0)
        batch_var = x.var(axis=0)
        batch_count = x.shape[0]
        delta = batch_mean - self.mean
        tot = self.count + batch_count
        self.mean = self.mean + delta * batch_count / tot
        m_a = self.var * self.count
        m_b = batch_var * batch_count
        m2 = m_a + m_b + np.square(delta) * self.count * batch_count / tot
        self.var = m2 / tot
        self.count = tot

    def normalize(self, x: np.ndarray) -> np.ndarray:
        return ((np.asarray(x, np.float64) - self.mean) / np.sqrt(self.var + 1e-8)).astype(
            np.float32
        )


class RSNorm:
    """Transparent observation-normalising agent wrapper (parity: agent.py:225).

    Wraps any agent: intercepts get_action/learn/test, normalising observations
    with running statistics updated during training."""

    def __init__(self, agent):
        self.agent = agent
        obs_space = getattr(agent, "observation_space", None)
        if obs_space is not None and hasattr(obs_space, "shape") and obs_space.shape:
            self.rms: Any = RunningMeanStd(obs_space.shape)
        else:
            self.rms = RunningMeanStd(())

    def _norm_obs(self, obs, update: bool = True):
        if isinstance(obs, dict):
            return obs  # dict spaces: pass through (per-key norm TODO parity)
        if update:
            self.rms.update(obs)
        return self.rms.normalize(obs)

    def get_action(self, obs, *args, training: bool = True, **kwargs):
        obs = self._norm_obs(obs, update=training)
        return self.agent.get_action(obs, *args, training=training, **kwargs)

    def _norm_batch(self, batch):
        batch = dict(batch)
        if "obs" in batch and not isinstance(batch["obs"], dict):
            batch["obs"] = self.rms.normalize(np.asarray(batch["obs"]))
        if "next_obs" in batch and not isinstance(batch["next_obs"], dict):
            batch["next_obs"] = self.rms.normalize(np.asarray(batch["next_obs"]))
        return batch

    def learn(self, experiences, *args, **kwargs):
        if isinstance(experiences, dict):
            experiences = self._norm_batch(experiences)
        elif isinstance(experiences, tuple) and experiences and isinstance(
            experiences[0], dict
        ):
            # PER/n-step tuples: (batch, idxs, weights[, n_batch]) — normalise
            # every dict element (review finding; parity with the reference's
            # tuple handling)
            experiences = tuple(
                self._norm_batch(e) if isinstance(e, dict) else e
                for e in experiences
            )
        return self.agent.learn(experiences, *args, **kwargs)

    def test(self, env, *args, **kwargs):
        return self.agent.test(env, *args, **kwargs)

    def __getattr__(self, item):
        return getattr(self.agent, item)


class AsyncAgentsWrapper:
    """Turn-based (AEC-style) PettingZoo support (parity: agent.py:458).

    In a turn-based env only a subset of agents observes/acts each step, and an
    agent's experience spans from its action until its NEXT turn (accumulating
    the rewards in between). This wrapper:
    - ``get_action``: filters to the active agents (entries whose obs is not
      None) before delegating, so multi-agent algorithms always see full
      batched dicts;
    - ``record_step``: buffers each acting agent's (obs, action) and, when that
      agent's next turn (or episode end) arrives, emits its completed
      transition with the accumulated inter-turn reward.
    """

    def __init__(self, agent):
        self.agent = agent
        self._pending: Dict[str, Dict[str, Any]] = {}

    def get_action(self, obs, *args, **kwargs):
        active = {a: o for a, o in obs.items() if o is not None}
        if not active:
            return {a: None for a in obs}
        # multi-agent algorithms index obs by EVERY agent id — substitute
        # zero placeholders for inactive agents, then drop their actions
        ref = next(iter(active.values()))
        batch_shape = np.asarray(ref).shape[:1] if np.asarray(ref).ndim > 1 else ()
        full = {}
        for aid in obs:
            if obs[aid] is not None:
                full[aid] = obs[aid]
            else:
                space = self.agent.observation_spaces[aid]
                full[aid] = np.zeros(batch_shape + tuple(space.shape), np.float32)
        actions = self.agent.get_action(full, *args, **kwargs)
        return {a: (actions.get(a) if obs[a] is not None else None) for a in obs}

    def record_step(self, obs, actions, rewards, dones):
        """Feed one env step; returns a list of ``(agent_id, transition)``
        pairs for experiences that just closed (parity: the reference's
        inactive-agent experience buffering, agent.py:458).

        A list (not a dict) because one step can close TWO transitions for the
        same agent — the buffered inter-turn one and the episode-ending action
        — and consumers key multi-agent buffers by real agent ids (advisor
        finding: synthetic '#final' keys would mis-key them).
        """
        completed: list = []
        for aid, r in rewards.items():
            if aid in self._pending:
                self._pending[aid]["reward"] += float(np.asarray(r).squeeze())
        for aid, o in obs.items():
            pending = self._pending.get(aid)
            acted_now = actions.get(aid) is not None and o is not None
            done = bool(np.asarray(dones.get(aid, False)).squeeze())
            if pending is not None and (acted_now or done):
                completed.append((aid, {
                    "obs": pending["obs"],
                    "action": pending["action"],
                    "reward": np.float32(pending["reward"]),
                    "next_obs": o if o is not None else pending["obs"],
                    "done": np.float32(done),
                }))
                del self._pending[aid]
            if acted_now and not done:
                self._pending[aid] = {
                    "obs": o, "action": actions[aid], "reward": 0.0,
                }
            elif acted_now and done:
                # the episode-ending action closes immediately with this
                # step's reward (it would otherwise be dropped — review finding)
                completed.append((aid, {
                    "obs": o,
                    "action": actions[aid],
                    "reward": np.float32(np.asarray(rewards.get(aid, 0.0)).squeeze()),
                    "next_obs": o,
                    "done": np.float32(1.0),
                }))
        return completed

    def reset(self):
        self._pending = {}

    def learn(self, experiences, *args, **kwargs):
        return self.agent.learn(experiences, *args, **kwargs)

    def __getattr__(self, item):
        return getattr(self.agent, item)
