"""Agent wrappers (parity: agilerl/wrappers/agent.py — RSNorm:225 online obs
normalisation with Welford running stats (wrappers/utils.py:6 RunningMeanStd),
AsyncAgentsWrapper:458 for turn-based PettingZoo envs).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np


class RunningMeanStd:
    """Welford online mean/variance (parity: wrappers/utils.py:6)."""

    def __init__(self, shape=(), epsilon: float = 1e-4):
        self.mean = np.zeros(shape, np.float64)
        self.var = np.ones(shape, np.float64)
        self.count = epsilon

    def update(self, x: np.ndarray) -> None:
        x = np.asarray(x, np.float64)
        if x.ndim == len(self.mean.shape):
            x = x[None]
        batch_mean = x.mean(axis=0)
        batch_var = x.var(axis=0)
        batch_count = x.shape[0]
        delta = batch_mean - self.mean
        tot = self.count + batch_count
        self.mean = self.mean + delta * batch_count / tot
        m_a = self.var * self.count
        m_b = batch_var * batch_count
        m2 = m_a + m_b + np.square(delta) * self.count * batch_count / tot
        self.var = m2 / tot
        self.count = tot

    def normalize(self, x: np.ndarray) -> np.ndarray:
        return ((np.asarray(x, np.float64) - self.mean) / np.sqrt(self.var + 1e-8)).astype(
            np.float32
        )


def build_rms(observation_space, epsilon: float = 1e-4,
              norm_obs_keys=None):
    """RunningMeanStd tree matching a space's structure (parity: RSNorm.
    build_rms, agent.py:274): Dict spaces get one RMS per (selected) key,
    Tuple spaces one per element."""
    from gymnasium import spaces as S

    if isinstance(observation_space, S.Dict):
        items = observation_space.spaces.items()
        if norm_obs_keys is not None:
            items = [(k, v) for k, v in items if k in norm_obs_keys]
        return {k: build_rms(v, epsilon) for k, v in items}
    if isinstance(observation_space, S.Tuple):
        return tuple(build_rms(v, epsilon) for v in observation_space.spaces)
    if isinstance(observation_space, (S.Discrete, S.MultiDiscrete, S.MultiBinary)):
        # categorical leaves (Discrete keys feeding one-hot encoders) must
        # stay integer — normalising them would break downstream
        # preprocessing. Integer BOX leaves (uint8 images) DO get normalised,
        # as in the reference's build_rms (review finding).
        return None
    return RunningMeanStd(getattr(observation_space, "shape", ()) or (), epsilon)


class RSNorm:
    """Transparent observation-normalising agent wrapper (parity: agent.py:225).

    Wraps any agent — single- or multi-agent, flat/Dict/Tuple observation
    spaces — intercepting get_action/learn, normalising observations with
    running statistics updated during training. ``norm_obs_keys`` restricts
    which Dict keys are normalised (parity: agent.py:252)."""

    def __init__(self, agent, epsilon: float = 1e-4, norm_obs_keys=None):
        self.agent = agent
        self.norm_obs_keys = norm_obs_keys
        self.multi_agent = hasattr(agent, "observation_spaces") and isinstance(
            getattr(agent, "observation_spaces"), dict
        )
        if self.multi_agent:
            self.obs_rms: Any = {
                aid: build_rms(space, epsilon, norm_obs_keys)
                for aid, space in agent.observation_spaces.items()
            }
        else:
            self.obs_rms = build_rms(
                getattr(agent, "observation_space", None), epsilon, norm_obs_keys
            )

    # back-compat: flat single-agent callers read .rms
    @property
    def rms(self):
        return self.obs_rms

    @staticmethod
    def _apply(rms, obs, update: bool):
        if rms is None:  # unnormalised leaf (integer space or unknown)
            return obs
        if not isinstance(rms, (dict, tuple)) and isinstance(obs, (dict, tuple)):
            # structure mismatch (agent without a gymnasium Dict space emitting
            # dict obs): pass through rather than crash (review finding — the
            # pre-rewrite wrapper passed dict obs through unconditionally)
            return obs
        if isinstance(rms, dict):
            out = dict(obs)
            for k, sub in rms.items():
                out[k] = RSNorm._apply(sub, obs[k], update)
            return out
        if isinstance(rms, tuple):
            return tuple(
                RSNorm._apply(sub, o, update) for sub, o in zip(rms, obs)
            )
        if update:
            rms.update(obs)
        return rms.normalize(obs)

    def _norm_obs(self, obs, update: bool = True):
        if self.multi_agent:
            return {
                aid: self._apply(self.obs_rms[aid], o, update)
                if o is not None else None
                for aid, o in obs.items()
            }
        return self._apply(self.obs_rms, obs, update)

    def get_action(self, obs, *args, training: bool = True, **kwargs):
        obs = self._norm_obs(obs, update=training)
        return self.agent.get_action(obs, *args, training=training, **kwargs)

    def _norm_batch(self, batch):
        batch = dict(batch)
        for key in ("obs", "next_obs"):
            if key in batch:
                if self.multi_agent:
                    batch[key] = self._norm_obs(batch[key], update=False)
                else:
                    batch[key] = self._apply(self.obs_rms, batch[key], update=False)
        return batch

    def learn(self, experiences, *args, **kwargs):
        if isinstance(experiences, dict):
            experiences = self._norm_batch(experiences)
        elif isinstance(experiences, tuple) and experiences and isinstance(
            experiences[0], dict
        ):
            # PER/n-step tuples: (batch, idxs, weights[, n_batch]) — normalise
            # every dict element (review finding; parity with the reference's
            # tuple handling)
            experiences = tuple(
                self._norm_batch(e) if isinstance(e, dict) else e
                for e in experiences
            )
        return self.agent.learn(experiences, *args, **kwargs)

    def test(self, env, *args, **kwargs):
        return self.agent.test(env, *args, **kwargs)

    def __getattr__(self, item):
        return getattr(self.agent, item)


class AsyncAgentsWrapper:
    """Turn-based (AEC-style) PettingZoo support (parity: agent.py:458).

    In a turn-based env only a subset of agents observes/acts each step, and an
    agent's experience spans from its action until its NEXT turn (accumulating
    the rewards in between). This wrapper:
    - ``get_action``: filters to the active agents (entries whose obs is not
      None) before delegating, so multi-agent algorithms always see full
      batched dicts;
    - ``record_step``: buffers each acting agent's (obs, action) and, when that
      agent's next turn (or episode end) arrives, emits its completed
      transition with the accumulated inter-turn reward.
    """

    def __init__(self, agent):
        self.agent = agent
        self._pending: Dict[Any, Dict[str, Any]] = {}

    # -- reference-parity NaN-row machinery ----------------------------- #
    @staticmethod
    def _leaf_inactive(value) -> Optional[np.ndarray]:
        """Per-leaf all-NaN row mask; None strictly means 'cannot detect'
        (unbatched or integer leaf). An all-False mask means 'detectably
        active' — the distinction matters when AND-combining leaves."""
        arr = np.asarray(value)
        if arr.ndim < 2 or not np.issubdtype(arr.dtype, np.floating):
            return None
        flat = arr.reshape(arr.shape[0], -1)
        return np.isnan(flat).all(axis=1)

    @staticmethod
    def _inactive_rows(value) -> Optional[np.ndarray]:
        """Boolean [N] mask of env rows where the agent is inactive (all-NaN
        observation across EVERY float leaf — the AsyncPettingZooVecEnv
        placeholder; parity: extract_inactive_agents, agent.py:477). A single
        all-NaN leaf (e.g. one glitched sensor) does NOT mark the row inactive
        when another leaf carries finite data (review finding). None for
        unbatched/int-only obs."""
        if isinstance(value, (dict, tuple)):
            leaves = (list(value.values()) if isinstance(value, dict)
                      else list(value))
            masks = [AsyncAgentsWrapper._leaf_inactive(leaf) for leaf in leaves]
            masks = [m for m in masks if m is not None]
        else:
            m = AsyncAgentsWrapper._leaf_inactive(value)
            masks = [m] if m is not None else []
        if not masks:
            return None
        out = masks[0]
        for m in masks[1:]:
            out = out & m
        return out if out.any() else None

    def extract_inactive_agents(self, obs):
        """Split a batched observation dict into ({agent: inactive row idx},
        obs with NaN rows zero-substituted) (parity: agent.py:477 — the
        reference drops the rows; our algorithms take full batched dicts, so
        rows are substituted and the resulting actions masked instead)."""
        inactive: Dict[str, np.ndarray] = {}
        cleaned = {}
        for aid, value in obs.items():
            mask = self._inactive_rows(value) if value is not None else None
            if mask is None:
                cleaned[aid] = value
                continue
            inactive[aid] = np.where(mask)[0]
            cleaned[aid] = self._substitute_rows(value, mask)
        return inactive, cleaned

    @staticmethod
    def _substitute_rows(value, mask):
        if isinstance(value, dict):
            return {k: AsyncAgentsWrapper._substitute_rows(v, mask)
                    for k, v in value.items()}
        if isinstance(value, tuple):
            return tuple(AsyncAgentsWrapper._substitute_rows(v, mask)
                         for v in value)
        arr = np.array(value, copy=True)
        if arr.ndim >= 1 and np.issubdtype(arr.dtype, np.floating):
            arr[mask] = 0.0
        return arr

    def get_action(self, obs, *args, **kwargs):
        active = {a: o for a, o in obs.items() if o is not None}
        if not active:
            return {a: None for a in obs}
        # vectorized partial activity: zero-substitute NaN rows, act, then
        # mask the placeholder rows' actions (parity: get_action, agent.py:560)
        inactive, cleaned = self.extract_inactive_agents(active)
        # multi-agent algorithms index obs by EVERY agent id — substitute
        # zero placeholders for fully-absent agents, then drop their actions
        ref = next(iter(cleaned.values()))
        ref_leaf = ref if not isinstance(ref, (dict, tuple)) else (
            next(iter(ref.values())) if isinstance(ref, dict) else ref[0]
        )
        batch_shape = (
            np.asarray(ref_leaf).shape[:1] if np.asarray(ref_leaf).ndim > 1 else ()
        )
        full = {}
        for aid in obs:
            if obs[aid] is not None:
                full[aid] = cleaned[aid]
            else:
                space = self.agent.observation_spaces[aid]
                full[aid] = np.zeros(batch_shape + tuple(space.shape), np.float32)
        actions = self.agent.get_action(full, *args, **kwargs)
        out = {}
        for a in obs:
            if obs[a] is None:
                out[a] = None
                continue
            act = actions.get(a)
            rows = inactive.get(a)
            if rows is not None and act is not None and len(rows):
                act = np.array(act, copy=True)
                if np.issubdtype(act.dtype, np.integer):
                    act[rows] = 0  # env discards these; 0 keeps the dtype
                else:
                    act = act.astype(np.float32)
                    act[rows] = np.nan
            out[a] = act
        return out

    def record_step(self, obs, actions, rewards, dones, autoreset=None):
        """Feed one env step; returns a list of ``(agent_id, transition)``
        pairs for experiences that just closed (parity: the reference's
        inactive-agent experience buffering, agent.py:458).

        A list (not a dict) because one step can close TWO transitions for the
        same agent — the buffered inter-turn one and the episode-ending action
        — and consumers key multi-agent buffers by real agent ids (advisor
        finding: synthetic '#final' keys would mis-key them).

        Vectorized envs (NaN-placeholder rows from AsyncPettingZooVecEnv)
        dispatch to ``record_step_vec``, which buffers per (agent, env index)
        and returns ``(agent_id, env_idx, transition)`` triples.
        """
        for aid, value in obs.items():
            if value is not None and self._looks_batched(aid, value):
                return self.record_step_vec(obs, actions, rewards, dones,
                                            autoreset=autoreset)
        completed: list = []
        for aid, r in rewards.items():
            if aid in self._pending:
                self._pending[aid]["reward"] += float(np.asarray(r).squeeze())
        for aid, o in obs.items():
            pending = self._pending.get(aid)
            acted_now = actions.get(aid) is not None and o is not None
            done = bool(np.asarray(dones.get(aid, False)).squeeze())
            if pending is not None and (acted_now or done):
                completed.append((aid, {
                    "obs": pending["obs"],
                    "action": pending["action"],
                    "reward": np.float32(pending["reward"]),
                    "next_obs": o if o is not None else pending["obs"],
                    "done": np.float32(done),
                }))
                del self._pending[aid]
            if acted_now and not done:
                self._pending[aid] = {
                    "obs": o, "action": actions[aid], "reward": 0.0,
                }
            elif acted_now and done:
                # the episode-ending action closes immediately with this
                # step's reward (it would otherwise be dropped — review finding)
                completed.append((aid, {
                    "obs": o,
                    "action": actions[aid],
                    "reward": np.float32(np.asarray(rewards.get(aid, 0.0)).squeeze()),
                    "next_obs": o,
                    "done": np.float32(1.0),
                }))
        return completed

    def _looks_batched(self, aid, value) -> bool:
        """Batched iff the leading axis is a batch axis over the agent's
        observation space — NOT merely ndim>=2, which would misroute
        unbatched image/board observations (review finding)."""
        space = getattr(self.agent, "observation_spaces", {}).get(aid)
        if isinstance(value, dict):
            key = next(iter(value))
            sub = space.spaces.get(key) if space is not None and hasattr(space, "spaces") else None
            return self._leaf_batched(value[key], sub)
        if isinstance(value, tuple):
            sub = space.spaces[0] if space is not None and hasattr(space, "spaces") else None
            return self._leaf_batched(value[0], sub)
        return self._leaf_batched(value, space)

    @staticmethod
    def _leaf_batched(leaf, space) -> bool:
        arr = np.asarray(leaf)
        if space is not None and getattr(space, "shape", None) is not None:
            return arr.ndim > len(space.shape)
        return arr.ndim >= 2

    @staticmethod
    def _row(value, i):
        if isinstance(value, dict):
            return {k: AsyncAgentsWrapper._row(v, i) for k, v in value.items()}
        if isinstance(value, tuple):
            return tuple(AsyncAgentsWrapper._row(v, i) for v in value)
        return np.asarray(value)[i]

    def record_step_vec(self, obs, actions, rewards, dones, autoreset=None):
        """Per-(agent, env-row) turn buffering over a vectorized async env
        (parity: the reference's inactive-agent handling rides NaN
        placeholders the same way, agent.py:477/560). An agent's row is
        inactive when its observation row is all-NaN; its action row is NaN
        (or the 0 placeholder get_action wrote) and ignored. Rewards at
        inactive rows are NaN per get_placeholder_value and skipped.

        ``autoreset``: boolean [N] mask of env rows whose EPISODE just ended
        (AsyncPettingZooVecEnv provides it as ``info["autoreset"]``) — pass it
        for EXACT closure semantics: pending transitions close with done=1
        precisely at autoreset rows, and one agent dying mid-episode leaves
        its teammates' in-flight transitions open. Without the mask the
        fallback is conservative: ANY agent's done closes all pendings at
        that row (turn-based envs report done only for the agent that acted
        last — an AND-of-dones would never fire and stale pendings would
        bootstrap across the reset, which is strictly worse than the
        occasional early closure).

        Returns a list of ``(agent_id, env_idx, transition)`` triples.
        """
        completed: list = []
        if autoreset is not None:
            episode_end = np.asarray(autoreset, bool).reshape(-1)
        else:
            episode_end = None
            for aid, d in dones.items():
                if d is None:
                    continue
                d = np.asarray(d, np.float64).reshape(-1)
                flags = np.nan_to_num(d, nan=0.0).astype(bool)
                episode_end = flags if episode_end is None \
                    else (episode_end | flags)
        for aid, r in rewards.items():
            if r is None:
                continue
            r = np.asarray(r, np.float64).reshape(-1)
            for i in range(r.shape[0]):
                key = (aid, i)
                if key in self._pending and not np.isnan(r[i]):
                    self._pending[key]["reward"] += float(r[i])
        for aid, value in obs.items():
            if value is None:
                continue
            mask = self._inactive_rows(value)
            n = np.asarray(
                value if not isinstance(value, (dict, tuple)) else (
                    next(iter(value.values())) if isinstance(value, dict)
                    else value[0]
                )
            ).shape[0]
            act = actions.get(aid)
            d_val = dones.get(aid)
            done_arr = np.asarray(
                d_val if d_val is not None else np.zeros(n), np.float64
            ).reshape(-1)
            for i in range(n):
                inactive = bool(mask[i]) if mask is not None else False
                row_act = None if act is None else np.asarray(act)[i]
                if row_act is not None and np.issubdtype(
                    np.asarray(row_act).dtype, np.floating
                ) and np.isnan(np.asarray(row_act)).all():
                    row_act = None
                acted_now = (not inactive) and row_act is not None
                d = done_arr[i]
                done = bool(d) and not np.isnan(d)
                # the EPISODE ending at this row closes every pending
                # transition there — a dead agent's buffered step must not
                # bootstrap into the NEXT episode after autoreset
                if episode_end is not None and episode_end[i]:
                    done = True
                key = (aid, i)
                pending = self._pending.get(key)
                o_row = self._row(value, i)
                if pending is not None and (acted_now or done):
                    completed.append((aid, i, {
                        "obs": pending["obs"],
                        "action": pending["action"],
                        "reward": np.float32(pending["reward"]),
                        "next_obs": o_row if not inactive else pending["obs"],
                        "done": np.float32(done),
                    }))
                    del self._pending[key]
                if acted_now and not done:
                    self._pending[key] = {
                        "obs": o_row, "action": row_act, "reward": 0.0,
                    }
                elif acted_now and done:
                    r_val = rewards.get(aid)
                    r_now = np.asarray(
                        r_val if r_val is not None else np.zeros(n), np.float64
                    ).reshape(-1)[i]
                    completed.append((aid, i, {
                        "obs": o_row,
                        "action": row_act,
                        "reward": np.float32(0.0 if np.isnan(r_now) else r_now),
                        "next_obs": o_row,
                        "done": np.float32(1.0),
                    }))
        return completed

    def reset(self):
        self._pending = {}

    def learn(self, experiences, *args, **kwargs):
        return self.agent.learn(experiences, *args, **kwargs)

    def __getattr__(self, item):
        return getattr(self.agent, item)
