"""MakeEvolvable (deprecated; parity: agilerl/wrappers/make_evolvable.py:26 —
reflects an arbitrary torch nn.Module into an evolvable clone).

The reference introspects a torch module's layer list to rebuild it as an
evolvable net. The JAX analogue takes an (init_fn, apply_fn) pair or an
architecture description and rebuilds it as an EvolvableMLP/EvolvableCNN. As in
the reference, this path is DEPRECATED — prefer constructing Evolvable* modules
directly or using DummyEvolvable for frozen nets.
"""

from __future__ import annotations

import warnings
from typing import Any, Optional, Sequence

import jax
import numpy as np


def MakeEvolvable(
    num_inputs: Optional[int] = None,
    num_outputs: Optional[int] = None,
    hidden_layers: Optional[Sequence[int]] = None,
    input_shape: Optional[Sequence[int]] = None,
    channels: Optional[Sequence[int]] = None,
    kernels: Optional[Sequence[int]] = None,
    strides: Optional[Sequence[int]] = None,
    activation: str = "ReLU",
    key: Optional[jax.Array] = None,
):
    """Build an evolvable net from a plain architecture description."""
    warnings.warn(
        "MakeEvolvable is deprecated (as in the reference); construct "
        "EvolvableMLP/EvolvableCNN directly.",
        DeprecationWarning,
        stacklevel=2,
    )
    if key is None:
        key = jax.random.PRNGKey(np.random.randint(0, 2**31 - 1))
    if input_shape is not None and channels is not None:
        from agilerl_tpu.modules.cnn import EvolvableCNN

        return EvolvableCNN(
            input_shape=tuple(input_shape),
            num_outputs=num_outputs,
            channel_size=tuple(channels),
            kernel_size=tuple(kernels or [3] * len(channels)),
            stride_size=tuple(strides or [1] * len(channels)),
            activation=activation,
            key=key,
        )
    from agilerl_tpu.modules.mlp import EvolvableMLP

    return EvolvableMLP(
        num_inputs=num_inputs,
        num_outputs=num_outputs,
        hidden_size=tuple(hidden_layers or (64, 64)),
        activation=activation,
        key=key,
    )
