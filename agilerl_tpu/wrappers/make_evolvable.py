"""MakeEvolvable (parity: agilerl/wrappers/make_evolvable.py:26 — reflects an
arbitrary torch nn.Module into an evolvable clone).

Two entry modes, matching the reference's surface:

1. **Module introspection** (reference detect_architecture,
   make_evolvable.py:307): pass a torch ``nn.Module`` plus an example
   ``input_tensor``. Forward hooks record the Linear/Conv2d/activation/norm
   sequence in call order; the detected architecture is rebuilt as an
   EvolvableMLP or EvolvableCNN and — beyond the reference — the torch weights
   are imported into the JAX params, so the evolvable clone is
   forward-equivalent to the original network (tested to ~1e-5). torch is
   host-side only here: it is used purely as a reflection source; compute runs
   in JAX.

2. **Architecture description** (kwargs): build an EvolvableMLP/EvolvableCNN
   directly from sizes. Kept for callers that have no torch module.

As in the reference, this wrapper is a migration aid — prefer constructing
Evolvable* modules directly.
"""

from __future__ import annotations

import warnings
from typing import Any, Optional, Sequence

import jax
import numpy as np
from agilerl_tpu.utils.rng import derive_key

SUPPORTED_ACTIVATIONS = {
    "ReLU": "ReLU",
    "Tanh": "Tanh",
    "Sigmoid": "Sigmoid",
    "GELU": "GELU",
    "ELU": "ELU",
    "LeakyReLU": "LeakyReLU",
    "Softsign": "Softsign",
    "Softplus": "Softplus",
    "PReLU": "PReLU",
    "Identity": "Identity",
    "Mish": "Mish",
    "SiLU": "SiLU",
}


def _detect_torch_architecture(network, input_tensor):
    """Run one forward pass with hooks and return the layer record in call
    order (reference detect_architecture, make_evolvable.py:307)."""
    import torch
    import torch.nn as nn

    records = []

    def hook(module, args, output):
        if isinstance(module, nn.Linear):
            records.append(("linear", module))
        elif isinstance(module, nn.Conv2d):
            records.append(("conv", module))
        elif isinstance(module, nn.LayerNorm):
            records.append(("layernorm", module))
        elif type(module).__name__ in SUPPORTED_ACTIVATIONS:
            records.append(("act", module))
        elif isinstance(module, (nn.Flatten, nn.Identity, nn.Dropout)):
            pass
        elif len(list(module.children())) == 0 and not isinstance(
            module, (nn.Sequential, nn.ModuleList)
        ):
            records.append(("unsupported", module))

    handles = [m.register_forward_hook(hook) for m in network.modules()]
    try:
        with torch.no_grad():
            network(input_tensor)
    finally:
        for h in handles:
            h.remove()
    return records


def _nhwc_permutation(c: int, h: int, w: int) -> np.ndarray:
    """Index map from torch's flattened NCHW features to our NHWC flatten
    order: perm[j] = the NCHW flat index that lands at NHWC flat position j."""
    idx = np.arange(c * h * w).reshape(c, h, w)  # value = torch flat index
    return idx.transpose(1, 2, 0).reshape(-1)  # NHWC order


def _from_torch_module(network, input_tensor, key):
    """Rebuild a torch module as an evolvable JAX clone with imported weights."""
    import torch

    records = _detect_torch_architecture(network, input_tensor)
    unsupported = [type(m).__name__ for k, m in records if k == "unsupported"]
    if unsupported:
        raise ValueError(
            f"MakeEvolvable cannot reflect layers {sorted(set(unsupported))}; "
            "supported: Linear, Conv2d, LayerNorm, Flatten and standard "
            "activations (reference supports the same families)"
        )

    convs = [m for k, m in records if k == "conv"]
    linears = [m for k, m in records if k == "linear"]
    if not linears:
        raise ValueError("network must end in at least one Linear layer")

    # activation between hidden layers = the activation seen BEFORE the final
    # linear (an activation appearing only after it is the output activation,
    # not a hidden one); Evolvable modules use ONE activation network-wide, so
    # mixed hidden activations cannot be reflected faithfully — raise
    last_linear_pos = max(i for i, (k, _) in enumerate(records) if k == "linear")
    hidden_act_mods = [m for k, m in records[:last_linear_pos] if k == "act"]
    hidden_acts = sorted({type(m).__name__ for m in hidden_act_mods})
    if len(hidden_acts) > 1:
        raise ValueError(
            f"MakeEvolvable needs a single hidden activation (found "
            f"{hidden_acts}); Evolvable modules apply one activation "
            "network-wide"
        )
    hidden_act = (
        SUPPORTED_ACTIVATIONS.get(hidden_acts[0], "ReLU") if hidden_acts else "Identity"
    )
    out_acts = [
        type(m).__name__ for k, m in records[last_linear_pos + 1:] if k == "act"
    ]
    output_activation = SUPPORTED_ACTIVATIONS.get(out_acts[0]) if out_acts else None
    for k, m in records:
        # PReLU's slope is LEARNABLE in torch; our PReLU is fixed at 0.25 —
        # anything else would silently break forward equivalence
        if k == "act" and type(m).__name__ == "PReLU":
            w = m.weight.detach().cpu().numpy()
            if w.size != 1 or abs(float(w.ravel()[0]) - 0.25) > 1e-6:
                raise ValueError(
                    "MakeEvolvable cannot reflect PReLU with a trained/"
                    "per-channel slope (JAX side uses a fixed 0.25 slope)"
                )
    norms = [m for k, m in records if k == "layernorm"]

    def t2np(t, like=None, fill=0.0) -> np.ndarray:
        if t is None:  # bias=False / affine-less layers
            return np.full(like, fill, np.float32)
        return t.detach().cpu().numpy().astype(np.float32)

    if convs:
        if len(linears) != 1:
            raise ValueError(
                "conv networks must end in exactly one Linear head to map onto "
                "EvolvableCNN (conv stack + dense output)"
            )
        if norms:
            # EvolvableCNN's layer_norm is channels-last over conv features —
            # torch LayerNorms in a conv net don't map 1:1, and dropping them
            # would break the forward-equivalence guarantee
            raise ValueError(
                "MakeEvolvable cannot reflect LayerNorm inside conv networks; "
                "remove the norm or construct EvolvableCNN directly"
            )
        for m in convs:
            kh, kw = m.kernel_size
            if kh != kw:
                raise ValueError("only square conv kernels are supported")
            if m.stride[0] != m.stride[1]:
                raise ValueError("only symmetric conv strides are supported")
            if any(p != 0 for p in m.padding):
                raise ValueError("only padding=0 (VALID) convs are supported")
            if tuple(m.dilation) != (1, 1):
                raise ValueError("only dilation=1 convs are supported")
            if m.groups != 1:
                raise ValueError("only groups=1 convs are supported")
        from agilerl_tpu.modules.cnn import EvolvableCNN

        n, c, h, w = input_tensor.shape
        head = linears[0]
        module = EvolvableCNN(
            input_shape=(h, w, c),
            num_outputs=head.out_features,
            channel_size=tuple(m.out_channels for m in convs),
            kernel_size=tuple(m.kernel_size[0] for m in convs),
            stride_size=tuple(m.stride[0] for m in convs),
            activation=hidden_act,
            output_activation=output_activation,
            layer_norm=False,  # torch norms don't map 1:1; keep exact parity
            key=key,
        )
        params = module.params
        for i, m in enumerate(convs):
            # torch OIHW -> our HWIO
            params[f"conv_{i}"]["kernel"] = jax.numpy.asarray(
                t2np(m.weight).transpose(2, 3, 1, 0)
            )
            params[f"conv_{i}"]["bias"] = jax.numpy.asarray(
                t2np(m.bias, like=(m.out_channels,))
            )
        # reorder the head's input features from NCHW-flat to NHWC-flat
        fh, fw = _conv_stack_spatial(h, w, convs)
        perm = _nhwc_permutation(convs[-1].out_channels, fh, fw)
        head_w = t2np(head.weight)  # (out, in) over NCHW-flat features
        params["output"]["kernel"] = jax.numpy.asarray(head_w[:, perm].T)
        params["output"]["bias"] = jax.numpy.asarray(
            t2np(head.bias, like=(head.out_features,))
        )
        module.load_state_dict(params)
        return module

    from agilerl_tpu.modules.mlp import EvolvableMLP

    if len(linears) < 2:
        raise ValueError("MLP networks need at least one hidden Linear + output")
    # EvolvableMLP computes Linear -> LayerNorm -> activation; a torch net
    # ordered differently (e.g. Linear -> act -> LayerNorm) would import
    # cleanly but compute something else — require each norm to directly
    # follow its Linear
    for i, (k, m) in enumerate(records):
        if k == "layernorm" and (i == 0 or records[i - 1][0] != "linear"):
            raise ValueError(
                "MakeEvolvable needs each LayerNorm directly after a Linear "
                "(Evolvable modules compute Linear -> LayerNorm -> activation)"
            )
    if norms and len(norms) != len(linears) - 1:
        # EvolvableMLP norms every hidden layer or none — a partial torch norm
        # pattern would leave fresh (still-normalising) norm_i params in place
        raise ValueError(
            f"MakeEvolvable needs a LayerNorm after every hidden Linear or "
            f"none (found {len(norms)} norms for {len(linears) - 1} hidden "
            "layers)"
        )
    module = EvolvableMLP(
        num_inputs=linears[0].in_features,
        num_outputs=linears[-1].out_features,
        hidden_size=tuple(m.out_features for m in linears[:-1]),
        activation=hidden_act,
        output_activation=output_activation,
        layer_norm=bool(norms),
        key=key,
    )
    params = module.params
    for i, m in enumerate(linears[:-1]):
        params[f"layer_{i}"]["kernel"] = jax.numpy.asarray(t2np(m.weight).T)
        params[f"layer_{i}"]["bias"] = jax.numpy.asarray(
            t2np(m.bias, like=(m.out_features,))
        )
    params["output"]["kernel"] = jax.numpy.asarray(t2np(linears[-1].weight).T)
    params["output"]["bias"] = jax.numpy.asarray(
        t2np(linears[-1].bias, like=(linears[-1].out_features,))
    )
    for i, m in enumerate(norms):
        dim = (m.normalized_shape[-1],)
        # elementwise_affine=False means scale 1 / bias 0 exactly
        params[f"norm_{i}"]["scale"] = jax.numpy.asarray(
            t2np(m.weight, like=dim, fill=1.0)
        )
        params[f"norm_{i}"]["bias"] = jax.numpy.asarray(t2np(m.bias, like=dim))
    module.load_state_dict(params)
    return module


def _conv_stack_spatial(h: int, w: int, convs) -> tuple:
    for m in convs:
        k, s = m.kernel_size[0], m.stride[0]
        h = (h - k) // s + 1
        w = (w - k) // s + 1
    return h, w


def MakeEvolvable(
    network: Any = None,
    input_tensor: Any = None,
    num_inputs: Optional[int] = None,
    num_outputs: Optional[int] = None,
    hidden_layers: Optional[Sequence[int]] = None,
    input_shape: Optional[Sequence[int]] = None,
    channels: Optional[Sequence[int]] = None,
    kernels: Optional[Sequence[int]] = None,
    strides: Optional[Sequence[int]] = None,
    activation: str = "ReLU",
    key: Optional[jax.Array] = None,
):
    """Build an evolvable net by introspecting a torch module (network +
    input_tensor) or from a plain architecture description (kwargs)."""
    if key is None:
        key = derive_key()
    if network is not None:
        if input_tensor is None:
            raise ValueError(
                "MakeEvolvable(network=...) needs an example input_tensor to "
                "trace the architecture (reference make_evolvable.py:82)"
            )
        return _from_torch_module(network, input_tensor, key)

    warnings.warn(
        "MakeEvolvable from an architecture description is deprecated (as in "
        "the reference); construct EvolvableMLP/EvolvableCNN directly.",
        DeprecationWarning,
        stacklevel=2,
    )
    if input_shape is not None and channels is not None:
        from agilerl_tpu.modules.cnn import EvolvableCNN

        return EvolvableCNN(
            input_shape=tuple(input_shape),
            num_outputs=num_outputs,
            channel_size=tuple(channels),
            kernel_size=tuple(kernels or [3] * len(channels)),
            stride_size=tuple(strides or [1] * len(channels)),
            activation=activation,
            key=key,
        )
    from agilerl_tpu.modules.mlp import EvolvableMLP

    return EvolvableMLP(
        num_inputs=num_inputs,
        num_outputs=num_outputs,
        hidden_size=tuple(hidden_layers or (64, 64)),
        activation=activation,
        key=key,
    )
