"""On-policy rollout storage with GAE (parity: agilerl/components/rollout_buffer.py
— RolloutBuffer:26, compute_returns_and_advantages:413 (GAE), flat tensor batches
get_tensor_batch:525, BPTT sequence batches prepare_sequence_tensors:722 /
get_minibatch_sequences:845, incl. recurrent hidden-state storage).

TPU-first: storage is a [T, N, ...] pytree pre-allocated on device; per-step
writes are jitted index updates; GAE is one lax.scan over reversed time; flat
and BPTT-sequence minibatching are jitted gathers over permuted indices.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Iterator, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from agilerl_tpu.utils.rng import derive_key

PyTree = Any


class RolloutState(NamedTuple):
    data: Dict[str, PyTree]  # each leaf [T, N, ...]
    t: jax.Array  # int32 step cursor
    advantages: jax.Array  # [T, N]
    returns: jax.Array  # [T, N]


@functools.partial(jax.jit, donate_argnums=(0,))
def _write_step(state: RolloutState, step: Dict[str, PyTree]) -> RolloutState:
    def write(buf, x):
        return buf.at[state.t].set(jnp.asarray(x).astype(buf.dtype))

    data = dict(state.data)
    for k, v in step.items():
        data[k] = jax.tree_util.tree_map(write, data[k], v)
    return state._replace(data=data, t=state.t + 1)


@functools.partial(jax.jit, static_argnames=("gamma", "gae_lambda"))
def _compute_gae(
    rewards: jax.Array,  # [T, N]
    values: jax.Array,  # [T, N]
    dones: jax.Array,  # [T, N] done AFTER step t (the step's own terminal flag)
    last_value: jax.Array,  # [N] V(s_T) — value of the obs after the last step
    last_done: jax.Array,  # [N] unused (kept for API compat; dones[T-1] already
    # carries the final step's terminal flag under this storage convention)
    gamma: float,
    gae_lambda: float,
) -> Tuple[jax.Array, jax.Array]:
    """GAE via reverse lax.scan (parity: rollout_buffer.py:413).

    Storage convention: dones[t] = 1 iff the episode ended AT step t (the env
    autoresets, so obs[t+1] belongs to the next episode). Hence step t's own
    done masks BOTH its bootstrap and the advantage carried from t+1:
        delta_t = r_t + gamma * V(s_{t+1}) * (1 - done_t) - V(s_t)
        A_t     = delta_t + gamma * lambda * (1 - done_t) * A_{t+1}
    (The CleanRL form indexes dones[t+1] because it stores reset flags; using
    it with per-step terminal flags leaks values across episode boundaries.)"""

    def step(carry, xs):
        gae, next_value = carry
        reward, value, done = xs
        nonterminal = 1.0 - done
        delta = reward + gamma * next_value * nonterminal - value
        gae = delta + gamma * gae_lambda * nonterminal * gae
        return (gae, value), gae

    init = (jnp.zeros_like(last_value), last_value)
    _, adv_rev = jax.lax.scan(
        step, init, (rewards[::-1], values[::-1], dones[::-1])
    )
    advantages = adv_rev[::-1]
    returns = advantages + values
    return advantages, returns


@jax.jit
def _flat_gather(data: PyTree, idx: jax.Array) -> PyTree:
    """Gather flattened [T*N, ...] minibatch by flat indices."""

    def g(buf):
        flat = buf.reshape((-1,) + buf.shape[2:])
        return flat[idx]

    return jax.tree_util.tree_map(g, data)


class RolloutBuffer:
    """Fixed-horizon rollout buffer over N vectorised envs."""

    def __init__(
        self,
        capacity: int,
        num_envs: int,
        gamma: float = 0.99,
        gae_lambda: float = 0.95,
        recurrent: bool = False,
    ):
        self.capacity = int(capacity)
        self.num_envs = int(num_envs)
        self.gamma = float(gamma)
        self.gae_lambda = float(gae_lambda)
        self.recurrent = recurrent
        self.state: Optional[RolloutState] = None
        self._key = derive_key()

    @property
    def full(self) -> bool:
        return self.state is not None and int(self.state.t) >= self.capacity

    def reset(self) -> None:
        if self.state is not None:
            self.state = self.state._replace(t=jnp.zeros((), jnp.int32))

    #: backfill value per key when that key first appears AFTER the schema
    #: was frozen (producers override — e.g. action_mask backfills with 1
    #: because unmasked sampling ≡ all-ones mask)
    backfill_fills = {"action_mask": 1}

    def add(self, **step: PyTree) -> None:
        """step keys: obs, action, reward, done, value, log_prob
        (+ hidden_state pytree when recurrent)."""

        def alloc(x, fill=0):
            x = jnp.asarray(x)
            return jnp.full((self.capacity,) + x.shape, fill, x.dtype)

        if self.state is None:
            data = {k: jax.tree_util.tree_map(alloc, v) for k, v in step.items()}
            self.state = RolloutState(
                data=data,
                t=jnp.zeros((), jnp.int32),
                advantages=jnp.zeros((self.capacity, self.num_envs)),
                returns=jnp.zeros((self.capacity, self.num_envs)),
            )
        elif any(k not in self.state.data for k in step):
            # schema grew after the first add (e.g. an env that only publishes
            # action_mask on step infos, latched mid-rollout): allocate the
            # new key, backfilling prior rows per backfill_fills
            data = dict(self.state.data)
            for k, v in step.items():
                if k not in data:
                    fill = self.backfill_fills.get(k, 0)
                    data[k] = jax.tree_util.tree_map(
                        lambda x, _f=fill: alloc(x, _f), v
                    )
            self.state = self.state._replace(data=data)
        self.state = _write_step(self.state, step)

    def compute_returns_and_advantages(
        self, last_value: jax.Array, last_done: jax.Array
    ) -> None:
        s = self.state
        adv, ret = _compute_gae(
            s.data["reward"].astype(jnp.float32),
            s.data["value"].astype(jnp.float32),
            s.data["done"].astype(jnp.float32),
            jnp.asarray(last_value, jnp.float32),
            jnp.asarray(last_done, jnp.float32),
            self.gamma,
            self.gae_lambda,
        )
        self.state = s._replace(advantages=adv, returns=ret)

    # -- flat minibatches (parity: get_tensor_batch:525) ----------------- #
    def minibatch_indices(
        self, batch_size: int, key: Optional[jax.Array] = None
    ) -> np.ndarray:
        total = self.capacity * self.num_envs
        if key is None:
            self._key, key = jax.random.split(self._key)
        perm = jax.random.permutation(key, total)
        n_batches = max(total // batch_size, 1)
        return np.asarray(perm[: n_batches * batch_size]).reshape(n_batches, batch_size)

    def get_batch(self, idx: jax.Array) -> Dict[str, PyTree]:
        s = self.state
        data = dict(s.data)
        data["advantages"] = s.advantages
        data["returns"] = s.returns
        return _flat_gather(data, jnp.asarray(idx))

    def get_all_flat(self) -> Dict[str, PyTree]:
        s = self.state
        data = dict(s.data)
        data["advantages"] = s.advantages
        data["returns"] = s.returns
        return jax.tree_util.tree_map(
            lambda buf: buf.reshape((-1,) + buf.shape[2:]), data
        )

    # -- BPTT sequence minibatches (parity: get_minibatch_sequences:845) -- #
    def get_sequences(
        self, seq_len: int, key: Optional[jax.Array] = None
    ) -> Dict[str, PyTree]:
        """Chop [T, N] into [num_seqs, seq_len, ...] sequences (time-major
        within each sequence) including the hidden state at each sequence
        start, for truncated-BPTT recurrent PPO."""
        assert self.capacity % seq_len == 0, "capacity must divide by seq_len"
        s = self.state
        n_chunks = self.capacity // seq_len

        def chop(buf):
            # [T, N, ...] -> [n_chunks, seq_len, N, ...] -> [n_chunks*N, seq_len, ...]
            x = buf.reshape((n_chunks, seq_len) + buf.shape[1:])
            x = jnp.moveaxis(x, 2, 1)  # [n_chunks, N, seq_len, ...]
            return x.reshape((n_chunks * self.num_envs, seq_len) + buf.shape[2:])

        data = dict(s.data)
        data["advantages"] = s.advantages
        data["returns"] = s.returns
        seqs = {}
        for k, v in data.items():
            if k == "hidden_state":
                # keep only the hidden state at each sequence start:
                # leaf [T, L, N, H] -> [n_chunks, N, L, H] -> [n_chunks*N, L, H]
                def chop_hidden(buf):
                    x = buf[::seq_len]
                    x = jnp.moveaxis(x, 2, 1)
                    return x.reshape((n_chunks * self.num_envs,) + x.shape[2:])

                seqs[k] = jax.tree_util.tree_map(chop_hidden, v)
            else:
                seqs[k] = jax.tree_util.tree_map(chop, v)
        return seqs
