from agilerl_tpu.components.data import ReplayDataset, Transition
from agilerl_tpu.components.multi_agent_replay_buffer import MultiAgentReplayBuffer
from agilerl_tpu.components.replay_buffer import (
    MultiStepReplayBuffer,
    PrioritizedReplayBuffer,
    ReplayBuffer,
)
from agilerl_tpu.components.rollout_buffer import RolloutBuffer
from agilerl_tpu.components.sampler import Sampler
from agilerl_tpu.components.segment_tree import MinSegmentTree, SumSegmentTree

__all__ = [
    "ReplayBuffer",
    "MultiStepReplayBuffer",
    "PrioritizedReplayBuffer",
    "MultiAgentReplayBuffer",
    "RolloutBuffer",
    "Sampler",
    "SumSegmentTree",
    "MinSegmentTree",
    "Transition",
    "ReplayDataset",
]
