"""Sampler: uniform / PER / n-step dispatch
(parity: agilerl/components/sampler.py — Sampler:25, dispatch :149,182,194,
distributed DataLoader path :165).

The distributed path becomes per-host key-folded sampling (see data.ReplayDataset)
— no DataLoader needed on TPU.
"""

from __future__ import annotations

from typing import Optional

from agilerl_tpu.components.replay_buffer import (
    MultiStepReplayBuffer,
    PrioritizedReplayBuffer,
)


class Sampler:
    """Dispatches sampling by buffer type (parity: sampler.py:149,182,194).

    - dataset: iterate an epoch iterator (the reference's DataLoader path)
    - PER memory: returns ``(batch, idxs, weights)``, plus the paired n-step
      batch at the SAME indices when ``n_step_memory`` is given — the Rainbow
      paired-buffer contract lives HERE, not only in the training loop
    - plain memory: uniform sample; ``idxs`` forces index-aligned gathers
    """

    def __init__(self, memory=None, dataset=None, per: bool = False,
                 n_step: bool = False, n_step_memory=None):
        self.memory = memory
        self.dataset = dataset
        self.n_step_memory = n_step_memory
        self.per = per or isinstance(memory, PrioritizedReplayBuffer)
        self.n_step = (
            n_step
            or n_step_memory is not None
            or isinstance(memory, MultiStepReplayBuffer)
        )
        self._iter = iter(dataset) if dataset is not None else None

    def flush(self) -> None:
        """Drain any staged (chunked-ingestion) rows into the device rings
        before sampling — see ``replay_buffer.drain_staging`` for the
        paired-ring alignment contract."""
        from agilerl_tpu.components.replay_buffer import drain_staging

        drain_staging(self.memory, self.n_step_memory)

    def sample(self, batch_size: int, beta: Optional[float] = None, idxs=None, **kw):
        if self._iter is not None:
            return next(self._iter)
        self.flush()
        if self.per:
            batch, idx, weights = self.memory.sample(
                batch_size, beta=beta if beta is not None else 0.4
            )
            if self.n_step_memory is not None:
                # paired n-step batch at the SAME ring positions (parity:
                # sampler.py:194 — the buffers are index-aligned by
                # construction in train_off_policy)
                return (batch, idx, weights,
                        self.n_step_memory.sample_from_indices(idx))
            return batch, idx, weights
        if idxs is not None:
            return self.memory.sample_from_indices(idxs)
        if self.n_step_memory is not None:
            # non-PER paired n-step: draw shared indices so both rings return
            # the same transitions, and keep the agents' 4-tuple contract
            # (batch, idxs, weights, n_batch) with uniform IS weights.
            # Indices come from the buffer's own PRNG key (deterministic
            # under seeding; global np.random would not be — review finding).
            import jax
            import jax.numpy as jnp

            key = kw.get("key")
            if key is None:
                self.memory._key, key = jax.random.split(self.memory._key)
            idx = jax.random.randint(key, (batch_size,), 0, len(self.memory))
            weights = jnp.ones((batch_size,), jnp.float32)
            return (self.memory.sample_from_indices(idx), idx, weights,
                    self.n_step_memory.sample_from_indices(idx))
        return self.memory.sample(batch_size)
