"""Sampler: uniform / PER / n-step dispatch
(parity: agilerl/components/sampler.py — Sampler:25, dispatch :149,182,194,
distributed DataLoader path :165).

The distributed path becomes per-host key-folded sampling (see data.ReplayDataset)
— no DataLoader needed on TPU.
"""

from __future__ import annotations

from typing import Optional

from agilerl_tpu.components.replay_buffer import (
    MultiStepReplayBuffer,
    PrioritizedReplayBuffer,
)


class Sampler:
    def __init__(self, memory=None, dataset=None, per: bool = False, n_step: bool = False):
        self.memory = memory
        self.dataset = dataset
        self.per = per or isinstance(memory, PrioritizedReplayBuffer)
        # informational: n-step pairing is driven by the training loop's
        # paired-buffer scheme, not by the sampler itself
        self.n_step = n_step or isinstance(memory, MultiStepReplayBuffer)
        self._iter = iter(dataset) if dataset is not None else None

    def sample(self, batch_size: int, beta: Optional[float] = None, idxs=None, **kw):
        if self._iter is not None:
            return next(self._iter)
        if self.per:
            return self.memory.sample(batch_size, beta=beta if beta is not None else 0.4)
        if idxs is not None:
            return self.memory.sample_from_indices(idxs)
        return self.memory.sample(batch_size)
