"""Transition container + dataset shims (parity: agilerl/components/data.py —
Transition:69 tensorclass, ReplayDataset:96).

The reference wraps the buffer in a torch IterableDataset so HF Accelerate can
shard sampling across ranks. On TPU the equivalent is per-host sampling with a
host-specific PRNG fold — provided here as ShardedSampler for multi-host loops.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class Transition:
    obs: Any
    action: Any
    reward: Any
    next_obs: Any
    done: Any

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Transition":
        return Transition(**{k: d[k] for k in ("obs", "action", "reward", "next_obs", "done")})


class ReplayDataset:
    """Iterator over buffer samples (parity: ReplayDataset:96). Each host folds
    its process index into the sampling key so multi-host data-parallel training
    draws disjoint batches without a DataLoader."""

    def __init__(self, buffer, batch_size: int, key: Optional[jax.Array] = None):
        self.buffer = buffer
        self.batch_size = batch_size
        self.key = key if key is not None else jax.random.PRNGKey(0)
        self.key = jax.random.fold_in(self.key, jax.process_index())

    def __iter__(self):
        while True:
            self.key, sub = jax.random.split(self.key)
            yield self.buffer.sample(self.batch_size, key=sub)
