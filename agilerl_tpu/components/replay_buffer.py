"""Replay buffers as device-resident pytree ring buffers.

Parity: agilerl/components/replay_buffer.py — ReplayBuffer:12 (lazy init from
first transition :60, vectorised add :72, uniform sample :114),
MultiStepReplayBuffer:141 (n-step fold _get_n_step_info:206),
PrioritizedReplayBuffer:261 (proportional PER, IS weights :383) and
components/segment_tree.py.

TPU-first design: storage is a struct-of-arrays pytree pre-allocated in HBM.
``add`` is a jitted donated-buffer update via lax.dynamic_update_slice (no
host<->device churn); ``sample`` is a jitted gather. The PER "segment tree" of
the reference becomes a dense priority array + cumulative-sum inverse-CDF
sampling — O(N) cumsum on the VPU beats pointer-chasing trees on TPU and is
fully vectorised.

Host<->device pipelining (docs/performance.md): every buffer also exposes a
host-side **staging ring** — ``stage()`` appends transitions to a host list
and ``flush()`` coalesces them into ONE batched, donated ``_add`` dispatch,
so the interop training loops pay one device round-trip per ``flush_every``
env steps instead of one per step. ``len(buffer)`` / ``is_full`` read a
host-mirrored size counter and never sync a device scalar, keeping warmup
gates off the dispatch critical path. ``MultiStepReplayBuffer`` folds its
n-step windows **vectorised over the whole staged chunk** at flush time
(identical, op-for-op, to the per-step Python fold — see
tests/test_components/test_chunked_ingestion.py).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from agilerl_tpu.utils.rng import global_seed

PyTree = Any


class BufferState(NamedTuple):
    """Device-side ring-buffer state (a pytree; safe to donate through jit)."""

    storage: PyTree  # each leaf [capacity, ...]
    pos: jax.Array  # int32 write cursor
    size: jax.Array  # int32 current fill


def _zeros_like_batch(example: PyTree, capacity: int) -> PyTree:
    """Allocate [capacity, ...] storage from an example (unbatched) transition."""

    def alloc(x):
        x = jnp.asarray(x)
        return jnp.zeros((capacity,) + x.shape, x.dtype)

    return jax.tree_util.tree_map(alloc, example)


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=("batched",))
def _add(state: BufferState, transition: PyTree, batched: bool = False) -> BufferState:
    storage = state.storage
    if not batched:
        transition = jax.tree_util.tree_map(lambda x: jnp.asarray(x)[None], transition)
    n = jax.tree_util.tree_leaves(transition)[0].shape[0]
    capacity = jax.tree_util.tree_leaves(storage)[0].shape[0]
    idx = (state.pos + jnp.arange(n)) % capacity

    def write(buf, x):
        return buf.at[idx].set(x.astype(buf.dtype))

    storage = jax.tree_util.tree_map(write, storage, transition)
    return BufferState(
        storage=storage,
        pos=(state.pos + n) % capacity,
        size=jnp.minimum(state.size + n, capacity),
    )


@functools.partial(jax.jit, static_argnames=("batch_size",))
def _sample(state: BufferState, key: jax.Array, batch_size: int) -> PyTree:
    idx = jax.random.randint(key, (batch_size,), 0, jnp.maximum(state.size, 1))
    return jax.tree_util.tree_map(lambda buf: buf[idx], state.storage)


@functools.partial(jax.jit, static_argnames=())
def _gather(state: BufferState, idx: jax.Array) -> PyTree:
    return jax.tree_util.tree_map(lambda buf: buf[idx], state.storage)


def _num_rows(transition: PyTree, batched: bool) -> int:
    if not batched:
        return 1
    leaf = jax.tree_util.tree_leaves(transition)[0]
    # read the leading dim WITHOUT materialising device arrays on host —
    # a np.asarray here would reintroduce a per-add device sync
    shape = getattr(leaf, "shape", None)
    if shape is None:
        shape = np.asarray(leaf).shape
    return int(shape[0])


def _as_batched_host(transition: PyTree, batched: bool) -> PyTree:
    """Host-side COPY of a transition, normalised to [N, ...] leaves.

    The copy is load-bearing: staged rows outlive the env step that produced
    them, and vector envs that reuse their observation buffers (gymnasium
    ``copy=False``, envpool) would otherwise overwrite every staged view
    before flush. The eager path never had the hazard — it materialises to
    device inside ``_add`` immediately."""
    if batched:
        return jax.tree_util.tree_map(
            lambda x: np.array(x, copy=True), transition
        )
    return jax.tree_util.tree_map(
        lambda x: np.array(x, copy=True)[None], transition
    )


def _concat_chunks(chunks: list) -> PyTree:
    if len(chunks) == 1:
        return chunks[0]
    return jax.tree_util.tree_map(
        lambda *xs: np.concatenate(xs, axis=0), *chunks
    )


def drain_staging(memory, n_step_memory=None) -> None:
    """Drain chunked-ingestion staging before any sample: fold the n-step
    buffer's staged steps and forward the displaced raw chunk to the MAIN
    buffer (both rings receive the same rows in the same order — the
    paired-index contract PER/n-step sampling relies on), then flush the
    main buffer's own staging. The single owner of this invariant — both
    ``Sampler.flush`` and the fused learn path call it."""
    if n_step_memory is not None and hasattr(n_step_memory, "take_raw"):
        raw = n_step_memory.take_raw()
        if raw is not None and memory is not None:
            memory.add(raw, batched=True)
    if memory is not None and hasattr(memory, "flush"):
        memory.flush()


class ReplayBuffer:
    """Uniform experience replay in HBM (parity: replay_buffer.py:12).

    Lazy storage allocation happens on the first ``add`` (parity with the
    reference's lazy ``_init`` :60) so callers never declare obs specs.

    ``seed=`` makes the sampling key deterministic; without it the key is
    drawn from global numpy randomness (reproducible only under a global
    ``np.random.seed``). ``stage()``/``flush()`` implement the chunked
    ingestion path: staged transitions live on host until ``flush`` writes
    them all in one device dispatch. ``len()`` counts FLUSHED rows only and
    never syncs the device (host-mirrored counter).
    """

    def __init__(self, max_size: int, device=None,
                 seed: Optional[int] = None,
                 flush_every: Optional[int] = None):
        self.max_size = int(max_size)
        self.state: Optional[BufferState] = None
        # an explicitly configured cadence is remembered so the training
        # loops' pipelining default doesn't clobber it
        self._flush_every_user_set = flush_every is not None
        self.flush_every = max(int(flush_every), 1) if flush_every else 1
        self._staged: list = []
        self._staged_calls = 0
        self._size_host = 0
        self.seed(seed)

    def seed(self, seed: Optional[int] = None) -> None:
        """(Re)seed the sampling PRNG (threaded from the training loops'
        ``seed=`` so runs are reproducible)."""
        if seed is None:
            seed = global_seed()
        self._key = jax.random.PRNGKey(int(seed))

    def __len__(self) -> int:
        return self._size_host

    @property
    def is_full(self) -> bool:
        return len(self) >= self.max_size

    def _ensure_init(self, transition: PyTree, batched: bool) -> None:
        if self.state is not None:
            return
        example = transition
        if batched:
            example = jax.tree_util.tree_map(lambda x: jnp.asarray(x)[0], transition)
        self.state = BufferState(
            storage=_zeros_like_batch(example, self.max_size),
            pos=jnp.zeros((), jnp.int32),
            size=jnp.zeros((), jnp.int32),
        )

    # -- device write paths -------------------------------------------- #
    def _device_add(self, transition: PyTree, batched: bool) -> None:
        self._ensure_init(transition, batched)
        self.state = _add(self.state, transition, batched=batched)

    def add(self, transition: PyTree, batched: bool = False) -> None:
        """Append one transition (or a [N, ...] batch when batched=True) —
        eager: one device dispatch per call. Any staged rows flush first so
        ring order matches call order."""
        if self._staged:
            ReplayBuffer.flush(self)
        if batched and _num_rows(transition, batched) > self.max_size:
            # oversized chunk (e.g. a long-deferred n-step raw chunk): route
            # through the staging flush, which splits into capacity-sized
            # dispatches with well-defined write order
            ReplayBuffer.stage(self, transition, batched=True)
            ReplayBuffer.flush(self)
            return
        self._device_add(transition, batched)
        self._size_host = min(
            self._size_host + _num_rows(transition, batched), self.max_size
        )

    def stage(self, transition: PyTree, batched: bool = False) -> None:
        """Queue a transition on host; auto-flushes every ``flush_every``
        calls. One ``flush`` = one device dispatch for the whole chunk."""
        self._staged.append(_as_batched_host(transition, batched))
        self._staged_calls += 1
        if self._staged_calls >= self.flush_every:
            self.flush()

    def flush(self) -> None:
        """Write all staged rows in one batched, donated ``_add`` dispatch.

        Chunks longer than the ring capacity are split so every dispatch
        writes distinct slots (a single scatter with duplicate indices has
        no defined write order — sequential sub-chunks keep the outcome
        bit-identical to per-step adds)."""
        if not self._staged:
            return
        chunk = _concat_chunks(self._staged)
        self._staged = []
        self._staged_calls = 0
        rows = _num_rows(chunk, True)
        for lo in range(0, rows, self.max_size):
            piece = jax.tree_util.tree_map(
                lambda x: x[lo:lo + self.max_size], chunk
            )
            self._device_add(piece, batched=True)
        self._size_host = min(self._size_host + rows, self.max_size)

    def sample(self, batch_size: int, key: Optional[jax.Array] = None) -> PyTree:
        self.flush()
        assert self.state is not None and len(self) > 0, "buffer is empty"
        if key is None:
            self._key, key = jax.random.split(self._key)
        return _sample(self.state, key, batch_size)

    def sample_from_indices(self, idx: np.ndarray) -> PyTree:
        self.flush()
        return _gather(self.state, jnp.asarray(idx))

    def clear(self) -> None:
        self.state = None
        self._staged = []
        self._staged_calls = 0
        self._size_host = 0

    # -- whole-run snapshots (resilience subsystem) ---------------------- #
    def state_dict(self) -> Dict[str, Any]:
        """Host-picklable snapshot of the full ring: storage, cursors, the
        sampling PRNG key and the host-mirrored size counter. The staging
        ring is flushed first (reusing ``stage()``/``flush()``), so the
        capture is exactly what per-step ingestion would have produced."""
        self.flush()
        sd: Dict[str, Any] = {
            "kind": type(self).__name__,
            "max_size": self.max_size,
            "flush_every": self.flush_every,
            "flush_every_user_set": self._flush_every_user_set,
            "size_host": self._size_host,
            "key": np.asarray(jax.device_get(self._key)),
            "state": None,
        }
        if self.state is not None:
            sd["state"] = {
                "storage": jax.device_get(self.state.storage),
                "pos": int(self.state.pos),
                "size": int(self.state.size),
            }
        return sd

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        """Restore a :meth:`state_dict` capture in place (sampling continues
        the exact PRNG stream the snapshotted run would have drawn)."""
        self._staged = []
        self._staged_calls = 0
        self.max_size = int(sd["max_size"])
        self.flush_every = max(int(sd["flush_every"]), 1)
        self._flush_every_user_set = bool(sd.get("flush_every_user_set", False))
        self._size_host = int(sd["size_host"])
        self._key = jnp.asarray(sd["key"])
        st = sd.get("state")
        if st is None:
            self.state = None
        else:
            self.state = BufferState(
                storage=jax.tree_util.tree_map(jnp.asarray, st["storage"]),
                pos=jnp.asarray(st["pos"], jnp.int32),
                size=jnp.asarray(st["size"], jnp.int32),
            )


# --------------------------------------------------------------------------- #
# N-step buffer
# --------------------------------------------------------------------------- #


class MultiStepReplayBuffer(ReplayBuffer):
    """N-step return folding over vectorised envs
    (parity: replay_buffer.py:141, _get_n_step_info:206).

    Keeps a host-side window of the last n vectorised transitions; once the
    window is full, every ``add``:
      1. pushes the FUSED n-step transition (gamma-folded reward, n-ahead
         next_obs/done) into this buffer's own device ring, and
      2. returns the OLDEST raw 1-step transition for the caller to store in
         the main replay buffer.
    Because both buffers then append in lockstep, index i refers to the same
    start step in both — so PER indices sampled from the main buffer can be
    mirrored here via ``sample_from_indices`` (parity: the reference's paired
    buffers, replay_buffer.py:196 + train_off_policy.py:340).

    Call ``reset_horizon()`` whenever the env is reset or the acting agent
    changes — otherwise folds would span unrelated trajectories.
    """

    def __init__(self, max_size: int, n_step: int = 3, gamma: float = 0.99,
                 device=None, seed: Optional[int] = None,
                 flush_every: Optional[int] = None):
        super().__init__(max_size, seed=seed, flush_every=flush_every)
        self.n_step = int(n_step)
        self.gamma = float(gamma)
        self._horizon: list = []
        # chunked-ingestion state: raw per-step transitions staged since the
        # last fold, plus folded-but-untaken raw chunks for the main buffer
        self._staged_steps: list = []
        self._pending_raw: list = []

    def reset_horizon(self) -> None:
        """Folds must not span env resets / agent switches. Pending staged
        steps are folded first (they happened before the reset)."""
        self.flush()
        self._horizon = []

    def clear(self) -> None:
        # transitions added after clear() must not fold with stale pre-clear
        # steps (advisor finding)
        self._staged_steps = []
        self._pending_raw = []
        super().clear()
        self._horizon = []

    def add(self, transition: Dict, batched: bool = False) -> Optional[Dict]:
        """transition keys: obs, action, reward, next_obs, done
        (+ optional "_boundary" = terminated|truncated so folds stop at
        truncations/autoresets too — "done" itself stays terminated-only for
        correct bootstrapping). Returns the oldest raw transition once the
        window is full, else None."""
        self._horizon.append(
            jax.tree_util.tree_map(lambda x: np.asarray(x), transition)
        )
        if len(self._horizon) < self.n_step:
            return None
        fused = self._fold()
        oldest = dict(self._horizon.pop(0))
        oldest.pop("_boundary", None)
        super().add(fused, batched=batched)
        return oldest

    def _fold(self) -> Dict:
        first = self._horizon[0]
        reward = np.zeros_like(np.asarray(first["reward"], np.float32))
        next_obs = None
        done = np.zeros_like(np.asarray(first["done"], np.float32))
        discount = 1.0
        alive = np.ones_like(done)
        for tr in self._horizon:
            r = np.asarray(tr["reward"], np.float32)
            # the fold freezes at ANY episode boundary (terminated OR
            # truncated/autoreset) — review finding; stored done stays
            # terminated-only via the "done" key handling below
            d = np.asarray(tr.get("_boundary", tr["done"]), np.float32)
            reward = reward + discount * r * alive
            # next_obs/done from the last alive step per env
            if next_obs is None:
                next_obs = jax.tree_util.tree_map(np.asarray, tr["next_obs"])
                done = np.asarray(tr["done"], np.float32).copy()
            else:
                step_next = jax.tree_util.tree_map(np.asarray, tr["next_obs"])
                upd = alive.astype(bool)
                next_obs = jax.tree_util.tree_map(
                    lambda cur, new: np.where(
                        upd.reshape(upd.shape + (1,) * (new.ndim - upd.ndim)), new, cur
                    ),
                    next_obs,
                    step_next,
                )
                done = np.where(upd, np.asarray(tr["done"], np.float32), done)
            alive = alive * (1.0 - d)
            discount *= self.gamma
        out = {**first, "reward": reward, "next_obs": next_obs, "done": done}
        out.pop("_boundary", None)
        return out

    # -- chunked ingestion: vectorised fold over a staged chunk --------- #
    def stage(self, transition: Dict, batched: bool = False) -> None:
        """Queue one raw step on host (no device dispatch, no fold yet).
        Auto-folds every ``flush_every`` steps. Do not mix with per-step
        ``add`` on the same instance — the carried window is shared."""
        self._staged_steps.append(_as_batched_host(transition, batched))
        if len(self._staged_steps) >= self.flush_every:
            self.flush()

    def flush(self) -> None:
        """Fold every staged step VECTORISED (one pass over the chunk, all
        window starts at once), push the fused chunk into this buffer's ring
        in one dispatch, and stash the oldest-raw chunk for ``take_raw``.

        The fold below runs the SAME numpy ops in the same order as the
        per-step ``_fold`` — only vectorised over the M window starts — so
        the resulting rows are bit-identical to per-step ingestion."""
        if self._staged_steps:
            steps, self._staged_steps = self._staged_steps, []
            seq = self._horizon + steps
            n = self.n_step
            if len(seq) >= n:
                fused, raw = self._fold_chunk(seq, len(self._horizon))
                self._horizon = seq[-(n - 1):] if n > 1 else []
                ReplayBuffer.stage(self, fused, batched=True)
                self._pending_raw.append(raw)
            else:
                self._horizon = seq
        ReplayBuffer.flush(self)

    def take_raw(self) -> Optional[Dict]:
        """The 1-step transitions displaced by folds since the last call, as
        one batched chunk for the MAIN buffer (keeps the paired rings
        index-aligned: both receive the same rows in the same order)."""
        self.flush()
        if not self._pending_raw:
            return None
        raw, self._pending_raw = _concat_chunks(self._pending_raw), []
        return raw

    def _fold_chunk(self, seq: list, n_prev: int) -> Tuple[Dict, Dict]:
        """All n-step folds completed by this chunk, vectorised.

        seq: the carried window + the staged steps, each a host transition
        with [N, ...] leaves. n_prev: how many entries are carry — outputs
        are produced for every window END landing in the new steps, i.e.
        window starts s = max(0, n_prev - n + 1) .. len(seq) - n (the same
        outputs the per-step path would have produced, in the same order).
        Returns (fused_chunk, raw_chunk), both flattened to [M*N, ...]."""
        n = self.n_step
        first_start = max(0, n_prev - n + 1)
        starts = np.arange(first_start, len(seq) - n + 1)

        def at(j, key):
            # [M, N, ...] gather of `key` across window position j
            return np.stack([np.asarray(seq[s + j][key]) for s in starts])

        def at_tree(j, key):
            return jax.tree_util.tree_map(
                lambda *xs: np.stack(xs),
                *[seq[s + j][key] for s in starts],
            )

        # gather the window-start rows ONCE — they are the raw chunk, the
        # fused chunk's carried keys, and the loop's j=0 inputs all at once
        keys = [k for k in seq[0] if k != "_boundary"]
        first = {k: at_tree(0, k) for k in keys}

        reward = np.zeros_like(np.asarray(first["reward"]).astype(np.float32))
        done = None
        next_obs = None
        discount = 1.0
        alive = np.ones_like(reward)
        for j in range(n):
            r = (np.asarray(first["reward"]) if j == 0
                 else at(j, "reward")).astype(np.float32)
            d = np.stack([
                np.asarray(seq[s + j].get("_boundary", seq[s + j]["done"]))
                for s in starts
            ]).astype(np.float32)
            reward = reward + discount * r * alive
            if next_obs is None:
                next_obs = first["next_obs"]
                done = np.asarray(first["done"]).astype(np.float32).copy()
            else:
                step_next = at_tree(j, "next_obs")
                upd = alive.astype(bool)
                next_obs = jax.tree_util.tree_map(
                    lambda cur, new: np.where(
                        upd.reshape(upd.shape + (1,) * (new.ndim - upd.ndim)),
                        new, cur,
                    ),
                    next_obs,
                    step_next,
                )
                done = np.where(upd, at(j, "done").astype(np.float32), done)
            alive = alive * (1.0 - d)
            discount *= self.gamma

        def flat(x):
            # [M, N, ...] -> [M*N, ...] (step-major: per-step add order)
            return np.reshape(x, (-1,) + x.shape[2:])

        fused = {**first, "reward": reward, "next_obs": next_obs, "done": done}
        fused = jax.tree_util.tree_map(flat, fused)
        raw = jax.tree_util.tree_map(flat, first)
        return fused, raw

    # -- whole-run snapshots (resilience subsystem) ---------------------- #
    def state_dict(self) -> Dict[str, Any]:
        """Ring snapshot + the n-step carry: the fold window (``_horizon``)
        and any folded-but-untaken raw chunks, so a resumed run folds the
        exact same windows the uninterrupted run would have. ``flush()``
        (called by the base capture) folds staged steps first."""
        sd = super().state_dict()
        sd["n_step"] = self.n_step
        sd["gamma"] = self.gamma
        sd["horizon"] = [
            jax.tree_util.tree_map(np.asarray, tr) for tr in self._horizon
        ]
        sd["pending_raw"] = [
            jax.tree_util.tree_map(np.asarray, chunk)
            for chunk in self._pending_raw
        ]
        return sd

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        super().load_state_dict(sd)
        self.n_step = int(sd["n_step"])
        self.gamma = float(sd["gamma"])
        self._horizon = list(sd.get("horizon", []))
        self._pending_raw = list(sd.get("pending_raw", []))
        self._staged_steps = []


# --------------------------------------------------------------------------- #
# Prioritized buffer — dense-array PER
# --------------------------------------------------------------------------- #


class PERState(NamedTuple):
    buffer: BufferState
    priorities: jax.Array  # [capacity] float32 (alpha-powered)
    max_priority: jax.Array


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=("batched",))
def _per_add(state: PERState, transition: PyTree, batched: bool = False) -> PERState:
    if not batched:
        transition = jax.tree_util.tree_map(lambda x: jnp.asarray(x)[None], transition)
    n = jax.tree_util.tree_leaves(transition)[0].shape[0]
    capacity = state.priorities.shape[0]
    idx = (state.buffer.pos + jnp.arange(n)) % capacity
    new_buf = _add(state.buffer, transition, batched=True)
    pri = state.priorities.at[idx].set(state.max_priority)
    return PERState(buffer=new_buf, priorities=pri, max_priority=state.max_priority)


@functools.partial(jax.jit, static_argnames=("batch_size",))
def _per_sample(
    state: PERState, key: jax.Array, batch_size: int, beta: jax.Array
) -> Tuple[PyTree, jax.Array, jax.Array]:
    """Inverse-CDF proportional sampling on a dense cumsum (replaces the
    reference's SumSegmentTree — O(N) scan on the VPU, fully batched)."""
    size = state.buffer.size
    capacity = state.priorities.shape[0]
    valid = jnp.arange(capacity) < size
    p = jnp.where(valid, state.priorities, 0.0)
    cdf = jnp.cumsum(p)
    total = cdf[-1]
    u = jax.random.uniform(key, (batch_size,)) * total
    idx = jnp.searchsorted(cdf, u, side="right")
    idx = jnp.clip(idx, 0, jnp.maximum(size - 1, 0))
    batch = jax.tree_util.tree_map(lambda buf: buf[idx], state.buffer.storage)
    probs = p[idx] / jnp.maximum(total, 1e-12)
    weights = (size.astype(jnp.float32) * probs) ** (-beta)
    # normalise by the buffer-global max weight, derived from the minimum valid
    # priority (parity: _calculate_weights:383 uses min_tree.min()/sum_tree.sum())
    # — batch-max normalisation would inflate step sizes whenever the sampled
    # batch misses the lowest-priority rows (advisor finding).
    p_min = jnp.min(jnp.where(valid, state.priorities, jnp.inf)) / jnp.maximum(
        total, 1e-12
    )
    max_weight = (size.astype(jnp.float32) * jnp.maximum(p_min, 1e-12)) ** (-beta)
    weights = weights / jnp.maximum(max_weight, 1e-12)
    return batch, idx, weights


@jax.jit
def _per_update(state: PERState, idx: jax.Array, priorities: jax.Array, alpha: jax.Array) -> PERState:
    # floor the raw priority (parity: reference replay_buffer.py:425
    # max(priority, 1e-5)): a zero TD error must not zero the priority — the
    # row would never be resampled, and the global-min IS normalisation would
    # divide by an astronomical max weight, collapsing every weight to ~0
    powered = jnp.maximum(jnp.abs(priorities), 1e-5) ** alpha
    pri = state.priorities.at[idx].set(powered)
    return PERState(
        buffer=state.buffer,
        priorities=pri,
        max_priority=jnp.maximum(state.max_priority, jnp.max(powered)),
    )


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional PER (parity: replay_buffer.py:261).

    Chunked ingestion mirrors :class:`ReplayBuffer`: staged rows land in one
    ``_per_add`` dispatch (every row gets the current max priority — exactly
    what per-step adds would assign, since ``max_priority`` only moves in
    ``update_priorities``)."""

    def __init__(self, max_size: int, alpha: float = 0.6, device=None,
                 seed: Optional[int] = None,
                 flush_every: Optional[int] = None):
        super().__init__(max_size, seed=seed, flush_every=flush_every)
        self.alpha = float(alpha)
        self.per_state: Optional[PERState] = None

    def _ensure_per_init(self, transition: PyTree, batched: bool) -> None:
        if self.per_state is not None:
            return
        example = transition
        if batched:
            example = jax.tree_util.tree_map(lambda x: jnp.asarray(x)[0], transition)
        buf = BufferState(
            storage=_zeros_like_batch(example, self.max_size),
            pos=jnp.zeros((), jnp.int32),
            size=jnp.zeros((), jnp.int32),
        )
        self.per_state = PERState(
            buffer=buf,
            priorities=jnp.zeros((self.max_size,), jnp.float32),
            max_priority=jnp.ones((), jnp.float32),
        )

    def _device_add(self, transition: PyTree, batched: bool) -> None:
        # the base add/stage/flush machinery routes every write through here
        self._ensure_per_init(transition, batched)
        self.per_state = _per_add(self.per_state, transition, batched=batched)

    def sample(
        self, batch_size: int, beta: float = 0.4, key: Optional[jax.Array] = None
    ) -> Tuple[PyTree, jax.Array, jax.Array]:
        self.flush()
        assert self.per_state is not None and len(self) > 0
        if key is None:
            self._key, key = jax.random.split(self._key)
        return _per_sample(self.per_state, key, batch_size, jnp.float32(beta))

    def update_priorities(self, idx: jax.Array, priorities: jax.Array) -> None:
        self.per_state = _per_update(
            self.per_state, idx, jnp.asarray(priorities), jnp.float32(self.alpha)
        )

    def sample_from_indices(self, idx) -> PyTree:
        self.flush()
        return _gather(self.per_state.buffer, jnp.asarray(idx))

    def clear(self) -> None:
        super().clear()
        self.per_state = None

    # -- whole-run snapshots (resilience subsystem) ---------------------- #
    def state_dict(self) -> Dict[str, Any]:
        """Ring + priority array + running max priority (the base capture's
        ``state`` stays None for PER — everything lives in ``per_state``)."""
        sd = super().state_dict()
        sd["alpha"] = self.alpha
        if self.per_state is None:
            sd["per_state"] = None
        else:
            buf = self.per_state.buffer
            sd["per_state"] = {
                "storage": jax.device_get(buf.storage),
                "pos": int(buf.pos),
                "size": int(buf.size),
                "priorities": np.asarray(jax.device_get(self.per_state.priorities)),
                "max_priority": float(self.per_state.max_priority),
            }
        return sd

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        super().load_state_dict(sd)
        self.alpha = float(sd.get("alpha", self.alpha))
        ps = sd.get("per_state")
        if ps is None:
            self.per_state = None
            return
        self.per_state = PERState(
            buffer=BufferState(
                storage=jax.tree_util.tree_map(jnp.asarray, ps["storage"]),
                pos=jnp.asarray(ps["pos"], jnp.int32),
                size=jnp.asarray(ps["size"], jnp.int32),
            ),
            priorities=jnp.asarray(ps["priorities"], jnp.float32),
            max_priority=jnp.asarray(ps["max_priority"], jnp.float32),
        )
