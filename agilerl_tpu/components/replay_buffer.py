"""Replay buffers as device-resident pytree ring buffers.

Parity: agilerl/components/replay_buffer.py — ReplayBuffer:12 (lazy init from
first transition :60, vectorised add :72, uniform sample :114),
MultiStepReplayBuffer:141 (n-step fold _get_n_step_info:206),
PrioritizedReplayBuffer:261 (proportional PER, IS weights :383) and
components/segment_tree.py.

TPU-first design: storage is a struct-of-arrays pytree pre-allocated in HBM.
``add`` is a jitted donated-buffer update via lax.dynamic_update_slice (no
host<->device churn); ``sample`` is a jitted gather. The PER "segment tree" of
the reference becomes a dense priority array + cumulative-sum inverse-CDF
sampling — O(N) cumsum on the VPU beats pointer-chasing trees on TPU and is
fully vectorised.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

PyTree = Any


class BufferState(NamedTuple):
    """Device-side ring-buffer state (a pytree; safe to donate through jit)."""

    storage: PyTree  # each leaf [capacity, ...]
    pos: jax.Array  # int32 write cursor
    size: jax.Array  # int32 current fill


def _zeros_like_batch(example: PyTree, capacity: int) -> PyTree:
    """Allocate [capacity, ...] storage from an example (unbatched) transition."""

    def alloc(x):
        x = jnp.asarray(x)
        return jnp.zeros((capacity,) + x.shape, x.dtype)

    return jax.tree_util.tree_map(alloc, example)


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=("batched",))
def _add(state: BufferState, transition: PyTree, batched: bool = False) -> BufferState:
    storage = state.storage
    if not batched:
        transition = jax.tree_util.tree_map(lambda x: jnp.asarray(x)[None], transition)
    n = jax.tree_util.tree_leaves(transition)[0].shape[0]
    capacity = jax.tree_util.tree_leaves(storage)[0].shape[0]
    idx = (state.pos + jnp.arange(n)) % capacity

    def write(buf, x):
        return buf.at[idx].set(x.astype(buf.dtype))

    storage = jax.tree_util.tree_map(write, storage, transition)
    return BufferState(
        storage=storage,
        pos=(state.pos + n) % capacity,
        size=jnp.minimum(state.size + n, capacity),
    )


@functools.partial(jax.jit, static_argnames=("batch_size",))
def _sample(state: BufferState, key: jax.Array, batch_size: int) -> PyTree:
    idx = jax.random.randint(key, (batch_size,), 0, jnp.maximum(state.size, 1))
    return jax.tree_util.tree_map(lambda buf: buf[idx], state.storage)


@functools.partial(jax.jit, static_argnames=())
def _gather(state: BufferState, idx: jax.Array) -> PyTree:
    return jax.tree_util.tree_map(lambda buf: buf[idx], state.storage)


class ReplayBuffer:
    """Uniform experience replay in HBM (parity: replay_buffer.py:12).

    Lazy storage allocation happens on the first ``add`` (parity with the
    reference's lazy ``_init`` :60) so callers never declare obs specs.
    """

    def __init__(self, max_size: int, device=None):
        self.max_size = int(max_size)
        self.state: Optional[BufferState] = None
        self._key = jax.random.PRNGKey(np.random.randint(0, 2**31 - 1))

    def __len__(self) -> int:
        return 0 if self.state is None else int(self.state.size)

    @property
    def is_full(self) -> bool:
        return len(self) >= self.max_size

    def _ensure_init(self, transition: PyTree, batched: bool) -> None:
        if self.state is not None:
            return
        example = transition
        if batched:
            example = jax.tree_util.tree_map(lambda x: jnp.asarray(x)[0], transition)
        self.state = BufferState(
            storage=_zeros_like_batch(example, self.max_size),
            pos=jnp.zeros((), jnp.int32),
            size=jnp.zeros((), jnp.int32),
        )

    def add(self, transition: PyTree, batched: bool = False) -> None:
        """Append one transition (or a [N, ...] batch when batched=True)."""
        self._ensure_init(transition, batched)
        self.state = _add(self.state, transition, batched=batched)

    def sample(self, batch_size: int, key: Optional[jax.Array] = None) -> PyTree:
        assert self.state is not None and len(self) > 0, "buffer is empty"
        if key is None:
            self._key, key = jax.random.split(self._key)
        return _sample(self.state, key, batch_size)

    def sample_from_indices(self, idx: np.ndarray) -> PyTree:
        return _gather(self.state, jnp.asarray(idx))

    def clear(self) -> None:
        self.state = None


# --------------------------------------------------------------------------- #
# N-step buffer
# --------------------------------------------------------------------------- #


class MultiStepReplayBuffer(ReplayBuffer):
    """N-step return folding over vectorised envs
    (parity: replay_buffer.py:141, _get_n_step_info:206).

    Keeps a host-side window of the last n vectorised transitions; once the
    window is full, every ``add``:
      1. pushes the FUSED n-step transition (gamma-folded reward, n-ahead
         next_obs/done) into this buffer's own device ring, and
      2. returns the OLDEST raw 1-step transition for the caller to store in
         the main replay buffer.
    Because both buffers then append in lockstep, index i refers to the same
    start step in both — so PER indices sampled from the main buffer can be
    mirrored here via ``sample_from_indices`` (parity: the reference's paired
    buffers, replay_buffer.py:196 + train_off_policy.py:340).

    Call ``reset_horizon()`` whenever the env is reset or the acting agent
    changes — otherwise folds would span unrelated trajectories.
    """

    def __init__(self, max_size: int, n_step: int = 3, gamma: float = 0.99, device=None):
        super().__init__(max_size)
        self.n_step = int(n_step)
        self.gamma = float(gamma)
        self._horizon: list = []

    def reset_horizon(self) -> None:
        self._horizon = []

    def clear(self) -> None:
        # transitions added after clear() must not fold with stale pre-clear
        # steps (advisor finding)
        super().clear()
        self.reset_horizon()

    def add(self, transition: Dict, batched: bool = False) -> Optional[Dict]:
        """transition keys: obs, action, reward, next_obs, done
        (+ optional "_boundary" = terminated|truncated so folds stop at
        truncations/autoresets too — "done" itself stays terminated-only for
        correct bootstrapping). Returns the oldest raw transition once the
        window is full, else None."""
        self._horizon.append(
            jax.tree_util.tree_map(lambda x: np.asarray(x), transition)
        )
        if len(self._horizon) < self.n_step:
            return None
        fused = self._fold()
        oldest = dict(self._horizon.pop(0))
        oldest.pop("_boundary", None)
        super().add(fused, batched=batched)
        return oldest

    def _fold(self) -> Dict:
        first = self._horizon[0]
        reward = np.zeros_like(np.asarray(first["reward"], np.float32))
        next_obs = None
        done = np.zeros_like(np.asarray(first["done"], np.float32))
        discount = 1.0
        alive = np.ones_like(done)
        for tr in self._horizon:
            r = np.asarray(tr["reward"], np.float32)
            # the fold freezes at ANY episode boundary (terminated OR
            # truncated/autoreset) — review finding; stored done stays
            # terminated-only via the "done" key handling below
            d = np.asarray(tr.get("_boundary", tr["done"]), np.float32)
            reward = reward + discount * r * alive
            # next_obs/done from the last alive step per env
            if next_obs is None:
                next_obs = jax.tree_util.tree_map(np.asarray, tr["next_obs"])
                done = np.asarray(tr["done"], np.float32).copy()
            else:
                step_next = jax.tree_util.tree_map(np.asarray, tr["next_obs"])
                upd = alive.astype(bool)
                next_obs = jax.tree_util.tree_map(
                    lambda cur, new: np.where(
                        upd.reshape(upd.shape + (1,) * (new.ndim - upd.ndim)), new, cur
                    ),
                    next_obs,
                    step_next,
                )
                done = np.where(upd, np.asarray(tr["done"], np.float32), done)
            alive = alive * (1.0 - d)
            discount *= self.gamma
        out = {**first, "reward": reward, "next_obs": next_obs, "done": done}
        out.pop("_boundary", None)
        return out


# --------------------------------------------------------------------------- #
# Prioritized buffer — dense-array PER
# --------------------------------------------------------------------------- #


class PERState(NamedTuple):
    buffer: BufferState
    priorities: jax.Array  # [capacity] float32 (alpha-powered)
    max_priority: jax.Array


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=("batched",))
def _per_add(state: PERState, transition: PyTree, batched: bool = False) -> PERState:
    if not batched:
        transition = jax.tree_util.tree_map(lambda x: jnp.asarray(x)[None], transition)
    n = jax.tree_util.tree_leaves(transition)[0].shape[0]
    capacity = state.priorities.shape[0]
    idx = (state.buffer.pos + jnp.arange(n)) % capacity
    new_buf = _add(state.buffer, transition, batched=True)
    pri = state.priorities.at[idx].set(state.max_priority)
    return PERState(buffer=new_buf, priorities=pri, max_priority=state.max_priority)


@functools.partial(jax.jit, static_argnames=("batch_size",))
def _per_sample(
    state: PERState, key: jax.Array, batch_size: int, beta: jax.Array
) -> Tuple[PyTree, jax.Array, jax.Array]:
    """Inverse-CDF proportional sampling on a dense cumsum (replaces the
    reference's SumSegmentTree — O(N) scan on the VPU, fully batched)."""
    size = state.buffer.size
    capacity = state.priorities.shape[0]
    valid = jnp.arange(capacity) < size
    p = jnp.where(valid, state.priorities, 0.0)
    cdf = jnp.cumsum(p)
    total = cdf[-1]
    u = jax.random.uniform(key, (batch_size,)) * total
    idx = jnp.searchsorted(cdf, u, side="right")
    idx = jnp.clip(idx, 0, jnp.maximum(size - 1, 0))
    batch = jax.tree_util.tree_map(lambda buf: buf[idx], state.buffer.storage)
    probs = p[idx] / jnp.maximum(total, 1e-12)
    weights = (size.astype(jnp.float32) * probs) ** (-beta)
    # normalise by the buffer-global max weight, derived from the minimum valid
    # priority (parity: _calculate_weights:383 uses min_tree.min()/sum_tree.sum())
    # — batch-max normalisation would inflate step sizes whenever the sampled
    # batch misses the lowest-priority rows (advisor finding).
    p_min = jnp.min(jnp.where(valid, state.priorities, jnp.inf)) / jnp.maximum(
        total, 1e-12
    )
    max_weight = (size.astype(jnp.float32) * jnp.maximum(p_min, 1e-12)) ** (-beta)
    weights = weights / jnp.maximum(max_weight, 1e-12)
    return batch, idx, weights


@jax.jit
def _per_update(state: PERState, idx: jax.Array, priorities: jax.Array, alpha: jax.Array) -> PERState:
    # floor the raw priority (parity: reference replay_buffer.py:425
    # max(priority, 1e-5)): a zero TD error must not zero the priority — the
    # row would never be resampled, and the global-min IS normalisation would
    # divide by an astronomical max weight, collapsing every weight to ~0
    powered = jnp.maximum(jnp.abs(priorities), 1e-5) ** alpha
    pri = state.priorities.at[idx].set(powered)
    return PERState(
        buffer=state.buffer,
        priorities=pri,
        max_priority=jnp.maximum(state.max_priority, jnp.max(powered)),
    )


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional PER (parity: replay_buffer.py:261)."""

    def __init__(self, max_size: int, alpha: float = 0.6, device=None):
        super().__init__(max_size)
        self.alpha = float(alpha)
        self.per_state: Optional[PERState] = None

    def __len__(self) -> int:
        return 0 if self.per_state is None else int(self.per_state.buffer.size)

    def add(self, transition: PyTree, batched: bool = False) -> None:
        if self.per_state is None:
            example = transition
            if batched:
                example = jax.tree_util.tree_map(lambda x: jnp.asarray(x)[0], transition)
            buf = BufferState(
                storage=_zeros_like_batch(example, self.max_size),
                pos=jnp.zeros((), jnp.int32),
                size=jnp.zeros((), jnp.int32),
            )
            self.per_state = PERState(
                buffer=buf,
                priorities=jnp.zeros((self.max_size,), jnp.float32),
                max_priority=jnp.ones((), jnp.float32),
            )
        self.per_state = _per_add(self.per_state, transition, batched=batched)

    def sample(
        self, batch_size: int, beta: float = 0.4, key: Optional[jax.Array] = None
    ) -> Tuple[PyTree, jax.Array, jax.Array]:
        assert self.per_state is not None and len(self) > 0
        if key is None:
            self._key, key = jax.random.split(self._key)
        return _per_sample(self.per_state, key, batch_size, jnp.float32(beta))

    def update_priorities(self, idx: jax.Array, priorities: jax.Array) -> None:
        self.per_state = _per_update(
            self.per_state, idx, jnp.asarray(priorities), jnp.float32(self.alpha)
        )

    def sample_from_indices(self, idx) -> PyTree:
        return _gather(self.per_state.buffer, jnp.asarray(idx))

    def clear(self) -> None:
        self.per_state = None
