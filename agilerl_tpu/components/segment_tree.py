"""Array-backed segment trees (parity: agilerl/components/segment_tree.py —
SegmentTree:5, SumSegmentTree:111, MinSegmentTree:159).

The PER buffer itself uses a dense cumsum inverse-CDF (see replay_buffer.py) —
on TPU an O(N) vectorised scan beats pointer-chasing. These trees are provided
for API parity and for host-side consumers: a flat numpy heap layout
(tree[1]=root), vectorised batch updates, and O(log N) prefix-sum descent.
"""

from __future__ import annotations

import operator
from typing import Callable

import numpy as np


class SegmentTree:
    def __init__(self, capacity: int, operation: Callable, init_value: float):
        assert capacity > 0 and (capacity & (capacity - 1)) == 0, (
            "capacity must be a positive power of 2"
        )
        self.capacity = capacity
        self.operation = operation
        self.init_value = init_value
        self.tree = np.full(2 * capacity, init_value, dtype=np.float64)

    def __setitem__(self, idx, val) -> None:
        idx = np.atleast_1d(np.asarray(idx, dtype=np.int64)) + self.capacity
        val = np.broadcast_to(np.asarray(val, dtype=np.float64), idx.shape)
        self.tree[idx] = val
        # vectorised upward propagation level by level
        parents = np.unique(idx // 2)
        while parents.size and parents[0] >= 1:
            left = self.tree[2 * parents]
            right = self.tree[2 * parents + 1]
            self.tree[parents] = self.operation(left, right)
            parents = np.unique(parents // 2)
            if parents.size and parents[-1] == 0:
                parents = parents[parents >= 1]

    def __getitem__(self, idx):
        return self.tree[np.asarray(idx) + self.capacity]

    def reduce(self, start: int = 0, end: int = None) -> float:
        """Aggregate over [start, end)."""
        if end is None:
            end = self.capacity
        result = self.init_value
        start += self.capacity
        end += self.capacity
        while start < end:
            if start & 1:
                result = self.operation(result, self.tree[start])
                start += 1
            if end & 1:
                end -= 1
                result = self.operation(result, self.tree[end])
            start //= 2
            end //= 2
        return float(result)


class SumSegmentTree(SegmentTree):
    def __init__(self, capacity: int):
        super().__init__(capacity, np.add, 0.0)

    def sum(self, start: int = 0, end: int = None) -> float:
        return self.reduce(start, end)

    def retrieve(self, upperbound: float) -> int:
        """Find highest i such that prefix_sum(i) <= upperbound."""
        idx = 1
        while idx < self.capacity:
            left = 2 * idx
            if self.tree[left] > upperbound:
                idx = left
            else:
                upperbound -= self.tree[left]
                idx = left + 1
        return idx - self.capacity


class MinSegmentTree(SegmentTree):
    def __init__(self, capacity: int):
        super().__init__(capacity, np.minimum, float("inf"))

    def min(self, start: int = 0, end: int = None) -> float:
        return self.reduce(start, end)
