"""Multi-agent replay buffer (parity: agilerl/components/multi_agent_replay_buffer.py
— MultiAgentReplayBuffer:16, single-env and vectorised save paths :169,213).

Storage is one device ring buffer whose transition pytree is dict-of-agents:
{"obs": {agent: [...]}, "action": {agent: [...]}, ...} — the flat BufferState
machinery from replay_buffer.py handles it unchanged because agents are just
pytree branches.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax

from agilerl_tpu.components.replay_buffer import ReplayBuffer


class MultiAgentReplayBuffer(ReplayBuffer):
    def __init__(self, max_size: int, agent_ids: List[str], device=None,
                 seed: Optional[int] = None,
                 flush_every: Optional[int] = None):
        super().__init__(max_size, seed=seed, flush_every=flush_every)
        self.agent_ids = list(agent_ids)

    def _transition(self, obs, action, reward, next_obs, done) -> Dict[str, Any]:
        return {
            "obs": {a: obs[a] for a in self.agent_ids},
            "action": {a: action[a] for a in self.agent_ids},
            "reward": {a: reward[a] for a in self.agent_ids},
            "next_obs": {a: next_obs[a] for a in self.agent_ids},
            "done": {a: done[a] for a in self.agent_ids},
        }

    def save_to_memory(
        self,
        obs: Dict[str, Any],
        action: Dict[str, Any],
        reward: Dict[str, Any],
        next_obs: Dict[str, Any],
        done: Dict[str, Any],
        is_vectorised: bool = False,
    ) -> None:
        """Parity: save_to_memory single-env :169 / vectorised :213."""
        self.add(self._transition(obs, action, reward, next_obs, done),
                 batched=is_vectorised)

    def stage_to_memory(
        self,
        obs: Dict[str, Any],
        action: Dict[str, Any],
        reward: Dict[str, Any],
        next_obs: Dict[str, Any],
        done: Dict[str, Any],
        is_vectorised: bool = False,
    ) -> None:
        """Chunked-ingestion variant of ``save_to_memory``: queue on host,
        coalesced into one device dispatch per ``flush_every`` steps (the
        training loop flushes before every sample)."""
        self.stage(self._transition(obs, action, reward, next_obs, done),
                   batched=is_vectorised)
