from agilerl_tpu.vector.pz_async_vec_env import AsyncPettingZooVecEnv
from agilerl_tpu.vector.pz_vec_env import PettingZooVecEnv, sanitize_ma_transition

__all__ = ["PettingZooVecEnv", "AsyncPettingZooVecEnv", "sanitize_ma_transition"]
