"""PettingZoo parallel-env vectorisation base API
(parity: agilerl/vector/pz_vec_env.py — PettingZooVecEnv: reset/step_async/
step_wait with per-agent dict obs).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np


class PettingZooVecEnv:
    """Synchronous vectorisation of PettingZoo parallel envs: the baseline
    implementation of the vec API (async shared-memory variant in
    pz_async_vec_env.py)."""

    def __init__(self, env_fns: List[Callable]):
        self.envs = [fn() for fn in env_fns]
        self.num_envs = len(self.envs)
        e0 = self.envs[0]
        self.agents = list(e0.possible_agents)
        self.possible_agents = list(e0.possible_agents)
        self.observation_spaces = {a: e0.observation_space(a) for a in self.agents}
        self.action_spaces = {a: e0.action_space(a) for a in self.agents}
        self.agent_ids = self.agents
        self._actions = None

    def observation_space(self, agent: str):
        return self.observation_spaces[agent]

    def action_space(self, agent: str):
        return self.action_spaces[agent]

    def _stack_obs(self, obs_list):
        """Stack per-env obs dicts leaf-wise so Dict/Tuple spaces keep their
        structure and every leaf keeps its own dtype (uint8 images, bool
        flags) — flat np.stack over dicts yields object arrays. Missing
        agents get NaN/zero placeholders (same convention as the async
        worker's write_obs)."""
        from agilerl_tpu.vector.pz_async_vec_env import (
            _obs_leaves, _rebuild_obs, _space_leaves, placeholder_obs,
        )

        out = {}
        for a in self.agents:
            space = self.observation_spaces[a]
            rows = [
                _obs_leaves(space, o[a]) if isinstance(o, dict) and a in o
                and o[a] is not None else _obs_leaves(space, placeholder_obs(space))
                for o in obs_list
            ]
            leaves = [
                np.stack([np.asarray(r[li], dtype).reshape(shape)
                          for r in rows])
                for li, (key, dtype, shape) in enumerate(_space_leaves(space))
            ]
            out[a] = _rebuild_obs(space, leaves)
        return out

    def reset(self, seed: Optional[int] = None, options=None):
        obs_list, info_list = [], []
        for i, e in enumerate(self.envs):
            obs, info = e.reset(seed=None if seed is None else seed + i, options=options)
            obs_list.append(obs)
            info_list.append(info)
        return self._stack_obs(obs_list), {}

    def step_async(self, actions: Dict[str, np.ndarray]) -> None:
        self._actions = actions

    def step_wait(self):
        actions = self._actions
        obs_l, rew_l, term_l, trunc_l = [], [], [], []
        for i, e in enumerate(self.envs):
            act_i = {a: np.asarray(actions[a])[i] for a in self.agents}
            # gymnasium-style scalars for Discrete
            act_i = {
                a: (int(v) if np.ndim(v) == 0 and not isinstance(
                    self.action_spaces[a], type(None)
                ) and hasattr(self.action_spaces[a], "n") else v)
                for a, v in act_i.items()
            }
            obs, rew, term, trunc, _ = e.step(act_i)
            if not e.agents:  # episode over -> autoreset
                obs, _ = e.reset()
            obs_l.append(obs)
            rew_l.append(rew)
            term_l.append(term)
            trunc_l.append(trunc)

        def stack(dicts, default=0.0):
            return {
                a: np.stack([np.asarray(d.get(a, default)) for d in dicts])
                for a in self.agents
            }

        return (self._stack_obs(obs_l), stack(rew_l), stack(term_l, False),
                stack(trunc_l, False), {})

    def step(self, actions):
        self.step_async(actions)
        return self.step_wait()

    def close(self):
        for e in self.envs:
            e.close()


def sanitize_ma_transition(obs_dict, reward_dict):
    """Replace NaN placeholder observations/rewards (dead or inactive agents —
    the AsyncPettingZooVecEnv convention, get_placeholder_value parity) with
    finite zeros for the STANDARD training loops, which have no inactivity
    notion. AsyncAgentsWrapper consumers get the NaN-aware path instead;
    without this, one dead agent would poison Q-targets for the whole team.
    """

    def clean(v):
        if isinstance(v, dict):
            return {k: clean(x) for k, x in v.items()}
        if isinstance(v, tuple):
            return tuple(clean(x) for x in v)
        arr = np.asarray(v)
        if np.issubdtype(arr.dtype, np.floating) and np.isnan(arr).any():
            return np.nan_to_num(arr, nan=0.0)
        return v

    return ({a: clean(v) for a, v in obs_dict.items()},
            {a: clean(v) for a, v in reward_dict.items()})
