"""Async (multiprocessing) PettingZoo vectorisation
(parity: agilerl/vector/pz_async_vec_env.py — AsyncPettingZooVecEnv:79, worker
loop _async_worker:906, pipe control, shared-memory observation buffers
create_shared_memory:733, autoreset, error propagation _raise_if_errors:541).

Workers write observations into a shared multiprocessing.Array per agent (the
reference's shared-memory design), commands travel over pipes. On TPU hosts the
env processes overlap with device compute exactly like the reference overlaps
with CUDA streams.
"""

from __future__ import annotations

import enum
import multiprocessing as mp
import traceback
from typing import Callable, Dict, List, Optional

import numpy as np


class AsyncState(enum.Enum):
    DEFAULT = "default"
    WAITING_RESET = "reset"
    WAITING_STEP = "step"


def _flatdim(space) -> int:
    from gymnasium import spaces as S

    if isinstance(space, S.Discrete):
        return 1
    return int(np.prod(space.shape)) if space.shape else 1


def _async_worker(index, env_fn, pipe, parent_pipe, shm, agents, obs_dims):
    """Worker loop (parity: pz_async_vec_env.py:906)."""
    parent_pipe.close()
    env = env_fn()

    def write_obs(obs):
        for a in agents:
            arr = np.frombuffer(shm[a].get_obj(), dtype=np.float32)
            dim = obs_dims[a]
            flat = np.asarray(obs.get(a, np.zeros(dim)), np.float32).reshape(-1)
            arr[index * dim : (index + 1) * dim] = flat[:dim]

    try:
        while True:
            cmd, data = pipe.recv()
            if cmd == "reset":
                obs, info = env.reset(seed=data)
                write_obs(obs)
                pipe.send(((), True))
            elif cmd == "step":
                action = {a: data[a] for a in env.agents} if env.agents else data
                obs, rew, term, trunc, _ = env.step(action)
                if not env.agents:  # autoreset
                    obs, _ = env.reset()
                write_obs(obs)
                out = (
                    {a: float(rew.get(a, 0.0)) for a in agents},
                    {a: bool(term.get(a, False)) for a in agents},
                    {a: bool(trunc.get(a, False)) for a in agents},
                )
                pipe.send((out, True))
            elif cmd == "close":
                env.close()
                pipe.send(((), True))
                break
    except Exception:  # pragma: no cover - error path
        pipe.send((traceback.format_exc(), False))


class AsyncPettingZooVecEnv:
    def __init__(self, env_fns: List[Callable], context: str = "spawn"):
        ctx = mp.get_context(context)
        self.num_envs = len(env_fns)
        probe = env_fns[0]()
        self.agents = list(probe.possible_agents)
        self.possible_agents = list(probe.possible_agents)
        self.observation_spaces = {a: probe.observation_space(a) for a in self.agents}
        self.action_spaces = {a: probe.action_space(a) for a in self.agents}
        self.agent_ids = self.agents
        probe.close()
        self._obs_dims = {a: _flatdim(self.observation_spaces[a]) for a in self.agents}
        # shared-memory observation buffers (parity: create_shared_memory:733)
        self._shm = {
            a: ctx.Array("f", self.num_envs * self._obs_dims[a]) for a in self.agents
        }
        self._pipes, self._procs = [], []
        for i, fn in enumerate(env_fns):
            parent, child = ctx.Pipe()
            proc = ctx.Process(
                target=_async_worker,
                args=(i, fn, child, parent, self._shm, self.agents, self._obs_dims),
                daemon=True,
            )
            proc.start()
            child.close()
            self._pipes.append(parent)
            self._procs.append(proc)
        self._state = AsyncState.DEFAULT

    def observation_space(self, agent: str):
        return self.observation_spaces[agent]

    def action_space(self, agent: str):
        return self.action_spaces[agent]

    def _assert_is_running(self):
        assert all(p.is_alive() for p in self._procs), "worker died"

    def _raise_if_errors(self, results):
        for out, ok in results:
            if not ok:
                raise RuntimeError(f"env worker error:\n{out}")

    def _read_obs(self) -> Dict[str, np.ndarray]:
        out = {}
        for a in self.agents:
            space = self.observation_spaces[a]
            arr = np.frombuffer(self._shm[a].get_obj(), dtype=np.float32).copy()
            shape = space.shape
            if shape and int(np.prod(shape)) == self._obs_dims[a]:
                arr = arr.reshape(self.num_envs, *shape)
            elif shape == ():  # Discrete and friends: scalar per env
                arr = arr.reshape(self.num_envs)
            else:
                arr = arr.reshape(self.num_envs, self._obs_dims[a])
            dtype = getattr(space, "dtype", None)
            out[a] = arr.astype(dtype) if dtype is not None else arr
        return out

    def reset(self, seed: Optional[int] = None, options=None):
        self._assert_is_running()
        for i, pipe in enumerate(self._pipes):
            pipe.send(("reset", None if seed is None else seed + i))
        results = [pipe.recv() for pipe in self._pipes]
        self._raise_if_errors(results)
        return self._read_obs(), {}

    def step_async(self, actions: Dict[str, np.ndarray]) -> None:
        self._assert_is_running()
        for i, pipe in enumerate(self._pipes):
            act_i = {a: np.asarray(actions[a])[i] for a in self.agents}
            act_i = {
                a: int(v) if hasattr(self.action_spaces[a], "n") else v
                for a, v in act_i.items()
            }
            pipe.send(("step", act_i))
        self._state = AsyncState.WAITING_STEP

    def step_wait(self):
        results = [pipe.recv() for pipe in self._pipes]
        self._raise_if_errors(results)
        self._state = AsyncState.DEFAULT
        rews, terms, truncs = zip(*[r for r, ok in results])
        stack = lambda ds: {a: np.array([d[a] for d in ds]) for a in self.agents}  # noqa: E731
        return self._read_obs(), stack(rews), stack(terms), stack(truncs), {}

    def step(self, actions):
        self.step_async(actions)
        return self.step_wait()

    def close(self):
        try:
            for pipe in self._pipes:
                pipe.send(("close", None))
            for pipe in self._pipes:
                pipe.recv()
        except (BrokenPipeError, EOFError):
            pass
        for p in self._procs:
            p.join(timeout=2)
