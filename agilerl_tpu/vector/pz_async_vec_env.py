"""Async (multiprocessing) PettingZoo vectorisation
(parity: agilerl/vector/pz_async_vec_env.py — AsyncPettingZooVecEnv:79, worker
loop _async_worker:906, pipe control, typed shared-memory observation buffers
create_shared_memory:733, autoreset with final-observation propagation,
dead-agent placeholders get_placeholder_value:765, error propagation
_raise_if_errors:541).

Observations travel through per-agent, per-leaf typed shared-memory blocks
(Dict/Tuple spaces decompose into leaves, each with its own dtype — parity with
the reference's per-space typed segments); commands and small payloads
(rewards, infos, final observations at episode ends) travel over pipes. On TPU
hosts the env processes overlap with device compute exactly like the reference
overlaps with CUDA streams.
"""

from __future__ import annotations

import enum
import multiprocessing as mp
import traceback
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np


class AsyncState(enum.Enum):
    DEFAULT = "default"
    WAITING_RESET = "reset"
    WAITING_STEP = "step"


# ctypes typecodes for the shared Arrays, keyed by numpy dtype name
_TYPECODES = {
    "float32": "f", "float64": "d",
    "int8": "b", "int16": "h", "int32": "i", "int64": "q",
    "uint8": "B", "uint16": "H", "uint32": "I", "uint64": "Q",
    "bool": "B",  # stored as uint8, cast back on read
}


def _space_leaves(space, prefix: str = "") -> List[Tuple[str, np.dtype, tuple]]:
    """Flatten a (possibly Dict/Tuple) space into (key, dtype, shape) leaves."""
    from gymnasium import spaces as S

    if isinstance(space, S.Dict):
        out = []
        for k in space.spaces:
            out.extend(_space_leaves(space.spaces[k], f"{prefix}{k}."))
        return out
    if isinstance(space, S.Tuple):
        out = []
        for i, sub in enumerate(space.spaces):
            out.extend(_space_leaves(sub, f"{prefix}{i}."))
        return out
    if isinstance(space, S.Discrete):
        return [(prefix, np.dtype(space.dtype or np.int64), ())]
    shape = tuple(space.shape) if space.shape else ()
    return [(prefix, np.dtype(space.dtype or np.float32), shape)]


def _obs_leaves(space, obs) -> List[np.ndarray]:
    """Walk an observation in the same order as _space_leaves."""
    from gymnasium import spaces as S

    if isinstance(space, S.Dict):
        out = []
        for k in space.spaces:
            out.extend(_obs_leaves(space.spaces[k], obs[k]))
        return out
    if isinstance(space, S.Tuple):
        out = []
        for i, sub in enumerate(space.spaces):
            out.extend(_obs_leaves(sub, obs[i]))
        return out
    return [np.asarray(obs)]


def _rebuild_obs(space, leaves: List[np.ndarray]):
    """Inverse of _obs_leaves for batched [N, ...] leaf arrays (consumes from
    the front of `leaves`)."""
    from gymnasium import spaces as S

    if isinstance(space, S.Dict):
        return {k: _rebuild_obs(space.spaces[k], leaves) for k in space.spaces}
    if isinstance(space, S.Tuple):
        return tuple(_rebuild_obs(sub, leaves) for sub in space.spaces)
    return leaves.pop(0)


def placeholder_obs(space):
    """Placeholder observation for an agent absent from a step's dicts
    (parity: get_placeholder_value:765): NaN for float spaces — detectably
    invalid, which is what AsyncAgentsWrapper keys inactivity on — and 0 for
    integer spaces (NaN is unrepresentable there)."""
    from gymnasium import spaces as S

    if isinstance(space, S.Dict):
        return {k: placeholder_obs(space.spaces[k]) for k in space.spaces}
    if isinstance(space, S.Tuple):
        return tuple(placeholder_obs(sub) for sub in space.spaces)
    if isinstance(space, S.Discrete):
        return np.zeros((), dtype=space.dtype or np.int64)
    dtype = np.dtype(space.dtype or np.float32)
    if np.issubdtype(dtype, np.floating):
        return np.full(space.shape or (), np.nan, dtype=dtype)
    return np.zeros(space.shape or (), dtype=dtype)


def _async_worker(index, env_fn, pipe, parent_pipe, shm, agents, spaces_by_agent):
    """Worker loop (parity: pz_async_vec_env.py:906)."""
    parent_pipe.close()
    env = env_fn()
    # the leaf layout is static for the worker's lifetime — don't re-walk the
    # space tree on every step
    leaves_by_agent = {a: _space_leaves(spaces_by_agent[a]) for a in agents}

    def write_obs(obs):
        for a in agents:
            space = spaces_by_agent[a]
            value = obs.get(a) if isinstance(obs, dict) else None
            if value is None:
                value = placeholder_obs(space)
            leaves = _obs_leaves(space, value)
            for (key, dtype, shape), leaf in zip(leaves_by_agent[a], leaves):
                block, np_dtype = shm[a][key]
                size = int(np.prod(shape)) if shape else 1
                arr = np.frombuffer(block.get_obj(), dtype=np_dtype)
                arr[index * size : (index + 1) * size] = np.asarray(
                    leaf, np_dtype
                ).reshape(-1)

    try:
        while True:
            cmd, data = pipe.recv()
            if cmd == "reset":
                seed, options = data
                obs, info = env.reset(seed=seed, options=options)
                write_obs(obs)
                pipe.send((({a: info.get(a, {}) for a in agents}
                            if isinstance(info, dict) else {}), True))
            elif cmd == "step":
                action = {a: data[a] for a in env.agents} if env.agents else data
                obs, rew, term, trunc, info = env.step(action)
                final_obs = None
                if not env.agents:  # episode over for every agent: autoreset
                    # capture the TRUE final observations before reset —
                    # without them MA off-policy bootstrap targets at episode
                    # boundaries would use the next episode's reset obs
                    final_obs = {
                        a: np.asarray(v, copy=True) if not isinstance(v, (dict, tuple))
                        else v
                        for a, v in obs.items()
                    }
                    obs, _ = env.reset()
                write_obs(obs)
                # missing agents get NaN rewards (parity: get_placeholder_value
                # :765 — NaN is detectable downstream, 0.0 is a legal reward)
                out = (
                    {a: float(rew[a]) if a in rew else float("nan")
                     for a in agents},
                    {a: bool(term.get(a, False)) for a in agents},
                    {a: bool(trunc.get(a, False)) for a in agents},
                    {a: info.get(a, {}) for a in agents}
                    if isinstance(info, dict) else {},
                    final_obs,
                )
                pipe.send((out, True))
            elif cmd == "close":
                env.close()
                pipe.send(((), True))
                break
    except Exception:  # pragma: no cover - error path
        pipe.send((traceback.format_exc(), False))


class AsyncPettingZooVecEnv:
    def __init__(self, env_fns: List[Callable], context: str = "spawn"):
        ctx = mp.get_context(context)
        self.num_envs = len(env_fns)
        probe = env_fns[0]()
        self.agents = list(probe.possible_agents)
        self.possible_agents = list(probe.possible_agents)
        self.observation_spaces = {a: probe.observation_space(a) for a in self.agents}
        self.action_spaces = {a: probe.action_space(a) for a in self.agents}
        self.agent_ids = self.agents
        probe.close()
        # typed shared-memory blocks, one per (agent, space leaf)
        # (parity: create_shared_memory:733 — the reference types segments per
        # sub-space; float32-flattening would corrupt int/uint8/Dict obs)
        self._shm: Dict[str, Dict[str, tuple]] = {}
        for a in self.agents:
            self._shm[a] = {}
            for key, dtype, shape in _space_leaves(self.observation_spaces[a]):
                np_dtype = np.dtype("uint8") if dtype == np.dtype(bool) else dtype
                code = _TYPECODES[dtype.name]
                size = int(np.prod(shape)) if shape else 1
                self._shm[a][key] = (ctx.Array(code, self.num_envs * size), np_dtype)
        self._pipes, self._procs = [], []
        for i, fn in enumerate(env_fns):
            parent, child = ctx.Pipe()
            proc = ctx.Process(
                target=_async_worker,
                args=(i, fn, child, parent, self._shm, self.agents,
                      self.observation_spaces),
                daemon=True,
            )
            proc.start()
            child.close()
            self._pipes.append(parent)
            self._procs.append(proc)
        self._state = AsyncState.DEFAULT

    def observation_space(self, agent: str):
        return self.observation_spaces[agent]

    def action_space(self, agent: str):
        return self.action_spaces[agent]

    def _assert_is_running(self):
        assert all(p.is_alive() for p in self._procs), "worker died"

    def _raise_if_errors(self, results):
        for out, ok in results:
            if not ok:
                raise RuntimeError(f"env worker error:\n{out}")

    def _read_obs(self) -> Dict[str, np.ndarray]:
        out = {}
        for a in self.agents:
            space = self.observation_spaces[a]
            leaves = []
            for key, dtype, shape in _space_leaves(space):
                block, np_dtype = self._shm[a][key]
                arr = np.frombuffer(block.get_obj(), dtype=np_dtype).copy()
                if dtype == np.dtype(bool):
                    arr = arr.astype(bool)
                leaves.append(arr.reshape((self.num_envs,) + shape))
            out[a] = _rebuild_obs(space, leaves)
        return out

    def reset(self, seed: Optional[int] = None, options=None):
        self._assert_is_running()
        if self._state is not AsyncState.DEFAULT:
            # a pending step result would be mistaken for the reset ack
            raise RuntimeError(
                f"reset called while an async call is pending "
                f"(state={self._state.name})"
            )
        for i, pipe in enumerate(self._pipes):
            pipe.send(("reset",
                       (None if seed is None else seed + i, options)))
        results = [pipe.recv() for pipe in self._pipes]
        self._raise_if_errors(results)
        infos = [r for r, ok in results]
        return self._read_obs(), {"env_infos": infos}

    def step_async(self, actions: Dict[str, np.ndarray]) -> None:
        self._assert_is_running()
        if self._state is not AsyncState.DEFAULT:
            # parity: the reference raises AlreadyPendingCallError
            # (pz_async_vec_env.py:288) instead of double-queueing commands
            raise RuntimeError(
                f"step_async called while an async call is pending "
                f"(state={self._state.name})"
            )
        for i, pipe in enumerate(self._pipes):
            act_i = {a: np.asarray(actions[a])[i] for a in self.agents}
            act_i = {
                a: int(v) if hasattr(self.action_spaces[a], "n") else v
                for a, v in act_i.items()
            }
            pipe.send(("step", act_i))
        self._state = AsyncState.WAITING_STEP

    def step_wait(self):
        self._assert_is_running()
        if self._state is not AsyncState.WAITING_STEP:
            # parity: NoAsyncCallError (reference :308) — without this guard
            # the pipe.recv() below would block forever
            raise RuntimeError(
                "step_wait called without a pending step_async "
                f"(state={self._state.name})"
            )
        results = [pipe.recv() for pipe in self._pipes]
        self._raise_if_errors(results)
        self._state = AsyncState.DEFAULT
        rews, terms, truncs, env_infos, finals = zip(*[r for r, ok in results])
        stack = lambda ds: {a: np.array([d[a] for d in ds]) for a in self.agents}  # noqa: E731
        next_obs = self._read_obs()
        info: Dict = {"env_infos": list(env_infos)}
        # which env rows just autoreset — consumers (AsyncAgentsWrapper) use
        # this to close stale pending transitions exactly at episode ends
        info["autoreset"] = np.array([f is not None for f in finals], bool)
        if any(f is not None for f in finals):
            # merged per-agent final-obs batch: the true pre-reset successor
            # where an env just finished, the current obs elsewhere
            final_obs = {}
            for a in self.agents:
                space = self.observation_spaces[a]
                rows = [
                    _obs_leaves(space, finals[i][a])
                    if finals[i] is not None and a in finals[i]
                    else None
                    for i in range(self.num_envs)
                ]
                out_leaves = []
                for li, (key, dtype, shape) in enumerate(_space_leaves(space)):
                    block, np_dtype = self._shm[a][key]
                    cur = np.frombuffer(block.get_obj(), dtype=np_dtype).copy()
                    vals = cur.reshape((self.num_envs,) + shape).astype(dtype)
                    for i in range(self.num_envs):
                        if rows[i] is not None:
                            vals[i] = np.asarray(rows[i][li], dtype).reshape(shape)
                    out_leaves.append(vals)
                final_obs[a] = _rebuild_obs(space, out_leaves)
            info["final_obs"] = final_obs
        return next_obs, stack(rews), stack(terms), stack(truncs), info

    def step(self, actions):
        self.step_async(actions)
        return self.step_wait()

    def close(self):
        try:
            for pipe in self._pipes:
                pipe.send(("close", None))
            for pipe in self._pipes:
                pipe.recv()
        except (BrokenPipeError, EOFError, ConnectionResetError):
            pass  # workers already dead (e.g. after a propagated crash)
        for p in self._procs:
            p.join(timeout=2)
