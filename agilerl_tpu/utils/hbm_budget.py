"""Per-chip HBM budgeting for sharded GRPO training (the 7B dress rehearsal).

The reference leans on DeepSpeed's memory estimator + vLLM's
gpu_memory_utilization knob (/root/reference/agilerl/algorithms/core/base.py:
2081, 3101) to fit 7B training on accelerators; the TPU equivalent is a
static budget over the GSPMD shardings in parallel/mesh.gpt_param_specs —
every term below mirrors how that spec tree actually shards the tensors.

All sizes come from jax.eval_shape over the REAL init functions (no weights
materialised), so the budget can't drift from the model code.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import numpy as np

from agilerl_tpu.llm import model as M

HBM_PER_CHIP = {
    # usable HBM per chip (GiB) by generation
    "v4": 32, "v5e": 16, "v5p": 95, "v6e": 32,
}

GIB = 1024 ** 3


def _tree_bytes(tree) -> int:
    return sum(
        int(np.prod(l.shape)) * l.dtype.itemsize
        for l in jax.tree_util.tree_leaves(tree)
    )


def param_counts(config: M.GPTConfig, lora_rank: int = 8,
                 lora_targets=("wq", "wv")) -> Dict[str, int]:
    """Exact parameter counts/bytes via eval_shape on the real initialisers."""
    base = jax.eval_shape(lambda k: M.init_params(k, config),
                          jax.random.PRNGKey(0))
    lora = jax.eval_shape(
        lambda k: M.init_lora(k, config, lora_rank, lora_targets),
        jax.random.PRNGKey(0),
    )
    n_base = sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(base))
    n_lora = sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(lora))
    # A/B split: they shard on DIFFERENT mesh axes (lora_specs: A on fsdp,
    # B on tp), so the per-chip budget needs them separately
    a_bytes = b_bytes = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(lora):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        nbytes = int(np.prod(leaf.shape)) * leaf.dtype.itemsize
        if name == "A":
            a_bytes += nbytes
        elif name == "B":
            b_bytes += nbytes
    return {
        "base_params": n_base,
        "base_bytes": _tree_bytes(base),
        "lora_params": n_lora,
        "lora_bytes": _tree_bytes(lora),
        "lora_a_bytes": a_bytes,
        "lora_b_bytes": b_bytes,
    }


def grpo_hbm_budget(
    config: M.GPTConfig,
    fsdp: int,
    tp: int,
    batch_global: int,
    seq_len: int,
    dp: int = 1,
    lora_rank: int = 8,
    lora_targets=("wq", "wv"),
    gen_batch_global: Optional[int] = None,
    gen_total_len: Optional[int] = None,
    logit_chunk: int = 512,
) -> Dict[str, Any]:
    """Per-chip HBM budget (bytes) for the sharded GRPO step on an
    (fsdp, tp) mesh, batch sharded over fsdp, per-block remat.

    Terms (matching parallel/mesh.gpt_param_specs shardings):
    - base weights: bf16, matmul weights sharded over fsdp x tp
    - LoRA adapter: fp32 A/B (each sharded over one axis -> /fsdp) + AdamW
      moments (2x fp32) + transient grad (1x)
    - activation checkpoints: per-block remat stores the L block INPUTS,
      [B_local, T, d] bf16 each (residual stream is tp-replicated)
    - within-block recompute peak: the largest single-block working set
      during backward (QKV + flash-attn workspace + SwiGLU gate/up, /tp)
    - lm-head loss chunk: the fused/chunked loss never materialises
      [B, T, V] — only [B_local, chunk, V/tp] plus its bwd double-buffer
    - KV cache (generation phase): 2 x L x [B_local, P+N, kv_heads, hd] bf16,
      kv heads sharded over tp (GQA floor: at least 1 head per chip)
    """
    counts = param_counts(config, lora_rank, lora_targets)
    d, L, T = config.d_model, config.n_layer, seq_len
    # batch shards over BOTH data axes (dp, fsdp); weights are replicated
    # over dp (each dp slice holds the fsdp x tp shard)
    B_local = max(batch_global // (dp * fsdp), 1)
    bf16 = 2

    base_per_chip = counts["base_bytes"] / (fsdp * tp)
    # param + 2 AdamW moments + transient grad = 4x; A shards over fsdp,
    # B over tp (lora_specs), replicated leaves (none today) would be full
    other = counts["lora_bytes"] - counts["lora_a_bytes"] - counts["lora_b_bytes"]
    lora_state = 4 * (counts["lora_a_bytes"] / fsdp
                      + counts["lora_b_bytes"] / tp + other)
    # remat checkpoints: block inputs only
    ckpt = L * B_local * T * d * bf16
    # one block's live working set (recomputed in backward): qkv + attn out +
    # swiglu gate/up/down intermediates, head/ff dims sharded over tp
    qkv = B_local * T * (config.n_head + 2 * config.kv_heads) * config.head_dim * bf16 / tp
    ffn = B_local * T * config.ff_dim * 2 * bf16 / tp  # gate + up
    block_peak = (qkv + ffn + 2 * B_local * T * d * bf16) * 2  # x2 bwd residency
    # chunked lm-head loss: logits chunk + bwd double buffer, vocab / tp
    head_chunk = 2 * B_local * logit_chunk * config.vocab_size * 4 / tp
    budget = {
        "base_weights": base_per_chip,
        "lora_adapter_state": lora_state,
        "remat_checkpoints": ckpt,
        "block_recompute_peak": block_peak,
        "lm_head_loss_chunk": head_chunk,
    }
    if gen_batch_global and gen_total_len:
        Bg = max(gen_batch_global // (dp * fsdp), 1)
        kv_heads_local = max(config.kv_heads // tp, 1)
        budget["kv_cache_generation"] = (
            2 * L * Bg * gen_total_len * kv_heads_local * config.head_dim * bf16
        )
    budget["total"] = sum(budget.values())
    budget["meta"] = {
        "counts": counts, "dp": dp, "fsdp": fsdp, "tp": tp,
        "batch_global": batch_global, "batch_local": B_local, "seq_len": T,
    }
    return budget


def render_budget_md(budget: Dict[str, Any],
                     hbm_gib: float = HBM_PER_CHIP["v5p"]) -> str:
    """Markdown table of a grpo_hbm_budget result against a chip's HBM."""
    meta = budget["meta"]
    lines = [
        f"| term | per-chip GiB |",
        f"|---|---|",
    ]
    for k, v in budget.items():
        if k in ("total", "meta"):
            continue
        lines.append(f"| {k.replace('_', ' ')} | {v / GIB:.2f} |")
    total = budget["total"] / GIB
    lines.append(f"| **total** | **{total:.2f}** |")
    lines.append(
        f"| HBM per chip | {hbm_gib:.0f} "
        f"({'fits, ' + format(hbm_gib - total, '.1f') + ' GiB headroom' if total < hbm_gib else 'OVER BUDGET'}) |"
    )
    dp_part = f"dp={meta['dp']} x " if meta.get("dp", 1) > 1 else ""
    header = (
        f"mesh {dp_part}fsdp={meta['fsdp']} x tp={meta['tp']}, "
        f"global batch {meta['batch_global']} (local {meta['batch_local']}), "
        f"seq {meta['seq_len']}, "
        f"base params {meta['counts']['base_params'] / 1e9:.2f}B"
    )
    return header + "\n\n" + "\n".join(lines)
