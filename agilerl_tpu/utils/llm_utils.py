"""HF-dataset-as-gym for LLM RL finetuning
(parity: agilerl/utils/llm_utils.py — HuggingFaceGym:74, ReasoningGym:265,
PreferenceGym:464, context-length filtering :227, distributed-aware batching).

Tokenizer protocol: ``encode(str) -> List[int]``, ``decode(List[int]) -> str``,
``pad_token_id``, ``eos_token_id`` — satisfied by HF tokenizers and by the
in-tree CharTokenizer used in tests.

Multi-host note: the reference uses torch DistributedSampler; here each host
slices the dataset by ``jax.process_index()`` stride (same effect, no sampler
object).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from agilerl_tpu.llm.generate import left_pad


class CharTokenizer:
    """Tiny char-level tokenizer for tests/demos. id 0 = pad, 1 = eos."""

    def __init__(self, alphabet: str = "0123456789+-*=() abcdefghijklmnopqrstuvwxyz"):
        self.pad_token_id = 0
        self.eos_token_id = 1
        self._c2i = {c: i + 2 for i, c in enumerate(alphabet)}
        self._i2c = {i + 2: c for i, c in enumerate(alphabet)}
        self.vocab_size = len(alphabet) + 2

    def encode(self, text: str) -> List[int]:
        return [self._c2i[c] for c in text if c in self._c2i]

    def decode(self, ids) -> str:
        return "".join(self._i2c.get(int(i), "") for i in ids)


class HuggingFaceGym:
    """Dataset -> gym base (parity: llm_utils.py:74)."""

    def __init__(
        self,
        train_dataset,
        test_dataset,
        tokenizer,
        data_batch_size: int = 8,
        max_context_length: Optional[int] = None,
        question_key: str = "question",
        answer_key: str = "answer",
        seed: int = 0,
    ):
        self.tokenizer = tokenizer
        self.data_batch_size = int(data_batch_size)
        self.max_context_length = max_context_length
        self.question_key = question_key
        self.answer_key = answer_key
        self._rng = np.random.default_rng(seed + jax.process_index())
        self.train_rows = self._filter(list(train_dataset))
        self.test_rows = self._filter(list(test_dataset))
        # multi-host sharding: each host sees a strided slice
        if jax.process_count() > 1:
            self.train_rows = self.train_rows[jax.process_index():: jax.process_count()]
        self._epoch = 0
        self._cursor = 0
        self.num_epochs = 0

    def _filter(self, rows: List[Dict]) -> List[Dict]:
        """Context-length filtering (parity: llm_utils.py:227)."""
        if self.max_context_length is None:
            return rows
        out = []
        for r in rows:
            if len(self.tokenizer.encode(str(r[self.question_key]))) <= self.max_context_length:
                out.append(r)
        return out

    def eval_row_batches(self):
        """Yield the FULL test split in data_batch_size windows (parity: the
        reference iterates its whole test dataloader per evaluation,
        llm_utils.py test loader usage — a fixed first-slice eval would score
        every generation on the same handful of prompts)."""
        for start in range(0, len(self.test_rows), self.data_batch_size):
            yield self.test_rows[start : start + self.data_batch_size]

    def _next_batch(self, eval_mode: bool = False) -> List[Dict]:
        rows = self.test_rows if eval_mode else self.train_rows
        if eval_mode:
            return rows[: self.data_batch_size]
        if self._cursor + self.data_batch_size > len(rows):
            self._cursor = 0
            self._epoch += 1
            self.num_epochs = self._epoch
            order = self._rng.permutation(len(rows))
            self.train_rows = [rows[i] for i in order]
            rows = self.train_rows
        batch = rows[self._cursor : self._cursor + self.data_batch_size]
        self._cursor += self.data_batch_size
        return batch

    def _tokenize_prompts(self, rows: List[Dict]) -> Dict[str, np.ndarray]:
        seqs = [self.tokenizer.encode(str(r[self.question_key])) for r in rows]
        max_len = self.max_context_length
        if max_len is None:
            # bucket prompt length to a multiple of 32 so generate/learn jit
            # caches stay bounded instead of recompiling per batch shape
            longest = max(len(s) for s in seqs)
            max_len = ((longest + 31) // 32) * 32
        ids, mask = left_pad(seqs, pad_id=self.tokenizer.pad_token_id,
                             max_len=max_len)
        return {"input_ids": ids, "attention_mask": mask}

    def __len__(self):
        return len(self.train_rows)

    # -- resumable data-stream state --------------------------------------- #
    # the resilience snapshot's env entry: capture_env_rng prefers an env's
    # own state_dict over raw PRNG attributes, so a resumed run continues
    # the exact prompt stream instead of restarting the data epoch
    def state_dict(self) -> Dict:
        """Epoch/cursor counters, the epoch-shuffle RNG, and the current
        shuffled row order it produced (a fresh env would otherwise replay
        epoch 0's order and diverge from the uninterrupted run)."""
        return {
            "rng": self._rng.bit_generator.state,
            "epoch": self._epoch,
            "cursor": self._cursor,
            "num_epochs": self.num_epochs,
            "train_rows": list(self.train_rows),
        }

    def load_state_dict(self, state: Dict) -> None:
        from agilerl_tpu.resilience.snapshot import restore_np_generator

        self._rng = restore_np_generator(state["rng"])
        self._epoch = int(state["epoch"])
        self._cursor = int(state["cursor"])
        self.num_epochs = int(state["num_epochs"])
        self.train_rows = list(state["train_rows"])


class ReasoningGym(HuggingFaceGym):
    """reset() -> tokenized prompt batch; step(completions) -> rewards
    (parity: llm_utils.py:265)."""

    def __init__(self, *args, reward_fn: Callable[[str, Any, str], float], **kwargs):
        super().__init__(*args, **kwargs)
        self.reward_fn = reward_fn
        self._current: Optional[List[Dict]] = None
        self._current_prompts = None

    def reset(self, eval_mode: bool = False) -> Dict[str, np.ndarray]:
        self._current = self._next_batch(eval_mode)
        self._current_prompts = self._tokenize_prompts(self._current)
        return self._current_prompts

    def _rewards(self, completion_ids, completion_mask, group_size: int) -> np.ndarray:
        rewards = []
        for i, row in enumerate(self._current):
            group = []
            for g in range(group_size):
                r = i * group_size + g
                ids = np.asarray(completion_ids[r])
                m = np.asarray(completion_mask[r]).astype(bool)
                text = self.tokenizer.decode(ids[m])
                group.append(
                    float(self.reward_fn(text, row[self.answer_key], str(row[self.question_key])))
                )
            rewards.append(group)
        return np.asarray(rewards, np.float32)

    def step(
        self, completion_ids, completion_mask
    ) -> Tuple[Dict[str, np.ndarray], np.ndarray]:
        """completion_ids: [B*G, N]. Returns (next prompt batch, rewards [B, G])."""
        group_size = completion_ids.shape[0] // len(self._current)
        rewards = self._rewards(completion_ids, completion_mask, group_size)
        next_prompts = self.reset()
        return next_prompts, rewards

    def step_eval(self, completion_ids, completion_mask):
        rewards = self._rewards(completion_ids, completion_mask, 1)
        return None, rewards.reshape(-1)

    def state_dict(self) -> Dict:
        state = super().state_dict()
        # the rows step() will score the in-flight completions against
        state["current_rows"] = self._current
        return state

    def load_state_dict(self, state: Dict) -> None:
        super().load_state_dict(state)
        rows = state.get("current_rows")
        self._current = rows
        self._current_prompts = (
            None if rows is None else self._tokenize_prompts(rows)
        )

    def eval_batches(self):
        """Iterate tokenized prompt batches over the whole test split; each
        yielded batch becomes current for step_eval reward computation. The
        TRAIN state is snapshotted and restored afterwards — otherwise the
        first training step after an evaluation would compute rewards against
        the last eval window's answers and assemble learn batches from eval
        prompt tokens (review finding: silent train-data corruption)."""
        saved = (self._current, self._current_prompts)
        try:
            for rows in self.eval_row_batches():
                self._current = rows
                self._current_prompts = self._tokenize_prompts(rows)
                yield self._current_prompts
        finally:
            self._current, self._current_prompts = saved

    def assemble_learn_batch(self, completion_ids, completion_mask):
        """Concatenate the last prompt batch with completions into full
        sequences + action masks for GRPO.learn.

        Returns (ids [B*G, P+N], action_masks [B*G, P+N-1])."""
        prompts = self._current_prompts
        B, P = prompts["input_ids"].shape
        G = completion_ids.shape[0] // B
        prompt_ids = np.repeat(prompts["input_ids"], G, axis=0)
        ids = np.concatenate([prompt_ids, np.asarray(completion_ids)], axis=1)
        N = completion_ids.shape[1]
        action_mask = np.zeros((B * G, P + N - 1), np.float32)
        action_mask[:, P - 1:] = np.asarray(completion_mask, np.float32)
        return ids, action_mask


class PreferenceGym(HuggingFaceGym):
    """Preference-pair batches for DPO (parity: llm_utils.py:464). Dataset rows
    need prompt/chosen/rejected keys."""

    def __init__(
        self,
        *args,
        prompt_key: str = "prompt",
        chosen_key: str = "chosen",
        rejected_key: str = "rejected",
        max_completion_length: Optional[int] = None,
        **kwargs,
    ):
        kwargs.setdefault("question_key", prompt_key)
        super().__init__(*args, **kwargs)
        self.prompt_key = prompt_key
        self.chosen_key = chosen_key
        self.rejected_key = rejected_key
        self.max_completion_length = max_completion_length

    def reset(self, eval_mode: bool = False) -> Dict[str, np.ndarray]:
        return self._build_batch(self._next_batch(eval_mode))

    def eval_batches(self):
        """Iterate preference batches over the whole test split."""
        for rows in self.eval_row_batches():
            yield self._build_batch(rows)

    def _build_batch(self, rows: List[Dict]) -> Dict[str, np.ndarray]:
        tok = self.tokenizer

        def build(key):
            seqs, masks = [], []
            for r in rows:
                p = tok.encode(str(r[self.prompt_key]))
                c = tok.encode(str(r[key])) + [tok.eos_token_id]
                if self.max_completion_length:
                    c = c[: self.max_completion_length]
                seqs.append(p + c)
                masks.append(len(p))
            ids, attn = left_pad(seqs, pad_id=tok.pad_token_id)
            # prompt mask: 1 where token is part of the COMPLETION prediction
            # targets (parity: create_prompt_masks, core/base.py:3087)
            P = ids.shape[1]
            loss_mask = np.zeros((len(rows), P - 1), np.float32)
            for i, (seq, plen) in enumerate(zip(seqs, masks)):
                total = len(seq)
                start = P - total + plen  # left-pad offset + prompt length
                loss_mask[i, max(start - 1, 0):] = 1.0
            return ids, attn, loss_mask

        c_ids, c_attn, c_lm = build(self.chosen_key)
        r_ids, r_attn, r_lm = build(self.rejected_key)
        return {
            "chosen_ids": c_ids, "chosen_mask": c_attn, "chosen_loss_mask": c_lm,
            "rejected_ids": r_ids, "rejected_mask": r_attn, "rejected_loss_mask": r_lm,
        }
