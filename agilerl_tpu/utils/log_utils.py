"""Metric log combination across hosts (parity: agilerl/utils/log_utils.py —
DistributeCombineLogs:10, used by the legacy ILQL stack).

Host-side accumulation; the cross-host reduce rides
jax.experimental.multihost_utils instead of torch.distributed gathers.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np


class CombineLogs:
    """Accumulate (value, weight) pairs per metric and reduce to weighted means."""

    def __init__(self):
        self._logs: Dict[str, List] = {}

    def accum(self, metrics: Dict[str, float], weight: float = 1.0) -> None:
        for k, v in metrics.items():
            self._logs.setdefault(k, []).append((float(v), float(weight)))

    def reduce(self, across_hosts: bool = False) -> Dict[str, float]:
        out = {}
        for k, pairs in self._logs.items():
            vals = np.array([p[0] for p in pairs])
            wts = np.array([p[1] for p in pairs])
            num, den = float((vals * wts).sum()), float(wts.sum())
            if across_hosts:
                import jax

                if jax.process_count() > 1:
                    from jax.experimental import multihost_utils

                    both = multihost_utils.process_allgather(np.array([num, den]))
                    num, den = float(both[..., 0].sum()), float(both[..., 1].sum())
            out[k] = num / max(den, 1e-12)
        return out

    def clear(self) -> None:
        self._logs = {}


DistributeCombineLogs = CombineLogs  # parity alias
