from agilerl_tpu.utils import llm_utils, minari_utils, profiling, spaces, utils
from agilerl_tpu.utils.utils import create_population, make_vect_envs

__all__ = [
    "utils", "spaces", "llm_utils", "minari_utils", "profiling",
    "create_population", "make_vect_envs",
]
