"""Orbax-backed sharded checkpointing — the uniform replacement for the
reference's three checkpoint tiers (SURVEY.md §5.4: (a) per-agent dill
checkpoints core/base.py:919-1051, (b) population checkpoints utils/utils.py:656,
(c) DeepSpeed/PEFT LLM checkpoints core/base.py:2114-2237).

Pickle checkpoints (EvolvableAlgorithm.save_checkpoint) remain the lightweight
per-agent path; these orbax helpers add:
- sharded, async-capable saves of arbitrarily large pytrees (LLM tier) where
  every host writes only its param shards (multi-host safe);
- atomic versioned step directories with retention.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Dict, Optional, Union

import jax


def save_pytree(path: Union[str, Path], tree: Any, step: Optional[int] = None) -> None:
    """Save a (possibly sharded) pytree with orbax."""
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    ckptr = ocp.StandardCheckpointer()
    target = path if step is None else path / f"step_{step}"
    ckptr.save(target, tree, force=True)
    ckptr.wait_until_finished()


def load_pytree(path: Union[str, Path], like: Any = None, step: Optional[int] = None) -> Any:
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    target = path if step is None else path / f"step_{step}"
    ckptr = ocp.StandardCheckpointer()
    if like is not None:
        return ckptr.restore(target, like)
    return ckptr.restore(target)


def save_llm_checkpoint(agent, path: Union[str, Path], include_base: bool = False) -> None:
    """LLM checkpoint = adapters (+ optionally base weights) + attrs
    (parity: save_llm_checkpoint utils/utils.py:1021 / PEFT save_pretrained
    core/base.py:2125 — adapters-only is the default, exactly as the reference
    saves only the LoRA adapters)."""
    import pickle

    path = Path(path).absolute()
    path.mkdir(parents=True, exist_ok=True)
    save_pytree(path / "actor_adapter", agent.actor.params)
    save_pytree(path / "reference_adapter", agent.reference.params)
    if include_base:
        save_pytree(path / "base_params", agent.base_params)
    attrs = {
        "model_config": agent.model_config,
        "init_dict": {k: v for k, v in agent.init_dict.items() if k != "base_params"},
        "fitness": agent.fitness,
        "steps": agent.steps,
    }
    with open(path / "attributes.pkl", "wb") as f:
        pickle.dump(attrs, f)


def load_llm_checkpoint(agent, path: Union[str, Path]) -> None:
    """Restore adapters + training attrs into an existing agent (the reference
    deliberately requires re-instantiation for LLM load, core/base.py:2196 —
    same here)."""
    import pickle

    path = Path(path).absolute()
    agent.actor.params = load_pytree(path / "actor_adapter", agent.actor.params)
    agent.reference.params = load_pytree(path / "reference_adapter", agent.reference.params)
    if (path / "base_params").exists():
        agent.base_params = load_pytree(path / "base_params", agent.base_params)
    attrs_file = path / "attributes.pkl"
    if attrs_file.exists():
        with open(attrs_file, "rb") as f:
            attrs = pickle.load(f)
        agent.fitness = list(attrs.get("fitness", agent.fitness))
        agent.steps = list(attrs.get("steps", agent.steps))
    agent._clear_jit_cache()
