"""Orbax-backed sharded checkpointing — the uniform replacement for the
reference's three checkpoint tiers (SURVEY.md §5.4: (a) per-agent dill
checkpoints core/base.py:919-1051, (b) population checkpoints utils/utils.py:656,
(c) DeepSpeed/PEFT LLM checkpoints core/base.py:2114-2237).

Pickle checkpoints (EvolvableAlgorithm.save_checkpoint) remain the lightweight
per-agent path; these orbax helpers add:
- sharded, async-capable saves of arbitrarily large pytrees (LLM tier) where
  every host writes only its param shards (multi-host safe);
- atomic versioned step directories (staged under ``step_N.tmp`` and
  published with the resilience subsystem's fsync + ``os.replace`` commit,
  so a kill mid-save never leaves a half-written step dir) with optional
  retention (``keep_last=K`` prunes older step dirs after each save).

orbax-checkpoint is an optional dependency: ``pip install
agilerl-tpu[checkpoint]``.
"""

from __future__ import annotations

import shutil
from pathlib import Path
from typing import Any, Dict, List, Optional, Union

import jax

_STEP_PREFIX = "step_"


def _require_orbax():
    try:
        import orbax.checkpoint as ocp
    except ImportError as e:
        raise ImportError(
            "orbax-checkpoint is required for sharded pytree checkpoints "
            "(save_pytree/load_pytree) but is not installed. Install it with "
            "`pip install orbax-checkpoint` or `pip install "
            "'agilerl-tpu[checkpoint]'`. For CPU-scale whole-run snapshots "
            "no orbax is needed — use agilerl_tpu.resilience.Resilience, "
            "which pickles through the same atomic-commit protocol."
        ) from e
    return ocp


def step_dirs(path: Union[str, Path]) -> List[Path]:
    """Committed ``step_N`` directories under ``path``, ascending by step
    (uncommitted ``*.tmp`` staging dirs are invisible)."""
    path = Path(path)
    if not path.is_dir():
        return []
    out = []
    for d in path.iterdir():
        if not d.is_dir() or d.name.endswith(".tmp"):
            continue
        if d.name.startswith(_STEP_PREFIX):
            try:
                out.append((int(d.name[len(_STEP_PREFIX):]), d))
            except ValueError:
                continue
    return [d for _, d in sorted(out)]


def retain_step_dirs(path: Union[str, Path], keep_last: int) -> int:
    """Prune all but the newest ``keep_last`` committed step dirs. Returns
    how many were removed."""
    dirs = step_dirs(path)
    removed = 0
    for d in dirs[: -max(int(keep_last), 1)]:
        shutil.rmtree(d, ignore_errors=True)
        removed += 1
    return removed


def save_pytree(
    path: Union[str, Path],
    tree: Any,
    step: Optional[int] = None,
    keep_last: Optional[int] = None,
) -> None:
    """Save a (possibly sharded) pytree with orbax.

    With ``step``, the checkpoint is staged under ``step_N.tmp`` and
    atomically published as ``step_N`` (resilience commit protocol), then
    older step dirs beyond ``keep_last`` are pruned."""
    ocp = _require_orbax()

    path = Path(path).absolute()
    ckptr = ocp.StandardCheckpointer()
    if step is None:
        ckptr.save(path, tree, force=True)
        ckptr.wait_until_finished()
        return
    from agilerl_tpu.resilience.atomic import commit_dir

    final = path / f"{_STEP_PREFIX}{step}"
    tmp = path / (final.name + ".tmp")
    if tmp.exists():
        shutil.rmtree(tmp)
    ckptr.save(tmp, tree, force=True)
    ckptr.wait_until_finished()
    commit_dir(tmp, final)
    if keep_last is not None:
        retain_step_dirs(path, keep_last)


def load_pytree(path: Union[str, Path], like: Any = None, step: Optional[int] = None) -> Any:
    ocp = _require_orbax()

    path = Path(path).absolute()
    target = path if step is None else path / f"{_STEP_PREFIX}{step}"
    ckptr = ocp.StandardCheckpointer()
    if like is not None:
        return ckptr.restore(target, like)
    return ckptr.restore(target)


def save_llm_checkpoint(agent, path: Union[str, Path], include_base: bool = False) -> None:
    """LLM checkpoint = adapters (+ optionally base weights) + attrs
    (parity: save_llm_checkpoint utils/utils.py:1021 / PEFT save_pretrained
    core/base.py:2125 — adapters-only is the default, exactly as the reference
    saves only the LoRA adapters)."""
    path = Path(path).absolute()
    path.mkdir(parents=True, exist_ok=True)
    save_pytree(path / "actor_adapter", agent.actor.params)
    save_pytree(path / "reference_adapter", agent.reference.params)
    if include_base:
        save_pytree(path / "base_params", agent.base_params)
    attrs = {
        "model_config": agent.model_config,
        "init_dict": {k: v for k, v in agent.init_dict.items() if k != "base_params"},
        "fitness": agent.fitness,
        "steps": agent.steps,
    }
    # atomic (tmp + fsync + replace): load_llm_checkpoint unpickles this file
    # blindly — a kill mid-dump previously left a truncated pickle that a
    # later restore would crash on (GX004)
    from agilerl_tpu.resilience.atomic import atomic_pickle

    atomic_pickle(path / "attributes.pkl", attrs)


def load_llm_checkpoint(agent, path: Union[str, Path]) -> None:
    """Restore adapters + training attrs into an existing agent (the reference
    deliberately requires re-instantiation for LLM load, core/base.py:2196 —
    same here)."""
    import pickle

    path = Path(path).absolute()
    agent.actor.params = load_pytree(path / "actor_adapter", agent.actor.params)
    agent.reference.params = load_pytree(path / "reference_adapter", agent.reference.params)
    if (path / "base_params").exists():
        agent.base_params = load_pytree(path / "base_params", agent.base_params)
    attrs_file = path / "attributes.pkl"
    if attrs_file.exists():
        with open(attrs_file, "rb") as f:
            attrs = pickle.load(f)
        agent.fitness = list(attrs.get("fitness", agent.fitness))
        agent.steps = list(attrs.get("steps", agent.steps))
    agent._clear_jit_cache()
