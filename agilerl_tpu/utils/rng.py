"""Sanctioned RNG derivation — the ONE place the global numpy stream is drawn.

PR 3's determinism protocol captures and restores the *global* numpy RNG
precisely because unseeded components historically fell back to it (the
evolution-cloning bug). The rules that keep seeded and kill-resumed runs
bit-identical:

- components that need randomness take a threaded ``np.random.Generator`` or
  jax key;
- when a caller passes neither, the fallback seed is drawn HERE from the
  global stream — so ``np.random.seed(s)`` at run start makes every unseeded
  fallback reproducible, and the resilience snapshot (which captures global
  numpy state) makes it resume-exact;
- no other module draws ``np.random.*`` module-level functions (static rule
  GX003 enforces this; this file is its allowlist).

Before this helper, several fallbacks used ``np.random.default_rng()`` with
no seed — OS entropy that escaped both the seed and the snapshot, so an
unseeded ``TournamentSelection()`` stayed nondeterministic even under
``np.random.seed`` (the GX003 dogfood finding fixed in this PR).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["global_seed", "derive_rng", "derive_key"]


def global_seed(bound: int = 2 ** 31 - 1) -> int:
    """Draw a fallback seed from the global numpy stream — the audited root
    draw of the determinism protocol (captured by resilience snapshots,
    reproducible under ``np.random.seed``)."""
    return int(np.random.randint(0, bound))  # graftcheck: disable=GX003


def derive_rng(rng: Optional[np.random.Generator] = None,
               seed: Optional[int] = None) -> np.random.Generator:
    """Return ``rng`` unchanged when given; otherwise a Generator seeded from
    ``seed`` (when given) or the global stream. Use for every
    ``rng: Optional[Generator] = None`` fallback."""
    if rng is not None:
        return rng
    return np.random.default_rng(seed if seed is not None else global_seed())


def derive_key(key=None, seed: Optional[int] = None):
    """Return ``key`` unchanged when given; otherwise a fresh jax PRNG key
    seeded from ``seed`` or the global stream. The jax import is deferred so
    host-only consumers of this module never pay it."""
    if key is not None:
        return key
    import jax

    return jax.random.PRNGKey(seed if seed is not None else global_seed())
