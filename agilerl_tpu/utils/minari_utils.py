"""Offline-dataset ingestion (parity: agilerl/utils/minari_utils.py —
Minari dataset -> buffer/h5 :74,111; bundled h5 sets in data/cartpole,
data/pendulum).

Minari is not in this image, so the loaders gate on import; the h5 path (the
format the reference ships its offline data in) is fully supported via h5py,
plus a generator to produce offline datasets from any trained agent.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Dict, Optional, Union

import numpy as np


def load_h5_dataset(path: Union[str, Path]) -> Dict[str, np.ndarray]:
    """Load an offline dataset with observations/actions/rewards/
    next_observations/terminals arrays (the reference's h5 schema)."""
    import h5py

    out: Dict[str, np.ndarray] = {}
    with h5py.File(path, "r") as f:
        for key in ("observations", "actions", "rewards", "next_observations", "terminals"):
            if key in f:
                out[key] = np.asarray(f[key])
    if "next_observations" not in out and "observations" in out:
        obs = out["observations"]
        out["next_observations"] = np.concatenate([obs[1:], obs[-1:]], axis=0)
    return out


def save_h5_dataset(path: Union[str, Path], dataset: Dict[str, np.ndarray]) -> None:
    import h5py

    with h5py.File(path, "w") as f:
        for k, v in dataset.items():
            f.create_dataset(k, data=np.asarray(v))


def read_minari_h5(path: Union[str, Path]) -> Dict[str, np.ndarray]:
    """Vendored reader for the Minari on-disk HDF5 layout — one
    ``episode_<i>`` group per episode carrying observations/actions/rewards/
    terminations(/truncations) arrays, observations one row longer than the
    rest. Runs without the minari package, so the ingestion path is testable
    against a committed fixture (parity: minari_utils.py:74 — the reference
    delegates to minari.load_dataset, which reads exactly this layout)."""
    import h5py

    obs, act, rew, next_obs, term = [], [], [], [], []
    with h5py.File(path, "r") as f:
        names = sorted(
            (k for k in f.keys() if k.startswith("episode_")),
            key=lambda s: int(s.rsplit("_", 1)[1]),
        )
        if not names:
            raise ValueError(f"{path}: no episode_<i> groups — not a minari file")
        for name in names:
            g = f[name]
            o = np.asarray(g["observations"])
            obs.append(o[:-1])
            next_obs.append(o[1:])
            act.append(np.asarray(g["actions"]))
            rew.append(np.asarray(g["rewards"]))
            term.append(np.asarray(g["terminations"]))
    return {
        "observations": np.concatenate(obs),
        "actions": np.concatenate(act),
        "rewards": np.concatenate(rew).astype(np.float32),
        "next_observations": np.concatenate(next_obs),
        "terminals": np.concatenate(term).astype(np.float32),
    }


def _resolve_minari_path(dataset_id: str, data_dir=None) -> Optional[Path]:
    """Locate a dataset's main_data.hdf5: a direct file path, or the
    standard ~/.minari/datasets/<id>/data/main_data.hdf5 tree."""
    import os

    direct = Path(dataset_id)
    if direct.is_file():
        return direct
    root = Path(
        data_dir
        or os.environ.get("MINARI_DATASETS_PATH",
                          Path.home() / ".minari" / "datasets")
    )
    candidate = root / dataset_id / "data" / "main_data.hdf5"
    return candidate if candidate.is_file() else None


def minari_to_agile_dataset(
    dataset_id: str, data_dir=None, **kwargs
) -> Dict[str, np.ndarray]:
    """Convert a Minari dataset (parity: minari_utils.py:111). An on-disk
    dataset (a direct path to main_data.hdf5, or the standard tree under
    data_dir/MINARI_DATASETS_PATH) is read by the vendored reader whether or
    not the minari package is installed; a bare dataset id with no local
    file goes through minari.load_dataset."""
    path = _resolve_minari_path(dataset_id, data_dir)
    if path is not None:
        return read_minari_h5(path)
    try:
        import minari  # type: ignore
    except ImportError:
        raise FileNotFoundError(
            f"minari is not installed and no on-disk dataset found for "
            f"{dataset_id!r}; pass a path to a main_data.hdf5, set "
            "MINARI_DATASETS_PATH, load h5 data with load_h5_dataset, "
            "or generate data with collect_offline_dataset"
        )
    ds = minari.load_dataset(dataset_id)
    obs, act, rew, next_obs, term = [], [], [], [], []
    for ep in ds.iterate_episodes():
        obs.append(ep.observations[:-1])
        next_obs.append(ep.observations[1:])
        act.append(ep.actions)
        rew.append(ep.rewards)
        term.append(ep.terminations)
    return {
        "observations": np.concatenate(obs),
        "actions": np.concatenate(act),
        "rewards": np.concatenate(rew),
        "next_observations": np.concatenate(next_obs),
        "terminals": np.concatenate(term).astype(np.float32),
    }


def minari_to_agile_buffer(
    dataset_id: str, memory, data_dir=None
) -> Any:
    """Fill a replay buffer from a Minari dataset
    (parity: minari_utils.py:74 minari_to_agile_buffer)."""
    ds = minari_to_agile_dataset(dataset_id, data_dir=data_dir)
    memory.add(
        {
            "obs": ds["observations"],
            "action": ds["actions"],
            "reward": ds["rewards"],
            "next_obs": ds["next_observations"],
            "done": ds["terminals"],
        },
        batched=True,
    )
    return memory


def collect_offline_dataset(
    env, agent=None, steps: int = 10_000, epsilon: float = 0.3, seed: int = 0
) -> Dict[str, np.ndarray]:
    """Roll a (possibly epsilon-random) policy to build an offline dataset —
    replaces the reference's bundled h5 files with on-demand generation."""
    rng = np.random.default_rng(seed)
    num_envs = getattr(env, "num_envs", 1)
    obs_l, act_l, rew_l, next_l, term_l = [], [], [], [], []
    obs, _ = env.reset(seed=seed)
    for _ in range(steps // num_envs):
        if agent is not None and rng.random() > epsilon:
            action = np.asarray(agent.get_action(obs, training=False))
        else:
            sp = getattr(env, "single_action_space", env.action_space)
            if hasattr(sp, "n"):
                action = rng.integers(0, sp.n, size=num_envs)
            else:
                action = rng.uniform(sp.low, sp.high, size=(num_envs,) + sp.shape).astype(
                    np.float32
                )
        next_obs, reward, terminated, truncated, info = env.step(action)
        obs_l.append(obs)
        act_l.append(action)
        rew_l.append(reward)
        next_l.append(info.get("final_obs", next_obs) if isinstance(info, dict) else next_obs)
        term_l.append(np.asarray(terminated, np.float32))
        obs = next_obs
    return {
        "observations": np.concatenate(obs_l),
        "actions": np.concatenate(act_l),
        "rewards": np.concatenate(rew_l).astype(np.float32),
        "next_observations": np.concatenate(next_l),
        "terminals": np.concatenate(term_l),
    }
