"""Gymnasium-space introspection helpers (parity: agilerl/utils/evolvable_networks.py
get_default_encoder_config:168 and agilerl/utils/algo_utils.py obs utilities).

Observation conversion targets NHWC float32/uint8 jax arrays; discrete obs are
one-hot encoded on device.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from gymnasium import spaces


def is_image_space(space: Any) -> bool:
    return isinstance(space, spaces.Box) and len(space.shape) == 3


def is_vector_space(space: Any) -> bool:
    return (
        isinstance(space, (spaces.Discrete, spaces.MultiDiscrete, spaces.MultiBinary))
        or (isinstance(space, spaces.Box) and len(space.shape) <= 1)
    )


def obs_dim(space: Any) -> int:
    """Flat feature dimension of a non-image space."""
    if isinstance(space, spaces.Discrete):
        return int(space.n)
    if isinstance(space, spaces.MultiDiscrete):
        return int(np.sum(space.nvec))
    if isinstance(space, spaces.MultiBinary):
        return int(np.prod(space.shape))
    if isinstance(space, spaces.Box):
        return int(np.prod(space.shape)) if space.shape else 1
    raise TypeError(f"Unsupported observation space {type(space)}")


def image_shape_nhwc(space: spaces.Box) -> Tuple[int, int, int]:
    """Return (H, W, C). Accepts CHW (torch-style) or HWC boxes; a leading dim
    of <= 4 with trailing square dims is treated as channels-first."""
    s = space.shape
    assert len(s) == 3
    if s[0] <= 4 and s[1] == s[2]:
        return (s[1], s[2], s[0])
    return (s[0], s[1], s[2])


def action_dim(space: Any) -> int:
    if isinstance(space, spaces.Discrete):
        return int(space.n)
    if isinstance(space, spaces.MultiDiscrete):
        return int(np.sum(space.nvec))
    if isinstance(space, spaces.MultiBinary):
        return int(np.prod(space.shape))
    if isinstance(space, spaces.Box):
        return int(np.prod(space.shape))
    raise TypeError(f"Unsupported action space {type(space)}")


def preprocess_observation(space: Any, obs: Any) -> Any:
    """Convert a host/raw observation into network-ready jax arrays
    (parity: agilerl/utils/algo_utils.py:889 preprocess_observation).

    - Discrete -> one-hot float32
    - MultiDiscrete -> concatenated one-hots
    - Box images: CHW inputs transposed to NHWC
    - Dict/Tuple: recursed per subspace
    Vectorised over any number of leading batch dims.
    """
    if isinstance(space, spaces.Dict):
        return {k: preprocess_observation(space.spaces[k], obs[k]) for k in space.spaces}
    if isinstance(space, spaces.Tuple):
        return tuple(
            preprocess_observation(s, o) for s, o in zip(space.spaces, obs)
        )
    x = jnp.asarray(obs)
    if isinstance(space, spaces.Discrete):
        return jax.nn.one_hot(x.astype(jnp.int32), space.n)
    if isinstance(space, spaces.MultiDiscrete):
        parts = [
            jax.nn.one_hot(x[..., i].astype(jnp.int32), int(n))
            for i, n in enumerate(space.nvec)
        ]
        return jnp.concatenate(parts, axis=-1)
    if isinstance(space, spaces.MultiBinary):
        return x.astype(jnp.float32).reshape(*x.shape[: x.ndim - len(space.shape)], -1)
    if isinstance(space, spaces.Box):
        if len(space.shape) == 3:
            s = space.shape
            if s[0] <= 4 and s[1] == s[2] and x.shape[-3:] == tuple(s):
                # channels-first input -> NHWC
                x = jnp.moveaxis(x, -3, -1)
            return x
        flat_from = x.ndim - len(space.shape) if space.shape else x.ndim
        if len(space.shape) > 1:
            x = x.reshape(*x.shape[:flat_from], -1)
        elif space.shape == ():
            x = x[..., None]
        return x.astype(jnp.float32)
    raise TypeError(f"Unsupported observation space {type(space)}")


def sample_obs(space: Any, batch: int = 1) -> Any:
    """Draw a batched numpy observation sample for smoke tests/tracing."""
    if isinstance(space, spaces.Dict):
        return {k: sample_obs(s, batch) for k, s in space.spaces.items()}
    if isinstance(space, spaces.Tuple):
        return tuple(sample_obs(s, batch) for s in space.spaces)
    return np.stack([space.sample() for _ in range(batch)])
