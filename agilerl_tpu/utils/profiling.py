"""Tracing / profiling / MFU accounting — first-class on TPU
(parity+: the reference has NO in-library tracer, SURVEY.md §5.1 — profiling is
demonstrated via external cProfile/torch.profiler scripts and the only MFU
accounting is EvolvableGPT.estimate_mfu, agilerl/modules/gpt.py:516. Here
jax.profiler traces and per-step MFU/step-time metrics are built in.)
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, Iterator, Optional, Tuple

import jax


@contextlib.contextmanager
def profile_trace(logdir: str = "/tmp/agilerl_tpu_trace") -> Iterator[None]:
    """Capture a jax.profiler trace viewable in TensorBoard/Perfetto."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named trace span for host-side phases."""
    return jax.profiler.TraceAnnotation(name)


def transformer_flops_per_token(config) -> float:
    """Approximate fwd+bwd FLOPs per token for the GPT config (6N + attention),
    PaLM-style accounting."""
    d, L = config.d_model, config.n_layer
    ff = config.ff_dim
    # parameter count (mirrors llm/model.init_params)
    attn = d * config.n_head * config.head_dim * 2 + d * config.kv_heads * config.head_dim * 2
    mlp = 3 * d * ff
    n_params = config.vocab_size * d + L * (attn + mlp)
    return 6.0 * n_params + 12.0 * L * config.max_seq_len * d


#: peak bf16 FLOPs/s per chip by generation — the ONE table (hbm_budget and
#: the 7B plan read it too)
PEAK_BF16_FLOPS = {
    "tpu v4": 275e12, "tpu v5": 197e12, "tpu v5 lite": 197e12,
    "tpu v5p": 459e12, "tpu v6e": 918e12, "tpu v6 lite": 918e12,
}


#: fallback peak (TPU v5 bf16) for TPU generations missing from the table
_FALLBACK_TPU_PEAK = 197e12


def peak_flops_info(device=None, registry=None) -> Tuple[Optional[float], bool]:
    """``(peak_bf16_flops, estimated)`` for the device's chip generation.

    ``peak`` is None when the backend has no well-defined peak (CPU/GPU) — no
    fabricated MFU. A TPU generation missing from PEAK_BF16_FLOPS falls back
    to the v5 peak with ``estimated=True`` and a one-time warning event
    through ``registry`` (a run's own registry so the event reaches its JSONL
    stream; the process-default registry otherwise), so the silent-default
    failure mode (wrong-by-4x MFU on a future chip, nobody notices) cannot
    recur.
    """
    device = device or jax.devices()[0]
    if device.platform != "tpu":
        return None, False
    kind = device.device_kind.lower()
    peak = PEAK_BF16_FLOPS.get(kind)
    if peak is not None:
        return peak, False
    if registry is None:
        from agilerl_tpu.observability import get_registry

        registry = get_registry()
    registry.warn_once(
        f"peak_flops:{kind}",
        f"unknown TPU device_kind {kind!r}: no entry in PEAK_BF16_FLOPS — "
        f"falling back to {_FALLBACK_TPU_PEAK:.0f} FLOPs/s (TPU v5 bf16); "
        "MFU readings will be tagged estimated=true",
        device_kind=kind,
        fallback_peak_flops=_FALLBACK_TPU_PEAK,
    )
    return _FALLBACK_TPU_PEAK, True


def peak_flops_per_device(device=None) -> Optional[float]:
    """Peak bf16 FLOPs/s for the device's chip generation; None when the
    backend has no well-defined peak (CPU)."""
    return peak_flops_info(device)[0]


def estimate_mfu(
    config,
    tokens_per_step: int,
    step_time_s: float,
    peak_flops: Optional[float] = None,
) -> float:
    """Model FLOPs utilisation (parity: modules/gpt.py:516, generalised).

    peak_flops defaults per detected TPU generation (bf16). On a backend with
    no defined peak (CPU/GPU) the historical v5 fallback is kept for
    backward compatibility but announced via a one-time warning event — the
    returned figure is an estimate, not a real MFU."""
    if peak_flops is None:
        peak_flops, _ = peak_flops_info()
        if peak_flops is None:
            from agilerl_tpu.observability import warn_once

            warn_once(
                "estimate_mfu:no-peak",
                "estimate_mfu called on a backend with no defined bf16 peak "
                f"(CPU/GPU): using the TPU v5 fallback {_FALLBACK_TPU_PEAK:.0f} "
                "FLOPs/s — treat the result as an estimate",
            )
            peak_flops = _FALLBACK_TPU_PEAK
    flops = transformer_flops_per_token(config) * tokens_per_step
    return flops / (step_time_s * peak_flops)


def achieved_flops_metrics(
    lowered, calls: int, elapsed_s: float
) -> Dict[str, Any]:
    """Achieved FLOPs/s (and MFU where the chip has a defined peak) for a
    lowered jitted program, using XLA's own cost analysis — no hand model.
    Returns {} when the analysis is unavailable."""
    try:
        cost = lowered.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0))
    except Exception:
        return {}
    if flops <= 0 or elapsed_s <= 0:
        return {}
    achieved = flops * calls / elapsed_s
    out: Dict[str, Any] = {"achieved_tflops_per_sec": round(achieved / 1e12, 4)}
    peak, estimated = peak_flops_info()
    out["mfu"] = round(achieved / peak, 4) if peak else None
    if estimated:
        out["estimated"] = True
    return out


class StepTimer:
    """Rolling fps / step-time / MFU tracker for training loops
    (parity: fps tracking in training/train_off_policy.py:439)."""

    def __init__(self, window: int = 20):
        self.window = window
        self._times = []
        self._last = None

    def tick(self) -> Optional[float]:
        now = time.perf_counter()
        dt = None
        if self._last is not None:
            dt = now - self._last
            self._times.append(dt)
            if len(self._times) > self.window:
                self._times.pop(0)
        self._last = now
        return dt

    @property
    def mean_step_time(self) -> float:
        return sum(self._times) / len(self._times) if self._times else float("nan")

    def throughput(self, units_per_step: float) -> float:
        st = self.mean_step_time
        return units_per_step / st if st == st and st > 0 else float("nan")
