"""Population factory, env makers, evolution glue, logging helpers
(parity: agilerl/utils/utils.py — create_population:218, make_vect_envs:47,
tournament_selection_and_mutation:706, save_population_checkpoint:656,
print_hyperparams:924, aggregate_metrics_across_gpus:1004).
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Any, Dict, List, Optional

import jax
import numpy as np

# Algo name -> class, populated lazily to avoid import cycles
_ALGO_CLASSES: Dict[str, Any] = {}


def get_algo_class(algo: str):
    if not _ALGO_CLASSES:
        from agilerl_tpu.algorithms.dqn import DQN
        from agilerl_tpu.algorithms.ppo import PPO

        _ALGO_CLASSES.update({"DQN": DQN, "PPO": PPO})
        try:
            from agilerl_tpu.algorithms.dqn_rainbow import RainbowDQN

            _ALGO_CLASSES["Rainbow DQN"] = RainbowDQN
            _ALGO_CLASSES["RainbowDQN"] = RainbowDQN
        except ImportError:
            pass
        try:
            from agilerl_tpu.algorithms.ddpg import DDPG
            from agilerl_tpu.algorithms.td3 import TD3

            _ALGO_CLASSES.update({"DDPG": DDPG, "TD3": TD3})
        except ImportError:
            pass
        try:
            from agilerl_tpu.algorithms.cqn import CQN

            _ALGO_CLASSES["CQN"] = CQN
        except ImportError:
            pass
        try:
            from agilerl_tpu.algorithms.neural_ucb_bandit import NeuralUCB
            from agilerl_tpu.algorithms.neural_ts_bandit import NeuralTS

            _ALGO_CLASSES.update({"NeuralUCB": NeuralUCB, "NeuralTS": NeuralTS})
        except ImportError:
            pass
        try:
            from agilerl_tpu.algorithms.maddpg import MADDPG
            from agilerl_tpu.algorithms.matd3 import MATD3

            _ALGO_CLASSES.update({"MADDPG": MADDPG, "MATD3": MATD3})
        except ImportError:
            pass
        try:
            from agilerl_tpu.algorithms.ippo import IPPO

            _ALGO_CLASSES["IPPO"] = IPPO
        except ImportError:
            pass
        try:
            from agilerl_tpu.algorithms.grpo import GRPO

            _ALGO_CLASSES["GRPO"] = GRPO
        except ImportError:
            pass
        try:
            from agilerl_tpu.algorithms.dpo import DPO

            _ALGO_CLASSES["DPO"] = DPO
        except ImportError:
            pass
    if algo not in _ALGO_CLASSES:
        raise KeyError(f"Unknown algorithm {algo!r}; known: {sorted(_ALGO_CLASSES)}")
    return _ALGO_CLASSES[algo]


# INIT_HP upper-case key -> constructor kwarg (parity with the reference's
# INIT_HP dict convention)
_INIT_HP_MAP = {
    "BATCH_SIZE": "batch_size",
    "LR": "lr",
    "LR_ACTOR": "lr_actor",
    "LR_CRITIC": "lr_critic",
    "GAMMA": "gamma",
    "TAU": "tau",
    "LEARN_STEP": "learn_step",
    "DOUBLE": "double",
    "N_STEP": "n_step",
    "PER": "per",
    "NUM_ATOMS": "num_atoms",
    "V_MIN": "v_min",
    "V_MAX": "v_max",
    "CLIP_COEF": "clip_coef",
    "ENT_COEF": "ent_coef",
    "VF_COEF": "vf_coef",
    "MAX_GRAD_NORM": "max_grad_norm",
    "UPDATE_EPOCHS": "update_epochs",
    "GAE_LAMBDA": "gae_lambda",
    "TARGET_KL": "target_kl",
    "POLICY_FREQ": "policy_freq",
    "O_U_NOISE": "O_U_noise",
    "EXPL_NOISE": "expl_noise",
    "MEAN_NOISE": "mean_noise",
    "THETA": "theta",
    "DT": "dt",
    "NUM_ENVS": "num_envs",
    "AGENT_IDS": "agent_ids",
    "LAMBDA": "lamb",
    "REG": "reg",
}


def create_population(
    algo: str,
    observation_space,
    action_space,
    net_config: Optional[Dict[str, Any]] = None,
    INIT_HP: Optional[Dict[str, Any]] = None,
    hp_config=None,
    population_size: Optional[int] = None,
    num_envs: int = 1,
    device=None,
    accelerator=None,
    seed: Optional[int] = None,
    **kwargs,
) -> List:
    """Build a population of agents (parity: utils/utils.py:218)."""
    INIT_HP = dict(INIT_HP or {})
    pop_size = population_size or INIT_HP.get("POP_SIZE", INIT_HP.get("POPULATION_SIZE", 4))
    algo_cls = get_algo_class(algo)

    import inspect

    # named ctor params across the whole MRO (subclasses forward **kwargs to
    # parents with the real named args, e.g. TD3 -> DDPG)
    named = set()
    for cls in algo_cls.__mro__:
        init = cls.__dict__.get("__init__")
        if init is None:
            continue
        for p in inspect.signature(init).parameters.values():
            if p.kind in (p.POSITIONAL_OR_KEYWORD, p.KEYWORD_ONLY):
                named.add(p.name)
    ctor_kwargs: Dict[str, Any] = {}
    for k, v in INIT_HP.items():
        key = _INIT_HP_MAP.get(k)
        # INIT_HP holds trainer-level keys too (PER, NUM_ENVS, N_STEP for the
        # loop) — only forward the ones this algorithm's signature names;
        # explicit **kwargs from the caller still error loudly below
        if key is not None and key in named:
            ctor_kwargs[key] = v
    ctor_kwargs.update(kwargs)
    if "num_envs" in named:
        ctor_kwargs.setdefault("num_envs", num_envs)

    population = []
    # seed=None must derive from the captured global stream, not OS entropy —
    # otherwise two np.random.seed-ed runs build different populations and
    # kill-resume diverges (GX003 bug class; see utils/rng.py)
    from agilerl_tpu.utils.rng import derive_rng

    rng = derive_rng(seed=seed)
    for idx in range(pop_size):
        population.append(
            algo_cls(
                observation_space,
                action_space,
                index=idx,
                net_config=net_config,
                hp_config=hp_config,
                seed=int(rng.integers(0, 2**31 - 1)),
                **ctor_kwargs,
            )
        )
    return population


def make_vect_envs(
    env_name: Optional[str] = None,
    num_envs: int = 1,
    *,
    make_env: Optional[Any] = None,
    should_async_vector: bool = True,
    prefer_jax: bool = True,
    **env_kwargs,
):
    """Vectorised env factory (parity: utils/utils.py:47).

    Prefers the in-tree pure-JAX env (zero-host-boundary) when the id is known;
    falls back to gymnasium vectorisation otherwise."""
    if make_env is None and prefer_jax and env_name is not None:
        from agilerl_tpu.envs import classic

        if env_name in classic.REGISTRY:
            from agilerl_tpu.envs.core import JaxVecEnv

            return JaxVecEnv(classic.make(env_name), num_envs=num_envs)
    import gymnasium as gym

    if make_env is not None:
        fns = [make_env for _ in range(num_envs)]
    else:
        fns = [lambda: gym.make(env_name, **env_kwargs) for _ in range(num_envs)]
    cls = gym.vector.AsyncVectorEnv if should_async_vector else gym.vector.SyncVectorEnv
    return cls(fns)


def make_multi_agent_vect_envs(
    env,
    num_envs: int = 1,
    should_async_vector: bool = True,
    **env_kwargs,
):
    """Vectorise a PettingZoo parallel env factory (parity: utils/utils.py:82).
    `env` is a callable returning a fresh parallel env."""
    from agilerl_tpu.vector import AsyncPettingZooVecEnv, PettingZooVecEnv

    fns = [lambda: env(**env_kwargs) for _ in range(num_envs)]
    cls = AsyncPettingZooVecEnv if should_async_vector else PettingZooVecEnv
    return cls(fns)


def make_skill_vect_envs(env_name: str, skill, num_envs: int = 1):
    """Vectorise a gym env wrapped in a curriculum Skill (parity:
    utils/utils.py:101; the Skill wrapper lives in wrappers/learning.py)."""
    import gymnasium as gym

    return gym.vector.AsyncVectorEnv(
        [lambda: skill(gym.make(env_name)) for _ in range(num_envs)]
    )


def observation_space_channels_to_first(observation_space):
    """[H, W, C] -> [C, H, W] space transform (parity: utils/utils.py:120).

    The in-tree CNN encoder is NHWC (TPU conv layout) so this is only needed
    when interfacing with channels-first torch policies via MakeEvolvable or
    when mirroring reference configs that set swap_channels."""
    from gymnasium import spaces

    if isinstance(observation_space, spaces.Dict):
        return spaces.Dict(
            {
                k: observation_space_channels_to_first(v)
                for k, v in observation_space.spaces.items()
            }
        )
    if isinstance(observation_space, spaces.Tuple):
        return spaces.Tuple(
            tuple(observation_space_channels_to_first(s)
                  for s in observation_space.spaces)
        )
    if isinstance(observation_space, spaces.Box) and len(observation_space.shape) == 3:
        low = np.moveaxis(observation_space.low, -1, 0)
        high = np.moveaxis(observation_space.high, -1, 0)
        return spaces.Box(low=low, high=high, dtype=observation_space.dtype)
    return observation_space


def calculate_vectorized_scores(
    rewards: np.ndarray,
    terminations: np.ndarray,
    include_unterminated: bool = False,
    only_first_episode: bool = True,
) -> List[float]:
    """Segment per-env reward rows into episode scores at termination points
    (parity: utils/utils.py:861)."""
    episode_rewards: List[float] = []
    num_envs = rewards.shape[0]
    for env_index in range(num_envs):
        term_idx = np.where(terminations[env_index] == 1)[0]
        if len(term_idx) == 0:
            episode_rewards.append(float(np.sum(rewards[env_index])))
            continue
        start = 0
        for t in term_idx:
            episode_rewards.append(float(np.sum(rewards[env_index, start : t + 1])))
            start = t + 1
            if only_first_episode:
                break
        if (
            include_unterminated
            and not only_first_episode
            and start < rewards.shape[1]
        ):
            episode_rewards.append(float(np.sum(rewards[env_index, start:])))
    return episode_rewards


def get_env_defined_actions(info: Dict[str, Any], agents) -> Optional[Dict[str, Any]]:
    """Per-agent env-dictated actions from a PettingZoo info dict (parity:
    utils/utils.py:962). Returns None when no agent has one."""
    eda = {
        agent: info.get(agent, {}).get("env_defined_action", None)
        for agent in agents
    }
    if all(v is None for v in eda.values()):
        return None
    return eda


def extract_action_masks(info: Dict[str, Any], agents) -> Optional[Dict[str, Any]]:
    """Per-agent invalid-action masks from a PettingZoo info dict (parity:
    MultiAgentRLAlgorithm.process_infos, core/base.py). None when absent."""
    masks = {
        agent: info.get(agent, {}).get("action_mask", None) for agent in agents
    }
    if all(v is None for v in masks.values()):
        return None
    return masks


def process_ma_infos(infos: Optional[Dict[str, Any]], agent_ids):
    """One-stop extraction of (action masks, env-defined actions) from a
    PettingZoo info dict for the MA get_action paths (parity:
    MultiAgentRLAlgorithm.process_infos, reference maddpg.py:414).
    Masks come back as jnp [B, n] arrays (atleast_2d) or None per agent."""
    if not infos:
        return None, None
    import jax.numpy as jnp

    masks = None
    raw_masks = extract_action_masks(infos, agent_ids)
    if raw_masks is not None:
        masks = {
            a: (None if raw_masks[a] is None
                else jnp.atleast_2d(jnp.asarray(raw_masks[a])))
            for a in agent_ids
        }
    return masks, get_env_defined_actions(infos, agent_ids)


def apply_env_defined_actions(
    eda: Optional[Dict[str, Any]], out: Dict[str, np.ndarray]
) -> Dict[str, np.ndarray]:
    """Overwrite policy actions with env-dictated ones, PER ROW:
    - numpy masked array: only unmasked rows are forced;
    - float array with NaN: non-NaN rows are forced;
    - scalar or full array: every row.
    (parity: apply_env_defined_actions, reference algo_utils)."""
    if eda is None:
        return out
    for a, forced in eda.items():
        if forced is None:
            continue
        cur = out[a]
        if isinstance(forced, np.ma.MaskedArray):
            keep = np.ma.getmaskarray(forced)
            vals = np.broadcast_to(forced.filled(0), cur.shape)
            out[a] = np.where(
                np.broadcast_to(keep, cur.shape), cur, vals.astype(cur.dtype)
            )
            continue
        forced_arr = np.asarray(forced)
        if forced_arr.dtype.kind == "f" and np.isnan(forced_arr).any():
            vals = np.broadcast_to(forced_arr, cur.shape)
            out[a] = np.where(
                np.isnan(vals), cur, np.nan_to_num(vals).astype(cur.dtype)
            )
            continue
        out[a] = np.broadcast_to(forced_arr.astype(cur.dtype), cur.shape).copy()
    return out


def forced_action_arrays(
    eda: Optional[Dict[str, Any]], agent_ids, batch: int, action_spaces=None
):
    """Normalise env-defined actions into per-agent (values, valid) pairs for
    resolution INSIDE a policy's act function (on-policy agents must compute
    the log-prob of the action actually executed). valid is ELEMENT-WISE
    (same shape as values) — exactly apply_env_defined_actions' semantics,
    where a NaN/masked COMPONENT keeps the policy's component and the rest of
    the row is still forced. None when nothing is forced.

    action_spaces (optional per-agent dict) disambiguates a bare 1-D action
    vector whose length happens to equal batch: with the space known, the
    target shape is always (batch,) + the space's action dims."""
    if eda is None:
        return None
    from gymnasium import spaces as S

    def space_trailing(space):
        if space is None:
            return None
        if isinstance(space, S.MultiDiscrete):
            return (len(space.nvec),)
        if isinstance(space, (S.Box, S.MultiBinary)):
            return tuple(space.shape)
        return ()  # Discrete: scalar action per row

    def row_shape(arr, trailing):
        if trailing is not None:
            return (batch,) + trailing
        # no space info: [B]/[B, ...dims] pass through; scalars and bare
        # per-row action vectors broadcast up to a leading batch axis
        if arr.ndim == 0:
            return (batch,)
        if arr.shape[0] == batch:
            return arr.shape
        return (batch,) + arr.shape

    out = {}
    for a in agent_ids:
        forced = eda.get(a)
        if forced is None:
            continue  # absent agents are simply not in the dict
        trailing = space_trailing(
            action_spaces.get(a) if action_spaces else None
        )
        if isinstance(forced, np.ma.MaskedArray):
            arr = np.asarray(forced.filled(0))
            invalid = np.ma.getmaskarray(forced)
        else:
            arr = np.asarray(forced)
            invalid = (
                np.isnan(arr) if arr.dtype.kind == "f"
                else np.zeros(arr.shape, bool)
            )
        tgt = row_shape(arr, trailing)
        # a [B, 1] column vector against a scalar-per-row target collapses
        # its trailing unit dims instead of failing the broadcast
        while arr.ndim > len(tgt) and arr.shape[-1] == 1:
            arr, invalid = arr[..., 0], invalid[..., 0]
        try:
            vals = np.broadcast_to(arr, tgt).copy()
        except ValueError:
            raise ValueError(
                f"env_defined_action for {a!r} has shape "
                f"{np.asarray(forced).shape}, incompatible with the action "
                f"target shape {tgt}"
            ) from None
        if vals.dtype.kind == "f":
            vals = np.nan_to_num(vals)
        # dtype is PRESERVED (continuous Box actions must not truncate to
        # int) and so are trailing action dims (review finding)
        out[a] = (vals, (~np.broadcast_to(invalid, tgt)).copy())
    return out if out else None


def gather_across_hosts(value) -> np.ndarray:
    """All-gather a host-local scalar/array across processes, stacked on a
    leading process axis (parity: utils/utils.py:985 gather_tensor — the
    accelerate gather becomes a process_allgather).

    Deliberately NOT retried: a per-host retry of a collective desyncs the
    pod (the retrying host re-issues an op its peers already completed and
    pairs with the wrong collective). Collectives fail fast; the resilience
    subsystem's snapshot-resume is the recovery path (docs/resilience.md)."""
    arr = np.asarray(value)
    if jax.process_count() == 1:
        return arr[None]
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr))


def consolidate_mutations(population: List) -> None:
    """Cross-host mutation-consistency check (parity redesign:
    utils/utils.py:1047 — the reference BROADCASTS rank-0's mutation choices
    because each rank mutates independently; here every host runs the same
    deterministic RNG so the decisions are already identical, and this
    function VERIFIES that invariant instead, raising on divergence)."""
    if jax.process_count() == 1:
        return
    import zlib

    # NB: not Python hash() — str hashing is salted per-process
    # (PYTHONHASHSEED), which would make identical decisions "diverge"
    local = np.asarray(
        [zlib.crc32(repr((agent.index, getattr(agent, "mut", None))).encode())
         for agent in population],
        np.int64,
    )
    gathered = gather_across_hosts(local)
    if not (gathered == gathered[0]).all():
        raise RuntimeError(
            "mutation decisions diverged across hosts — the replicated-RNG "
            f"invariant is broken (per-host digests: {gathered.tolist()})"
        )


def tournament_selection_and_mutation(
    population: List,
    tournament,
    mutation,
    env_name: Optional[str] = None,
    algo: Optional[str] = None,
    elite_path: Optional[str] = None,
    save_elite: bool = False,
    accelerator=None,
    language_model: bool = False,
    lineage=None,
) -> List:
    """select -> mutate -> optionally save elite (parity: utils/utils.py:706).

    ``lineage`` (an observability.LineageTracker) attaches to the tournament
    and mutation engines for this call so genealogy is recorded without the
    caller mutating HPO objects itself."""
    if lineage is not None:
        tournament.lineage = lineage
        mutation.lineage = lineage
    elite, population = tournament.select(population)
    population = mutation.mutation(population)
    if save_elite and elite_path is not None:
        path = Path(elite_path)
        if path.suffix == "":
            path = path / f"{algo or elite.algo}_elite.ckpt"
        elite.save_checkpoint(path)
    return population


def save_population_checkpoint(
    population: List, save_path: str, overwrite_checkpoints: bool = True, accelerator=None
) -> None:
    """Checkpoint every member (parity: utils/utils.py:656).
    overwrite_checkpoints=False keeps per-step history by appending the
    member's current step count to the filename."""
    for agent in population:
        p = Path(save_path)
        stem = f"{p.stem}_{agent.index}"
        if not overwrite_checkpoints:
            stem = f"{stem}_step{agent.steps[-1]}"
        path = p.parent / f"{stem}{p.suffix or '.ckpt'}"
        agent.save_checkpoint(path)


def resume_population_from_checkpoint(pop: List, checkpoint_path: Optional[str]) -> List:
    """Restore each member in place from its `{stem}_{index}` checkpoint file
    if one exists (parity: the reference trainers' wandb-resume restore path,
    agilerl/training/train_off_policy.py resume branch). Members without a file
    (e.g. population grew) keep their fresh initialisation.

    Corrupt/torn files (a kill mid-save predating the atomic
    ``save_checkpoint``, disk trouble) are skipped with a warn-once instead of
    crashing mid-restore — that member simply keeps its fresh weights. For
    crash-consistent whole-run restore use the resilience subsystem
    (``agilerl_tpu.resilience.Resilience``) instead."""
    if checkpoint_path is None:
        return pop
    import pickle

    for agent in pop:
        p = Path(checkpoint_path)
        f = p.parent / f"{p.stem}_{agent.index}{p.suffix or '.ckpt'}"
        if not f.exists():
            continue
        # torn pickles fail before touching the agent, but an incompatible
        # checkpoint (another code version) can raise from INSIDE _restore,
        # which mutates networks, then optimizers, then attrs in sequence —
        # capture the pre-restore state so a mid-sequence failure rolls
        # back instead of leaving a silently inconsistent agent
        before = agent.checkpoint_dict()
        try:
            agent.load_checkpoint(f)
        except (pickle.UnpicklingError, EOFError, OSError, AttributeError,
                KeyError, IndexError, ValueError, ImportError) as e:
            from agilerl_tpu.observability import warn_once

            try:
                agent._restore(before)
                detail = f"agent {agent.index} keeps its current weights"
            except Exception:
                detail = (f"agent {agent.index} could not be rolled back "
                          "and may be inconsistent")
            warn_once(
                f"resume:corrupt_checkpoint:{f.name}",
                f"skipping corrupt/torn checkpoint {f} "
                f"({type(e).__name__}: {e}) — {detail}",
            )
    return pop


def load_population_checkpoint(algo: str, save_path: str, indices: List[int], **kwargs) -> List:
    cls = get_algo_class(algo)
    pop = []
    for idx in indices:
        p = Path(save_path)
        path = p.parent / f"{p.stem}_{idx}{p.suffix or '.ckpt'}"
        pop.append(cls.load(path))
    return pop


def print_hyperparams(population: List) -> None:
    """Log per-agent HPs + fitness (parity: utils/utils.py:924)."""
    for agent in population:
        hps = {name: getattr(agent, name) for name in agent.hp_config.names()}
        fit = np.mean(agent.fitness[-5:]) if agent.fitness else float("nan")
        print(
            f"Agent {agent.index}: fitness(5)={fit:.2f} mut={agent.mut} "
            f"steps={agent.steps[-1]} {hps}"
        )


def plot_population_score(pop, path: Optional[str] = None):
    """Plot per-agent fitness curves (parity: utils/utils.py:945). Gated on
    matplotlib availability."""
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:  # pragma: no cover
        return None
    fig, ax = plt.subplots()
    for agent in pop:
        ax.plot(agent.fitness, label=f"agent {agent.index}")
    ax.set_xlabel("evaluation")
    ax.set_ylabel("fitness")
    ax.legend()
    if path:
        fig.savefig(path)
    return fig


def aggregate_metrics_across_hosts(value: float) -> float:
    """Mean-reduce a host scalar across processes (parity: utils/utils.py:1004
    aggregate_metrics_across_gpus — torch.distributed gather becomes a psum over
    the pod when running multi-host)."""
    if jax.process_count() == 1:
        return float(value)
    from jax.experimental import multihost_utils

    # not retried — see gather_across_hosts: per-host collective retry
    # desyncs the pod; snapshot-resume is the recovery path
    arr = multihost_utils.process_allgather(np.asarray([value]))
    return float(np.mean(arr))


def default_progress_bar(total: int, desc: str = ""):
    try:
        from tqdm import trange

        return trange(total, desc=desc)
    except ImportError:  # pragma: no cover
        return range(total)


def init_wandb(project: str = "agilerl-tpu", config: Optional[dict] = None, **kwargs):
    """W&B is optional in this image; no-op fallback (parity: utils.py:799)."""
    try:
        import wandb

        wandb.init(project=project, config=config, **kwargs)
        return wandb
    except Exception:
        return None
