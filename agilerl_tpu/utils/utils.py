"""Population factory, env makers, evolution glue, logging helpers
(parity: agilerl/utils/utils.py — create_population:218, make_vect_envs:47,
tournament_selection_and_mutation:706, save_population_checkpoint:656,
print_hyperparams:924, aggregate_metrics_across_gpus:1004).
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Any, Dict, List, Optional

import jax
import numpy as np

# Algo name -> class, populated lazily to avoid import cycles
_ALGO_CLASSES: Dict[str, Any] = {}


def get_algo_class(algo: str):
    if not _ALGO_CLASSES:
        from agilerl_tpu.algorithms.dqn import DQN
        from agilerl_tpu.algorithms.ppo import PPO

        _ALGO_CLASSES.update({"DQN": DQN, "PPO": PPO})
        try:
            from agilerl_tpu.algorithms.dqn_rainbow import RainbowDQN

            _ALGO_CLASSES["Rainbow DQN"] = RainbowDQN
            _ALGO_CLASSES["RainbowDQN"] = RainbowDQN
        except ImportError:
            pass
        try:
            from agilerl_tpu.algorithms.ddpg import DDPG
            from agilerl_tpu.algorithms.td3 import TD3

            _ALGO_CLASSES.update({"DDPG": DDPG, "TD3": TD3})
        except ImportError:
            pass
        try:
            from agilerl_tpu.algorithms.cqn import CQN

            _ALGO_CLASSES["CQN"] = CQN
        except ImportError:
            pass
        try:
            from agilerl_tpu.algorithms.neural_ucb_bandit import NeuralUCB
            from agilerl_tpu.algorithms.neural_ts_bandit import NeuralTS

            _ALGO_CLASSES.update({"NeuralUCB": NeuralUCB, "NeuralTS": NeuralTS})
        except ImportError:
            pass
        try:
            from agilerl_tpu.algorithms.maddpg import MADDPG
            from agilerl_tpu.algorithms.matd3 import MATD3

            _ALGO_CLASSES.update({"MADDPG": MADDPG, "MATD3": MATD3})
        except ImportError:
            pass
        try:
            from agilerl_tpu.algorithms.ippo import IPPO

            _ALGO_CLASSES["IPPO"] = IPPO
        except ImportError:
            pass
        try:
            from agilerl_tpu.algorithms.grpo import GRPO

            _ALGO_CLASSES["GRPO"] = GRPO
        except ImportError:
            pass
        try:
            from agilerl_tpu.algorithms.dpo import DPO

            _ALGO_CLASSES["DPO"] = DPO
        except ImportError:
            pass
    if algo not in _ALGO_CLASSES:
        raise KeyError(f"Unknown algorithm {algo!r}; known: {sorted(_ALGO_CLASSES)}")
    return _ALGO_CLASSES[algo]


# INIT_HP upper-case key -> constructor kwarg (parity with the reference's
# INIT_HP dict convention)
_INIT_HP_MAP = {
    "BATCH_SIZE": "batch_size",
    "LR": "lr",
    "LR_ACTOR": "lr_actor",
    "LR_CRITIC": "lr_critic",
    "GAMMA": "gamma",
    "TAU": "tau",
    "LEARN_STEP": "learn_step",
    "DOUBLE": "double",
    "N_STEP": "n_step",
    "PER": "per",
    "NUM_ATOMS": "num_atoms",
    "V_MIN": "v_min",
    "V_MAX": "v_max",
    "CLIP_COEF": "clip_coef",
    "ENT_COEF": "ent_coef",
    "VF_COEF": "vf_coef",
    "MAX_GRAD_NORM": "max_grad_norm",
    "UPDATE_EPOCHS": "update_epochs",
    "GAE_LAMBDA": "gae_lambda",
    "TARGET_KL": "target_kl",
    "POLICY_FREQ": "policy_freq",
    "O_U_NOISE": "O_U_noise",
    "EXPL_NOISE": "expl_noise",
    "MEAN_NOISE": "mean_noise",
    "THETA": "theta",
    "DT": "dt",
    "NUM_ENVS": "num_envs",
    "AGENT_IDS": "agent_ids",
    "LAMBDA": "lamb",
    "REG": "reg",
}


def create_population(
    algo: str,
    observation_space,
    action_space,
    net_config: Optional[Dict[str, Any]] = None,
    INIT_HP: Optional[Dict[str, Any]] = None,
    hp_config=None,
    population_size: Optional[int] = None,
    num_envs: int = 1,
    device=None,
    accelerator=None,
    seed: Optional[int] = None,
    **kwargs,
) -> List:
    """Build a population of agents (parity: utils/utils.py:218)."""
    INIT_HP = dict(INIT_HP or {})
    pop_size = population_size or INIT_HP.get("POP_SIZE", INIT_HP.get("POPULATION_SIZE", 4))
    algo_cls = get_algo_class(algo)

    import inspect

    # named ctor params across the whole MRO (subclasses forward **kwargs to
    # parents with the real named args, e.g. TD3 -> DDPG)
    named = set()
    for cls in algo_cls.__mro__:
        init = cls.__dict__.get("__init__")
        if init is None:
            continue
        for p in inspect.signature(init).parameters.values():
            if p.kind in (p.POSITIONAL_OR_KEYWORD, p.KEYWORD_ONLY):
                named.add(p.name)
    ctor_kwargs: Dict[str, Any] = {}
    for k, v in INIT_HP.items():
        key = _INIT_HP_MAP.get(k)
        # INIT_HP holds trainer-level keys too (PER, NUM_ENVS, N_STEP for the
        # loop) — only forward the ones this algorithm's signature names;
        # explicit **kwargs from the caller still error loudly below
        if key is not None and key in named:
            ctor_kwargs[key] = v
    ctor_kwargs.update(kwargs)
    if "num_envs" in named:
        ctor_kwargs.setdefault("num_envs", num_envs)

    population = []
    rng = np.random.default_rng(seed)
    for idx in range(pop_size):
        population.append(
            algo_cls(
                observation_space,
                action_space,
                index=idx,
                net_config=net_config,
                hp_config=hp_config,
                seed=int(rng.integers(0, 2**31 - 1)),
                **ctor_kwargs,
            )
        )
    return population


def make_vect_envs(
    env_name: Optional[str] = None,
    num_envs: int = 1,
    *,
    make_env: Optional[Any] = None,
    should_async_vector: bool = True,
    prefer_jax: bool = True,
    **env_kwargs,
):
    """Vectorised env factory (parity: utils/utils.py:47).

    Prefers the in-tree pure-JAX env (zero-host-boundary) when the id is known;
    falls back to gymnasium vectorisation otherwise."""
    if make_env is None and prefer_jax and env_name is not None:
        from agilerl_tpu.envs import classic

        if env_name in classic.REGISTRY:
            from agilerl_tpu.envs.core import JaxVecEnv

            return JaxVecEnv(classic.make(env_name), num_envs=num_envs)
    import gymnasium as gym

    if make_env is not None:
        fns = [make_env for _ in range(num_envs)]
    else:
        fns = [lambda: gym.make(env_name, **env_kwargs) for _ in range(num_envs)]
    cls = gym.vector.AsyncVectorEnv if should_async_vector else gym.vector.SyncVectorEnv
    return cls(fns)


def make_multi_agent_vect_envs(
    env,
    num_envs: int = 1,
    should_async_vector: bool = True,
    **env_kwargs,
):
    """Vectorise a PettingZoo parallel env factory (parity: utils/utils.py:82).
    `env` is a callable returning a fresh parallel env."""
    from agilerl_tpu.vector import AsyncPettingZooVecEnv, PettingZooVecEnv

    fns = [lambda: env(**env_kwargs) for _ in range(num_envs)]
    cls = AsyncPettingZooVecEnv if should_async_vector else PettingZooVecEnv
    return cls(fns)


def tournament_selection_and_mutation(
    population: List,
    tournament,
    mutation,
    env_name: Optional[str] = None,
    algo: Optional[str] = None,
    elite_path: Optional[str] = None,
    save_elite: bool = False,
    accelerator=None,
    language_model: bool = False,
) -> List:
    """select -> mutate -> optionally save elite (parity: utils/utils.py:706)."""
    elite, population = tournament.select(population)
    population = mutation.mutation(population)
    if save_elite and elite_path is not None:
        path = Path(elite_path)
        if path.suffix == "":
            path = path / f"{algo or elite.algo}_elite.ckpt"
        elite.save_checkpoint(path)
    return population


def save_population_checkpoint(
    population: List, save_path: str, overwrite_checkpoints: bool = True, accelerator=None
) -> None:
    """Checkpoint every member (parity: utils/utils.py:656).
    overwrite_checkpoints=False keeps per-step history by appending the
    member's current step count to the filename."""
    for agent in population:
        p = Path(save_path)
        stem = f"{p.stem}_{agent.index}"
        if not overwrite_checkpoints:
            stem = f"{stem}_step{agent.steps[-1]}"
        path = p.parent / f"{stem}{p.suffix or '.ckpt'}"
        agent.save_checkpoint(path)


def resume_population_from_checkpoint(pop: List, checkpoint_path: Optional[str]) -> List:
    """Restore each member in place from its `{stem}_{index}` checkpoint file
    if one exists (parity: the reference trainers' wandb-resume restore path,
    agilerl/training/train_off_policy.py resume branch). Members without a file
    (e.g. population grew) keep their fresh initialisation."""
    if checkpoint_path is None:
        return pop
    for agent in pop:
        p = Path(checkpoint_path)
        f = p.parent / f"{p.stem}_{agent.index}{p.suffix or '.ckpt'}"
        if f.exists():
            agent.load_checkpoint(f)
    return pop


def load_population_checkpoint(algo: str, save_path: str, indices: List[int], **kwargs) -> List:
    cls = get_algo_class(algo)
    pop = []
    for idx in indices:
        p = Path(save_path)
        path = p.parent / f"{p.stem}_{idx}{p.suffix or '.ckpt'}"
        pop.append(cls.load(path))
    return pop


def print_hyperparams(population: List) -> None:
    """Log per-agent HPs + fitness (parity: utils/utils.py:924)."""
    for agent in population:
        hps = {name: getattr(agent, name) for name in agent.hp_config.names()}
        fit = np.mean(agent.fitness[-5:]) if agent.fitness else float("nan")
        print(
            f"Agent {agent.index}: fitness(5)={fit:.2f} mut={agent.mut} "
            f"steps={agent.steps[-1]} {hps}"
        )


def plot_population_score(pop, path: Optional[str] = None):
    """Plot per-agent fitness curves (parity: utils/utils.py:945). Gated on
    matplotlib availability."""
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:  # pragma: no cover
        return None
    fig, ax = plt.subplots()
    for agent in pop:
        ax.plot(agent.fitness, label=f"agent {agent.index}")
    ax.set_xlabel("evaluation")
    ax.set_ylabel("fitness")
    ax.legend()
    if path:
        fig.savefig(path)
    return fig


def aggregate_metrics_across_hosts(value: float) -> float:
    """Mean-reduce a host scalar across processes (parity: utils/utils.py:1004
    aggregate_metrics_across_gpus — torch.distributed gather becomes a psum over
    the pod when running multi-host)."""
    if jax.process_count() == 1:
        return float(value)
    from jax.experimental import multihost_utils

    arr = multihost_utils.process_allgather(np.asarray([value]))
    return float(np.mean(arr))


def default_progress_bar(total: int, desc: str = ""):
    try:
        from tqdm import trange

        return trange(total, desc=desc)
    except ImportError:  # pragma: no cover
        return range(total)


def init_wandb(project: str = "agilerl-tpu", config: Optional[dict] = None, **kwargs):
    """W&B is optional in this image; no-op fallback (parity: utils.py:799)."""
    try:
        import wandb

        wandb.init(project=project, config=config, **kwargs)
        return wandb
    except Exception:
        return None
