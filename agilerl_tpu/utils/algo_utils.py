"""Algorithm utilities (parity: agilerl/utils/algo_utils.py — observation
preprocessing :889 lives in utils/spaces.py; module/checkpoint helpers :525 live
in algorithms/core/base.py; the dataclasses below mirror the config objects
:1406-1443).

VLLMConfig has no analogue by design: generation is the in-tree jitted decode
loop, configured by GenerationConfig instead (no engine, no tensor-parallel
subgroups, no sleep mode — SURVEY.md §2.8 TP row).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from agilerl_tpu.algorithms.core.optimizer import CosineLRScheduleConfig  # noqa: F401
from agilerl_tpu.utils.spaces import (  # noqa: F401
    action_dim,
    obs_dim,
    preprocess_observation,
)


@dataclasses.dataclass
class GenerationConfig:
    """Decode-loop settings for LLM algorithms (replaces VLLMConfig,
    algo_utils.py:1406)."""

    max_new_tokens: int = 64
    temperature: float = 0.9
    top_k: Optional[int] = None
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0


def chkpt_attribute_to_device(chkpt: dict, device=None) -> dict:
    """Move checkpoint arrays onto device (parity: algo_utils chkpt helpers)."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda x: jnp.asarray(x) if hasattr(x, "shape") else x, chkpt
    )


def key_in_nested_dict(d: dict, key: str) -> bool:
    """Recursive key search (parity: algo_utils.py key_in_nested_dict)."""
    if key in d:
        return True
    return any(
        isinstance(v, dict) and key_in_nested_dict(v, key) for v in d.values()
    )
