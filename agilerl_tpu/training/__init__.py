from agilerl_tpu.training.launch import (
    PodLauncher,
    driver_role,
    idle_role,
    launch_flywheel,
    learner_role,
    read_loss_stream,
    rollout_role,
)
from agilerl_tpu.training.train_bandits import train_bandits
from agilerl_tpu.training.train_elastic import train_elastic_pbt
from agilerl_tpu.training.train_llm_online import finetune_llm_reasoning_online
from agilerl_tpu.training.train_multi_agent_off_policy import train_multi_agent_off_policy
from agilerl_tpu.training.train_multi_agent_on_policy import train_multi_agent_on_policy
from agilerl_tpu.training.train_off_policy import train_off_policy
from agilerl_tpu.training.train_offline import train_offline
from agilerl_tpu.training.train_on_policy import train_on_policy

__all__ = [
    "PodLauncher", "launch_flywheel", "read_loss_stream",
    "rollout_role", "learner_role", "driver_role", "idle_role",
    "train_off_policy",
    "train_on_policy",
    "train_offline",
    "train_bandits",
    "train_elastic_pbt",
    "finetune_llm_reasoning_online",
    "train_multi_agent_off_policy",
    "train_multi_agent_on_policy",
]
