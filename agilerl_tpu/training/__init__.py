from agilerl_tpu.training.train_bandits import train_bandits
from agilerl_tpu.training.train_elastic import train_elastic_pbt
from agilerl_tpu.training.train_llm_online import finetune_llm_reasoning_online
from agilerl_tpu.training.train_multi_agent_off_policy import train_multi_agent_off_policy
from agilerl_tpu.training.train_multi_agent_on_policy import train_multi_agent_on_policy
from agilerl_tpu.training.train_off_policy import train_off_policy
from agilerl_tpu.training.train_offline import train_offline
from agilerl_tpu.training.train_on_policy import train_on_policy

__all__ = [
    "train_off_policy",
    "train_on_policy",
    "train_offline",
    "train_bandits",
    "train_elastic_pbt",
    "finetune_llm_reasoning_online",
    "train_multi_agent_off_policy",
    "train_multi_agent_on_policy",
]
