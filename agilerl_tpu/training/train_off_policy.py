"""Evolutionary off-policy training loop (parity: agilerl/training/train_off_policy.py
— train_off_policy:41: per-agent env stepping, n-step/PER buffer variants
:340-429, learn cadence, fitness eval, tournament+mutation, fps tracking :439,
wandb + checkpointing; the Accelerate DataLoader path :213 is replaced by
device-resident buffers).

Host↔device pipelining (docs/performance.md): the hot loop stages
transitions on host and coalesces them into one batched buffer dispatch per
``flush_every`` steps; learning goes through each algorithm's fused
``learn_from_buffer`` jit (sample + learn + PER priority write-back in ONE
dispatch) whose loss stays a device array so JAX async dispatch overlaps it
with the next host ``env.step``; warmup gates read the buffers'
host-mirrored size counters. The loop syncs on the learn stream only at
eval/telemetry cadence. Net effect: ≤2 device dispatches per env step
(action + amortised flush/learn) instead of 3–5 blocking ones.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from agilerl_tpu.components.sampler import Sampler
from agilerl_tpu.observability import init_run_telemetry
from agilerl_tpu.resilience import max_fitness
from agilerl_tpu.utils.utils import (
    print_hyperparams,
    resume_population_from_checkpoint,
    save_population_checkpoint,
    tournament_selection_and_mutation,
)


def merge_final_obs(next_obs, final_obs, done):
    """Bootstrap-target obs: ``final_obs`` only where done, else ``next_obs``.

    gymnasium SAME_STEP autoreset envs provide ``final_observation`` as an
    object array with None entries for non-done envs (advisor finding) —
    substituting it wholesale would corrupt non-done rows. JaxVecEnv returns a
    dense array with final_obs == next_obs when not done, so the merge is a
    no-op there.
    """
    if final_obs is None:
        return next_obs
    done = np.atleast_1d(np.asarray(done)).astype(bool)
    if isinstance(final_obs, np.ndarray) and final_obs.dtype == object:
        # gymnasium object array: one entry per env, None where not done
        if isinstance(next_obs, dict):
            out = {k: np.array(v, copy=True) for k, v in next_obs.items()}
            for i, f in enumerate(final_obs):
                if f is not None and done[i]:
                    for k in out:
                        out[k][i] = np.asarray(f[k])
            return out
        out = np.array(next_obs, copy=True)
        for i, f in enumerate(final_obs):
            if f is not None and done[i]:
                out[i] = np.asarray(f)
        return out

    def merge(n, f):
        n, f = np.asarray(n), np.asarray(f)
        if f.shape != n.shape:
            return n
        d = done.reshape(done.shape + (1,) * max(n.ndim - done.ndim, 0))
        return np.where(d, f, n)

    return jax.tree_util.tree_map(merge, next_obs, final_obs)


def _substitute_rows(transition, prev_transition, mask):
    """Replace rows of `transition` where `mask` is set with the corresponding
    rows of `prev_transition` (obs leaves may be pytrees)."""

    def sub(tv, pv):
        tv, pv = np.asarray(tv), np.asarray(pv)
        if tv.ndim == 0:
            return pv if mask[0] else tv
        m = mask.reshape(mask.shape + (1,) * (tv.ndim - mask.ndim))
        return np.where(m, pv, tv)

    return jax.tree_util.tree_map(sub, transition, prev_transition)


def train_off_policy(
    env,
    env_name: str,
    algo: str,
    pop: List,
    memory,
    INIT_HP: Optional[Dict] = None,
    MUT_P: Optional[Dict] = None,
    swap_channels: bool = False,
    max_steps: int = 50_000,
    evo_steps: int = 5_000,
    eval_steps: Optional[int] = None,
    eval_loop: int = 1,
    learning_delay: int = 0,
    eps_start: float = 1.0,
    eps_end: float = 0.1,
    eps_decay: float = 0.995,
    target: Optional[float] = None,
    n_step: bool = False,
    per: bool = False,
    n_step_memory=None,
    tournament=None,
    mutation=None,
    checkpoint: Optional[int] = None,
    checkpoint_path: Optional[str] = None,
    overwrite_checkpoints: bool = False,
    save_elite: bool = False,
    elite_path: Optional[str] = None,
    wb: bool = False,
    verbose: bool = True,
    accelerator=None,
    wandb_api_key: Optional[str] = None,
    resume: bool = False,
    telemetry=None,
    seed: Optional[int] = None,
    flush_every: Optional[int] = None,
    resilience=None,
) -> Tuple[List, List[List[float]]]:
    # resilience= supersedes the ad-hoc checkpoint/checkpoint_path plumbing:
    # whole-run crash-consistent snapshots (population + buffers + RNG +
    # counters + lineage) with preemption-aware final saves. The legacy path
    # below is kept for plain weight checkpoints.
    if resume and resilience is None:
        resume_population_from_checkpoint(pop, checkpoint_path)
    telem = init_run_telemetry(wb=wb, config=INIT_HP, telemetry=telemetry)
    telem.attach_evolution(tournament, mutation)
    # thread the run seed into the buffers' sampling PRNGs so runs are
    # reproducible end to end (the buffers otherwise self-seed from global
    # numpy randomness)
    if seed is not None:
        if hasattr(memory, "seed"):
            memory.seed(seed)
        if n_step_memory is not None and hasattr(n_step_memory, "seed"):
            n_step_memory.seed(seed + 1)
    # chunked ingestion: coalesce up to flush_every host steps into one
    # buffer dispatch (sampling always flushes first, so cadence only
    # bounds staleness, never correctness)
    use_staging = hasattr(memory, "stage") and (
        not (n_step and n_step_memory is not None)
        or hasattr(n_step_memory, "stage")
    )
    for buf in (memory, n_step_memory):
        if buf is None or not hasattr(buf, "flush_every"):
            continue
        if flush_every is not None:
            buf.flush_every = max(int(flush_every), 1)
        elif not getattr(buf, "_flush_every_user_set", False):
            buf.flush_every = 8  # pipelining default for untouched buffers
    sampler = Sampler(
        memory=memory, per=per,
        n_step_memory=n_step_memory if n_step else None,
    )
    num_envs = getattr(env, "num_envs", 1)
    epsilon = eps_start
    pop_fitnesses: List[List[float]] = [[] for _ in pop]
    total_steps = 0
    checkpoint_count = 0

    def _counters():
        return {"total_steps": total_steps, "checkpoint_count": checkpoint_count,
                "epsilon": epsilon, "pop_fitnesses": pop_fitnesses}

    try:
        if resilience is not None:
            resilience.attach(
                pop=pop, memory=memory,
                n_step_memory=n_step_memory if n_step else None,
                tournament=tournament, mutation=mutation,
                telemetry=telem, env=env,
            )
            if resume:
                restored = resilience.resume(_counters())
                total_steps = int(restored["total_steps"])
                checkpoint_count = int(restored["checkpoint_count"])
                epsilon = float(restored["epsilon"])
                pop_fitnesses = [list(f) for f in restored["pop_fitnesses"]]
        start = time.time()

        # gymnasium >=1.0 vector envs autoreset on the NEXT step: the post-done
        # step ignores the action and returns (reset_obs, reward 0) — such rows
        # must not enter the replay buffer. JaxVecEnv autoresets same-step, so
        # every row is valid there.
        next_step_autoreset = "NEXT_STEP" in str(getattr(env, "autoreset_mode", ""))

        while np.min([agent.steps[-1] for agent in pop]) < max_steps:
            sync_wait_total = 0.0
            for agent in pop:
                if resilience is not None and resilience.abort_generation:
                    break
                obs, info = env.reset()
                prev_done = np.zeros(num_envs, dtype=bool)
                prev_transition = None
                if n_step and n_step_memory is not None:
                    # folds must not span the reset / the previous agent's steps
                    # (reset_horizon folds any staged pre-reset steps first)
                    n_step_memory.reset_horizon()
                # fused sample+learn path: one jit dispatch per learn step, loss
                # kept on device (sync-free). PER requires the algorithm to
                # write priorities back in-dispatch.
                use_fused = (
                    hasattr(agent, "learn_from_buffer")
                    and (not per or getattr(agent, "supports_fused_per", False))
                    # custom user memories without device ring state fall back
                    # to the legacy sample→learn path
                    and hasattr(memory, "per_state" if per else "state")
                )
                pending_loss = None
                scores = np.zeros(num_envs)
                completed_scores: List[float] = []
                steps = 0
                learn_every = max(agent.learn_step, 1)
                for _ in range(max(evo_steps // num_envs, 1)):
                    # masked envs publish per-step action masks on the info dict
                    # (parity: train_off_policy.py:268)
                    action_mask = info.get("action_mask") if isinstance(info, dict) else None
                    t_act = time.perf_counter()
                    action = agent.get_action(obs, epsilon=epsilon, action_mask=action_mask)
                    t_host = time.perf_counter()
                    next_obs, reward, terminated, truncated, info = env.step(np.asarray(action))
                    done = np.logical_or(terminated, truncated)
                    # bootstrap target must see the TRUE successor state, not the
                    # autoreset obs (review finding; gymnasium final_observation);
                    # merged per-env — final_obs applies only where done
                    final = (
                        info.get("final_obs", info.get("final_observation"))
                        if isinstance(info, dict) else None
                    )
                    store_next = merge_final_obs(next_obs, final, done)
                    scores += np.asarray(reward)
                    for i, d in enumerate(np.atleast_1d(done)):
                        if d:
                            completed_scores.append(float(np.atleast_1d(scores)[i]))
                            scores[i] = 0.0

                    transition = {
                        "obs": obs,
                        "action": action,
                        "reward": np.asarray(reward, np.float32),
                        "next_obs": store_next,
                        "done": np.asarray(terminated, np.float32),
                    }
                    if n_step and n_step_memory is not None:
                        # fused n-step goes into n_step_memory's own ring; the
                        # OLDEST raw transitions displaced by the fold go into
                        # the main buffer so both rings stay index-aligned
                        # (parity: reference's paired-buffer scheme,
                        # train_off_policy.py:340). _boundary stops folds at
                        # truncations/autoresets.
                        transition["_boundary"] = np.asarray(done, np.float32)
                        if next_step_autoreset and prev_done.any() and prev_transition:
                            # gymnasium NEXT_STEP autoreset: this row is a bogus
                            # filler (obs = old terminal obs, ignored action, done
                            # False — training on it would bootstrap the old
                            # terminal obs into the NEW episode). Substitute the
                            # env's previous (real, episode-ending) row: a benign
                            # duplicate whose _boundary=1 keeps folds frozen, and
                            # paired-buffer indices stay aligned (advisor finding).
                            transition = _substitute_rows(
                                transition, prev_transition, prev_done
                            )
                        prev_transition = transition
                        if use_staging:
                            n_step_memory.stage(transition, batched=num_envs > 1)
                        else:
                            one_step = n_step_memory.add(transition, batched=num_envs > 1)
                            if one_step is not None:
                                memory.add(one_step, batched=num_envs > 1)
                    elif next_step_autoreset and prev_done.any():
                        keep = np.where(~prev_done)[0]
                        if keep.size:
                            kept = jax.tree_util.tree_map(
                                lambda v: np.asarray(v)[keep], transition
                            )
                            if use_staging:
                                memory.stage(kept, batched=True)
                            else:
                                memory.add(kept, batched=True)
                    elif use_staging:
                        memory.stage(transition, batched=num_envs > 1)
                    else:
                        memory.add(transition, batched=num_envs > 1)
                    prev_done = np.atleast_1d(done).astype(bool)

                    obs = next_obs
                    steps += num_envs
                    total_steps += num_envs
                    epsilon = max(eps_end, epsilon * eps_decay)

                    learn_block_s = 0.0
                    if steps % learn_every < num_envs:
                        # drain staging so warmup gating sees every stored row
                        # (host-mirrored counters — no device sync here)
                        sampler.flush()
                        if (
                            len(memory) >= agent.batch_size
                            and len(memory) >= learning_delay
                        ):
                            if use_fused:
                                # ONE dispatch: sample + learn (+ PER priority
                                # write-back), issued WITHOUT blocking — the
                                # device chews on it while the host steps the env
                                pending_loss = agent.learn_from_buffer(
                                    memory,
                                    n_step_memory if n_step else None,
                                )
                            elif per:
                                t_learn = time.perf_counter()
                                # same IS-weight beta as the fused path would
                                # use (agent-defined, else the 0.4 default)
                                sampled = sampler.sample(
                                    agent.batch_size,
                                    beta=getattr(agent, "beta", None),
                                )
                                idxs = sampled[1]
                                result = agent.learn(sampled)
                                new_priorities = (
                                    result[1] if isinstance(result, tuple) else None
                                )
                                if new_priorities is not None:
                                    memory.update_priorities(idxs, new_priorities)
                                learn_block_s = time.perf_counter() - t_learn
                            else:
                                t_learn = time.perf_counter()
                                agent.learn(sampler.sample(agent.batch_size))
                                learn_block_s = time.perf_counter() - t_learn
                    # legacy learn blocks on the device (float(loss) etc.), so
                    # its time counts as device wait, not host work — otherwise
                    # an unpipelined run would report overlap near 1
                    telem.step(
                        env_steps=num_envs, agent_index=agent.index,
                        host_time_s=(time.perf_counter() - t_host) - learn_block_s,
                        device_time_s=(t_host - t_act) + learn_block_s,
                    )
                    if resilience is not None and resilience.abort_generation:
                        break  # final snapshot happens at the boundary below

                # segment sync point (eval/telemetry cadence): drain staging and
                # wait for the learn stream — the ONLY place the hot path blocks
                # on the device outside action selection
                sampler.flush()
                t_sync = time.perf_counter()
                if pending_loss is not None:
                    jax.block_until_ready(pending_loss)
                sync_wait_total += time.perf_counter() - t_sync
                agent.steps[-1] += steps
                mean_score = float(np.mean(completed_scores)) if completed_scores else float(np.mean(scores))
                agent.scores.append(mean_score)

            if resilience is not None and resilience.abort_generation:
                # on_preempt="now": final snapshot mid-generation, skip the
                # (expensive) eval + evolution, exit cleanly. Under
                # "finish_generation" this stays False and the boundary
                # step_boundary below takes the final snapshot instead.
                resilience.step_boundary(total_steps, _counters(), pop=pop)
                break

            # evaluation + evolution
            fitnesses = [
                agent.test(env, swap_channels=swap_channels, max_steps=eval_steps, loop=eval_loop)
                for agent in pop
            ]
            for i, f in enumerate(fitnesses):
                pop_fitnesses[i].append(f)
            telem.record_eval(pop, fitnesses)
            telem.log_step(
                {"global_step": total_steps, "fps": total_steps / (time.time() - start),
                 "eval/mean_fitness": float(np.mean(fitnesses)),
                 # how long the generation spent blocked waiting for the learn
                 # stream at its sync points — the pipelining win shrinks this
                 "pipeline/sync_wait_s": round(sync_wait_total, 6)}
            )
            if verbose:
                fps = total_steps / (time.time() - start)
                print(
                    f"--- steps {total_steps} fps {fps:.0f} eps {epsilon:.3f} "
                    f"fitness {[f'{f:.1f}' for f in fitnesses]}"
                )
                print_hyperparams(pop)

            if tournament is not None and mutation is not None:
                pop = tournament_selection_and_mutation(
                    pop, tournament, mutation, env_name=env_name, algo=algo,
                    elite_path=elite_path, save_elite=save_elite,
                )

            for agent in pop:
                agent.steps.append(agent.steps[-1])

            if resilience is not None:
                # the crash-consistent step boundary: cadence snapshot when due,
                # final snapshot + clean exit when a preemption was requested
                if resilience.step_boundary(
                    total_steps, _counters(), pop=pop,
                    fitness=max_fitness(fitnesses),
                ):
                    break
            elif checkpoint is not None and checkpoint_path is not None:
                if total_steps // checkpoint > checkpoint_count:
                    save_population_checkpoint(pop, checkpoint_path, overwrite_checkpoints)
                    checkpoint_count = total_steps // checkpoint

            if target is not None and np.min(fitnesses) >= target:
                break

    finally:
        # a crash escaping the loop must not leak the guard's process-wide
        # SIGTERM/SIGINT handlers (or an unflushed telemetry sink) into a
        # driver that catches the exception and keeps running
        if resilience is not None:
            resilience.close()
        if telemetry is None:
            telem.close()
    return pop, pop_fitnesses
