"""Evolutionary off-policy training loop (parity: agilerl/training/train_off_policy.py
— train_off_policy:41: per-agent env stepping, n-step/PER buffer variants
:340-429, learn cadence, fitness eval, tournament+mutation, fps tracking :439,
wandb + checkpointing; the Accelerate DataLoader path :213 is replaced by
device-resident buffers).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from agilerl_tpu.utils.utils import (
    init_wandb,
    print_hyperparams,
    save_population_checkpoint,
    tournament_selection_and_mutation,
)


def train_off_policy(
    env,
    env_name: str,
    algo: str,
    pop: List,
    memory,
    INIT_HP: Optional[Dict] = None,
    MUT_P: Optional[Dict] = None,
    swap_channels: bool = False,
    max_steps: int = 50_000,
    evo_steps: int = 5_000,
    eval_steps: Optional[int] = None,
    eval_loop: int = 1,
    learning_delay: int = 0,
    eps_start: float = 1.0,
    eps_end: float = 0.1,
    eps_decay: float = 0.995,
    target: Optional[float] = None,
    n_step: bool = False,
    per: bool = False,
    n_step_memory=None,
    tournament=None,
    mutation=None,
    checkpoint: Optional[int] = None,
    checkpoint_path: Optional[str] = None,
    overwrite_checkpoints: bool = False,
    save_elite: bool = False,
    elite_path: Optional[str] = None,
    wb: bool = False,
    verbose: bool = True,
    accelerator=None,
    wandb_api_key: Optional[str] = None,
    resume: bool = False,
) -> Tuple[List, List[List[float]]]:
    if resume and checkpoint_path is not None:
        from pathlib import Path as _P

        for agent in pop:
            p = _P(checkpoint_path)
            f = p.parent / f"{p.stem}_{agent.index}{p.suffix or '.ckpt'}"
            if f.exists():
                agent.load_checkpoint(f)
    wandb_run = init_wandb(config=INIT_HP) if wb else None
    num_envs = getattr(env, "num_envs", 1)
    epsilon = eps_start
    pop_fitnesses: List[List[float]] = [[] for _ in pop]
    total_steps = 0
    checkpoint_count = 0
    start = time.time()

    # gymnasium >=1.0 vector envs autoreset on the NEXT step: the post-done
    # step ignores the action and returns (reset_obs, reward 0) — such rows
    # must not enter the replay buffer. JaxVecEnv autoresets same-step, so
    # every row is valid there.
    next_step_autoreset = "NEXT_STEP" in str(getattr(env, "autoreset_mode", ""))

    while np.min([agent.steps[-1] for agent in pop]) < max_steps:
        for agent in pop:
            obs, _ = env.reset()
            prev_done = np.zeros(num_envs, dtype=bool)
            if n_step and n_step_memory is not None:
                # folds must not span the reset / the previous agent's steps
                n_step_memory.reset_horizon()
            scores = np.zeros(num_envs)
            completed_scores: List[float] = []
            steps = 0
            for _ in range(max(evo_steps // num_envs, 1)):
                action = agent.get_action(obs, epsilon=epsilon)
                next_obs, reward, terminated, truncated, info = env.step(np.asarray(action))
                done = np.logical_or(terminated, truncated)
                # bootstrap target must see the TRUE successor state, not the
                # autoreset obs (review finding; gymnasium final_observation)
                store_next = info.get("final_obs", info.get("final_observation", next_obs))                     if isinstance(info, dict) else next_obs
                scores += np.asarray(reward)
                for i, d in enumerate(np.atleast_1d(done)):
                    if d:
                        completed_scores.append(float(np.atleast_1d(scores)[i]))
                        scores[i] = 0.0

                transition = {
                    "obs": obs,
                    "action": action,
                    "reward": np.asarray(reward, np.float32),
                    "next_obs": store_next,
                    "done": np.asarray(terminated, np.float32),
                }
                if n_step and n_step_memory is not None:
                    # fused n-step goes into n_step_memory's own ring; the
                    # returned OLDEST raw transition goes into the main buffer
                    # so both rings stay index-aligned (parity: reference's
                    # paired-buffer scheme, train_off_policy.py:340).
                    # _boundary stops folds at truncations/autoresets.
                    transition["_boundary"] = np.asarray(done, np.float32)
                    one_step = n_step_memory.add(transition, batched=num_envs > 1)
                    if one_step is not None:
                        memory.add(one_step, batched=num_envs > 1)
                elif next_step_autoreset and prev_done.any():
                    keep = np.where(~prev_done)[0]
                    if keep.size:
                        memory.add(
                            {k: np.asarray(v)[keep] for k, v in transition.items()},
                            batched=True,
                        )
                else:
                    memory.add(transition, batched=num_envs > 1)
                prev_done = np.atleast_1d(done).astype(bool)

                obs = next_obs
                steps += num_envs
                total_steps += num_envs
                epsilon = max(eps_end, epsilon * eps_decay)

                if (
                    len(memory) >= agent.batch_size
                    and len(memory) >= learning_delay
                    and steps % max(agent.learn_step, 1) < num_envs
                ):
                    if per:
                        batch, idxs, weights = memory.sample(agent.batch_size)
                        if n_step and n_step_memory is not None:
                            n_batch = n_step_memory.sample_from_indices(idxs)
                            result = agent.learn((batch, idxs, weights, n_batch))
                        else:
                            result = agent.learn((batch, idxs, weights))
                        new_priorities = (
                            result[1] if isinstance(result, tuple) else None
                        )
                        if new_priorities is not None:
                            memory.update_priorities(idxs, new_priorities)
                    else:
                        agent.learn(memory.sample(agent.batch_size))

            agent.steps[-1] += steps
            mean_score = float(np.mean(completed_scores)) if completed_scores else float(np.mean(scores))
            agent.scores.append(mean_score)

        # evaluation + evolution
        fitnesses = [
            agent.test(env, swap_channels=swap_channels, max_steps=eval_steps, loop=eval_loop)
            for agent in pop
        ]
        for i, f in enumerate(fitnesses):
            pop_fitnesses[i].append(f)
        if wandb_run is not None:
            wandb_run.log(
                {"global_step": total_steps, "fps": total_steps / (time.time() - start),
                 "eval/mean_fitness": float(np.mean(fitnesses))}
            )
        if verbose:
            fps = total_steps / (time.time() - start)
            print(
                f"--- steps {total_steps} fps {fps:.0f} eps {epsilon:.3f} "
                f"fitness {[f'{f:.1f}' for f in fitnesses]}"
            )
            print_hyperparams(pop)

        if tournament is not None and mutation is not None:
            pop = tournament_selection_and_mutation(
                pop, tournament, mutation, env_name=env_name, algo=algo,
                elite_path=elite_path, save_elite=save_elite,
            )

        for agent in pop:
            agent.steps.append(agent.steps[-1])

        if checkpoint is not None and checkpoint_path is not None:
            if total_steps // checkpoint > checkpoint_count:
                save_population_checkpoint(pop, checkpoint_path, overwrite_checkpoints)
                checkpoint_count = total_steps // checkpoint

        if target is not None and np.min(fitnesses) >= target:
            break

    return pop, pop_fitnesses
