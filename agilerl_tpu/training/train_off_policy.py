"""Evolutionary off-policy training loop (parity: agilerl/training/train_off_policy.py
— train_off_policy:41: per-agent env stepping, n-step/PER buffer variants
:340-429, learn cadence, fitness eval, tournament+mutation, fps tracking :439,
wandb + checkpointing; the Accelerate DataLoader path :213 is replaced by
device-resident buffers).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from agilerl_tpu.components.sampler import Sampler
from agilerl_tpu.observability import init_run_telemetry
from agilerl_tpu.utils.utils import (
    print_hyperparams,
    resume_population_from_checkpoint,
    save_population_checkpoint,
    tournament_selection_and_mutation,
)


def merge_final_obs(next_obs, final_obs, done):
    """Bootstrap-target obs: ``final_obs`` only where done, else ``next_obs``.

    gymnasium SAME_STEP autoreset envs provide ``final_observation`` as an
    object array with None entries for non-done envs (advisor finding) —
    substituting it wholesale would corrupt non-done rows. JaxVecEnv returns a
    dense array with final_obs == next_obs when not done, so the merge is a
    no-op there.
    """
    if final_obs is None:
        return next_obs
    done = np.atleast_1d(np.asarray(done)).astype(bool)
    if isinstance(final_obs, np.ndarray) and final_obs.dtype == object:
        # gymnasium object array: one entry per env, None where not done
        if isinstance(next_obs, dict):
            out = {k: np.array(v, copy=True) for k, v in next_obs.items()}
            for i, f in enumerate(final_obs):
                if f is not None and done[i]:
                    for k in out:
                        out[k][i] = np.asarray(f[k])
            return out
        out = np.array(next_obs, copy=True)
        for i, f in enumerate(final_obs):
            if f is not None and done[i]:
                out[i] = np.asarray(f)
        return out

    def merge(n, f):
        n, f = np.asarray(n), np.asarray(f)
        if f.shape != n.shape:
            return n
        d = done.reshape(done.shape + (1,) * max(n.ndim - done.ndim, 0))
        return np.where(d, f, n)

    import jax

    return jax.tree_util.tree_map(merge, next_obs, final_obs)


def _substitute_rows(transition, prev_transition, mask):
    """Replace rows of `transition` where `mask` is set with the corresponding
    rows of `prev_transition` (obs leaves may be pytrees)."""
    import jax

    def sub(tv, pv):
        tv, pv = np.asarray(tv), np.asarray(pv)
        if tv.ndim == 0:
            return pv if mask[0] else tv
        m = mask.reshape(mask.shape + (1,) * (tv.ndim - mask.ndim))
        return np.where(m, pv, tv)

    return jax.tree_util.tree_map(sub, transition, prev_transition)


def train_off_policy(
    env,
    env_name: str,
    algo: str,
    pop: List,
    memory,
    INIT_HP: Optional[Dict] = None,
    MUT_P: Optional[Dict] = None,
    swap_channels: bool = False,
    max_steps: int = 50_000,
    evo_steps: int = 5_000,
    eval_steps: Optional[int] = None,
    eval_loop: int = 1,
    learning_delay: int = 0,
    eps_start: float = 1.0,
    eps_end: float = 0.1,
    eps_decay: float = 0.995,
    target: Optional[float] = None,
    n_step: bool = False,
    per: bool = False,
    n_step_memory=None,
    tournament=None,
    mutation=None,
    checkpoint: Optional[int] = None,
    checkpoint_path: Optional[str] = None,
    overwrite_checkpoints: bool = False,
    save_elite: bool = False,
    elite_path: Optional[str] = None,
    wb: bool = False,
    verbose: bool = True,
    accelerator=None,
    wandb_api_key: Optional[str] = None,
    resume: bool = False,
    telemetry=None,
) -> Tuple[List, List[List[float]]]:
    if resume:
        resume_population_from_checkpoint(pop, checkpoint_path)
    telem = init_run_telemetry(wb=wb, config=INIT_HP, telemetry=telemetry)
    telem.attach_evolution(tournament, mutation)
    sampler = Sampler(
        memory=memory, per=per,
        n_step_memory=n_step_memory if n_step else None,
    )
    num_envs = getattr(env, "num_envs", 1)
    epsilon = eps_start
    pop_fitnesses: List[List[float]] = [[] for _ in pop]
    total_steps = 0
    checkpoint_count = 0
    start = time.time()

    # gymnasium >=1.0 vector envs autoreset on the NEXT step: the post-done
    # step ignores the action and returns (reset_obs, reward 0) — such rows
    # must not enter the replay buffer. JaxVecEnv autoresets same-step, so
    # every row is valid there.
    next_step_autoreset = "NEXT_STEP" in str(getattr(env, "autoreset_mode", ""))

    while np.min([agent.steps[-1] for agent in pop]) < max_steps:
        for agent in pop:
            obs, info = env.reset()
            prev_done = np.zeros(num_envs, dtype=bool)
            prev_transition = None
            if n_step and n_step_memory is not None:
                # folds must not span the reset / the previous agent's steps
                n_step_memory.reset_horizon()
            scores = np.zeros(num_envs)
            completed_scores: List[float] = []
            steps = 0
            for _ in range(max(evo_steps // num_envs, 1)):
                # masked envs publish per-step action masks on the info dict
                # (parity: train_off_policy.py:268)
                action_mask = info.get("action_mask") if isinstance(info, dict) else None
                action = agent.get_action(obs, epsilon=epsilon, action_mask=action_mask)
                next_obs, reward, terminated, truncated, info = env.step(np.asarray(action))
                done = np.logical_or(terminated, truncated)
                # bootstrap target must see the TRUE successor state, not the
                # autoreset obs (review finding; gymnasium final_observation);
                # merged per-env — final_obs applies only where done
                final = (
                    info.get("final_obs", info.get("final_observation"))
                    if isinstance(info, dict) else None
                )
                store_next = merge_final_obs(next_obs, final, done)
                scores += np.asarray(reward)
                for i, d in enumerate(np.atleast_1d(done)):
                    if d:
                        completed_scores.append(float(np.atleast_1d(scores)[i]))
                        scores[i] = 0.0

                transition = {
                    "obs": obs,
                    "action": action,
                    "reward": np.asarray(reward, np.float32),
                    "next_obs": store_next,
                    "done": np.asarray(terminated, np.float32),
                }
                if n_step and n_step_memory is not None:
                    # fused n-step goes into n_step_memory's own ring; the
                    # returned OLDEST raw transition goes into the main buffer
                    # so both rings stay index-aligned (parity: reference's
                    # paired-buffer scheme, train_off_policy.py:340).
                    # _boundary stops folds at truncations/autoresets.
                    transition["_boundary"] = np.asarray(done, np.float32)
                    if next_step_autoreset and prev_done.any() and prev_transition:
                        # gymnasium NEXT_STEP autoreset: this row is a bogus
                        # filler (obs = old terminal obs, ignored action, done
                        # False — training on it would bootstrap the old
                        # terminal obs into the NEW episode). Substitute the
                        # env's previous (real, episode-ending) row: a benign
                        # duplicate whose _boundary=1 keeps folds frozen, and
                        # paired-buffer indices stay aligned (advisor finding).
                        transition = _substitute_rows(
                            transition, prev_transition, prev_done
                        )
                    prev_transition = transition
                    one_step = n_step_memory.add(transition, batched=num_envs > 1)
                    if one_step is not None:
                        memory.add(one_step, batched=num_envs > 1)
                elif next_step_autoreset and prev_done.any():
                    keep = np.where(~prev_done)[0]
                    if keep.size:
                        import jax as _jax

                        memory.add(
                            _jax.tree_util.tree_map(
                                lambda v: np.asarray(v)[keep], transition
                            ),
                            batched=True,
                        )
                else:
                    memory.add(transition, batched=num_envs > 1)
                prev_done = np.atleast_1d(done).astype(bool)

                obs = next_obs
                steps += num_envs
                total_steps += num_envs
                epsilon = max(eps_end, epsilon * eps_decay)
                telem.step(env_steps=num_envs, agent_index=agent.index)

                if (
                    len(memory) >= agent.batch_size
                    and len(memory) >= learning_delay
                    and steps % max(agent.learn_step, 1) < num_envs
                ):
                    if per:
                        sampled = sampler.sample(agent.batch_size)
                        idxs = sampled[1]
                        result = agent.learn(sampled)
                        new_priorities = (
                            result[1] if isinstance(result, tuple) else None
                        )
                        if new_priorities is not None:
                            memory.update_priorities(idxs, new_priorities)
                    else:
                        agent.learn(sampler.sample(agent.batch_size))

            agent.steps[-1] += steps
            mean_score = float(np.mean(completed_scores)) if completed_scores else float(np.mean(scores))
            agent.scores.append(mean_score)

        # evaluation + evolution
        fitnesses = [
            agent.test(env, swap_channels=swap_channels, max_steps=eval_steps, loop=eval_loop)
            for agent in pop
        ]
        for i, f in enumerate(fitnesses):
            pop_fitnesses[i].append(f)
        telem.record_eval(pop, fitnesses)
        telem.log_step(
            {"global_step": total_steps, "fps": total_steps / (time.time() - start),
             "eval/mean_fitness": float(np.mean(fitnesses))}
        )
        if verbose:
            fps = total_steps / (time.time() - start)
            print(
                f"--- steps {total_steps} fps {fps:.0f} eps {epsilon:.3f} "
                f"fitness {[f'{f:.1f}' for f in fitnesses]}"
            )
            print_hyperparams(pop)

        if tournament is not None and mutation is not None:
            pop = tournament_selection_and_mutation(
                pop, tournament, mutation, env_name=env_name, algo=algo,
                elite_path=elite_path, save_elite=save_elite,
            )

        for agent in pop:
            agent.steps.append(agent.steps[-1])

        if checkpoint is not None and checkpoint_path is not None:
            if total_steps // checkpoint > checkpoint_count:
                save_population_checkpoint(pop, checkpoint_path, overwrite_checkpoints)
                checkpoint_count = total_steps // checkpoint

        if target is not None and np.min(fitnesses) >= target:
            break

    if telemetry is None:
        telem.close()
    return pop, pop_fitnesses
