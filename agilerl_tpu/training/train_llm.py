"""LLM finetuning loops (parity: agilerl/training/train_llm.py —
finetune_llm_reasoning:25 (GRPO over ReasoningGym; asserts arch/param/act
mutation probs are 0 for LLMs :97-109), finetune_llm_preference:417 (DPO over
PreferenceGym); per-epoch reference refresh; rank-0-decides evolution becomes
replicated deterministic RNG — every host seeds the same tournament so no
object broadcast is needed).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from agilerl_tpu.observability import init_run_telemetry
from agilerl_tpu.resilience import max_fitness
from agilerl_tpu.utils.utils import (
    print_hyperparams,
    resume_population_from_checkpoint,
    save_population_checkpoint,
    tournament_selection_and_mutation,
)


def _assert_llm_mutations(mutation) -> None:
    """LLMs only mutate RL hyperparameters (parity: train_llm.py:97-109)."""
    if mutation is None:
        return
    assert mutation.architecture_mut == 0, "architecture mutation must be 0 for LLMs"
    assert mutation.parameters_mut == 0, "parameter mutation must be 0 for LLMs"
    assert mutation.activation_mut == 0, "activation mutation must be 0 for LLMs"


def finetune_llm_reasoning(
    pop: List,
    env,
    INIT_HP: Optional[Dict] = None,
    max_reward: Optional[float] = None,
    wb: bool = False,
    evaluation_interval: int = 10,
    verbose: bool = True,
    accelerator=None,
    checkpoint_interval: Optional[int] = None,
    checkpoint_path: Optional[str] = None,
    overwrite_checkpoints: bool = False,
    max_steps: int = 200,
    evo_steps: Optional[int] = None,
    tournament=None,
    mutation=None,
    wandb_api_key: Optional[str] = None,
    save_elite: bool = False,
    elite_path: Optional[str] = None,
    resume: bool = False,
    telemetry=None,
    resilience=None,
) -> Tuple[List, List[List[float]]]:
    """GRPO reasoning finetune (parity: train_llm.py:25)."""
    _assert_llm_mutations(mutation)
    if resume and resilience is None:
        resume_population_from_checkpoint(pop, checkpoint_path)
    telem = init_run_telemetry(wb=wb, config=INIT_HP, telemetry=telemetry)
    telem.attach_evolution(tournament, mutation)
    if telem.timeline.model_config is None:
        # bind the population's transformer config so the timeline can emit
        # MFU (tokens/step vs the chip's bf16 peak) alongside step_time_s
        telem.timeline.set_model_config(getattr(pop[0], "model_config", None))
    pop_fitnesses: List[List[float]] = [[] for _ in pop]
    done_steps = 0
    # cross-step loop state: each env.step returns the NEXT batch, carried
    # via `prompts = next_prompts` below — so it belongs to the snapshot
    # (a resumed run that re-reset the env would draw a fresh batch and
    # diverge from the uninterrupted stream)
    prompts = None

    def _counters():
        return {"done_steps": done_steps, "pop_fitnesses": pop_fitnesses,
                "prompts": prompts}

    try:
        if resilience is not None:
            resilience.attach(pop=pop, tournament=tournament, mutation=mutation,
                              telemetry=telem, env=env)
            if resume:
                restored = resilience.resume(_counters())
                done_steps = int(restored["done_steps"])
                pop_fitnesses = [list(f) for f in restored["pop_fitnesses"]]
                prompts = restored.get("prompts")
        start = time.time()

        if prompts is None:
            prompts = env.reset()
        for step in range(done_steps + 1, max_steps + 1):
            for agent in pop:
                agent.set_reference_policy(env.num_epochs)
                completions, completion_mask = agent.get_action(prompts)
                ids, action_masks = env.assemble_learn_batch(completions, completion_mask)
                next_prompts, rewards = env.step(completions, completion_mask)
                loss, kl = agent.learn((ids, action_masks, rewards))
                agent.steps[-1] += int(np.asarray(rewards).size)
                if verbose:
                    print(
                        f"[{step}] agent {agent.index} loss {loss:.4f} "
                        f"reward {np.mean(rewards):.3f}"
                    )
                telem.log_step({
                    "train/loss": loss, "train/mean_reward": float(np.mean(rewards)),
                    "agent": agent.index,
                })
                telem.step(tokens=int(np.asarray(ids).size), agent_index=agent.index,
                           metrics={"loss": float(loss)})
                prompts = next_prompts

            if step % evaluation_interval == 0:
                fitnesses = [agent.test(env) for agent in pop]
                for i, f in enumerate(fitnesses):
                    pop_fitnesses[i].append(f)
                if verbose:
                    print(f"=== eval @ {step}: {[f'{f:.3f}' for f in fitnesses]}")
                    print_hyperparams(pop)
                telem.record_eval(pop, fitnesses)
                telem.log_step({"eval/mean_fitness": float(np.mean(fitnesses))})
                if tournament is not None and mutation is not None:
                    pop = tournament_selection_and_mutation(
                        pop, tournament, mutation, language_model=True,
                        elite_path=elite_path, save_elite=save_elite,
                    )
                # stop AFTER the checkpoint block below so the state that
                # reached the target is the state on disk (review finding)
                stop = max_reward is not None and np.max(fitnesses) >= max_reward
                last_fitness = max_fitness(fitnesses)
            else:
                stop = False
                last_fitness = None
            done_steps = step
            if resilience is not None:
                if resilience.step_boundary(
                    step, _counters(), pop=pop, fitness=last_fitness,
                ):
                    break
                if stop:
                    # the state that reached the target must be the state on
                    # disk (same contract as the legacy stop-checkpoint below)
                    resilience.snapshot(step, _counters(), kind="final",
                                        fitness=last_fitness)
            elif checkpoint_interval is not None and checkpoint_path is not None:
                if stop or step % checkpoint_interval == 0:
                    save_population_checkpoint(pop, checkpoint_path, overwrite_checkpoints)
            if stop:
                break

    finally:
        # a crash escaping the loop must not leak the guard's process-wide
        # SIGTERM/SIGINT handlers (or an unflushed telemetry sink) into a
        # driver that catches the exception and keeps running
        if resilience is not None:
            resilience.close()
        if telemetry is None:
            telem.close()
    return pop, pop_fitnesses


def finetune_llm_preference(
    pop: List,
    env,
    INIT_HP: Optional[Dict] = None,
    max_reward: Optional[float] = None,
    wb: bool = False,
    evaluation_interval: int = 10,
    verbose: bool = True,
    accelerator=None,
    checkpoint_interval: Optional[int] = None,
    checkpoint_path: Optional[str] = None,
    overwrite_checkpoints: bool = False,
    max_steps: int = 200,
    tournament=None,
    mutation=None,
    wandb_api_key: Optional[str] = None,
    save_elite: bool = False,
    elite_path: Optional[str] = None,
    resume: bool = False,
    telemetry=None,
    resilience=None,
) -> Tuple[List, List[List[float]]]:
    """DPO preference finetune (parity: train_llm.py:417)."""
    _assert_llm_mutations(mutation)
    if resume and resilience is None:
        resume_population_from_checkpoint(pop, checkpoint_path)
    telem = init_run_telemetry(wb=wb, config=INIT_HP, telemetry=telemetry)
    telem.attach_evolution(tournament, mutation)
    if telem.timeline.model_config is None:
        telem.timeline.set_model_config(getattr(pop[0], "model_config", None))
    pop_fitnesses: List[List[float]] = [[] for _ in pop]
    done_steps = 0

    def _counters():
        return {"done_steps": done_steps, "pop_fitnesses": pop_fitnesses}

    try:
        if resilience is not None:
            resilience.attach(pop=pop, tournament=tournament, mutation=mutation,
                              telemetry=telem, env=env)
            if resume:
                restored = resilience.resume(_counters())
                done_steps = int(restored["done_steps"])
                pop_fitnesses = [list(f) for f in restored["pop_fitnesses"]]
        for step in range(done_steps + 1, max_steps + 1):
            batch = env.reset()
            for agent in pop:
                agent.set_reference_policy(env.num_epochs)
                loss, acc = agent.learn(batch)
                agent.steps[-1] += len(batch["chosen_ids"])
                if verbose:
                    print(f"[{step}] agent {agent.index} dpo loss {loss:.4f} acc {acc:.3f}")
                telem.log_step({"train/loss": loss, "train/acc": acc, "agent": agent.index})
                telem.step(tokens=int(np.asarray(batch["chosen_ids"]).size),
                           agent_index=agent.index, metrics={"loss": float(loss)})

            if step % evaluation_interval == 0:
                fitnesses = [agent.test(env) for agent in pop]
                for i, f in enumerate(fitnesses):
                    pop_fitnesses[i].append(f)
                if verbose:
                    print(f"=== eval @ {step}: {[f'{f:.3f}' for f in fitnesses]}")
                telem.record_eval(pop, fitnesses)
                telem.log_step({"eval/mean_fitness": float(np.mean(fitnesses))})
                if tournament is not None and mutation is not None:
                    pop = tournament_selection_and_mutation(
                        pop, tournament, mutation, language_model=True,
                        elite_path=elite_path, save_elite=save_elite,
                    )
                stop = max_reward is not None and np.max(fitnesses) >= max_reward
                last_fitness = max_fitness(fitnesses)
            else:
                stop = False
                last_fitness = None
            done_steps = step
            if resilience is not None:
                if resilience.step_boundary(
                    step, _counters(), pop=pop, fitness=last_fitness,
                ):
                    break
                if stop:
                    # the state that reached the target must be the state on
                    # disk (same contract as the legacy stop-checkpoint below)
                    resilience.snapshot(step, _counters(), kind="final",
                                        fitness=last_fitness)
            elif checkpoint_interval is not None and checkpoint_path is not None:
                if stop or step % checkpoint_interval == 0:
                    save_population_checkpoint(pop, checkpoint_path, overwrite_checkpoints)
            if stop:
                break

    finally:
        # a crash escaping the loop must not leak the guard's process-wide
        # SIGTERM/SIGINT handlers (or an unflushed telemetry sink) into a
        # driver that catches the exception and keeps running
        if resilience is not None:
            resilience.close()
        if telemetry is None:
            telem.close()
    return pop, pop_fitnesses
