"""Multi-process pod launcher: the whole stack as real OS processes.

ROADMAP item 1. Every pod-to-pod interaction in the serving/training stack
already flows through commit-dir stores on one filesystem root — weights
(:class:`~agilerl_tpu.llm.flywheel.WeightStore`), trajectories
(:class:`~agilerl_tpu.llm.flywheel.TrajectoryStore`), KV transfers,
telemetry snapshots, compiled executables. This module adds the only
missing piece: spawning the roles as **separate OS processes** and
supervising them, the Podracer/Sebulba deployment shape (decoupled
actor/learner pods on cheap preemptible hosts) and DistServe-style role
disaggregation.

Layers:

- :class:`PodLauncher` — launcher-side composition root: declare roles
  (:meth:`add_role`), :meth:`start` the fleet, :meth:`run` the supervision
  loop (restart crashed roles, honour SIGTERM by draining the whole fleet
  through each child's :class:`~agilerl_tpu.resilience.preemption
  .PreemptionGuard`), :meth:`shutdown` explicitly. Liveness and leadership
  ride :class:`~agilerl_tpu.resilience.membership.HeartbeatStore` leases
  (with the same-host pid probe, so a killed local role surfaces on the
  next poll, not after the lease window).

- Child-side **role entry points** (referenced by spec as
  ``agilerl_tpu.training.launch:<fn>``): :func:`rollout_role` /
  :func:`learner_role` wrap the GRPO flywheel pods in poll-cadence tick
  loops; :func:`driver_role` is the generic adapter for anything exposing
  a step method (``ServingFleet.step``, ``ElasticPBTController``
  generation boundaries); :func:`idle_role` is the trivial role the
  tests/docs drive. Role objects are REBUILT inside the child from
  ``module:function`` entry points — nothing is pickled across the exec
  boundary, and a joining process warm-starts compiled executables from
  the persistent executable store instead of recompiling.

- :func:`launch_flywheel` — convenience composition: one learner + N
  rollout processes over one root, supervised to completion; with
  ``max_staleness_epochs=0`` and one actor the lockstep gate reproduces
  the in-process :class:`~agilerl_tpu.llm.flywheel.OnlineGRPOFlywheel`
  loss/param stream exactly (the tier-1 equivalence gate).

Store layout under the launch root::

    root/
      specs/        role spec JSON (argv of each child)
      status/       per-role exit status (atomic)
      logs/         per-role stdout/stderr + JSONL event streams
      membership/   HeartbeatStore leases (pid-probed)
      telemetry/    per-pod TelemetryPublisher snapshots
      weights/      WeightStore epochs (launch_flywheel)
      trajectories/ TrajectoryStore batches (launch_flywheel)
      cursors/      per-actor rollout seq cursors (respawn-safe)
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Union

from agilerl_tpu.resilience.preemption import PreemptionGuard
from agilerl_tpu.resilience.proc import (
    TELEMETRY_DIR,
    ProcessSupervisor,
    RoleContext,
    RoleSpec,
    read_statuses,
    resolve_target,
)

#: launch-root store layout shared by the launcher and the flywheel roles
WEIGHTS_DIR = "weights"
TRAJECTORIES_DIR = "trajectories"
CURSORS_DIR = "cursors"


class PodLauncher:
    """Compose and supervise a fleet of role processes over one root.

    Usage::

        launcher = PodLauncher(root, lease_timeout=2.0)
        launcher.add_role("learner", "agilerl_tpu.training.launch:learner_role",
                          kwargs={...})
        launcher.add_role("rollout_0", "agilerl_tpu.training.launch:rollout_role",
                          kwargs={...})
        launcher.start()
        summary = launcher.run(timeout=120.0)

    The launcher installs its own :class:`PreemptionGuard` for the
    supervision loop: a SIGTERM to the launcher drains the WHOLE fleet —
    forwarded termination, per-role final snapshots, telemetry flushes —
    before the launcher itself exits (clean end-to-end preemption)."""

    def __init__(self, root: Union[str, Path], lease_timeout: float = 5.0,
                 grace_s: float = 10.0, max_restarts: int = 2,
                 poll_interval: float = 0.05, registry=None,
                 probe_pids: bool = True):
        self.root = Path(root)
        self.supervisor = ProcessSupervisor(
            self.root, lease_timeout=lease_timeout, grace_s=grace_s,
            max_restarts=max_restarts, registry=registry,
            probe_pids=probe_pids)
        self.poll_interval = float(poll_interval)
        self.guard = PreemptionGuard(registry=registry)
        self._specs: List[RoleSpec] = []
        self._registry_override = registry
        self._started = False
        self._telemetry_agg = None
        self._telemetry_next = 0.0

    @property
    def heartbeat(self):
        return self.supervisor.heartbeat

    @property
    def metrics(self):
        return self.supervisor.metrics

    # -- composition ------------------------------------------------------- #
    def add_role(self, name: str, target: str,
                 kwargs: Optional[Dict[str, Any]] = None, replica: int = 0,
                 member_id: Optional[int] = None, poll_interval: float = 0.0,
                 beat_interval: Optional[float] = None,
                 env: Optional[Dict[str, str]] = None) -> RoleSpec:
        """Declare one role. ``member_id`` defaults to the declaration
        index — the first-declared role is therefore the membership leader
        (lowest live id), so declare the learner/controller first."""
        if any(s.name == name for s in self._specs):
            raise ValueError(f"duplicate role name {name!r}")
        spec = RoleSpec(
            name=name, target=target, root=str(self.root),
            member_id=(len(self._specs) if member_id is None
                       else int(member_id)),
            kwargs=dict(kwargs or {}), replica=int(replica),
            lease_timeout=self.supervisor.lease_timeout,
            beat_interval=beat_interval, poll_interval=float(poll_interval),
            env=dict(env or {}))
        self._specs.append(spec)
        return spec

    # -- lifecycle --------------------------------------------------------- #
    def start(self, wait_for_members: bool = True,
              join_timeout: float = 60.0) -> None:
        """Spawn every declared role; optionally block until every member
        has either a live lease or a completed exit (a very fast role can
        finish and tombstone its lease before the first poll — that is a
        join, not missing capacity). Bounded, so genuinely missing
        capacity surfaces as an error instead of an indefinite wait."""
        if not self._specs:
            raise ValueError("no roles declared — add_role() first")
        self.guard.install()
        for spec in self._specs:
            self.supervisor.spawn(spec)
        self._started = True
        if wait_for_members:
            self._join_barrier(join_timeout)
            self.heartbeat.expect([s.member_id for s in self._specs])

    def _join_barrier(self, timeout: float) -> None:
        from agilerl_tpu.resilience.membership import MembershipChange

        deadline = time.monotonic() + float(timeout)
        while True:
            live = set(self.heartbeat.alive())
            joined = [
                s for s in self._specs
                if s.member_id in live
                or self.supervisor.procs[s.name].poll() is not None
            ]
            if len(joined) == len(self._specs):
                return
            if time.monotonic() >= deadline:
                missing = [s.name for s in self._specs if s not in joined]
                raise MembershipChange(
                    f"launch join timed out after {timeout}s: roles never "
                    f"came up: {missing}", alive=sorted(live))
            time.sleep(self.poll_interval)

    def poll(self) -> List[Dict[str, Any]]:
        """One supervision step: reap/restart role exits and surface
        membership changes (the pid probe makes a killed local role show up
        here immediately)."""
        events = self.supervisor.poll()
        self.heartbeat.poll()
        # fold telemetry continuously (rate-limited): counter rebasing is
        # stateful — a restarted role's pre-crash high-water mark is only
        # banked if the aggregator SAW it before the fresh incarnation's
        # near-zero snapshot replaced it as the newest entry
        now = time.monotonic()
        if now >= self._telemetry_next:
            self._telemetry().poll()
            self._telemetry_next = now + max(
                self.supervisor.lease_timeout / 4.0, 0.25)
        return events

    def run(self, timeout: float = 300.0,
            until: Optional[Callable[[], bool]] = None) -> Dict[str, Any]:
        """Supervise until every role exits, ``until()`` turns true, the
        launcher is preempted, or the deadline passes — then drain the
        fleet and return the shutdown summary."""
        if not self._started:
            self.start()
        deadline = time.monotonic() + float(timeout)
        timed_out = False
        while True:
            self.poll()
            if self.guard.requested:
                break
            if until is not None and until():
                break
            if not self.supervisor.running():
                break
            if time.monotonic() >= deadline:
                timed_out = True
                break
            time.sleep(self.poll_interval)
        summary = self.shutdown()
        summary["preempted"] = bool(self.guard.requested)
        summary["timed_out"] = timed_out
        return summary

    def shutdown(self, grace_s: Optional[float] = None) -> Dict[str, Any]:
        return self.supervisor.shutdown(grace_s)

    def statuses(self) -> Dict[str, Dict[str, Any]]:
        return read_statuses(self.root)

    def _telemetry(self):
        if self._telemetry_agg is None:
            from agilerl_tpu.observability import TelemetryAggregator

            self._telemetry_agg = TelemetryAggregator(
                self.root / TELEMETRY_DIR, metrics=self.metrics)
        return self._telemetry_agg

    def aggregate_telemetry(self) -> Dict[str, Any]:
        """Fleet-wide metrics view (``registry.dump()`` form) merged from
        every role's published telemetry snapshots (the cross-process
        plane, exercised for real now that pods are processes). The
        aggregator is the launcher's own long-lived one, folded on every
        :meth:`poll` — so counters survive role restarts (rebased, not
        reset) instead of reflecting only each pod's newest snapshot."""
        agg = self._telemetry()
        agg.poll()
        return agg.merged_dump()


# --------------------------------------------------------------------------- #
# child-side role entry points
# --------------------------------------------------------------------------- #
def _flywheel_stores(ctx: RoleContext, keep_last: int):
    from agilerl_tpu.llm.flywheel import TrajectoryStore, WeightStore

    weights = WeightStore(ctx.root / WEIGHTS_DIR, keep_last=keep_last,
                          metrics=ctx.metrics)
    trajectories = TrajectoryStore(ctx.root / TRAJECTORIES_DIR,
                                   metrics=ctx.metrics)
    return weights, trajectories


def _build(entry: str, kwargs: Optional[Dict[str, Any]]):
    return resolve_target(entry)(**(kwargs or {}))


class _RolloutRole:
    """Poll-cadence driver around :class:`RolloutPod`: adopt the freshest
    published epoch, roll out when the flow-control gate opens, finish
    after ``max_seqs`` published batches. The per-actor cursor file makes
    a respawned actor continue its seq line instead of replaying it."""

    def __init__(self, ctx: RoleContext):
        kw = ctx.spec.kwargs
        from agilerl_tpu.llm.flywheel import RolloutPod

        agent = _build(kw["make_agent"], kw.get("agent_kwargs"))
        env = _build(kw["make_env"], kw.get("env_kwargs"))
        weights, trajectories = _flywheel_stores(
            ctx, int(kw.get("keep_last", 4)))
        actor_id = int(kw.get("actor_id", 0))
        cursor = ctx.root / CURSORS_DIR / f"actor_{actor_id:03d}.json"
        cursor.parent.mkdir(parents=True, exist_ok=True)
        self.pod = RolloutPod(agent, env, weights, trajectories,
                              actor_id=actor_id, metrics=ctx.metrics,
                              cursor_path=cursor)
        self.ctx = ctx
        self.max_seqs = int(kw["max_seqs"])
        self.max_staleness = int(kw.get("max_staleness_epochs", 0))
        self.max_inflight = int(kw.get("max_inflight",
                                       self.max_staleness + 1))
        self.greedy = bool(kw.get("greedy", False))
        #: single-actor lockstep gate: only produce seq k once epoch
        #: >= k - max_staleness is published — with staleness 0 this is
        #: exactly the in-process driver's interleave, so the loss/param
        #: stream matches bit for bit (the equivalence gate)
        self.lockstep = bool(kw.get("lockstep", False))

    def tick(self) -> bool:
        if self.pod.seq >= self.max_seqs:
            return True
        self.pod.poll_weights()
        if self.pod.weight_epoch < 0:
            return False  # nothing published yet — idle, stay live
        if self.pod.traj_store.pending() >= self.max_inflight:
            return False  # flow control: anything more would be stale
        if self.lockstep and \
                self.pod.weight_epoch < self.pod.seq - self.max_staleness:
            return False  # the learner has not caught up to our seq line
        self.pod.rollout_once(greedy=self.greedy)
        return self.pod.seq >= self.max_seqs


class _LearnerRole:
    """Poll-cadence driver around :class:`LearnerPod` with warm restart:
    a respawned learner process restores the optimizer/reference/RNG state
    that rides every published weight epoch (``carry_state``) and resumes
    the exact loss stream; a fresh root publishes epoch 0 so actors can
    adopt before the first learn."""

    def __init__(self, ctx: RoleContext):
        kw = ctx.spec.kwargs
        from agilerl_tpu.llm.flywheel import LearnerPod

        agent = _build(kw["make_agent"], kw.get("agent_kwargs"))
        weights, trajectories = _flywheel_stores(
            ctx, int(kw.get("keep_last", 4)))
        self.pod = LearnerPod(
            agent, weights, trajectories,
            max_staleness_epochs=int(kw.get("max_staleness_epochs", 0)),
            metrics=ctx.metrics, publish_initial=False,
            carry_state=bool(kw.get("carry_state", True)))
        if not self.pod.restore_from_store():
            self.pod.publish()  # fresh root: epoch 0 = the initial adapter
        self.max_epochs = int(kw["max_epochs"])

    def tick(self) -> bool:
        if self.pod.epoch >= self.max_epochs:
            return True
        # cap the per-tick batch budget so a backlog (multiple actors ahead
        # of the learner) can never train PAST max_epochs inside one step
        self.pod.step(max_batches=self.max_epochs - self.pod.epoch)
        return self.pod.epoch >= self.max_epochs


def rollout_role(ctx: RoleContext) -> _RolloutRole:
    """Entry point: GRPO rollout pod as a supervised process.

    kwargs: ``make_agent``/``make_env`` (``module:function`` entry points,
    with optional ``agent_kwargs``/``env_kwargs``), ``actor_id``,
    ``max_seqs``, ``max_staleness_epochs``, ``max_inflight``, ``greedy``,
    ``lockstep``, ``keep_last``."""
    return _RolloutRole(ctx)


def learner_role(ctx: RoleContext) -> _LearnerRole:
    """Entry point: GRPO learner pod as a supervised process.

    kwargs: ``make_agent`` (+ ``agent_kwargs``), ``max_epochs``,
    ``max_staleness_epochs``, ``carry_state``, ``keep_last``."""
    return _LearnerRole(ctx)


class _DriverRole:
    """Generic poll-cadence adapter: build an object from an entry point,
    call one bounded method per tick. This is how serving-fleet steps
    (``method="step"``) and elastic-PBT generation boundaries run as
    processes without bespoke drivers — the object's own store wiring
    (KV transfers, executables, telemetry) is untouched."""

    def __init__(self, ctx: RoleContext):
        kw = ctx.spec.kwargs
        self.obj = _build(kw["make"], kw.get("make_kwargs"))
        self._method = getattr(self.obj, str(kw.get("method", "step")))
        self._method_kwargs = dict(kw.get("method_kwargs") or {})
        self.max_ticks = kw.get("max_ticks")
        self.ticks = 0

    def tick(self) -> bool:
        self._method(**self._method_kwargs)
        self.ticks += 1
        return self.max_ticks is not None and self.ticks >= int(self.max_ticks)

    def drain(self) -> None:
        final = getattr(self.obj, "drain", None)
        if callable(final):
            final()


def driver_role(ctx: RoleContext) -> _DriverRole:
    """Entry point: generic step-method driver (serving fleet, PBT host).

    kwargs: ``make`` (+ ``make_kwargs``), ``method`` (default ``"step"``,
    + ``method_kwargs``), ``max_ticks`` (None = run until preempted)."""
    return _DriverRole(ctx)


class _IdleRole:
    """Trivial role for tests and docs: counts ticks (optionally forever)
    and records a drain marker on graceful exit — the smallest thing that
    exercises the full harness contract."""

    def __init__(self, ctx: RoleContext):
        self.ctx = ctx
        self.max_ticks = ctx.spec.kwargs.get("max_ticks")
        self.ticks = 0

    def tick(self) -> bool:
        self.ticks += 1
        self.ctx.metrics.counter("launch/idle_ticks_total").inc()
        return (self.max_ticks is not None
                and self.ticks >= int(self.max_ticks))

    def drain(self) -> None:
        from agilerl_tpu.resilience.atomic import atomic_write_bytes
        import json

        atomic_write_bytes(
            self.ctx.root / f"drain_{self.ctx.spec.name}.json",
            json.dumps({"role": self.ctx.spec.name,
                        "ticks": self.ticks}).encode())


def idle_role(ctx: RoleContext) -> _IdleRole:
    """Entry point: the trivial tick-counting role (tests/docs).

    kwargs: ``max_ticks`` (None = tick until preempted)."""
    return _IdleRole(ctx)


# --------------------------------------------------------------------------- #
# flywheel composition
# --------------------------------------------------------------------------- #
def read_loss_stream(root: Union[str, Path]) -> List[float]:
    """The learner's per-epoch loss stream, read from weight-epoch
    MANIFESTS (no payload unpickling). Bounded by the store's ``keep_last``
    — pass a large ``keep_last`` to :func:`launch_flywheel` when the full
    stream matters (the equivalence gate does)."""
    from agilerl_tpu.resilience.store import committed_entries, read_manifest

    losses: List[float] = []
    for entry in committed_entries(Path(root) / WEIGHTS_DIR, "epoch_"):
        try:
            manifest = read_manifest(entry)
        except Exception:
            continue
        if "loss" in manifest:
            losses.append(manifest["loss"])  # JSON scalar — already host
    return losses


def launch_flywheel(
    root: Union[str, Path],
    make_agent: str,
    make_env: str,
    max_epochs: int,
    num_rollouts: int = 1,
    max_staleness_epochs: int = 0,
    agent_kwargs: Optional[Dict[str, Any]] = None,
    env_kwargs: Optional[Dict[str, Any]] = None,
    rollout_seqs: Optional[int] = None,
    keep_last: Optional[int] = None,
    lease_timeout: float = 5.0,
    grace_s: float = 15.0,
    max_restarts: int = 2,
    timeout: float = 300.0,
    greedy: bool = False,
    env: Optional[Dict[str, str]] = None,
    registry=None,
) -> Dict[str, Any]:
    """One learner + ``num_rollouts`` rollout processes over ``root``,
    supervised to ``max_epochs`` published weight epochs.

    ``make_agent``/``make_env`` are ``module:function`` entry points — the
    SAME construction must yield RNG-identical agents in every process, so
    pass the seed through ``agent_kwargs``. With one rollout and staleness
    0 the lockstep gate reproduces the in-process driver's stream exactly.
    Returns the shutdown summary plus the loss stream read back from the
    weight-epoch manifests."""
    max_epochs = int(max_epochs)
    staleness = int(max_staleness_epochs)
    greedy = bool(greedy)
    total_seqs = max_epochs if rollout_seqs is None else int(rollout_seqs)
    per_actor = [total_seqs // num_rollouts] * num_rollouts
    for i in range(total_seqs % num_rollouts):
        per_actor[i] += 1
    keep = int(keep_last) if keep_last is not None else max(4, max_epochs + 1)
    launcher = PodLauncher(root, lease_timeout=lease_timeout,
                           grace_s=grace_s, max_restarts=max_restarts,
                           registry=registry)
    launcher.add_role(
        "learner", "agilerl_tpu.training.launch:learner_role",
        kwargs={"make_agent": make_agent, "agent_kwargs": agent_kwargs,
                "max_epochs": max_epochs,
                "max_staleness_epochs": staleness,
                "keep_last": keep},
        env=env)
    lockstep = num_rollouts == 1
    for i in range(num_rollouts):
        launcher.add_role(
            f"rollout_{i}", "agilerl_tpu.training.launch:rollout_role",
            kwargs={"make_agent": make_agent, "agent_kwargs": agent_kwargs,
                    "make_env": make_env, "env_kwargs": env_kwargs,
                    "actor_id": i, "max_seqs": per_actor[i],
                    "max_staleness_epochs": staleness,
                    "greedy": greedy, "lockstep": lockstep,
                    "keep_last": keep},
            replica=i, poll_interval=0.01, env=env)
    launcher.start()
    summary = launcher.run(timeout=timeout)
    summary["losses"] = read_loss_stream(root)
    summary["root"] = str(root)
    return summary
