"""Entry point for elastic preemption-native pod-scale PBT.

``train_elastic_pbt`` is the loop-shaped wrapper around
:class:`~agilerl_tpu.parallel.elastic.ElasticPBTController`: build the
controller over a host topology and a shared store, optionally resume from
the latest complete snapshot, drive N generations, and hand back the
controller (fitness history, lineage ids, layout) for inspection — the
scan-native sibling of the ``resilience=``/``resume=`` kwargs the interop
loops grew in PR 3.

Typical tier-1 emulation (single process, virtual CPU mesh)::

    engine = EvoDQN(env, net_cfg, optax.adam(1e-3), num_envs=4, ...)
    ctl = train_elastic_pbt(
        engine, pop_size=4, generations=6, store_dir="runs/exp/elastic",
        n_hosts=2, heartbeat_timeout=0.5,
        fault_injector=FaultInjector(kill_host_at={2: 1}),
    )

On a real preemptible slice, run one process per host with
``hosts=[EmulatedHost(jax.process_index(), jax.local_devices())]`` and the
same shared ``store_dir``; pass ``resume=True`` so a rescheduled pod
continues the run from the last committed snapshot.
"""

from __future__ import annotations

from pathlib import Path
from typing import List, Optional, Sequence, Union

from agilerl_tpu.parallel.elastic import (
    ElasticPBTController,
    EmulatedHost,
    IslandConfig,
)


def train_elastic_pbt(
    engine,
    pop_size: int,
    generations: int,
    store_dir: Union[str, Path],
    *,
    seed: int = 0,
    hosts: Optional[List[EmulatedHost]] = None,
    n_hosts: Optional[int] = None,
    devices: Optional[Sequence] = None,
    heartbeat_timeout: float = 2.0,
    generation_timeout: Optional[float] = None,
    snapshot_every: int = 1,
    keep_last: int = 3,
    keep_best: bool = True,
    island: Optional[IslandConfig] = None,
    telemetry=None,
    fault_injector=None,
    max_members_per_device: Optional[int] = None,
    resume: bool = False,
    controller: Optional[ElasticPBTController] = None,
) -> ElasticPBTController:
    """Run ``generations`` of elastic PBT; returns the controller. Pass a
    pre-built ``controller`` to continue an in-process run (all topology
    kwargs are then ignored)."""
    if controller is None:
        controller = ElasticPBTController(
            engine, pop_size, store_dir,
            seed=seed, hosts=hosts, n_hosts=n_hosts, devices=devices,
            heartbeat_timeout=heartbeat_timeout,
            generation_timeout=generation_timeout,
            snapshot_every=snapshot_every, keep_last=keep_last,
            keep_best=keep_best, island=island, telemetry=telemetry,
            fault_injector=fault_injector,
            max_members_per_device=max_members_per_device,
        )
    if resume:
        controller.resume()
    controller.run(generations)
    return controller
