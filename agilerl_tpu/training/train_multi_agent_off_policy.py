"""Multi-agent off-policy evolutionary training
(parity: agilerl/training/train_multi_agent_off_policy.py — dict-keyed variant
of train_off_policy over MultiAgentReplayBuffer).

Pipelined like train_off_policy (docs/performance.md): transitions are
staged on host and coalesced into one buffer dispatch per ``flush_every``
steps, warmup gates read the host-mirrored size counter, and the timeline
carries host/device/overlap gauges.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from agilerl_tpu.observability import init_run_telemetry
from agilerl_tpu.resilience import max_fitness
from agilerl_tpu.vector import sanitize_ma_transition
from agilerl_tpu.utils.utils import (
    print_hyperparams,
    resume_population_from_checkpoint,
    save_population_checkpoint,
    tournament_selection_and_mutation,
)


def train_multi_agent_off_policy(
    env,
    env_name: str,
    algo: str,
    pop: List,
    memory,
    INIT_HP: Optional[Dict] = None,
    MUT_P: Optional[Dict] = None,
    sum_scores: bool = True,
    max_steps: int = 50_000,
    evo_steps: int = 5_000,
    eval_steps: Optional[int] = None,
    eval_loop: int = 1,
    learning_delay: int = 0,
    target: Optional[float] = None,
    tournament=None,
    mutation=None,
    checkpoint: Optional[int] = None,
    checkpoint_path: Optional[str] = None,
    overwrite_checkpoints: bool = False,
    save_elite: bool = False,
    elite_path: Optional[str] = None,
    wb: bool = False,
    verbose: bool = True,
    accelerator=None,
    wandb_api_key: Optional[str] = None,
    resume: bool = False,
    telemetry=None,
    seed: Optional[int] = None,
    flush_every: Optional[int] = None,
    resilience=None,
) -> Tuple[List, List[List[float]]]:
    if resume and resilience is None:
        resume_population_from_checkpoint(pop, checkpoint_path)
    telem = init_run_telemetry(wb=wb, config=INIT_HP, telemetry=telemetry)
    telem.attach_evolution(tournament, mutation)
    if seed is not None and hasattr(memory, "seed"):
        memory.seed(seed)
    use_staging = hasattr(memory, "stage_to_memory")
    if hasattr(memory, "flush_every"):
        if flush_every is not None:
            memory.flush_every = max(int(flush_every), 1)
        elif not getattr(memory, "_flush_every_user_set", False):
            memory.flush_every = 8  # pipelining default for untouched buffers
    num_envs = getattr(env, "num_envs", 1)
    agent_ids = pop[0].agent_ids
    pop_fitnesses: List[List[float]] = [[] for _ in pop]
    total_steps = 0
    checkpoint_count = 0

    def _counters():
        return {"total_steps": total_steps, "checkpoint_count": checkpoint_count,
                "pop_fitnesses": pop_fitnesses}

    try:
        if resilience is not None:
            resilience.attach(pop=pop, memory=memory, tournament=tournament,
                              mutation=mutation, telemetry=telem, env=env)
            if resume:
                restored = resilience.resume(_counters())
                total_steps = int(restored["total_steps"])
                checkpoint_count = int(restored["checkpoint_count"])
                pop_fitnesses = [list(f) for f in restored["pop_fitnesses"]]
        start = time.time()

        while np.min([agent.steps[-1] for agent in pop]) < max_steps:
            for agent in pop:
                if resilience is not None and resilience.abort_generation:
                    break
                obs, info = env.reset()
                steps = 0
                learn_every = max(agent.learn_step, 1)
                for _ in range(max(evo_steps // num_envs, 1)):
                    # forward the env's info dict: action masks / env-defined
                    # actions ride it (parity: reference train_multi_agent.py)
                    t_act = time.perf_counter()
                    actions = agent.get_action(obs, infos=info)
                    t_host = time.perf_counter()
                    next_obs, reward, terminated, truncated, info = env.step(actions)
                    # dead/inactive agents arrive as NaN placeholders — zero them
                    # before they can reach the buffer (NaN Q-target poisoning)
                    next_obs, reward = sanitize_ma_transition(next_obs, reward)
                    done = {
                        a: np.asarray(terminated[a], np.float32) for a in agent_ids
                    }
                    store_next = (
                        info.get("final_obs", next_obs) if isinstance(info, dict) else next_obs
                    )
                    if store_next is not next_obs:
                        # final_obs is assembled from shared memory and can carry
                        # NaN placeholder rows too (review finding)
                        store_next, _ = sanitize_ma_transition(store_next, {})
                    if use_staging:
                        # chunked ingestion: one coalesced buffer dispatch per
                        # flush_every steps instead of one per step
                        memory.stage_to_memory(
                            obs, actions, reward, store_next, done,
                            is_vectorised=num_envs > 1,
                        )
                    else:
                        memory.save_to_memory(
                            obs, actions, reward, store_next, done,
                            is_vectorised=num_envs > 1,
                        )
                    obs = next_obs
                    steps += num_envs
                    total_steps += num_envs
                    learn_block_s = 0.0
                    if steps % learn_every < num_envs:
                        if use_staging:
                            memory.flush()
                        if (
                            len(memory) >= agent.batch_size
                            and len(memory) >= learning_delay
                        ):
                            t_learn = time.perf_counter()
                            agent.learn(memory.sample(agent.batch_size))
                            learn_block_s = time.perf_counter() - t_learn
                    # the learn call blocks on the device — count it as device
                    # wait so overlap_fraction stays honest
                    telem.step(
                        env_steps=num_envs, agent_index=agent.index,
                        host_time_s=(time.perf_counter() - t_host) - learn_block_s,
                        device_time_s=(t_host - t_act) + learn_block_s,
                    )
                    if resilience is not None and resilience.abort_generation:
                        break
                if use_staging:
                    memory.flush()
                agent.steps[-1] += steps

            if resilience is not None and resilience.abort_generation:
                resilience.step_boundary(total_steps, _counters(), pop=pop)
                break

            fitnesses = [
                agent.test(env, max_steps=eval_steps, loop=eval_loop, sum_scores=sum_scores)
                for agent in pop
            ]
            for i, f in enumerate(fitnesses):
                pop_fitnesses[i].append(f)
            telem.record_eval(pop, fitnesses)
            telem.log_step({"global_step": total_steps,
                            "eval/mean_fitness": float(np.mean(fitnesses))})
            if verbose:
                fps = total_steps / (time.time() - start)
                print(f"--- steps {total_steps} fps {fps:.0f} fitness {[f'{f:.1f}' for f in fitnesses]}")
                print_hyperparams(pop)

            if tournament is not None and mutation is not None:
                pop = tournament_selection_and_mutation(
                    pop, tournament, mutation, env_name=env_name, algo=algo,
                    elite_path=elite_path, save_elite=save_elite,
                )
            for agent in pop:
                agent.steps.append(agent.steps[-1])
            if resilience is not None:
                if resilience.step_boundary(
                    total_steps, _counters(), pop=pop,
                    fitness=max_fitness(fitnesses),
                ):
                    break
            elif checkpoint is not None and checkpoint_path is not None:
                if total_steps // checkpoint > checkpoint_count:
                    save_population_checkpoint(pop, checkpoint_path, overwrite_checkpoints)
                    checkpoint_count = total_steps // checkpoint
            if target is not None and np.min(fitnesses) >= target:
                break

    finally:
        # a crash escaping the loop must not leak the guard's process-wide
        # SIGTERM/SIGINT handlers (or an unflushed telemetry sink) into a
        # driver that catches the exception and keeps running
        if resilience is not None:
            resilience.close()
        if telemetry is None:
            telem.close()
    return pop, pop_fitnesses
