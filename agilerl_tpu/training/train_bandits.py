"""Contextual-bandit training loop (parity: agilerl/training/train_bandits.py —
BanditEnv loop with regret tracking, fitness eval, evolution).

Pipelined like train_off_policy (docs/performance.md): per-arm transitions
are staged on host and coalesced into one buffer dispatch per
``flush_every`` pulls; warmup gates read the host-mirrored size counter,
and the timeline carries host/device/overlap gauges.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from agilerl_tpu.observability import init_run_telemetry
from agilerl_tpu.resilience import max_fitness
from agilerl_tpu.utils.utils import (
    print_hyperparams,
    resume_population_from_checkpoint,
    save_population_checkpoint,
    tournament_selection_and_mutation,
)


def train_bandits(
    env,
    env_name: str,
    algo: str,
    pop: List,
    memory,
    INIT_HP: Optional[Dict] = None,
    MUT_P: Optional[Dict] = None,
    swap_channels: bool = False,
    max_steps: int = 10_000,
    episode_steps: int = 100,
    evo_steps: int = 500,
    eval_steps: Optional[int] = None,
    eval_loop: int = 1,
    target: Optional[float] = None,
    tournament=None,
    mutation=None,
    checkpoint: Optional[int] = None,
    checkpoint_path: Optional[str] = None,
    overwrite_checkpoints: bool = False,
    save_elite: bool = False,
    elite_path: Optional[str] = None,
    wb: bool = False,
    verbose: bool = True,
    accelerator=None,
    wandb_api_key: Optional[str] = None,
    resume: bool = False,
    telemetry=None,
    seed: Optional[int] = None,
    flush_every: Optional[int] = None,
    resilience=None,
) -> Tuple[List, List[List[float]]]:
    if resume and resilience is None:
        resume_population_from_checkpoint(pop, checkpoint_path)
    telem = init_run_telemetry(wb=wb, config=INIT_HP, telemetry=telemetry)
    telem.attach_evolution(tournament, mutation)
    if seed is not None and hasattr(memory, "seed"):
        memory.seed(seed)
    use_staging = hasattr(memory, "stage")
    if hasattr(memory, "flush_every"):
        if flush_every is not None:
            memory.flush_every = max(int(flush_every), 1)
        elif not getattr(memory, "_flush_every_user_set", False):
            memory.flush_every = 8  # pipelining default for untouched buffers
    pop_fitnesses: List[List[float]] = [[] for _ in pop]
    total_steps = 0
    checkpoint_count = 0

    def _counters():
        return {"total_steps": total_steps, "checkpoint_count": checkpoint_count,
                "pop_fitnesses": pop_fitnesses}

    try:
        if resilience is not None:
            resilience.attach(pop=pop, memory=memory, tournament=tournament,
                              mutation=mutation, telemetry=telem, env=env)
            if resume:
                restored = resilience.resume(_counters())
                total_steps = int(restored["total_steps"])
                checkpoint_count = int(restored["checkpoint_count"])
                pop_fitnesses = [list(f) for f in restored["pop_fitnesses"]]
        start = time.time()

        while np.min([agent.steps[-1] for agent in pop]) < max_steps:
            for agent in pop:
                if resilience is not None and resilience.abort_generation:
                    break
                context = env.reset()
                regret_free = 0.0
                learn_every = max(agent.learn_step, 1)
                for step in range(max(evo_steps, 1)):
                    t_act = time.perf_counter()
                    arm = agent.get_action(context)
                    t_host = time.perf_counter()
                    next_context, reward = env.step(arm)
                    regret_free += float(np.asarray(reward).squeeze())
                    transition = {
                        "obs": np.asarray(context)[int(arm)],
                        "action": np.int32(arm),
                        "reward": np.float32(np.asarray(reward).squeeze()),
                        "next_obs": np.asarray(next_context)[int(arm)],
                        "done": np.float32(1.0),
                    }
                    if use_staging:
                        # chunked ingestion: one coalesced buffer dispatch per
                        # flush_every pulls (sampling flushes first)
                        memory.stage(transition)
                    else:
                        memory.add(transition)
                    context = next_context
                    total_steps += 1
                    agent.steps[-1] += 1
                    learn_block_s = 0.0
                    if step % learn_every == 0:
                        if use_staging:
                            memory.flush()
                        if len(memory) >= agent.batch_size:
                            t_learn = time.perf_counter()
                            agent.learn(memory.sample(agent.batch_size))
                            learn_block_s = time.perf_counter() - t_learn
                    # the learn call blocks on the device — count it as device
                    # wait so overlap_fraction stays honest
                    telem.step(
                        env_steps=1, agent_index=agent.index,
                        host_time_s=time.perf_counter() - t_host - learn_block_s,
                        device_time_s=t_host - t_act + learn_block_s,
                    )
                    if resilience is not None and resilience.abort_generation:
                        break
                if use_staging:
                    memory.flush()
                agent.scores.append(regret_free / max(evo_steps, 1))

            if resilience is not None and resilience.abort_generation:
                resilience.step_boundary(total_steps, _counters(), pop=pop)
                break

            fitnesses = [
                agent.test(env, max_steps=eval_steps or 100, loop=eval_loop) for agent in pop
            ]
            for i, f in enumerate(fitnesses):
                pop_fitnesses[i].append(f)
            telem.record_eval(pop, fitnesses)
            telem.log_step({"global_step": total_steps,
                            "eval/mean_fitness": float(np.mean(fitnesses))})
            if verbose:
                print(f"--- steps {total_steps} fitness {[f'{f:.2f}' for f in fitnesses]}")
                print_hyperparams(pop)

            if tournament is not None and mutation is not None:
                pop = tournament_selection_and_mutation(
                    pop, tournament, mutation, env_name=env_name, algo=algo,
                    elite_path=elite_path, save_elite=save_elite,
                )
            for agent in pop:
                agent.steps.append(agent.steps[-1])
            if resilience is not None:
                if resilience.step_boundary(
                    total_steps, _counters(), pop=pop,
                    fitness=max_fitness(fitnesses),
                ):
                    break
            elif checkpoint is not None and checkpoint_path is not None:
                if total_steps // checkpoint > checkpoint_count:
                    save_population_checkpoint(pop, checkpoint_path, overwrite_checkpoints)
                    checkpoint_count = total_steps // checkpoint
            if target is not None and np.min(fitnesses) >= target:
                break

    finally:
        # a crash escaping the loop must not leak the guard's process-wide
        # SIGTERM/SIGINT handlers (or an unflushed telemetry sink) into a
        # driver that catches the exception and keeps running
        if resilience is not None:
            resilience.close()
        if telemetry is None:
            telem.close()
    return pop, pop_fitnesses
