"""Offline RL training from a fixed dataset (parity: agilerl/training/train_offline.py
— h5 dataset -> buffer -> CQN/CQL learn loop, fitness eval, evolution).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from agilerl_tpu.observability import init_run_telemetry
from agilerl_tpu.utils.utils import (
    print_hyperparams,
    resume_population_from_checkpoint,
    save_population_checkpoint,
    tournament_selection_and_mutation,
)


def train_offline(
    env,
    env_name: str,
    dataset,
    algo: str,
    pop: List,
    memory,
    INIT_HP: Optional[Dict] = None,
    MUT_P: Optional[Dict] = None,
    swap_channels: bool = False,
    max_steps: int = 50_000,
    evo_steps: int = 5_000,
    eval_steps: Optional[int] = None,
    eval_loop: int = 1,
    target: Optional[float] = None,
    tournament=None,
    mutation=None,
    checkpoint: Optional[int] = None,
    checkpoint_path: Optional[str] = None,
    overwrite_checkpoints: bool = False,
    save_elite: bool = False,
    elite_path: Optional[str] = None,
    wb: bool = False,
    verbose: bool = True,
    accelerator=None,
    wandb_api_key: Optional[str] = None,
    resume: bool = False,
    telemetry=None,
) -> Tuple[List, List[List[float]]]:
    """dataset: dict-like with observations/actions/rewards/next_observations/
    terminals arrays (h5py.File or numpy dict; parity with the reference's
    h5 format in data/cartpole)."""
    if resume:
        resume_population_from_checkpoint(pop, checkpoint_path)
    telem = init_run_telemetry(wb=wb, config=INIT_HP, telemetry=telemetry)
    telem.attach_evolution(tournament, mutation)

    if len(memory) == 0:
        obs = np.asarray(dataset["observations"])
        transition = {
            "obs": obs,
            "action": np.asarray(dataset["actions"]).squeeze(),
            "reward": np.asarray(dataset["rewards"], np.float32).squeeze(),
            "next_obs": np.asarray(dataset["next_observations"]),
            "done": np.asarray(dataset["terminals"], np.float32).squeeze(),
        }
        memory.add(transition, batched=True)

    pop_fitnesses: List[List[float]] = [[] for _ in pop]
    total_steps = 0
    checkpoint_count = 0
    start = time.time()

    while np.min([agent.steps[-1] for agent in pop]) < max_steps:
        for agent in pop:
            for _ in range(max(evo_steps // max(agent.learn_step, 1), 1)):
                agent.learn(memory.sample(agent.batch_size))
                agent.steps[-1] += agent.learn_step
                total_steps += agent.learn_step
                telem.step(env_steps=agent.learn_step, agent_index=agent.index)

        fitnesses = [
            agent.test(env, swap_channels=swap_channels, max_steps=eval_steps, loop=eval_loop)
            for agent in pop
        ]
        for i, f in enumerate(fitnesses):
            pop_fitnesses[i].append(f)
        telem.record_eval(pop, fitnesses)
        telem.log_step({"global_step": total_steps,
                        "eval/mean_fitness": float(np.mean(fitnesses))})
        if verbose:
            print(f"--- steps {total_steps} fitness {[f'{f:.1f}' for f in fitnesses]}")
            print_hyperparams(pop)

        if tournament is not None and mutation is not None:
            pop = tournament_selection_and_mutation(
                pop, tournament, mutation, env_name=env_name, algo=algo,
                elite_path=elite_path, save_elite=save_elite,
            )
        for agent in pop:
            agent.steps.append(agent.steps[-1])
        if checkpoint is not None and checkpoint_path is not None:
            if total_steps // checkpoint > checkpoint_count:
                save_population_checkpoint(pop, checkpoint_path, overwrite_checkpoints)
                checkpoint_count = total_steps // checkpoint
        if target is not None and np.min(fitnesses) >= target:
            break

    if telemetry is None:
        telem.close()
    return pop, pop_fitnesses
