"""Evolutionary on-policy training loop (parity: agilerl/training/train_on_policy.py
— train_on_policy:30: collect_rollouts -> agent.learn() per cadence :217-245,
fitness eval, tournament+mutation; the deprecated non-buffer experiences path is
not carried over).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from agilerl_tpu.observability import init_run_telemetry
from agilerl_tpu.resilience import max_fitness
from agilerl_tpu.rollouts.on_policy import collect_rollouts
from agilerl_tpu.utils.utils import (
    print_hyperparams,
    resume_population_from_checkpoint,
    save_population_checkpoint,
    tournament_selection_and_mutation,
)


def train_on_policy(
    env,
    env_name: str,
    algo: str,
    pop: List,
    INIT_HP: Optional[Dict] = None,
    MUT_P: Optional[Dict] = None,
    swap_channels: bool = False,
    max_steps: int = 50_000,
    evo_steps: int = 5_000,
    eval_steps: Optional[int] = None,
    eval_loop: int = 1,
    target: Optional[float] = None,
    tournament=None,
    mutation=None,
    checkpoint: Optional[int] = None,
    checkpoint_path: Optional[str] = None,
    overwrite_checkpoints: bool = False,
    save_elite: bool = False,
    elite_path: Optional[str] = None,
    wb: bool = False,
    verbose: bool = True,
    accelerator=None,
    wandb_api_key: Optional[str] = None,
    resume: bool = False,
    telemetry=None,
    resilience=None,
) -> Tuple[List, List[List[float]]]:
    if resume and resilience is None:
        resume_population_from_checkpoint(pop, checkpoint_path)
    telem = init_run_telemetry(wb=wb, config=INIT_HP, telemetry=telemetry)
    telem.attach_evolution(tournament, mutation)
    num_envs = getattr(env, "num_envs", 1)
    pop_fitnesses: List[List[float]] = [[] for _ in pop]
    total_steps = 0
    checkpoint_count = 0

    def _counters():
        return {"total_steps": total_steps, "checkpoint_count": checkpoint_count,
                "pop_fitnesses": pop_fitnesses}

    try:
        if resilience is not None:
            resilience.attach(pop=pop, tournament=tournament, mutation=mutation,
                              telemetry=telem, env=env)
            if resume:
                restored = resilience.resume(_counters())
                total_steps = int(restored["total_steps"])
                checkpoint_count = int(restored["checkpoint_count"])
                pop_fitnesses = [list(f) for f in restored["pop_fitnesses"]]
        start = time.time()

        while np.min([agent.steps[-1] for agent in pop]) < max_steps:
            for agent in pop:
                if resilience is not None and resilience.abort_generation:
                    break
                steps = 0
                agent._last_obs = None  # fresh episodes per generation
                for _ in range(max(evo_steps // (agent.learn_step * num_envs), 1)):
                    collect_rollouts(agent, env, n_steps=agent.learn_step)
                    agent.learn()
                    steps += agent.learn_step * num_envs
                    total_steps += agent.learn_step * num_envs
                    telem.step(env_steps=agent.learn_step * num_envs,
                               agent_index=agent.index)
                    if resilience is not None and resilience.abort_generation:
                        break
                agent.steps[-1] += steps

            if resilience is not None and resilience.abort_generation:
                resilience.step_boundary(total_steps, _counters(), pop=pop)
                break

            fitnesses = [
                agent.test(env, swap_channels=swap_channels, max_steps=eval_steps, loop=eval_loop)
                for agent in pop
            ]
            for i, f in enumerate(fitnesses):
                pop_fitnesses[i].append(f)
            telem.record_eval(pop, fitnesses)
            telem.log_step(
                {"global_step": total_steps, "fps": total_steps / (time.time() - start),
                 "eval/mean_fitness": float(np.mean(fitnesses))}
            )
            if verbose:
                fps = total_steps / (time.time() - start)
                print(
                    f"--- steps {total_steps} fps {fps:.0f} "
                    f"fitness {[f'{f:.1f}' for f in fitnesses]}"
                )
                print_hyperparams(pop)

            if tournament is not None and mutation is not None:
                pop = tournament_selection_and_mutation(
                    pop, tournament, mutation, env_name=env_name, algo=algo,
                    elite_path=elite_path, save_elite=save_elite,
                )

            for agent in pop:
                agent.steps.append(agent.steps[-1])

            if resilience is not None:
                if resilience.step_boundary(
                    total_steps, _counters(), pop=pop,
                    fitness=max_fitness(fitnesses),
                ):
                    break
            elif checkpoint is not None and checkpoint_path is not None:
                if total_steps // checkpoint > checkpoint_count:
                    save_population_checkpoint(pop, checkpoint_path, overwrite_checkpoints)
                    checkpoint_count = total_steps // checkpoint

            if target is not None and np.min(fitnesses) >= target:
                break

    finally:
        # a crash escaping the loop must not leak the guard's process-wide
        # SIGTERM/SIGINT handlers (or an unflushed telemetry sink) into a
        # driver that catches the exception and keeps running
        if resilience is not None:
            resilience.close()
        if telemetry is None:
            telem.close()
    return pop, pop_fitnesses
