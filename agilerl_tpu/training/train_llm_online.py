"""Online GRPO flywheel entry point (ROADMAP item 3): the disaggregated
analogue of ``finetune_llm_reasoning`` — rollout and learner pods exchange
adapter epochs and trajectory batches through atomic commit-dir stores
(llm/flywheel.py), with the staleness-aware importance-corrected learn
step. ``max_staleness_epochs=0`` is the synchronous mode, loss-stream
equivalent to the interleaved loop on the same prompt set (the tier-1
gate); larger budgets let decode run ahead of learn.

Wired to ``telemetry=`` / ``resilience=`` exactly like the other loop
entry points: losses route through the RunTelemetry facade, evaluations
feed best-fitness snapshot retention, and a SIGTERM lands a final
snapshot at the next learner-epoch boundary.
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import List, Optional, Tuple, Union

from agilerl_tpu.llm.flywheel import (
    LearnerPod,
    OnlineGRPOFlywheel,
    RolloutPod,
    TrajectoryStore,
    WeightStore,
)
from agilerl_tpu.observability import init_run_telemetry
from agilerl_tpu.resilience import max_fitness
from agilerl_tpu.training.train_llm import _assert_llm_mutations


def finetune_llm_reasoning_online(
    agent,
    env,
    workdir: Union[str, Path],
    INIT_HP: Optional[dict] = None,
    max_reward: Optional[float] = None,
    wb: bool = False,
    evaluation_interval: int = 10,
    verbose: bool = True,
    max_epochs: int = 200,
    max_staleness_epochs: int = 2,
    rho_clip: float = 2.0,
    importance_correction: bool = True,
    keep_weight_epochs: int = 4,
    actor_agent=None,
    fleet=None,
    autoscaler=None,
    plan=None,
    mesh=None,
    mutation=None,
    wandb_api_key: Optional[str] = None,
    resume: bool = False,
    telemetry=None,
    resilience=None,
    telemetry_export_dir=None,
) -> Tuple[object, List[float]]:
    """Disaggregated online GRPO over a ReasoningGym-style env.

    ``agent`` is the LEARNER's GRPO instance. ``actor_agent`` defaults to
    the same object — the colocated single-process emulation every CPU
    test and bench runs (the elastic tier's emulated-host precedent); pass
    a clone sharing ``base_params`` for genuinely separate pods. ``fleet``
    routes rollouts through a ServingFleet (with ``autoscaler`` watching
    its SLO telemetry); ``plan``/``mesh`` place the learner through the
    declarative sharding engine. Returns ``(agent, fitnesses)``."""
    _assert_llm_mutations(mutation)
    if resume and resilience is None:
        raise ValueError(
            "resume=True requires resilience= (the snapshot defines the "
            "epoch line to continue; without one the fresh learner would "
            "start at epoch 0 under a reused workdir's newer epochs and "
            "drop every batch as negative-lag)")
    telem = init_run_telemetry(wb=wb, config=INIT_HP, telemetry=telemetry)
    if telem.timeline.model_config is None:
        telem.timeline.set_model_config(getattr(agent, "model_config", None))
    workdir = Path(workdir)
    reg = telem.registry
    weight_store = WeightStore(workdir / "weights",
                               keep_last=keep_weight_epochs, metrics=reg,
                               tracer=telem.tracer)
    traj_store = TrajectoryStore(workdir / "trajectories", metrics=reg,
                                 tracer=telem.tracer)
    if not resume:
        # a reused workdir's previous-run epochs would out-number the fresh
        # learner's: actors adopt the stale newest adapter, every batch
        # drops with negative lag, and the driver spins to max_ticks —
        # fresh runs start from clean stores (pass resume=True to continue)
        weight_store.truncate_above(-1)
        traj_store.clear()
    # explicit tracer pass-through: a RunTelemetry built with trace=... (or
    # AGILERL_TPU_TRACE) traces the batch lifecycle — rollout → trajectory
    # publish → learner consume → learn → weight publish → actor adoption —
    # even when several runs coexist in one process (the process-default
    # tracer only covers the most recent run)
    learner = LearnerPod(
        agent, weight_store, traj_store,
        max_staleness_epochs=max_staleness_epochs, rho_clip=rho_clip,
        importance_correction=importance_correction, metrics=reg,
        plan=plan, mesh=mesh, tracer=telem.tracer)
    rollout = RolloutPod(
        actor_agent if actor_agent is not None else agent, env,
        weight_store, traj_store, metrics=reg, fleet=fleet,
        autoscaler=autoscaler, tracer=telem.tracer)
    fly = OnlineGRPOFlywheel(rollout, learner, metrics=reg,
                             telemetry_dir=telemetry_export_dir)

    fitnesses: List[float] = []
    done_epochs = 0
    n_logged = 0
    tokens_logged = 0

    def _counters():
        # the rollout pod's carried prompt batch (each env.step returns the
        # NEXT batch) belongs to the snapshot exactly as in the interleaved
        # loop — a resumed run that re-reset the env would skip one batch
        # and diverge from the uninterrupted prompt stream
        return {"done_epochs": done_epochs, "pop_fitnesses": [fitnesses],
                "prompts": rollout._prompts}

    try:
        if resilience is not None:
            resilience.attach(pop=[agent], telemetry=telem, env=env)
            if resume:
                restored = resilience.resume(_counters())
                done_epochs = int(restored["done_epochs"])
                fitnesses = list(restored["pop_fitnesses"][0])
                rollout._prompts = restored.get("prompts")
                # continue the epoch line where the snapshot left it:
                # purge post-snapshot weight epochs (or actors would adopt
                # the PRE-crash adapter and GC could collect the restored
                # re-publish) and pre-crash trajectory leftovers (wrong
                # epoch line, stale prompt stream, colliding seq numbers),
                # then re-publish so actors adopt the RESTORED adapter
                learner.epoch = done_epochs
                weight_store.truncate_above(done_epochs)
                traj_store.clear()
                learner.publish()
        start = time.time()
        while done_epochs < max_epochs:
            target = min(done_epochs + evaluation_interval, max_epochs)
            fly.run(target)
            done_epochs = learner.epoch
            for loss in learner.losses[n_logged:]:
                telem.log_step({"train/loss": loss, "agent": agent.index})
            n_logged = len(learner.losses)
            telem.step(tokens=learner.tokens_trained - tokens_logged,
                       agent_index=agent.index)
            tokens_logged = learner.tokens_trained
            fitness = agent.test(env)
            fitnesses.append(fitness)
            if verbose:
                recent = learner.losses[-1] if learner.losses else None
                print(f"=== flywheel epoch {done_epochs}: fitness "
                      f"{fitness:.3f} loss {recent} dropped_stale "
                      f"{len(learner.dropped_seqs)}")
            telem.record_eval([agent], [fitness])
            telem.log_step({"eval/mean_fitness": fitness})
            stop = max_reward is not None and fitness >= max_reward
            last_fitness = max_fitness([fitness])
            if resilience is not None:
                if resilience.step_boundary(
                    done_epochs, _counters(), pop=[agent],
                    fitness=last_fitness,
                ):
                    break
                if stop:
                    resilience.snapshot(done_epochs, _counters(),
                                        kind="final", fitness=last_fitness)
            if stop:
                break
        if verbose:
            print(f"flywheel finished {done_epochs} epochs in "
                  f"{time.time() - start:.1f}s (stalls: "
                  f"{int(reg.counter('flywheel/decode_stalls_total').value)},"
                  f" dropped stale: {len(learner.dropped_seqs)})")
    finally:
        # a crash escaping the loop must not leak the guard's process-wide
        # SIGTERM/SIGINT handlers (or an unflushed telemetry sink) into a
        # driver that catches the exception and keeps running
        if resilience is not None:
            resilience.close()
        if telemetry is None:
            telem.close()
    return agent, fitnesses
