"""DPO — direct preference optimisation (parity: agilerl/algorithms/dpo.py —
preference learn:180 over chosen/rejected pairs with prompt masks
(create_prompt_masks core/base.py:3087), sigmoid DPO loss _dpo_loss_standard:361
(+ the Liger fused path :409 — replaced by ops/fused_loss.py), implicit reward
_compute_implicit_reward:530). Same LoRA actor/reference adapter layout as GRPO.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from agilerl_tpu.ops import pallas_enabled

from agilerl_tpu.algorithms.core.registry import (
    HyperparameterConfig,
    RLParameter,
)
from agilerl_tpu.algorithms.grpo import GRPO
from agilerl_tpu.llm import model as M


def default_hp_config() -> HyperparameterConfig:
    return HyperparameterConfig(
        lr=RLParameter(min=1e-8, max=1e-4, dtype=float),
        beta=RLParameter(min=0.01, max=1.0, dtype=float),
    )


class DPO(GRPO):
    def __init__(self, *args, beta: float = 0.1, label_smoothing: float = 0.0, **kwargs):
        kwargs.setdefault("hp_config", default_hp_config())
        super().__init__(*args, beta=beta, **kwargs)
        self.label_smoothing = float(label_smoothing)

    @property
    def init_dict(self) -> Dict[str, Any]:
        d = super().init_dict
        d["label_smoothing"] = self.label_smoothing
        return d

    # ------------------------------------------------------------------ #
    def _dpo_update_fn(self):
        config = self.model_config
        base = self.base_params
        tx = self.optimizer.tx
        smooth = self.label_smoothing

        # fused Pallas head + flash attention on TPU — both have custom VJPs,
        # so the differentiable DPO loss uses them too (Liger parity: dpo.py:409)
        use_pallas = pallas_enabled()

        def seq_logprob(lora, ids, mask, loss_mask):
            lp = M.token_logprobs(config, base, ids, attention_mask=mask, lora=lora,
                                  use_pallas=use_pallas, flash=use_pallas)
            return (lp * loss_mask).sum(axis=-1)

        @jax.jit
        def update(lora, ref_lora, opt_state, batch, beta):
            ref_c = seq_logprob(
                ref_lora, batch["chosen_ids"], batch["chosen_mask"],
                batch["chosen_loss_mask"],
            )
            ref_r = seq_logprob(
                ref_lora, batch["rejected_ids"], batch["rejected_mask"],
                batch["rejected_loss_mask"],
            )

            def loss_fn(lo):
                pol_c = seq_logprob(
                    lo, batch["chosen_ids"], batch["chosen_mask"],
                    batch["chosen_loss_mask"],
                )
                pol_r = seq_logprob(
                    lo, batch["rejected_ids"], batch["rejected_mask"],
                    batch["rejected_loss_mask"],
                )
                logits = beta * ((pol_c - ref_c) - (pol_r - ref_r))
                # sigmoid DPO loss with optional label smoothing (parity :361);
                # logits IS the implicit reward margin (parity:
                # _compute_implicit_reward:530)
                loss = (
                    -jax.nn.log_sigmoid(logits) * (1 - smooth)
                    - jax.nn.log_sigmoid(-logits) * smooth
                ).mean()
                acc = (logits > 0).astype(jnp.float32).mean()
                return loss, (acc, logits.mean())

            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(lora)
            updates, opt_state = tx.update(grads, opt_state, lora)
            lora = optax.apply_updates(lora, updates)
            return lora, opt_state, loss, aux

        return update

    def learn(self, experiences: Dict[str, np.ndarray]) -> Tuple[float, float]:
        """experiences: the PreferenceGym.reset() batch dict
        (parity: dpo.py:180). Returns (loss, preference accuracy)."""
        batch = {k: jnp.asarray(v) for k, v in experiences.items()}
        update = self.jit_fn("dpo_update", self._dpo_update_fn)
        lora, opt_state, loss, (acc, margin) = update(
            self.actor.params, self.reference.params, self.optimizer.opt_state,
            batch, jnp.float32(self.beta),
        )
        if not np.isfinite(float(loss)):
            raise RuntimeError(f"Non-finite DPO loss {float(loss)}")
        self.actor.params = lora
        self.optimizer.opt_state = opt_state
        return float(loss), float(acc)

    def test(self, env) -> float:
        """Preference accuracy on the FULL eval split (parity: dpo.py test —
        the reference iterates its whole test loader) — runs through the
        shared jitted logprob fn (fused/flash fast paths on TPU)."""
        logprobs = self.jit_fn("logprobs", self._logprob_fn)

        def seq_lp(lora, ids, mask, loss_mask):
            return (logprobs(lora, ids, mask) * loss_mask).sum(axis=-1)

        batches = env.eval_batches() if hasattr(env, "eval_batches") else [
            env.reset(eval_mode=True)
        ]
        correct, total = 0, 0
        for raw in batches:
            batch = {k: jnp.asarray(v) for k, v in raw.items()}
            pol_c = seq_lp(self.actor.params, batch["chosen_ids"],
                           batch["chosen_mask"], batch["chosen_loss_mask"])
            pol_r = seq_lp(self.actor.params, batch["rejected_ids"],
                           batch["rejected_mask"], batch["rejected_loss_mask"])
            ref_c = seq_lp(self.reference.params, batch["chosen_ids"],
                           batch["chosen_mask"], batch["chosen_loss_mask"])
            ref_r = seq_lp(self.reference.params, batch["rejected_ids"],
                           batch["rejected_mask"], batch["rejected_loss_mask"])
            margin = (pol_c - ref_c) - (pol_r - ref_r)
            correct += int((margin > 0).sum())
            total += int(margin.shape[0])
        fitness = correct / max(total, 1)
        self.fitness.append(fitness)
        return fitness
