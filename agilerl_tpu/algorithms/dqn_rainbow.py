"""Rainbow DQN (parity: agilerl/algorithms/dqn_rainbow.py — RainbowDQN:?,
C51 categorical projection loss _dqn_loss:284, PER + n-step fusion in learn:369
(combined 1-step & n-step losses, returns new priorities), noisy-net exploration
instead of epsilon-greedy).

TPU-first: the categorical projection is fully vectorised (scatter-add via
segment-sum-free index arithmetic), and the whole update — double-DQN action
selection, projection, cross-entropy, PER-weighted mean, optax step, soft target
update, priority computation — is one jitted function.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from agilerl_tpu.algorithms.core.base import RLAlgorithm
from agilerl_tpu.algorithms.core.optimizer import OptimizerWrapper
from agilerl_tpu.algorithms.core.registry import (
    HyperparameterConfig,
    NetworkGroup,
    OptimizerConfig,
    RLParameter,
)
from agilerl_tpu.networks.q_networks import RainbowQNetwork


def default_hp_config() -> HyperparameterConfig:
    return HyperparameterConfig(
        lr=RLParameter(min=1e-5, max=1e-2, dtype=float),
        batch_size=RLParameter(min=8, max=512, dtype=int),
        learn_step=RLParameter(min=1, max=16, dtype=int),
    )


def categorical_projection(
    next_dist: jax.Array,  # [B, atoms] probabilities of chosen next action
    reward: jax.Array,  # [B]
    done: jax.Array,  # [B]
    gamma: float | jax.Array,
    support: jax.Array,  # [atoms]
    v_min: float,
    v_max: float,
) -> jax.Array:
    """Project the Bellman-updated atom distribution back onto the fixed support
    (the C51 projection), batched with pure vector ops."""
    num_atoms = support.shape[0]
    delta_z = (v_max - v_min) / (num_atoms - 1)
    tz = reward[:, None] + gamma * (1.0 - done[:, None]) * support[None, :]
    tz = jnp.clip(tz, v_min, v_max)
    b = (tz - v_min) / delta_z  # [B, atoms]
    lower = jnp.floor(b).astype(jnp.int32)
    upper = jnp.ceil(b).astype(jnp.int32)
    # when b is integral, put full mass on lower
    eq = (upper == lower).astype(jnp.float32)
    w_lower = (upper.astype(jnp.float32) - b) + eq
    w_upper = b - lower.astype(jnp.float32)
    proj = jnp.zeros_like(next_dist)
    batch_idx = jnp.arange(next_dist.shape[0])[:, None]
    proj = proj.at[batch_idx, lower].add(next_dist * w_lower)
    proj = proj.at[batch_idx, jnp.clip(upper, 0, num_atoms - 1)].add(next_dist * w_upper)
    return proj


class RainbowDQN(RLAlgorithm):
    #: learn_from_buffer supports PER sampling + in-dispatch priority
    #: write-back (the training loop gates the fused path on this)
    supports_fused_per = True

    def __init__(
        self,
        observation_space,
        action_space,
        index: int = 0,
        hp_config: Optional[HyperparameterConfig] = None,
        net_config: Optional[Dict[str, Any]] = None,
        batch_size: int = 64,
        lr: float = 1e-4,
        learn_step: int = 5,
        gamma: float = 0.99,
        tau: float = 1e-3,
        beta: float = 0.4,
        prior_eps: float = 1e-6,
        num_atoms: int = 51,
        v_min: float = -100.0,
        v_max: float = 100.0,
        n_step: int = 3,
        noise_std: float = 0.5,
        **kwargs,
    ):
        super().__init__(
            observation_space, action_space, index=index,
            hp_config=hp_config or default_hp_config(), **kwargs,
        )
        self.batch_size = int(batch_size)
        self.lr = float(lr)
        self.learn_step = int(learn_step)
        self.gamma = float(gamma)
        self.tau = float(tau)
        self.beta = float(beta)
        self.prior_eps = float(prior_eps)
        self.num_atoms = int(num_atoms)
        self.v_min = float(v_min)
        self.v_max = float(v_max)
        self.n_step = int(n_step)
        self.noise_std = float(noise_std)
        self.net_config = dict(net_config or {})

        self.actor = RainbowQNetwork(
            observation_space, action_space, num_atoms=num_atoms, v_min=v_min,
            v_max=v_max, noise_std=noise_std, key=self.next_key(), **self.net_config,
        )
        self.actor_target = self.actor.clone()
        self.optimizer = OptimizerWrapper(optimizer="adam", lr=self.lr)
        self.register_network_group(
            NetworkGroup(eval="actor", shared="actor_target", policy=True)
        )
        self.register_optimizer(
            OptimizerConfig(name="optimizer", networks=["actor"], lr="lr")
        )
        self.finalize_registry()

    @property
    def init_dict(self) -> Dict[str, Any]:
        return {
            "observation_space": self.observation_space,
            "action_space": self.action_space,
            "index": self.index,
            "net_config": self.net_config,
            "batch_size": self.batch_size,
            "lr": self.lr,
            "learn_step": self.learn_step,
            "gamma": self.gamma,
            "tau": self.tau,
            "beta": self.beta,
            "prior_eps": self.prior_eps,
            "num_atoms": self.num_atoms,
            "v_min": self.v_min,
            "v_max": self.v_max,
            "n_step": self.n_step,
            "noise_std": self.noise_std,
        }

    # ------------------------------------------------------------------ #
    def _act_fn(self):
        config = self.actor.config

        @jax.jit
        def act(params, obs, key, action_mask):
            # noisy-net exploration: fresh noise each call (parity: noisy resets)
            q = RainbowQNetwork.apply(config, params, obs, key=key)
            if action_mask is not None:
                q = jnp.where(action_mask.astype(bool), q, -1e8)
            return jnp.argmax(q, axis=-1)

        return act

    def get_action(
        self, obs, epsilon: float = 0.0, action_mask=None, training: bool = True,
        **kwargs,
    ) -> np.ndarray:
        """epsilon is accepted for train-loop compatibility but ignored —
        exploration comes from the noisy nets (parity: the reference's Rainbow
        also takes the loop's epsilon and relies on noise instead)."""
        from agilerl_tpu.algorithms.dqn import _is_single

        obs = self.preprocess_observation(obs)
        single = _is_single(obs, self.observation_space)
        if single:
            obs = jax.tree_util.tree_map(lambda x: x[None], obs)
        mask = None if action_mask is None else jnp.asarray(action_mask)
        act = self.jit_fn("act" if mask is None else "act_masked", self._act_fn)
        key = self.next_key() if training else None
        actions = np.asarray(act(self.actor.params, obs, key, mask))
        return actions[0] if single else actions

    # ------------------------------------------------------------------ #
    def _loss_terms(self, config, params, tparams, batch, gamma, key):
        """Per-sample categorical cross-entropy loss (C51 + double selection)."""
        obs = batch["obs"]
        action = batch["action"].astype(jnp.int32)
        reward = batch["reward"].astype(jnp.float32)
        done = batch["done"].astype(jnp.float32)
        next_obs = batch["next_obs"]
        support = jnp.linspace(config.v_min, config.v_max, config.num_atoms)

        k1, k2, k3 = jax.random.split(key, 3)
        # double-DQN: choose a* online, evaluate with target
        q_online_next = RainbowQNetwork.apply(config, params, next_obs, key=k1)
        next_action = jnp.argmax(q_online_next, axis=-1)
        logp_target = RainbowQNetwork.apply_dist(config, tparams, next_obs, key=k2)
        next_dist = jnp.exp(logp_target)[
            jnp.arange(next_action.shape[0]), next_action
        ]  # [B, atoms]
        proj = categorical_projection(
            next_dist, reward, done, gamma, support, config.v_min, config.v_max
        )
        logp = RainbowQNetwork.apply_dist(config, params, obs, key=k3)
        logp_a = logp[jnp.arange(action.shape[0]), action]  # [B, atoms]
        return -jnp.sum(jax.lax.stop_gradient(proj) * logp_a, axis=-1)  # [B]

    def _train_core_fn(self):
        """Un-jitted C51 update — jitted standalone by ``_train_fn`` and
        inlined into the fused sample+learn dispatch."""
        config = self.actor.config
        tx = self.optimizer.tx
        use_n_step = self.n_step > 1
        loss_terms = self._loss_terms

        def train_step(params, tparams, opt_state, batch, weights, n_batch, gamma, tau, key):
            k1, k2 = jax.random.split(key)

            def loss_fn(p):
                elementwise = loss_terms(config, p, tparams, batch, gamma, k1)
                if use_n_step and n_batch is not None:
                    elementwise_n = loss_terms(
                        config, p, tparams, n_batch, gamma ** config_n_step, k2
                    )
                    elementwise = elementwise + elementwise_n
                loss = jnp.mean(elementwise * weights)
                return loss, elementwise

            (loss, elementwise), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            tparams = jax.tree_util.tree_map(
                lambda t, p: (1.0 - tau) * t + tau * p, tparams, params
            )
            return params, tparams, opt_state, loss, elementwise

        config_n_step = self.n_step
        return train_step

    def _train_fn(self):
        return jax.jit(self._train_core_fn())

    def _fused_learn_fn(self, per: bool, paired: bool):
        """sample + paired n-step gather + preprocess + C51 update + PER
        priority write-back, all in ONE jit (docs/performance.md)."""
        import functools

        from agilerl_tpu.algorithms.core import fused as F

        core = self._train_core_fn()
        obs_space = self.observation_space
        prior_eps = self.prior_eps

        if per:

            @functools.partial(
                jax.jit, donate_argnums=(0, 1, 2, 3), static_argnames=("batch_size",)
            )
            def fused_per(params, tparams, opt_state, per_state, nstep_buf,
                          key, gamma, tau, alpha, beta, batch_size):
                ks, kl = jax.random.split(key)
                batch, idx, weights = F.per_sample(per_state, ks, batch_size, beta)
                n_batch = None
                if paired:
                    n_batch = F.preprocess_batch(
                        dict(F.gather_paired(nstep_buf, idx)), obs_space
                    )
                batch = F.preprocess_batch(dict(batch), obs_space)
                params, tparams, opt_state, loss, elementwise = core(
                    params, tparams, opt_state, batch, weights, n_batch,
                    gamma, tau, kl,
                )
                per_state = F.per_write_back(
                    per_state, idx, elementwise + prior_eps, alpha
                )
                return params, tparams, opt_state, per_state, loss

            return fused_per

        @functools.partial(
            jax.jit, donate_argnums=(0, 1, 2), static_argnames=("batch_size",)
        )
        def fused(params, tparams, opt_state, buf_state, nstep_buf, key,
                  gamma, tau, batch_size):
            ks, kl = jax.random.split(key)
            batch, idx, weights = F.uniform_sample(buf_state, ks, batch_size)
            n_batch = None
            if paired:
                n_batch = F.preprocess_batch(
                    dict(F.gather_paired(nstep_buf, idx)), obs_space
                )
            batch = F.preprocess_batch(dict(batch), obs_space)
            params, tparams, opt_state, loss, _ = core(
                params, tparams, opt_state, batch, weights, n_batch,
                gamma, tau, kl,
            )
            return params, tparams, opt_state, loss

        return fused

    def learn_from_buffer(self, memory, n_step_memory=None, key=None,
                          beta: Optional[float] = None):
        """One fused sample+learn dispatch, with the paired n-step batch
        gathered at the SAME ring indices inside the jit and PER priorities
        written back in the same dispatch. Returns the loss as a device
        array (sync-free hot path)."""
        from agilerl_tpu.algorithms.core import fused as F

        state, nstep_buf, per = F.resolve_states(memory, n_step_memory)
        paired = nstep_buf is not None
        if key is None:
            key = self.next_key()
        if beta is None:
            beta = self.beta
        name = f"fused_learn{'_per' if per else ''}{'_nstep' if paired else ''}"
        fn = self.jit_fn(
            name,
            lambda: self._fused_learn_fn(per, paired),
            static_key=(self.actor.config, str(self.observation_space),
                        per, paired, self.n_step, self.prior_eps,
                        self.optimizer.optimizer_name,
                        self.optimizer.max_grad_norm),
        )
        if per:
            params, tparams, opt_state, per_state, loss = fn(
                self.actor.params, self.actor_target.params,
                self.optimizer.opt_state, state, nstep_buf, key,
                jnp.float32(self.gamma), jnp.float32(self.tau),
                jnp.float32(memory.alpha), jnp.float32(beta),
                batch_size=self.batch_size,
            )
            memory.per_state = per_state
        else:
            params, tparams, opt_state, loss = fn(
                self.actor.params, self.actor_target.params,
                self.optimizer.opt_state, state, nstep_buf, key,
                jnp.float32(self.gamma), jnp.float32(self.tau),
                batch_size=self.batch_size,
            )
        self.actor.params = params
        self.actor_target.params = tparams
        self.optimizer.opt_state = opt_state
        return loss

    def learn(self, experiences) -> Tuple[float, Optional[np.ndarray]]:
        """experiences: batch dict (uniform), or (batch, idxs, weights) for PER,
        or (batch, idxs, weights, n_batch) with the n-step fused batch
        (parity: learn:369). Returns (loss, new_priorities)."""
        n_batch = None
        idxs = None
        if isinstance(experiences, tuple):
            if len(experiences) == 4:
                batch, idxs, weights, n_batch = experiences
            else:
                batch, idxs, weights = experiences
            weights = jnp.asarray(weights)
        else:
            batch = experiences
            weights = jnp.ones_like(jnp.asarray(batch["reward"], jnp.float32))
        batch = dict(batch)
        batch["obs"] = self.preprocess_observation(batch["obs"])
        batch["next_obs"] = self.preprocess_observation(batch["next_obs"])
        if n_batch is not None:
            n_batch = dict(n_batch)
            n_batch["obs"] = self.preprocess_observation(n_batch["obs"])
            n_batch["next_obs"] = self.preprocess_observation(n_batch["next_obs"])

        train_step = self.jit_fn(
            "train" if n_batch is None else "train_nstep", self._train_fn
        )
        params, tparams, opt_state, loss, elementwise = train_step(
            self.actor.params, self.actor_target.params, self.optimizer.opt_state,
            batch, weights, n_batch, jnp.float32(self.gamma), jnp.float32(self.tau),
            self.next_key(),
        )
        self.actor.params = params
        self.actor_target.params = tparams
        self.optimizer.opt_state = opt_state
        new_priorities = None
        if idxs is not None:
            new_priorities = np.asarray(elementwise) + self.prior_eps
        return float(loss), new_priorities
