"""CQN — conservative Q-learning for offline RL on discrete actions
(parity: agilerl/algorithms/cqn.py — CQN:?, learn:216; DQN-style TD backup plus
the CQL regulariser logsumexp(Q(s,·)) - Q(s,a) that penalises OOD actions).
"""

from __future__ import annotations

import functools

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import optax

from agilerl_tpu.algorithms.dqn import DQN
from agilerl_tpu.networks.q_networks import QNetwork


class CQN(DQN):
    def __init__(self, observation_space, action_space, cql_alpha: float = 1.0, **kwargs):
        self.cql_alpha = float(cql_alpha)
        super().__init__(observation_space, action_space, **kwargs)

    @property
    def init_dict(self) -> Dict:
        d = super().init_dict
        d["cql_alpha"] = self.cql_alpha
        return d

    def _train_fn(self):
        config = self.actor.config
        tx = self.optimizer.tx
        double = self.double
        cql_alpha = self.cql_alpha

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
        def train_step(params, target_params, opt_state, batch, weights, gamma, tau):
            obs, action = batch["obs"], batch["action"].astype(jnp.int32)
            reward = batch["reward"].astype(jnp.float32)
            done = batch["done"].astype(jnp.float32)
            next_obs = batch["next_obs"]

            q_next_t = QNetwork.apply(config, target_params, next_obs)
            if double:
                next_a = jnp.argmax(QNetwork.apply(config, params, next_obs), axis=-1)
                q_next = jnp.take_along_axis(q_next_t, next_a[..., None], axis=-1)[..., 0]
            else:
                q_next = jnp.max(q_next_t, axis=-1)
            target = reward + gamma * (1.0 - done) * q_next

            def loss_fn(p):
                q = QNetwork.apply(config, p, obs)
                q_sel = jnp.take_along_axis(q, action[..., None], axis=-1)[..., 0]
                td_err = q_sel - jax.lax.stop_gradient(target)
                td = jnp.mean(weights * jnp.square(td_err))
                # conservative penalty: push down logsumexp, push up data actions
                cql = jnp.mean(jax.scipy.special.logsumexp(q, axis=-1) - q_sel)
                return td + cql_alpha * cql, jnp.abs(td_err)

            (loss, td_abs), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            target_params = jax.tree_util.tree_map(
                lambda t, p: (1.0 - tau) * t + tau * p, target_params, params
            )
            return params, target_params, opt_state, loss, td_abs

        return train_step
