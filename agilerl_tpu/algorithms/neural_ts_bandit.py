"""NeuralTS contextual bandit (parity: agilerl/algorithms/neural_ts_bandit.py —
NeuralTS:?, learn:258; Thompson sampling: per-arm reward sampled from
N(f(x_a), nu * g^T U^-1 g) with the diagonal design-matrix approximation).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from agilerl_tpu.algorithms.neural_ucb_bandit import NeuralUCB
from agilerl_tpu.networks.base import EvolvableNetwork


class NeuralTS(NeuralUCB):
    def _score_fn(self):
        config = self.actor.config
        lamb = self.lamb

        def f(params, x):
            return EvolvableNetwork.apply(config, params, x[None])[0, 0]

        @jax.jit
        def score(params, U, context, nu, key):
            values = jax.vmap(lambda x: f(params, x))(context)
            grads = jax.vmap(lambda x: jax.grad(f)(params, x))(context)
            var = jax.vmap(
                lambda g: lamb * sum(
                    jnp.sum(gl * gl / ul)
                    for gl, ul in zip(
                        jax.tree_util.tree_leaves(g), jax.tree_util.tree_leaves(U)
                    )
                ),
                in_axes=0,
            )(grads)
            sigma = jnp.sqrt(jnp.maximum(var, 1e-12))
            sampled = values + nu * sigma * jax.random.normal(key, values.shape)
            arm = jnp.argmax(sampled)
            chosen_g = jax.tree_util.tree_map(lambda g: g[arm], grads)
            new_U = jax.tree_util.tree_map(lambda u, g: u + g * g, U, chosen_g)
            return arm, new_U

        return score

    def get_action(self, context, training: bool = True, **kw) -> np.ndarray:
        context = self.preprocess_observation(np.asarray(context))
        if not training:
            greedy = self.jit_fn("greedy", self._greedy_fn)
            return np.asarray(greedy(self.actor.params, context))
        score = self.jit_fn("score", self._score_fn)
        arm, new_U = score(self.actor.params, self.U, context,
                           jnp.float32(self.gamma), self.next_key())
        self.U = new_U
        return np.asarray(arm)
