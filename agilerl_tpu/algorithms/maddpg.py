"""MADDPG (parity: agilerl/algorithms/maddpg.py — per-agent actors + centralized
critics over all obs+actions, Gumbel-softmax for discrete actions, per-agent
learn loop learn:571/_learn_individual:630, OU exploration; sub-agent
architecture-mutation sync handled by the HPO engine, hpo/mutation.py:887).

TPU-first: ALL agents' critic and actor updates are fused into ONE jitted
function (a static python loop over agent ids inside the trace), so a learn call
is a single XLA program regardless of agent count.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from gymnasium import spaces

from agilerl_tpu.algorithms.core.base import MultiAgentRLAlgorithm
from agilerl_tpu.algorithms.core.optimizer import OptimizerWrapper
from agilerl_tpu.algorithms.core.registry import (
    HyperparameterConfig,
    NetworkGroup,
    OptimizerConfig,
    RLParameter,
)
from agilerl_tpu.networks.base import EvolvableNetwork
from agilerl_tpu.utils.spaces import action_dim, obs_dim, preprocess_observation


def default_hp_config() -> HyperparameterConfig:
    return HyperparameterConfig(
        lr_actor=RLParameter(min=1e-5, max=1e-2, dtype=float),
        lr_critic=RLParameter(min=1e-5, max=1e-2, dtype=float),
        batch_size=RLParameter(min=8, max=512, dtype=int),
        learn_step=RLParameter(min=1, max=16, dtype=int),
    )


def gumbel_softmax(logits: jax.Array, key: jax.Array, tau: float = 1.0, hard: bool = True):
    """Gumbel-softmax sampling (parity: modules/custom_components.py:10)."""
    g = -jnp.log(-jnp.log(jax.random.uniform(key, logits.shape, minval=1e-10) + 1e-10))
    y = jax.nn.softmax((logits + g) / tau, axis=-1)
    if hard:
        y_hard = jax.nn.one_hot(jnp.argmax(y, axis=-1), logits.shape[-1])
        y = y_hard + y - jax.lax.stop_gradient(y)
    return y


def flatten_ma_obs(obs_spaces, agent_ids, obs):
    """Centralized-critic obs input: per-agent preprocessed obs flattened and
    concatenated in agent order. Single source of truth for the critic input
    layout (shared by the train steps and critic_values)."""
    outs = []
    for aid in agent_ids:
        o = preprocess_observation(obs_spaces[aid], obs[aid])
        outs.append(o.reshape(o.shape[0], -1))
    return jnp.concatenate(outs, axis=-1)


def encode_ma_action(discrete, action_dims, aid, a):
    """Centralized-critic action encoding: one-hot for discrete agents, flat
    float vector otherwise."""
    if discrete[aid]:
        return jax.nn.one_hot(a.astype(jnp.int32), action_dims[aid])
    return a.astype(jnp.float32).reshape(a.shape[0], -1)


class MADDPG(MultiAgentRLAlgorithm):
    supports_activation_mutation = False

    def __init__(
        self,
        observation_spaces,
        action_spaces,
        agent_ids: Optional[List[str]] = None,
        index: int = 0,
        hp_config: Optional[HyperparameterConfig] = None,
        net_config: Optional[Dict[str, Any]] = None,
        batch_size: int = 64,
        lr_actor: float = 1e-4,
        lr_critic: float = 1e-3,
        learn_step: int = 5,
        gamma: float = 0.95,
        tau: float = 1e-2,
        expl_noise: float = 0.1,
        action_reg: float = 1e-3,
        **kwargs,
    ):
        super().__init__(
            observation_spaces, action_spaces, agent_ids=agent_ids, index=index,
            hp_config=hp_config or default_hp_config(), **kwargs,
        )
        self.batch_size = int(batch_size)
        self.lr_actor = float(lr_actor)
        self.lr_critic = float(lr_critic)
        self.learn_step = int(learn_step)
        self.gamma = float(gamma)
        self.tau = float(tau)
        self.expl_noise = float(expl_noise)
        self.action_reg = float(action_reg)
        self.net_config = dict(net_config or {})

        self.discrete = {
            aid: isinstance(self.action_spaces[aid], spaces.Discrete)
            for aid in self.agent_ids
        }
        self.action_dims = {aid: action_dim(self.action_spaces[aid]) for aid in self.agent_ids}
        total_obs = sum(obs_dim(self.observation_spaces[a]) for a in self.agent_ids)
        total_act = sum(self.action_dims.values())
        critic_space = spaces.Box(-np.inf, np.inf, (total_obs + total_act,), np.float32)

        # per-agent configs: MIXED/HETEROGENEOUS setups get the right encoder
        # family per space, with per-agent/group overrides honoured
        # (parity: base.py:1606 build_net_config)
        per_agent_cfg = self.build_net_config(self.net_config)
        # centralised critics see the flat joint vector: their configs come
        # from the ORIGINAL user encoder_config filtered against that space
        per_critic_cfg = self.build_critic_config(critic_space, self.net_config)
        self.actors: Dict[str, EvolvableNetwork] = {}
        self.critics: Dict[str, EvolvableNetwork] = {}
        for aid in self.agent_ids:
            a_cfg = per_agent_cfg[aid]
            head_cfg = dict(a_cfg.get("head_config", {}))
            if not self.discrete[aid]:
                head_cfg["output_activation"] = "Tanh"
            actor_kwargs = {**a_cfg, "head_config": head_cfg}
            self.actors[aid] = EvolvableNetwork(
                self.observation_spaces[aid], num_outputs=self.action_dims[aid],
                key=self.next_key(), **actor_kwargs,
            )
            self.critics[aid] = EvolvableNetwork(
                critic_space, num_outputs=1, key=self.next_key(),
                **per_critic_cfg[aid],
            )
        self.actor_targets = {aid: self.actors[aid].clone() for aid in self.agent_ids}
        self.critic_targets = {aid: self.critics[aid].clone() for aid in self.agent_ids}

        self.actor_optimizers = OptimizerWrapper(optimizer="adam", lr=self.lr_actor)
        self.critic_optimizers = OptimizerWrapper(optimizer="adam", lr=self.lr_critic)
        self.register_network_group(
            NetworkGroup(eval="actors", shared="actor_targets", policy=True, multiagent=True)
        )
        self.register_network_group(
            NetworkGroup(eval="critics", shared="critic_targets", multiagent=True)
        )
        self.register_optimizer(
            OptimizerConfig(name="actor_optimizers", networks=["actors"], lr="lr_actor")
        )
        self.register_optimizer(
            OptimizerConfig(name="critic_optimizers", networks=["critics"], lr="lr_critic")
        )
        self.finalize_registry()

    # ------------------------------------------------------------------ #
    @property
    def init_dict(self) -> Dict[str, Any]:
        return {
            "observation_spaces": self.observation_spaces,
            "action_spaces": self.action_spaces,
            "agent_ids": self.agent_ids,
            "index": self.index,
            "net_config": self.net_config,
            "batch_size": self.batch_size,
            "lr_actor": self.lr_actor,
            "lr_critic": self.lr_critic,
            "learn_step": self.learn_step,
            "gamma": self.gamma,
            "tau": self.tau,
            "expl_noise": self.expl_noise,
            "action_reg": self.action_reg,
        }

    def evolvable_attributes(self) -> Dict[str, Any]:
        return {
            "actors": self.actors,
            "actor_targets": self.actor_targets,
            "critics": self.critics,
            "critic_targets": self.critic_targets,
        }

    # -- acting ---------------------------------------------------------- #
    def _act_fn(self):
        actor_cfgs = {aid: self.actors[aid].config for aid in self.agent_ids}
        obs_spaces = self.observation_spaces
        discrete = self.discrete
        act_spaces = self.action_spaces
        agent_ids = tuple(self.agent_ids)

        @jax.jit
        def act(actor_params, obs, key, noise_scale, masks=None):
            out = {}
            for i, aid in enumerate(agent_ids):
                o = preprocess_observation(obs_spaces[aid], obs[aid])
                raw = EvolvableNetwork.apply(actor_cfgs[aid], actor_params[aid], o)
                k = jax.random.fold_in(key, i)
                if discrete[aid]:
                    if masks is not None and masks.get(aid) is not None:
                        # invalid-action mask from the env's info dict
                        raw = jnp.where(masks[aid].astype(bool), raw, -1e9)
                    sampled = jnp.argmax(gumbel_softmax(raw, k), axis=-1)
                    greedy = jnp.argmax(raw, axis=-1)
                    out[aid] = jnp.where(noise_scale > 0, sampled, greedy)
                else:
                    low = jnp.asarray(act_spaces[aid].low, jnp.float32)
                    high = jnp.asarray(act_spaces[aid].high, jnp.float32)
                    a = low + (raw + 1.0) * 0.5 * (high - low)
                    a = a + noise_scale * jax.random.normal(k, a.shape) * (high - low) * 0.5
                    out[aid] = jnp.clip(a, low, high)
            return out

        return act

    def get_action(
        self,
        obs: Dict[str, Any],
        training: bool = True,
        infos: Optional[Dict[str, Any]] = None,
        **kw,
    ) -> Dict[str, np.ndarray]:
        """infos (PettingZoo info dict) may carry per-agent "action_mask"
        (invalid discrete actions masked before sampling) and
        "env_defined_action" (env-dictated override) — parity:
        MADDPG.get_action + process_infos (reference maddpg.py:414)."""
        first = np.asarray(obs[self.agent_ids[0]])
        own_space = self.observation_spaces[self.agent_ids[0]]
        base_ndim = len(own_space.shape) if hasattr(own_space, "shape") and own_space.shape else 0
        single = first.ndim == base_ndim
        if single:
            obs = {a: np.asarray(o)[None] for a, o in obs.items()}
        act = self.jit_fn("act", self._act_fn)
        noise = jnp.float32(self.expl_noise if training else 0.0)
        actor_params = {a: self.actors[a].params for a in self.agent_ids}
        from agilerl_tpu.utils.utils import (
            apply_env_defined_actions,
            process_ma_infos,
        )

        masks, eda = process_ma_infos(infos, self.agent_ids)
        actions = act(actor_params, obs, self.next_key(), noise, masks)
        out = {a: np.asarray(v) for a, v in actions.items()}
        # off-policy: the EXECUTED action is what the buffer should hold, so
        # overriding after the policy ran is the correct semantics here
        out = apply_env_defined_actions(eda, out)
        if single:
            out = {a: v[0] for a, v in out.items()}
        return out

    def critic_values(self, obs: Dict[str, Any]) -> Dict[str, np.ndarray]:
        """Per-agent centralized-critic value Q_i(all obs, all current-policy
        actions) at the given batched dict obs — the probe-check surface
        (parity: the reference checks critic outputs directly,
        probe_envs_ma.py:1867)."""
        obs = {a: jnp.asarray(np.asarray(o)) for a, o in obs.items()}
        acts = self.get_action(obs, training=False)
        all_obs = flatten_ma_obs(self.observation_spaces, self.agent_ids, obs)
        enc = [
            encode_ma_action(
                self.discrete, self.action_dims, aid, jnp.asarray(acts[aid])
            )
            for aid in self.agent_ids
        ]
        q_in = jnp.concatenate([all_obs] + enc, axis=-1)
        return {
            aid: np.asarray(
                EvolvableNetwork.apply(
                    self.critics[aid].config, self.critics[aid].params, q_in
                )[..., 0]
            )
            for aid in self.agent_ids
        }

    # -- learning --------------------------------------------------------- #
    def _train_fn(self):
        agent_ids = tuple(self.agent_ids)
        actor_cfgs = {a: self.actors[a].config for a in agent_ids}
        critic_cfgs = {a: self.critics[a].config for a in agent_ids}
        obs_spaces = self.observation_spaces
        act_spaces = self.action_spaces
        discrete = self.discrete
        action_dims = self.action_dims
        a_tx = self.actor_optimizers.tx
        c_tx = self.critic_optimizers.tx
        action_reg = getattr(self, "action_reg", 1e-3)

        def flat_obs(obs):
            return flatten_ma_obs(obs_spaces, agent_ids, obs)

        def encode_action(aid, a):
            return encode_ma_action(discrete, action_dims, aid, a)

        def actor_out(aid, params, obs):
            o = preprocess_observation(obs_spaces[aid], obs[aid])
            raw = EvolvableNetwork.apply(actor_cfgs[aid], params, o)
            if discrete[aid]:
                return jax.nn.one_hot(jnp.argmax(raw, axis=-1), action_dims[aid])
            low = jnp.asarray(act_spaces[aid].low, jnp.float32)
            high = jnp.asarray(act_spaces[aid].high, jnp.float32)
            return low + (raw + 1.0) * 0.5 * (high - low)

        @jax.jit
        def train_step(actors, actor_ts, critics, critic_ts, a_opt, c_opt, batch, gamma, tau, key):
            obs, actions = batch["obs"], batch["action"]
            rewards, dones, next_obs = batch["reward"], batch["done"], batch["next_obs"]

            all_obs = flat_obs(obs)
            all_next_obs = flat_obs(next_obs)
            all_actions = jnp.concatenate(
                [encode_action(a, actions[a]) for a in agent_ids], axis=-1
            )
            next_target_actions = jnp.concatenate(
                [actor_out(a, actor_ts[a], next_obs) for a in agent_ids], axis=-1
            )
            critic_next_in = jnp.concatenate([all_next_obs, next_target_actions], axis=-1)
            critic_in = jnp.concatenate([all_obs, all_actions], axis=-1)

            losses = {}
            # --- critic updates (per agent, single trace) ---------------- #
            c_grads = {}
            for aid in agent_ids:
                q_next = EvolvableNetwork.apply(
                    critic_cfgs[aid], critic_ts[aid], critic_next_in
                )[..., 0]
                r = rewards[aid].astype(jnp.float32)
                d = dones[aid].astype(jnp.float32)
                target = jax.lax.stop_gradient(r + gamma * (1.0 - d) * q_next)

                def c_loss(p, target=target, aid=aid):
                    q = EvolvableNetwork.apply(critic_cfgs[aid], p, critic_in)[..., 0]
                    return jnp.mean(jnp.square(q - target))

                loss, grads = jax.value_and_grad(c_loss)(critics[aid])
                losses[f"critic_{aid}"] = loss
                c_grads[aid] = grads

            updates, c_opt = c_tx.update(c_grads, c_opt, critics)
            critics = optax.apply_updates(critics, updates)

            # --- actor updates ------------------------------------------- #
            a_grads = {}
            for i, aid in enumerate(agent_ids):

                def joint_q(aid, my_action):
                    parts = []
                    for other in agent_ids:
                        if other == aid:
                            parts.append(my_action)
                        else:
                            parts.append(encode_action(other, actions[other]))
                    joint = jnp.concatenate(parts, axis=-1)
                    q_in = jnp.concatenate([all_obs, joint], axis=-1)
                    return EvolvableNetwork.apply(
                        critic_cfgs[aid], critics[aid], q_in
                    )[..., 0]

                def a_loss(p, aid=aid, joint_q=joint_q):
                    o = preprocess_observation(obs_spaces[aid], obs[aid])
                    raw = EvolvableNetwork.apply(actor_cfgs[aid], p, o)
                    reg = action_reg * jnp.mean(jnp.square(raw))
                    if discrete[aid]:
                        # expected-Q policy loss: Σ_a π(a|o) Q(s, onehot(a)) —
                        # queries the critic ONLY at the one-hot vertices it
                        # was trained on. Differentiating THROUGH the critic at
                        # a vertex (gumbel straight-through) follows an
                        # interpolation the critic never fit, and its local
                        # gradient can point away from the better action
                        # (probe-grid finding: the actor saturated on the
                        # wrong action while the critic was vertex-perfect).
                        n = action_dims[aid]
                        probs = jax.nn.softmax(raw, axis=-1)  # [B, n]
                        B = raw.shape[0]
                        qs = jnp.stack(
                            [
                                joint_q(
                                    aid,
                                    jnp.broadcast_to(jnp.eye(n)[j], (B, n)),
                                )
                                for j in range(n)
                            ],
                            axis=-1,
                        )  # [B, n]
                        return -jnp.mean(
                            jnp.sum(probs * jax.lax.stop_gradient(qs), axis=-1)
                        ) + reg
                    low = jnp.asarray(act_spaces[aid].low, jnp.float32)
                    high = jnp.asarray(act_spaces[aid].high, jnp.float32)
                    my_action = low + (raw + 1.0) * 0.5 * (high - low)
                    return -jnp.mean(joint_q(aid, my_action)) + reg

                loss, grads = jax.value_and_grad(a_loss)(actors[aid])
                losses[f"actor_{aid}"] = loss
                a_grads[aid] = grads

            updates, a_opt = a_tx.update(a_grads, a_opt, actors)
            actors = optax.apply_updates(actors, updates)

            # --- soft target updates ------------------------------------- #
            actor_ts = jax.tree_util.tree_map(
                lambda t, p: (1 - tau) * t + tau * p, actor_ts, actors
            )
            critic_ts = jax.tree_util.tree_map(
                lambda t, p: (1 - tau) * t + tau * p, critic_ts, critics
            )
            mean_loss = sum(
                losses[f"critic_{a}"] for a in agent_ids
            ) / len(agent_ids)
            return actors, actor_ts, critics, critic_ts, a_opt, c_opt, mean_loss

        return train_step

    def learn(self, experiences: Dict[str, Dict[str, jax.Array]]) -> float:
        """experiences: dict with obs/action/reward/next_obs/done, each a dict
        keyed by agent id with [B, ...] leaves (parity: learn:571)."""
        train_step = self.jit_fn("train", self._train_fn)
        actors = {a: self.actors[a].params for a in self.agent_ids}
        actor_ts = {a: self.actor_targets[a].params for a in self.agent_ids}
        critics = {a: self.critics[a].params for a in self.agent_ids}
        critic_ts = {a: self.critic_targets[a].params for a in self.agent_ids}
        (actors, actor_ts, critics, critic_ts, a_opt, c_opt, loss) = train_step(
            actors, actor_ts, critics, critic_ts,
            self.actor_optimizers.opt_state, self.critic_optimizers.opt_state,
            experiences, jnp.float32(self.gamma), jnp.float32(self.tau), self.next_key(),
        )
        for a in self.agent_ids:
            self.actors[a].params = actors[a]
            self.actor_targets[a].params = actor_ts[a]
            self.critics[a].params = critics[a]
            self.critic_targets[a].params = critic_ts[a]
        self.actor_optimizers.opt_state = a_opt
        self.critic_optimizers.opt_state = c_opt
        return float(loss)
