"""DDPG (parity: agilerl/algorithms/ddpg.py — DDPG:?, OU/Gaussian action noise
action_noise:391, soft target updates, optional shared encoder
share_encoder_parameters:335).

TPU-first: critic TD step and actor policy-gradient step are one jitted fused
update; OU noise state is a device array threaded through get_action.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from agilerl_tpu.algorithms.core.base import RLAlgorithm
from agilerl_tpu.algorithms.core.optimizer import OptimizerWrapper
from agilerl_tpu.algorithms.core.registry import (
    HyperparameterConfig,
    NetworkGroup,
    OptimizerConfig,
    RLParameter,
)
from agilerl_tpu.networks.actors import DeterministicActor
from agilerl_tpu.networks.q_networks import ContinuousQNetwork


def default_hp_config() -> HyperparameterConfig:
    return HyperparameterConfig(
        lr_actor=RLParameter(min=1e-5, max=1e-2, dtype=float),
        lr_critic=RLParameter(min=1e-5, max=1e-2, dtype=float),
        batch_size=RLParameter(min=8, max=512, dtype=int),
        learn_step=RLParameter(min=1, max=16, dtype=int),
    )


class DDPG(RLAlgorithm):
    supports_activation_mutation = False
    #: learn_from_buffer is uniform-replay only (learn has no priority
    #: output) — the training loop falls back to the legacy path under PER
    supports_fused_per = False

    def __init__(
        self,
        observation_space,
        action_space,
        index: int = 0,
        hp_config: Optional[HyperparameterConfig] = None,
        net_config: Optional[Dict[str, Any]] = None,
        batch_size: int = 64,
        lr_actor: float = 1e-4,
        lr_critic: float = 1e-3,
        learn_step: int = 5,
        gamma: float = 0.99,
        tau: float = 1e-3,
        policy_freq: int = 2,
        O_U_noise: bool = True,
        expl_noise: float = 0.1,
        mean_noise: float = 0.0,
        theta: float = 0.15,
        dt: float = 1e-2,
        **kwargs,
    ):
        super().__init__(
            observation_space, action_space, index=index,
            hp_config=hp_config or default_hp_config(), **kwargs,
        )
        self.batch_size = int(batch_size)
        self.lr_actor = float(lr_actor)
        self.lr_critic = float(lr_critic)
        self.learn_step = int(learn_step)
        self.gamma = float(gamma)
        self.tau = float(tau)
        self.policy_freq = int(policy_freq)
        self.O_U_noise = bool(O_U_noise)
        self.expl_noise = float(expl_noise)
        self.mean_noise = float(mean_noise)
        self.theta = float(theta)
        self.dt = float(dt)
        self.net_config = dict(net_config or {})
        self._learn_counter = 0
        self._ou_state: Optional[jax.Array] = None

        self.actor = DeterministicActor(
            observation_space, action_space, key=self.next_key(), **self.net_config
        )
        self.actor_target = self.actor.clone()
        self.critic = ContinuousQNetwork(
            observation_space, action_space, key=self.next_key(), **self.net_config
        )
        self.critic_target = self.critic.clone()

        self.actor_optimizer = OptimizerWrapper(optimizer="adam", lr=self.lr_actor)
        self.critic_optimizer = OptimizerWrapper(optimizer="adam", lr=self.lr_critic)
        self.register_network_group(
            NetworkGroup(eval="actor", shared="actor_target", policy=True)
        )
        self.register_network_group(
            NetworkGroup(eval="critic", shared="critic_target")
        )
        self.register_optimizer(
            OptimizerConfig(name="actor_optimizer", networks=["actor"], lr="lr_actor")
        )
        self.register_optimizer(
            OptimizerConfig(name="critic_optimizer", networks=["critic"], lr="lr_critic")
        )
        self.finalize_registry()

    @property
    def init_dict(self) -> Dict[str, Any]:
        return {
            "observation_space": self.observation_space,
            "action_space": self.action_space,
            "index": self.index,
            "net_config": self.net_config,
            "batch_size": self.batch_size,
            "lr_actor": self.lr_actor,
            "lr_critic": self.lr_critic,
            "learn_step": self.learn_step,
            "gamma": self.gamma,
            "tau": self.tau,
            "policy_freq": self.policy_freq,
            "O_U_noise": self.O_U_noise,
            "expl_noise": self.expl_noise,
            "mean_noise": self.mean_noise,
            "theta": self.theta,
            "dt": self.dt,
        }

    # ------------------------------------------------------------------ #
    def action_noise(self, shape) -> np.ndarray:
        """OU or Gaussian exploration noise (parity: ddpg.py:391)."""
        if self.O_U_noise:
            if self._ou_state is None or self._ou_state.shape != shape:
                self._ou_state = jnp.zeros(shape)
            noise = jax.random.normal(self.next_key(), shape)
            self._ou_state = (
                self._ou_state
                + self.theta * (self.mean_noise - self._ou_state) * self.dt
                + self.expl_noise * jnp.sqrt(self.dt) * noise
            )
            return np.asarray(self._ou_state)
        return np.asarray(
            self.mean_noise + self.expl_noise * jax.random.normal(self.next_key(), shape)
        )

    def _act_fn(self):
        config = self.actor.config
        low = self.actor.action_low
        high = self.actor.action_high

        @jax.jit
        def act(params, obs):
            raw = DeterministicActor.apply(config, params, obs)
            return DeterministicActor.rescale(raw, low, high)

        return act

    def get_action(self, obs, training: bool = True, **kw) -> np.ndarray:
        from agilerl_tpu.algorithms.dqn import _is_single

        obs = self.preprocess_observation(obs)
        single = _is_single(obs, self.observation_space)
        if single:
            obs = jax.tree_util.tree_map(lambda x: x[None], obs)
        act = self.jit_fn("act", self._act_fn)
        action = np.asarray(act(self.actor.params, obs))
        if training:
            action = action + self.action_noise(action.shape)
        action = np.clip(
            action, self.action_space.low, self.action_space.high
        ).astype(np.float32)
        return action[0] if single else action

    # ------------------------------------------------------------------ #
    def _critic_core_fn(self):
        """Un-jitted critic TD step — jitted standalone by ``_critic_fn``
        and inlined into the fused sample+learn dispatch."""
        a_cfg = self.actor.config
        c_cfg = self.critic.config
        low, high = self.actor.action_low, self.actor.action_high
        tx = self.critic_optimizer.tx

        def critic_step(cparams, ct_params, at_params, opt_state, batch, gamma, tau):
            obs = batch["obs"]
            action = batch["action"].astype(jnp.float32)
            reward = batch["reward"].astype(jnp.float32)
            done = batch["done"].astype(jnp.float32)
            next_obs = batch["next_obs"]

            next_action = DeterministicActor.rescale(
                DeterministicActor.apply(a_cfg, at_params, next_obs), low, high
            )
            q_next = ContinuousQNetwork.apply(c_cfg, ct_params, next_obs, action=next_action)
            target = reward + gamma * (1.0 - done) * q_next

            def loss_fn(p):
                q = ContinuousQNetwork.apply(c_cfg, p, obs, action=action)
                return jnp.mean(jnp.square(q - jax.lax.stop_gradient(target)))

            loss, grads = jax.value_and_grad(loss_fn)(cparams)
            updates, opt_state = tx.update(grads, opt_state, cparams)
            cparams = optax.apply_updates(cparams, updates)
            ct_params = jax.tree_util.tree_map(
                lambda t, p: (1.0 - tau) * t + tau * p, ct_params, cparams
            )
            return cparams, ct_params, opt_state, loss

        return critic_step

    def _critic_fn(self):
        return jax.jit(self._critic_core_fn())

    def _actor_core_fn(self):
        a_cfg = self.actor.config
        c_cfg = self.critic.config
        low, high = self.actor.action_low, self.actor.action_high
        tx = self.actor_optimizer.tx

        def actor_step(aparams, at_params, cparams, opt_state, batch, tau):
            obs = batch["obs"]

            def loss_fn(p):
                action = DeterministicActor.rescale(
                    DeterministicActor.apply(a_cfg, p, obs), low, high
                )
                q = ContinuousQNetwork.apply(c_cfg, cparams, obs, action=action)
                return -jnp.mean(q)

            loss, grads = jax.value_and_grad(loss_fn)(aparams)
            updates, opt_state = tx.update(grads, opt_state, aparams)
            aparams = optax.apply_updates(aparams, updates)
            at_params = jax.tree_util.tree_map(
                lambda t, p: (1.0 - tau) * t + tau * p, at_params, aparams
            )
            return aparams, at_params, opt_state, loss

        return actor_step

    def _actor_fn(self):
        return jax.jit(self._actor_core_fn())

    def _fused_learn_fn(self):
        """Uniform sample + critic TD step + (policy_freq-gated) actor step
        as ONE jit. The actor cadence rides a traced bool through
        ``lax.cond`` so the cadence never recompiles
        (docs/performance.md)."""
        import functools

        from agilerl_tpu.algorithms.core import fused as F
        from agilerl_tpu.components.replay_buffer import _sample as _buffer_sample

        critic_core = self._critic_core_fn()
        actor_core = self._actor_core_fn()
        obs_space = self.observation_space

        @functools.partial(
            jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5),
            static_argnames=("batch_size",),
        )
        def fused(aparams, at_params, cparams, ct_params, a_opt, c_opt,
                  buf_state, key, gamma, tau, do_actor, batch_size):
            batch = F.preprocess_batch(
                dict(_buffer_sample(buf_state, key, batch_size)), obs_space
            )
            cparams, ct_params, c_opt, closs = critic_core(
                cparams, ct_params, at_params, c_opt, batch, gamma, tau
            )

            def run_actor(ops):
                ap, atp, ao = ops
                ap, atp, ao, _ = actor_core(ap, atp, cparams, ao, batch, tau)
                return ap, atp, ao

            aparams, at_params, a_opt = jax.lax.cond(
                do_actor, run_actor, lambda ops: ops,
                (aparams, at_params, a_opt),
            )
            return aparams, at_params, cparams, ct_params, a_opt, c_opt, closs

        return fused

    def _fused_static_key(self) -> tuple:
        """Everything the fused jit closes over, hashably — population
        members with identical architectures/action bounds share ONE
        compiled executable through the process-global jit cache."""
        import numpy as np

        return (
            self.actor.config, self.critic.config,
            str(self.observation_space),
            tuple(np.asarray(self.actor.action_low).ravel().tolist()),
            tuple(np.asarray(self.actor.action_high).ravel().tolist()),
            self.actor_optimizer.optimizer_name,
            self.actor_optimizer.max_grad_norm,
            self.critic_optimizer.optimizer_name,
            self.critic_optimizer.max_grad_norm,
        )

    def learn_from_buffer(self, memory, n_step_memory=None, key=None,
                          beta=None):
        """One fused sample+learn dispatch (uniform replay only — the
        DDPG/TD3 learn contract has no priority output, exactly like the
        legacy path). Returns the critic loss as a device array."""
        from agilerl_tpu.algorithms.core import fused as F

        state, _, per = F.resolve_states(memory, n_step_memory)
        if per:
            raise NotImplementedError(
                f"{type(self).__name__}.learn_from_buffer supports uniform "
                "replay only (no priority output to write back)"
            )
        if key is None:
            key = self.next_key()
        self._learn_counter += 1
        do_actor = self._learn_counter % self.policy_freq == 0
        fn = self.jit_fn("fused_learn", self._fused_learn_fn,
                         static_key=self._fused_static_key())
        aparams, at_params, cparams, ct_params, a_opt, c_opt, closs = fn(
            self.actor.params, self.actor_target.params,
            self.critic.params, self.critic_target.params,
            self.actor_optimizer.opt_state, self.critic_optimizer.opt_state,
            state, key, jnp.float32(self.gamma), jnp.float32(self.tau),
            jnp.bool_(do_actor), batch_size=self.batch_size,
        )
        self.actor.params = aparams
        self.actor_target.params = at_params
        self.critic.params = cparams
        self.critic_target.params = ct_params
        self.actor_optimizer.opt_state = a_opt
        self.critic_optimizer.opt_state = c_opt
        return closs

    def learn(self, experiences: Dict[str, jax.Array]) -> float:
        batch = dict(experiences)
        batch["obs"] = self.preprocess_observation(batch["obs"])
        batch["next_obs"] = self.preprocess_observation(batch["next_obs"])

        critic_step = self.jit_fn("critic", self._critic_fn)
        cparams, ct_params, c_opt, closs = critic_step(
            self.critic.params, self.critic_target.params, self.actor_target.params,
            self.critic_optimizer.opt_state, batch,
            jnp.float32(self.gamma), jnp.float32(self.tau),
        )
        self.critic.params = cparams
        self.critic_target.params = ct_params
        self.critic_optimizer.opt_state = c_opt

        self._learn_counter += 1
        if self._learn_counter % self.policy_freq == 0:
            actor_step = self.jit_fn("actor", self._actor_fn)
            aparams, at_params, a_opt, _ = actor_step(
                self.actor.params, self.actor_target.params, self.critic.params,
                self.actor_optimizer.opt_state, batch, jnp.float32(self.tau),
            )
            self.actor.params = aparams
            self.actor_target.params = at_params
            self.actor_optimizer.opt_state = a_opt
        return float(closs)
