"""IPPO — independent PPO with per-GROUP shared networks (parity:
agilerl/algorithms/ippo.py — homogeneous agents share one actor/critic per
group; grouped rollout learn _learn_individual:687).

TPU-first: each group's minibatch update is one jitted function; experiences
from all agents of a group are stacked into one batch so homogeneous agents
train as extra batch rows (free MXU utilisation).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from agilerl_tpu.algorithms.core.base import MultiAgentRLAlgorithm
from agilerl_tpu.algorithms.core.optimizer import OptimizerWrapper
from agilerl_tpu.algorithms.core.registry import (
    HyperparameterConfig,
    NetworkGroup,
    OptimizerConfig,
    RLParameter,
)
from agilerl_tpu.components.rollout_buffer import RolloutBuffer
from agilerl_tpu.vector import sanitize_ma_transition
from agilerl_tpu.networks import distributions as D
from agilerl_tpu.networks.actors import StochasticActor
from agilerl_tpu.networks.base import EvolvableNetwork
from agilerl_tpu.networks.value_networks import ValueNetwork
from agilerl_tpu.utils.spaces import preprocess_observation


def default_hp_config() -> HyperparameterConfig:
    return HyperparameterConfig(
        lr=RLParameter(min=1e-5, max=1e-2, dtype=float),
        batch_size=RLParameter(min=32, max=1024, dtype=int),
        learn_step=RLParameter(min=64, max=4096, dtype=int),
    )


class IPPO(MultiAgentRLAlgorithm):
    supports_activation_mutation = False

    def __init__(
        self,
        observation_spaces,
        action_spaces,
        agent_ids: Optional[List[str]] = None,
        index: int = 0,
        hp_config: Optional[HyperparameterConfig] = None,
        net_config: Optional[Dict[str, Any]] = None,
        batch_size: int = 64,
        lr: float = 3e-4,
        learn_step: int = 128,
        gamma: float = 0.99,
        gae_lambda: float = 0.95,
        clip_coef: float = 0.2,
        ent_coef: float = 0.01,
        vf_coef: float = 0.5,
        max_grad_norm: float = 0.5,
        update_epochs: int = 4,
        num_envs: int = 1,
        **kwargs,
    ):
        super().__init__(
            observation_spaces, action_spaces, agent_ids=agent_ids, index=index,
            hp_config=hp_config or default_hp_config(), **kwargs,
        )
        self.batch_size = int(batch_size)
        self.lr = float(lr)
        self.learn_step = int(learn_step)
        self.gamma = float(gamma)
        self.gae_lambda = float(gae_lambda)
        self.clip_coef = float(clip_coef)
        self.ent_coef = float(ent_coef)
        self.vf_coef = float(vf_coef)
        self.max_grad_norm = float(max_grad_norm)
        self.update_epochs = int(update_epochs)
        self.num_envs = int(num_envs)
        self.net_config = dict(net_config or {})

        # one actor/critic per GROUP (homogeneous agents share; parity:
        # ippo.py); MIXED setups get per-group configs with the encoder
        # family matched to each group's space (parity: base.py:1606)
        per_agent_cfg = self.build_net_config(self.net_config)
        self.actors: Dict[str, StochasticActor] = {}
        self.critics: Dict[str, ValueNetwork] = {}
        self.rollout_buffers: Dict[str, RolloutBuffer] = {}
        for gid, members in self.grouped_agents.items():
            rep = members[0]
            g_cfg = per_agent_cfg[rep]
            self.actors[gid] = StochasticActor(
                self.observation_spaces[rep], self.action_spaces[rep],
                key=self.next_key(), **g_cfg,
            )
            self.critics[gid] = ValueNetwork(
                self.observation_spaces[rep], key=self.next_key(), **g_cfg
            )
            # one buffer per agent-slot: stacked as extra env rows
            self.rollout_buffers[gid] = RolloutBuffer(
                capacity=self.learn_step,
                num_envs=self.num_envs * len(members),
                gamma=self.gamma,
                gae_lambda=self.gae_lambda,
            )

        self.optimizer = OptimizerWrapper(
            optimizer="adam", lr=self.lr, max_grad_norm=self.max_grad_norm
        )
        self.register_network_group(NetworkGroup(eval="actors", policy=True, multiagent=True))
        self.register_network_group(NetworkGroup(eval="critics", multiagent=True))
        self.register_optimizer(
            OptimizerConfig(name="optimizer", networks=["actors", "critics"], lr="lr")
        )
        self.finalize_registry()
        # one optax state PER GROUP: a single shared Adam state would keep
        # applying stale momentum to group A's params while group B trains
        # (review finding)
        self._init_group_opt_states()
        self._last_obs = None
        self._last_done = None

    def _group_params(self, gid: str):
        return {"actors": {gid: self.actors[gid].params},
                "critics": {gid: self.critics[gid].params}}

    def _init_group_opt_states(self) -> None:
        self.optimizer.opt_state = {
            gid: self.optimizer.tx.init(self._group_params(gid))
            for gid in self.grouped_agents
        }

    def reinit_optimizers(self) -> None:
        self._init_group_opt_states()

    @property
    def init_dict(self) -> Dict[str, Any]:
        return {
            "observation_spaces": self.observation_spaces,
            "action_spaces": self.action_spaces,
            "agent_ids": self.agent_ids,
            "index": self.index,
            "net_config": self.net_config,
            "batch_size": self.batch_size,
            "lr": self.lr,
            "learn_step": self.learn_step,
            "gamma": self.gamma,
            "gae_lambda": self.gae_lambda,
            "clip_coef": self.clip_coef,
            "ent_coef": self.ent_coef,
            "vf_coef": self.vf_coef,
            "update_epochs": self.update_epochs,
            "num_envs": self.num_envs,
        }

    def evolvable_attributes(self) -> Dict[str, Any]:
        return {"actors": self.actors, "critics": self.critics}

    # ------------------------------------------------------------------ #
    def _group_of(self, aid: str) -> str:
        return self.get_group_id(aid)

    def _act_fn(self):
        groups = {g: ms for g, ms in self.grouped_agents.items()}
        actor_cfgs = {g: self.actors[g].config for g in groups}
        critic_cfgs = {g: self.critics[g].config for g in groups}
        dist_cfgs = {g: self.actors[g].dist_config for g in groups}
        obs_spaces = self.observation_spaces

        @functools.partial(jax.jit, static_argnames=("deterministic",))
        def act(actor_params, critic_params, obs, key, deterministic=False,
                masks=None, forced=None):
            actions, logps, values = {}, {}, {}
            i = 0
            for gid, members in groups.items():
                for aid in members:
                    o = preprocess_observation(obs_spaces[aid], obs[aid])
                    logits = EvolvableNetwork.apply(actor_cfgs[gid], actor_params[gid], o)
                    dist_extra = actor_params[gid].get("dist")
                    mask = masks.get(aid) if masks is not None else None
                    k = jax.random.fold_in(key, i)
                    if deterministic:
                        a = D.mode(dist_cfgs[gid], logits, mask)
                    else:
                        a = D.sample(dist_cfgs[gid], logits, k, dist_extra, mask)
                    if forced is not None and aid in forced:
                        # env-defined actions resolve BEFORE the log-prob so
                        # the buffer stores the executed action's likelihood
                        f_vals, f_valid = forced[aid]
                        # collapse trailing unit dims so a [B, 1] force
                        # matches a [B] action instead of silently
                        # broadcasting to [B, B] (review finding)
                        fv, ok = f_vals, f_valid
                        while fv.ndim > a.ndim and fv.shape[-1] == 1:
                            fv, ok = fv[..., 0], ok[..., 0]
                        if fv.ndim > a.ndim:
                            raise ValueError(
                                f"env_defined_action for {aid!r} has shape "
                                f"{f_vals.shape} but the action is {a.shape}"
                            )
                        # element-wise valid resolves per COMPONENT — same
                        # semantics as apply_env_defined_actions
                        ok = ok.reshape(ok.shape + (1,) * (a.ndim - ok.ndim))
                        fv = fv.reshape(fv.shape + (1,) * (a.ndim - fv.ndim))
                        a = jnp.where(ok, fv.astype(a.dtype), a)
                    actions[aid] = a
                    logps[aid] = D.log_prob(dist_cfgs[gid], logits, a, dist_extra,
                                            mask=mask)
                    values[aid] = EvolvableNetwork.apply(
                        critic_cfgs[gid], critic_params[gid], o
                    )[..., 0]
                    i += 1
            return actions, logps, values

        return act

    def get_action(
        self,
        obs: Dict[str, Any],
        training: bool = True,
        infos: Optional[Dict[str, Any]] = None,
        **kw,
    ):
        """infos may carry per-agent "action_mask" (invalid actions masked in
        the policy distribution) and "env_defined_action" (env-dictated
        override) — parity: IPPO.get_action + process_infos."""
        first = np.asarray(obs[self.agent_ids[0]])
        own_space = self.observation_spaces[self.agent_ids[0]]
        base_ndim = len(own_space.shape) if own_space.shape else 0
        single = first.ndim == base_ndim
        if single:
            obs = {a: np.asarray(o)[None] for a, o in obs.items()}
        act = self.jit_fn("act", self._act_fn)
        actor_params = {g: self.actors[g].params for g in self.actors}
        critic_params = {g: self.critics[g].params for g in self.critics}
        from agilerl_tpu.utils.utils import (
            forced_action_arrays,
            process_ma_infos,
        )

        masks, eda = process_ma_infos(infos, self.agent_ids)
        batch = np.asarray(obs[self.agent_ids[0]]).shape[0]
        forced = forced_action_arrays(eda, self.agent_ids, batch,
                                      self.action_spaces)
        if forced is not None:
            forced = {a: (jnp.asarray(v), jnp.asarray(ok))
                      for a, (v, ok) in forced.items()}
        actions, logps, values = act(
            actor_params, critic_params, obs, self.next_key(),
            deterministic=not training, masks=masks, forced=forced,
        )
        self._cached_logps = {a: np.asarray(v) for a, v in logps.items()}
        self._cached_values = {a: np.asarray(v) for a, v in values.items()}
        # masks used this step (ones when absent) — buffered so learn()
        # recomputes log-probs/entropy on the SAME masked distribution
        # maskedness LATCHES the first time the env publishes any mask —
        # mask-free envs never pay the buffering/apply_mask cost, and once
        # latched every step caches a mask (ones fallback) so the buffer
        # schema stays stable (the rollout buffer ones-backfills rows from
        # before the latch)
        if masks is not None and not getattr(self, "_ma_masked", False):
            self._ma_masked = True
        self._cached_masks = {}
        if getattr(self, "_ma_masked", False):
            for a in self.agent_ids:
                dist_cfg = self.actors[self.get_group_id(a)].dist_config
                if dist_cfg.kind == "normal":
                    continue  # masks are a no-op for continuous heads
                # mask width is the head's logit width (sum(nvec) for
                # MultiDiscrete), so rollout-time and learn-time
                # distributions stay identical for every maskable kind
                width = D.head_output_dim(dist_cfg)
                if masks is not None and masks.get(a) is not None:
                    m = np.broadcast_to(np.asarray(masks[a]), (batch, width))
                else:
                    m = np.ones((batch, width), np.float32)
                self._cached_masks[a] = np.asarray(m, np.float32)
        out = {a: np.asarray(v) for a, v in actions.items()}
        if single:
            out = {a: v[0] for a, v in out.items()}
        return out

    # ------------------------------------------------------------------ #
    def collect_rollouts(self, env, n_steps: Optional[int] = None) -> float:
        """Step the parallel env, stacking each group's agents as extra env
        rows in that group's rollout buffer."""
        n_steps = n_steps or self.learn_step
        if self._last_obs is None:
            obs, info = env.reset()
            self._last_obs = obs
            self._last_info = info
        obs = self._last_obs
        info = getattr(self, "_last_info", None)
        total_r = 0.0
        for _ in range(n_steps):
            actions = self.get_action(obs, infos=info)
            next_obs, rew, term, trunc, info = env.step(actions)
            self._last_info = info
            # dead/inactive agents arrive as NaN placeholders from the async
            # vec env — zero them before buffering (AsyncAgentsWrapper is the
            # NaN-aware path; the plain loop must stay finite)
            next_obs, rew = sanitize_ma_transition(next_obs, rew)
            # time-limit bootstrapping per agent at truncation boundaries
            final = info.get("final_obs") if isinstance(info, dict) else None
            if final is not None:
                final, _ = sanitize_ma_transition(final, {})
                rew = dict(rew)
                for aid in self.agent_ids:
                    t_arr = np.asarray(trunc[aid], bool)
                    if t_arr.any():
                        gid = self.get_group_id(aid)
                        o = preprocess_observation(self.observation_spaces[aid], final[aid])
                        v = np.asarray(EvolvableNetwork.apply(
                            self.critics[gid].config, self.critics[gid].params, o
                        )[..., 0])
                        # np.where, not v * t_arr: nan * False == nan, so a
                        # NaN critic value at a dead row would re-poison the
                        # sanitized reward (review finding)
                        rew[aid] = np.asarray(rew[aid], np.float32) + np.where(
                            t_arr, self.gamma * v, 0.0
                        ).astype(np.float32)
            for gid, members in self.grouped_agents.items():
                g_obs = np.concatenate([np.asarray(obs[a]) for a in members], axis=0)
                g_act = np.concatenate([np.asarray(actions[a]) for a in members], axis=0)
                g_rew = np.concatenate([np.asarray(rew[a], np.float32) for a in members], axis=0)
                g_done = np.concatenate(
                    [np.logical_or(term[a], trunc[a]).astype(np.float32) for a in members],
                    axis=0,
                )
                g_logp = np.concatenate([self._cached_logps[a] for a in members], axis=0)
                g_val = np.concatenate([self._cached_values[a] for a in members], axis=0)
                step = dict(
                    obs=g_obs, action=g_act, reward=g_rew, done=g_done,
                    value=g_val, log_prob=g_logp,
                )
                cached_masks = getattr(self, "_cached_masks", {})
                if all(a in cached_masks for a in members):
                    step["action_mask"] = np.concatenate(
                        [cached_masks[a] for a in members], axis=0
                    )
                self.rollout_buffers[gid].add(**step)
            total_r += float(np.mean([np.mean(np.asarray(rew[a])) for a in self.agent_ids]))
            obs = next_obs
        self._last_obs = obs
        self._last_done = {
            a: np.logical_or(term[a], trunc[a]).astype(np.float32) for a in self.agent_ids
        }
        return total_r / n_steps

    def _update_fn_for(self, gid: str):
        actor_cfg = self.actors[gid].config
        critic_cfg = self.critics[gid].config
        dist_cfg = self.actors[gid].dist_config
        space = self.observation_spaces[self.grouped_agents[gid][0]]
        tx = self.optimizer.tx

        @jax.jit
        def update(params, opt_state, batch, clip, ent_coef, vf_coef):
            def loss_fn(p):
                obs = preprocess_observation(space, batch["obs"])
                logits = EvolvableNetwork.apply(actor_cfg, p["actors"][gid], obs)
                dist_extra = p["actors"][gid].get("dist")
                mask = batch.get("action_mask")
                new_logp = D.log_prob(dist_cfg, logits, batch["action"], dist_extra,
                                      mask=mask)
                entropy = D.entropy(dist_cfg, logits, dist_extra, mask=mask).mean()
                value = EvolvableNetwork.apply(critic_cfg, p["critics"][gid], obs)[..., 0]
                adv = batch["advantages"]
                adv = (adv - adv.mean()) / (adv.std() + 1e-8)
                ratio = jnp.exp(new_logp - batch["log_prob"])
                pg = jnp.maximum(
                    -adv * ratio, -adv * jnp.clip(ratio, 1 - clip, 1 + clip)
                ).mean()
                v_loss = 0.5 * jnp.square(value - batch["returns"]).mean()
                return pg - ent_coef * entropy + vf_coef * v_loss

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        return update

    def learn(self, experiences=None) -> float:
        total, n = 0.0, 0
        for gid, members in self.grouped_agents.items():
            params = self._group_params(gid)
            opt_state = self.optimizer.opt_state[gid]
            buf = self.rollout_buffers[gid]
            if buf.state is None:
                continue
            last_obs = np.concatenate(
                [np.asarray(self._last_obs[a]) for a in members], axis=0
            )
            last_done = np.concatenate([self._last_done[a] for a in members], axis=0)
            o = preprocess_observation(self.observation_spaces[members[0]], last_obs)
            last_value = EvolvableNetwork.apply(
                self.critics[gid].config, self.critics[gid].params, o
            )[..., 0]
            buf.compute_returns_and_advantages(last_value, jnp.asarray(last_done))
            update = self.jit_fn(f"update_{gid}", lambda gid=gid: self._update_fn_for(gid))
            for _ in range(self.update_epochs):
                for idx in buf.minibatch_indices(self.batch_size, key=self.next_key()):
                    batch = buf.get_batch(idx)
                    params, opt_state, loss = update(
                        params, opt_state, batch,
                        jnp.float32(self.clip_coef), jnp.float32(self.ent_coef),
                        jnp.float32(self.vf_coef),
                    )
                    total += float(loss)
                    n += 1
            buf.reset()
            self.actors[gid].params = params["actors"][gid]
            self.critics[gid].params = params["critics"][gid]
            self.optimizer.opt_state[gid] = opt_state
        return total / max(n, 1)
