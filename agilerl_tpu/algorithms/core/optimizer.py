"""Optimizer wrapper over optax (parity: agilerl/algorithms/core/optimizer_wrapper.py
— OptimizerWrapper:63; single, multi-net and per-agent-dict shapes; re-created
wholesale after any architecture mutation, core/base.py:643-694).

TPU-first: learning rate lives INSIDE the optax state via inject_hyperparams, so
an lr hyperparameter mutation is a pure state edit — no optimizer re-creation
and no XLA recompile. Architecture mutations call ``reinit`` which rebuilds the
state for the new param tree shape.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import jax
import optax

OPTIMIZERS: Dict[str, Callable] = {
    "adam": optax.adam,
    "adamw": optax.adamw,
    "sgd": optax.sgd,
    "rmsprop": optax.rmsprop,
}


import dataclasses


@dataclasses.dataclass
class CosineLRScheduleConfig:
    """Cosine schedule with warmup (parity: agilerl/utils/algo_utils.py:1406
    CosineLRScheduleConfig, consumed by the LLM algorithms)."""

    num_epochs: int = 10
    warmup_proportion: float = 0.05
    min_lr_fraction: float = 0.1
    steps_per_epoch: int = 100


class OptimizerWrapper:
    """Holds an optax transform + its state over one params pytree.

    ``params`` is a dict {network_attr_name: net.params} so one optimizer can
    span several networks (PPO actor+critic) or per-agent dicts (MADDPG).
    """

    def __init__(
        self,
        optimizer: str = "adam",
        lr: float = 1e-3,
        max_grad_norm: Optional[float] = None,
        lr_schedule: Optional[CosineLRScheduleConfig] = None,
        **kwargs,
    ):
        self.optimizer_name = optimizer
        self.lr = float(lr)
        self.max_grad_norm = max_grad_norm
        self.lr_schedule = lr_schedule
        self.kwargs = kwargs
        self.tx = self._build()
        self.opt_state = None

    def _build(self) -> optax.GradientTransformation:
        if self.lr_schedule is not None:
            total = self.lr_schedule.num_epochs * self.lr_schedule.steps_per_epoch
            warmup = max(int(total * self.lr_schedule.warmup_proportion), 1)
            schedule = optax.warmup_cosine_decay_schedule(
                init_value=0.0,
                peak_value=self.lr,
                warmup_steps=warmup,
                decay_steps=total,
                end_value=self.lr * self.lr_schedule.min_lr_fraction,
            )
            base = OPTIMIZERS[self.optimizer_name](learning_rate=schedule, **self.kwargs)
        else:
            base = optax.inject_hyperparams(OPTIMIZERS[self.optimizer_name])(
                learning_rate=self.lr, **self.kwargs
            )
        if self.max_grad_norm is not None:
            return optax.chain(optax.clip_by_global_norm(self.max_grad_norm), base)
        return base

    def init(self, params: Any) -> None:
        self.opt_state = self.tx.init(params)

    def reinit(self, params: Any) -> None:
        """Rebuild state after an architecture mutation (parity: base.py:744)."""
        self.opt_state = self.tx.init(params)

    def set_lr(self, lr: float) -> None:
        """Edit lr in-place in the optax state (no recompile, no reinit)."""
        self.lr = float(lr)
        if self.opt_state is not None:
            self.opt_state = _set_injected_lr(self.opt_state, self.lr)
        self.tx = self._build()

    def update(self, grads: Any, params: Any):
        updates, self.opt_state = self.tx.update(grads, self.opt_state, params)
        return optax.apply_updates(params, updates)

    def state_dict(self) -> Any:
        return self.opt_state

    def load_state_dict(self, state: Any) -> None:
        self.opt_state = state


def _set_injected_lr(opt_state: Any, lr: float) -> Any:
    """Find the InjectHyperparamsState and overwrite learning_rate."""
    import jax.numpy as jnp

    def visit(state):
        if isinstance(state, optax.InjectHyperparamsState):
            hp = dict(state.hyperparams)
            hp["learning_rate"] = jnp.asarray(lr, jnp.float32)
            return state._replace(hyperparams=hp)
        if isinstance(state, dict):  # per-group state dicts (IPPO)
            return {k: visit(v) for k, v in state.items()}
        if isinstance(state, tuple) and not hasattr(state, "_fields"):
            return tuple(visit(s) for s in state)
        return state

    return visit(opt_state)
