"""Mutation registry: network groups, optimizer configs, HP mutation spaces.

Parity: agilerl/algorithms/core/registry.py — MutationRegistry:372,
NetworkGroup:245, OptimizerConfig:44, HyperparameterConfig:189, RLParameter:109.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np
from agilerl_tpu.utils.rng import derive_rng


@dataclasses.dataclass
class NetworkGroup:
    """One evolvable network role in an algorithm: an eval net plus any nets
    that must share its architecture (targets, twin critics)
    (parity: registry.py:245)."""

    eval: str  # attribute name of the evaluated/trained network
    shared: Union[str, List[str], None] = None  # attrs rebuilt from eval after mutation
    policy: bool = False  # is this the acting policy?
    multiagent: bool = False

    def shared_names(self) -> List[str]:
        if self.shared is None:
            return []
        return [self.shared] if isinstance(self.shared, str) else list(self.shared)


@dataclasses.dataclass
class OptimizerConfig:
    """Metadata binding an optimizer attribute to its networks + lr HP
    (parity: registry.py:44)."""

    name: str  # attribute name of the OptimizerWrapper
    networks: List[str]  # attribute names of the nets it optimises
    lr: str = "lr"  # attribute name of the learning-rate HP
    optimizer: str = "adam"
    kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class RLParameter:
    """Mutation space for one scalar hyperparameter (parity: registry.py:109)."""

    min: float
    max: float
    shrink_factor: float = 0.8
    grow_factor: float = 1.2
    dtype: type = float

    def mutate(self, value, rng: Optional[np.random.Generator] = None):
        """Randomly grow or shrink within [min, max] (parity: registry.py:135)."""
        rng = derive_rng(rng)
        factor = self.grow_factor if rng.random() < 0.5 else self.shrink_factor
        new = value * factor
        new = float(np.clip(new, self.min, self.max))
        if self.dtype is int:
            new = int(round(new))
            new = int(np.clip(new, int(self.min), int(self.max)))
        return self.dtype(new)


@dataclasses.dataclass
class HyperparameterConfig:
    """Named collection of RLParameters (parity: registry.py:189)."""

    params: Dict[str, RLParameter] = dataclasses.field(default_factory=dict)

    def __init__(self, **kwargs: RLParameter):
        self.params = dict(kwargs)

    def names(self) -> List[str]:
        return list(self.params.keys())

    def sample(self, rng: Optional[np.random.Generator] = None) -> Optional[str]:
        rng = derive_rng(rng)
        if not self.params:
            return None
        return str(rng.choice(self.names()))

    def __getitem__(self, k: str) -> RLParameter:
        return self.params[k]

    def __contains__(self, k: str) -> bool:
        return k in self.params

    def __bool__(self) -> bool:
        return bool(self.params)


class MutationRegistry:
    """Per-agent registry of network groups, optimizers and hooks
    (parity: registry.py:372)."""

    def __init__(self, hp_config: Optional[HyperparameterConfig] = None):
        self.groups: List[NetworkGroup] = []
        self.optimizer_configs: List[OptimizerConfig] = []
        self.hooks: List[str] = []  # method names called after mutations
        self.hp_config = hp_config or HyperparameterConfig()

    def register_group(self, group: NetworkGroup) -> None:
        self.groups.append(group)

    def register_optimizer(self, cfg: OptimizerConfig) -> None:
        self.optimizer_configs.append(cfg)

    def register_hook(self, method_name: str) -> None:
        self.hooks.append(method_name)

    @property
    def policy_group(self) -> Optional[NetworkGroup]:
        for g in self.groups:
            if g.policy:
                return g
        return None

    def all_network_names(self) -> List[str]:
        names: List[str] = []
        for g in self.groups:
            names.append(g.eval)
            names.extend(g.shared_names())
        return names

    def validate(self) -> None:
        """Exactly one group must be the policy (parity: core/base.py:582).
        Raises (not asserts — survives python -O) on zero or multiple."""
        n_policy = sum(1 for g in self.groups if g.policy)
        if n_policy != 1:
            raise ValueError(
                f"An algorithm must register exactly one NetworkGroup with "
                f"policy=True (found {n_policy})"
            )
